"""Queryable run registry: index telemetry run dirs into a sqlite
database and answer the questions artifact-grepping can't —

  * ``index``   — walk a results root, upsert every run's manifest +
    summary + collective-ledger aggregates into ``runs.sqlite``
  * ``list``    — tabulate indexed runs (filter by strategy/model/group)
  * ``show``    — one run's summary metrics + per-collective bandwidth
  * ``diff``    — regression deltas between two runs: throughput, step
    time, host syncs, loss, and per-(kind, bucket, axis) busbw
  * ``export-cost-model`` — fold ledger aggregates across >= N indexed
    runs into ``cost_model.json``: the measured bus bandwidth per
    (collective kind, payload bucket, mesh axis) an autotuner can use
    as its communication cost table.  ``load_cost_model`` round-trips
    it back for consumers.
  * ``export-memory-priors`` — fold memory-ledger verdicts across >= N
    indexed runs into ``memory_priors.json``: the measured-over-
    predicted waterline ratio (overall + per strategy) plus typical
    per-category GB, which ``memory_plan.load_memory_priors`` feeds
    back into ``analytic_waterline(priors=...)`` so measured residuals
    recalibrate the analytic model the way bench priors anchor the
    tuner.
  * ``chaos``   — tabulate chaos campaign cells (scripts/chaos.py);
    ``index`` picks up a ``chaos_report.json`` sitting in the results
    root (or passed explicitly) into the ``chaos_cells`` table.

The database is disposable — ``index`` rebuilds rows from the run-dir
artifacts, which remain the source of truth.

  python scripts/runs.py index --results-dir runs
  python scripts/runs.py list
  python scripts/runs.py diff RUN_A RUN_B
  python scripts/runs.py export-cost-model --out cost_model.json
  python scripts/runs.py export-memory-priors --out memory_priors.json
"""

from __future__ import annotations

import argparse
import json
import sqlite3
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

DB_FILENAME = "runs.sqlite"
COST_MODEL_SCHEMA = 1

# summary metrics surfaced as real columns (everything else stays in
# the summary_json blob); sign says which direction is an improvement
# for ``diff``: +1 higher-is-better, -1 lower-is-better
_METRICS = {
    "steps_recorded": +1,
    "total_tokens": +1,
    "tokens_per_second": +1,
    "step_time_ms": -1,
    "final_loss": -1,
    "host_sync_count": -1,
}

_SCHEMA_SQL = """
CREATE TABLE IF NOT EXISTS runs (
    run_id        TEXT PRIMARY KEY,
    run_dir       TEXT NOT NULL,
    strategy      TEXT,
    model         TEXT,
    status        TEXT,
    launch_group  TEXT,
    rank          INTEGER,
    started_utc   TEXT,
    device_count  INTEGER,
    steps_recorded   REAL,
    total_tokens     REAL,
    tokens_per_second REAL,
    step_time_ms     REAL,
    final_loss       REAL,
    host_sync_count  REAL,
    contract_ok   INTEGER,
    rules_ok      INTEGER,
    sim           INTEGER,
    summary_json  TEXT
);
CREATE TABLE IF NOT EXISTS lint_verdicts (
    report       TEXT NOT NULL,
    strategy     TEXT NOT NULL,
    contract_ok  INTEGER,
    rules_ok     INTEGER,
    diff_contracts_ok INTEGER,
    ok           INTEGER,
    PRIMARY KEY (report, strategy)
);
CREATE TABLE IF NOT EXISTS ledger_aggregates (
    run_id         TEXT NOT NULL,
    kind           TEXT NOT NULL,
    payload_bucket TEXT NOT NULL,
    axis           TEXT NOT NULL,
    sites          INTEGER,
    events         INTEGER,
    total_us       REAL,
    bytes_moved    REAL,
    bus_bytes_moved REAL,
    algbw_gbps     REAL,
    busbw_gbps     REAL,
    PRIMARY KEY (run_id, kind, payload_bucket, axis)
);
CREATE TABLE IF NOT EXISTS memory_aggregates (
    run_id TEXT NOT NULL,
    key    TEXT NOT NULL,
    gb     REAL,
    PRIMARY KEY (run_id, key)
);
CREATE TABLE IF NOT EXISTS chaos_cells (
    report       TEXT NOT NULL,
    started_utc  TEXT,
    cell         TEXT NOT NULL,
    fault        TEXT,
    strategy     TEXT,
    status       TEXT,
    duration_s   REAL,
    invariants_json TEXT,
    PRIMARY KEY (report, cell)
);
"""


def connect(db_path: str) -> sqlite3.Connection:
    conn = sqlite3.connect(db_path)
    conn.row_factory = sqlite3.Row
    conn.executescript(_SCHEMA_SQL)
    # migrate pre-existing dbs created before the static-verdict columns
    # (CREATE TABLE IF NOT EXISTS never alters an existing table)
    for col in ("contract_ok", "rules_ok", "sim"):
        try:
            conn.execute(f"ALTER TABLE runs ADD COLUMN {col} INTEGER")
        except sqlite3.OperationalError:
            pass  # already present
    return conn


def _ok_int(verdict) -> int | None:
    """A manifest/report verdict dict -> 1/0/NULL for the index."""
    if not isinstance(verdict, dict) or "ok" not in verdict:
        return None
    return 1 if verdict.get("ok") else 0


def _load_json(path: Path) -> dict | None:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


# ------------------------------------------------------------------ index

def index_run_dir(conn: sqlite3.Connection, run_dir: str) -> str | None:
    """Upsert one run dir; returns the run_id, or None if the dir has
    no readable manifest (not a telemetry run)."""
    d = Path(run_dir)
    man = _load_json(d / "manifest.json")
    if man is None:
        return None
    summary = _load_json(d / "summary.json") or {}
    run_id = man.get("run_id") or d.name
    extra = man.get("extra") or {}
    row = {
        "run_id": run_id,
        "run_dir": str(d),
        "strategy": man.get("strategy"),
        "model": man.get("model"),
        "status": summary.get("status", "running"),
        "launch_group": extra.get("launch_group"),
        "rank": extra.get("rank", man.get("process_index", 0)),
        "started_utc": man.get("started_utc"),
        "device_count": man.get("device_count"),
        # the two static marks the manifest records at step 0: the
        # collective-contract verdict and the partition-rules verdict
        "contract_ok": _ok_int(man.get("contract")),
        "rules_ok": _ok_int(man.get("rules")),
        # simulator runs are marked so queries never silently mix
        # virtual-clock metrics with wall-clock metrics
        "sim": 1 if (summary.get("sim")
                     or (man.get("config") or {}).get("substrate")
                     == "sim") else 0,
        "summary_json": json.dumps(summary),
    }
    for m in _METRICS:
        row[m] = summary.get(m)
    cols = ", ".join(row)
    ph = ", ".join(f":{k}" for k in row)
    conn.execute(
        f"INSERT OR REPLACE INTO runs ({cols}) VALUES ({ph})", row)
    conn.execute("DELETE FROM ledger_aggregates WHERE run_id = ?",
                 (run_id,))
    ledger = _load_json(d / "collectives.json") or {}
    for agg in (ledger.get("aggregates") or {}).values():
        conn.execute(
            "INSERT OR REPLACE INTO ledger_aggregates VALUES "
            "(?,?,?,?,?,?,?,?,?,?,?)",
            (run_id, agg["kind"], agg["payload_bucket"], agg["axis"],
             agg.get("sites"), agg.get("events"), agg.get("total_us"),
             agg.get("bytes_moved"), agg.get("bus_bytes_moved"),
             agg.get("algbw_gbps"), agg.get("busbw_gbps")))
    conn.execute("DELETE FROM memory_aggregates WHERE run_id = ?",
                 (run_id,))
    memdoc = _load_json(d / "memory.json")
    if memdoc:
        from distributed_training_sandbox_tpu.telemetry.memledger import (
            memory_aggregates)
        for key, gb in memory_aggregates(memdoc).items():
            conn.execute(
                "INSERT OR REPLACE INTO memory_aggregates VALUES (?,?,?)",
                (run_id, key, gb))
    conn.commit()
    return run_id


def index_lint_report(conn: sqlite3.Connection, path: str) -> int:
    """Upsert one ``scripts/lint_sharding.py --json`` report
    (``schema_version`` >= 2) into ``lint_verdicts``: one row per
    strategy with its contract / rules verdicts plus the report-wide
    diff-contracts verdict — queryable next to the runs table's
    ledger-backed marks.  Returns the number of strategies indexed."""
    doc = _load_json(Path(path))
    if doc is None or int(doc.get("schema_version") or 0) < 2 \
            or "strategies" not in doc:
        return 0
    report = str(Path(path).resolve())
    diff_ok = _ok_int(doc.get("diff_contracts"))
    conn.execute("DELETE FROM lint_verdicts WHERE report = ?", (report,))
    n = 0
    for name, sub in (doc.get("strategies") or {}).items():
        conn.execute(
            "INSERT OR REPLACE INTO lint_verdicts VALUES (?,?,?,?,?,?)",
            (report, name, _ok_int(sub.get("contract")),
             _ok_int(sub.get("rules")), diff_ok,
             1 if sub.get("ok") else 0))
        n += 1
    conn.commit()
    return n


def index_chaos_report(conn: sqlite3.Connection, path: str) -> int:
    """Upsert one ``chaos_report.json`` (scripts/chaos.py) into the
    ``chaos_cells`` table; returns the number of cells indexed."""
    doc = _load_json(Path(path))
    if doc is None or doc.get("schema") != 1 or "cells" not in doc:
        return 0
    report = str(Path(path).resolve())
    conn.execute("DELETE FROM chaos_cells WHERE report = ?", (report,))
    for c in doc["cells"]:
        conn.execute(
            "INSERT OR REPLACE INTO chaos_cells VALUES "
            "(?,?,?,?,?,?,?,?)",
            (report, doc.get("started_utc"), c.get("cell"),
             c.get("fault"), c.get("strategy"), c.get("status"),
             c.get("duration_s"),
             json.dumps(c.get("invariants") or {})))
    conn.commit()
    return len(doc["cells"])


def index_results_dir(conn: sqlite3.Connection,
                      results_dir: str) -> list[str]:
    indexed = []
    root = Path(results_dir)
    if not root.is_dir():
        return indexed
    for entry in sorted(root.iterdir()):
        if entry.is_dir():
            rid = index_run_dir(conn, str(entry))
            if rid is not None:
                indexed.append(rid)
    if (root / "chaos_report.json").is_file():
        n = index_chaos_report(conn, str(root / "chaos_report.json"))
        if n:
            print(f"[runs] indexed chaos report "
                  f"({n} cells) from {root / 'chaos_report.json'}")
    return indexed


# ------------------------------------------------------------------ query

def _fetch_run(conn: sqlite3.Connection, run_id: str) -> sqlite3.Row:
    row = conn.execute("SELECT * FROM runs WHERE run_id = ?",
                       (run_id,)).fetchone()
    if row is None:
        raise KeyError(f"run {run_id!r} not indexed; run "
                       f"`runs.py index` first")
    return row


def _substrate(row: sqlite3.Row) -> str:
    try:
        return "sim" if row["sim"] else "real"
    except (IndexError, KeyError):
        return "real"


def diff_runs(conn: sqlite3.Connection, run_a: str,
              run_b: str, allow_mixed_substrates: bool = False) -> dict:
    """Regression deltas ``run_b - run_a`` (a = baseline).  Each metric
    row carries the delta, the percentage, and a verdict sign:
    improved / regressed / flat by the metric's better-direction.

    Refuses a sim-vs-real pair unless ``allow_mixed_substrates`` —
    virtual-clock latencies against wall-clock latencies is not a
    regression signal, and silently mixing them poisons gates."""
    a, b = _fetch_run(conn, run_a), _fetch_run(conn, run_b)
    sub_a, sub_b = _substrate(a), _substrate(b)
    if sub_a != sub_b and not allow_mixed_substrates:
        raise ValueError(
            f"substrate mismatch: {run_a} is {sub_a} but {run_b} is "
            f"{sub_b} — a virtual-clock run cannot gate a wall-clock "
            f"run (pass --mixed-substrates to annotate instead)")
    metrics = {}
    for m, better in _METRICS.items():
        va, vb = a[m], b[m]
        if va is None or vb is None:
            continue
        delta = vb - va
        pct = (delta / va * 100.0) if va else None
        verdict = "flat"
        if abs(delta) > 1e-12:
            verdict = "improved" if delta * better > 0 else "regressed"
        metrics[m] = {"baseline": va, "current": vb,
                      "delta": round(delta, 6),
                      "pct": round(pct, 3) if pct is not None else None,
                      "verdict": verdict}
    # per-collective busbw deltas where both runs measured the key
    rows = conn.execute(
        "SELECT a.kind, a.payload_bucket, a.axis, "
        "       a.busbw_gbps AS base, b.busbw_gbps AS cur "
        "FROM ledger_aggregates a JOIN ledger_aggregates b "
        "  ON a.kind = b.kind AND a.payload_bucket = b.payload_bucket "
        " AND a.axis = b.axis "
        "WHERE a.run_id = ? AND b.run_id = ?", (run_a, run_b))
    busbw = {}
    for r in rows:
        key = f"{r['kind']}|{r['payload_bucket']}|{r['axis']}"
        delta = (r["cur"] or 0.0) - (r["base"] or 0.0)
        busbw[key] = {"baseline_gbps": r["base"],
                      "current_gbps": r["cur"],
                      "delta_gbps": round(delta, 4)}
    # per-category memory deltas where both runs filed a memory ledger;
    # direction-aware: memory GROWTH is the regression
    rows = conn.execute(
        "SELECT a.key, a.gb AS base, b.gb AS cur "
        "FROM memory_aggregates a JOIN memory_aggregates b "
        "  ON a.key = b.key "
        "WHERE a.run_id = ? AND b.run_id = ?", (run_a, run_b))
    memory = {}
    for r in rows:
        base, cur = r["base"] or 0.0, r["cur"] or 0.0
        delta = cur - base
        pct = (delta / base * 100.0) if base else None
        verdict = "flat"
        if abs(delta) > 1e-9:
            verdict = "regressed" if delta > 0 else "improved"
        memory[r["key"]] = {"baseline_gb": base, "current_gb": cur,
                            "delta_gb": round(delta, 6),
                            "pct": round(pct, 3) if pct is not None
                            else None,
                            "verdict": verdict}
    return {"baseline": run_a, "current": run_b,
            "substrates": {"baseline": sub_a, "current": sub_b},
            "substrate_mismatch": sub_a != sub_b,
            "metrics": metrics, "busbw": busbw, "memory": memory}


# ------------------------------------------------------------- cost model

def export_cost_model(conn: sqlite3.Connection,
                      run_ids: list[str] | None = None,
                      min_runs: int = 3) -> dict:
    """Fold ledger aggregates across indexed runs into the autotuner's
    communication cost table.  Pooling is time-weighted (total bus
    bytes over total time), matching the ledger's own aggregation —
    NOT a mean of per-run bandwidths, which would overweight short
    runs.  Requires >= ``min_runs`` distinct contributing runs so one
    noisy run can't become the cost model."""
    where, params = "", []
    if run_ids:
        where = ("WHERE run_id IN (%s)"
                 % ",".join("?" * len(run_ids)))
        params = list(run_ids)
    rows = conn.execute(
        f"SELECT * FROM ledger_aggregates {where}", params).fetchall()
    contributing = sorted({r["run_id"] for r in rows})
    if len(contributing) < min_runs:
        raise ValueError(
            f"cost model needs >= {min_runs} runs with ledger "
            f"aggregates; have {len(contributing)}: {contributing}")
    entries: dict[str, dict] = {}
    for r in rows:
        key = f"{r['kind']}|{r['payload_bucket']}|{r['axis']}"
        e = entries.setdefault(key, {
            "kind": r["kind"], "payload_bucket": r["payload_bucket"],
            "axis": r["axis"], "runs": 0, "events": 0,
            "total_us": 0.0, "bytes_moved": 0.0,
            "bus_bytes_moved": 0.0})
        e["runs"] += 1
        e["events"] += r["events"] or 0
        e["total_us"] += r["total_us"] or 0.0
        e["bytes_moved"] += r["bytes_moved"] or 0.0
        e["bus_bytes_moved"] += r["bus_bytes_moved"] or 0.0
    for e in entries.values():
        t = e["total_us"]
        e["algbw_gbps"] = round(e["bytes_moved"] / t / 1e3, 4) \
            if t else 0.0
        e["busbw_gbps"] = round(e["bus_bytes_moved"] / t / 1e3, 4) \
            if t else 0.0
        e["total_us"] = round(e["total_us"], 3)
        e["bus_bytes_moved"] = round(e["bus_bytes_moved"], 1)
    return {
        # schema_version is the pinned contract the autotuner loads
        # against ("schema" kept as a legacy alias for older exports)
        "schema_version": COST_MODEL_SCHEMA,
        "schema": COST_MODEL_SCHEMA,
        "runs": contributing,
        "n_runs": len(contributing),
        "entries": entries,
    }


class CostModel:
    """Loaded ``cost_model.json``: measured bus bandwidth per
    (collective kind, payload bucket, mesh axis).  The constructor IS
    the drift gate: ``tuner/`` loads exports only through here, so a
    bumped or missing ``schema_version`` fails loudly instead of
    mis-ranking silently."""

    def __init__(self, doc: dict):
        ver = doc.get("schema_version", doc.get("schema"))
        if ver != COST_MODEL_SCHEMA:
            raise ValueError(
                f"cost model schema_version {ver!r} != "
                f"{COST_MODEL_SCHEMA} — re-export with "
                f"scripts/runs.py export-cost-model")
        if not isinstance(doc.get("entries"), dict):
            raise ValueError("cost model has no entries table")
        self.doc = doc
        self.entries: dict[str, dict] = doc["entries"]
        self.runs: list[str] = list(doc.get("runs", []))

    def busbw_gbps(self, kind: str, payload_bucket: str,
                   axis: str) -> float | None:
        e = self.entries.get(f"{kind}|{payload_bucket}|{axis}")
        return None if e is None else e["busbw_gbps"]

    def estimate_us(self, kind: str, nbytes: int,
                    axis: str) -> float | None:
        """Predicted wall time for one event: the autotuner-facing
        query (bucket resolved from the byte count)."""
        from distributed_training_sandbox_tpu.telemetry.ledger import (
            payload_bucket)
        bw = self.busbw_gbps(kind, payload_bucket(nbytes), axis)
        if not bw:
            return None
        return nbytes / (bw * 1e3)   # GB/s == bytes/us / 1e3


def load_cost_model(path: str) -> CostModel:
    with open(path) as f:
        return CostModel(json.load(f))


# ----------------------------------------------------------- memory priors

def export_memory_priors(conn: sqlite3.Connection,
                         run_ids: list[str] | None = None,
                         min_runs: int = 3) -> dict:
    """Fold memory-ledger verdicts across indexed runs into the
    predictor's recalibration priors: the median measured-over-
    predicted waterline ratio (overall + per strategy — this is the
    scalar ``analytic_waterline(priors=...)`` multiplies by) and the
    median attributed GB per category.  Requires >= ``min_runs``
    distinct contributing runs so one outlier can't steer the model;
    schema is gated on load by ``memory_plan.load_memory_priors``."""
    import statistics

    from distributed_training_sandbox_tpu.memory_plan import (
        MEMORY_PRIORS_SCHEMA_VERSION)

    where, params = "", []
    if run_ids:
        where = ("WHERE run_id IN (%s)" % ",".join("?" * len(run_ids)))
        params = list(run_ids)
    ratios: list[float] = []
    by_strategy: dict[str, list[float]] = {}
    contributing = []
    for r in conn.execute(f"SELECT * FROM runs {where}", params):
        verdict = (json.loads(r["summary_json"] or "{}")
                   .get("memory") or {})
        measured = verdict.get("measured_gb")
        predicted = verdict.get("predicted_gb",
                                verdict.get("compiled_gb"))
        if not measured or not predicted:
            continue
        contributing.append(r["run_id"])
        ratio = measured / predicted
        ratios.append(ratio)
        by_strategy.setdefault(r["strategy"] or "?", []).append(ratio)
    if len(contributing) < min_runs:
        raise ValueError(
            f"memory priors need >= {min_runs} runs with a memory "
            f"verdict; have {len(contributing)}: {sorted(contributing)}")
    by_cat: dict[str, list[float]] = {}
    for r in conn.execute(
            f"SELECT * FROM memory_aggregates {where}", params):
        if r["run_id"] in contributing and r["key"].startswith("cat/"):
            by_cat.setdefault(r["key"][4:], []).append(r["gb"] or 0.0)
    return {
        "schema_version": MEMORY_PRIORS_SCHEMA_VERSION,
        "runs": sorted(contributing),
        "n_runs": len(contributing),
        "overall_ratio": round(statistics.median(ratios), 4),
        "by_strategy": {s: round(statistics.median(v), 4)
                        for s, v in sorted(by_strategy.items())},
        "by_category": {c: round(statistics.median(v), 6)
                        for c, v in sorted(by_cat.items())},
    }


# -------------------------------------------------------------------- cli

def _cmd_index(conn, args) -> int:
    ids = index_results_dir(conn, args.results_dir)
    for d in args.run_dirs:
        if Path(d).is_file() and d.endswith(".json"):
            # a JSON arg is a report, not a run dir: lint_sharding --json
            # (schema_version >= 2) or a chaos campaign report
            n = index_lint_report(conn, d)
            if n:
                print(f"[runs] indexed lint report ({n} strategies) "
                      f"from {d}")
                continue
            n = index_chaos_report(conn, d)
            print(f"[runs] indexed chaos report ({n} cells) from {d}")
            continue
        rid = index_run_dir(conn, d)
        if rid is not None:
            ids.append(rid)
    print(f"[runs] indexed {len(ids)} run(s) into {args.db}")
    return 0


def _cmd_list(conn, args) -> int:
    q = "SELECT * FROM runs WHERE 1=1"
    params: list = []
    for col in ("strategy", "model", "launch_group"):
        val = getattr(args, col.replace("launch_group", "group"))
        if val:
            q += f" AND {col} = ?"
            params.append(val)
    q += " ORDER BY started_utc, run_id"
    rows = conn.execute(q, params).fetchall()
    hdr = (f"{'run_id':32} {'strategy':10} {'status':10} {'sim':>3} "
           f"{'steps':>6} {'step_ms':>9} {'tok/s':>12} {'group'}")
    print(hdr)
    print("-" * len(hdr))
    for r in rows:
        print(f"{r['run_id']:32} {str(r['strategy']):10} "
              f"{str(r['status']):10} "
              f"{'sim' if _substrate(r) == 'sim' else '-':>3} "
              f"{_fmt(r['steps_recorded'], 0):>6} "
              f"{_fmt(r['step_time_ms'], 2):>9} "
              f"{_fmt(r['tokens_per_second'], 0):>12} "
              f"{r['launch_group'] or '-'}")
    print(f"[runs] {len(rows)} run(s)")
    return 0


def _fmt(v, nd) -> str:
    return "-" if v is None else f"{v:.{nd}f}"


def _cmd_show(conn, args) -> int:
    row = _fetch_run(conn, args.run_id)
    summary = json.loads(row["summary_json"] or "{}")
    print(f"[runs] {row['run_id']}  ({row['run_dir']})")
    for col in ("strategy", "model", "status", "launch_group", "rank",
                "started_utc", "device_count"):
        print(f"  {col:18} {row[col]}")
    for m in sorted(summary):
        v = summary[m]
        if isinstance(v, (int, float, str)):
            print(f"  {m:18} {v}")
    aggs = conn.execute(
        "SELECT * FROM ledger_aggregates WHERE run_id = ? "
        "ORDER BY kind, payload_bucket, axis",
        (args.run_id,)).fetchall()
    if aggs:
        print("  collective aggregates:")
        for a in aggs:
            print(f"    {a['kind']:22} {a['payload_bucket']:8} "
                  f"axis={a['axis']:10} busbw={a['busbw_gbps']} GB/s "
                  f"({a['events']} events, {a['total_us']:.0f} us)")
    mems = conn.execute(
        "SELECT * FROM memory_aggregates WHERE run_id = ? ORDER BY key",
        (args.run_id,)).fetchall()
    if mems:
        print("  memory aggregates:")
        for m in mems:
            print(f"    {m['key']:28} {_fmt(m['gb'], 6):>12} GB")
    return 0


def _cmd_diff(conn, args) -> int:
    try:
        d = diff_runs(conn, args.baseline, args.current,
                      allow_mixed_substrates=args.mixed_substrates)
    except ValueError as e:
        print(f"[runs] REFUSED: {e}", file=sys.stderr)
        return 2
    print(f"[runs] {args.current} vs baseline {args.baseline}")
    if d["substrate_mismatch"]:
        print(f"[runs] WARNING: mixed substrates — baseline is "
              f"{d['substrates']['baseline']}, current is "
              f"{d['substrates']['current']}; deltas compare a virtual "
              f"clock against a wall clock and are NOT a regression "
              f"signal")
    for m, row in d["metrics"].items():
        pct = f" ({row['pct']:+.1f}%)" if row["pct"] is not None else ""
        print(f"  {m:18} {row['baseline']} -> {row['current']} "
              f"[{row['verdict']}{pct}]")
    for key, row in d["busbw"].items():
        print(f"  busbw {key:34} {row['baseline_gbps']} -> "
              f"{row['current_gbps']} GB/s "
              f"({row['delta_gbps']:+.3f})")
    for key, row in d["memory"].items():
        pct = f" ({row['pct']:+.1f}%)" if row["pct"] is not None else ""
        print(f"  mem   {key:34} {row['baseline_gb']} -> "
              f"{row['current_gb']} GB [{row['verdict']}{pct}]")
    if args.json:
        print(json.dumps(d, indent=2))
    regressed = [m for m, row in d["metrics"].items()
                 if row["verdict"] == "regressed"]
    regressed += [f"memory:{k}" for k, row in d["memory"].items()
                  if row["verdict"] == "regressed"]
    return 1 if (args.fail_on_regression and regressed) else 0


def _cmd_chaos(conn, args) -> int:
    q = "SELECT * FROM chaos_cells WHERE 1=1"
    params: list = []
    if args.status:
        q += " AND status = ?"
        params.append(args.status)
    q += " ORDER BY report, strategy, cell"
    rows = conn.execute(q, params).fetchall()
    if not rows:
        print("[runs] no chaos cells indexed; `runs.py index "
              "path/to/chaos_report.json` first")
        return 0
    hdr = (f"{'cell':24} {'fault':14} {'strategy':8} {'status':7} "
           f"{'dur_s':>7}  failed invariants")
    print(hdr)
    print("-" * len(hdr))
    red = 0
    for r in rows:
        inv = json.loads(r["invariants_json"] or "{}")
        bad = ",".join(k for k, v in inv.items() if not v)
        red += r["status"] != "green"
        print(f"{r['cell']:24} {str(r['fault']):14} "
              f"{str(r['strategy']):8} {str(r['status']):7} "
              f"{_fmt(r['duration_s'], 1):>7}  {bad or '-'}")
    print(f"[runs] {len(rows)} cell(s), {red} red")
    return 1 if (args.fail_on_red and red) else 0


def _cmd_export(conn, args) -> int:
    try:
        model = export_cost_model(conn, args.run_ids or None,
                                  min_runs=args.min_runs)
    except ValueError as e:
        print(f"[runs] {e}", file=sys.stderr)
        return 2
    with open(args.out, "w") as f:
        json.dump(model, f, indent=2)
        f.write("\n")
    print(f"[runs] cost model from {model['n_runs']} run(s), "
          f"{len(model['entries'])} (kind, bucket, axis) entr(ies) "
          f"-> {args.out}")
    for key, e in sorted(model["entries"].items()):
        print(f"  {key:44} busbw={e['busbw_gbps']} GB/s "
              f"over {e['runs']} run(s)")
    return 0


def _cmd_export_memory(conn, args) -> int:
    try:
        priors = export_memory_priors(conn, args.run_ids or None,
                                      min_runs=args.min_runs)
    except ValueError as e:
        print(f"[runs] {e}", file=sys.stderr)
        return 2
    with open(args.out, "w") as f:
        json.dump(priors, f, indent=2)
        f.write("\n")
    print(f"[runs] memory priors from {priors['n_runs']} run(s): "
          f"measured/predicted ratio {priors['overall_ratio']} "
          f"-> {args.out}")
    for s, v in priors["by_strategy"].items():
        print(f"  {s:12} ratio={v}")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="index + query telemetry run dirs")
    p.add_argument("--db", type=str, default=DB_FILENAME,
                   help=f"sqlite path (default ./{DB_FILENAME})")
    sub = p.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("index", help="index run dirs into the db")
    s.add_argument("run_dirs", nargs="*",
                   help="individual run dirs to index")
    s.add_argument("--results-dir", type=str, default="runs",
                   help="walk this root for run dirs (default: runs)")

    s = sub.add_parser("list", help="tabulate indexed runs")
    s.add_argument("--strategy", type=str, default=None)
    s.add_argument("--model", type=str, default=None)
    s.add_argument("--group", type=str, default=None,
                   help="filter by launch_group")

    s = sub.add_parser("show", help="one run's metrics + ledger")
    s.add_argument("run_id")

    s = sub.add_parser("diff", help="regression deltas: current vs "
                                    "baseline")
    s.add_argument("baseline")
    s.add_argument("current")
    s.add_argument("--json", action="store_true",
                   help="also dump the machine-readable diff")
    s.add_argument("--fail-on-regression", action="store_true",
                   help="exit 1 if any metric regressed")
    s.add_argument("--mixed-substrates", action="store_true",
                   help="annotate (instead of refuse) a sim-vs-real "
                        "comparison")

    s = sub.add_parser("chaos", help="tabulate indexed chaos campaign "
                                     "cells")
    s.add_argument("--status", type=str, default=None,
                   help="filter by cell status (green / red)")
    s.add_argument("--fail-on-red", action="store_true",
                   help="exit 1 if any indexed cell is red")

    s = sub.add_parser("export-cost-model",
                       help="fold ledger aggregates across runs into "
                            "cost_model.json")
    s.add_argument("run_ids", nargs="*",
                   help="restrict to these runs (default: all indexed)")
    s.add_argument("--out", type=str, default="cost_model.json")
    s.add_argument("--min-runs", type=int, default=3,
                   help="minimum distinct contributing runs (default 3)")

    s = sub.add_parser("export-memory-priors",
                       help="fold memory-ledger verdicts across runs "
                            "into the predictor's recalibration priors")
    s.add_argument("run_ids", nargs="*",
                   help="restrict to these runs (default: all indexed)")
    s.add_argument("--out", type=str, default="memory_priors.json")
    s.add_argument("--min-runs", type=int, default=3,
                   help="minimum distinct contributing runs (default 3)")

    args = p.parse_args(argv)
    conn = connect(args.db)
    try:
        return {"index": _cmd_index, "list": _cmd_list,
                "show": _cmd_show, "diff": _cmd_diff,
                "chaos": _cmd_chaos,
                "export-cost-model": _cmd_export,
                "export-memory-priors": _cmd_export_memory,
                }[args.cmd](conn, args)
    finally:
        conn.close()


if __name__ == "__main__":
    raise SystemExit(main())
