"""Cross-rank fleet timeline: merge N per-rank telemetry run dirs from
one ``dts-launch`` group into a single Perfetto/chrome-trace document,
plus the two reports single-run tooling cannot produce:

  * **straggler report** — for every pump sync site (same span name +
    step across >= 2 ranks), which rank arrived last and by how much,
    aggregated into per-rank "time blocked waiting on peers": the
    cross-rank twin of the single-run host_sync breakdown.  A rank that
    computes slowly arrives *late* at the barrier and barely waits; its
    peers arrive early and eat the lag — so blame lands on the last
    arrival, not the longest wait.
  * **request swimlanes** — serving spans carrying a ``trace_id`` are
    grouped per request onto their own named tracks, and the final
    prefill span's stamped ``t_submit/t_admit/t_first`` yield a TTFT
    decomposition (queue wait + prefill) per request, counting a
    failover replay ONCE (the last completed attempt wins) while still
    listing every replica the trace touched.

Cross-rank time alignment rides the ``clock_anchor.json`` sidecar each
SpanStream writes: span timestamps are already unix-epoch µs anchored
by a bounded-error midpoint capture, so ranks merge by timestamp
directly and the report carries the worst anchor error as its
confidence bound.

  python scripts/fleet_timeline.py RUN_DIR [RUN_DIR ...]
  python scripts/fleet_timeline.py --results-dir runs --group NAME
  python scripts/fleet_timeline.py --results-dir runs   # newest group
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# pid blocks in the merged doc: one fake "process" per rank, plus one
# for the per-request swimlanes
RANK_PID_BASE = 1000
REQUEST_PID = 2000


def _load_json(path: Path) -> dict | None:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


# ---------------------------------------------------------------- discovery

def discover_groups(results_dir: str) -> dict[str, list[str]]:
    """Map ``launch_group`` -> sorted run dirs under ``results_dir``.
    Runs without a stamped group fall back to a group per run_id prefix
    (strategy + timestamp with any ``-rN`` suffix stripped), so N ranks
    of one pre-group launch still merge."""
    groups: dict[str, list[str]] = {}
    root = Path(results_dir)
    if not root.is_dir():
        return groups
    for entry in sorted(root.iterdir()):
        man = _load_json(entry / "manifest.json")
        if man is None:
            continue
        group = (man.get("extra") or {}).get("launch_group")
        if not group:
            rid = man.get("run_id") or entry.name
            base = rid
            parts = rid.rsplit("-r", 1)
            if len(parts) == 2 and parts[1].isdigit():
                base = parts[0]
            group = base
        groups.setdefault(str(group), []).append(str(entry))
    return groups


def load_rank_stream(run_dir: str) -> dict:
    """One rank's merged-timeline inputs: manifest, spans, clock anchor,
    and the resolved rank (manifest extra wins, then the anchor sidecar,
    then per-span stamps, then 0)."""
    from distributed_training_sandbox_tpu.telemetry import (
        read_clock_anchor, read_spans)
    man = _load_json(Path(run_dir) / "manifest.json") or {}
    spans = read_spans(run_dir)
    anchor = read_clock_anchor(run_dir)
    rank = (man.get("extra") or {}).get("rank")
    if rank is None and anchor is not None:
        rank = anchor.get("rank")
    if rank is None and spans:
        rank = spans[0].get("rank")
    return {
        "run_dir": str(run_dir),
        "rank": int(rank or 0),
        "pid": man.get("pid") or (anchor or {}).get("pid"),
        "manifest": man,
        "spans": spans,
        "anchor": anchor,
    }


# ---------------------------------------------------------------- straggler

def straggler_report(streams: list[dict]) -> dict:
    """Per-sync-site arrival attribution across ranks.

    A sync site is a (span name, step) pair observed on >= 2 ranks with
    a ``pump`` category; arrival = span start (``ts_us``).  Per site the
    last-arriving rank is the straggler and every earlier rank's
    ``blocked_on_peers`` grows by its head start; per-rank aggregates
    and the overall straggler (largest attributed lateness) follow."""
    ranks = sorted({s["rank"] for s in streams})
    sites: dict[tuple, dict[int, dict]] = {}
    for st in streams:
        for sp in st["spans"]:
            if sp.get("cat") != "pump" or "step" not in sp:
                continue
            key = (sp["name"], int(sp["step"]))
            # one arrival per rank per site: keep the EARLIEST (retries
            # of the same site would skew attribution late)
            cur = sites.setdefault(key, {}).get(st["rank"])
            if cur is None or sp["ts_us"] < cur["ts_us"]:
                sites[key][st["rank"]] = sp
    per_rank = {r: {"blocked_on_peers_ms": 0.0, "times_last": 0,
                    "lateness_ms": 0.0, "sites": 0} for r in ranks}
    rows = []
    for (name, step), by_rank in sorted(sites.items(),
                                        key=lambda kv: (kv[0][1], kv[0][0])):
        if len(by_rank) < 2:
            continue
        arrivals = {r: sp["ts_us"] for r, sp in by_rank.items()}
        last_rank = max(arrivals, key=lambda r: arrivals[r])
        t_last = arrivals[last_rank]
        lag_ms = (t_last - min(arrivals.values())) / 1e3
        for r, t in arrivals.items():
            per_rank[r]["sites"] += 1
            per_rank[r]["blocked_on_peers_ms"] += (t_last - t) / 1e3
        per_rank[last_rank]["times_last"] += 1
        per_rank[last_rank]["lateness_ms"] += lag_ms
        rows.append({
            "name": name, "step": step, "last_rank": last_rank,
            "lag_ms": round(lag_ms, 3),
            "arrival_offset_ms": {
                str(r): round((t - min(arrivals.values())) / 1e3, 3)
                for r, t in sorted(arrivals.items())},
        })
    for agg in per_rank.values():
        agg["blocked_on_peers_ms"] = round(agg["blocked_on_peers_ms"], 3)
        agg["lateness_ms"] = round(agg["lateness_ms"], 3)
    straggler = None
    if rows:
        straggler = max(per_rank,
                        key=lambda r: (per_rank[r]["lateness_ms"],
                                       per_rank[r]["times_last"]))
    anchor_errs = [st["anchor"]["anchor_error_us"] for st in streams
                   if st.get("anchor")
                   and st["anchor"].get("anchor_error_us") is not None]
    return {
        "ranks": ranks,
        "sync_sites": rows,
        "per_rank": {str(r): agg for r, agg in per_rank.items()},
        "straggler": straggler,
        "max_anchor_error_us": (round(max(anchor_errs), 3)
                                if anchor_errs else None),
    }


# ---------------------------------------------------------------- requests

def request_report(streams: list[dict]) -> list[dict]:
    """Per-request TTFT decomposition from prefill spans carrying a
    ``trace_id``.  A failover replay leaves prefill spans on >= 2
    replicas under ONE trace_id; only the LAST attempt (the one that
    reached first-token) is decomposed — the replay counts once — but
    every replica the trace touched is listed, as is the attempt
    count."""
    by_tid: dict[str, list[dict]] = {}
    for st in streams:
        for sp in st["spans"]:
            if sp.get("name") != "serve/prefill_chunk" \
                    or sp.get("trace_id") is None:
                continue
            by_tid.setdefault(str(sp["trace_id"]), []).append(sp)
    out = []
    for tid, attempts in sorted(by_tid.items()):
        last = max(attempts, key=lambda s: s["ts_us"])
        replicas = sorted({s.get("replica") for s in attempts
                           if s.get("replica") is not None})
        row = {
            "trace_id": tid,
            "request_id": last.get("request_id", last.get("rid")),
            "replicas": replicas,
            "attempts": len(attempts),
            "replayed": len(attempts) > 1,
        }
        t_sub, t_adm, t_first = (last.get("t_submit_s"),
                                 last.get("t_admit_s"),
                                 last.get("t_first_s"))
        if None not in (t_sub, t_adm, t_first):
            row["queue_wait_ms"] = round(1e3 * (t_adm - t_sub), 3)
            row["prefill_ms"] = round(1e3 * (t_first - t_adm), 3)
            row["ttft_ms"] = round(1e3 * (t_first - t_sub), 3)
        out.append(row)
    return out


# ---------------------------------------------------------------- timeline

def merge_timeline(run_dirs: list[str], group: str | None = None) -> dict:
    """One Perfetto doc from N per-rank run dirs: a named process track
    per rank (threads = span categories), a ``requests`` process whose
    threads are per-trace_id swimlanes, and the straggler + request
    reports embedded under ``metadata``."""
    streams = [load_rank_stream(d) for d in run_dirs]
    streams.sort(key=lambda s: s["rank"])
    events: list[dict] = []
    all_ts = [sp["ts_us"] for st in streams for sp in st["spans"]]
    t0 = min(all_ts) if all_ts else 0.0

    tid_of_cat: dict[tuple, int] = {}
    for st in streams:
        pid = RANK_PID_BASE + st["rank"]
        label = f"rank {st['rank']}"
        if st.get("pid"):
            label += f" (pid {st['pid']})"
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "args": {"name": label}})
        cats = sorted({sp.get("cat") or "host" for sp in st["spans"]})
        for i, cat in enumerate(cats, start=1):
            tid_of_cat[(pid, cat)] = i
            events.append({"ph": "M", "name": "thread_name", "pid": pid,
                           "tid": i, "args": {"name": cat}})
        for sp in st["spans"]:
            cat = sp.get("cat") or "host"
            args = {k: v for k, v in sp.items()
                    if k not in ("schema", "name", "cat", "ts_us",
                                 "dur_us")}
            events.append({
                "ph": "X", "name": sp["name"], "cat": cat,
                "pid": pid, "tid": tid_of_cat[(pid, cat)],
                "ts": sp["ts_us"] - t0, "dur": sp["dur_us"],
                "args": args})

    # request swimlanes: one thread per trace_id, spans from EVERY
    # replica/rank interleave on it — a replayed request reads as one
    # lane with a visible gap at the failover
    traced = [(st, sp) for st in streams for sp in st["spans"]
              if sp.get("trace_id") is not None]
    if traced:
        events.append({"ph": "M", "name": "process_name",
                       "pid": REQUEST_PID, "args": {"name": "requests"}})
        tids = sorted({str(sp["trace_id"]) for _, sp in traced})
        tid_of_trace = {t: i for i, t in enumerate(tids, start=1)}
        for t, i in tid_of_trace.items():
            events.append({"ph": "M", "name": "thread_name",
                           "pid": REQUEST_PID, "tid": i,
                           "args": {"name": t}})
        for st, sp in traced:
            args = {k: v for k, v in sp.items()
                    if k not in ("schema", "name", "cat", "ts_us",
                                 "dur_us")}
            args["rank"] = st["rank"]
            events.append({
                "ph": "X", "name": sp["name"], "cat": "request",
                "pid": REQUEST_PID,
                "tid": tid_of_trace[str(sp["trace_id"])],
                "ts": sp["ts_us"] - t0, "dur": sp["dur_us"],
                "args": args})

    # metadata first, then X events by ts — what trace viewers expect
    events.sort(key=lambda e: (0 if e["ph"] == "M" else 1,
                               e.get("ts", 0.0)))
    report = straggler_report(streams)
    requests = request_report(streams)
    return {
        "displayTimeUnit": "ms",
        "traceEvents": events,
        "metadata": {
            "group": group,
            "run_dirs": [st["run_dir"] for st in streams],
            "ranks": report["ranks"],
            "straggler_report": report,
            "requests": requests,
        },
    }


def _print_report(report: dict, requests: list[dict]) -> None:
    rows = report["sync_sites"]
    print(f"[fleet-timeline] ranks: {report['ranks']}, "
          f"{len(rows)} shared sync site(s), clock anchor error "
          f"<= {report['max_anchor_error_us']} us")
    for row in rows[:20]:
        print(f"[fleet-timeline]   {row['name']} step {row['step']}: "
              f"rank {row['last_rank']} last by {row['lag_ms']} ms")
    if len(rows) > 20:
        print(f"[fleet-timeline]   ... {len(rows) - 20} more site(s)")
    for r, agg in sorted(report["per_rank"].items()):
        print(f"[fleet-timeline] rank {r}: blocked on peers "
              f"{agg['blocked_on_peers_ms']} ms over {agg['sites']} "
              f"site(s); last {agg['times_last']}x "
              f"(+{agg['lateness_ms']} ms attributed)")
    if report["straggler"] is not None:
        print(f"[fleet-timeline] straggler: rank {report['straggler']}")
    replayed = [q for q in requests if q["replayed"]]
    if requests:
        print(f"[fleet-timeline] {len(requests)} request swimlane(s), "
              f"{len(replayed)} replayed across replicas")
    for q in replayed:
        print(f"[fleet-timeline]   {q['trace_id']}: replicas "
              f"{q['replicas']}, ttft {q.get('ttft_ms')} ms = queue "
              f"{q.get('queue_wait_ms')} + prefill {q.get('prefill_ms')}")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="merge per-rank telemetry run dirs into one "
                    "Perfetto timeline + straggler report")
    p.add_argument("run_dirs", nargs="*",
                   help="per-rank telemetry run dirs to merge")
    p.add_argument("--results-dir", type=str, default=None,
                   help="discover run dirs here, grouped by the "
                        "launcher-stamped launch_group")
    p.add_argument("--group", type=str, default=None,
                   help="which launch group to merge (default: the "
                        "newest one)")
    p.add_argument("--out", type=str, default=None,
                   help="merged timeline path (default "
                        "<first run dir>/fleet_timeline.json)")
    p.add_argument("--report", type=str, default=None,
                   help="also write the straggler/request report JSON "
                        "here ('-' = stdout)")
    args = p.parse_args(argv)

    run_dirs = list(args.run_dirs)
    group = args.group
    if args.results_dir:
        groups = discover_groups(args.results_dir)
        if not groups:
            print(f"[fleet-timeline] no telemetry runs under "
                  f"{args.results_dir}", file=sys.stderr)
            return 2
        if group is None:
            # newest group by run-dir mtime
            group = max(groups, key=lambda g: max(
                os.path.getmtime(d) for d in groups[g]))
        if group not in groups:
            print(f"[fleet-timeline] group {group!r} not found; have "
                  f"{sorted(groups)}", file=sys.stderr)
            return 2
        run_dirs += groups[group]
    if not run_dirs:
        p.error("give RUN_DIR arguments or --results-dir")

    doc = merge_timeline(run_dirs, group=group)
    out = Path(args.out) if args.out \
        else Path(run_dirs[0]) / "fleet_timeline.json"
    out.parent.mkdir(parents=True, exist_ok=True)
    with open(out, "w") as f:
        json.dump(doc, f)
    n_x = sum(e["ph"] == "X" for e in doc["traceEvents"])
    print(f"[fleet-timeline] merged {len(run_dirs)} rank dir(s), "
          f"{n_x} span(s) -> {out}")
    report = doc["metadata"]["straggler_report"]
    requests = doc["metadata"]["requests"]
    _print_report(report, requests)
    if args.report:
        payload = json.dumps({"straggler_report": report,
                              "requests": requests}, indent=2)
        if args.report == "-":
            print(payload)
        else:
            Path(args.report).write_text(payload + "\n")
            print(f"[fleet-timeline] report -> {args.report}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
