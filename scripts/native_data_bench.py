"""Native vs numpy data-engine benchmark (host-side, no devices).

Times the three host hot spots of the packed-LM pipeline — the Zipfian
synthetic sampler, the window packer, the epoch shuffle — numpy twins
(``data/packing.py``) vs the C++ engine (``native/dtsdata.cpp``).
Writes ``data_results/native_data_bench.json`` and prints the table.

    python scripts/native_data_bench.py [--tokens 20000000] [--vocab 128256]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np  # noqa: E402

from distributed_training_sandbox_tpu.data import native, packing  # noqa: E402


def timeit(f, *args, reps=3):
    best = float("inf")
    out = None
    for _ in range(reps):
        t0 = time.perf_counter()
        out = f(*args)
        best = min(best, time.perf_counter() - t0)
    return best, out


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--tokens", type=int, default=20_000_000)
    p.add_argument("--vocab", type=int, default=128_256)
    p.add_argument("--seq", type=int, default=8192)
    p.add_argument("--out-dir", default="data_results")
    args = p.parse_args(argv)

    if not native.available():
        raise SystemExit(f"native engine unavailable: "
                         f"{native.build_error()}")

    rows = []

    t_np, stream = timeit(packing.synthetic_token_stream, args.tokens,
                          args.vocab, 42)
    t_cc, _ = timeit(native.synthetic_token_stream, args.tokens,
                     args.vocab, 42)
    rows.append({"op": f"zipf sample ({args.tokens / 1e6:.0f}M tokens, "
                       f"vocab {args.vocab})",
                 "numpy_s": round(t_np, 3), "native_s": round(t_cc, 3),
                 "speedup": round(t_np / t_cc, 1)})

    t_np, _ = timeit(packing.pack_tokens, stream, args.seq)
    t_cc, _ = timeit(native.pack_tokens, stream, args.seq)
    rows.append({"op": f"pack windows (seq {args.seq})",
                 "numpy_s": round(t_np, 4), "native_s": round(t_cc, 4),
                 "speedup": round(t_np / t_cc, 1)})

    n = args.tokens // (args.seq + 1)
    rng = np.random.default_rng(0)
    t_np, _ = timeit(lambda: rng.permutation(n))
    t_cc, _ = timeit(native.shuffle_indices, n, 0)
    rows.append({"op": f"epoch shuffle ({n} windows)",
                 "numpy_s": round(t_np, 5), "native_s": round(t_cc, 5),
                 "speedup": round(t_np / t_cc, 1)})

    print("| op | numpy s | native s | speedup |\n|---|---|---|---|")
    for r in rows:
        print(f"| {r['op']} | {r['numpy_s']} | {r['native_s']} | "
              f"{r['speedup']}× |")
    out = Path(args.out_dir)
    out.mkdir(exist_ok=True)
    (out / "native_data_bench.json").write_text(json.dumps(rows, indent=1))
    print(f"[native-data] wrote {out / 'native_data_bench.json'}")


if __name__ == "__main__":
    main()
