"""DDP batch-size utilization sweep — twin of the reference's one
committed experiment table (``/root/reference/DDP/EXPERIMENTS.md:9-12``:
GPU utilization / SM efficiency / occupancy at bs 8/32/64/128, with the
bs-128 OOM edge).

The TPU-honest columns: step time, samples/s, achieved model
TFLOPS/device, MFU against the chip's bf16 peak, and the compile-time
memory plan (``compiled.memory_analysis()`` — the allocator on this
substrate exposes no runtime stats).  The sweep keeps doubling the batch
past the reference's grid until the step fails to compile/run, recording
the OOM edge the same way the reference's bs-128 row does.

    python scripts/ddp_utilization.py [--model smollm3-350m] [--seq 128]

Writes ``ddp_results/utilization_<platform>.json`` and appends the
markdown table to EXPERIMENTS.md (idempotent: replaces its own section).
"""

from __future__ import annotations

import argparse
import functools
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# v5e TensorCore peak (bf16); used only for the MFU column.
PEAK_BF16 = {"tpu": 197e12}

SECTION = "## DDP batch-size utilization sweep"


def run_one(bs: int, seq: int, mcfg, mesh, num_steps: int) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from distributed_training_sandbox_tpu.models import (
        classification_loss, init_classifier_params)
    from distributed_training_sandbox_tpu.parallel import (
        broadcast_params, make_ddp_train_step, optim)
    from distributed_training_sandbox_tpu.ops import smap
    from distributed_training_sandbox_tpu.utils.flops import (
        get_model_flops_per_token)
    from jax.sharding import PartitionSpec as P

    params = init_classifier_params(jax.random.PRNGKey(0), mcfg)
    params = jax.jit(smap(lambda p: broadcast_params(p, "dp"),
                          mesh, P(), P()))(params)
    opt_state = optim.sgd_init(params)
    step = make_ddp_train_step(
        functools.partial(classification_loss, cfg=mcfg),
        lambda g, s, p: optim.sgd_update(g, s, p, lr=1e-3), mesh, "dp")

    key = jax.random.PRNGKey(1)
    batch = {
        "input_ids": jax.random.randint(key, (bs, seq), 0,
                                        mcfg.vocab_size, jnp.int32),
        "attention_mask": jnp.ones((bs, seq), jnp.int32),
        "labels": jnp.zeros((bs,), jnp.int32),
    }

    # compile-time memory plan of the whole jitted step
    lowered = step.lower(params, opt_state, batch)
    compiled = lowered.compile()
    ma = compiled.memory_analysis()
    plan_gb = (ma.argument_size_in_bytes + ma.temp_size_in_bytes
               + ma.output_size_in_bytes) / 2**30

    for _ in range(2):   # compile + settle
        params, opt_state, loss = step(params, opt_state, batch)
        np.asarray(loss)
    t0 = time.perf_counter()
    for _ in range(num_steps):
        params, opt_state, loss = step(params, opt_state, batch)
    np.asarray(loss)
    dt = (time.perf_counter() - t0) / num_steps

    ws = int(mesh.devices.size)
    # The benchmarked model is the CLASSIFIER: its head is one pooled
    # (B,H)@(H,2) matmul, not a per-token vocab projection — drop the
    # 2·h·vocab/token LM-head term or TFLOPS/MFU overstate by ~10-15%.
    ft = get_model_flops_per_token(mcfg, seq, include_lm_head=False)
    tflops_dev = bs * seq * ft / dt / ws / 1e12
    peak = PEAK_BF16.get(jax.devices()[0].platform)
    return {
        "batch_size": bs, "seq": seq, "step_ms": round(dt * 1e3, 1),
        "samples_per_sec": round(bs / dt, 1),
        "tokens_per_sec": round(bs * seq / dt, 1),
        "tflops_per_device": round(tflops_dev, 2),
        "mfu_pct": round(100 * tflops_dev * 1e12 / peak, 1) if peak
        else None,
        "memory_plan_gb": round(plan_gb, 2),
    }


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="smollm3-350m")
    p.add_argument("--seq", type=int, default=128)
    p.add_argument("--num-steps", type=int, default=10)
    p.add_argument("--max-batch", type=int, default=4096)
    p.add_argument("--cpu-devices", type=int, default=0)
    p.add_argument("--out-dir", default="ddp_results")
    args = p.parse_args(argv)

    if args.cpu_devices:
        from distributed_training_sandbox_tpu.utils import use_cpu_devices
        use_cpu_devices(args.cpu_devices)

    import jax
    from distributed_training_sandbox_tpu.models import (
        MODEL_REGISTRY, transformer as T)
    from distributed_training_sandbox_tpu.utils import make_mesh

    from distributed_training_sandbox_tpu.utils import classify_failure

    mcfg = getattr(T, MODEL_REGISTRY[args.model])
    mesh = make_mesh()
    platform = jax.devices()[0].platform
    out = Path(args.out_dir)
    out.mkdir(exist_ok=True)
    path = out / f"utilization_{platform}.json"

    def persist(rows):
        path.write_text(json.dumps(
            {"model": args.model, "platform": platform, "rows": rows},
            indent=1))

    rows = []
    bs_grid = [8, 32, 64, 128]      # the reference's grid...
    nxt = 256                       # ...then double to find the edge
    while bs_grid:
        bs = bs_grid.pop(0)
        try:
            r = run_one(bs, args.seq, mcfg, mesh, args.num_steps)
            rows.append(r)
            print(f"[ddp-util] {r}", flush=True)
            if not bs_grid and nxt <= args.max_batch:
                bs_grid.append(nxt)
                nxt *= 2
        except Exception as e:   # noqa: BLE001 — the OOM edge IS the result
            kind, msg = classify_failure(e)
            if kind == "oom":   # XLA's own verdict: this row IS the edge
                rows.append({"batch_size": bs, "seq": args.seq,
                             "error": f"OOM: {msg[:180]}"})
                print(f"[ddp-util] bs={bs}: OOM (edge found)", flush=True)
                break
            # anything else is a real failure, not the edge — persist the
            # measured rows, then re-raise so it can't be published as
            # the OOM wall
            persist(rows)
            raise
        persist(rows)
    persist(rows)
    print(f"[ddp-util] wrote {path}")

    # append/replace our section in EXPERIMENTS.md
    md = [SECTION, "",
          f"`scripts/ddp_utilization.py --model {args.model} --seq "
          f"{args.seq}` on {platform} — twin of the reference's "
          "bs 8/32/64/128 GPU-utilization table "
          "(`DDP/EXPERIMENTS.md:9-12`), with TPU-honest columns "
          "(MFU = achieved model TFLOPS / chip bf16 peak; memory is the "
          "compile-time plan — this substrate exposes no runtime "
          "allocator stats).", "",
          "| batch | step ms | samples/s | TFLOPS/dev | MFU | "
          "plan GB |", "|---|---|---|---|---|---|"]
    for r in rows:
        if "error" in r:
            md.append(f"| {r['batch_size']} | — | — | — | — | "
                      f"**edge: {r['error'][:60]}** |")
        else:
            mfu = f"{r['mfu_pct']}%" if r["mfu_pct"] is not None else "—"
            md.append(f"| {r['batch_size']} | {r['step_ms']} | "
                      f"{r['samples_per_sec']} | {r['tflops_per_device']} "
                      f"| {mfu} | {r['memory_plan_gb']} |")
    md.append("")
    exp = Path("EXPERIMENTS.md")
    text = exp.read_text() if exp.exists() else ""
    if SECTION in text:
        head, _, tail = text.partition(SECTION)
        rest = tail.split("\n## ", 1)
        text = head + "\n".join(md) + (
            "\n## " + rest[1] if len(rest) > 1 else "")
    else:
        text = text.rstrip() + "\n\n" + "\n".join(md)
    exp.write_text(text)
    print("[ddp-util] EXPERIMENTS.md updated")


if __name__ == "__main__":
    main()
