"""02-operations teaching twin: every collective, live on an 8-device mesh.

The reference teaches its communication layer interactively in
``02-operations.ipynb`` (cells 2-42): two ``nbdistributed`` ranks walk
through send/recv, isend/irecv + wait, broadcast, scatter, all_reduce
(SUM/MAX/MIN/PRODUCT), reduce-to-one, and both all_gather flavors, printing
each tensor before and after the op.  This script is the TPU-native twin of
that notebook (SURVEY.md §2.6): the same progression — point-to-point →
one-to-all → reductions → gathers — demonstrated with this framework's own
collectives layer (``ops/collectives.py``) on an 8-device
``jax.sharding.Mesh``, plus the TPU-only extras the reference's course
builds toward (reduce_scatter, all_to_all, barrier).

Where the notebook prints per-rank tensors, we print per-device shards; where
it relies on the reader imagining the layout, we show it with
``jax.debug.visualize_array_sharding``.  Two deliberate differences from the
torch mental model, called out inline:

  * There are no per-rank Python processes.  One program runs on all devices
    (SPMD); "rank" is ``lax.axis_index`` *inside* the traced computation, and
    per-rank branching is ``jnp.where`` / masking, not ``if rank == 0:``.
  * Every JAX dispatch is already asynchronous — the isend/irecv/wait
    progression (nb cells 16-21) maps to "dispatch, then
    ``block_until_ready``", demonstrated in §2.

Runs top-to-bottom offline: with fewer than 8 real devices it forces an
8-device CPU-sim platform (the repo's gloo-mode twin, SURVEY.md §4).

    python scripts/ops_demo.py
"""

from __future__ import annotations

import io
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

SEP = "─" * 72


def _banner(title: str, body: str = "") -> None:
    print(f"\n{SEP}\n{title}\n{SEP}")
    if body:
        print(body.strip() + "\n")


def tinfo(name: str, arr, *, values: bool = True) -> None:
    """Twin of the notebook's ``tinfo`` helper (cell 8): shape / dtype /
    placement / value — here one line per device shard instead of one print
    per rank process."""
    import numpy as np
    print(f"  {name}: shape={tuple(arr.shape)} dtype={arr.dtype}")
    for s in sorted(arr.addressable_shards, key=lambda s: s.index):
        dev = f"{s.device.platform}:{s.device.id}"
        val = np.asarray(s.data).ravel()
        txt = np.array2string(val, max_line_width=60, threshold=8)
        print(f"    device {dev}  shard{s.index}  " +
              (txt if values else f"shape={s.data.shape}"))


def viz(arr) -> None:
    """``jax.debug.visualize_array_sharding`` with a fallback for >2-D /
    exotic layouts (the visualizer only draws 1-D/2-D arrays)."""
    import jax
    try:
        jax.debug.visualize_array_sharding(arr)
    except (ValueError, NotImplementedError):
        print(f"  [sharding: {arr.sharding}]")


def _real_device_count() -> int:
    """Count devices in a subprocess: probing in-process would initialize
    the backend and make a later use_cpu_devices() a no-op."""
    import subprocess
    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; print(len(jax.devices()))"],
            capture_output=True, text=True, timeout=120)
        return int(r.stdout.strip().splitlines()[-1])
    except Exception:
        return 0


def main() -> dict:
    """Run the whole walkthrough; returns computed results keyed by section
    so the test suite can assert semantics, not just 'it printed'."""
    # §0 — %dist_init twin: bring up the device "world" ------------------
    if _real_device_count() < 8:
        from distributed_training_sandbox_tpu.utils import use_cpu_devices
        use_cpu_devices(8)
    import jax

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_training_sandbox_tpu.ops import collectives as C
    from distributed_training_sandbox_tpu.utils import make_mesh

    mesh = make_mesh({"dev": -1}, register=False)
    n = int(mesh.shape["dev"])
    results: dict = {}

    _banner(
        "§0  World setup — the %dist_init twin (nb cell 2)",
        f"""
The notebook spawns {2} worker processes and gives each a CUDA device.
JAX's SPMD model needs no worker processes: one program, {n} devices, one
named Mesh.  Everything below runs inside shard_map over this mesh, where
`lax.axis_index("dev")` plays the role of `rank`.""")
    print(f"  mesh: {mesh}")
    print(f"  devices: {[f'{d.platform}:{d.id}' for d in mesh.devices.ravel()]}")

    shard = NamedSharding(mesh, P("dev"))
    repl = NamedSharding(mesh, P())

    # §1 — point-to-point: send/recv as a ring permute (nb cells 11-14) ---
    _banner(
        "§1  Point → point: send/recv (nb cells 11-14)",
        """
torch: rank0 `dist.send(t, dst=1)`, rank1 `dist.recv(t, src=0)`.
SPMD has no one-sided send; the collective form of "device i sends to
device j" is `lax.ppermute`, here a +1 ring so every device passes its
payload to its neighbour.  Each device's payload is `[rank, rank, rank]`;
after the hop, device i holds the values of device i-1.""")
    payload = jax.device_put(
        np.repeat(np.arange(n, dtype=np.float32), 3).reshape(n, 3), shard)
    print("before (each device holds its own rank):")
    tinfo("payload", payload)
    viz(payload)
    ring = jax.jit(C.smap(lambda x: C.ppermute_ring(x[0], "dev", shift=1)[None],
                          mesh, in_specs=P("dev"), out_specs=P("dev")))
    moved = ring(payload)
    print("after ppermute_ring(shift=1) (each device holds rank-1's data):")
    tinfo("payload", moved)
    results["ppermute"] = np.asarray(moved)

    # §2 — async: isend/irecv/wait ↔ dispatch + block_until_ready --------
    _banner(
        "§2  Asynchronous ops — isend/irecv + wait (nb cells 16-22)",
        """
torch: `request = dist.isend(...)` ... `request.wait()`.
Every JAX op is dispatched asynchronously already: the call returns a
future-like Array immediately and the host keeps running — the notebook's
"overlap compute with communication" goal is the default.  The twin of
`request.wait()` is `jax.block_until_ready(x)`.""")
    fut = ring(moved)          # dispatched; host is NOT blocked here
    print("  dispatched ring hop; host continues immediately "
          "(overlapped compute happens here)")
    fut = jax.block_until_ready(fut)   # request.wait()
    print("  block_until_ready(...) returned — transfer complete:")
    tinfo("payload", fut)
    results["async"] = np.asarray(fut)

    # §3 — one → all: broadcast (nb cells 3-5, 24-26) --------------------
    _banner(
        "§3  One → all: broadcast (nb cells 3-5, 24-26)",
        """
torch: rank0 holds [1,2,3], rank1 holds empty; `dist.broadcast(t, src=0)`.
Here every device enters with its own distinct row (rank*10 + [1,2,3]) and
leaves with device 0's row.  The wrapper implements broadcast as a masked
psum — one all-reduce on the wire, which is exactly how NCCL accounts small
broadcasts too (reference README.md:11-12).""")
    distinct = jax.device_put(
        (np.arange(n, dtype=np.float32)[:, None] * 10
         + np.array([1.0, 2.0, 3.0])), shard)
    print("before (every device has its own row):")
    tinfo("t", distinct)
    bcast = jax.jit(C.smap(lambda x: C.broadcast(x, "dev", root=0),
                           mesh, in_specs=P("dev"), out_specs=P("dev")))
    after = bcast(distinct)
    print("after broadcast(root=0) (everyone has device 0's row):")
    tinfo("t", after)
    results["broadcast"] = np.asarray(after)

    # §4 — one → all: scatter (nb cells 28-30) ---------------------------
    _banner(
        "§4  One → all: scatter (nb cells 28-30)",
        """
torch: rank0 builds `[tensor([0,1]), tensor([2,3])]`, `dist.scatter` hands
one chunk to each rank.  The SPMD formulation: the source tensor is
(logically) everywhere, each device slices its own chunk.  In the global
view that IS what `device_put` with a sharded layout does — watch the
sharding visualization: one replicated array in, a dim-0-sharded array out.""")
    src = jax.device_put(np.arange(2 * n, dtype=np.int32), repl)
    print("before: source replicated on all devices")
    viz(src)
    scat = jax.jit(C.smap(lambda x: C.scatter(x, "dev")[None],
                          mesh, in_specs=P(), out_specs=P("dev")))
    chunks = scat(src)
    print("after scatter: each device owns a 2-element chunk:")
    tinfo("chunk", chunks)
    viz(chunks.reshape(n * 2))
    results["scatter"] = np.asarray(chunks)

    # §5 — all → all reductions: SUM / MAX / MIN / PRODUCT (cells 33-36) -
    _banner(
        "§5  All → all reductions: all_reduce (nb cells 33-36)",
        """
torch: every rank holds `[0+rank, 1+rank, 2+rank]`, then all_reduce with
SUM, MAX, MIN, PRODUCT.  Same data here — note PRODUCT has no XLA
primitive; the wrapper builds it from three psums (sign / zero / log-sum),
a teaching-op only (see ops/collectives.py).""")
    base = jax.device_put(
        (np.arange(n, dtype=np.float32)[:, None]
         + np.arange(3, dtype=np.float32)), shard)
    print("before (rank r holds [r, r+1, r+2]):")
    tinfo("t", base)
    for op in ("sum", "max", "min", "prod"):
        f = jax.jit(C.smap(lambda x, op=op: C.all_reduce(x[0], "dev", op)[None],
                           mesh, in_specs=P("dev"), out_specs=P("dev")))
        out = f(base)
        row = np.asarray(out)[0]
        print(f"  all_reduce({op.upper():7s}) -> every device: {row}")
        results[f"all_reduce_{op}"] = np.asarray(out)

    # §6 — all → one: reduce to a root (nb cell 38) ----------------------
    _banner(
        "§6  All → one: reduce (nb cell 38)",
        """
torch: `dist.reduce(t, dst=0)` — only rank 0 gets the sum ("useful for
metrics printed only on rank0").  SPMD twin: psum + keep-if-root mask; the
non-root devices deliberately keep their original value, matching NCCL's
undefined-on-non-root contract the notebook shows.""")
    red = jax.jit(C.smap(
        lambda x: jnp.where(C.axis_rank("dev") == 0,
                            C.all_reduce(x[0], "dev"), x[0])[None],
        mesh, in_specs=P("dev"), out_specs=P("dev")))
    out = red(base)
    print("after reduce(dst=0) (device 0 has the sum, rest unchanged):")
    tinfo("t", out)
    results["reduce"] = np.asarray(out)

    # §7 — gathers (nb cells 40-41) --------------------------------------
    _banner(
        "§7  Gathering: all_gather (nb cells 40-41)",
        """
torch shows two flavors — a list of tensors and `all_gather_into_tensor`.
XLA only has the tensor form (`lax.all_gather`, tiled): every device ends
holding the (n, 3) concatenation.  Watch the sharding: input is sharded
across devices, output is fully replicated.""")
    print("before (each device: its own [r, r+1, r+2]):")
    tinfo("t", base)
    viz(base)
    gat = jax.jit(C.smap(lambda x: C.all_gather(x[0][None], "dev"),
                         mesh, in_specs=P("dev"), out_specs=P()))
    gathered = gat(base)
    print("after all_gather (every device holds all rows):")
    viz(gathered)
    print(f"  value:\n{np.asarray(gathered)}")
    results["all_gather"] = np.asarray(gathered)

    # §8 — beyond the notebook: the TPU course's next stops --------------
    _banner(
        "§8  Bonus: reduce_scatter / all_to_all / barrier",
        """
The notebook stops at gathers; the strategies built on top of it do not.
ZeRO-2's grad sharding is `reduce_scatter` (zero2.py:107 twin), expert /
sequence parallelism is `all_to_all`, and `dist.barrier` is — as the
reference's README.md:11 observes from its own traces — just a 1-element
all_reduce.""")
    rs = jax.jit(C.smap(lambda x: C.reduce_scatter(x, "dev")[None],
                        mesh, in_specs=P(), out_specs=P("dev")))
    # Every device contributes the same vector [0..n); the sum is n*i at
    # position i, and reduce_scatter leaves device i holding position i.
    contrib = jax.device_put(np.arange(n, dtype=np.float32), repl)
    out = rs(contrib)
    print(f"  reduce_scatter(rows 0..{n - 1} summed over {n} devices) -> "
          f"device i keeps {n}*i:")
    tinfo("shard", out)
    results["reduce_scatter"] = np.asarray(out)

    a2a = jax.jit(C.smap(
        lambda x: C.all_to_all(x[0], "dev", split_axis=0, concat_axis=0)[None],
        mesh, in_specs=P("dev"), out_specs=P("dev")))
    grid = jax.device_put(
        np.arange(n * n, dtype=np.float32).reshape(n, n), shard)
    print("\n  all_to_all on an (n, n) grid — the distributed transpose:")
    tinfo("before (device i: row i)", grid, values=False)
    t_grid = a2a(grid)
    print(f"  after: device i holds column i -> "
          f"{np.asarray(t_grid)[0].tolist()} on device 0")
    results["all_to_all"] = np.asarray(t_grid)

    bar = jax.jit(C.smap(lambda: C.barrier("dev")[None],
                         mesh, in_specs=(), out_specs=P("dev")))
    tok = jax.block_until_ready(bar())
    print(f"\n  barrier() -> psum of 1 over {n} devices = "
          f"{float(np.asarray(tok)[0])} (== world size; "
          f"block_until_ready gives the host-side fence)")
    results["barrier"] = np.asarray(tok)

    _banner("§9  Shutdown — the %dist_shutdown twin (nb cell 42)",
            "Nothing to tear down: no worker processes were started.")
    return results


if __name__ == "__main__":
    main()
