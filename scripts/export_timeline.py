"""Merge a run's host-phase spans with its device trace into one
chrome-trace timeline.

A :class:`telemetry.TelemetryRun` leaves two time-domain artifacts in
its run dir: ``spans.jsonl`` (host waits — prefetch queue, pump sync
barriers, checkpoint saves, serving bursts) and, when profiling was on,
the XLA profiler session it *owns* (``manifest.json:profile_sessions``).
This script joins them into a single ``traceEvents`` JSON that
``chrome://tracing`` / Perfetto loads directly: device rows keep the
pid/tid layout XLA wrote; host spans land on a synthetic "host phases"
process with one thread per category (pump / prefetch / checkpoint /
serve).

Clock honesty: the two sides run on DIFFERENT clocks — spans are
unix-epoch µs from a ``perf_counter``-anchored stream, device events use
XLA's internal trace timebase.  There is no cross-clock sync point to
align them exactly, so each side is zeroed to its own earliest
timestamp.  Relative durations and within-side ordering are exact;
host-vs-device alignment is approximate (both start near the profiled
window), good for "where does the host stall" reading, not for
nanosecond attribution across the boundary.

Usage:
  python scripts/export_timeline.py <run-dir> [--out timeline.json.gz]
"""

from __future__ import annotations

import argparse
import glob
import gzip
import json
import os
import sys
from pathlib import Path

# Prepend the checkout root so the source tree always wins over any
# installed copy of the package (`pip install -e .` makes this a no-op).
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

HOST_PID = 999000   # far above any XLA device pid


def _load_json(path: str) -> dict | None:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def find_trace_file(run_dir: str) -> str | None:
    """The device trace this run owns: the session recorded in its
    manifest when present, else newest under the summary's trace dir."""
    from distributed_training_sandbox_tpu.utils.trace_analysis import (
        latest_trace_file)
    manifest = _load_json(os.path.join(run_dir, "manifest.json")) or {}
    summary = _load_json(os.path.join(run_dir, "summary.json")) or {}
    sessions = manifest.get("profile_sessions") or \
        summary.get("profile_sessions") or []
    for sess in reversed(sessions):
        files = glob.glob(os.path.join(sess, "**", "*.trace.json.gz"),
                          recursive=True)
        if files:
            return max(files, key=os.path.getmtime)
    trace_dir = summary.get("trace_dir")
    if trace_dir and os.path.isdir(trace_dir):
        return latest_trace_file(trace_dir)
    return None


def load_device_events(trace_file: str) -> list[dict]:
    with gzip.open(trace_file, "rt") as f:
        doc = json.load(f)
    return list(doc.get("traceEvents") or [])


def span_events(spans: list[dict]) -> list[dict]:
    """Host spans as chrome-trace ph="X" events on the synthetic host
    process, one tid per category so Perfetto gives each its own row."""
    cats = sorted({s.get("cat") or "host" for s in spans})
    tid_of = {c: i + 1 for i, c in enumerate(cats)}
    out = [{"ph": "M", "pid": HOST_PID, "name": "process_name",
            "args": {"name": "host phases"}}]
    for c in cats:
        out.append({"ph": "M", "pid": HOST_PID, "tid": tid_of[c],
                    "name": "thread_name", "args": {"name": c}})
    for s in spans:
        ev = {"ph": "X", "pid": HOST_PID,
              "tid": tid_of[s.get("cat") or "host"],
              "name": s.get("name", "?"),
              "ts": float(s.get("ts_us", 0.0)),
              "dur": float(s.get("dur_us", 0.0))}
        attrs = {k: v for k, v in s.items()
                 if k not in ("schema", "name", "cat", "ts_us", "dur_us")}
        if attrs:
            ev["args"] = attrs
        out.append(ev)
    return out


def _rebase(events: list[dict]) -> None:
    """Zero a side's ``ts`` to its own earliest event (in place)."""
    ts = [e["ts"] for e in events if "ts" in e]
    if not ts:
        return
    t0 = min(ts)
    for e in events:
        if "ts" in e:
            e["ts"] = e["ts"] - t0


def build_timeline(run_dir: str) -> dict:
    """The merged chrome-trace document for one run dir."""
    from distributed_training_sandbox_tpu.telemetry.spans import read_spans
    spans = read_spans(run_dir)
    host = span_events(spans) if spans else []
    _rebase(host)
    device: list[dict] = []
    trace_file = find_trace_file(run_dir)
    if trace_file:
        device = load_device_events(trace_file)
        _rebase(device)
    # metadata (track-naming ph="M") events first, then everything in
    # timestamp order — some viewers resolve track names lazily and
    # mis-group out-of-order streams
    merged = sorted(device + host,
                    key=lambda e: (0 if e.get("ph") == "M" else 1,
                                   e.get("ts", 0.0)))
    return {
        "displayTimeUnit": "ms",
        "traceEvents": merged,
        "metadata": {
            "run_dir": os.path.abspath(run_dir),
            "host_spans": len(spans),
            "device_trace": trace_file,
            "clock_note": ("host and device sides are independently "
                           "zeroed to their own first event; cross-side "
                           "alignment is approximate"),
        },
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("run_dir", help="telemetry run directory "
                   "(contains manifest.json / spans.jsonl)")
    p.add_argument("--out", default=None,
                   help="output path (.json or .json.gz); default "
                   "<run-dir>/timeline.json.gz")
    args = p.parse_args(argv)
    if not os.path.isdir(args.run_dir):
        print(f"error: not a directory: {args.run_dir}", file=sys.stderr)
        return 2
    doc = build_timeline(args.run_dir)
    if not doc["traceEvents"]:
        print(f"error: {args.run_dir} has neither spans.jsonl nor an "
              f"owned device trace — nothing to export", file=sys.stderr)
        return 1
    out = args.out or os.path.join(args.run_dir, "timeline.json.gz")
    if out.endswith(".gz"):
        with gzip.open(out, "wt") as f:
            json.dump(doc, f)
    else:
        with open(out, "w") as f:
            json.dump(doc, f, indent=1)
    n_dev = sum(1 for e in doc["traceEvents"]
                if e.get("pid") != HOST_PID and e.get("ph") == "X")
    n_host = doc["metadata"]["host_spans"]
    print(f"wrote {out}: {n_host} host spans + {n_dev} device events "
          f"(load in chrome://tracing or ui.perfetto.dev)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
