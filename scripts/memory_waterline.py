"""Reproduce the reference's FSDP memory-waterline observations on TPU.

The reference documents what its profiler shows for FSDP SmolLM3-3B on
2×A100 (``/root/reference/README.md:22-33``): ~12 GB static at rest
(bf16 param shard + AdamW state + metadata), a sawtooth of per-layer
gathers through forward/backward, and **three ~4 GB fp32 spikes** at the
loss — logits, log-probs, and grad-wrt-log-probs, each (B·S=8192) × 128k
vocab × 4 bytes.  This script regenerates the same phase accounting for
the TPU build and writes ``EXPERIMENTS.md``.

Methodology (honest limits): the axon-tunneled v5e exposes no runtime
allocator stats (``device.memory_stats()`` → None), so the waterline is
assembled from the two sources that ARE exact:

  * component sizes by tensor walk (``utils/memory.py``) — the at-rest
    waterline (params / grads / optimizer state), same accounting as the
    reference's ``print_memory_stats``;
  * XLA's compile-time allocator plan (``compiled.memory_analysis()``) —
    argument + output + temp buffer sizes for each jitted step variant.
    ``temp_size_in_bytes`` is the compiler's actual activation/scratch
    high-water reservation, i.e. exactly the quantity the reference
    eyeballs off its profiler's memory timeline.

The A/B that matters: the dense-loss step (the reference's design)
versus the streamed-vocab-loss step (this repo's) — the three spikes
exist in the former's temp plan and are absent from the latter's.

    python scripts/memory_waterline.py [--out EXPERIMENTS.md]
"""

from __future__ import annotations

import argparse
import dataclasses
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

GB = 1 << 30


def analyze(step, *args) -> dict:
    """Compile-time memory plan; on backends that validate HBM fit at
    compile (axon) an over-budget plan comes back as the compiler's own
    used-vs-capacity numbers instead (parsed by the shared
    ``utils.memory.parse_hbm_oom`` — the same helper ``bench.py`` and
    the memory planner's compiler-OOM fallback use)."""
    from distributed_training_sandbox_tpu.utils.memory import parse_hbm_oom
    try:
        c = step.lower(*args).compile()
    except Exception as e:
        oom = parse_hbm_oom(str(e))
        if oom:
            return {"oom": True, "needed_gb": oom[0],
                    "capacity_gb": oom[1]}
        raise
    ma = c.memory_analysis()
    return {
        "args_gb": ma.argument_size_in_bytes / GB,
        "out_gb": ma.output_size_in_bytes / GB,
        "temp_gb": ma.temp_size_in_bytes / GB,
        "alias_gb": ma.alias_size_in_bytes / GB,
    }


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--out", default="EXPERIMENTS.md")
    p.add_argument("--model", default="SMOLLM3_3B_L8")
    p.add_argument("--seq", type=int, default=8192)
    p.add_argument("--batch", type=int, default=2)
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp
    from distributed_training_sandbox_tpu.models import transformer as T
    from distributed_training_sandbox_tpu.parallel import fsdp
    from distributed_training_sandbox_tpu.utils import make_mesh
    from distributed_training_sandbox_tpu.utils.memory import (
        device_memory_stats, tree_size_mb)

    cfg = getattr(T, args.model)
    mesh = make_mesh()
    ws = int(mesh.devices.size)
    B, S = max(args.batch, ws), args.seq
    platform = jax.devices()[0].platform

    params = T.init_params(jax.random.PRNGKey(0), cfg)
    shards = fsdp.shard_params_fsdp(params, mesh)
    del params
    opt = fsdp.init_fsdp_opt_state(shards)
    ids = jnp.zeros((B, S), jnp.int32)
    batch = (ids, ids)

    p_mb = tree_size_mb(shards)
    o_mb = tree_size_mb(opt)

    ids1 = jnp.zeros((ws, S), jnp.int32)
    variants = {}
    for name, over, b in (
        ("streamed_loss", {}, batch),
        ("streamed_save_dots", {"remat_policy": "save_dots"}, batch),
        ("dense_loss", {"loss_vocab_chunk": None}, batch),
        ("streamed_no_remat", {"remat": False}, batch),
        ("streamed_loss_b1", {}, (ids1, ids1)),
        ("dense_loss_b1", {"loss_vocab_chunk": None}, (ids1, ids1)),
    ):
        vcfg = dataclasses.replace(cfg, **over)
        step = fsdp.make_fsdp_train_step(shards, vcfg, mesh, donate=False)
        variants[name] = analyze(step, shards, opt, b)
        variants[name]["batch"] = int(b[0].shape[0])
        print(f"[waterline] {name}: {variants[name]}", flush=True)

    spike = B * S * cfg.vocab_size * 4 / GB
    runtime = device_memory_stats()
    runtime_note = (
        f"live allocator stats: {runtime}"
        if runtime and any(runtime.values()) else
        "runtime allocator stats unavailable through the axon tunnel — "
        "compile-time plan used instead")

    def vrow(name):
        v = variants[name]
        if v.get("oom"):
            return (f"| {name} | {v['batch']} | — | **does not fit: "
                    f"{v['needed_gb']:.2f} GB needed / "
                    f"{v['capacity_gb']:.2f} GB HBM** | — |")
        return (f"| {name} | {v['batch']} | {v['args_gb']:.2f} "
                f"| {v['temp_gb']:.2f} | {v['out_gb']:.2f} |")

    def spike_story():
        dense, stream = variants["dense_loss"], variants["streamed_loss"]
        if dense.get("oom"):
            head = (f"* `dense_loss` at batch {B} does not even compile: "
                    f"XLA's allocator wants **{dense['needed_gb']:.2f} GB** "
                    f"against {dense['capacity_gb']:.2f} GB of HBM — the "
                    f"spike buffers are right there in the failed plan.")
        else:
            head = (f"* `dense_loss` plans {dense['temp_gb']:.2f} GB of "
                    f"temp — the spikes are in the compiler's plan.")
        d1, s1 = variants["dense_loss_b1"], variants["streamed_loss_b1"]
        if not d1.get("oom") and not s1.get("oom"):
            per = B // max(d1["batch"], 1)
            tail = (f"* At batch {d1['batch']} (one {spike / per:.2f} GB "
                    f"logits-shaped buffer), the plans compile side by "
                    f"side: dense {d1['temp_gb']:.2f} GB temp vs streamed "
                    f"{s1['temp_gb']:.2f} GB — "
                    f"{d1['temp_gb'] - s1['temp_gb']:.2f} GB of loss-phase "
                    f"buffers removed by streaming.")
        else:
            tail = ("* The batch-1 dense plan also exceeds HBM; the spike "
                    "magnitude is the analytic B·S·V·4 above.")
        return head + "\n" + tail

    doc = f"""# EXPERIMENTS — FSDP memory waterline on TPU

Twin of the reference's measured memory phases
(`/root/reference/README.md:22-33`).  Regenerate with
`python scripts/memory_waterline.py` (run on the target hardware).

Config: `{args.model}` (the 3B architecture at {cfg.num_hidden_layers}
layers), batch {B} × seq {S}, vocab {cfg.vocab_size:,}, {ws}-device
`{platform}` mesh, explicit-FSDP step (AdamW, bf16 params).

## At rest (the reference's "~12 GB static" line)

The reference holds a bf16 3B 2-way shard + bf16 AdamW state ≈ 3.1 + 6.2
GB/device.  This build, per device (tensor walk, `utils/memory.py`):

| component | GB/device |
|---|---|
| param shards | {p_mb / 1024:.2f} |
| AdamW state (mu+nu) | {o_mb / 1024:.2f} |
| gradients (transient, = params) | {p_mb / 1024:.2f} |
| **total at rest** | **{(p_mb + o_mb) / 1024:.2f}** |

## Step memory plan (XLA `memory_analysis`, {platform})

`temp` is XLA's allocated scratch/activation high-water for one whole
train step — the quantity whose sawtooth+spikes the reference reads off
its profiler timeline.  ({runtime_note}.)

| step variant | batch | args GB | temp GB | out GB |
|---|---|---|---|---|
""" + "\n".join(vrow(n) for n in variants) + f"""

## The three ~4 GB spikes, found and removed

One fp32 logits-shaped buffer at this config is B·S·V·4 =
**{spike:.2f} GB** at batch {B} ({spike / B:.2f} GB at batch 1 — the
same B·S=8192 shape as the reference's trio of ~4 GB spikes: logits,
log-probs, grad-wrt-log-probs).

{spike_story()}
* `streamed_loss` (this repo's `loss_vocab_chunk`
  = {cfg.loss_vocab_chunk}) plans
  {variants['streamed_loss']['temp_gb']:.2f} GB of temp at batch {B}:
  the vocab streams through an online logsumexp in
  {cfg.loss_vocab_chunk}-row chunks, so no (B, S, V) tensor ever exists
  — forward OR backward.  This is what lets one 16 GB v5e train the
  8-layer 3B geometry at seq 8192 at all.
* `streamed_no_remat` isolates rematerialisation: without
  `jax.checkpoint` on the layer scan the activation plan is
  {'**unplannable (exceeds HBM: ' + format(variants['streamed_no_remat'].get('needed_gb', 0), '.2f') + ' GB needed)**'
   if variants['streamed_no_remat'].get('oom') else
   format(variants['streamed_no_remat']['temp_gb'], '.2f') + ' GB of temp'}
  (all {cfg.num_hidden_layers} layers' activations held for the
  backward) vs {variants['streamed_loss']['temp_gb']:.2f} GB with remat
  — the FLOPs-for-HBM trade the reference's `reshard_after_forward`
  comments gesture at, applied to activations.
* `streamed_save_dots` (remat_policy="save_dots") keeps every matmul
  output resident so the backward recomputes only elementwise ops:
  {'the plan exceeds HBM at this config (' + format(variants['streamed_save_dots'].get('needed_gb', 0), '.2f') + ' GB needed)'
   if variants['streamed_save_dots'].get('oom') else
   'it plans ' + format(variants['streamed_save_dots']['temp_gb'], '.2f') + ' GB of temp'}
  — the FLOPs-vs-HBM middle point between full remat and no remat
  (throughput for each policy is measured separately by `bench.py`;
  see `bench_matrix_tpu.json`).

## Reading guide vs the reference

| reference observation (README.md:22-33) | this build |
|---|---|
| ~12 GB at rest (3B 2-way bf16 + AdamW) | {(p_mb + o_mb) / 1024:.2f} GB at rest ({cfg.num_hidden_layers}-layer geometry, 1 device) |
| per-layer gather sawtooth in fwd/bwd | same choreography (`fsdp_layer_gather` scopes in traces); amplitude = one layer's full params |
| 3 × ~4 GB fp32 loss spikes | absent by design (streamed vocab); dense variant reproduces them in-plan |
"""
    Path(args.out).write_text(doc)
    print(f"[waterline] wrote {args.out}")


if __name__ == "__main__":
    main()
