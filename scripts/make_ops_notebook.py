"""Generate the NOTEBOOK form of the 02-operations teaching twin.

The reference teaches its communication layer as an interactive notebook
(``02-operations.ipynb``); this repo's tested script twin is
``scripts/ops_demo.py``.  VERDICT r2 noted the remaining delta is the
*form* — so this generator derives a real ``.ipynb`` from the script:
it splits ``ops_demo.main()`` at its ``# §N`` section markers into code
cells (one per section, sharing one namespace like notebook cells do),
EXECUTES them in order capturing each cell's stdout, and writes
``notebooks/02_operations_tpu.ipynb`` with those real outputs embedded.
The script stays the source of truth (and the tested artifact); re-run
this after editing it.

    python scripts/make_ops_notebook.py [--out notebooks/02_operations_tpu.ipynb]
"""

from __future__ import annotations

import argparse
import contextlib
import io
import json
import re
import sys
import textwrap
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

MARKER = re.compile(r"^    # (§\d+[^\n]*)$", re.M)


def split_sections() -> tuple[str, list[tuple[str, str]]]:
    """(module docstring, [(section title, dedented code), ...])."""
    src = (REPO / "scripts" / "ops_demo.py").read_text()
    module_doc = src.split('"""')[1]
    body = src.split("def main() -> dict:", 1)[1]
    body = body.split('so the test suite can assert semantics, '
                      "not just 'it printed'.\"\"\"", 1)[1]
    body = body.split("\nif __name__", 1)[0]
    # drop the trailing `return results`
    body = re.sub(r"\n    return results\s*$", "\n", body)

    def dedent4(code: str) -> str:
        # textwrap.dedent would bail: the banner strings embed column-0
        # text.  Function-body code is uniformly 4-deep — strip exactly
        # that from code lines and leave string-internal flush-left
        # lines untouched.
        return "\n".join(l[4:] if l.startswith("    ") else l
                         for l in code.splitlines())

    parts = MARKER.split(body)
    # parts = [pre, title1, code1, title2, code2, ...]; pre is empty-ish
    sections = []
    pre = parts[0]
    for title, code in zip(parts[1::2], parts[2::2]):
        sections.append((title.strip(), dedent4(code).strip("\n")))
    if pre.strip():
        sections.insert(0, ("setup", dedent4(pre).strip("\n")))
    return module_doc, sections


def helper_cell() -> str:
    """The script's helper defs, verbatim (banner/tinfo/viz)."""
    src = (REPO / "scripts" / "ops_demo.py").read_text()
    helpers = src.split('SEP = "─" * 72', 1)[1]
    helpers = helpers.split("def main() -> dict:", 1)[0]
    # repo-root discovery at RUN time (no baked absolute paths: the
    # committed notebook must work from any clone location)
    return ('import io, sys\n'
            'from pathlib import Path\n'
            'root = next(p for p in [Path.cwd(), *Path.cwd().parents]\n'
            '            if (p / "distributed_training_sandbox_tpu").exists())\n'
            'sys.path.insert(0, str(root))\n\n'
            'SEP = "─" * 72' + helpers.rstrip())


def run_cells(cells: list[str]) -> list[str]:
    ns: dict = {}
    outs = []
    for code in cells:
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            exec(compile(code, "<cell>", "exec"), ns)  # noqa: S102
        outs.append(buf.getvalue())
    return outs


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--out", default="notebooks/02_operations_tpu.ipynb")
    args = p.parse_args(argv)

    module_doc, sections = split_sections()
    code_cells = [helper_cell()] + [c for _, c in sections]
    outputs = run_cells(code_cells)

    nb_cells = [{
        "cell_type": "markdown", "metadata": {},
        "source": ("# 02-operations — the TPU twin, notebook form\n\n"
                   + module_doc.strip()).splitlines(keepends=True),
    }]
    titles = ["helpers (tinfo / viz / banner — nb cell 8)"] + \
        [t for t, _ in sections]
    for title, code, out in zip(titles, code_cells, outputs):
        nb_cells.append({
            "cell_type": "markdown", "metadata": {},
            "source": [f"## {title}"],
        })
        cell = {
            "cell_type": "code", "metadata": {},
            "execution_count": None,
            "source": code.splitlines(keepends=True),
            "outputs": [],
        }
        if out:
            cell["outputs"] = [{
                "output_type": "stream", "name": "stdout",
                "text": out.splitlines(keepends=True),
            }]
        nb_cells.append(cell)

    nb = {
        "cells": nb_cells,
        "metadata": {
            "kernelspec": {"display_name": "Python 3",
                           "language": "python", "name": "python3"},
            "language_info": {"name": "python"},
        },
        "nbformat": 4, "nbformat_minor": 5,
    }
    out_path = REPO / args.out
    out_path.parent.mkdir(exist_ok=True)
    out_path.write_text(json.dumps(nb, indent=1))
    print(f"[ops-notebook] {len(nb_cells)} cells "
          f"({len(code_cells)} code, executed) -> {out_path}")


if __name__ == "__main__":
    main()
