"""MoE-transformer training: switch-MoE MLP in every layer, dp × ep
(the reference's MoE story is one README learning note — SURVEY.md §2.2;
see ``parallel/expert.py`` and ``TransformerConfig.n_experts``).  Runs
under the resilience supervisor — the ep-sharded expert leaves round-trip
through RunState checkpoints with their shardings intact.

  python scripts/train_moe.py --cpu-devices 8 --ep 4 --experts 8 \\
      --num-steps 10
"""

from __future__ import annotations

import argparse
import dataclasses
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from distributed_training_sandbox_tpu.models import MODEL_REGISTRY as MODELS  # noqa: E402


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--cpu-devices", type=int, default=0)
    p.add_argument("--model", choices=sorted(MODELS), default="tiny")
    p.add_argument("--ep", type=int, default=2,
                   help="size of the ep mesh axis (dp gets the rest)")
    p.add_argument("--experts", type=int, default=8)
    p.add_argument("--moe-ffn", type=int, default=0,
                   help="per-expert ffn width (default intermediate/4)")
    p.add_argument("--top-k", type=int, default=1,
                   help="experts per token (1 = Switch, 2 = GShard "
                        "top-2 with normalized gates)")
    p.add_argument("--capacity-factor", type=float, default=2.0)
    p.add_argument("--z-weight", type=float, default=0.0,
                   help="router z-loss weight (ST-MoE) — the r5 corpus "
                        "A/B's router-collapse fix (keeps drop rates "
                        "single-digit at aux 0.01)")
    p.add_argument("--router-lr-mult", type=float, default=1.0,
                   help="LR multiplier on w_router leaves")
    args, rest = p.parse_known_args(argv)

    if args.cpu_devices:
        from distributed_training_sandbox_tpu.utils import use_cpu_devices
        use_cpu_devices(args.cpu_devices)

    from distributed_training_sandbox_tpu.utils import TrainConfig
    from distributed_training_sandbox_tpu import resilience as RZ

    cfg = TrainConfig.from_args(
        rest, sequence_length=256 if args.model == "tiny" else 8192)
    sup = RZ.Supervisor.from_config(
        cfg, strategy="moe",
        extra_fingerprint={"model": args.model, "ep": args.ep,
                           "experts": args.experts})
    return sup.run(lambda ctx: _leg(args, rest, cfg, ctx))


def _leg(args, rest, cfg, ctx):
    import itertools

    import jax
    import jax.numpy as jnp
    from distributed_training_sandbox_tpu.data import (
        make_packed_dataset, packed_batches)
    from distributed_training_sandbox_tpu.models import transformer as T
    from distributed_training_sandbox_tpu.ops import count_collectives
    from distributed_training_sandbox_tpu.parallel import expert, fsdp
    from distributed_training_sandbox_tpu.utils import (
        PerformanceTracker, ProfileSchedule, Profiler,
        make_mesh, print_memory_stats, set_seed)
    from distributed_training_sandbox_tpu.utils.flops import (
        get_model_flops_per_token)
    from distributed_training_sandbox_tpu.telemetry import TelemetryRun
    from distributed_training_sandbox_tpu.runtime import (
        DevicePrefetcher, StepPump)
    from distributed_training_sandbox_tpu import resilience as RZ
    from jax.sharding import PartitionSpec as P

    n_dev = len(jax.devices())
    if args.ep < 1 or n_dev % args.ep:
        raise SystemExit(f"--ep {args.ep} must be >= 1 and divide device "
                         f"count {n_dev}")
    if args.experts % args.ep:
        raise SystemExit(f"--experts {args.experts} must be divisible by "
                         f"ep={args.ep}")
    dp = n_dev // args.ep
    mesh = make_mesh({"dp": dp, "ep": args.ep})
    base: T.TransformerConfig = getattr(T, MODELS[args.model])
    mcfg = dataclasses.replace(
        base, n_experts=args.experts,
        moe_ffn=args.moe_ffn or max(base.intermediate_size // 4, 8),
        moe_top_k=args.top_k, moe_capacity_factor=args.capacity_factor,
        moe_router_z_weight=args.z_weight,
        moe_router_lr_mult=args.router_lr_mult)
    # consume the shared --precision knob (int8 variants quantize the
    # attention projections AND the per-expert MLP matmuls)
    if cfg.precision.startswith("int8"):
        mcfg = dataclasses.replace(mcfg, matmul_precision=cfg.precision)
    elif cfg.precision == "fp32":
        mcfg = dataclasses.replace(mcfg, dtype=jnp.float32)
    if cfg.batch_size % n_dev:
        if any(r == "--batch-size" or r.startswith("--batch-size=")
               for r in rest or []):
            raise SystemExit(f"--batch-size {cfg.batch_size} must be "
                             f"divisible by device count {n_dev}")
        cfg.batch_size = n_dev * max(1, cfg.batch_size // n_dev)
    print(f"[train_moe] model={args.model} experts={args.experts} "
          f"moe_ffn={mcfg.moe_ffn} ({mcfg.param_count()/1e9:.3f}B total) "
          f"mesh={dict(mesh.shape)} batch={cfg.batch_size} "
          f"seq={cfg.sequence_length} platform={jax.devices()[0].platform}")

    key = set_seed(cfg.seed)
    params = T.init_params(key, mcfg)
    shards = expert.shard_moe_lm_params(params, mesh)
    del params
    opt_state = fsdp.init_fsdp_opt_state(shards)
    print_memory_stats("train_moe-at-rest", params=shards,
                       opt_state=opt_state)
    rs = ctx.restore(like=RZ.RunState(params=shards, opt_state=opt_state,
                                      prng_key=key))
    if rs is not None:
        shards, opt_state = rs.params, rs.opt_state
    step = expert.make_moe_lm_train_step(shards, mcfg, mesh)

    input_ids, labels = make_packed_dataset(
        cfg.sequence_length, mcfg.vocab_size,
        num_tokens=max(cfg.batch_size * cfg.num_steps, 8)
        * (cfg.sequence_length + 1))
    probe = (jnp.zeros((cfg.batch_size, cfg.sequence_length), jnp.int32),) * 2
    counts = count_collectives(step, shards, opt_state, probe)
    print(f"[train_moe] per-step collectives (HLO): {counts} "
          f"(a2a dispatch/return in the scanned layer body + grad syncs)")
    from distributed_training_sandbox_tpu.analysis import evaluate_contract
    verdict = evaluate_contract("moe", counts, params=shards, mesh=mesh,
                                n_layers=mcfg.num_hidden_layers,
                                top_k=args.top_k)
    print(f"[train_moe] contract[moe]: {verdict.summary()}")
    ctx.verify_contract(verdict)
    from distributed_training_sandbox_tpu.analysis import (
        rules_manifest_verdict)
    rules_verdict = rules_manifest_verdict("moe", params=shards)
    print(f"[train_moe] rules[moe]: "
          f"{'ok' if rules_verdict['ok'] else 'MISMATCH'} "
          f"({rules_verdict.get('checked', 0)} leaves checked)")

    tracker = PerformanceTracker(
        warmup_steps=min(3, max(cfg.num_steps - 1, 0)),
        flops_per_token=get_model_flops_per_token(mcfg,
                                                  cfg.sequence_length),
        num_devices=n_dev)
    prof = Profiler(trace_dir=cfg.trace_dir,
                    schedule=ProfileSchedule(skip_first=0, wait=1,
                                             warmup=2, active=5)) \
        if cfg.profile else None
    batches = packed_batches(input_ids, labels, cfg.batch_size,
                             epochs=cfg.num_epochs * cfg.num_steps)
    if ctx.data_cursor:
        batches = itertools.islice(batches, ctx.data_cursor, None)
    # batch dim is sharded over the flattened (dp, ep) axes in the moe
    # step's in_spec — stage it that way from the prefetcher thread
    pref = DevicePrefetcher(batches, mesh=mesh, spec=P(("dp", "ep")),
                            depth=cfg.prefetch_depth)
    with pref, TelemetryRun(
            "moe", config=cfg, mesh=mesh, model=args.model,
            collective_counts=counts, profiler=prof,
            contract=verdict.to_dict(),
            rules=rules_verdict,
            lineage=ctx.manifest_lineage(),
            extra={"experts": args.experts, "ep": args.ep,
                   "top_k": args.top_k}) as telem:
        pref.spans = telem.spans   # prefetch waits onto the timeline
        pref.metrics = telem.metrics
        with StepPump(telem=telem, tracker=tracker, mode=cfg.dispatch,
                      sync_every=cfg.sync_every,
                      max_in_flight=cfg.max_in_flight) as pump:
            for i, batch in zip(range(ctx.start_step, cfg.num_steps), pref):
                if ctx.should_stop(i):
                    break
                if i == ctx.start_step:
                    # ledger join: compiled text at the loop's exact
                    # shardings (the staged batch, not a host copy); the
                    # memory ledger attributes the same compile's
                    # memory_analysis() to (shards, opt_state, batch)
                    telem.attach_step_hlo(step, shards, opt_state, batch)
                shards, opt_state, loss = step(shards, opt_state, batch)
                log = (lambda lf, i=i:
                       print(f"[train_moe] step {i:3d} loss {lf:.4f}")) \
                    if i % 5 == 0 or i == cfg.num_steps - 1 else None
                synced = pump.emit(
                    loss, tokens=cfg.batch_size * cfg.sequence_length,
                    log=log)
                ctx.after_step(i, synced, lambda i=i: RZ.RunState(
                    params=shards, opt_state=opt_state, step=i,
                    data_cursor=i + 1, prng_key=key,
                    loss_log=ctx.full_losses(pump.losses)))
        ctx.finalize(telem)
    metrics = pump.metrics or {}
    print(f"[train_moe] host syncs: {pump.host_sync_count} "
          f"({pump.sync_breakdown})")
    if prof:
        from distributed_training_sandbox_tpu.utils.trace_analysis import (
            split_from_trace)
        sp_ = split_from_trace(cfg.trace_dir)
        if sp_:
            print(sp_.report("train_moe"))
    if metrics:
        print(f"[train_moe] tokens/s {metrics['tokens_per_second']:.1f} "
              f"TFLOPS/dev (active) "
              f"{metrics.get('tflops_per_device', 0):.2f} "
              f"avg_loss {metrics.get('avg_loss', float('nan')):.4f}")
    if telem.run_dir:
        print(f"[train_moe] telemetry in {telem.run_dir}")
    metrics["losses"] = ctx.full_losses(pump.losses)
    return metrics


if __name__ == "__main__":
    main()
