"""Shared pipeline-parallel driver behind ``scripts/gpipe.py`` and
``scripts/1f1b.py`` — the epoch loop, synthetic data, JSON results file and
profiler of reference ``pp/gpipe.py:160-218`` / ``pp/1f1b.py:170-236``,
factored once (the reference duplicates it per file, SURVEY.md §2.8).
Runs under the resilience supervisor at epoch granularity: a RunState
checkpoint carries every stage's device-pinned params + Adam state, and
``--resume`` re-enters ``train_pipeline`` at the saved epoch with the
same fold_in(key, epoch) batch chain."""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

# Prepend the checkout root so the source tree always wins over any
# installed copy of the package (`pip install -e .` makes this a no-op).
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main(schedule: str, argv=None):
    from distributed_training_sandbox_tpu.models import (
        MODEL_REGISTRY as MODELS)

    p = argparse.ArgumentParser()
    p.add_argument("--cpu-devices", type=int, default=0)
    p.add_argument("--n-stages", type=int, default=2,
                   help="stage count; for the interleaved schedule this "
                        "is the TOTAL virtual-stage count (D*V)")
    p.add_argument("--virtual-per-device", type=int, default=2,
                   help="interleaved only: V chunks per device "
                        "(n_stages/V devices round-robin)")
    p.add_argument("--n-micro", type=int, default=4)
    p.add_argument("--model", choices=["mlp"] + sorted(MODELS),
                   default="mlp",
                   help="mlp = the reference's toy stack; otherwise "
                        "stage that transformer config "
                        "(build_transformer_pipeline)")
    p.add_argument("--results-file", type=str, default=None)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--warmup-epochs", type=int, default=0,
                   help="linear LR warmup over this many epochs — "
                        "large-vocab transformers need it here as much "
                        "as the flagship loop does")
    p.add_argument("--opt8", action="store_true",
                   help="int8-at-rest Adam moments per stage "
                        "(parallel.optim8) — halves the biggest "
                        "resident block for billion-param stage sets")
    args, rest = p.parse_known_args(argv)

    if args.cpu_devices:
        from distributed_training_sandbox_tpu.utils import use_cpu_devices
        use_cpu_devices(args.cpu_devices)

    from distributed_training_sandbox_tpu.utils import TrainConfig
    from distributed_training_sandbox_tpu import resilience as RZ

    cfg = TrainConfig.from_args(
        rest, batch_size=64, num_epochs=16,
        sequence_length=256 if args.model != "mlp" else 8192)
    sup = RZ.Supervisor.from_config(
        cfg, strategy=schedule,
        extra_fingerprint={"model": args.model, "n_stages": args.n_stages,
                           "n_micro": args.n_micro})
    return sup.run(lambda ctx: _leg(schedule, args, cfg, ctx))


def _leg(schedule, args, cfg, ctx):
    import jax
    from distributed_training_sandbox_tpu.utils import (
        set_seed, Profiler, ProfileSchedule)
    from distributed_training_sandbox_tpu.models import (
        pp_toy_mlp, MODEL_REGISTRY as MODELS)
    from distributed_training_sandbox_tpu.models import transformer as T
    from distributed_training_sandbox_tpu.models.mlp import PP_TOY_SIZES
    from distributed_training_sandbox_tpu.parallel.pipeline import (
        build_pipeline, build_transformer_pipeline, train_pipeline)
    from distributed_training_sandbox_tpu.resilience import RunState

    key = set_seed(cfg.seed)
    devices = None
    if schedule == "interleaved":
        v = args.virtual_per_device
        if args.n_stages % v:
            raise SystemExit(f"--n-stages {args.n_stages} not divisible "
                             f"by --virtual-per-device {v}")
        n_dev = args.n_stages // v
        devices = jax.local_devices()[:n_dev]
        if len(devices) < n_dev:
            raise SystemExit(f"need {n_dev} devices, have {len(devices)}")
    if args.model == "mlp":
        params = pp_toy_mlp(key)
        stages = build_pipeline(params, args.n_stages, devices=devices)
        width_in, width_out = PP_TOY_SIZES[0], PP_TOY_SIZES[-1]

        def make_batch(epoch):
            # fresh synthetic batch per epoch (reference gpipe.py:175-176)
            k = jax.random.fold_in(key, epoch)
            kx, ky = jax.random.split(k)
            return (jax.random.normal(kx, (cfg.batch_size, width_in)),
                    jax.random.normal(ky, (cfg.batch_size, width_out)))
    else:
        mcfg: T.TransformerConfig = getattr(T, MODELS[args.model])
        params = T.init_params(key, mcfg)
        stages = build_transformer_pipeline(params, mcfg, args.n_stages,
                                            devices=devices,
                                            opt8=args.opt8)

        def make_batch(epoch):
            # packed-window contract (inputs = w[:-1], labels = w[1:]),
            # matching lm_loss everywhere else.
            k = jax.random.fold_in(key, epoch)
            w = jax.random.randint(
                k, (cfg.batch_size, cfg.sequence_length + 1), 0,
                mcfg.vocab_size)
            return w[:, :-1], w[:, 1:]
    devs = [str(s.device) for s in stages]
    print(f"[{schedule}] model={args.model} stages={args.n_stages} "
          f"micro={args.n_micro} devices={devs}")

    # resume: every stage's device-pinned params + Adam state restore in
    # place (SingleDeviceSharding round-trips like any other sharding);
    # the epoch cursor re-enters the fold_in(key, epoch) batch chain
    rs = ctx.restore(like=RunState(
        params=[s.params for s in stages],
        opt_state=[s.opt_state for s in stages], prng_key=key))
    if rs is not None:
        for s, sp, so in zip(stages, rs.params, rs.opt_state):
            s.params, s.opt_state = sp, so
    start_epoch = ctx.start_step

    # choreography contract: stage programs must carry ZERO mesh
    # collectives — inter-stage comm is host-mediated device transfer.
    # gpipe vs 1f1b share the contract; interleaved rides on 1f1b's.
    from distributed_training_sandbox_tpu.analysis import evaluate_contract
    from distributed_training_sandbox_tpu.ops import count_collectives
    x0, _ = make_batch(start_epoch)
    stage_counts = count_collectives(
        stages[0].fwd.lower(stages[0].params, x0).as_text())
    cname = schedule if schedule in ("gpipe", "1f1b") else "1f1b"
    verdict = evaluate_contract(cname, stage_counts,
                                params=stages[0].params)
    print(f"[{schedule}] contract[{cname}]: {verdict.summary()}")
    ctx.verify_contract(verdict)

    prof = Profiler(trace_dir=cfg.trace_dir,
                    schedule=ProfileSchedule(skip_first=2, wait=1, warmup=1,
                                             active=4)) if cfg.profile else None

    ep_losses: list[float] = []

    def log(epoch, loss):
        ep_losses.append(float(loss))
        if epoch % 4 == 0 or epoch == cfg.num_epochs - 1:
            print(f"[{schedule}] epoch {epoch:3d} loss {loss:.6f}")
        if prof:
            prof.step()
        # pipeline schedules resolve the epoch loss host-side, so every
        # epoch is a sync point for the checkpointer
        ctx.after_step(epoch, True, lambda epoch=epoch: RunState(
            params=[s.params for s in stages],
            opt_state=[s.opt_state for s in stages],
            step=epoch, data_cursor=epoch + 1, prng_key=key,
            loss_log=ctx.full_losses(ep_losses)))

    if args.warmup_epochs:
        def lr_fn(e, *, _w=args.warmup_epochs, _lr=args.lr):
            return _lr * min(1.0, (e + 1) / _w)
    else:
        lr_fn = args.lr
    # Host-side batch prefetch: the pipeline's inter-stage comm is
    # host-mediated device transfer, so there is no mesh sharding to
    # commit to — but epoch e+1's synthetic batch can still be built
    # while the schedule runs epoch e.
    from distributed_training_sandbox_tpu.runtime import DevicePrefetcher
    pref = DevicePrefetcher(
        (make_batch(e) for e in range(start_epoch, cfg.num_epochs)),
        depth=cfg.prefetch_depth)
    with pref:
        result = train_pipeline(stages, schedule,
                                lambda e: next(pref),
                                num_epochs=cfg.num_epochs,
                                n_micro=args.n_micro,
                                lr=lr_fn, log=log,
                                start_epoch=start_epoch,
                                should_stop=ctx.should_stop)
    if prof:
        prof.stop()
    ctx.finalize()   # final RunState save; raises Preempted on SIGTERM

    out = result.as_dict()   # incl. max_stored_activations + memory plan
    out["contract"] = verdict.to_dict()
    out["pump"] = {"prefetch_depth": cfg.prefetch_depth,
                   "dispatch": "host-prefetch"}
    out["losses"] = ctx.full_losses(ep_losses)
    if ctx.manifest_lineage():
        out["resilience"] = ctx.manifest_lineage()
    print(f"[{schedule}] {json.dumps(out)}")
    if args.results_file:
        Path(args.results_file).write_text(json.dumps(out, indent=2))
        print(f"[{schedule}] results -> {args.results_file}")
    return out
