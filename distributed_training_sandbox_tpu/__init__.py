"""TPU-native distributed-training sandbox.

A ground-up JAX / XLA / shard_map / Pallas framework with the capabilities of
the reference `xo-toybox/distributed-training-sandbox` (CUDA/NCCL/torch):
from-scratch, trace-first implementations of DDP, ZeRO-1/2/3, fully-sharded
training of a real transformer, GPipe/1F1B pipeline schedules, and a
low-precision benchmark sweep — each replaying the reference's collective
choreography over a named TPU mesh, instrumented with the XLA profiler.

Layer map (SURVEY.md §1):
  L1 comm backend  -> ops.collectives (lax.psum / all_gather / psum_scatter /
                      ppermute over a named Mesh; ICI/DCN in place of NCCL)
  L2 shared utils  -> utils.{mesh,prng,memory,tracker,flops,profiling,config}
  L3 strategies    -> parallel.{ddp,zero1,zero2,zero3,fsdp,pipeline} + scripts/
  L4 launch        -> launch.launcher (config-driven, run-id'd trace dirs)
"""

__version__ = "0.2.0"

from . import utils, ops  # noqa: F401
# `launch` is importable as a subpackage (`from distributed_training_sandbox_tpu
# import launch`) but not imported eagerly: it is pure stdlib and must stay
# importable before jax backend initialization.
