from .mesh import (  # noqa: F401
    get,
    make_mesh,
    get_mesh,
    register_mesh,
    setup_distributed,
    shutdown_distributed,
    auto_initialize_from_env,
    bringup_barrier,
    BringupTimeout,
    host_to_global,
    process_local_put,
    local_scalar,
    use_cpu_devices,
)
from .prng import set_seed, key_for_axis  # noqa: F401
from .memory import (  # noqa: F401
    tree_size_mb,
    tree_local_size_mb,
    device_memory_stats,
    print_memory_stats,
    peak_memory_gb,
    classify_failure,
)
from .tracker import PerformanceTracker  # noqa: F401
from .flops import get_model_flops_per_token  # noqa: F401
from .profiling import ProfileSchedule, Profiler, annotate, scope  # noqa: F401
from .config import TrainConfig, build_argparser, build_run_id  # noqa: F401
from . import checkpoint  # noqa: F401  (orbax imported lazily inside)
