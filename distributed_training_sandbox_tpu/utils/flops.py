"""Analytic transformer FLOPs model in the role of ``get_model_flops_per_token``
(reference ``fsdp/utils.py:94-115``): per-token forward+backward FLOPs from the
architecture, feeding the TFLOPS / MFU metric in PerformanceTracker.

Convention note — this model deliberately does NOT match the reference's
formula term-for-term.  Differences:

  * the sequence-quadratic attention term carries a 0.5 causal discount
    (only half the positions are attended on average); the reference counts
    the full square;
  * the vocab head (``2·h·vocab`` per token) is included; the reference
    ignores it (at 128k vocab it is ~9% of a 3B model's per-token FLOPs).

Both conventions are self-consistent for A/B ratios; absolute TFLOPS printed
by this repo are computed under THIS convention, including when converting
the reference's published tok/s baselines for the ``vs_baseline`` ratio (see
``bench.py``), so the ratio remains apples-to-apples.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class FlopsConfig:
    hidden_size: int
    intermediate_size: int
    num_hidden_layers: int
    num_attention_heads: int
    num_key_value_heads: int
    vocab_size: int
    gated_mlp: bool = True


def get_model_flops_per_token(cfg, seq_len: int, *, backward_factor: float = 2.0,
                              causal: bool = True,
                              include_lm_head: bool = True) -> float:
    """Forward+backward FLOPs per token.

    Matmul FLOPs count 2·m·n·k; the backward pass re-does each matmul twice
    (grad-wrt-input and grad-wrt-weight), hence the (1 + backward_factor)
    multiplier — the same convention the reference's analytic model uses.
    ``cfg`` is any object with the FlopsConfig attribute names (an HF-style
    config works unchanged).

    ``include_lm_head=False`` drops the per-token vocab-projection term —
    the honest count for heads that are NOT a per-token unembedding (e.g.
    the pooled classifier, whose head is one (B,H)@(H,2) matmul; counting
    2·h·vocab per token there overstates TFLOPS/MFU by ~10-15% at
    SmolLM3-350M geometry).
    """
    h = cfg.hidden_size
    inter = cfg.intermediate_size
    layers = cfg.num_hidden_layers
    n_q = cfg.num_attention_heads
    n_kv = getattr(cfg, "num_key_value_heads", n_q) or n_q
    head_dim = getattr(cfg, "head_dim", None) or h // n_q
    vocab = cfg.vocab_size

    q_proj = 2 * h * (n_q * head_dim)
    kv_proj = 2 * 2 * h * (n_kv * head_dim)
    o_proj = 2 * (n_q * head_dim) * h
    # QK^T and PV: each is 2 · seq · head_dim per head per token; causal
    # attention touches half the positions on average.
    attn_quadratic = 2 * 2 * (n_q * head_dim) * seq_len * (0.5 if causal else 1.0)
    router = 0
    active_k = 1
    n_exp = getattr(cfg, "n_experts", 0)
    if n_exp:
        # top-k MoE: each token runs k experts of moe_ffn width (active
        # FLOPs, the MFU-relevant count) plus the router matmul.
        inter = getattr(cfg, "moe_ffn", None) or inter
        router = 2 * h * n_exp
        active_k = getattr(cfg, "moe_top_k", 1)
    mlp = (3 if getattr(cfg, "gated_mlp", True) else 2) * 2 * h * inter \
        * active_k
    per_layer = q_proj + kv_proj + o_proj + attn_quadratic + mlp + router
    head = 2 * h * vocab if include_lm_head else 0
    fwd = layers * per_layer + head
    return fwd * (1.0 + backward_factor)
