"""XLA profiler harness with torch.profiler-style schedule semantics.

The reference wraps every hot loop in ``torch.profiler.profile`` with a
``schedule(skip_first, wait, warmup, active, repeat)`` and
``tensorboard_trace_handler`` (``DDP/ddp.py:128-151``,
``fsdp/train_fsdp.py:106-138``), calling ``profiler.step()`` each iteration and
marking phases with ``record_function``.  The TPU twin drives
``jax.profiler.start_trace / stop_trace`` from the same schedule state machine
(warmup steps are traced too — they are how you *see* warmup in the timeline),
writes TensorBoard/perfetto-compatible traces into the same ``TRACE_DIR``
contract, and marks phases with ``jax.profiler.TraceAnnotation`` (host span) +
``jax.named_scope`` (device-side op names).
"""

from __future__ import annotations

import contextlib
import os
from dataclasses import dataclass

import jax


@dataclass(frozen=True)
class ProfileSchedule:
    """skip_first → (wait → warmup+active)×repeat, as in torch.profiler.

    The reference's DDP/zero schedule: skip_first=5, wait=1, warmup=2,
    active=5, repeat=1 (``DDP/ddp.py:132-138``); fsdp uses wait=5, warmup=5,
    active=10 (``fsdp/train_fsdp.py:124-137``).
    """
    skip_first: int = 5
    wait: int = 1
    warmup: int = 2
    active: int = 5
    repeat: int = 1

    def phase(self, step: int) -> str:
        """Phase for 0-based step index: 'skip' | 'wait' | 'trace' | 'done'."""
        if step < self.skip_first:
            return "skip"
        s = step - self.skip_first
        cycle = self.wait + self.warmup + self.active
        if self.repeat and s >= cycle * self.repeat:
            return "done"
        pos = s % cycle
        return "wait" if pos < self.wait else "trace"


def default_trace_dir() -> str:
    """TRACE_DIR env contract (reference ``modal_utils.py`` / ``zero1.py:210``:
    launcher exports TRACE_DIR, scripts default to ./profiler_traces)."""
    return os.environ.get("TRACE_DIR",
                          os.environ.get("DDP_TRACE_DIR", "./profiler_traces"))


class Profiler:
    """Schedule-driven jax.profiler session.  Call ``step()`` once per
    training step (the reference calls ``profiler.step()`` inside the
    optimizer-step block, ``DDP/ddp.py:172-173``)."""

    def __init__(self, trace_dir: str | None = None,
                 schedule: ProfileSchedule | None = None,
                 enabled: bool | None = None):
        self.trace_dir = trace_dir or default_trace_dir()
        self.schedule = schedule or ProfileSchedule()
        # rank-0-only tracing, as in every reference script
        self.enabled = (jax.process_index() == 0) if enabled is None else enabled
        self._step = 0
        self._tracing = False
        # session directories (plugins/profile/<ts>/) THIS profiler
        # created, newest last — recorded by diffing the dir around each
        # start/stop pair so trace analysis can target exactly the
        # session it owns instead of "newest file anywhere by mtime"
        self.owned_sessions: list[str] = []
        self._pre_sessions: set[str] = set()

    def _sessions(self) -> set[str]:
        from .trace_analysis import profile_session_dirs
        return set(profile_session_dirs(self.trace_dir))

    def _record_owned(self) -> None:
        new = sorted(self._sessions() - self._pre_sessions)
        self.owned_sessions.extend(
            s for s in new if s not in self.owned_sessions)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    def pending_transition(self) -> bool:
        """True iff the NEXT ``step()`` call will start or stop a trace.
        The async step pump barriers exactly there, so traces bound the
        intended steps even with work in flight."""
        if not self.enabled:
            return False
        return (self.schedule.phase(self._step + 1) == "trace") \
            != self._tracing

    def step(self) -> None:
        if not self.enabled:
            return
        self._step += 1
        phase = self.schedule.phase(self._step)  # phase of the *next* step
        if phase == "trace" and not self._tracing:
            os.makedirs(self.trace_dir, exist_ok=True)
            self._pre_sessions = self._sessions()
            jax.profiler.start_trace(self.trace_dir)
            self._tracing = True
        elif phase in ("wait", "done", "skip") and self._tracing:
            jax.profiler.stop_trace()
            self._tracing = False
            self._record_owned()

    def stop(self) -> None:
        if self._tracing:
            jax.profiler.stop_trace()
            self._tracing = False
            self._record_owned()


@contextlib.contextmanager
def annotate(name: str):
    """Host-side phase marker, twin of ``record_function`` phase labels
    ("data_movement", "forward", "sync_grads", "opt_step", … —
    ``DDP/ddp.py:158-170``).  Shows as a span in the profiler timeline."""
    with jax.profiler.TraceAnnotation(name):
        yield


def scope(name: str):
    """Device-side marker for code *inside* jit: prefixes XLA op names so
    collectives/matmuls attribute to the phase in the trace."""
    return jax.named_scope(name)
