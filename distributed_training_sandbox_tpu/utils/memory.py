"""Memory accounting: twin of reference ``training_utils/memory.py`` (component
sizes in MB by tensor-walking) plus device-allocator stats from the XLA client
(what ``torch.cuda.memory_allocated / max_memory_allocated`` is to the
reference, ``device.memory_stats()`` is here — reference
``DDP/training_utils/memory.py:8-50``, ``fsdp/utils.py:204-219``).

CPU-simulated devices expose no allocator stats; every accessor degrades to
zeros there so the same scripts run on the CI mesh.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

MB = 1024**2
GB = 1024**3


def classify_failure(e: Exception) -> tuple[str, str]:
    """(kind, message) for a benchmark-sweep failure: kind is ``"oom"``
    only when XLA's own verdict says so — anything else is a real error
    and must not be published as the memory edge (a transient compile
    bug would otherwise masquerade as the OOM wall).  The ONE place the
    OOM pattern lives, shared by every sweep script."""
    import re
    msg = str(e)
    m = re.search(r"(Ran out of memory|RESOURCE_EXHAUSTED)[^\n]*", msg)
    if m:
        return "oom", m.group(0)[:200]
    return "error", f"{type(e).__name__}: {msg[:200]}"


def parse_hbm_oom(msg: str) -> tuple[float, float] | None:
    """``(needed_gb, capacity_gb)`` from XLA's HBM verdict — the
    ``Used X.XXG of Y.YYG hbm`` clause its compile- and runtime-OOM
    messages both carry — or None when the text carries no such verdict.
    The ONE place this regex lives: ``scripts/memory_waterline.py``,
    ``bench.py``'s structured OOM rows and the memory planner's
    compiler-OOM fallback all parse through here."""
    import re
    m = re.search(r"Used ([\d.]+)G(?:iB)? of ([\d.]+)G(?:iB)? hbm", msg)
    if m:
        return float(m.group(1)), float(m.group(2))
    return None


def hbm_capacity_gb(device: jax.Device | None = None) -> float | None:
    """Per-device accelerator memory capacity in GB from the allocator's
    ``bytes_limit``, or None where the backend exposes none (CPU sim) —
    the planner's default ``--hbm-budget-gb`` when the user names no
    budget."""
    limit = device_memory_stats(device)["bytes_limit"]
    return limit / GB if limit else None


def tree_size_bytes(tree: Any) -> int:
    """Total bytes of all array leaves (tensor-walk twin of
    ``memory.py:8-34``)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "nbytes"):
            total += leaf.nbytes
        elif hasattr(leaf, "size") and hasattr(leaf, "dtype"):
            total += leaf.size * jnp.dtype(leaf.dtype).itemsize
    return total


def tree_size_mb(tree: Any) -> float:
    """`tree_size_bytes` in MB."""
    return tree_size_bytes(tree) / MB


def tree_local_size_mb(tree: Any) -> float:
    """Size of the *locally addressable* shards of all leaves, in MB — what
    one device actually holds.  For a ZeRO-sharded optimizer state this is
    ~1/ws of ``tree_size_mb``; that delta is the reference's A/B "pass
    signal" (``zero/zero1.py:316-324``)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if shards:
            local_dev_ids = {s.device.id for s in shards}
            # per-device bytes: one device's worth of addressable data
            per_dev = sum(s.data.nbytes for s in shards) / max(len(local_dev_ids), 1)
            total += per_dev
        elif hasattr(leaf, "nbytes"):
            total += leaf.nbytes
    return total / MB


def device_memory_stats(device: jax.Device | None = None) -> dict[str, int]:
    """Allocator stats for one device: ``bytes_in_use`` / ``peak_bytes_in_use``
    / ``bytes_limit`` (zeros when the backend exposes none, e.g. CPU sim)."""
    device = device or jax.local_devices()[0]
    stats = device.memory_stats() if hasattr(device, "memory_stats") else None
    stats = stats or {}
    return {
        "bytes_in_use": int(stats.get("bytes_in_use", 0)),
        "peak_bytes_in_use": int(stats.get("peak_bytes_in_use", 0)),
        "bytes_limit": int(stats.get("bytes_limit", 0)),
    }


def peak_memory_gb(device: jax.Device | None = None) -> float:
    return device_memory_stats(device)["peak_bytes_in_use"] / GB


def all_devices_memory_gb() -> dict[str, dict[str, float]]:
    """Per-device current/peak GB, twin of ``gpu_memory_usage_all``
    (``fsdp/utils.py:204-219``).  Delegates to the memory ledger's one
    shared sampler (``telemetry.memledger.get_sampler``) so every
    consumer polls the allocator through the same site.  Lazy import:
    memledger imports this module."""
    from ..telemetry.memledger import get_sampler
    return get_sampler().all_devices_gb()


def print_memory_stats(
    tag: str,
    params: Any = None,
    grads: Any = None,
    opt_state: Any = None,
    *,
    printer=print,
) -> dict[str, float]:
    """Component-wise MB + allocator totals, twin of ``print_memory_stats``
    (``DDP/training_utils/memory.py:37-50``).  Returns the dict it prints so
    tests/A-B comparisons can assert on it."""
    stats = {}
    if params is not None:
        stats["model_mb"] = tree_size_mb(params)
    if grads is not None:
        stats["grads_mb"] = tree_size_mb(grads)
    if opt_state is not None:
        stats["optimizer_mb"] = tree_size_mb(opt_state)
    dev = device_memory_stats()
    stats["device_in_use_mb"] = dev["bytes_in_use"] / MB
    stats["device_peak_mb"] = dev["peak_bytes_in_use"] / MB
    parts = " | ".join(f"{k}={v:,.1f}" for k, v in stats.items())
    printer(f"[memory:{tag}] {parts}")
    return stats
