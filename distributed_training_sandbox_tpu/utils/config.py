"""One small config layer unifying the reference's three mechanisms (SURVEY.md
§5.6): inline launcher dicts, per-script argparse, and the TRACE_DIR env
contract.  External knobs keep the reference surface:
``--script / --run-name / --num-steps / --num-epochs / --sequence-length /
--precision`` (reference ``fp8/fp8_benchmark.py:41-50``, ``pp/1f1b.py:172-174``).
"""

from __future__ import annotations

import argparse
import datetime
import re
from dataclasses import dataclass, field, fields

from .profiling import default_trace_dir


def default_results_dir() -> str:
    """RESULTS_DIR env contract for telemetry run directories, sibling of
    the TRACE_DIR one (``profiling.default_trace_dir``)."""
    import os
    return os.environ.get("RESULTS_DIR", "./runs")


def build_run_id(label: str | None = None) -> str:
    """``YYYYMMDD-HHMMSS[-label]`` run ids, UTC, sanitized label — twin of
    ``modal_utils.build_run_id`` (``modal_utils.py:98-104``)."""
    ts = datetime.datetime.now(datetime.timezone.utc).strftime("%Y%m%d-%H%M%S")
    if label:
        label = re.sub(r"[^A-Za-z0-9_.-]+", "-", label).strip("-")
        return f"{ts}-{label}" if label else ts
    return ts


@dataclass
class TrainConfig:
    num_steps: int = 20
    num_epochs: int = 1
    batch_size: int = 32
    sequence_length: int = 8192
    precision: str = "bf16"
    seed: int = 42
    run_name: str | None = None
    trace_dir: str = field(default_factory=default_trace_dir)
    profile: bool = True
    results_dir: str = field(default_factory=default_results_dir)
    telemetry: bool = True
    # live Prometheus scrape endpoint (telemetry.metrics): None = off,
    # 0 = ephemeral port (tests), >0 = fixed port
    metrics_port: int | None = None
    # --- async step pump (runtime/) --------------------------------------
    # dispatch: "async" = bounded in-flight dispatch, losses retired as
    # device arrays, host blocks only at the sync policy points;
    # "sync" = the classic block-every-step loop (the A/B baseline).
    dispatch: str = "async"
    prefetch_depth: int = 2      # DevicePrefetcher staging depth
    sync_every: int = 10         # barrier every N steps (0 = exit only)
    max_in_flight: int = 16      # bounded dispatch window (backpressure)
    bucket_mb: float | None = None  # ddp: all-reduce grads in ~N MB buckets
    # --- overlap engine (ops/collectives.py ring decomposition) ----------
    # overlap: "ring" decomposes the strategy's hot-path collectives into
    # ppermute ring hops the scheduler can hide behind compute — bitwise-
    # identical losses to "none" (fsdp gathers / tp rejoin psums);
    # "ring_fused" (fsdp only) additionally fuses the gather into the
    # projection matmuls (all_gather_matmul — numerically equivalent,
    # not bitwise).
    overlap: str = "none"
    # accum_steps: microbatched gradient accumulation — lax.scan over k
    # splits of the batch with a donated grad carry; per-microbatch
    # collectives pipeline against the next microbatch's compute.
    accum_steps: int = 1
    # quantize_grads: ddp int8 bucketed grad sync (ddp_q8 choreography);
    # error_feedback threads the EF residual so quantization error is
    # re-applied next step instead of compounding.
    quantize_grads: bool = False
    error_feedback: bool = False
    # --- memory planner (memory_plan/) -----------------------------------
    # offload: park optimizer state ("opt") — plus the named remat-saved
    # activations ("opt_act") — in pinned host memory, streamed around
    # the step under a declared transfer contract; hbm_budget_gb is the
    # per-device budget the pre-flight waterline predictor judges
    # against (default: the device's own bytes_limit when exposed);
    # auto_fit lets the planner pick remat × accum × quant × offload to
    # fit the target batch under that budget.
    offload: str = "none"
    auto_fit: bool = False
    hbm_budget_gb: float | None = None
    # --- resilience runtime (resilience/) --------------------------------
    # checkpoint_dir: RunState checkpoints (params + opt + PRNG root +
    # data cursor + loss log) land here; checkpoint_every=N saves async
    # at the pump's next sync point every N steps (0 = final state only).
    checkpoint_dir: str | None = None
    checkpoint_every: int = 0
    resume: bool = False         # restore the latest step before the loop
    max_restarts: int = 0        # in-process restart budget after a fault
    inject_fault: str | None = None  # debug: "crash@N" / "preempt@N[:leg]"
    # --- elastic mesh runtime (resilience/elastic.py) ---------------------
    # elastic: worker loss (kill_worker fault / heartbeat death / hung
    # step) shrinks the mesh to the survivors and resumes from the
    # latest checkpoint instead of being fatal; world_size builds the
    # mesh over the first N devices (0 = all — the survivor slice after
    # a shrink, or a deliberate small-mesh run); watchdog_timeout wraps
    # the pump's sync points so a hung collective raises a diagnosable
    # StepTimeoutError within the budget; heartbeat_dir is where this
    # worker's liveness file lands (the launcher coordinator's probe —
    # defaults to $DTS_HEARTBEAT_DIR when spawned by dts-launch).
    elastic: bool = False
    world_size: int = 0
    watchdog_timeout: float = 0.0
    heartbeat_dir: str | None = None

    @classmethod
    def from_args(cls, argv=None, **overrides) -> "TrainConfig":
        """CLI args win; ``overrides`` are script-specific *defaults* that
        apply only where the user passed nothing.

        This is the LAST parser in every script's chain, so leftover
        ``--flags`` are typos or abbreviations (abbrev is disabled) —
        silently dropping them would mean training with a different
        config than the user asked for; error instead."""
        ns, rest = build_argparser().parse_known_args(argv)
        unknown = [a for a in rest if a.startswith("--")]
        if unknown:
            raise SystemExit(
                f"unrecognized training flags: {' '.join(unknown)} "
                f"(abbreviations are not accepted; see --help)")
        kwargs = {f.name: getattr(ns, f.name) for f in fields(cls)
                  if hasattr(ns, f.name) and getattr(ns, f.name) is not None}
        for k, v in overrides.items():
            kwargs.setdefault(k, v)
        return cls(**kwargs)


def build_argparser(parser: argparse.ArgumentParser | None = None):
    # allow_abbrev=False: scripts detect explicitly-passed flags by
    # literal string match (e.g. the batch-size divisibility guards);
    # prefix abbreviations would silently bypass those checks.
    p = parser or argparse.ArgumentParser(conflict_handler="resolve",
                                          allow_abbrev=False)
    p.add_argument("--num-steps", dest="num_steps", type=int, default=None)
    p.add_argument("--num-epochs", dest="num_epochs", type=int, default=None)
    p.add_argument("--batch-size", dest="batch_size", type=int, default=None)
    p.add_argument("--sequence-length", dest="sequence_length", type=int,
                   default=None)
    # No "fp8" choice: v5e has no fp8 units and the reference's own
    # `--precision fp8` flag in fsdp/ is declared-but-ignored (its quirk #9,
    # SURVEY.md §2.9) — int8 is the implemented low-precision path here.
    p.add_argument("--precision", dest="precision",
                   choices=["bf16", "fp32", "int8", "int8_pallas",
                            "int8_bwd", "int8_pallas_bwd"],
                   default=None)
    p.add_argument("--seed", dest="seed", type=int, default=None)
    p.add_argument("--run-name", dest="run_name", type=str, default=None)
    p.add_argument("--trace-dir", dest="trace_dir", type=str, default=None)
    p.add_argument("--no-profile", dest="profile", action="store_false",
                   default=None)
    p.add_argument("--results-dir", dest="results_dir", type=str,
                   default=None,
                   help="telemetry run-dir root (default $RESULTS_DIR "
                        "or ./runs)")
    p.add_argument("--no-telemetry", dest="telemetry",
                   action="store_false", default=None,
                   help="disable the manifest/steps.jsonl/summary.json "
                        "run artifacts")
    p.add_argument("--metrics-port", dest="metrics_port", type=int,
                   default=None,
                   help="serve live Prometheus metrics on this port "
                        "while the run is going (0 = ephemeral port; "
                        "also writes periodic metrics.jsonl snapshots)")
    p.add_argument("--dispatch", dest="dispatch",
                   choices=["async", "sync"], default=None,
                   help="step pump mode: bounded async dispatch (default) "
                        "or the classic block-every-step loop")
    p.add_argument("--prefetch-depth", dest="prefetch_depth", type=int,
                   default=None,
                   help="batches staged ahead by the DevicePrefetcher "
                        "(default 2 = double buffering)")
    p.add_argument("--sync-every", dest="sync_every", type=int,
                   default=None,
                   help="async mode: host barrier every N steps "
                        "(0 = only at profile boundaries and loop exit)")
    p.add_argument("--max-in-flight", dest="max_in_flight", type=int,
                   default=None,
                   help="async mode: bound on dispatched steps with "
                        "unretired losses")
    p.add_argument("--bucket-mb", dest="bucket_mb", type=float,
                   default=None,
                   help="ddp: flatten per-dtype gradient leaves into "
                        "~N MB flat buckets before the all-reduce "
                        "(torch-DDP style; default: per-leaf)")
    p.add_argument("--overlap", dest="overlap",
                   choices=["none", "ring", "ring_fused"], default=None,
                   help="overlap engine: ring-decompose the strategy's "
                        "hot collectives (fsdp gathers / tp rejoins) "
                        "into schedulable ppermute hops; 'ring' is "
                        "bitwise-identical to 'none', 'ring_fused' "
                        "(fsdp) fuses the gather into the matmuls")
    p.add_argument("--accum-steps", dest="accum_steps", type=int,
                   default=None,
                   help="microbatched gradient accumulation: scan over "
                        "N microbatches per optimizer step (must divide "
                        "the per-device batch)")
    p.add_argument("--quantize-grads", dest="quantize_grads",
                   action="store_true", default=None,
                   help="ddp: int8 quantized bucketed gradient "
                        "all-reduce (per-bucket scales; ~8x less bus "
                        "traffic, within one half-quantum of exact)")
    p.add_argument("--error-feedback", dest="error_feedback",
                   action="store_true", default=None,
                   help="with --quantize-grads: carry the quantization "
                        "error as a per-rank residual applied to the "
                        "next step's buckets (EF-SGD)")
    p.add_argument("--offload", dest="offload",
                   choices=["none", "opt", "opt_act"], default=None,
                   help="host offload: park optimizer state (opt) — and "
                        "the named remat-saved activations (opt_act) — "
                        "in pinned host memory, streamed around the step "
                        "under a declared transfer contract")
    p.add_argument("--auto-fit", dest="auto_fit", action="store_true",
                   default=None,
                   help="memory planner: search remat × accum × quant × "
                        "offload and run the best predicted-fitting "
                        "config under --hbm-budget-gb")
    p.add_argument("--hbm-budget-gb", dest="hbm_budget_gb", type=float,
                   default=None,
                   help="per-device HBM budget the pre-flight waterline "
                        "prediction is judged against (default: the "
                        "device's reported capacity when exposed)")
    p.add_argument("--checkpoint-dir", dest="checkpoint_dir", type=str,
                   default=None,
                   help="save full RunState (params+opt+PRNG+data cursor) "
                        "checkpoints here; enables --resume")
    p.add_argument("--checkpoint-every", dest="checkpoint_every", type=int,
                   default=None,
                   help="async RunState save every N steps, written at "
                        "the pump's next sync point (0 = final only)")
    p.add_argument("--resume", dest="resume", action="store_true",
                   default=None,
                   help="resume from the latest step in --checkpoint-dir "
                        "(bitwise-exact: data cursor + PRNG included)")
    p.add_argument("--max-restarts", dest="max_restarts", type=int,
                   default=None,
                   help="in-process restart budget: resume from the "
                        "latest checkpoint after a crash/preemption")
    p.add_argument("--inject-fault", dest="inject_fault", type=str,
                   default=None,
                   help="debug fault injection: crash@N, preempt@N[:leg], "
                        "kill_worker@N:rank, hang@N, or slow@N:ms "
                        "(deterministic, fires once)")
    p.add_argument("--elastic", dest="elastic", action="store_true",
                   default=None,
                   help="elastic mesh: on worker loss / hung step, shrink "
                        "to the survivors (8→4→2), reshard-restore the "
                        "latest checkpoint, and continue (needs "
                        "--checkpoint-dir and --max-restarts)")
    p.add_argument("--world-size", dest="world_size", type=int,
                   default=None,
                   help="build the mesh over the first N visible devices "
                        "(0 = all; the survivor slice of an elastic "
                        "shrink, or a deliberate small-mesh run)")
    p.add_argument("--watchdog-timeout", dest="watchdog_timeout",
                   type=float, default=None,
                   help="collective watchdog: a pump sync point that "
                        "does not retire within N seconds raises "
                        "StepTimeoutError (step index + last contract "
                        "verdict attached) instead of hanging (0 = off)")
    p.add_argument("--heartbeat-dir", dest="heartbeat_dir", type=str,
                   default=None,
                   help="write this worker's per-step liveness file "
                        "here (the launcher coordinator's failure "
                        "detector; default $DTS_HEARTBEAT_DIR)")
    return p
