"""Warmup-aware throughput/TFLOPS tracker, twin of ``PerformanceTracker``
(reference ``fsdp/utils.py:129-193``): restarts its clock once warmup steps
have passed, then reports tokens/s, steps/s, per-device TFLOPS from the
analytic FLOPs model, and peak device memory.

Peak memory is *sampled*, not polled: ``device_memory_stats()`` is a
device round-trip, and the old behaviour of querying it inside every
``metrics()`` call put one on the critical path of every step.  The
allocator peak is monotone, so the tracker now samples it every
``memory_sample_every`` steps (default 10) and once more at finalize
(``metrics(sample_memory=True)`` — the step pump does this at close);
between samples ``metrics()`` reuses the cached value.  The returned
dict shape is unchanged.
"""

from __future__ import annotations

import time

import jax

from .memory import all_devices_memory_gb, GB


class PerformanceTracker:
    def __init__(self, warmup_steps: int = 5, flops_per_token: float | None = None,
                 num_devices: int | None = None,
                 memory_sample_every: int = 10):
        self.warmup_steps = warmup_steps
        self.flops_per_token = flops_per_token
        self.num_devices = num_devices or jax.device_count()
        self.memory_sample_every = max(int(memory_sample_every), 1)
        self.step_count = 0
        self.tokens = 0
        self.total_loss = 0.0
        self.loss_count = 0
        self.start = time.perf_counter()
        self._warmed_up = warmup_steps == 0
        self._prev_step_t = self.start
        self.last_step_time_s: float | None = None
        self._peak_gb: float | None = None
        self._mem_all: dict | None = None
        self._mem_sampled = False

    def step(self, tokens: int, loss: float | None = None) -> dict | None:
        """Record one optimizer step of ``tokens`` tokens.  Returns the metric
        dict once past warmup, else None.  Restart-at-warmup matches reference
        ``fsdp/utils.py:155-159``.  ``loss`` may be omitted and supplied
        later via :meth:`record_loss` (the async pump resolves losses at
        its sync points, not per step)."""
        now = time.perf_counter()
        self.last_step_time_s = now - self._prev_step_t
        self._prev_step_t = now
        self.step_count += 1
        if not self._warmed_up:
            if self.step_count >= self.warmup_steps:
                self._warmed_up = True
                self.step_count = 0
                self.tokens = 0
                self.total_loss = 0.0
                self.loss_count = 0
                self.start = time.perf_counter()
            return None
        self.tokens += tokens
        if loss is not None:
            self.record_loss(loss)
        return self.metrics(
            sample_memory=self.step_count % self.memory_sample_every == 0)

    def record_loss(self, loss: float) -> None:
        """Fold one resolved loss into the running average — the deferred
        twin of passing ``loss=`` to :meth:`step`."""
        self.total_loss += float(loss)
        self.loss_count += 1

    def _sample_memory(self) -> None:
        # one shared poll site for the whole process: the memory ledger's
        # sampler folds this read into its dispatch-phase peak too
        from ..telemetry.memledger import get_sampler
        peak = get_sampler().sample(phase="dispatch")["peak_bytes_in_use"]
        if peak:
            self._peak_gb = peak / GB
            self._mem_all = all_devices_memory_gb()
        self._mem_sampled = True

    def metrics(self, *, sample_memory: bool = False) -> dict:
        elapsed = max(time.perf_counter() - self.start, 1e-9)
        steps_per_second = self.step_count / elapsed
        tokens_per_second = self.tokens / elapsed
        out = {
            "steps_per_second": steps_per_second,
            "tokens_per_second": tokens_per_second,
            "total_tokens": self.tokens,
            "elapsed_s": elapsed,
        }
        if self.last_step_time_s is not None:
            # host wall-time of the most recent step — the per-step field
            # the telemetry JSONL schema records
            out["last_step_time_s"] = self.last_step_time_s
        if self.loss_count:
            out["avg_loss"] = self.total_loss / self.loss_count
        if self.flops_per_token:
            # per-device TFLOPS: tokens/s is the global rate, work is split
            # across devices (reference fsdp/utils.py:177-179).
            out["tflops_per_device"] = (
                tokens_per_second * self.flops_per_token / self.num_devices / 1e12
            )
        if sample_memory or not self._mem_sampled:
            self._sample_memory()
        if self._peak_gb is not None:
            out["peak_memory_gb"] = self._peak_gb
            out["memory_all_devices"] = self._mem_all
        return out
