"""Checkpoint / resume of sharded train state (Orbax-backed).

The reference has NO checkpointing — every run is random-init
(SURVEY.md §5.4: "no state_dict save/load anywhere"; models rebuilt from
config at ``fsdp/train_fsdp.py:61-64``).  A framework a reference user
switches to needs one, and Orbax is the idiomatic TPU choice: it writes
each device's shards in parallel (OCDBT/tensorstore), restores directly
into the requested ``NamedSharding`` layout — resharding on restore if
the mesh changed — and is async-capable for multi-host.

Surface (three calls, train-loop friendly):

    mgr = checkpoint_manager(dir, max_to_keep=3)
    save_state(mgr, step, {"params": shards, "opt": opt_state})
    state = restore_state(mgr, like={"params": shards, "opt": opt_state})

``like`` supplies the tree structure + shapes + shardings to restore
into (typically freshly-initialized state); restore is exact — resuming
mid-run reproduces the unbroken trajectory bit-for-bit, which the test
suite pins.
"""

from __future__ import annotations

import contextlib
import importlib
import os
from typing import Any

import jax


def _ocp():
    """Deferred orbax import — keeps ``utils`` import light for the many
    paths that never checkpoint."""
    return importlib.import_module("orbax.checkpoint")


def checkpoint_manager(directory: str | os.PathLike, *,
                       max_to_keep: int = 3) -> "ocp.CheckpointManager":
    """A step-indexed manager (keeps the newest ``max_to_keep`` steps)."""
    ocp = _ocp()
    options = ocp.CheckpointManagerOptions(max_to_keep=max_to_keep,
                                           create=True)
    return ocp.CheckpointManager(os.path.abspath(os.fspath(directory)),
                                 options=options)


def save_state(mgr: "ocp.CheckpointManager", step: int, state: Any,
               *, wait: bool = True) -> None:
    """Save a pytree of (possibly sharded) arrays under ``step``.
    ``wait=False`` leaves the write async (overlap with the next train
    steps); call ``mgr.wait_until_finished()`` before exiting."""
    mgr.save(step, args=_ocp().args.StandardSave(state))
    if wait:
        mgr.wait_until_finished()


@contextlib.contextmanager
def closing(mgr: "ocp.CheckpointManager"):
    """Guarantee ``wait_until_finished()`` on EVERY exit path — the
    async-save safety contract.  ``save_state(..., wait=False)`` lets the
    disk write overlap the next train steps, but a crash (or plain
    return) before the write commits would leave a torn newest step;
    wrapping the manager's lifetime in ``closing`` makes that impossible:

        with closing(checkpoint_manager(dir)) as mgr:
            save_state(mgr, step, state, wait=False)
            ...                     # crash here still waits the write out
    """
    try:
        yield mgr
    finally:
        mgr.wait_until_finished()


def latest_step(mgr: "ocp.CheckpointManager") -> int | None:
    return mgr.latest_step()


def restore_state(mgr: "ocp.CheckpointManager", *, like: Any,
                  step: int | None = None) -> Any:
    """Restore the newest (or given) step into ``like``'s structure,
    dtypes, and shardings — placement happens during restore, so a
    dp-sharded param tree comes back dp-sharded without a host round
    trip (and reshards automatically if ``like``'s mesh differs from
    the one that saved)."""
    if step is None:
        step = mgr.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint found under {mgr.directory}")
    ocp = _ocp()
    abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, like)
    return mgr.restore(step, args=ocp.args.StandardRestore(abstract))


def restore_params(ckpt_dir, params, *, tag: str = "restore"):
    """THE restore-and-report path the eval/demo scripts share: open
    ``ckpt_dir``, restore the newest step's ``{"params": ...}`` into
    ``params``' structure and shardings, print the one-line
    "restored step N from DIR" contract under ``tag``'s prefix, and
    return ``(restored_params, step)``.  Raises SystemExit with a
    readable message when the directory holds no steps."""
    mgr = checkpoint_manager(ckpt_dir)
    step = latest_step(mgr)
    if step is None:
        raise SystemExit(f"no checkpoint steps in {ckpt_dir}")
    state = restore_state(mgr, like={"params": params})
    print(f"[{tag}] restored step {step} from {ckpt_dir}")
    return state["params"], step
