"""Seeded determinism, twin of reference ``set_seed``
(``DDP/training_utils/utils.py:32-46``): one call seeds every RNG the run
touches.  On TPU the model/data randomness is a ``jax.random`` key (functional,
splittable); python/numpy are seeded too for the host-side data pipeline.
"""

from __future__ import annotations

import random

import numpy as np
import jax


def set_seed(seed: int = 42) -> jax.Array:
    """Seed python/numpy and return the root PRNG key for the run."""
    random.seed(seed)
    np.random.seed(seed)
    return jax.random.PRNGKey(seed)


def key_for_axis(key: jax.Array, axis_name: str) -> jax.Array:
    """Per-device key inside ``shard_map``: fold the device's coordinate on
    ``axis_name`` into ``key``.  The twin of per-rank seeding."""
    return jax.random.fold_in(key, jax.lax.axis_index(axis_name))
