"""Named-mesh runtime: the TPU twin of the reference's process-group layer.

The reference keeps a string-keyed accessor over torch.distributed state —
``get("ws"|"rank"|"lrank"|"pg")`` with an optional registered DeviceMesh
(reference ``DDP/training_utils/utils.py:49-87``).  Here the process group *is*
a ``jax.sharding.Mesh``: construction happens once, meshes are registered by
name, and ``get()`` answers the same questions (world size, process rank,
local device count, the mesh itself, named-axis sizes).

Unlike NCCL there is no per-rank process by default: JAX is SPMD, so
device-level "rank" only exists *inside* ``shard_map`` (``lax.axis_index``,
see ops.collectives.axis_rank).  Host-level rank == ``jax.process_index()``
and is what multi-host (DCN) code keys on.
"""

from __future__ import annotations

import math
import os
import re
from typing import Mapping, Sequence

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

_MESHES: dict[str, Mesh] = {}
DEFAULT_MESH = "default"


def use_cpu_devices(n: int = 8) -> None:
    """Force this process onto ``n`` simulated CPU devices.

    The CI/test substrate (SURVEY.md §7.1): the twin of the reference running
    gloo on 2 CPU ranks.  Must run before the JAX backend initializes.  When a
    backend is already live this is a no-op if the platform is already cpu.

    If the multi-process launcher's env contract is present
    (``DTS_COORDINATOR``/``DTS_NUM_PROCESSES``/``DTS_PROCESS_ID`` — the
    ``torchrun --nproc_per_node`` twin, set by ``dts-launch run
    --nprocs N``), the process also joins the distributed cluster here,
    so every strategy script's existing ``--cpu-devices`` bootstrap
    becomes multi-process-capable with no per-script changes.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m is None:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    elif int(m.group(1)) != n:
        os.environ["XLA_FLAGS"] = flags.replace(
            m.group(0), f"--xla_force_host_platform_device_count={n}")
    jax.config.update("jax_platforms", "cpu")
    auto_initialize_from_env()


_DTS_INITIALIZED = False


def auto_initialize_from_env() -> bool:
    """Join the launcher-spawned process group when the ``DTS_*`` env
    contract is set (no-op otherwise; returns whether it initialized).
    Guarded by a module flag, NOT ``jax.process_count()`` — querying the
    backend would initialize it single-process and lock distributed
    bring-up out."""
    global _DTS_INITIALIZED
    coord = os.environ.get("DTS_COORDINATOR")
    nprocs = os.environ.get("DTS_NUM_PROCESSES")
    if not coord or not nprocs or int(nprocs) < 2:
        return False
    if _DTS_INITIALIZED:
        return True
    setup_distributed(coord, num_processes=int(nprocs),
                      process_id=int(os.environ["DTS_PROCESS_ID"]))
    _DTS_INITIALIZED = True
    return True


def setup_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Multi-host (DCN) bring-up: twin of ``dist.init_process_group`` at
    reference ``zero/zero1.py:204``.

    Single-host (the common case here) is a no-op — ICI collectives need no
    process group.  On a multi-host TPU slice JAX auto-detects the topology,
    so all arguments are optional.
    """
    env_procs = os.environ.get("JAX_NUM_PROCESSES")
    if num_processes is None and env_procs is not None:
        num_processes = int(env_procs)
    if num_processes is not None and num_processes > 1:
        plats = str(jax.config.jax_platforms
                    or os.environ.get("JAX_PLATFORMS", ""))
        if "cpu" in plats:
            # CPU cross-process collectives need an explicit backend;
            # gloo ships with jaxlib (the reference's gloo-on-CPU-ranks
            # mode, modal_utils.py / SURVEY.md §7.1).
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )


def make_mesh(
    axes: Mapping[str, int] | None = None,
    *,
    devices: Sequence[jax.Device] | None = None,
    name: str = DEFAULT_MESH,
    register: bool = True,
) -> Mesh:
    """Build a named device mesh.  ``axes`` maps axis name -> size; one size
    may be -1 (fills with the remaining devices).  Default: 1-D ``dp`` mesh
    over every device.
    """
    devs = np.asarray(devices if devices is not None else jax.devices())
    if axes is None:
        axes = {"dp": devs.size}
    names = tuple(axes.keys())
    sizes = list(axes.values())
    if sizes.count(-1) > 1:
        raise ValueError("at most one mesh axis may be -1")
    if -1 in sizes:
        known = math.prod(s for s in sizes if s != -1)
        if devs.size % known:
            raise ValueError(f"{devs.size} devices not divisible by {known}")
        sizes[sizes.index(-1)] = devs.size // known
    total = math.prod(sizes)
    if total > devs.size:
        raise ValueError(f"mesh {dict(zip(names, sizes))} needs {total} devices, "
                         f"have {devs.size}")
    mesh = Mesh(devs.flatten()[:total].reshape(sizes), names)
    if register:
        _MESHES[name] = mesh
    return mesh


def register_mesh(name: str, mesh: Mesh) -> Mesh:
    """Twin of the reference's ``cache_mesh`` decorator registry
    (``DDP/training_utils/utils.py:49-60``)."""
    _MESHES[name] = mesh
    return mesh


def get_mesh(name: str = DEFAULT_MESH) -> Mesh:
    if name not in _MESHES:
        if name == DEFAULT_MESH:
            return make_mesh()
        raise KeyError(f"no mesh registered under {name!r}; "
                       f"have {sorted(_MESHES)}")
    return _MESHES[name]


def get(what: str, mesh_name: str = DEFAULT_MESH):
    """String-keyed runtime accessor, twin of reference
    ``DDP/training_utils/utils.py:63-87``.

    Keys:
      "ws"     -> world size: total device count of the mesh
      "rank"   -> host/process rank (``jax.process_index()``)
      "nprocs" -> process count
      "lrank"  -> local device count on this host
      "pg" | "mesh" -> the named ``Mesh`` (the process-group analogue)
      "axis:<name>" -> size of that mesh axis
    """
    if what in ("pg", "mesh"):
        return get_mesh(mesh_name)
    if what == "ws":
        return int(get_mesh(mesh_name).devices.size)
    if what == "rank":
        return jax.process_index()
    if what == "nprocs":
        return jax.process_count()
    if what == "lrank":
        return len(jax.local_devices())
    if what.startswith("axis:"):
        axis = what.split(":", 1)[1]
        return int(get_mesh(mesh_name).shape[axis])
    raise KeyError(f"unknown runtime key {what!r}")


def host_to_global(arr, mesh: Mesh, spec: PartitionSpec) -> jax.Array:
    """A host-identical value (same on every process, e.g. identically
    seeded) → one GLOBAL array sharded by ``spec`` over ``mesh``.
    Single-process this is just ``device_put``; multi-process it builds
    the global array from per-process local shards — what jit requires
    when the mesh spans processes (the torchrun-mode data path)."""
    arr = np.asarray(arr)
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    return jax.make_array_from_callback(arr.shape, sharding,
                                        lambda idx: arr[idx])


def local_scalar(x) -> float:
    """float() of a (replicated) result that works whether or not the
    array is fully addressable from this process."""
    if hasattr(x, "is_fully_addressable") and not x.is_fully_addressable:
        return float(np.asarray(x.addressable_data(0)))
    return float(x)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def sharded(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(*spec))
