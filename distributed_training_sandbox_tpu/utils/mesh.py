"""Named-mesh runtime: the TPU twin of the reference's process-group layer.

The reference keeps a string-keyed accessor over torch.distributed state —
``get("ws"|"rank"|"lrank"|"pg")`` with an optional registered DeviceMesh
(reference ``DDP/training_utils/utils.py:49-87``).  Here the process group *is*
a ``jax.sharding.Mesh``: construction happens once, meshes are registered by
name, and ``get()`` answers the same questions (world size, process rank,
local device count, the mesh itself, named-axis sizes).

Unlike NCCL there is no per-rank process by default: JAX is SPMD, so
device-level "rank" only exists *inside* ``shard_map`` (``lax.axis_index``,
see ops.collectives.axis_rank).  Host-level rank == ``jax.process_index()``
and is what multi-host (DCN) code keys on.
"""

from __future__ import annotations

import atexit
import math
import os
import re
import socket
import time
from typing import Mapping, Sequence

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

_MESHES: dict[str, Mesh] = {}
DEFAULT_MESH = "default"


class BringupTimeout(RuntimeError):
    """Distributed bring-up did not complete within the budget.

    Raised instead of letting ``jax.distributed.initialize`` hang
    forever when a peer never shows up (crashed before connecting, or
    was never launched) — the coordinator-side twin of a gloo connect
    timeout.  Carries enough context to tell WHICH rendezvous failed."""

    def __init__(self, coordinator: str | None, num_processes: int | None,
                 process_id: int | None, timeout_s: float, cause: str = ""):
        self.coordinator = coordinator
        self.num_processes = num_processes
        self.process_id = process_id
        self.timeout_s = timeout_s
        detail = f": {cause}" if cause else ""
        super().__init__(
            f"distributed bring-up timed out after {timeout_s:.0f}s "
            f"(coordinator={coordinator}, num_processes={num_processes}, "
            f"process_id={process_id}) — a peer is missing or the "
            f"coordinator is unreachable{detail}")


def use_cpu_devices(n: int = 8) -> None:
    """Force this process onto ``n`` simulated CPU devices.

    The CI/test substrate (SURVEY.md §7.1): the twin of the reference running
    gloo on 2 CPU ranks.  Must run before the JAX backend initializes.  When a
    backend is already live this is a no-op if the platform is already cpu.

    If the multi-process launcher's env contract is present
    (``DTS_COORDINATOR``/``DTS_NUM_PROCESSES``/``DTS_PROCESS_ID`` — the
    ``torchrun --nproc_per_node`` twin, set by ``dts-launch run
    --nprocs N``), the process also joins the distributed cluster here,
    so every strategy script's existing ``--cpu-devices`` bootstrap
    becomes multi-process-capable with no per-script changes.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m is None:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    elif int(m.group(1)) != n:
        os.environ["XLA_FLAGS"] = flags.replace(
            m.group(0), f"--xla_force_host_platform_device_count={n}")
    jax.config.update("jax_platforms", "cpu")
    auto_initialize_from_env()


_DTS_INITIALIZED = False


def auto_initialize_from_env() -> bool:
    """Join the launcher-spawned process group when the ``DTS_*`` env
    contract is set (no-op otherwise; returns whether it initialized).
    Guarded by a module flag, NOT ``jax.process_count()`` — querying the
    backend would initialize it single-process and lock distributed
    bring-up out."""
    global _DTS_INITIALIZED
    coord = os.environ.get("DTS_COORDINATOR")
    nprocs = os.environ.get("DTS_NUM_PROCESSES")
    if not coord or not nprocs or int(nprocs) < 2:
        return False
    if _DTS_INITIALIZED:
        return True
    setup_distributed(coord, num_processes=int(nprocs),
                      process_id=int(os.environ["DTS_PROCESS_ID"]))
    _DTS_INITIALIZED = True
    barrier = os.environ.get("DTS_BRINGUP_TIMEOUT")
    if barrier:
        # --distributed mode: prove every peer actually executes a
        # collective before the driver starts building state.  A peer
        # that connected to the coordinator but wedged before its first
        # psum becomes a StepTimeoutError here — the same exception the
        # elastic supervisor already knows how to restart from.
        bringup_barrier(float(barrier))
    return True


def setup_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    *,
    timeout_s: float | None = None,
) -> None:
    """Multi-host (DCN) bring-up: twin of ``dist.init_process_group`` at
    reference ``zero/zero1.py:204``.

    Single-host (the common case here) is a no-op — ICI collectives need no
    process group.  On a multi-host TPU slice JAX auto-detects the topology,
    so all arguments are optional.

    Bring-up is BOUNDED: ``timeout_s`` (default ``DTS_BRINGUP_TIMEOUT``
    or 120s) caps how long ``jax.distributed.initialize`` may wait for
    peers — a missing peer raises :class:`BringupTimeout` instead of
    hanging forever.  A coordinator port still in TIME_WAIT from a
    previous group (EADDRINUSE) is retried in place a few times before
    giving up; rotation to a *fresh* port is the launcher's job (it owns
    port selection).  ``jax.distributed.shutdown`` is registered via
    ``atexit`` so every exit path — clean return, uncaught exception,
    ``sys.exit`` — tears the group down.
    """
    env_procs = os.environ.get("JAX_NUM_PROCESSES")
    if num_processes is None and env_procs is not None:
        num_processes = int(env_procs)
    if num_processes is None or num_processes <= 1:
        return
    if timeout_s is None:
        timeout_s = float(os.environ.get("DTS_BRINGUP_TIMEOUT") or 120.0)
    plats = str(jax.config.jax_platforms
                or os.environ.get("JAX_PLATFORMS", ""))
    if "cpu" in plats:
        # CPU cross-process collectives need an explicit backend;
        # gloo ships with jaxlib (the reference's gloo-on-CPU-ranks
        # mode, modal_utils.py / SURVEY.md §7.1).
        jax.config.update("jax_cpu_collectives_implementation",
                          "gloo")
    if process_id is not None and process_id != 0 and coordinator_address:
        # jaxlib's coordination client converts a RegisterTask deadline
        # into a process-terminating FATAL abort — it never raises into
        # Python.  An unreachable coordinator must therefore be caught
        # BEFORE initialize, with a bounded TCP preflight; once the
        # coordinator accepts, initialize proceeds normally.
        host, _, port = coordinator_address.rpartition(":")
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                socket.create_connection(
                    (host or "127.0.0.1", int(port)),
                    timeout=min(1.0, timeout_s)).close()
                break
            except OSError as e:
                if time.monotonic() >= deadline:
                    raise BringupTimeout(
                        coordinator_address, num_processes, process_id,
                        timeout_s, cause=f"{type(e).__name__}: {e}") from e
                time.sleep(0.2)
    attempts, max_attempts = 0, 3
    while True:
        attempts += 1
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
                initialization_timeout=max(1, int(timeout_s)),
            )
            break
        except Exception as e:  # noqa: BLE001 - classified + re-raised
            msg = str(e)
            if ("EADDRINUSE" in msg or "address already in use" in
                    msg.lower()) and attempts < max_attempts:
                # coordinator port lingering in TIME_WAIT from the
                # previous group on the same address — transient
                print(f"[mesh] coordinator port busy "
                      f"({coordinator_address}), retry "
                      f"{attempts}/{max_attempts - 1}")
                time.sleep(0.5 * attempts)
                continue
            if ("DEADLINE_EXCEEDED" in msg or "timed out" in msg.lower()
                    or "timeout" in msg.lower()):
                raise BringupTimeout(coordinator_address, num_processes,
                                     process_id, timeout_s,
                                     cause=msg.splitlines()[0]) from e
            raise
    atexit.register(shutdown_distributed)


def shutdown_distributed() -> None:
    """Idempotent ``jax.distributed.shutdown`` — the teardown half of
    :func:`setup_distributed`, safe to call from a ``finally`` on any
    exit path (and registered via ``atexit`` so interpreter exit covers
    the paths no ``finally`` reaches).  A failed shutdown is reported,
    not raised: teardown must never mask the error that caused it."""
    global _DTS_INITIALIZED
    client = getattr(jax.distributed, "global_state", None)
    if client is None or getattr(client, "client", None) is None:
        _DTS_INITIALIZED = False
        return
    try:
        jax.distributed.shutdown()
    except Exception as e:  # noqa: BLE001 - teardown must not mask errors
        print(f"[mesh] WARNING: jax.distributed.shutdown failed: "
              f"{type(e).__name__}: {e}")
    _DTS_INITIALIZED = False


def bringup_barrier(timeout_s: float = 120.0) -> None:
    """Cross-process bring-up barrier: one tiny psum over EVERY device,
    run under the elastic :class:`~..resilience.elastic.Watchdog` so a
    peer that wedges after connecting surfaces as the same
    ``StepTimeoutError`` the step-level watchdog raises — one timeout
    machinery for bring-up and steady state.  Verifies the sum, so a
    short-changed mesh (a peer initialized with fewer devices than the
    group believes) is caught here, not ten minutes into training."""
    from ..resilience.elastic import Watchdog

    def _sync() -> float:
        devs = np.asarray(jax.devices())
        mesh = Mesh(devs, ("all",))
        ones = host_to_global(np.ones((devs.size,), np.float32),
                              mesh, PartitionSpec("all"))
        total = jax.jit(lambda x: x.sum(),
                        out_shardings=NamedSharding(mesh, PartitionSpec())
                        )(ones)
        return local_scalar(total)

    wd = Watchdog(timeout_s=timeout_s)
    total = wd.block(_sync, step=-1)
    ndev = len(jax.devices())
    if int(total) != ndev:
        raise RuntimeError(
            f"bring-up barrier mismatch: psum saw {int(total)} devices, "
            f"backend reports {ndev} — mesh does not span the group")


def make_mesh(
    axes: Mapping[str, int] | None = None,
    *,
    devices: Sequence[jax.Device] | None = None,
    name: str = DEFAULT_MESH,
    register: bool = True,
) -> Mesh:
    """Build a named device mesh.  ``axes`` maps axis name -> size; one size
    may be -1 (fills with the remaining devices).  Default: 1-D ``dp`` mesh
    over every device.
    """
    devs = np.asarray(devices if devices is not None else jax.devices())
    if axes is None:
        axes = {"dp": devs.size}
    names = tuple(axes.keys())
    sizes = list(axes.values())
    if sizes.count(-1) > 1:
        raise ValueError("at most one mesh axis may be -1")
    if -1 in sizes:
        known = math.prod(s for s in sizes if s != -1)
        if devs.size % known:
            raise ValueError(f"{devs.size} devices not divisible by {known}")
        sizes[sizes.index(-1)] = devs.size // known
    total = math.prod(sizes)
    if total > devs.size:
        raise ValueError(f"mesh {dict(zip(names, sizes))} needs {total} devices, "
                         f"have {devs.size}")
    mesh = Mesh(devs.flatten()[:total].reshape(sizes), names)
    if register:
        _MESHES[name] = mesh
    return mesh


def register_mesh(name: str, mesh: Mesh) -> Mesh:
    """Twin of the reference's ``cache_mesh`` decorator registry
    (``DDP/training_utils/utils.py:49-60``)."""
    _MESHES[name] = mesh
    return mesh


def get_mesh(name: str = DEFAULT_MESH) -> Mesh:
    if name not in _MESHES:
        if name == DEFAULT_MESH:
            return make_mesh()
        raise KeyError(f"no mesh registered under {name!r}; "
                       f"have {sorted(_MESHES)}")
    return _MESHES[name]


def get(what: str, mesh_name: str = DEFAULT_MESH):
    """String-keyed runtime accessor, twin of reference
    ``DDP/training_utils/utils.py:63-87``.

    Keys:
      "ws"     -> world size: total device count of the mesh
      "rank"   -> host/process rank (``jax.process_index()``)
      "nprocs" -> process count
      "lrank"  -> local device count on this host
      "pg" | "mesh" -> the named ``Mesh`` (the process-group analogue)
      "axis:<name>" -> size of that mesh axis
    """
    if what in ("pg", "mesh"):
        return get_mesh(mesh_name)
    if what == "ws":
        return int(get_mesh(mesh_name).devices.size)
    if what == "rank":
        return jax.process_index()
    if what == "nprocs":
        return jax.process_count()
    if what == "lrank":
        return len(jax.local_devices())
    if what.startswith("axis:"):
        axis = what.split(":", 1)[1]
        return int(get_mesh(mesh_name).shape[axis])
    raise KeyError(f"unknown runtime key {what!r}")


def host_to_global(arr, mesh: Mesh, spec: PartitionSpec) -> jax.Array:
    """A host-identical value (same on every process, e.g. identically
    seeded) → one GLOBAL array sharded by ``spec`` over ``mesh``.
    Single-process this is just ``device_put``; multi-process it builds
    the global array from per-process local shards — what jit requires
    when the mesh spans processes (the torchrun-mode data path)."""
    arr = np.asarray(arr)
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    return jax.make_array_from_callback(arr.shape, sharding,
                                        lambda idx: arr[idx])


def process_local_put(arr, mesh: Mesh, spec: PartitionSpec) -> jax.Array:
    """Stage a host-identical batch as one GLOBAL array by handing JAX
    only this process's slice — ``jax.make_array_from_process_local_data``,
    the data path the torchrun contract implies: each worker materializes
    its own shard, never the full global batch on-device.

    Single-process (or a spec fully addressable from here) degrades to
    plain ``device_put``.  When this process's shards are not one
    contiguous block of the global array (e.g. a strided device order),
    falls back to :func:`host_to_global`'s per-shard callback, which
    handles any layout.
    """
    arr = np.asarray(arr)
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1 or sharding.is_fully_addressable:
        return jax.device_put(arr, sharding)
    idx_map = sharding.addressable_devices_indices_map(arr.shape)
    # bounding box of the local shards, per dimension
    lo = [d for d in arr.shape]
    hi = [0] * arr.ndim
    for idx in idx_map.values():
        for d, sl in enumerate(idx):
            start = 0 if sl.start is None else sl.start
            stop = arr.shape[d] if sl.stop is None else sl.stop
            lo[d] = min(lo[d], start)
            hi[d] = max(hi[d], stop)
    box = tuple(slice(a, b) for a, b in zip(lo, hi))
    uniq_bounds = {
        tuple(((0 if sl.start is None else sl.start),
               (arr.shape[d] if sl.stop is None else sl.stop))
              for d, sl in enumerate(idx))
        for idx in idx_map.values()}
    covered = sum(math.prod(b - a for a, b in bounds)
                  for bounds in uniq_bounds)
    if covered != math.prod(b - a for a, b in zip(lo, hi)):
        # local shards don't tile the box — non-contiguous layout
        return host_to_global(arr, mesh, spec)
    return jax.make_array_from_process_local_data(
        sharding, np.ascontiguousarray(arr[box]), arr.shape)


def local_scalar(x) -> float:
    """float() of a (replicated) result that works whether or not the
    array is fully addressable from this process."""
    if hasattr(x, "is_fully_addressable") and not x.is_fully_addressable:
        return float(np.asarray(x.addressable_data(0)))
    return float(x)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def sharded(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(*spec))
