"""Post-hoc comm-vs-compute split from XLA profiler traces.

Twin of the reference's in-step communication timers
(``zero/zero2.py:91-135,219-228``: cuda-synchronized stopwatches around each
``dist`` call, printed as "communication overhead %").  Under jit there is
nothing to stopwatch — collectives are ops inside one compiled program — so
the split is recovered from the profiler trace instead: sum the durations of
collective-ish ops vs compute-ish ops in the chrome-trace JSON that
``jax.profiler`` writes (``plugins/profile/<ts>/*.trace.json.gz``).

Methodology notes (honest limits):
  * Trace events are HLO instructions; names keep their primitive root
    ("psum.7", "all-reduce.3", "fusion.12"), so classification is by name
    pattern.  Collective wait time shows up as Rendezvous (CPU backend) /
    megacore-fusion-wait (TPU) and counts as comm.
  * On overlap-capable hardware comm hidden under compute still counts
    toward comm time — the split is "time attributable to", not "critical
    path", matching what the reference's blocking timers measured.
  * Infra events (thread waits, host python, dispatch) belong to neither
    bucket and are excluded from the denominator.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
from dataclasses import dataclass

_COMM = re.compile(
    r"(all[-_]?reduce|all[-_]?gather|reduce[-_]?scatter|all[-_]?to[-_]?all"
    r"|collective[-_]?permute|psum|ppermute|rendezvous(?![ -_]?callback)"
    r"|send|recv|megacore[-_]?fusion[-_]?wait)",
    re.IGNORECASE)
_COMPUTE = re.compile(
    r"(^dot|\bdot\b|fusion|convolution|cumsum|reduce|transpose|copy|scatter"
    r"|gather|broadcast_in_dim|select|compare|add|multiply|divide|subtract"
    r"|exponential|log|rsqrt|tanh|iota|concatenate|slice|dynamic|pad|while"
    r"|convert|bitcast|clamp|maximum|minimum|negate|power|remainder|sign"
    r"|custom[-_]?call|tpu[-_]?custom)",
    re.IGNORECASE)
_IGNORE = re.compile(
    r"(Wait|PjitFunction|PjRt|block_until_ready|try_to_block|shard_arg"
    r"|\$|rendezvous callback|process_name|thread_name|program_interface)",
    re.IGNORECASE)


@dataclass
class CommSplit:
    comm_us: float
    compute_us: float
    other_us: float
    trace_file: str
    top_comm: list
    top_compute: list
    # wall-clock microseconds during which a comm event and a compute
    # event were running concurrently (different trace rows) — the
    # overlap the async pump/prefetcher exist to create
    overlap_us: float = 0.0

    @property
    def total_us(self) -> float:
        return self.comm_us + self.compute_us

    @property
    def comm_fraction(self) -> float:
        return self.comm_us / self.total_us if self.total_us else 0.0

    @property
    def overlap_fraction(self) -> float:
        """Fraction of comm time hidden under concurrent compute.
        0.0 on a fully serialized schedule (e.g. the CPU-sim backend)."""
        return self.overlap_us / self.comm_us if self.comm_us else 0.0

    def report(self, label: str = "") -> str:
        """The reference's print format (zero2.py:219-228): absolute times
        + overhead %."""
        pct = 100.0 * self.comm_fraction
        return (f"[{label}] comm/compute split (profiler trace): "
                f"comm {self.comm_us / 1e3:.2f} ms, "
                f"compute {self.compute_us / 1e3:.2f} ms "
                f"-> communication overhead {pct:.1f}% of categorized "
                f"device time, {100.0 * self.overlap_fraction:.1f}% of "
                f"comm overlapped with compute")


def profile_session_dirs(trace_dir: str) -> list[str]:
    """The profiler session directories under ``trace_dir``
    (``plugins/profile/<timestamp>/`` — one per start/stop_trace pair),
    sorted by name (timestamps sort chronologically)."""
    root = os.path.join(trace_dir, "plugins", "profile")
    try:
        return sorted(os.path.join(root, d) for d in os.listdir(root)
                      if os.path.isdir(os.path.join(root, d)))
    except OSError:
        return []


def latest_trace_file(trace_dir: str, session: str | None = None) \
        -> str | None:
    """Newest ``*.trace.json.gz`` under ``trace_dir`` — or, when
    ``session`` names a profiler session directory (absolute, or relative
    to ``trace_dir``), the trace inside exactly that session.  Passing
    the owned session fixes the misattribution hazard of the bare-mtime
    form: a concurrent run or a stale ``profiler_traces/`` entry can be
    newer than the trace this run actually wrote."""
    roots = [trace_dir]
    if session:
        sd = session if os.path.isabs(session) \
            else os.path.join(trace_dir, session)
        if os.path.isdir(sd):
            roots = [sd]
    files = []
    for r in roots:
        files += glob.glob(os.path.join(r, "**", "*.trace.json.gz"),
                           recursive=True)
    return max(files, key=os.path.getmtime) if files else None


def interval_overlap_us(comm_iv: list, compute_iv: list) -> float:
    """Total microseconds during which any ``comm`` interval and any
    ``compute`` interval (each ``(start, end)``) run concurrently.
    Compute intervals are merged first so stacked fusions don't double-
    count; each comm interval then contributes its intersection with the
    merged compute timeline."""
    if not comm_iv or not compute_iv:
        return 0.0
    merged: list[list[float]] = []
    for s, e in sorted(compute_iv):
        if merged and s <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], e)
        else:
            merged.append([s, e])
    total = 0.0
    for cs, ce in comm_iv:
        for ms, me in merged:
            if ms >= ce:
                break
            if me <= cs:
                continue
            total += min(ce, me) - max(cs, ms)
    return total


def split_from_trace(trace_dir: str, top_n: int = 5,
                     session: str | None = None) -> CommSplit | None:
    """Analyze the trace under ``trace_dir`` — the one in the owned
    ``session`` directory when given (see :func:`latest_trace_file`),
    else the newest.  Returns None when no trace exists (profiling
    disabled / single uncaptured step)."""
    tf = latest_trace_file(trace_dir, session=session)
    if tf is None:
        return None
    events = json.load(gzip.open(tf, "rt"))["traceEvents"]
    comm: dict[str, float] = {}
    compute: dict[str, float] = {}
    comm_iv: list = []
    compute_iv: list = []
    other = 0.0
    for e in events:
        if e.get("ph") != "X":
            continue
        name = e.get("name", "")
        dur = float(e.get("dur", 0.0))
        ts = e.get("ts")
        iv = (float(ts), float(ts) + dur) if ts is not None and dur else None
        # Comm first: collective stall events ("megacore-fusion-wait",
        # "Rendezvous") must win over _IGNORE's generic host-wait patterns
        # (the docstring's methodology note depends on it).
        if _COMM.search(name):
            comm[name] = comm.get(name, 0.0) + dur
            if iv:
                comm_iv.append(iv)
        elif _IGNORE.search(name):
            continue
        elif _COMPUTE.search(name):
            compute[name] = compute.get(name, 0.0) + dur
            if iv:
                compute_iv.append(iv)
        else:
            other += dur
    top = lambda d: sorted(d.items(), key=lambda kv: -kv[1])[:top_n]
    return CommSplit(
        comm_us=sum(comm.values()),
        compute_us=sum(compute.values()),
        other_us=other,
        trace_file=tf,
        top_comm=top(comm),
        top_compute=top(compute),
        overlap_us=interval_overlap_us(comm_iv, compute_iv),
    )


# -------------------------------------------- per-instance collectives
#
# Trace event names of device ops ARE compiled-HLO instruction names
# ("all-reduce.1", "all-gather-start.3"), one event per participating
# device row per invocation — verified on the CPU-sim backend against
# compile().as_text() for every contract strategy.  This extracts the
# per-instruction stats the CollectiveLedger (telemetry.ledger) joins
# against ops.hlo.collective_instances.

_COLLECTIVE_EVENT_RE = re.compile(
    r"^(all-reduce|all-gather|reduce-scatter|collective-permute|"
    r"all-to-all)(-start|-done)?(\.\d+)?$")


def normalize_event_name(name: str) -> str:
    """Trace event name -> HLO instruction name: strip a leading ``%``
    and any ``scope/`` prefixes XLA may attach."""
    return name.rsplit("/", 1)[-1].lstrip("%")


def collective_event_stats(trace_file: str) -> dict[str, dict]:
    """Per-instruction stats of every collective duration event in one
    chrome-trace file: ``{instruction name: {"count", "total_us"}}``.
    ``count`` sums across device rows (n_devices × invocations), so
    ``total_us/count`` is the mean duration of one device's
    participation — the number bandwidth math wants."""
    events = json.load(gzip.open(trace_file, "rt"))["traceEvents"]
    out: dict[str, dict] = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        name = normalize_event_name(e.get("name", ""))
        if not _COLLECTIVE_EVENT_RE.match(name):
            continue
        rec = out.setdefault(name, {"count": 0, "total_us": 0.0})
        rec["count"] += 1
        rec["total_us"] += float(e.get("dur", 0.0))
    return out


# --------------------------------------------------- HLO schedule shape

HLO_COLLECTIVES = ("all-gather", "reduce-scatter", "all-reduce",
                   "collective-permute", "all-to-all")


def hlo_computations(txt: str) -> dict[str, list[str]]:
    """Optimized-HLO text -> {computation name: instruction lines}.
    Header args may contain nested parens (tuple types), hence the
    greedy match up to the arrow."""
    comps: dict[str, list[str]] = {}
    cur = None
    for line in txt.splitlines():
        m = re.match(r"^(?:ENTRY\s+)?%?([\w\.\-]+)\s*\(.*\)\s*->.*\{",
                     line)
        if m:
            cur = m.group(1)
            comps[cur] = []
        elif cur is not None:
            comps[cur].append(line)
    return comps


def while_bodies(txt: str) -> set[str]:
    """Names of computations used as while-loop bodies."""
    return {m.group(1) for m in re.finditer(r"body=%?([\w\.\-]+)", txt)}


def collective_placement(txt: str) -> dict:
    """Per collective kind: how many sit inside while-loop bodies vs
    hoisted outside, plus async start/done pair count — the schedule-
    shape evidence behind ``scripts/overlap_analysis.py`` (the ZeRO-3
    in-loop re-gather vs ZeRO-2 hoisted gather distinction, reference
    ``fsdp/train_fsdp.py:84-88``)."""
    comps = hlo_computations(txt)
    bodies = while_bodies(txt)
    out: dict = {}
    for kind in HLO_COLLECTIVES:
        def count(lines):
            return sum(1 for l in lines
                       if f"{kind}(" in l or f"{kind}-start(" in l)
        in_loop = sum(count(lines) for name, lines in comps.items()
                      if name in bodies)
        total = sum(count(lines) for lines in comps.values())
        if total:
            out[kind] = {"total": total, "in_loop_body": in_loop,
                         "hoisted": total - in_loop}
    # opcode-anchored: a raw substring count would also hit the
    # instruction's own %name and the operand reference in the paired
    # -done line (~3 hits per actual pair).  Counted for EVERY
    # collective kind — async reduce-scatter/all-reduce pairs are
    # overlap evidence too.
    out["async_pairs"] = sum(txt.count(f"{kind}-start(")
                             for kind in HLO_COLLECTIVES)
    return out
