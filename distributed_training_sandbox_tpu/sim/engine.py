"""SimEngine: one replica's scheduler on the virtual clock.

This is a ServingEngine with the DEVICE removed and nothing else: the
slot/page bookkeeping is the real
:class:`~..serving.scheduler.ContinuousBatcher` over the real
:class:`~..serving.kv_pool.PageAllocator`, the prefix-hit model is the
real :class:`~..serving.kv_pool.RadixPrefixCache` running on token ids
and integer page ids exactly as it does in production (match → admit
grants only the non-cached suffix → insert at prefill completion,
twin-dedup and LRU eviction included), and
:meth:`SimEngine.step_round` replays ``ServingEngine.step_round``'s
round structure — admit, up to ``prefill_chunks_per_round`` prefill
chunks (FCFS-oldest, or all-residents-batched under flash), one decode
burst — with each device step replaced by its
:class:`~.cost.SimCostModel` duration.

The fixed-shape law carries over: a burst costs the same whatever the
occupancy, so the model is per-step constants, and timing mirrors the
real engine's stamps — ``t_first`` at the prefill-completing chunk,
``t_done`` at the end of the retiring burst.  The facade the fleet
router needs (``can_accept`` / ``enqueue`` / ``in_flight``) matches
``ServingEngine``'s, which is what lets the real ``Router`` and
``AdmissionController`` drive replicas without knowing which substrate
they are on.
"""

from __future__ import annotations

from ..serving.kv_pool import PageAllocator, RadixPrefixCache
from ..serving.scheduler import (DECODE, PREFILL, ContinuousBatcher,
                                 Request)
from .cost import SimCostModel

__all__ = ["SimEngine"]


class SimEngine:
    """Virtual-clock replica: real host bookkeeping, modeled device."""

    def __init__(self, *, cost: SimCostModel | None = None,
                 max_batch: int = 4, page_size: int = 8,
                 max_seq_len: int = 64, n_pages: int | None = None,
                 prefill_chunk: int = 16,
                 prefill_chunks_per_round: int = 2,
                 sync_every: int = 4, prefix_cache: bool = False,
                 spec_k: int = 0, flash_prefill: bool = False):
        self.cost = cost if cost is not None else SimCostModel()
        self.max_batch = int(max_batch)
        self.page_size = int(page_size)
        self.pages_per_request = -(-int(max_seq_len) // self.page_size)
        self.view_capacity = self.pages_per_request * self.page_size
        self.prefill_chunk = int(prefill_chunk)
        self.prefill_chunks_per_round = int(prefill_chunks_per_round)
        self.sync_every = max(int(sync_every), 1)
        self.spec_k = int(spec_k)
        self.flash_prefill = bool(flash_prefill)
        if n_pages is None:
            n_pages = self.max_batch * self.pages_per_request + 1
        if n_pages < self.pages_per_request + 1:
            raise ValueError(
                f"pool of {n_pages} pages cannot hold one request "
                f"({self.pages_per_request} pages + null)")
        self.n_pages = int(n_pages)
        self.allocator = PageAllocator(self.n_pages)
        self.batcher = ContinuousBatcher(self.max_batch, self.allocator,
                                         self.page_size)
        self.prefix_cache = (RadixPrefixCache(self.allocator,
                                              self.page_size)
                             if prefix_cache else None)
        self.batcher.prefix_cache = self.prefix_cache
        self.completed: list[Request] = []
        # per-slot decode progress (the engine's host mirrors, sans
        # device arrays): committed length, stop position, fractional
        # speculative token credit
        self._lengths = [0] * self.max_batch
        self._stop = [0] * self.max_batch
        self._spec_credit = [0.0] * self.max_batch
        self.stats = {"rounds": 0, "decode_steps": 0,
                      "prefill_chunks": 0, "occupancy_sum": 0,
                      "peak_pool_util": 0.0, "busy_s": 0.0}

    # ---- the router-facing facade (mirrors ServingEngine) ------------
    def can_accept(self, req: Request) -> bool:
        if self.batcher.waiting:
            return False
        if not any(r is None for r in self.batcher.slots):
            return False
        # same evictable-page credit as ServingEngine.can_accept
        free = self.allocator.free_pages
        if self.prefix_cache is not None:
            free += self.prefix_cache.reclaimable_pages
        return free >= self.batcher.pages_needed(req)

    def in_flight(self) -> int:
        return len(self.batcher.waiting) + sum(
            r is not None for r in self.batcher.slots)

    def enqueue(self, req: Request, now: float) -> None:
        self.batcher.submit(req, now)

    # ---- round execution ---------------------------------------------
    def _finish_prefill(self, req: Request, t: float) -> None:
        """Prefill completion at virtual time ``t`` — mirrors the real
        engine's ``_finish_prefill``: trie insert (twin dedup + page
        swaps), first-token stamp, flip to DECODE or retire when
        ``max_new == 1``."""
        if self.prefix_cache is not None:
            nodes, swaps = self.prefix_cache.insert(
                req.prompt, req.pages, req.cache_nodes)
            req.cache_nodes = nodes
            for i, pg in swaps.items():
                req.pages[i] = pg
        req.tokens.append(0)     # id is irrelevant on this substrate
        req.t_first = t
        stop = req.n_prompt + req.max_new_tokens - 1
        req.state = DECODE
        if req.n_prompt >= stop:            # max_new == 1
            self.batcher.retire(req, t)
            self.completed.append(req)
            return
        b = req.slot
        self._lengths[b] = req.n_prompt
        self._stop[b] = stop
        self._spec_credit[b] = 0.0

    def _slot_active(self, b: int) -> bool:
        req = self.batcher.slot_request(b)
        return (req is not None and req.state == DECODE
                and self._lengths[b] < self._stop[b])

    def step_round(self, now: float) -> tuple[list[Request], float]:
        """One scheduler round starting at virtual time ``now``;
        returns (requests finished this round, round's virtual cost)."""
        c = self.cost
        done_base = len(self.completed)
        spent = c.admit_s
        self.batcher.admit(now)
        # ---- prefill: same chunk schedule as the real engine --------
        for _ in range(self.prefill_chunks_per_round):
            if self.flash_prefill:
                reqs = sorted(
                    (r for r in self.batcher.slots
                     if r is not None and r.state == PREFILL),
                    key=lambda r: r.t_admit)
                if not reqs:
                    break
                spent += c.prefill_batch_chunk_s
                self.stats["prefill_chunks"] += 1
                for req in reqs:
                    req.prefill_pos = min(
                        req.prefill_pos + self.prefill_chunk,
                        req.n_prompt)
                    if req.prefill_pos >= req.n_prompt:
                        self._finish_prefill(req, now + spent)
            else:
                req = self.batcher.next_prefill()
                if req is None:
                    break
                spent += c.prefill_chunk_s
                self.stats["prefill_chunks"] += 1
                req.prefill_pos = min(
                    req.prefill_pos + self.prefill_chunk,
                    req.n_prompt)
                if req.prefill_pos >= req.n_prompt:
                    self._finish_prefill(req, now + spent)
        # ---- one decode burst (fixed cost, fixed shape) -------------
        active = [b for b in range(self.max_batch)
                  if self._slot_active(b)]
        if active:
            sync = self.sync_every
            spent += c.decode_burst_s(sync, self.spec_k)
            self.stats["decode_steps"] += sync
            t_end = now + spent
            per_macro = c.tokens_per_macro_step(self.spec_k)
            for b in active:
                req = self.batcher.slot_request(b)
                remaining = self._stop[b] - self._lengths[b]
                if self.spec_k:
                    self._spec_credit[b] += sync * per_macro
                    grant = min(int(self._spec_credit[b]), remaining)
                    self._spec_credit[b] -= grant
                else:
                    grant = min(sync, remaining)
                self._lengths[b] += grant
                req.tokens.extend([0] * grant)
                if self._lengths[b] >= self._stop[b]:
                    self.batcher.retire(req, t_end)
                    self.completed.append(req)
            self.stats["occupancy_sum"] += len(active)
        self.stats["rounds"] += 1
        self.stats["busy_s"] += spent
        self.stats["peak_pool_util"] = max(
            self.stats["peak_pool_util"],
            self.allocator.pages_in_use / max(self.n_pages - 1, 1))
        return self.completed[done_base:], spent

    # ---- failover -----------------------------------------------------
    def release_all(self) -> list[Request]:
        orphans = self.batcher.release_all()
        self._lengths = [0] * self.max_batch
        self._stop = [0] * self.max_batch
        self._spec_credit = [0.0] * self.max_batch
        return orphans
