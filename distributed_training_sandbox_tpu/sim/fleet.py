"""SimFleet: the fleet control plane on the virtual clock.

The REAL :class:`~..serving.router.AdmissionController` and
:class:`~..serving.router.Router` run here unmodified — same bounded
queue, same modeled-TTFT deadline shedding, same least-loaded dispatch
over the ``can_accept``/``in_flight`` facade — driving
:class:`~.engine.SimEngine` replicas whose device work is priced by
the calibrated :class:`~.cost.SimCostModel`.  The drive loop replays
``Fleet.run``'s structure event-for-event on virtual time: drain
arrivals due, roll any armed swap, dispatch, then step each working
replica's round SERIALLY (the host drives replicas one after another
in the real loop too — that serialization is part of what the
calibration measured, so the simulator must reproduce it to land in
the validation band).

Faults are scheduled on the virtual clock: ``schedule_kill(t, idx)``
freezes the replica at ``t`` (it keeps its residents and the router
keeps seeing it ``live`` — a hung replica looks healthy until the
watchdog fires, and the sim models that blind window) and declares it
dead ``failover_detect_s`` later, requeueing its unfinished requests
at the queue head exactly as ``Fleet._on_replica_death`` does.
Killing several replicas at one instant is the regional-failover
scenario.  ``schedule_swap_at(t)`` arms the rolling zero-drop weight
swap with the restore delay charged to the clock.

Everything is deterministic: seeded trace in, bitwise-identical
completed/shed sets and latency stream out (``digest()`` is the pin).
"""

from __future__ import annotations

import hashlib
import struct

import numpy as np

from ..serving.router import AdmissionController, Rejection, Router
from ..serving.scheduler import Request
from .clock import EventHeap, VirtualClock
from .cost import SimCostModel
from .engine import SimEngine

__all__ = ["SimFleet", "SimReplica", "simulate_trace"]

# TTFT thresholds (ms) the attainment curves are sampled at — spans
# one decode burst up to deep-queue territory on the CPU tier
ATTAINMENT_GRID_MS = (25.0, 50.0, 100.0, 200.0, 400.0, 800.0,
                      1600.0, 3200.0, 6400.0, 12800.0)


class SimReplica:
    """Mirror of ``fleet.Replica``: engine + liveness state.  Extra
    ``frozen`` flag models the hung-but-undetected window between a
    fault and its watchdog detection."""

    def __init__(self, idx: int, engine: SimEngine):
        self.idx = int(idx)
        self.engine = engine
        self.state = "live"
        self.frozen = False
        self.bursts = 0
        self.death: str | None = None


class SimFleet:
    """N simulated replicas behind the real router + admission."""

    def __init__(self, *, replicas: int = 2,
                 cost: SimCostModel | None = None,
                 max_queue: int = 8, burst_s_prior: float = 0.05,
                 calibrate_admission: bool = True,
                 deadline_s: float | None = None,
                 **engine_kwargs):
        n = int(replicas)
        if n < 1:
            raise ValueError(f"need >= 1 replica, got {n}")
        self.cost = cost if cost is not None else SimCostModel()
        self.deadline_s = deadline_s
        self.replicas = [SimReplica(i, SimEngine(cost=self.cost,
                                                 **engine_kwargs))
                         for i in range(n)]
        eng0 = self.replicas[0].engine
        self.view_capacity = eng0.view_capacity
        self.admission = AdmissionController(
            n * eng0.max_batch, max_queue=max_queue,
            burst_s=burst_s_prior, steps_per_burst=eng0.sync_every,
            calibrate=calibrate_admission)
        self.router = Router(self.admission)
        self._pending: list[Request] = []
        self._scheduled: list[tuple[float, str, dict]] = []
        self._rid = 0
        self.completed: list[Request] = []
        self.submitted: list[Request] = []
        self.events: list[dict] = []
        self.tenant_of: dict[int, int] = {}
        self._swap: dict | None = None
        self._pending_cost = 0.0
        self.clock = VirtualClock(0.0)

    # ---- intake (mirrors Fleet.submit) --------------------------------
    def submit(self, prompt, max_new_tokens: int,
               arrival_s: float | None = None,
               deadline_s: float | None = None,
               tenant: int = -1) -> Request | Rejection:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1 or max_new_tokens < 1:
            raise ValueError("need >= 1 prompt token and >= 1 new token")
        if prompt.size + max_new_tokens > self.view_capacity:
            raise ValueError(
                f"prompt {prompt.size} + new {max_new_tokens} exceeds "
                f"the fleet's view capacity {self.view_capacity} "
                f"(raise max_seq_len)")
        req = Request(rid=self._rid, prompt=prompt,
                      max_new_tokens=int(max_new_tokens),
                      arrival_s=(None if arrival_s is None
                                 else float(arrival_s)))
        self._rid += 1
        self.tenant_of[req.rid] = int(tenant)
        if deadline_s is None:
            deadline_s = self.deadline_s
        rej = self.router.submit(req, deadline_s)
        if rej is not None:
            return rej
        self._pending.append(req)
        self.submitted.append(req)
        return req

    # ---- fault / swap scheduling --------------------------------------
    def schedule_kill(self, t_s: float, replica_idx: int) -> None:
        """Replica dies at ``t_s``; the fleet notices (and fails over)
        ``cost.failover_detect_s`` later.  Schedule several at the same
        ``t_s`` for a regional failover."""
        self._scheduled.append((float(t_s), "freeze",
                                {"replica": int(replica_idx)}))
        self._scheduled.append(
            (float(t_s) + self.cost.failover_detect_s, "kill",
             {"replica": int(replica_idx)}))

    def schedule_swap_at(self, t_s: float, *,
                         after_completed: int = 0) -> None:
        """Arm the rolling weight swap at virtual time ``t_s`` — the
        restore is charged ``cost.swap_restore_s`` on the clock, then
        replicas drain and flip one at a time, zero-drop."""
        self._scheduled.append((float(t_s), "swap",
                                {"after": int(after_completed)}))

    # ---- event handling -----------------------------------------------
    def _event(self, now: float, event: str, **kw) -> None:
        self.events.append({"t_s": round(now, 6), "event": event, **kw})

    def _handle(self, kind: str, payload, now: float) -> None:
        if kind == "arrival":
            self.router.enqueue(payload)
            return
        if kind == "freeze":
            rep = self.replicas[payload["replica"]]
            if rep.state != "dead":
                rep.frozen = True
                self._event(now, "replica_fault_injected",
                            replica=rep.idx)
            return
        if kind == "kill":
            rep = self.replicas[payload["replica"]]
            if rep.state == "dead":
                return
            rep.state = "dead"
            rep.death = "SimKill"
            orphans = rep.engine.release_all()
            self.router.requeue_front(orphans)
            survivors = [r.idx for r in self.replicas
                         if r.state == "live"]
            self._event(now, "replica_dead", replica=rep.idx,
                        trigger="SimKill", burst=rep.bursts,
                        requeued=len(orphans))
            if not survivors:
                raise RuntimeError(
                    f"all {len(self.replicas)} replicas dead at "
                    f"t={now:.3f}s")
            return
        if kind == "swap":
            self._swap = {"after": payload["after"], "state": "armed",
                          "queue": []}
            return
        raise ValueError(f"unknown sim event kind {kind!r}")

    def _maybe_swap(self, now: float, force: bool = False) -> None:
        sw = self._swap
        if sw is None:
            return
        if sw["state"] == "armed":
            if len(self.completed) < sw["after"] and not force:
                return
            self._pending_cost += self.cost.swap_restore_s
            sw["queue"] = [r for r in self.replicas
                           if r.state != "dead"]
            sw["state"] = "draining"
            self._event(now, "swap_started",
                        replicas=[r.idx for r in sw["queue"]])
        if sw["state"] == "draining":
            while sw["queue"]:
                rep = sw["queue"][0]
                if rep.state == "dead":
                    sw["queue"].pop(0)
                    continue
                rep.state = "draining"
                if rep.engine.in_flight() > 0:
                    return
                rep.state = "live"
                sw["queue"].pop(0)
                self._event(now, "swap_replica", replica=rep.idx)
            self._event(now, "swap_complete")
            self._swap = None

    # ---- the drive loop (mirrors Fleet.run on virtual time) -----------
    def _has_work(self) -> bool:
        return bool(self.router.queue) or any(
            r.state != "dead" and r.engine.in_flight() > 0
            for r in self.replicas)

    def run(self) -> list[Request]:
        heap = EventHeap()
        arrivals = 0
        for req in sorted(self._pending,
                          key=lambda r: (r.arrival_s or 0.0, r.rid)):
            heap.push(req.arrival_s or 0.0, "arrival", req)
            arrivals += 1
        self._pending = []
        for t, kind, payload in sorted(self._scheduled,
                                       key=lambda e: e[0]):
            heap.push(t, kind, payload)
        self._scheduled = []
        clock = self.clock
        done_base = len(self.completed)
        while True:
            while heap and heap.peek_t() <= clock.now:
                _t, kind, payload = heap.pop()
                if kind == "arrival":
                    arrivals -= 1
                self._handle(kind, payload, clock.now)
            self._maybe_swap(clock.now,
                             force=arrivals == 0 and not self._has_work())
            if not self._has_work():
                if not heap and self._swap is None:
                    break
                if heap:
                    clock.advance_to(heap.peek_t())
                    continue
                break    # swap already forced above; nothing else runs
            self.router.dispatch(self.replicas, clock.now)
            round_cost, self._pending_cost = self._pending_cost, 0.0
            progressed = False
            for rep in self.replicas:
                if rep.state == "dead" or rep.frozen \
                        or rep.engine.in_flight() == 0:
                    continue
                done, cost = rep.engine.step_round(
                    clock.now + round_cost)
                self.admission.observe_burst(cost)
                if rep.engine.prefix_cache is not None:
                    self.admission.note_cache_hit_rate(
                        rep.engine.prefix_cache.hit_rate)
                rep.bursts += 1
                round_cost += cost
                self.completed.extend(done)
                progressed = True
            if round_cost > 0:
                clock.advance(round_cost)
            if not progressed:
                # nothing could step (frozen replicas holding work, or
                # queue waiting on a busy fleet): time passes until the
                # next scheduled event unfreezes the world
                if not heap:
                    if any(r.frozen and r.state != "dead"
                           for r in self.replicas):
                        raise RuntimeError(
                            "sim deadlock: frozen replica holds work "
                            "but no kill event is scheduled")
                    if round_cost == 0:
                        raise RuntimeError(
                            "sim deadlock: work pending but no replica "
                            "can progress and no events remain")
                else:
                    clock.advance_to(heap.peek_t())
        return self.completed[done_base:]

    # ---- reporting -----------------------------------------------------
    def dropped(self) -> list[int]:
        done = {r.rid for r in self.completed}
        return [r.rid for r in self.submitted if r.rid not in done]

    def digest(self) -> str:
        """sha256 over the completed set (rid, t_first, t_done,
        token count) and the shed set (rid, reason) — THE
        reproducibility pin: same seed + same knobs ⇒ same digest,
        bit for bit."""
        h = hashlib.sha256()
        for r in sorted(self.completed, key=lambda r: r.rid):
            h.update(struct.pack(
                "<qddq", r.rid, float(r.t_first or 0.0),
                float(r.t_done or 0.0), len(r.tokens)))
        for rej in self.router.rejections:
            h.update(struct.pack("<qd", rej.rid, rej.t_s))
            h.update(rej.reason.encode())
        return h.hexdigest()

    def slo_report(self, slo_ms: float | None = None) -> dict:
        """The fleet SLO aggregate on the sim substrate, plus what only
        this substrate can afford: per-tenant fairness and
        SLO-attainment curves over the full offered load.  ``slo_ms``
        is the reference TTFT threshold for the scalar fairness
        numbers (defaults to the admission deadline, else 400 ms)."""
        if slo_ms is None:
            slo_ms = (self.deadline_s * 1e3 if self.deadline_s
                      else 400.0)
        done = [r for r in self.completed if r.t_done is not None]
        ttft = np.array([r.ttft_s for r in done
                         if r.ttft_s is not None]) * 1e3
        ptl = np.array([r.per_token_s for r in done
                        if r.per_token_s is not None]) * 1e3
        pct = lambda a, q: (round(float(np.percentile(a, q)), 3)
                            if a.size else None)
        offered = self.admission.offered_total
        shed = list(self.router.rejections)

        # ---- per-tenant breakdown + fairness --------------------------
        ten_done: dict[int, list] = {}
        ten_offered: dict[int, int] = {}
        ten_shed: dict[int, int] = {}
        for rid, ten in self.tenant_of.items():
            ten_offered[ten] = ten_offered.get(ten, 0) + 1
        for rej in shed:
            ten = self.tenant_of.get(rej.rid, -1)
            ten_shed[ten] = ten_shed.get(ten, 0) + 1
        for r in done:
            ten = self.tenant_of.get(r.rid, -1)
            ten_done.setdefault(ten, []).append(r)
        grid = list(ATTAINMENT_GRID_MS)

        def curve(reqs, n_offered):
            tt = np.array([r.ttft_s for r in reqs
                           if r.ttft_s is not None]) * 1e3
            n = max(n_offered, 1)
            return [round(float((tt <= g).sum()) / n, 4) for g in grid]

        tenants = {}
        attained_fracs = []
        for ten in sorted(ten_offered):
            reqs = ten_done.get(ten, [])
            tt = np.array([r.ttft_s for r in reqs
                           if r.ttft_s is not None]) * 1e3
            n_off = ten_offered[ten]
            att = float((tt <= slo_ms).sum()) / max(n_off, 1)
            attained_fracs.append(att)
            tenants[str(ten)] = {
                "offered": n_off,
                "completed": len(reqs),
                "shed": ten_shed.get(ten, 0),
                "ttft_ms": {"p50": pct(tt, 50), "p99": pct(tt, 99)},
                "attainment": round(att, 4),
                "tokens": int(sum(len(r.tokens) for r in reqs)),
            }
        fair = np.array(attained_fracs, np.float64)
        jain = (float(fair.sum()) ** 2
                / (fair.size * float((fair ** 2).sum()))
                if fair.size and float((fair ** 2).sum()) > 0 else None)
        worst = (min(zip(attained_fracs, sorted(ten_offered)))
                 if attained_fracs else None)

        rep = {
            "substrate": "sim",
            "cost_model": self.cost.to_dict(),
            "replicas": len(self.replicas),
            "live": sum(r.state == "live" for r in self.replicas),
            "offered": offered,
            "submitted": len(self.submitted),
            "shed": len(shed),
            "completed": len(done),
            "dropped": len(self.dropped()),
            "virtual_duration_s": round(self.clock.now, 6),
            "ttft_ms": {"p50": pct(ttft, 50), "p99": pct(ttft, 99),
                        "mean": (round(float(ttft.mean()), 3)
                                 if ttft.size else None)},
            "per_token_ms": {"p50": pct(ptl, 50), "p99": pct(ptl, 99)},
            "admission": {
                "offered": self.admission.offered_total,
                "shed": self.admission.shed_total,
                "max_queue": self.admission.max_queue,
                "burst_s_prior": round(self.admission.burst_s, 5),
                "total_slots": self.admission.total_slots,
            },
            "rounds": sum(r.bursts for r in self.replicas),
            "slo_ms": slo_ms,
            "attainment": {
                "thresholds_ms": grid,
                "overall": curve(done, offered),
            },
            "tenants": tenants,
            "fairness": {
                "jain_attainment": (round(jain, 4)
                                    if jain is not None else None),
                "worst_tenant": (
                    {"tenant": worst[1],
                     "attainment": round(worst[0], 4)}
                    if worst else None),
            },
            "events": list(self.events),
            "digest": self.digest(),
        }
        if self.replicas[0].engine.prefix_cache is not None:
            live = [r for r in self.replicas if r.state != "dead"]
            rep["prefix_cache"] = {
                "hit_rate": round(float(np.mean(
                    [r.engine.prefix_cache.hit_rate
                     for r in live])), 4) if live else None,
            }
        return rep


def simulate_trace(trace, *, cost: SimCostModel | None = None,
                   replicas: int = 2, deadline_s: float | None = None,
                   backoff_s: float | None = None,
                   kills: tuple = (), swap_at_s: float | None = None,
                   fleet_kwargs: dict | None = None,
                   engine_kwargs: dict | None = None) -> SimFleet:
    """Drive a trace end to end: submit every record in arrival order
    with serve_bench's queue-full backpressure (later arrivals shift by
    one modeled burst per overflow — the 429-pacing the real driver
    applies), schedule any faults, run, return the fleet for
    reporting.  ``trace`` is a list of
    :class:`~..serving.traces.TraceRequest` or (t, prompt, new)
    triples."""
    fleet = SimFleet(replicas=replicas, deadline_s=deadline_s,
                     **(fleet_kwargs or {}), cost=cost,
                     **(engine_kwargs or {}))
    if backoff_s is None:
        backoff_s = fleet.admission.burst_s
    for t_s, idx in kills:
        fleet.schedule_kill(t_s, idx)
    if swap_at_s is not None:
        fleet.schedule_swap_at(swap_at_s)
    offset = 0.0
    for rec in trace:
        if hasattr(rec, "arrival_s"):
            t, prompt, new, tenant = (rec.arrival_s, rec.prompt,
                                      rec.max_new, rec.tenant)
        else:
            t, prompt, new = rec
            tenant = -1
        r = fleet.submit(prompt, max_new_tokens=new,
                         arrival_s=t + offset, tenant=tenant)
        if isinstance(r, Rejection) and r.reason == "queue_full":
            offset += backoff_s
    fleet.run()
    return fleet
