"""Virtual-clock fleet simulator: the serving control plane's second,
fast execution substrate.

The REAL host logic — :class:`~..serving.router.AdmissionController`,
:class:`~..serving.router.Router`,
:class:`~..serving.scheduler.ContinuousBatcher`,
:class:`~..serving.kv_pool.PageAllocator` and
:class:`~..serving.kv_pool.RadixPrefixCache` — runs UNMODIFIED against
an injected clock; only the device work (prefill chunks, decode
bursts, spec verify) is replaced by durations from a
:class:`~.cost.SimCostModel` calibrated on measured per-burst costs
from real `serve_bench` runs.  A 10^5-request diurnal tenant-skewed
trace simulates on the CPU tier in minutes, bitwise-reproducible from
the seed, and the simulator's TTFT/p99 predictions are validated
against real fleet runs on matched traces (``tests/test_sim.py``) —
the same measured-beats-modeled discipline the planner and tuner
follow.

Entry points: :class:`SimFleet` here, ``scripts/sim_bench.py`` /
``dts-launch sim`` for trace generation, policy comparison and
knob-space pre-ranking.
"""

from .clock import EventHeap, VirtualClock
from .cost import SimCostModel
from .engine import SimEngine
from .fleet import SimFleet, SimReplica, simulate_trace

__all__ = ["VirtualClock", "EventHeap", "SimCostModel", "SimEngine",
           "SimFleet", "SimReplica", "simulate_trace"]
