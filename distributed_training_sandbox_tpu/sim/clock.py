"""Virtual clock + deterministic event heap — the simulator's time
substrate.

Nothing in this package reads a wall clock (the ``wall-clock-in-sim``
pitfall lint enforces it): time is a float the simulation advances,
and ordering between same-timestamp events is broken by a monotonic
sequence number, never by payload comparison or insertion accident.
That pair of rules is what makes a 10^5-event run bitwise-reproducible
from its seed.
"""

from __future__ import annotations

import heapq
import itertools

__all__ = ["VirtualClock", "EventHeap"]


class VirtualClock:
    """Monotonic virtual seconds.  ``advance`` moves by a duration,
    ``advance_to`` jumps forward to an absolute time (idle skip to the
    next event); both refuse to move backwards — a negative dt is a
    cost-model bug, not a scheduling decision."""

    def __init__(self, t0: float = 0.0):
        self.now = float(t0)

    def advance(self, dt: float) -> float:
        if dt < 0:
            raise ValueError(f"virtual clock cannot rewind (dt={dt})")
        self.now += float(dt)
        return self.now

    def advance_to(self, t: float) -> float:
        self.now = max(self.now, float(t))
        return self.now

    def __repr__(self) -> str:
        return f"VirtualClock(now={self.now:.6f})"


class EventHeap:
    """Min-heap of ``(t_s, seq, kind, payload)`` events.  ``seq`` is a
    per-heap monotonic counter, so two events at the same virtual time
    pop in push order and the payload is never compared."""

    def __init__(self):
        self._heap: list[tuple] = []
        self._seq = itertools.count()

    def push(self, t_s: float, kind: str, payload=None) -> None:
        heapq.heappush(self._heap,
                       (float(t_s), next(self._seq), kind, payload))

    def pop(self) -> tuple:
        """(t_s, kind, payload) of the earliest event."""
        t, _seq, kind, payload = heapq.heappop(self._heap)
        return t, kind, payload

    def peek_t(self) -> float | None:
        return self._heap[0][0] if self._heap else None

    def __len__(self) -> int:
        return len(self._heap)

    def __bool__(self) -> bool:
        return bool(self._heap)
