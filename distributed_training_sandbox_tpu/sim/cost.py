"""Calibrated service-time model: what a replica's device work costs
on the virtual clock.

The fixed-shape law is what makes this model small: every jitted
serving step has a static shape — a prefill chunk is always ``(1, C)``
(or ``(B, C)`` batched under flash), a decode burst is always
``sync_every`` steps over the full ``max_batch`` — so its cost is a
CONSTANT, independent of occupancy.  The whole device is therefore
four scalars (admit overhead, per prefill chunk, per decode step, per
speculative macro-step) plus two control-plane delays (weight-swap
restore, failover detection).

Calibration follows measured-beats-modeled: :meth:`from_fleet` reads
the per-phase totals a live :class:`~..serving.fleet.Fleet` just
accumulated (``stats["prefill_s"] / stats["decode_s"]``),
:meth:`from_summary` / :meth:`from_run_dir` read the same totals from
an archived run's ``summary.json`` (``scheduler.prefill_ms_total`` /
``decode_ms_total``, filed per replica), and the swap/failover delays
come from the fleet event timeline when one is present.  The
checked-in defaults are CPU-tier numbers for TINY_LM — good enough
for policy A/B ranking, NOT for absolute latency claims; anything
absolute must recalibrate against a real run (the validation gate in
``tests/test_sim.py`` enforces the agreement band).
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass, replace
from pathlib import Path

__all__ = ["SimCostModel"]


@dataclass(frozen=True)
class SimCostModel:
    """Virtual seconds per unit of replica work (CPU-tier defaults)."""
    admit_s: float = 2e-4          # scheduler round overhead
    prefill_chunk_s: float = 8e-3  # one (1, C) prefill step
    prefill_batch_chunk_s: float = 1.2e-2   # one (B, C) flash chunk
    decode_step_s: float = 5e-3    # one fixed-shape decode step
    spec_step_s: float = 9e-3      # one macro-step (k draft + verify)
    spec_acceptance: float = 0.6   # mean accepted/proposed per slot
    swap_restore_s: float = 0.15   # checkpoint restore, once per swap
    failover_detect_s: float = 0.5  # death -> watchdog detection
    source: str = "defaults"

    # ---- derived -----------------------------------------------------
    def decode_burst_s(self, sync_every: int, spec_k: int = 0) -> float:
        """Cost of one burst: ``sync_every`` decode steps, or
        ``sync_every`` speculative macro-steps when ``spec_k > 0``."""
        per = self.spec_step_s if spec_k else self.decode_step_s
        return per * max(int(sync_every), 1)

    def tokens_per_macro_step(self, spec_k: int) -> float:
        """Expected committed tokens per macro-step: 1 bonus token plus
        the accepted draft prefix (temp-0 speculation commits
        1..k+1)."""
        if not spec_k:
            return 1.0
        return 1.0 + float(spec_k) * self.spec_acceptance

    # ---- calibration -------------------------------------------------
    @classmethod
    def from_fleet(cls, fleet) -> "SimCostModel":
        """Calibrate from a live Fleet that just ran: aggregate the
        replicas' measured per-phase totals into per-unit costs."""
        stats = [r.engine.stats for r in fleet.replicas]
        spec_k = getattr(fleet.replicas[0].engine, "spec_k", 0)
        acc = None
        prop = sum(s["spec_proposed"] for s in stats)
        if spec_k and prop:
            acc = sum(s["spec_accepted"] for s in stats) / prop
        return cls._from_totals(
            rounds=sum(s["rounds"] for s in stats),
            prefill_chunks=sum(s["prefill_chunks"] for s in stats),
            decode_steps=sum(s["decode_steps"] for s in stats),
            admit_s=sum(s["admit_s"] for s in stats),
            prefill_s=sum(s["prefill_s"] for s in stats),
            decode_s=sum(s["decode_s"] for s in stats),
            spec_k=spec_k, spec_acceptance=acc,
            events=getattr(fleet, "events", None),
            source="fleet:live")

    @classmethod
    def from_summary(cls, summary: dict,
                     source: str = "summary") -> "SimCostModel":
        """Calibrate from an archived run's ``summary.json`` dict —
        either a fleet run (per-replica scheduler blocks) or a
        single-engine serving run (one scheduler block)."""
        scheds, spec_k, acc, events = [], 0, None, None
        fleet = summary.get("fleet")
        if fleet:
            scheds = [r["scheduler"] for r in fleet.get(
                "replica_slo", []) if "scheduler" in r]
            events = fleet.get("events")
        serving = summary.get("serving")
        if serving and not scheds:
            scheds = [serving["scheduler"]]
            spec = serving.get("speculative") or {}
            spec_k = spec.get("k", 0)
            acc = spec.get("acceptance_rate")
        if not scheds:
            raise ValueError(
                "summary has no scheduler block with measured "
                "per-phase totals (needs a fleet/serving run recorded "
                "at or after the simulator landed)")
        tot = lambda k: sum(s.get(k) or 0 for s in scheds)
        return cls._from_totals(
            rounds=tot("rounds"), prefill_chunks=tot("prefill_chunks"),
            decode_steps=tot("decode_steps"),
            admit_s=tot("admit_ms_total") / 1e3,
            prefill_s=tot("prefill_ms_total") / 1e3,
            decode_s=tot("decode_ms_total") / 1e3,
            spec_k=spec_k, spec_acceptance=acc, events=events,
            source=source)

    @classmethod
    def from_run_dir(cls, run_dir) -> "SimCostModel":
        run_dir = Path(run_dir)
        summary = json.loads((run_dir / "summary.json").read_text())
        return cls.from_summary(summary, source=f"run:{run_dir.name}")

    @classmethod
    def from_registry(cls, db_path) -> "SimCostModel":
        """Calibrate from the newest REAL (non-sim) serving/fleet row
        in the run registry whose run_dir still has its summary."""
        import sqlite3
        conn = sqlite3.connect(str(db_path))
        try:
            conn.row_factory = sqlite3.Row
            try:
                rows = conn.execute(
                    "SELECT run_id, run_dir FROM runs "
                    "WHERE COALESCE(sim, 0) = 0 "
                    "ORDER BY started_utc DESC"
                ).fetchall()
            except sqlite3.OperationalError:
                # registry predates the sim column
                rows = conn.execute(
                    "SELECT run_id, run_dir FROM runs "
                    "ORDER BY started_utc DESC").fetchall()
        finally:
            conn.close()
        for row in rows:
            summ = Path(row["run_dir"] or "") / "summary.json"
            if not summ.is_file():
                continue
            try:
                return cls.from_summary(
                    json.loads(summ.read_text()),
                    source=f"registry:{row['run_id']}")
            except (ValueError, KeyError, json.JSONDecodeError):
                continue
        raise ValueError(
            f"no indexed real run under {db_path} carries measured "
            f"per-phase scheduler totals — run serve_bench and "
            f"`scripts/runs.py index` first")

    @classmethod
    def _from_totals(cls, *, rounds, prefill_chunks, decode_steps,
                     admit_s, prefill_s, decode_s, spec_k=0,
                     spec_acceptance=None, events=None,
                     source="measured") -> "SimCostModel":
        d = cls()           # defaults fill whatever wasn't measured
        kw = {"source": source}
        if rounds:
            kw["admit_s"] = admit_s / rounds
        if prefill_chunks and prefill_s > 0:
            per = prefill_s / prefill_chunks
            kw["prefill_chunk_s"] = per
            kw["prefill_batch_chunk_s"] = per * (
                d.prefill_batch_chunk_s / d.prefill_chunk_s)
        if decode_steps and decode_s > 0:
            per = decode_s / decode_steps
            if spec_k:
                # the calibration run's decode totals ARE macro-steps
                kw["spec_step_s"] = per
                kw["decode_step_s"] = per * (
                    d.decode_step_s / d.spec_step_s)
            else:
                kw["decode_step_s"] = per
                kw["spec_step_s"] = per * (
                    d.spec_step_s / d.decode_step_s)
        if spec_acceptance is not None:
            kw["spec_acceptance"] = float(spec_acceptance)
        for k, v in cls._delays_from_events(events or []).items():
            kw[k] = v
        return replace(d, **kw)

    @staticmethod
    def _delays_from_events(events) -> dict:
        """Swap/failover delays from a fleet event timeline (the chaos
        rows): restore duration = swap_started−swap_complete span over
        the replicas swapped; detection delay is only observable as
        the burst gap before replica_dead, so it stays a default
        unless a chaos summary pins it."""
        out = {}
        t_start, n_replicas = None, 0
        for ev in events:
            if ev.get("event") == "swap_started":
                t_start = ev.get("t_s")
                n_replicas = max(len(ev.get("replicas", [])), 1)
            elif ev.get("event") == "swap_complete" \
                    and t_start is not None:
                span = float(ev["t_s"]) - float(t_start)
                if span > 0:
                    out["swap_restore_s"] = span / n_replicas
                t_start = None
        return out

    # ---- (de)serialization -------------------------------------------
    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "SimCostModel":
        known = {f for f in cls.__dataclass_fields__}
        return cls(**{k: v for k, v in d.items() if k in known})
