"""ZeRO-1/2/3 from scratch over a named TPU mesh.

Reference mechanisms (SURVEY.md §2.2):
  * ZeRO-1 ``ShardedOptimizer`` (``zero/zero1.py:43-108``): optimizer state
    partitioned by param; per step, per-param grad all_reduce + average →
    local Adam on the owned partition → per-param broadcast from owner.
  * ZeRO-2 (``zero/zero2.py:94-133``): grads reduce_scattered per param
    instead of all_reduced; update + broadcast as ZeRO-1.
  * ZeRO-3 (``zero/zero3.py:36-77,104-165``): params sharded at rest;
    ``materialize()`` all_gathers around every layer in forward AND backward
    (hooks), grads sharded, local Adam, no broadcast.

TPU design (deliberate deviations, all visible in the HLO counts):
  * Partition granularity is the **flat per-param chunk**: each param is
    flattened, padded to a multiple of ws, and every device owns 1/ws of
    *every* param — instead of whole-param ownership with the remainder
    spread (``zero1.py:55-62``).  Whole-param ownership gives devices
    different state *shapes*, which fights SPMD; chunking gives the same
    per-device memory saving (exactly 1/ws, not just on average) with one
    program.  The reference's owner-rank arithmetic lives on in
    ``owner_of_param`` (used by tests to pin the rule).
  * ``rebuild="broadcast"`` (default) reconstructs updated params with a
    masked psum — the wire/trace twin of the reference's per-param
    ``dist.broadcast`` (NCCL accounts those as all_reduce too,
    ``README.md:11-12``), so ZeRO-1 shows 12 grad all_reduces + 12 param
    rebuilds per step = the reference's 60+60 per 5 profiled steps.
    ``rebuild="all_gather"`` is the faster choice ((ws-1)/ws the bytes).
  * ZeRO-2 reduce_scatters the *unconcatenated* grad via ``lax.psum_scatter``
    — fixing the reference's ws× concat memory spike that its README admits
    (``README.md:19``, ``zero2.py:104``).
  * ZeRO-3 materializes params per layer inside ``jax.checkpoint``, so the
    backward pass re-gathers exactly like the reference's backward pre-hooks
    (``zero3.py:56-77``): 2 params × 6 layers × (fwd+bwd) = 24 all_gathers
    per step = the reference's 120 per 5 steps.  Gradients arrive through
    the all_gather transpose — a psum_scatter per param, which both averages
    *and* shards in one collective (the reference all_reduces full grads
    then discards the non-owned part, ``zero3.py:123-165``; same math, less
    traffic).  Its for/else grad-nulling bug (``zero3.py:150-153``) is
    intended-behavior-only here.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..ops import collectives as C
from ..utils.profiling import scope
from . import optim


# ---------------------------------------------------------------- partition

def partition_params(n_params: int, ws: int) -> list[list[int]]:
    """The reference's whole-param partition rule: contiguous param-index
    ranges, remainder spread over the leading ranks (``zero1.py:55-62``)."""
    base, rem = divmod(n_params, ws)
    out, start = [], 0
    for r in range(ws):
        size = base + (1 if r < rem else 0)
        out.append(list(range(start, start + size)))
        start += size
    return out


def owner_of_param(i: int, n_params: int, ws: int) -> int:
    """Arithmetic owner-rank recomputation, twin of ``zero1.py:91-102``."""
    base, rem = divmod(n_params, ws)
    boundary = rem * (base + 1)
    if i < boundary:
        return i // (base + 1)
    return rem + (i - boundary) // base if base else ws - 1


# ------------------------------------------------------------ chunk helpers

def _padded_size(size: int, ws: int) -> int:
    return -(-size // ws) * ws


def _pad_flat(x: jax.Array, ws: int) -> jax.Array:
    """Flatten and zero-pad to a multiple of ws — the one place the chunk
    alignment rule lives (local_chunk, ZeRO-2 reduce_scatter and chunk_shapes
    must all agree on it)."""
    flat = x.reshape(-1)
    pad = _padded_size(flat.size, ws) - flat.size
    return jnp.pad(flat, (0, pad)) if pad else flat


def local_chunk(full: jax.Array, axis: str) -> jax.Array:
    """This device's flat chunk of ``full`` (pad-to-ws then slice).  Pure
    data movement, no collective."""
    ws = C.axis_size(axis)
    idx = lax.axis_index(axis)
    flat = _pad_flat(full, ws)
    c = flat.size // ws
    return lax.dynamic_slice(flat, (idx * c,), (c,))


def rebuild_param(chunk: jax.Array, shape, size: int, axis: str,
                  mode: str = "broadcast") -> jax.Array:
    """Reassemble the full param from per-device chunks.

    mode="broadcast": masked psum — each device contributes its chunk at its
    offset, zeros elsewhere; the psum is the per-param owner-broadcast twin.
    mode="all_gather": tiled all_gather (less traffic, same result).
    """
    if mode == "all_gather":
        flat = C.all_gather(chunk, axis)
    elif mode == "broadcast":
        ws = C.axis_size(axis)
        idx = lax.axis_index(axis)
        padded = jnp.zeros((chunk.size * ws,), chunk.dtype)
        padded = lax.dynamic_update_slice(padded, chunk, (idx * chunk.size,))
        flat = C.all_reduce(padded, axis)
    else:
        raise ValueError(f"unknown rebuild mode {mode!r}")
    return flat[:size].reshape(shape)


def chunk_shapes(params, ws: int):
    """ShapeDtypeStructs of the per-device chunk tree (for init/state)."""
    return jax.tree.map(
        lambda p: jax.ShapeDtypeStruct((_padded_size(p.size, ws) // ws,),
                                       p.dtype), params)


# ------------------------------------------------------------- ZeRO-1 / -2

def make_zero_train_step(
    loss_fn: Callable,
    mesh: Mesh,
    axis: str = "dp",
    *,
    stage: int = 1,
    lr: float = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    rebuild: str = "broadcast",
    with_barrier: bool = True,
    donate: bool = True,
):
    """Jitted ZeRO-1 or ZeRO-2 step:
    ``(params, opt_state, batch) -> (params, opt_state, loss)``.

    ``params`` replicated (P()); ``opt_state`` = AdamState whose mu/nu leaves
    are flat per-param chunks sharded on ``axis``; ``batch`` sharded on
    ``axis`` (data parallel over the same axis, as ZeRO composes with DP).
    """
    if stage not in (1, 2):
        raise ValueError("use make_zero3_train_step for stage 3")
    ws = int(mesh.shape[axis])

    def step(params, opt_state, batch):
        with scope("forward_backward"):
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        with scope("loss_mean"):
            loss = C.all_reduce(loss, axis, mean=True)

        if stage == 1:
            # per-param all_reduce + average, then chunk (zero1.py:80-84)
            with scope("all_reduce_gradients"):
                grads = C.tree_all_reduce(grads, axis, mean=True)
            grad_chunks = jax.tree.map(lambda g: local_chunk(g, axis), grads)
        else:
            # per-param reduce_scatter straight to the chunk (zero2.py:94-115
            # minus the ws-fold concat spike)
            with scope("reduce_scatter_gradients"):
                grad_chunks = jax.tree.map(
                    lambda g: C.reduce_scatter(_pad_flat(g, ws), axis) / ws,
                    grads)

        with scope("opt_step"):
            param_chunks = jax.tree.map(lambda p: local_chunk(p, axis), params)
            new_chunks, opt_state = optim.adam_update(
                grad_chunks, opt_state, param_chunks,
                lr=lr, b1=b1, b2=b2, eps=eps)

        with scope("broadcast_parameters"):
            params = jax.tree.map(
                lambda c, p: rebuild_param(c, p.shape, p.size, axis, rebuild),
                new_chunks, params)

        if with_barrier:
            with scope("barrier"):
                loss = loss + 0.0 * C.barrier(axis)
        return params, opt_state, loss

    state_specs = optim.AdamState(mu=P(axis), nu=P(axis), count=P())  # spec-ok
    sharded = C.smap(step, mesh,
                     in_specs=(P(), state_specs, P(axis)),  # spec-ok
                     out_specs=(P(), state_specs, P()))
    return jax.jit(sharded, donate_argnums=(0, 1) if donate else ())


def init_zero_opt_state(params, mesh: Mesh, axis: str = "dp"):
    """AdamState over flat per-param chunks, sharded on ``axis`` (each device
    holds 1/ws of every param's mu/nu — the ZeRO-1/2 memory saving)."""
    ws = int(mesh.shape[axis])

    def init():
        zeros = jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype), chunk_shapes(params, ws))
        return optim.AdamState(mu=zeros, nu=zeros,
                               count=jnp.zeros((), jnp.int32))

    specs = optim.AdamState(mu=P(axis), nu=P(axis), count=P())
    return jax.jit(C.smap(init, mesh, (), specs))()


# ------------------------------------------------------------------ ZeRO-3

def make_zero3_mlp_loss(shapes: list[dict], axis: str):
    """Layered MLP loss over *chunked* params with per-layer materialize
    inside ``jax.checkpoint`` — forward gathers + backward re-gathers, the
    hook twin (``zero3.py:56-77``).  ``shapes``: per-layer {"w": (in,out),
    "b": (out,)} shapes of the full params.

    Materialize is always all_gather (as in the reference's traces): its AD
    transpose is a psum_scatter, which sums the per-device grad contributions
    into each chunk.  A masked-psum rebuild must NOT be differentiated
    through — psum's shard_map transpose treats the cotangent as device-local
    and would drop the cross-device reduction.
    """

    def layer_call(chunk_layer, x, meta, is_last):
        with scope("materialize"):
            w = rebuild_param(chunk_layer["w"], meta["w"],
                              math.prod(meta["w"]), axis, "all_gather")
            b = rebuild_param(chunk_layer["b"], meta["b"],
                              math.prod(meta["b"]), axis, "all_gather")
        x = x @ w + b
        return x if is_last else jax.nn.relu(x)

    def loss_fn(chunk_params, batch):
        x, y = batch
        for i, (chunk_layer, meta) in enumerate(zip(chunk_params, shapes)):
            x = jax.checkpoint(
                partial(layer_call, meta=meta, is_last=i == len(shapes) - 1)
            )(chunk_layer, x)
        return jnp.mean((x - y) ** 2)

    return loss_fn


def make_zero3_train_step(
    chunk_loss_fn: Callable,
    mesh: Mesh,
    axis: str = "dp",
    *,
    lr: float = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    with_barrier: bool = True,
    donate: bool = True,
):
    """Jitted ZeRO-3 step over chunk-sharded params:
    ``(chunk_params, opt_state, batch) -> (chunk_params, opt_state, loss)``.

    ``chunk_loss_fn(chunk_params, local_batch)`` must materialize full params
    internally (see make_zero3_mlp_loss).  Its gradient w.r.t. the chunks
    arrives via the all_gather transpose — one psum_scatter per param, summed
    over the axis — so we divide by ws for the data-parallel mean.
    """
    ws = int(mesh.shape[axis])

    def step(chunk_params, opt_state, batch):
        with scope("forward_backward"):
            loss, grad_chunks = jax.value_and_grad(chunk_loss_fn)(
                chunk_params, batch)
        with scope("loss_mean"):
            loss = C.all_reduce(loss, axis, mean=True)
        with scope("grad_mean"):
            grad_chunks = jax.tree.map(lambda g: g / ws, grad_chunks)
        with scope("opt_step"):
            chunk_params, opt_state = optim.adam_update(
                grad_chunks, opt_state, chunk_params,
                lr=lr, b1=b1, b2=b2, eps=eps)
        if with_barrier:
            with scope("barrier"):
                loss = loss + 0.0 * C.barrier(axis)
        return chunk_params, opt_state, loss

    state_specs = optim.AdamState(mu=P(axis), nu=P(axis), count=P())  # spec-ok
    sharded = C.smap(step, mesh,
                     in_specs=(P(axis), state_specs, P(axis)),  # spec-ok
                     out_specs=(P(axis), state_specs, P()))  # spec-ok
    return jax.jit(sharded, donate_argnums=(0, 1) if donate else ())


def shard_params_zero3(params, mesh: Mesh, axis: str = "dp"):
    """Move replicated params to at-rest chunk sharding (P(axis) flat chunks)
    — the ``Zero3ParamManager`` at-init sharding (``zero3.py:104-110``)."""
    sharded = C.smap(
        lambda p: jax.tree.map(lambda a: local_chunk(a, axis), p),
        mesh, P(), P(axis))
    return jax.jit(sharded)(params)
