"""Fully-sharded data parallel training of the real transformer LM.

Twin of the reference's FSDP2 path (``fsdp/train_fsdp.py:78-97``): every
parameter sharded at rest, per-decoder-layer all-gather around compute,
gradients reduce-scattered back to shards, optimizer stepping on shards
(created *after* sharding in the reference — here the optimizer state is
simply built with the same sharding as the params).

Two variants, mirroring the course's from-scratch-then-library rule:

  * **explicit** (`make_fsdp_train_step`): shard_map with hand-placed
    collectives.  Per-layer params are gathered *inside* the rematerialized
    ``lax.scan`` body (``models.transformer.forward``'s ``layer_hook``
    seam), so the backward pass re-gathers them — exactly
    ``reshard_after_forward=True`` (ZeRO-3, reference
    ``train_fsdp.py:84-85``).  With ``reshard_after_forward=False`` the
    gather happens once before the scan and the gathered params stay live
    through the backward (ZeRO-2, ``train_fsdp.py:86``).  Gradients need no
    separate choreography: they flow through the all_gather's AD transpose,
    which IS a psum_scatter — the backward reduce-scatter of FSDP, one per
    gathered leaf, summed across the dp axis.
  * **auto** (`make_fsdp_auto_train_step`): jit with NamedSharding
    constraints only — XLA chooses the collective schedule.  The analogue of
    using torch's ``fully_shard`` after hand-rolling ZeRO.

Sharding layout (`fsdp_specs`): stacked layer leaves (L, a, b) shard their
*first non-layer* dim; plain leaves (embedding, final norm) shard dim 0.
All-gathers are then contiguous row gathers, and every hot matmul sees full
(in, out) operands on the MXU.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import transformer as T
from ..ops import collectives as C
from ..utils.profiling import scope
from . import optim


def _spec_map(f, tree, specs, *rest):
    """tree.map over (leaf, spec) pairs — PartitionSpec is itself a leaf."""
    return jax.tree.map(f, tree, specs, *rest,
                        is_leaf=lambda x: isinstance(x, P))


# ------------------------------------------------------------------ layout

def fsdp_specs(params, axis: str = "dp") -> dict:
    """PartitionSpec tree: shard dim 0 of plain leaves, dim 1 of stacked
    (L, ...) layer leaves (dim 0 is the scan/layer dim)."""

    def leaf_spec(path, leaf):
        inside_layers = any(getattr(k, "key", None) == "layers"
                            for k in path)
        if inside_layers:
            return P(None, axis)
        return P(axis)

    return jax.tree_util.tree_map_with_path(leaf_spec, params)


def check_divisibility(params, specs, mesh: Mesh) -> None:
    def chk(path, leaf, spec):
        for dim, name in enumerate(spec):
            if name is None:
                continue
            ws = int(mesh.shape[name])
            if leaf.shape[dim] % ws:
                raise ValueError(
                    f"param {jax.tree_util.keystr(path)} dim {dim} of size "
                    f"{leaf.shape[dim]} not divisible by mesh axis "
                    f"{name!r}={ws}")
    jax.tree_util.tree_map_with_path(chk, params, specs)


def shard_params_fsdp(params, mesh: Mesh, axis: str = "dp"):
    """Move (replicated/host) params to their at-rest FSDP sharding — the
    ``fully_shard(module)`` moment (reference ``train_fsdp.py:90-94``)."""
    specs = fsdp_specs(params, axis)
    check_divisibility(params, specs, mesh)
    return _spec_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
        params, specs)


def _q8_scale_spec(spec: P, ndim: int) -> P:
    """The Q8 scale leaf's spec: the param's spec with its LAST dim
    unsharded (the scale's last dim is 1)."""
    entries = list(spec) + [None] * (ndim - len(spec))
    entries[ndim - 1] = None
    return P(*entries)


def q8_state_specs(params_sharded, specs):
    """PartitionSpec tree matching ``optim8.adam8_init``'s state: Q8
    leaves for ndim ≥ 2 params, plain specs for 1-D ones."""
    from .optim8 import Q8

    def leaf(p, s):
        if p.ndim < 2:
            return s
        return Q8(q=s, scale=_q8_scale_spec(s, p.ndim))

    return _spec_map(leaf, params_sharded, specs)


def init_fsdp_opt_state8(params_sharded, axis: str = "dp"):
    """int8-at-rest Adam moments (``parallel.optim8``) sharded like the
    params — cuts the largest resident block (mu/nu, 3.31 GB of the
    flagship's 4.96 GB at rest, EXPERIMENTS.md) to ~half.  ``axis``
    must match the FSDP axis the params were sharded over."""
    from . import optim8

    state = optim8.adam8_init(params_sharded)
    specs = fsdp_specs(params_sharded, axis)
    sspecs = q8_state_specs(params_sharded, specs)
    leaf = jax.tree.leaves(params_sharded)[0]
    if not isinstance(getattr(leaf, "sharding", None), NamedSharding):
        return state
    mesh = leaf.sharding.mesh
    put = lambda x, s: jax.device_put(x, NamedSharding(mesh, s))
    placed = jax.tree.map(
        lambda x, s: put(x, s), (state.mu, state.nu), (sspecs, sspecs),
        is_leaf=lambda x: isinstance(x, P))
    return optim.AdamState(
        mu=placed[0], nu=placed[1],
        count=jax.device_put(state.count, NamedSharding(mesh, P())))


def init_fsdp_opt_state(params_sharded, state_dtype=None):
    """Adam state with the same sharding as the param shards it tracks —
    optimizer-after-sharding (reference ``train_fsdp.py:96-97``).  The
    reference's bf16 model gives bf16 torch AdamW state (README.md:23's
    6.2 GB for 3B 2-way); ``state_dtype`` overrides for fp32 state."""

    def zeros(p):
        dt = state_dtype or p.dtype
        return jnp.zeros(p.shape, dt, device=p.sharding)

    count = jnp.zeros((), jnp.int32)
    leaf = jax.tree.leaves(params_sharded)[0]
    if isinstance(getattr(leaf, "sharding", None), NamedSharding):
        # Commit the step counter replicated on the params' mesh so the
        # whole state tree lives on ONE device set — required for e.g.
        # checkpoint restore, which places arrays exactly as templated.
        count = jax.device_put(count, NamedSharding(leaf.sharding.mesh,
                                                    P()))
    return optim.AdamState(mu=jax.tree.map(zeros, params_sharded),
                           nu=jax.tree.map(zeros, params_sharded),
                           count=count)


# ---------------------------------------------------------------- explicit

OVERLAP_MODES = ("none", "ring", "ring_fused", "ring_fused_pallas")


def _gather_leaf(x, spec: P, axis: str, quantized: bool = False,
                 overlap: str = "none", fuse_matmul=False,
                 quantized_grads: bool = False):
    """all_gather a shard back to full size along its sharded dim (no-op for
    leaves this axis doesn't shard).  ``quantized``: ship int8 + scales
    over the wire and dequantize after (the torchao fp8-all-gather twin,
    reference ``fp8/fp8_benchmark.py:79-81``).  Like torchao — which only
    low-precision-casts Linear weights — 1-D leaves (RMSNorm scales) stay
    in full precision: quantizing them saves negligible bandwidth and costs
    outsized numerics.  ``quantized_grads`` additionally quantizes those
    gathers' BACKWARD reduce-scatter (the EQuARX grad-traffic leg —
    ``quant.quantized_reduce_scatter``).

    ``overlap="ring"``: the gather runs as the ppermute ring
    (``C.ring_all_gather``) — bitwise-identical values and grads, but
    n-1 schedulable hops instead of one monolithic collective.
    ``fuse_matmul`` (ring_fused modes, layer-hook leaves only; False or
    the chunk-matmul impl name): a 2-D projection weight sharded along
    its contraction dim is NOT gathered — it returns as a
    :class:`C.RingShard` and the model's projection matmul runs it as
    the decomposed ``all_gather_matmul`` ("xla") or its Pallas
    tile-kernel twin ("pallas")."""
    for dim, name in enumerate(spec):
        if name == axis:
            if quantized and x.ndim > 1:
                from ..ops.quant import quantized_all_gather
                return quantized_all_gather(x, axis, dim, quantized_grads)
            if fuse_matmul and x.ndim == 2 and dim == 0:
                return C.RingShard(
                    x, axis, "pallas" if fuse_matmul == "pallas" else "xla")
            if overlap in ("ring", "ring_fused", "ring_fused_pallas"):
                return C.ring_all_gather(x, axis, dim)
            return C.all_gather(x, axis, axis=dim)
    return x


def microbatch_value_and_grad(loss_fn, params, batch, accum_steps: int):
    """Gradient accumulation over ``accum_steps`` microbatches:
    ``lax.scan`` over the leading-dim split of ``batch``, value_and_grad
    per microbatch, grads summed into a donated scan carry, one final
    /accum_steps — the per-microbatch collectives (FSDP gathers, TP
    rejoins, their transposes) then pipeline against the next
    microbatch's compute instead of arriving as one end-of-step burst.
    Remat-aware: each microbatch's forward re-runs under the model's own
    ``jax.checkpoint`` policy inside ``loss_fn``, so only one
    microbatch's activations (at the configured remat granularity) are
    ever live.  Returns ``(mean_loss, mean_grads)`` — identical to one
    full-batch step up to fp re-association of the batch reduction
    (pinned tight by tests/test_overlap.py)."""
    if accum_steps == 1:
        return jax.value_and_grad(loss_fn)(params, batch)
    B = jax.tree.leaves(batch)[0].shape[0]
    if B % accum_steps:
        raise ValueError(
            f"accum_steps={accum_steps} must divide the per-device "
            f"batch {B} (global batch / dp axis size)")
    micro = jax.tree.map(
        lambda t: t.reshape(accum_steps, B // accum_steps, *t.shape[1:]),
        batch)

    def body(carry, mbatch):
        g_acc, l_acc = carry
        loss, grads = jax.value_and_grad(loss_fn)(params, mbatch)
        return (jax.tree.map(jnp.add, g_acc, grads),
                l_acc + loss.astype(jnp.float32)), None

    init = (jax.tree.map(jnp.zeros_like, params),
            jnp.zeros((), jnp.float32))
    (g_sum, l_sum), _ = jax.lax.scan(body, init, micro)
    return (l_sum / accum_steps,
            jax.tree.map(lambda g: g / accum_steps, g_sum))


def make_fsdp_train_step(
    params_sharded,
    cfg: T.TransformerConfig,
    mesh: Mesh,
    axis: str = "dp",
    *,
    reshard_after_forward: bool = True,
    quantized_gather: bool = False,
    quantized_grads: bool = False,
    overlap: str = "none",
    accum_steps: int = 1,
    offload: str = "none",
    sp_axis: str | None = None,
    lr: float = 3e-4,
    lr_schedule: Callable | None = None,
    b1: float = 0.9,
    b2: float = 0.95,
    eps: float = 1e-8,
    donate: bool = True,
    loss_fn: Callable | None = None,
    state_precision: str = "full",
):
    """Jitted explicit-FSDP step:
    ``(param_shards, opt_state, batch) -> (param_shards, opt_state, loss)``.

    ``params_sharded`` provides the tree structure/specs to jit against;
    ``batch`` = (input_ids, labels) sharded on the batch dim (dp).
    ``loss_fn(params, batch, cfg, layer_hook=...)`` defaults to the
    causal-LM loss (models.transformer.lm_loss).

    ``sp_axis`` adds sequence/context parallelism (parallel/sequence.py):
    the batch's sequence dim shards over that mesh axis, attention runs
    as the ring (``ops/ring_attention.py``), and the sp-replicated param
    grads get an explicit mean-psum across the ring.

    ``lr_schedule``: optional ``count -> lr`` (e.g.
    ``optim.warmup_cosine_schedule``) evaluated on the optimizer step
    counter inside the jitted step; overrides the constant ``lr``.

    ``state_precision``: "full" (moments in the params' dtype,
    ``init_fsdp_opt_state``) or "int8" (``init_fsdp_opt_state8`` /
    ``optim8.adam8_update`` — int8-at-rest moments, ~half the largest
    resident block; pass the matching opt state).

    ``overlap`` (the overlap engine, SimpleFSDP arXiv:2411.00284):
    "none" = monolithic per-leaf all_gathers; "ring" = the same gathers
    decomposed into ppermute ring hops (bitwise-identical losses/grads —
    the backward is pinned to the monolithic psum_scatter transpose);
    "ring_fused" = 2-D projection weights stay sharded and their matmuls
    run as decomposed ``all_gather_matmul`` collective matmuls
    (numerically equivalent, not bitwise: the chunked contraction
    re-associates the K-sum); "ring_fused_pallas" = the same choreography
    with each per-chunk tile matmul lowered through the Pallas kernel
    (``ops.collectives.all_gather_matmul_pallas`` — bitwise-identical to
    ring_fused at whole-chunk blocks).  Both fused modes require the
    per-layer gather seam (reshard_after_forward=True), a dense model,
    and full-precision gathers.

    ``quantized_grads`` (requires ``quantized_gather``): the quantized
    gathers' backward reduce-scatter also runs two-shot int8 on the wire
    (``ops.quant.quantized_reduce_scatter`` — the EQuARX grad-traffic
    leg; ~4x fewer backward bus bytes, per-contribution half-quantum
    error bound).

    ``accum_steps``: microbatched gradient accumulation —
    ``lax.scan`` over accum_steps splits of the batch with a donated
    grad carry (see :func:`microbatch_value_and_grad`); must divide the
    per-device batch.

    ``offload`` (memory planner, ``memory_plan/offload.py``): "opt" /
    "opt_act" park the optimizer state in pinned host memory between
    steps — the jitted step streams it on-device (MoveToDevice) for the
    Adam update and back (MoveToHost) after, transfers XLA's scheduler
    can hide behind the backward.  Pass an opt state placed with
    ``memory_plan.offload_tree``; the step's state output returns to
    host placement.  "opt_act" additionally expects
    ``cfg.offload_activations`` (named remat saves offloaded).  On
    backends without a pinned_host space the step is built transfer-free
    and is bitwise-identical to ``offload="none"``.
    """
    ws = int(mesh.shape[axis])
    if overlap not in OVERLAP_MODES:
        raise ValueError(f"overlap={overlap!r}; choose from "
                         f"{OVERLAP_MODES}")
    if overlap.startswith("ring_fused"):
        if quantized_gather:
            raise ValueError(f"overlap={overlap!r} fuses full-precision "
                             "collective matmuls; it does not compose "
                             "with quantized_gather (use overlap='ring')")
        if not reshard_after_forward:
            raise ValueError(f"overlap={overlap!r} needs the per-layer "
                             "gather seam — reshard_after_forward=False "
                             "keeps gathered weights live, which "
                             "contradicts fused re-ringing")
        if getattr(cfg, "n_experts", 0):
            raise ValueError(f"overlap={overlap!r} covers dense "
                             "projection leaves only; MoE expert leaves "
                             "shard their expert dim, not a contraction "
                             "dim (use overlap='ring')")
    if quantized_grads and not quantized_gather:
        raise ValueError("quantized_grads quantizes the backward "
                         "reduce-scatter of the quantized gathers; it "
                         "requires quantized_gather=True")
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    from ..memory_plan.offload import (
        DEVICE_KIND, HOST_KIND, OFFLOAD_MODES as _OFF,
        stream_tree, supports_host_offload)
    if offload not in _OFF:
        raise ValueError(f"offload={offload!r}; choose from {_OFF}")
    if sp_axis is not None:
        cfg = dataclasses.replace(cfg, attention_impl="ring",
                                  sp_axis=sp_axis)
    base_loss = loss_fn or T.lm_loss
    # per-leaf LR multipliers: the MoE router trains slower when
    # cfg.moe_router_lr_mult < 1 (router-collapse mitigation, ST-MoE)
    lr_mults = None
    if getattr(cfg, "moe_router_lr_mult", 1.0) != 1.0:
        lr_mults = jax.tree_util.tree_map_with_path(
            lambda path, _leaf: (cfg.moe_router_lr_mult
                                 if any(getattr(k, "key", None) == "w_router"
                                        for k in path) else 1.0),
            params_sharded)
    specs = fsdp_specs(params_sharded, axis)
    check_divisibility(params_sharded, specs, mesh)
    layer_specs = specs["layers"]
    # Inside the scan body each stacked leaf has lost its layer dim, so its
    # sharded dim shifts from 1 to 0.
    hook_specs = jax.tree.map(lambda s: P(*s[1:]), layer_specs,  # spec-ok
                              is_leaf=lambda x: isinstance(x, P))

    fuse = {"ring_fused": "xla", "ring_fused_pallas": "pallas"}.get(
        overlap, False)

    def layer_hook(layer):
        with scope("fsdp_layer_gather"):
            return _spec_map(
                lambda x, s: _gather_leaf(x, s, axis, quantized_gather,
                                          overlap, fuse_matmul=fuse,
                                          quantized_grads=quantized_grads),
                layer, hook_specs)

    def step(shards, opt_state, batch):
        def sharded_loss(shards, batch):
            # Root group: embed / final_norm / lm_head gathered up front
            # (the root fully_shard wrap, reference train_fsdp.py:94).
            # Never matmul-fused: embed is a lookup table, not a
            # projection operand.
            with scope("fsdp_root_gather"):
                outer = {k: _gather_leaf(v, specs[k], axis,
                                         quantized_gather, overlap,
                                         quantized_grads=quantized_grads)
                         for k, v in shards.items() if k != "layers"}
            if reshard_after_forward:
                params = {**outer, "layers": shards["layers"]}
                return base_loss(params, batch, cfg, layer_hook=layer_hook)
            # ZeRO-2 mode: gather ALL layers once, keep them live through
            # the backward — more memory, half the gathers (the 3000 vs
            # 1849 tok/s knob, train_fsdp.py:85-86).
            with scope("fsdp_pre_gather_layers"):
                full_layers = _spec_map(
                    lambda x, s: _gather_leaf(
                        x, s, axis, quantized_gather, overlap,
                        quantized_grads=quantized_grads),
                    shards["layers"], layer_specs)
            params = {**outer, "layers": full_layers}
            return base_loss(params, batch, cfg, layer_hook=None)

        with scope("forward_backward"):
            # Grads w.r.t. the SHARDS: each all_gather transposes to a
            # psum_scatter — the FSDP backward reduce-scatter.  With
            # accum_steps > 1 the scan's per-microbatch transposes
            # pipeline against the next microbatch's forward.
            loss, grad_shards = microbatch_value_and_grad(
                sharded_loss, shards, batch, accum_steps)
        with scope("loss_mean"):
            loss = C.all_reduce(loss, axis, mean=True)
            if sp_axis is not None:
                loss = C.all_reduce(loss, sp_axis, mean=True)
        with scope("grad_mean"):
            # dp contributions were already summed into the shards by the
            # gathers' AD transposes; finish the mean.  Under SP the
            # params are replicated across sp_axis, so those grads need
            # an explicit mean-psum across the ring too.
            grad_shards = jax.tree.map(
                (lambda g: C.all_reduce(g, sp_axis, mean=True) / ws)
                if sp_axis is not None else (lambda g: g / ws),
                grad_shards)
        with scope("opt_step"):
            lr_t = lr_schedule(opt_state.count) if lr_schedule else lr
            if state_precision == "int8":
                from . import optim8
                shards, opt_state = optim8.adam8_update(
                    grad_shards, opt_state, shards,
                    lr=lr_t, b1=b1, b2=b2, eps=eps, lr_mults=lr_mults)
            else:
                shards, opt_state = optim.adam_update(
                    grad_shards, opt_state, shards,
                    lr=lr_t, b1=b1, b2=b2, eps=eps, lr_mults=lr_mults)
        return shards, opt_state, loss

    if state_precision == "int8":
        sspec = q8_state_specs(params_sharded, specs)
        state_specs = optim.AdamState(mu=sspec, nu=sspec, count=P())
    else:
        state_specs = optim.AdamState(mu=specs, nu=specs, count=P())
    batch_spec = P(axis) if sp_axis is None else P(axis, sp_axis)  # spec-ok
    sharded = C.smap(step, mesh,
                     in_specs=(specs, state_specs, batch_spec),
                     out_specs=(specs, state_specs, P()))
    if offload != "none" and supports_host_offload():
        # host-resident opt state: stream it on-device for the update and
        # back after — the MoveToDevice/MoveToHost pair the offload
        # contract declares (memory_plan.OffloadPlan).  Transfers sit
        # OUTSIDE shard_map (each leaf keeps its partition spec, only the
        # memory space changes) so the choreography inside is untouched.
        def offload_step(shards, opt_state, batch):
            opt_dev = stream_tree(opt_state, DEVICE_KIND)
            shards, opt_dev, loss = sharded(shards, opt_dev, batch)
            return shards, stream_tree(opt_dev, HOST_KIND), loss

        return jax.jit(offload_step,
                       donate_argnums=(0, 1) if donate else ())
    return jax.jit(sharded, donate_argnums=(0, 1) if donate else ())


# -------------------------------------------------------------------- auto

def make_fsdp_auto_train_step(
    params_sharded,
    cfg: T.TransformerConfig,
    mesh: Mesh,
    axis: str = "dp",
    *,
    lr: float = 3e-4,
    b1: float = 0.9,
    b2: float = 0.95,
    eps: float = 1e-8,
    donate: bool = True,
):
    """Library-mode FSDP: jit + NamedSharding constraints, XLA inserts and
    schedules the collectives (its scheduler may prefetch gathers — this is
    the variant that can beat the explicit one, as torch FSDP2 is to the
    reference's hand-rolled zero3)."""
    specs = fsdp_specs(params_sharded, axis)
    check_divisibility(params_sharded, specs, mesh)
    pshard = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                          is_leaf=lambda x: isinstance(x, P))
    sshard = optim.AdamState(mu=pshard, nu=pshard,
                             count=NamedSharding(mesh, P()))
    bshard = NamedSharding(mesh, P(axis))  # spec-ok

    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(
            lambda p: T.lm_loss(p, batch, cfg))(params)
        params, opt_state = optim.adam_update(
            grads, opt_state, params, lr=lr, b1=b1, b2=b2, eps=eps)
        return params, opt_state, loss

    return jax.jit(
        step,
        in_shardings=(pshard, sshard, (bshard, bshard)),
        out_shardings=(pshard, sshard, NamedSharding(mesh, P())),
        donate_argnums=(0, 1) if donate else ())
