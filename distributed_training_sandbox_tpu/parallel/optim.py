"""Functional per-leaf optimizers with torch semantics.

The sharded-optimizer strategies (ZeRO-1/2/3) need to run Adam on *individual
params or shards* with state they manage themselves — exactly what the
reference does by pruning ``optimizer.param_groups`` (``zero/zero1.py:71-74``).
A plain functional Adam over arbitrary pytrees gives that; hyperparameter
defaults match torch.optim.Adam (lr 1e-3, betas (0.9, 0.999), eps 1e-8, with
bias correction) so A/B loss curves line up with the reference's toys.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class AdamState(NamedTuple):
    mu: any
    nu: any
    count: jax.Array


def adam_init(params) -> AdamState:
    zeros = lambda p: jnp.zeros_like(p)
    return AdamState(mu=jax.tree.map(zeros, params),
                     nu=jax.tree.map(zeros, params),
                     count=jnp.zeros((), jnp.int32))


def adam_update(grads, state: AdamState, params, *, lr=1e-3, b1=0.9, b2=0.999,
                eps=1e-8, lr_mults=None):
    """``lr_mults``: optional pytree of scalars matching ``params`` —
    per-leaf LR multipliers (e.g. a slow MoE router,
    ``TransformerConfig.moe_router_lr_mult``).  Grad scaling can NOT do
    this job: Adam divides by √nu, so a scaled gradient nearly cancels;
    only the step itself can be scaled."""
    count = state.count + 1
    mu = jax.tree.map(lambda m, g: b1 * m + (1 - b1) * g, state.mu, grads)
    nu = jax.tree.map(lambda v, g: b2 * v + (1 - b2) * g * g, state.nu, grads)
    c = count.astype(jnp.float32)
    bc1 = 1 - b1 ** c
    bc2 = 1 - b2 ** c

    def upd(p, m, v, s=1.0):
        # fp32 math, cast back: keeps bf16 params bf16 (a silent f32
        # promotion here changes the train-step's input types and forces
        # a retrace-and-fail on step 2).
        step = (lr * s) * (m.astype(jnp.float32) / bc1) / (
            jnp.sqrt(v.astype(jnp.float32) / bc2) + eps)
        return (p.astype(jnp.float32) - step).astype(p.dtype)

    if lr_mults is None:
        new_params = jax.tree.map(upd, params, mu, nu)
    else:
        new_params = jax.tree.map(upd, params, mu, nu, lr_mults)
    return new_params, AdamState(mu=mu, nu=nu, count=count)


def warmup_cosine_schedule(peak_lr: float, warmup_steps: int,
                           total_steps: int, min_ratio: float = 0.1):
    """``count -> lr``: linear warmup to ``peak_lr`` over ``warmup_steps``
    then cosine decay to ``min_ratio·peak_lr`` at ``total_steps``.

    The warmup exists for a measured reason: with Adam's second-moment
    estimate still cold, a full-size first step kicks the loss up before
    it recovers (the unremarked 12.2→18.5 step-2 spike in the r3
    ``precision_results`` logs).  ``count`` is the optimizer step counter
    (0 on the first update), may be traced."""

    def sched(count):
        c = count.astype(jnp.float32)
        warm = peak_lr * (c + 1.0) / max(warmup_steps, 1)
        span = max(total_steps - warmup_steps, 1)
        prog = jnp.clip((c - warmup_steps) / span, 0.0, 1.0)
        floor = min_ratio * peak_lr
        cos = floor + (peak_lr - floor) * 0.5 * (1 + jnp.cos(jnp.pi * prog))
        return jnp.where(c < warmup_steps, warm, cos)

    return sched


from functools import partial


@partial(jax.jit, donate_argnums=(0, 1, 2))
def adam_step_donated(grads, state: AdamState, params, lr):
    """``adam_update`` as ONE compiled program with grads/state/params
    donated: XLA aliases the outputs onto the input buffers, so the
    update runs in place instead of materializing a second copy of the
    whole optimizer state — the difference between fitting and OOM for
    a billion-param single-chip pipeline stage set (the eager tree.map
    path transiently holds old+new mu/nu/params simultaneously).
    ``lr`` is traced, so a warmup schedule doesn't recompile."""
    return adam_update(grads, state, params, lr=lr)


class SGDState(NamedTuple):
    momentum: any


def sgd_init(params, momentum: float = 0.0) -> SGDState:
    if momentum:
        return SGDState(momentum=jax.tree.map(jnp.zeros_like, params))
    return SGDState(momentum=None)


def sgd_update(grads, state: SGDState, params, *, lr=1e-3, momentum=0.0):
    if momentum and state.momentum is not None:
        buf = jax.tree.map(lambda b, g: momentum * b + g, state.momentum, grads)
        new_params = jax.tree.map(lambda p, b: p - lr * b, params, buf)
        return new_params, SGDState(momentum=buf)
    new_params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
    return new_params, state
