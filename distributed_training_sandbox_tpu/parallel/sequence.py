"""Sequence/context parallelism: FSDP × ring-attention training.

The capability the reference lacks outright (SURVEY.md §5.7: long context
is handled there only by seq-len sweeps to 8192) and the one that defines
the TPU build's scaling story past a single chip's HBM: shard the
*sequence* dimension of activations across a mesh axis and run exact
causal attention with K/V blocks circulating the ring
(``ops/ring_attention.py``).

Layout over a 2-D mesh ``("dp", "sp")``:

  * batch dim sharded on ``dp``; sequence dim sharded on ``sp``
  * params FSDP-sharded over ``dp`` (per-layer gather inside the remat
    scan — the explicit choreography of ``parallel/fsdp.py``) and
    replicated over ``sp``
  * forward: everything except attention is token-local (matmuls, norms,
    the streamed-vocab loss); attention is the ring
  * backward: the dp all_gathers transpose to psum_scatters (FSDP's
    reduce-scatter), the ring's ppermutes transpose to reverse-direction
    ppermutes, and the sp-replicated param grads need one explicit
    psum over ``sp``

RoPE positions and causal structure use each rank's global chunk offset
(``models/transformer.py:hidden_states`` applies ``axis_index(sp) · S``
when ``cfg.sp_axis`` is set).  The loss is a mean over local tokens;
chunks are equal-sized, so the all-axis mean of means equals the global
mean.

The actual step builder lives in ``fsdp.make_fsdp_train_step`` (one
choreography, optional ``sp_axis``) so the FSDP gather logic and its
knobs (reshard_after_forward, quantized_gather, loss_fn) exist once and
apply to the SP variant too; this module is the SP-facing surface.
"""

from __future__ import annotations

import dataclasses

from jax.sharding import Mesh

from ..models import transformer as T
from .fsdp import make_fsdp_train_step


def sp_config(cfg: T.TransformerConfig, sp_axis: str = "sp"
              ) -> T.TransformerConfig:
    """The config switched to ring attention over ``sp_axis``."""
    return dataclasses.replace(cfg, attention_impl="ring", sp_axis=sp_axis)


def make_sp_train_step(
    params_sharded,
    cfg: T.TransformerConfig,
    mesh: Mesh,
    *,
    dp_axis: str = "dp",
    sp_axis: str = "sp",
    **kwargs,
):
    """Jitted FSDP×SP step:
    ``(param_shards, opt_state, batch) -> (param_shards, opt_state, loss)``
    with ``batch`` = (input_ids, labels), both (B, S_global), sharded
    P(dp, sp).  ``params_sharded`` is the dp-FSDP-sharded tree
    (``fsdp.shard_params_fsdp`` — sp sees replicas).  Accepts every
    ``make_fsdp_train_step`` knob (reshard_after_forward, lr, donate, …).
    """
    return make_fsdp_train_step(params_sharded, cfg, mesh, axis=dp_axis,
                                sp_axis=sp_axis, **kwargs)
