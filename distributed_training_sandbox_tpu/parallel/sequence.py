"""Sequence/context parallelism: FSDP × ring-attention training.

The capability the reference lacks outright (SURVEY.md §5.7: long context
is handled there only by seq-len sweeps to 8192) and the one that defines
the TPU build's scaling story past a single chip's HBM: shard the
*sequence* dimension of activations across a mesh axis and run exact
causal attention with K/V blocks circulating the ring
(``ops/ring_attention.py``).

Layout over a 2-D mesh ``("dp", "sp")``:

  * batch dim sharded on ``dp``; sequence dim sharded on ``sp``
  * params FSDP-sharded over ``dp`` (per-layer gather inside the remat
    scan — the explicit choreography of ``parallel/fsdp.py``) and
    replicated over ``sp``
  * forward: everything except attention is token-local (matmuls, norms,
    the streamed-vocab loss); attention is the ring
  * backward: the dp all_gathers transpose to psum_scatters (FSDP's
    reduce-scatter), the ring's ppermutes transpose to reverse-direction
    ppermutes, and the sp-replicated param grads need one explicit
    psum over ``sp``

RoPE positions and causal structure use each rank's global chunk offset
(``models/transformer.py:hidden_states`` applies ``axis_index(sp) · S``
when ``cfg.sp_axis`` is set).  The loss is a mean over local tokens;
chunks are equal-sized, so the all-axis mean of means equals the global
mean.

The actual step builder lives in ``fsdp.make_fsdp_train_step`` (one
choreography, optional ``sp_axis``) so the FSDP gather logic and its
knobs (reshard_after_forward, quantized_gather, loss_fn) exist once and
apply to the SP variant too; this module is the SP-facing surface.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ..models import transformer as T
from .fsdp import make_fsdp_train_step


def sp_config(cfg: T.TransformerConfig, sp_axis: str = "sp",
              layout: str = "contiguous") -> T.TransformerConfig:
    """The config switched to ring attention over ``sp_axis``.
    ``layout="zigzag"`` selects the balanced striped layout (~half the
    ring's score FLOPs; see ``ops/ring_attention.py``) — feed batches
    through ``zigzag_shuffle`` then."""
    return dataclasses.replace(cfg, attention_impl="ring", sp_axis=sp_axis,
                               ring_layout=layout)


def _zigzag_perm(n_dev: int) -> np.ndarray:
    """Stripe order giving device r stripes (r, 2D−1−r) under contiguous
    equal sharding: [0, 2D−1, 1, 2D−2, ...]."""
    return np.array([s for r in range(n_dev)
                     for s in (r, 2 * n_dev - 1 - r)])


def zigzag_shuffle(x, n_dev: int, axis: int = 1):
    """Reorder a GLOBAL sequence dim into zigzag stripe order, so a plain
    contiguous P(sp) sharding lands stripes (r, 2D−1−r) on device r.
    Apply to input_ids and labels identically — token-mean losses are
    permutation-invariant, so training semantics are unchanged."""
    S = x.shape[axis]
    if S % (2 * n_dev):
        raise ValueError(f"sequence length {S} must divide into "
                         f"2·{n_dev} zigzag stripes")
    w = S // (2 * n_dev)
    shape = x.shape
    stripes = x.reshape(*shape[:axis], 2 * n_dev, w, *shape[axis + 1:])
    out = jnp.take(stripes, _zigzag_perm(n_dev), axis=axis)
    return out.reshape(shape)


def zigzag_unshuffle(x, n_dev: int, axis: int = 1):
    """Inverse of ``zigzag_shuffle`` (restore natural sequence order)."""
    S = x.shape[axis]
    if S % (2 * n_dev):
        raise ValueError(f"sequence length {S} must divide into "
                         f"2·{n_dev} zigzag stripes")
    w = S // (2 * n_dev)
    shape = x.shape
    stripes = x.reshape(*shape[:axis], 2 * n_dev, w, *shape[axis + 1:])
    out = jnp.take(stripes, np.argsort(_zigzag_perm(n_dev)), axis=axis)
    return out.reshape(shape)


def make_sp_train_step(
    params_sharded,
    cfg: T.TransformerConfig,
    mesh: Mesh,
    *,
    dp_axis: str = "dp",
    sp_axis: str = "sp",
    **kwargs,
):
    """Jitted FSDP×SP step:
    ``(param_shards, opt_state, batch) -> (param_shards, opt_state, loss)``
    with ``batch`` = (input_ids, labels), both (B, S_global), sharded
    P(dp, sp).  ``params_sharded`` is the dp-FSDP-sharded tree
    (``fsdp.shard_params_fsdp`` — sp sees replicas).  Accepts every
    ``make_fsdp_train_step`` knob (reshard_after_forward, lr, donate, …).
    """
    return make_fsdp_train_step(params_sharded, cfg, mesh, axis=dp_axis,
                                sp_axis=sp_axis, **kwargs)
