"""Sequence/context parallelism: FSDP × ring-attention training.

The capability the reference lacks outright (SURVEY.md §5.7: long context
is handled there only by seq-len sweeps to 8192) and the one that defines
the TPU build's scaling story past a single chip's HBM: shard the
*sequence* dimension of activations across a mesh axis and run exact
causal attention with K/V blocks circulating the ring
(``ops/ring_attention.py``).

Layout over a 2-D mesh ``("dp", "sp")``:

  * batch dim sharded on ``dp``; sequence dim sharded on ``sp``
  * params FSDP-sharded over ``dp`` (per-layer gather inside the remat
    scan — the explicit choreography of ``parallel/fsdp.py``) and
    replicated over ``sp``
  * forward: everything except attention is token-local (matmuls, norms,
    the streamed-vocab loss); attention is the ring
  * backward: the dp all_gathers transpose to psum_scatters (FSDP's
    reduce-scatter), the ring's ppermutes transpose to reverse-direction
    ppermutes, and the sp-replicated param grads need one explicit
    psum over ``sp``

RoPE positions and causal structure use each rank's global chunk offset
(``models/transformer.py:hidden_states`` applies ``axis_index(sp) · S``
when ``cfg.sp_axis`` is set).  The loss is a mean over local tokens;
chunks are equal-sized, so the all-axis mean of means equals the global
mean.
"""

from __future__ import annotations

import dataclasses

import jax
from jax.sharding import Mesh, PartitionSpec as P

from ..models import transformer as T
from ..ops import collectives as C
from ..utils.profiling import scope
from . import optim
from .fsdp import (check_divisibility, fsdp_specs, _gather_leaf, _spec_map)


def sp_config(cfg: T.TransformerConfig, sp_axis: str = "sp"
              ) -> T.TransformerConfig:
    """The config switched to ring attention over ``sp_axis``."""
    return dataclasses.replace(cfg, attention_impl="ring", sp_axis=sp_axis)


def make_sp_train_step(
    params_sharded,
    cfg: T.TransformerConfig,
    mesh: Mesh,
    *,
    dp_axis: str = "dp",
    sp_axis: str = "sp",
    lr: float = 3e-4,
    b1: float = 0.9,
    b2: float = 0.95,
    eps: float = 1e-8,
    donate: bool = True,
):
    """Jitted FSDP×SP step:
    ``(param_shards, opt_state, batch) -> (param_shards, opt_state, loss)``
    with ``batch`` = (input_ids, labels), both (B, S_global), sharded
    P(dp, sp).  ``params_sharded`` is the dp-FSDP-sharded tree
    (``fsdp.shard_params_fsdp`` — sp sees replicas).
    """
    cfg = sp_config(cfg, sp_axis)
    ws_dp = int(mesh.shape[dp_axis])
    specs = fsdp_specs(params_sharded, dp_axis)
    check_divisibility(params_sharded, specs, mesh)
    layer_specs = specs["layers"]
    hook_specs = jax.tree.map(lambda s: P(*s[1:]), layer_specs,
                              is_leaf=lambda x: isinstance(x, P))

    def layer_hook(layer):
        with scope("fsdp_layer_gather"):
            return _spec_map(lambda x, s: _gather_leaf(x, s, dp_axis),
                             layer, hook_specs)

    def step(shards, opt_state, batch):
        def sharded_loss(shards, batch):
            with scope("fsdp_root_gather"):
                outer = {k: _gather_leaf(v, specs[k], dp_axis)
                         for k, v in shards.items() if k != "layers"}
            params = {**outer, "layers": shards["layers"]}
            return T.lm_loss(params, batch, cfg, layer_hook=layer_hook)

        with scope("forward_backward"):
            loss, grad_shards = jax.value_and_grad(sharded_loss)(
                shards, batch)
        with scope("loss_mean"):
            loss = C.all_reduce(C.all_reduce(loss, dp_axis, mean=True),
                                sp_axis, mean=True)
        with scope("grad_sync"):
            # dp: the gather transposes already psum_scattered; finish the
            # mean.  sp: params are replicated, so the shard grads need an
            # explicit mean-psum across the ring.
            grad_shards = jax.tree.map(
                lambda g: C.all_reduce(g, sp_axis, mean=True) / ws_dp,
                grad_shards)
        with scope("opt_step"):
            shards, opt_state = optim.adam_update(
                grad_shards, opt_state, shards,
                lr=lr, b1=b1, b2=b2, eps=eps)
        return shards, opt_state, loss

    state_specs = optim.AdamState(mu=specs, nu=specs, count=P())
    sharded = C.smap(step, mesh,
                     in_specs=(specs, state_specs, P(dp_axis, sp_axis)),
                     out_specs=(specs, state_specs, P()))
    return jax.jit(sharded, donate_argnums=(0, 1) if donate else ())
