"""Tensor parallelism: Megatron-style sharded transformer layers.

The reference's course outline names TP ("Week 4: Tensor Parallelism from
scratch") but never implements it (SURVEY.md §2.2: ABSENT) — on TPU it is
a natural named-mesh-axis extension and the second axis of this build's
2-D/3-D scaling story (dp × tp, dp × sp).

Layout over the ``tp`` axis (the classic column-then-row pairing):

  * attention: wq/wk/wv shard their OUTPUT dim — each device owns
    ``num_heads / tp`` query heads (and the matching share of KV heads;
    GQA group structure is preserved because nq and nkv divide evenly);
    attention itself is embarrassingly parallel over heads; wo shards its
    INPUT dim, so each device's contribution is a partial sum → one
    ``psum`` rejoins the residual stream.
  * MLP: w_gate/w_up shard the intermediate dim (column), w_down shards
    its input dim (row) → one ``psum``.
  * norms, embedding, unembedding: replicated (their grads are mean-psum'd
    across ``tp`` at step time).

Two psums per layer per direction — the canonical Megatron choreography,
visible and countable in the HLO like every other strategy here.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import transformer as T
from ..ops import collectives as C
from ..utils.profiling import scope
from . import optim


def check_tp_divisibility(cfg: T.TransformerConfig, tp: int) -> None:
    dims = [("num_attention_heads", cfg.num_attention_heads),
            ("num_key_value_heads", cfg.num_key_value_heads)]
    if cfg.n_experts and cfg.moe_ffn:
        dims.append(("moe_ffn", cfg.moe_ffn))
    else:   # dense MLP, or experts defaulting to intermediate_size
        dims.append(("intermediate_size", cfg.intermediate_size))
    bad = [(n, v) for n, v in dims if v % tp]
    if bad:
        raise ValueError(f"tp={tp} must divide " + ", ".join(
            f"{n}={v}" for n, v in bad))


def tp_specs(params, axis: str = "tp") -> dict:
    """PartitionSpec tree for Megatron sharding.  Dense stacked layer
    leaves are (L, in, out): column-parallel ones shard dim 2,
    row-parallel ones (wo, w_down) shard dim 1.  MoE expert leaves are
    (L, E, in, out): the SAME column/row roles one dim later — each
    expert's FFN is Megatron-split across the tp group (w_router, like
    every other dense leaf, replicated)."""
    row = {"wo", "w_down"}
    col = {"wq", "wk", "wv", "w_gate", "w_up"}

    def leaf_spec(path, leaf):
        name = next((getattr(k, "key", None) for k in reversed(path)
                     if getattr(k, "key", None)), None)
        if name in col:
            return (P(None, None, None, axis) if leaf.ndim == 4
                    else P(None, None, axis))
        if name in row:
            return (P(None, None, axis, None) if leaf.ndim == 4
                    else P(None, axis, None))
        return P()

    return jax.tree_util.tree_map_with_path(leaf_spec, params)


def shard_params_tp(params, mesh: Mesh, axis: str = "tp"):
    specs = tp_specs(params, axis)
    return jax.tree.map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
        params, specs, is_leaf=lambda x: isinstance(x, P))


def tp_lm_loss(params, batch, cfg: T.TransformerConfig, *,
               axis: str = "tp", overlap: str = "none") -> jax.Array:
    """Causal-LM loss with Megatron TP layers (shard_map only): the
    shared decoder body (``transformer._layer_body``) runs with
    ``tp_axis`` set — local head/intermediate shards, two psums per layer
    — via the ``layer_body`` seam, so the scaffold AND the layer math
    exist exactly once.  ``params`` hold LOCAL shards; embedding/norms/
    loss are replicated and identical on every tp rank.

    Composes with sequence parallelism: with ``cfg.sp_axis`` set (ring
    attention), each device holds its tp-share of heads AND its sp-chunk
    of the sequence — the KV ring circulates over ``sp_axis`` within
    each tp group, carrying only the local heads.

    ``overlap="ring"`` decomposes the two per-layer row-parallel rejoin
    psums into psum_scatter + ring all-gather (bitwise-identical — see
    ``ops.collectives.decomposed_all_reduce``)."""
    import functools
    return T.lm_loss(params, batch, cfg, layer_body=functools.partial(
        T._layer_body, tp_axis=axis, tp_overlap=overlap))


def make_tp_train_step(
    params_sharded,
    cfg: T.TransformerConfig,
    mesh: Mesh,
    *,
    dp_axis: str = "dp",
    tp_axis: str = "tp",
    sp_axis: str | None = None,
    overlap: str = "none",
    accum_steps: int = 1,
    lr: float = 3e-4,
    b1: float = 0.9,
    b2: float = 0.95,
    eps: float = 1e-8,
    donate: bool = True,
    loss_fn: Callable | None = None,
):
    """Jitted dp×tp step:
    ``(param_shards, opt_state, batch) -> (param_shards, opt_state, loss)``.
    Batch (input_ids, labels) sharded P(dp); params tp-sharded per
    ``tp_specs`` and replicated over dp (grads mean-psum'd over every
    axis each leaf is replicated on).

    ``sp_axis`` makes it the full 3-D dp×sp×tp step: the batch's
    sequence dim shards over ``sp_axis`` and attention becomes the KV
    ring over it (carrying only this device's tp-share of heads).

    ``overlap="ring"``: the per-layer row-parallel rejoin psums run
    decomposed (psum_scatter + ring all-gather) — bitwise-identical
    loss/grads, tp-1 schedulable hops per rejoin.  ``overlap="q8"``:
    the rejoin psums run as EQuARX two-shot quantized all-reduces
    (``ops.quant.quantized_all_reduce`` — int8 codes + scales on the
    wire, ~4x fewer bus bytes, per-contribution half-quantum error
    bound; grad psums stay full-precision).  Both apply to the default
    ``tp_lm_loss`` only (a custom ``loss_fn`` owns its own
    collectives).  ``accum_steps``: microbatched gradient accumulation
    over leading-dim batch splits (``fsdp.microbatch_value_and_grad``)."""
    ws_dp = int(mesh.shape[dp_axis])
    ws_tp = int(mesh.shape[tp_axis])
    check_tp_divisibility(cfg, ws_tp)
    if overlap not in ("none", "ring", "q8"):
        raise ValueError(f"overlap={overlap!r}; the tp step supports "
                         f"'none', 'ring' or 'q8'")
    if overlap != "none" and loss_fn is not None:
        raise ValueError(f"overlap={overlap!r} rewires tp_lm_loss's "
                         "rejoin psums; a custom loss_fn owns its own "
                         "collectives — rewire them there instead")
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    if sp_axis is None and cfg.sp_axis is not None:
        raise ValueError(
            f"cfg.sp_axis={cfg.sp_axis!r} (ring attention) but "
            f"make_tp_train_step got sp_axis=None — the batch would "
            f"replicate over {cfg.sp_axis!r} and sp grads would never "
            f"sync.  Pass sp_axis={cfg.sp_axis!r} (the step sets the "
            f"ring config itself).")
    n_total = ws_dp * ws_tp
    rep_axes = [dp_axis]
    if sp_axis is not None:
        cfg = dataclasses.replace(cfg, attention_impl="ring",
                                  sp_axis=sp_axis)
        n_total *= int(mesh.shape[sp_axis])
        rep_axes.append(sp_axis)
    # loss_fn contract: (params, batch, cfg) -> scalar, same as fsdp's;
    # a loss that declares an ``axis`` parameter (like tp_lm_loss) gets
    # the tp axis forwarded.
    if loss_fn is None:
        base_loss = lambda p, b, c: tp_lm_loss(p, b, c, axis=tp_axis,
                                               overlap=overlap)
    else:
        import inspect
        if "axis" in inspect.signature(loss_fn).parameters:
            base_loss = lambda p, b, c: loss_fn(p, b, c, axis=tp_axis)
        else:
            base_loss = loss_fn
    specs = tp_specs(params_sharded, tp_axis)

    def sync_grad(g, spec):
        # Sum the copies over every axis this leaf is replicated on (one
        # fused psum over the combined group), then normalize by total
        # device count: grads of the global-mean loss.
        axes = tuple(rep_axes) + ((tp_axis,) if tp_axis not in spec
                                  else ())
        return lax.psum(g, axes) / n_total

    def step(shards, opt_state, batch):
        with scope("forward_backward"):
            from .fsdp import microbatch_value_and_grad
            loss, grads = microbatch_value_and_grad(
                lambda p, b: base_loss(p, b, cfg), shards, batch,
                accum_steps)
        with scope("loss_mean"):
            # one fused mean over every axis (tp ranks hold identical
            # losses; including tp re-establishes replication for the
            # P() out_spec explicitly).
            loss = lax.pmean(loss, tuple(rep_axes + [tp_axis]))
        with scope("grad_sync"):
            grads = jax.tree.map(
                sync_grad, grads, specs,
                is_leaf=lambda x: isinstance(x, P))
        with scope("opt_step"):
            shards, opt_state = optim.adam_update(
                grads, opt_state, shards, lr=lr, b1=b1, b2=b2, eps=eps)
        return shards, opt_state, loss

    state_specs = optim.AdamState(mu=specs, nu=specs, count=P())
    batch_spec = P(dp_axis) if sp_axis is None else P(dp_axis, sp_axis)  # spec-ok
    sharded = C.smap(step, mesh,
                     in_specs=(specs, state_specs, batch_spec),
                     out_specs=(specs, state_specs, P()))
    return jax.jit(sharded, donate_argnums=(0, 1) if donate else ())
