from . import optim  # noqa: F401
from . import zero  # noqa: F401
from . import fsdp  # noqa: F401
from . import sequence  # noqa: F401
from . import tensor  # noqa: F401
from . import expert  # noqa: F401
from . import composable  # noqa: F401
from .composable import (  # noqa: F401
    ComposableBuild,
    MeshPlan,
    make_composable_train_step,
)
from .ddp import (  # noqa: F401
    sync_gradients,
    bucket_gradients,
    broadcast_params,
    params_sync_error,
    make_ddp_train_step,
    shard_range,
)
