"""Pipeline parallelism: GPipe and 1F1B schedules, host-driven.

Twin of reference ``pp/gpipe.py`` and ``pp/1f1b.py``: a layered toy MLP split
into contiguous stages placed on different devices *in one process*, a
host-side scheduler moving microbatch activations stage-to-stage, per-stage
optimizers.  The reference's cross-stage hop is a CUDA peer copy
(``gpipe.py:108``), not a collective — the twin here is an explicit
``jax.device_put`` between stage devices (D2D over ICI on a TPU slice);
the scheduler itself is pure host Python in both.

Mechanics mapping:
  * stage forward keeps the *input* microbatch (the reference keeps
    ``x.detach().requires_grad_(True)``, ``1f1b.py:112-123``); the backward
    re-runs the stage under ``jax.vjp`` on that stored input and applies the
    incoming output-cotangent — functionally identical to
    ``out.backward(gradient=grad_output)`` + relaying ``x.grad``
    (``1f1b.py:137-156``), with recompute instead of a stored autograd graph.
  * GPipe (`run_gpipe`): all forwards stage-by-stage draining deque queues
    (``gpipe.py:92-115``), then all backwards in reverse microbatch order
    (``:119-147``).
  * 1F1B (`run_1f1b`): clock scheduler, ``ticks = n_micro + n_stages - 1``
    (``1f1b.py:102``); per tick each stage does at most one forward and one
    backward; the last stage enqueues its backward immediately after its
    forward (``:130-131``), so peak stored activations ~n_stages instead of
    ~n_microbatches (``1f1b.py:4-11``).
  * last stage computes loss/n_micro (``gpipe.py:110-115``); gradients
    accumulate across microbatches; per-stage Adam steps afterwards
    (``gpipe.py:149-151``).

Known-bug note: the reference's GPipe backward leans on a loop-leaked
``out`` variable for device placement (``gpipe.py:126``, SURVEY.md §2.9.7);
here every transfer is explicit.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import asdict, dataclass, field
from functools import partial
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..models.mlp import mlp_apply, mlp_apply_stage
from ..utils.memory import device_memory_stats, MB
from . import optim


@partial(jax.jit, donate_argnums=(0, 1))
def _tree_add_donated(acc, gp):
    return jax.tree.map(jnp.add, acc, gp)


def split_stages(params: list, n_stages: int) -> list[list]:
    """Contiguous layer chunks, remainder to the earlier stages — the twin
    of slicing ``nn.Sequential`` into per-device chunks (``gpipe.py:38-47``,
    6 layers over 2 stages -> 3+3)."""
    n = len(params)
    base, rem = divmod(n, n_stages)
    out, start = [], 0
    for s in range(n_stages):
        size = base + (1 if s < rem else 0)
        out.append(params[start:start + size])
        start += size
    return out


class PipelineStage:
    """One stage: its params pinned to a device + jitted fwd / bwd / loss
    kernels.  ``apply_fn(stage_params, x)`` is the stage's forward."""

    def __init__(self, stage_params, device: jax.Device,
                 apply_fn: Callable = mlp_apply, is_last: bool = False,
                 loss_fn: Callable | None = None, has_aux: bool = False,
                 aux_weight: float = 0.0, opt8: bool = False):
        self.device = device
        self.params = jax.device_put(stage_params, device)
        self.is_last = is_last
        self.opt8 = opt8
        self.aux_weight = aux_weight if has_aux else 0.0
        # Uniform internal contract: the stage forward yields (out, aux)
        # where aux is this stage's additive side loss (the MoE
        # load-balance sum over its layers; constant 0 for dense stages).
        # The schedulers feed the aux cotangent (aux_weight / n_micro)
        # straight into each stage's vjp — the aux gradient is local to
        # the stage, so threading it across stages isn't needed; only the
        # scalar VALUES travel (for the reported loss).
        if has_aux:
            apply = apply_fn
        else:
            apply = lambda p, x: (apply_fn(p, x),  # noqa: E731
                                  jnp.zeros((), jnp.float32))
        loss2 = loss_fn or (lambda out, y: jnp.mean((out - y) ** 2))
        # a loss may also take the stage params (3-arg form) — how the
        # transformer's last stage reaches its unembedding for the
        # streamed-vocab loss.
        import inspect
        try:
            params_ = inspect.signature(loss2).parameters.values()
            required_pos = sum(
                1 for q in params_
                if q.kind in (q.POSITIONAL_ONLY, q.POSITIONAL_OR_KEYWORD)
                and q.default is q.empty)
        except (ValueError, TypeError):
            # builtins / some transformed callables have no inspectable
            # signature — default to the common 2-arg form.
            required_pos = 2
        if required_pos >= 3:
            loss = loss2
        else:
            loss = lambda out, y, p: loss2(out, y)  # noqa: E731

        aux_w = self.aux_weight

        def fwd(p, x):
            return apply(p, x)           # (out, aux)

        def bwd(p, x, gout, aux_ct):
            _, vjp = jax.vjp(apply, p, x)
            gp, gx = vjp((gout, aux_ct))
            return gp, gx

        def last_fwd_bwd(p, x, y, inv_n_micro):
            def scaled(p, x):
                out, aux = apply(p, x)
                return (loss(out, y, p) + aux_w * aux) * inv_n_micro
            # allow_int: a SINGLE-stage pipeline (monolithic diagnosis
            # runs) has first==last, so x is the int32 token ids — the
            # input cotangent is float0 and never relayed
            (l, (gp, gx)) = jax.value_and_grad(
                scaled, argnums=(0, 1), allow_int=True)(p, x)
            return l, gp, gx

        self.fwd = jax.jit(fwd)
        self.bwd = jax.jit(bwd)
        self.last_fwd_bwd = jax.jit(last_fwd_bwd)
        # accumulated grads + stored fwd inputs (microbatch queue)
        self.grad_acc = None
        if opt8:
            from . import optim8
            self.opt_state = optim8.adam8_init(self.params)
        else:
            self.opt_state = optim.adam_init(self.params)
        # high-water mark of concurrently stored activations — the
        # observable form of 1F1B's ~n_stages vs GPipe's ~n_micro peak
        # (1f1b.py:4-11) on substrates without allocator stats.
        self.max_stored = 0
        # example input/label shapes, captured by the schedulers for
        # memory_plan_mb's compile-time analysis
        self.input_sds = None
        self.label_sds = None

    def accumulate(self, gp):
        if self.grad_acc is None:
            self.grad_acc = gp
        else:
            # donated add: the accumulator is updated in place — the
            # eager tree.map holds acc + gp + result simultaneously,
            # which is the difference between fitting and OOM at
            # billion-param stages
            self.grad_acc = _tree_add_donated(self.grad_acc, gp)

    def step(self, lr: float = 1e-3):
        """Per-stage Adam step (``gpipe.py:57,149-151``).  Donated +
        jitted: grads, state and params buffers are reused in place —
        billion-param stage sets OOM otherwise (old and new state
        coexist across the eager tree.map)."""
        if self.grad_acc is None:
            return
        grads, self.grad_acc = self.grad_acc, None
        if self.opt8:
            from . import optim8
            self.params, self.opt_state = optim8.adam8_step_donated(
                grads, self.opt_state, self.params, jnp.float32(lr))
        else:
            self.params, self.opt_state = optim.adam_step_donated(
                grads, self.opt_state, self.params, jnp.float32(lr))

    def peak_memory_mb(self) -> float:
        return device_memory_stats(self.device)["peak_bytes_in_use"] / MB

    def memory_plan_mb(self) -> float:
        """Compile-time peak estimate for this stage's backward kernel
        (vjp = forward + backward in one program): arguments (params +
        stored activation) + XLA temp buffers.  The substrate-honest
        number on backends whose allocator exposes no runtime stats
        (``compiled.memory_analysis()``, as scripts/memory_waterline.py
        uses) — 0.0 when no microbatch has been seen yet."""
        if getattr(self, "input_sds", None) is None:
            return 0.0
        try:
            x = self.input_sds
            if self.is_last:
                c = self.last_fwd_bwd.lower(
                    self.params, x, self.label_sds,
                    jax.ShapeDtypeStruct((), jnp.float32)).compile()
            else:
                out, _aux = jax.eval_shape(self.fwd, self.params, x)
                c = self.bwd.lower(
                    self.params, x, out,
                    jax.ShapeDtypeStruct((), jnp.float32)).compile()
            ma = c.memory_analysis()
            return (ma.argument_size_in_bytes + ma.temp_size_in_bytes
                    + ma.output_size_in_bytes) / MB
        except Exception:
            return 0.0


def build_pipeline(params: list, n_stages: int,
                   devices: Sequence[jax.Device] | None = None,
                   apply_fn: Callable | None = None,
                   loss_fn: Callable | None = None) -> list[PipelineStage]:
    """Split a layered model over ``n_stages`` devices (device i holds stage
    i, cycling if fewer devices than stages — the reference requires
    n_gpus == n_stages, ``gpipe.py:17-20``).  The default apply keeps
    inter-stage ReLUs with their chunk (mlp_apply_stage); pass ``apply_fn``
    for custom layer stacks (it is used as-is for every stage)."""
    from functools import partial

    devs = list(devices if devices is not None else jax.local_devices())
    chunks = split_stages(params, n_stages)
    stages = []
    for s, chunk in enumerate(chunks):
        is_last = s == n_stages - 1
        apply = apply_fn or partial(mlp_apply_stage, last_stage=is_last)
        stages.append(PipelineStage(chunk, devs[s % len(devs)], apply,
                                    is_last=is_last, loss_fn=loss_fn))
    return stages


def build_transformer_pipeline(params: dict, cfg, n_stages: int,
                               devices: Sequence[jax.Device] | None = None,
                               opt8: bool = False) -> list[PipelineStage]:
    """Stage the real LM (``models.transformer``) over ``n_stages``
    devices — the extension past the reference's toy-MLP-only pipelines:
    stage 0 embeds and runs its layer slice, middle stages run layers,
    the last stage adds final norm + unembedding + the LM loss.

    Layer slices stay in stacked (L_s, ...) form, so each stage's forward
    is the same ``lax.scan`` over ``_layer_body`` the monolithic model
    uses (NoPE flags sliced per stage by GLOBAL layer index).

    Tied embeddings are untied here: with per-stage optimizers (the
    reference's design, ``gpipe.py:57``) the embedding would need a
    cross-stage grad sum every step to stay shared; instead the last
    stage gets its own unembedding initialized from ``embed`` (or the
    existing ``lm_head``) and the two train independently from then on.
    """
    import numpy as np

    from ..models import transformer as T

    if cfg.n_experts and cfg.ep_axis is not None:
        raise ValueError(
            "MoE×PP stages run one process per stage — experts must be "
            "stage-local (cfg.ep_axis=None); shard experts with the "
            "dp×ep step instead (parallel.expert.make_moe_lm_train_step)")
    L = cfg.num_hidden_layers
    if n_stages > L:
        raise ValueError(f"n_stages={n_stages} exceeds "
                         f"num_hidden_layers={L}")
    flags = np.asarray(T._rope_flags(cfg))
    layer_slices = split_stages(list(range(L)), n_stages)
    devs = list(devices if devices is not None else jax.local_devices())

    head = params.get("lm_head")
    if head is None:
        head = jnp.asarray(params["embed"]).T.copy()  # untie (see above)

    stages = []
    for s, idxs in enumerate(layer_slices):
        lo, hi = idxs[0], idxs[-1] + 1
        first, last = s == 0, s == n_stages - 1
        sp = {"layers": jax.tree.map(lambda v: v[lo:hi],
                                     params["layers"])}
        if first:
            sp["embed"] = params["embed"]
        if last:
            sp["final_norm"] = params["final_norm"]
            sp["lm_head"] = head
        stage_flags = jnp.asarray(flags[lo:hi])

        def apply(p, x, *, _first=first, _last=last,
                  _flags=stage_flags):
            if _first:
                x = p["embed"].astype(cfg.dtype)[x]
            B, S = x.shape[:2]
            cos, sin = T._rope_tables(S, cfg.resolved_head_dim,
                                      cfg.rope_theta)

            def body(carry, scanned):
                layer, use_rope = scanned
                h, aux = T._layer_body(carry, layer, cfg=cfg, cos=cos,
                                       sin=sin, use_rope=use_rope)
                return h, aux

            if cfg.remat:
                body = jax.checkpoint(
                    body, prevent_cse=False,
                    policy=T.resolve_remat_policy(cfg))
            x, auxs = jax.lax.scan(body, x, (p["layers"], _flags))
            if _last:
                x = T.rms_norm(x, p["final_norm"], cfg.rms_norm_eps)
            if cfg.n_experts:   # stage aux = its layers' balance losses
                return x, jnp.sum(auxs)
            return x

        def lm_xent(hidden, labels, p):
            # shared numerics with lm_loss (streamed vocab honored);
            # lm_head is (H, vocab), xent wants (vocab, H) rows.
            return T.xent_from_hidden(
                hidden, p["lm_head"].astype(cfg.dtype).T, labels,
                chunk=cfg.loss_vocab_chunk)

        stages.append(PipelineStage(
            sp, devs[s % len(devs)], apply, is_last=last,
            loss_fn=lm_xent if last else None,  # only last has lm_head
            has_aux=bool(cfg.n_experts),
            aux_weight=cfg.moe_aux_weight, opt8=opt8))
    return stages


def _microbatch(x, y, n_micro: int):
    if x.shape[0] % n_micro:
        raise ValueError(f"batch {x.shape[0]} not divisible by "
                         f"n_micro={n_micro}")
    return (jnp.split(x, n_micro), jnp.split(y, n_micro))


def _to_stage(x, stage: PipelineStage):
    """The cross-stage hop: explicit device transfer (``gpipe.py:106-109``,
    ``.to(cuda:i+1, non_blocking=True)``)."""
    return jax.device_put(x, stage.device)


def run_gpipe(stages: list[PipelineStage], x, y, n_micro: int = 4,
              lr: float = 1e-3) -> float:
    """One GPipe step: all forwards, then all backwards, then per-stage
    optimizer steps.  Returns the (already 1/n_micro-scaled, summed) batch
    loss, as the reference accumulates it (``gpipe.py:110-115``)."""
    n_stages = len(stages)
    xs, ys = _microbatch(x, y, n_micro)
    inv = jnp.float32(1.0 / n_micro)

    fwd_q: list[deque] = [deque() for _ in range(n_stages)]
    # stored (input, gout-cotangent placeholder) per stage per microbatch
    stored: list[list] = [[] for _ in range(n_stages)]
    for mb in range(n_micro):
        fwd_q[0].append(jnp.asarray(xs[mb]))

    # ---- all-forward phase, stage by stage (gpipe.py:92-115)
    acts_last: list = []
    aux_terms: list = []   # non-last stages' weighted aux losses (device)
    for s, stage in enumerate(stages):
        while fwd_q[s]:
            xin = _to_stage(fwd_q[s].popleft(), stage)
            stored[s].append(xin)
            stage.input_sds = jax.ShapeDtypeStruct(xin.shape, xin.dtype)
            stage.max_stored = max(stage.max_stored, len(stored[s]))
            if stage.is_last:
                acts_last.append(xin)
            else:
                out, aux = stage.fwd(stage.params, xin)
                fwd_q[s + 1].append(out)
                if stage.aux_weight:
                    aux_terms.append(stage.aux_weight * inv * aux)

    # ---- all-backward phase, reverse microbatch order (gpipe.py:119-147)
    # losses stay device scalars until the end: a float() per microbatch
    # would sync the host and serialize the cross-stage overlap
    mb_losses = []
    for mb in reversed(range(n_micro)):
        yd = _to_stage(ys[mb], stages[-1])
        stages[-1].label_sds = jax.ShapeDtypeStruct(yd.shape, yd.dtype)
        l, gp, gx = stages[-1].last_fwd_bwd(
            stages[-1].params, acts_last[mb], yd, inv)
        stages[-1].accumulate(gp)
        mb_losses.append(l)
        g = gx
        for s in range(n_stages - 2, -1, -1):
            stage = stages[s]
            g = _to_stage(g, stage)
            gp, g = stage.bwd(stage.params, stored[s][mb], g,
                              jnp.float32(stage.aux_weight) * inv)
            stage.accumulate(gp)

    for stage in stages:
        stage.step(lr)
    loss = float(jnp.sum(jnp.stack(mb_losses)))
    # earlier stages' weighted aux (the last stage's is inside l); the
    # terms live on DIFFERENT stage devices, so sum on host, not stacked
    loss += sum(float(a) for a in aux_terms)
    return loss


def run_1f1b(stages: list[PipelineStage], x, y, n_micro: int = 4,
             lr: float = 1e-3, schedule_trace: list | None = None) -> float:
    """One 1F1B step: clock scheduler, exactly ``ticks = n_micro + n_stages
    - 1`` iterations (``1f1b.py:102-107``), no early exit.  Each tick, each
    stage (ascending order) does at most one forward and one backward.

    Tick-level semantics pinned to the reference (``1f1b.py:107-158``):
    stages iterate in ascending order and queues are NOT snapshotted at
    tick start, so a forward output enqueued for stage s+1 is consumed in
    the SAME tick — a microbatch traverses the whole forward pipeline in
    one tick, while backward gradients (relayed to a lower, already-visited
    stage) advance one stage per tick.  That skew is why exactly
    ``n_micro + n_stages - 1`` ticks drain the pipeline: stage 0 launches
    mb k at tick k, mb k's backward reaches stage 0 at tick
    k + n_stages - 1.  Activations are freed as backwards consume them, so
    peak stored microbatch inputs per stage ~n_stages (``1f1b.py:4-11``).

    ``schedule_trace``: optional list collecting ``(tick, stage, op, mb)``
    events for tick-parity tests — the in-memory form of what the
    reference's profiler trace would show.
    """
    n_stages = len(stages)
    xs, ys = _microbatch(x, y, n_micro)
    inv = jnp.float32(1.0 / n_micro)

    fwd_q: list[deque] = [deque() for _ in range(n_stages)]
    bwd_q: list[deque] = [deque() for _ in range(n_stages)]
    for mb in range(n_micro):
        fwd_q[0].append((mb, jnp.asarray(xs[mb])))
    stored: list[dict] = [dict() for _ in range(n_stages)]

    mb_losses = []
    aux_terms: list = []
    ticks = n_micro + n_stages - 1
    for tick in range(ticks):
        for s, stage in enumerate(stages):
            # one forward per tick per stage (1f1b.py:112-131)
            if fwd_q[s]:
                mb, xin = fwd_q[s].popleft()
                xin = _to_stage(xin, stage)
                stored[s][mb] = xin
                stage.input_sds = jax.ShapeDtypeStruct(xin.shape,
                                                       xin.dtype)
                stage.max_stored = max(stage.max_stored, len(stored[s]))
                if stage.is_last:
                    # last stage backs-prop immediately (1f1b.py:130-131)
                    bwd_q[s].append((mb, None))
                else:
                    out, aux = stage.fwd(stage.params, xin)
                    fwd_q[s + 1].append((mb, out))
                    if stage.aux_weight:
                        aux_terms.append(stage.aux_weight * inv * aux)
                if schedule_trace is not None:
                    schedule_trace.append((tick, s, "fwd", mb))
            # one backward per tick per stage (1f1b.py:134-158)
            if bwd_q[s]:
                mb, gout = bwd_q[s].popleft()
                xin = stored[s].pop(mb)  # free the activation
                if stage.is_last:
                    yd = _to_stage(ys[mb], stage)
                    stage.label_sds = jax.ShapeDtypeStruct(yd.shape,
                                                           yd.dtype)
                    l, gp, gx = stage.last_fwd_bwd(stage.params, xin, yd, inv)
                    mb_losses.append(l)
                else:
                    gp, gx = stage.bwd(stage.params, xin,
                                       _to_stage(gout, stage),
                                       jnp.float32(stage.aux_weight) * inv)
                stage.accumulate(gp)
                if s > 0:
                    bwd_q[s - 1].append((mb, gx))
                if schedule_trace is not None:
                    schedule_trace.append((tick, s, "bwd", mb))

    leftover = sum(len(q) for q in fwd_q + bwd_q)
    assert leftover == 0, (
        f"1F1B clock did not drain in {ticks} ticks: {leftover} queued items")

    for stage in stages:
        stage.step(lr)
    loss = float(jnp.sum(jnp.stack(mb_losses)))
    # per-stage-device aux scalars: host sum (see run_gpipe note)
    loss += sum(float(a) for a in aux_terms)
    return loss


def run_interleaved_1f1b(stages: list[PipelineStage], x, y,
                         n_micro: int = 4, lr: float = 1e-3,
                         n_devices: int | None = None,
                         schedule_trace: list | None = None,
                         stats: dict | None = None) -> float:
    """One interleaved (virtual-stage) 1F1B step — the schedule the
    reference only NAMES in its variants-to-know list (``pp/1f1b.py:14-19``).

    ``stages`` holds ``D·V`` *virtual* stages round-robin over ``D``
    devices (virtual stage q lives on device ``q % D`` — exactly
    ``build_pipeline``'s cycling placement), each device owning V
    non-contiguous model chunks (Megatron's interleaving layout).  The
    clock is the PHYSICAL one the plain scheduler's pinned reference
    semantics don't model: per tick each DEVICE executes at most one
    forward and one backward among its resident chunks, and work
    enqueued this tick is visible only next tick (no same-tick cascade).
    Priorities per device: backward = oldest microbatch first (frees
    activations soonest); forward = deepest resident chunk first
    (depth-first — push in-flight microbatches toward the loss before
    admitting new ones).

    Why it helps: with V chunks per device the pipeline ramp fills a
    device after ~``(D-1)/V`` of a microbatch-traversal instead of
    ``D-1`` — the bubble fraction falls by ~V (Megatron-LM's interleaved
    schedule).  ``V=1`` degrades to a physical plain 1F1B, which is the
    in-model baseline the bubble comparison tests pin against
    ``(S-1)/(M+S-1)`` theory.

    ``stats`` (optional dict) receives: ticks, bubble_fraction,
    per_device_busy, device_max_stored (peak concurrently-stored
    microbatch inputs summed over a device's resident chunks).
    Returns the scaled batch loss, numerically identical to
    ``run_gpipe``/``run_1f1b`` on the same stages (schedule changes
    order, not math).
    """
    n_virtual = len(stages)
    if n_devices is None:
        seen: list = []
        for s in stages:
            if s.device not in seen:
                seen.append(s.device)
        n_devices = len(seen)
    D = n_devices
    if n_virtual % D:
        raise ValueError(f"{n_virtual} virtual stages not divisible by "
                         f"{D} devices")
    for q, s in enumerate(stages):
        if s.device != stages[q % D].device:
            raise ValueError(
                f"virtual stage {q} on {s.device} breaks the round-robin "
                f"layout (expected device of stage {q % D})")
    V = n_virtual // D
    xs, ys = _microbatch(x, y, n_micro)
    inv = jnp.float32(1.0 / n_micro)

    fwd_q: list[deque] = [deque() for _ in range(n_virtual)]
    bwd_q: list[deque] = [deque() for _ in range(n_virtual)]
    stored: list[dict] = [dict() for _ in range(n_virtual)]
    for mb in range(n_micro):
        fwd_q[0].append((mb, jnp.asarray(xs[mb])))

    mb_losses, aux_terms = [], []
    per_dev_busy = [0] * D
    dev_max_stored = [0] * D
    tick = 0
    tick_limit = 4 * (n_micro + D) * V + 64   # generous drain bound
    while any(fwd_q[q] or bwd_q[q] for q in range(n_virtual)):
        if tick >= tick_limit:
            raise AssertionError(
                f"interleaved clock failed to drain within {tick_limit} "
                f"ticks")
        pending = []   # (kind, q, item) applied at tick end — snapshot
        for d in range(D):
            resident = range(d, n_virtual, D)
            busy = False
            # ---- one backward: oldest microbatch first
            cands = [(bwd_q[q][0][0], -q) for q in resident if bwd_q[q]]
            if cands:
                mb_min, negq = min(cands)
                q = -negq
                stage = stages[q]
                mb, gout = bwd_q[q].popleft()
                xin = stored[q].pop(mb)
                if stage.is_last:
                    yd = _to_stage(ys[mb], stage)
                    stage.label_sds = jax.ShapeDtypeStruct(yd.shape,
                                                           yd.dtype)
                    l, gp, gx = stage.last_fwd_bwd(stage.params, xin, yd,
                                                   inv)
                    mb_losses.append(l)
                else:
                    gp, gx = stage.bwd(stage.params, xin,
                                       _to_stage(gout, stage),
                                       jnp.float32(stage.aux_weight) * inv)
                stage.accumulate(gp)
                if q > 0:
                    pending.append((bwd_q, q - 1, (mb, gx)))
                if schedule_trace is not None:
                    schedule_trace.append((tick, d, q, "bwd", mb))
                busy = True
            # ---- one forward: deepest resident chunk first
            fcands = [q for q in resident if fwd_q[q]]
            if fcands:
                q = max(fcands)
                stage = stages[q]
                mb, xin = fwd_q[q].popleft()
                xin = _to_stage(xin, stage)
                stored[q][mb] = xin
                stage.input_sds = jax.ShapeDtypeStruct(xin.shape, xin.dtype)
                stage.max_stored = max(stage.max_stored, len(stored[q]))
                if stage.is_last:
                    pending.append((bwd_q, q, (mb, None)))
                else:
                    out, aux = stage.fwd(stage.params, xin)
                    pending.append((fwd_q, q + 1, (mb, out)))
                    if stage.aux_weight:
                        aux_terms.append(stage.aux_weight * inv * aux)
                if schedule_trace is not None:
                    schedule_trace.append((tick, d, q, "fwd", mb))
                busy = True
            per_dev_busy[d] += busy
            dev_max_stored[d] = max(
                dev_max_stored[d],
                sum(len(stored[q]) for q in resident))
        for queue, q, item in pending:
            queue[q].append(item)
        tick += 1

    for stage in stages:
        stage.step(lr)
    if stats is not None:
        stats.update(
            ticks=tick, n_devices=D, n_virtual=V * D, v=V,
            bubble_fraction=round(1.0 - sum(per_dev_busy) / (D * tick), 4),
            per_device_busy=list(per_dev_busy),
            device_max_stored=list(dev_max_stored))
    loss = float(jnp.sum(jnp.stack(mb_losses)))
    loss += sum(float(a) for a in aux_terms)
    return loss


@dataclass
class PipeResult:
    """JSON results schema twin of ``gpipe.py:205-218``, extended with
    the substrate-honest memory pair: runtime allocator peaks when the
    backend exposes them, plus ALWAYS the compile-time per-stage plan
    (args + XLA temps of the stage's backward program) and the stored-
    activation high-water mark — the observable GPipe-vs-1F1B story on
    backends whose allocator reports nothing."""
    schedule: str
    final_loss: float
    avg_loss: float
    total_time_s: float
    avg_epoch_time_s: float
    epochs_per_s: float
    n_stages: int = 0       # virtual-stage count for interleaved runs
    n_micro: int = 0
    # every-epoch loss curve — "the pipeline learns" must be visible in
    # the artifact, not inferred from final vs avg (r4 verdict weak #1)
    losses: list = field(default_factory=list)
    peak_memory_mb: dict = field(default_factory=dict)
    total_peak_memory_mb: float = 0.0
    # "allocator" when peak_memory_mb carries real runtime stats,
    # "compiled_plan" when the allocator reports nothing there and the
    # plan columns are the meaningful numbers.
    memory_source: str = "allocator"
    memory_plan_mb: dict = field(default_factory=dict)
    max_stored_activations: dict = field(default_factory=dict)
    activation_mb_per_microbatch: dict = field(default_factory=dict)
    # interleaved runs: ticks / bubble_fraction / device_max_stored from
    # the physical per-device clock (run_interleaved_1f1b's stats)
    schedule_stats: dict = field(default_factory=dict)

    def as_dict(self) -> dict:
        d = asdict(self)
        if self.memory_source == "compiled_plan":
            # the allocator reported nothing — the zeros are dead, drop
            # them rather than publish 0.0 next to the honest plan
            del d["peak_memory_mb"], d["total_peak_memory_mb"]
        return d


def train_pipeline(stages: list[PipelineStage], schedule: str,
                   make_batch: Callable[[int], tuple],
                   num_epochs: int, n_micro: int = 4,
                   lr: float | Callable[[int], float] = 1e-3,
                   log: Callable | None = None,
                   start_epoch: int = 0,
                   should_stop: Callable[[int], bool] | None = None
                   ) -> PipeResult:
    """Epoch loop + metrics, twin of the reference's ``__main__`` epoch loop
    and JSON dump (``1f1b.py:186-205``, ``gpipe.py:205-218``).

    ``lr`` may be a schedule ``epoch -> lr`` — large-vocab models need
    warmup here exactly as the flagship loop does (an lr=1e-3 cold Adam
    start on a 1B-param model spikes the loss for the whole short run;
    that, not a staging bug, was the r4 rising-loss artifact).

    ``start_epoch``/``should_stop`` are the resilience driver's resume/
    preemption hooks: epochs before ``start_epoch`` were replayed from a
    checkpoint (``make_batch``/``lr`` still see absolute epoch indices);
    ``should_stop(epoch)`` is polled before each epoch so a preemption
    notice exits the schedule between epochs, never mid-microbatch."""
    sched_stats: dict = {}
    if schedule == "interleaved":
        def run(stages, x, y, n_micro, lr):
            return run_interleaved_1f1b(stages, x, y, n_micro=n_micro,
                                        lr=lr, stats=sched_stats)
    else:
        run = {"gpipe": run_gpipe, "1f1b": run_1f1b}[schedule]
    lr_fn = lr if callable(lr) else (lambda _e: lr)
    losses = []
    t0 = time.perf_counter()
    for epoch in range(start_epoch, num_epochs):
        if should_stop is not None and should_stop(epoch):
            break
        x, y = make_batch(epoch)
        loss = run(stages, x, y, n_micro=n_micro, lr=lr_fn(epoch))
        losses.append(loss)
        if log:
            log(epoch, loss)
    total = time.perf_counter() - t0
    n_run = max(len(losses), 1)
    peaks = {f"device_{i}": s.peak_memory_mb() for i, s in enumerate(stages)}
    plan = {f"device_{i}": round(s.memory_plan_mb(), 1)
            for i, s in enumerate(stages)}
    act_mb = {
        f"device_{i}":
            round(int(np.prod(s.input_sds.shape))
                  * jnp.dtype(s.input_sds.dtype).itemsize / MB, 3)
            if s.input_sds is not None else 0.0
        for i, s in enumerate(stages)}
    return PipeResult(
        schedule=schedule,
        n_stages=len(stages),
        n_micro=n_micro,
        final_loss=losses[-1] if losses else float("nan"),
        avg_loss=sum(losses) / n_run if losses else float("nan"),
        losses=[round(float(l), 6) for l in losses],
        total_time_s=total,
        avg_epoch_time_s=total / n_run,
        epochs_per_s=n_run / total if total else 0.0,
        peak_memory_mb=peaks,
        total_peak_memory_mb=sum(peaks.values()),
        memory_source=("allocator" if any(peaks.values())
                       else "compiled_plan"),
        memory_plan_mb=plan,
        max_stored_activations={f"device_{i}": s.max_stored
                                for i, s in enumerate(stages)},
        activation_mb_per_microbatch=act_mb,
        schedule_stats=sched_stats,
    )
