"""Composable N-D mesh driver: one rule-driven train step per `MeshPlan`.

ROADMAP item 1, second half.  PR 17 made the partition rules
(``analysis.rules.RuleSet``) the declarative source of truth — placement,
generated contracts, drift lint — but execution still lived in one
hand-built vertical driver per strategy.  This module folds execution
onto the same rules:

  * :class:`MeshPlan` names the mesh — axis sizes over dp/fsdp/tp/sp —
    plus the weight-update-sharding degree W0–W3 of arXiv:2004.13336
    ("Automatic Cross-Replica Sharding of Weight Update Computation"),
    which collapses ddp and the three ZeRO stages into ONE config axis
    instead of four modules.  ``w_layout`` picks the W3 representation:
    ``"flat"`` = ZeRO-3 per-param owner chunks, ``"named"`` = FSDP named
    leaf dims (same memory law, different wire choreography).
  * :func:`make_composable_train_step` executes any supported plan.
    Legacy-shaped plans (1-D data parallel at any W degree, dp×tp,
    dp×sp, fsdp) dispatch to the existing hand factories with identical
    hyperparameters — the parity law holds BITWISE, loss-for-loss,
    because it is the same compiled program.  Genuinely new shapes
    (dp×fsdp×tp) run the rule-driven 3-axis step below, whose
    param/opt/batch shardings come from the strategy's ``RuleSet`` and
    whose ``CollectiveContract`` is *generated* by
    ``analysis.contract_gen`` — nothing hand-registered.

The 3-axis dp×fsdp×tp choreography (``_make_dp_fsdp_tp_step``):
FSDP gathers over ``fsdp`` around each scanned layer (backward
re-gathers via remat; grads arrive pre-summed over fsdp through the
all_gather's psum_scatter transpose), Megatron tp math inside the layer
body (two rejoin psums per layer over ``tp``), batch sharded jointly
over ``(dp, fsdp)``, and one fused grad psum over the axes each leaf is
replicated on, normalized by the total device count — the same
transpose algebra the 1-D/2-D steps pin in isolation, composed.
"""

from __future__ import annotations

import dataclasses
import functools
import re
from typing import Any, Callable

import jax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import transformer as T
from ..ops import collectives as C
from ..utils.profiling import scope
from . import fsdp, optim, sequence, tensor, zero
from .ddp import make_ddp_train_step

MESH_PLAN_AXES = ("dp", "fsdp", "tp", "sp")
W_LAYOUTS = ("flat", "named")

_PLAN_TOKEN = re.compile(r"^(dp|fsdp|tp|sp)(\d+)$|^w([0-3])(flat|named)?$")


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    """Named mesh-axis sizes + the weight-update-sharding degree.

    Grammar (``MeshPlan.parse``): ``x``- or ``,``-separated tokens,
    each ``<axis><size>`` or ``w<degree>[flat|named]``, e.g.
    ``"dp8xw1"`` (ZeRO-1), ``"dp2xfsdp2xtp2"`` (the 3-axis combo),
    ``"dp8xw3named"`` (FSDP).  Omitted axes default to 1; omitted W
    degree to 0 (replicated update = ddp).

    The W degree applies to the ``dp`` axis (that is what
    arXiv:2004.13336 shards the weight update over); a ``fsdp`` axis of
    size > 1 is *named-dim W3 over its own axis* and therefore requires
    ``w == 0`` on dp — the two compose as separate mesh axes, not as one
    doubly-sharded axis.
    """
    dp: int = 1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    w: int = 0
    w_layout: str = "flat"

    def __post_init__(self):
        for name in MESH_PLAN_AXES:
            size = getattr(self, name)
            if not isinstance(size, int) or size < 1:
                raise ValueError(f"MeshPlan.{name}={size!r}: axis sizes "
                                 f"must be integers >= 1")
        if self.w not in (0, 1, 2, 3):
            raise ValueError(f"MeshPlan.w={self.w!r}: the weight-update-"
                             f"sharding degree is W0..W3")
        if self.w_layout not in W_LAYOUTS:
            raise ValueError(f"MeshPlan.w_layout={self.w_layout!r}: "
                             f"choose from {W_LAYOUTS}")
        if self.w and self.fsdp > 1:
            raise ValueError(
                f"MeshPlan(dp={self.dp}, fsdp={self.fsdp}, w={self.w}): "
                f"an fsdp axis IS named-dim W3 over its own axis; a "
                f"nonzero W degree on dp does not compose with it")
        if self.w_layout == "named" and self.w not in (0, 3):
            raise ValueError(
                f"MeshPlan.w_layout='named' is the FSDP representation "
                f"of W3; it is meaningless at w={self.w} (zero{self.w} "
                f"state is flat owner chunks by construction)")

    # ------------------------------------------------------------ grammar

    @classmethod
    def parse(cls, text: str) -> "MeshPlan":
        """``"dp2xfsdp2xtp2"`` / ``"dp8,w1"`` / ``"dp8xw3named"`` -> plan."""
        sizes = {}
        w, w_layout = 0, None
        for tok in re.split(r"[x,×]", text.strip().lower()):
            if not tok:
                continue
            m = _PLAN_TOKEN.match(tok)
            if not m:
                raise ValueError(
                    f"bad MeshPlan token {tok!r} in {text!r}; tokens are "
                    f"<axis><size> (axes {MESH_PLAN_AXES}) or "
                    f"w<0-3>[flat|named]")
            if m.group(1):
                if m.group(1) in sizes:
                    raise ValueError(f"duplicate axis {m.group(1)!r} "
                                     f"in {text!r}")
                sizes[m.group(1)] = int(m.group(2))
            else:
                w = int(m.group(3))
                w_layout = m.group(4)
        return cls(w=w, w_layout=w_layout or "flat", **sizes)

    def describe(self) -> str:
        toks = [f"{a}{getattr(self, a)}" for a in MESH_PLAN_AXES
                if getattr(self, a) > 1] or ["dp1"]
        if self.w:
            toks.append(f"w{self.w}"
                        + ("named" if self.w == 3
                           and self.w_layout == "named" else ""))
        return "x".join(toks)

    # ----------------------------------------------------------- geometry

    @property
    def ways(self) -> int:
        """Total device count the plan spans."""
        return self.dp * self.fsdp * self.tp * self.sp

    def axis_sizes(self) -> dict:
        return {a: getattr(self, a) for a in MESH_PLAN_AXES}

    def mesh_axes(self) -> dict:
        """Axis-name -> size for ``make_mesh``: the size-1 axes are
        dropped (a trivial axis only renames specs), dp kept as the
        fallback so the mesh is never empty."""
        active = {a: getattr(self, a) for a in MESH_PLAN_AXES
                  if getattr(self, a) > 1}
        return active or {"dp": 1}

    # Memory-law factors for the analytic waterline
    # (``memory_plan.predictor.analytic_waterline``): how many ways the
    # params at rest / optimizer state / global batch divide.
    @property
    def param_shard_ways(self) -> int:
        return self.fsdp * self.tp * (self.dp if self.w >= 3 else 1)

    @property
    def opt_shard_ways(self) -> int:
        return self.fsdp * self.tp * (self.dp if self.w >= 1 else 1)

    @property
    def data_ways(self) -> int:
        """Ways the global batch dim divides (sp divides seq, not batch)."""
        return self.dp * self.fsdp

    # --------------------------------------------------------- resolution

    def normalized(self) -> "MeshPlan":
        """Canonical form: a pure ``fsdp`` axis with nothing else active
        IS legacy FSDP — named-dim W3 over an axis called ``dp`` — so it
        renames to keep the legacy mesh/contract/ruleset names."""
        if self.fsdp > 1 and self.dp == 1 and self.tp == 1 \
                and self.sp == 1 and self.w == 0:
            return MeshPlan(dp=self.fsdp, w=3, w_layout="named")
        return self

    def strategy_name(self) -> str:
        """The registered strategy (= RuleSet = contract) name this plan
        executes as.  Raises for unsupported axis combinations."""
        p = self.normalized()
        if p.fsdp > 1:
            if p.tp > 1 and p.sp == 1:
                return "composable_dp_fsdp_tp"
            raise ValueError(
                f"MeshPlan {self.describe()!r}: unsupported axis combo — "
                f"an fsdp axis currently composes with tp only "
                f"(dp×fsdp×tp); dp×fsdp alone or ×sp is future work")
        if p.tp > 1:
            if p.sp > 1:
                raise ValueError(
                    f"MeshPlan {self.describe()!r}: dp×tp×sp runs through "
                    f"the hand tp driver (make_tp_train_step sp_axis=); "
                    f"it is not yet folded into the composable surface")
            if p.w:
                raise ValueError(f"MeshPlan {self.describe()!r}: W>0 on "
                                 f"dp does not compose with tp yet")
            return "tp"
        if p.sp > 1:
            if p.w not in (0, 3):
                raise ValueError(f"MeshPlan {self.describe()!r}: sp rides "
                                 f"fsdp-over-dp (W3 named); w={p.w} does "
                                 f"not apply")
            return "sp"
        # 1-D data parallel: the W degree picks the strategy.
        if p.w == 0:
            return "ddp"
        if p.w == 1:
            return "composable_zero1"
        if p.w == 2:
            return "zero2"
        return "fsdp" if p.w_layout == "named" else "zero3"

    def validate(self, n_devices: int | None = None,
                 model_cfg: T.TransformerConfig | None = None,
                 seq_len: int | None = None) -> None:
        """Feasibility rules (the tuner prunes on the same three):
        axis product == device count, tp divides the head counts,
        sp divides the sequence length."""
        if n_devices is not None and self.ways != n_devices:
            raise ValueError(
                f"MeshPlan {self.describe()!r} spans {self.ways} devices; "
                f"{n_devices} available (axis product must match exactly)")
        if model_cfg is not None and self.tp > 1:
            tensor.check_tp_divisibility(model_cfg, self.tp)
        if seq_len is not None and self.sp > 1 and seq_len % self.sp:
            raise ValueError(f"MeshPlan sp={self.sp} must divide the "
                             f"sequence length {seq_len}")


def plan_feasible(dp: int, fsdp: int, tp: int, sp: int, *,
                  n_devices: int, n_heads: int | None = None,
                  n_kv_heads: int | None = None,
                  seq_len: int | None = None) -> bool:
    """Boolean twin of :meth:`MeshPlan.validate` over raw ints — the
    tuner's enumeration-time filter, importable without jax/model
    machinery (``tuner.knobs`` mirrors this logic; pinned together by
    tests/test_composable.py)."""
    if dp * fsdp * tp * sp != n_devices:
        return False
    if tp > 1:
        for heads in (n_heads, n_kv_heads):
            if heads is not None and heads % tp:
                return False
    if sp > 1 and seq_len is not None and seq_len % sp:
        return False
    return True


# -------------------------------------------------------------- the build

@dataclasses.dataclass
class ComposableBuild:
    """Everything a driver needs to run one plan: the jitted step, the
    placed initial state, the batch spec, and the contract/ruleset
    identity the telemetry verdicts key on."""
    plan: MeshPlan               # normalized
    strategy: str                # RuleSet / contract name
    mesh: Mesh
    step: Callable
    params: Any                  # placed as the step's in_spec expects
    opt_state: Any
    batch_spec: P
    contract_kwargs: dict = dataclasses.field(default_factory=dict)


def _spec_tree_axes(spec: P) -> set:
    out = set()
    for entry in spec:
        if entry is None:
            continue
        out.update((entry,) if isinstance(entry, str) else entry)
    return out


def _ruleset(strategy: str):
    from ..analysis import rules as R
    return R.RULESETS[strategy]


def _batch_spec_from_rules(strategy: str) -> P:
    """The strategy's batch placement straight from its RuleSet (every
    registered batch rule set here is a single catch-all rule)."""
    from ..analysis.rules import to_partition_spec
    rs = _ruleset(strategy)
    return to_partition_spec(rs.batch_rules[0].spec)


def shard_params_by_rules(params, mesh: Mesh, strategy: str,
                          role: str = "params"):
    """Place a (host/replicated) tree at its at-rest sharding as the
    strategy's partition rules declare it — the rule-driven twin of the
    per-family ``shard_params_*`` helpers."""
    specs = _ruleset(strategy).partition_specs(params, role)
    return jax.tree.map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
        params, specs, is_leaf=lambda x: isinstance(x, P))


def _mlp_chunk_loss(params, axis: str):
    """Auto-build the ZeRO-3 chunked loss for the toy-MLP tree (a list
    of ``{"w", "b"}`` layers — the `_zero_driver` model family)."""
    if not (isinstance(params, (list, tuple)) and params
            and all(isinstance(layer, dict) and set(layer) == {"w", "b"}
                    for layer in params)):
        raise ValueError(
            "MeshPlan w=3 w_layout='flat' (zero3) auto-builds its chunked "
            "loss for the toy-MLP tree only (list of {'w','b'} layers); "
            "pass a transformer plan w_layout='named' instead, or use "
            "zero.make_zero3_train_step directly with a custom chunk loss")
    shapes = [{k: v.shape for k, v in layer.items()} for layer in params]
    return zero.make_zero3_mlp_loss(shapes, axis)


def make_composable_train_step(
    params,
    plan: MeshPlan,
    mesh: Mesh,
    *,
    model_cfg: T.TransformerConfig | None = None,
    loss_fn: Callable | None = None,
    rebuild: str = "broadcast",
    overlap: str = "none",
    accum_steps: int = 1,
    donate: bool = True,
) -> ComposableBuild:
    """Resolve a :class:`MeshPlan` to one executable build.

    ``params`` enter replicated/host-side; the build places them at
    their at-rest sharding itself (flat chunks, named dims, tp shards —
    whatever the plan's rules say).  ``model_cfg`` is required for
    transformer-family plans (any of fsdp-named/tp/sp active);
    ``loss_fn`` is required for the replicated-param data-parallel
    family (ddp/zero1/zero2) and optional elsewhere.

    Legacy-shaped plans run the HAND step factories with their own
    default hyperparameters — bitwise-identical to the bespoke drivers
    by construction (pinned by tests/test_composable.py).  The dp×fsdp×tp
    combo runs the rule-driven 3-axis step (new code, new generated
    contract).
    """
    p = plan.normalized()
    strategy = p.strategy_name()
    # the mesh must realize the plan exactly (axis names AND sizes)
    want = (p.mesh_axes() if strategy != "composable_dp_fsdp_tp"
            else {a: getattr(p, a) for a in ("dp", "fsdp", "tp")})
    got = {k: int(v) for k, v in mesh.shape.items()}
    if got != {k: int(v) for k, v in want.items()}:
        raise ValueError(f"mesh axes {got} do not realize MeshPlan "
                         f"{p.describe()!r} (want {want})")
    batch_spec = _batch_spec_from_rules(strategy)

    if strategy == "composable_dp_fsdp_tp":
        if model_cfg is None:
            raise ValueError("dp×fsdp×tp is a transformer plan; pass "
                             "model_cfg")
        shards = shard_params_by_rules(params, mesh, strategy)
        step = _make_dp_fsdp_tp_step(
            shards, model_cfg, mesh, strategy=strategy, overlap=overlap,
            accum_steps=accum_steps, donate=donate, loss_fn=loss_fn)
        opt_state = fsdp.init_fsdp_opt_state(shards)
        return ComposableBuild(p, strategy, mesh, step, shards, opt_state,
                               batch_spec,
                               {"n_layers": model_cfg.num_hidden_layers})

    if strategy == "tp":
        if model_cfg is None:
            raise ValueError("a tp plan needs model_cfg")
        shards = tensor.shard_params_tp(params, mesh)
        step = tensor.make_tp_train_step(
            shards, model_cfg, mesh, overlap=overlap,
            accum_steps=accum_steps, donate=donate, loss_fn=loss_fn)
        opt_state = fsdp.init_fsdp_opt_state(shards)
        return ComposableBuild(p, strategy, mesh, step, shards, opt_state,
                               batch_spec,
                               {"n_layers": model_cfg.num_hidden_layers})

    if strategy == "sp":
        if model_cfg is None:
            raise ValueError("an sp plan needs model_cfg")
        shards = fsdp.shard_params_fsdp(params, mesh, "dp")
        step = sequence.make_sp_train_step(
            shards, model_cfg, mesh, accum_steps=accum_steps,
            donate=donate, loss_fn=loss_fn)
        opt_state = fsdp.init_fsdp_opt_state(shards)
        return ComposableBuild(p, strategy, mesh, step, shards, opt_state,
                               batch_spec,
                               {"n_layers": model_cfg.num_hidden_layers})

    if strategy == "fsdp":
        if model_cfg is None:
            raise ValueError("a w3-named (fsdp) plan needs model_cfg")
        shards = fsdp.shard_params_fsdp(params, mesh, "dp")
        step = fsdp.make_fsdp_train_step(
            shards, model_cfg, mesh, overlap=overlap,
            accum_steps=accum_steps, donate=donate, loss_fn=loss_fn)
        opt_state = fsdp.init_fsdp_opt_state(shards)
        return ComposableBuild(p, strategy, mesh, step, shards, opt_state,
                               batch_spec,
                               {"n_layers": model_cfg.num_hidden_layers})

    # -------- 1-D data-parallel family: the W degree is the strategy ----
    if strategy == "zero3":
        chunk_loss = _mlp_chunk_loss(params, "dp") if loss_fn is None \
            else loss_fn
        opt_state = zero.init_zero_opt_state(params, mesh, "dp")
        step = zero.make_zero3_train_step(chunk_loss, mesh, "dp",
                                          donate=donate)
        chunks = zero.shard_params_zero3(params, mesh, "dp")
        return ComposableBuild(p, strategy, mesh, step, chunks, opt_state,
                               batch_spec)

    if loss_fn is None:
        raise ValueError(f"a replicated-param data-parallel plan "
                         f"({strategy}) needs loss_fn")
    if strategy in ("composable_zero1", "zero2"):
        stage = 1 if strategy == "composable_zero1" else 2
        step = zero.make_zero_train_step(loss_fn, mesh, "dp", stage=stage,
                                         rebuild=rebuild, donate=donate)
        opt_state = zero.init_zero_opt_state(params, mesh, "dp")
        return ComposableBuild(p, strategy, mesh, step, params, opt_state,
                               batch_spec, {"rebuild": rebuild})

    assert strategy == "ddp", strategy
    step = make_ddp_train_step(
        loss_fn, lambda g, s, p_: optim.adam_update(g, s, p_), mesh, "dp",
        donate=donate)
    opt_state = optim.adam_init(params)
    return ComposableBuild(p, strategy, mesh, step, params, opt_state,
                           batch_spec)


# ------------------------------------------------- the new 3-axis step

def _make_dp_fsdp_tp_step(
    shards,
    cfg: T.TransformerConfig,
    mesh: Mesh,
    *,
    strategy: str = "composable_dp_fsdp_tp",
    dp_axis: str = "dp",
    fsdp_axis: str = "fsdp",
    tp_axis: str = "tp",
    overlap: str = "none",
    accum_steps: int = 1,
    lr: float = 3e-4,
    b1: float = 0.9,
    b2: float = 0.95,
    eps: float = 1e-8,
    donate: bool = True,
    loss_fn: Callable | None = None,
):
    """Jitted dp×fsdp×tp step:
    ``(param_shards, opt_state, batch) -> (param_shards, opt_state, loss)``.

    Placement comes from the strategy's RuleSet (column-parallel
    projections ``(L, in⊘fsdp, out⊘tp)``, row-parallel ``(L, in⊘tp,
    out⊘fsdp)``, everything else fsdp-sharded as in named-dim W3) and
    the choreography composes the pinned 1-D mechanisms:

      * per-layer fsdp all_gathers inside the remat scan (backward
        re-gathers; the gather transpose psum_scatters grads over fsdp),
      * Megatron tp layer math via the ``layer_body`` seam (two rejoin
        psums per layer over tp — each gathered projection is full on
        its fsdp dim, still a local tp shard),
      * batch sharded jointly over ``(dp, fsdp)`` — both axes carry
        data; the grad sync psums over dp (+ tp where a leaf is
        tp-replicated) and normalizes by dp·fsdp·tp, the fsdp sum
        having already arrived through the gather transpose.
    """
    tensor.check_tp_divisibility(cfg, int(mesh.shape[tp_axis]))
    if overlap not in ("none", "ring"):
        raise ValueError(f"overlap={overlap!r}: the 3-axis step composes "
                         f"'none' or 'ring' tp rejoins")
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    specs = _ruleset(strategy).partition_specs(shards, "params")
    fsdp.check_divisibility(shards, specs, mesh)
    layer_specs = specs["layers"]
    # inside the scan body each stacked leaf loses its layer dim
    hook_specs = jax.tree.map(lambda s: P(*s[1:]), layer_specs,  # spec-ok
                              is_leaf=lambda x: isinstance(x, P))
    ws_dp = int(mesh.shape[dp_axis])
    ws_fsdp = int(mesh.shape[fsdp_axis])
    ws_tp = int(mesh.shape[tp_axis])
    n_total = ws_dp * ws_fsdp * ws_tp

    base_loss = loss_fn or T.lm_loss
    layer_body = functools.partial(T._layer_body, tp_axis=tp_axis,
                                   tp_overlap=overlap)

    def layer_hook(layer):
        with scope("fsdp_layer_gather"):
            return jax.tree.map(
                lambda x, s: fsdp._gather_leaf(x, s, fsdp_axis),
                layer, hook_specs, is_leaf=lambda x: isinstance(x, P))

    def sharded_loss(shards_, batch):
        with scope("fsdp_root_gather"):
            outer = {k: fsdp._gather_leaf(v, specs[k], fsdp_axis)
                     for k, v in shards_.items() if k != "layers"}
        params = {**outer, "layers": shards_["layers"]}
        return base_loss(params, batch, cfg, layer_hook=layer_hook,
                         layer_body=layer_body)

    def sync_grad(g, spec):
        # fsdp contributions were summed by the gather transposes; psum
        # the dp replicas (+ tp for tp-replicated leaves — tp-sharded
        # leaves already carry the rejoin-psum transpose's ws_tp factor),
        # then normalize once by the full device count.
        axes = (dp_axis,) + ((tp_axis,)
                             if tp_axis not in _spec_tree_axes(spec)
                             else ())
        return lax.psum(g, axes) / n_total

    def step(shards_, opt_state, batch):
        with scope("forward_backward"):
            loss, grad_shards = fsdp.microbatch_value_and_grad(
                sharded_loss, shards_, batch, accum_steps)
        with scope("loss_mean"):
            loss = lax.pmean(loss, (dp_axis, fsdp_axis, tp_axis))
        with scope("grad_sync"):
            grad_shards = jax.tree.map(
                sync_grad, grad_shards, specs,
                is_leaf=lambda x: isinstance(x, P))
        with scope("opt_step"):
            shards_, opt_state = optim.adam_update(
                grad_shards, opt_state, shards_,
                lr=lr, b1=b1, b2=b2, eps=eps)
        return shards_, opt_state, loss

    state_specs = optim.AdamState(mu=specs, nu=specs, count=P())
    batch_spec = P((dp_axis, fsdp_axis))  # spec-ok
    sharded = C.smap(step, mesh,
                     in_specs=(specs, state_specs, batch_spec),
                     out_specs=(specs, state_specs, P()))
    return jax.jit(sharded, donate_argnums=(0, 1) if donate else ())
