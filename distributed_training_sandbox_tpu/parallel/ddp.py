"""Data parallelism from scratch — TPU twin of
``SimpleDistributedDataParallelism`` (reference ``DDP/ddp.py:30-56``).

Choreography parity with the reference:
  * init: every param broadcast from rank 0, then a cross-replica equality
    assertion (``DDP/ddp.py:34-41``) — here ``broadcast_params`` +
    ``params_sync_error`` (a psum'd divergence norm, the SPMD form of the
    same invariant, SURVEY.md §5.2);
  * per step: local forward/backward, then ``sync_gradients`` = one
    all_reduce **per param** followed by /world_size (``DDP/ddp.py:43-47``)
    — ``tree_all_reduce(mean=True)``, one psum per leaf in the HLO so trace
    counts match the reference's per-param NCCL kernels;
  * data: each rank takes a contiguous range of the dataset
    (``DDP/ddp.py:104-112``) — ``shard_range`` host-side, or hand the global
    batch to shard_map with in_spec P(axis) and let SPMD slice it.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..ops import collectives as C
from ..utils.profiling import scope


def broadcast_params(params, axis: str, root: int = 0):
    """Per-param broadcast from ``root`` (one collective per leaf)."""
    return jax.tree.map(lambda p: C.broadcast(p, axis, root), params)


def params_sync_error(params, axis: str) -> jax.Array:
    """Total squared divergence of params across the axis — 0.0 iff all
    replicas hold identical values (the DDP init assertion, SPMD form)."""
    def leaf_err(p):
        # compare against rank 0's value (a masked psum adds exact zeros, so
        # identical replicas give exactly 0.0 — a mean would not, since the
        # reduction's rounding differs from the local value)
        return jnp.sum((p - C.broadcast(p, axis, 0)) ** 2)
    errs = jax.tree.map(leaf_err, params)
    return C.all_reduce(
        jax.tree.reduce(jnp.add, errs, jnp.zeros(())), axis)


def sync_gradients(grads, axis: str):
    """Per-param all_reduce(SUM) then /ws (``DDP/ddp.py:43-47``)."""
    return C.tree_all_reduce(grads, axis, mean=True)


def bucket_gradients(grads, axis: str, bucket_mb: float, *,
                     mean: bool = True):
    """torch-DDP-style bucketed gradient sync: flatten the leaves of each
    dtype (in tree order) into one vector, split it into ``~bucket_mb``-MB
    flat chunks, all_reduce each chunk, and scatter the results back into
    the original tree.

    Versus the per-leaf :func:`sync_gradients` this trades n-leaves small
    all_reduces for ``ceil(bytes / bucket)`` large ones — the payload-
    shape knob EQuARX (arXiv:2506.17615) treats as first-class; the site
    count is pinned by the ``ddp_bucketed`` CollectiveContract
    (``analysis.contracts.ddp_bucket_count``).  Deterministic bucketing
    (exact-capacity splits of the concatenated vector, not greedy leaf
    packing) is what makes that count a closed formula over total param
    bytes and bucket size."""
    leaves, treedef = jax.tree.flatten(grads)
    cap_bytes = max(int(bucket_mb * 2 ** 20), 1)
    by_dtype: dict = {}
    for i, leaf in enumerate(leaves):
        by_dtype.setdefault(jnp.dtype(leaf.dtype), []).append(i)
    out = list(leaves)
    for dt, idxs in by_dtype.items():
        flat = jnp.concatenate([leaves[i].reshape(-1) for i in idxs])
        cap = max(cap_bytes // dt.itemsize, 1)
        chunks = [C.all_reduce(flat[s:s + cap], axis)
                  for s in range(0, flat.size, cap)]
        red = jnp.concatenate(chunks) if len(chunks) > 1 else chunks[0]
        if mean:
            red = red / C.axis_size(axis)
        off = 0
        for i in idxs:
            sz = leaves[i].size
            out[i] = red[off:off + sz].reshape(leaves[i].shape)
            off += sz
    return jax.tree.unflatten(treedef, out)


# int8 grad sync: default flat-bucket size when --bucket-mb is unset
# (the q8 path always buckets — per-bucket scales ARE the quantization
# granularity)
DEFAULT_Q8_BUCKET_MB = 25.0


def init_grad_residual(params, ws: int):
    """Error-feedback residual state for :func:`quantized_bucket_all_reduce`:
    one f32 zero tree PER RANK (each device's quantization error is its
    own), stacked on a leading dp dim so it rides the shard_map step as a
    P(dp)-sharded pytree next to the replicated opt state."""
    return jax.tree.map(
        lambda p: jnp.zeros((ws,) + tuple(p.shape), jnp.float32), params)


def quantized_bucket_all_reduce(grads, axis: str, bucket_mb: float, *,
                                residual=None, mean: bool = True):
    """int8 quantized gradient all-reduce (the EQuARX trade,
    arXiv:2506.17615), riding :func:`bucket_gradients`' deterministic
    flat buckets: per dtype the leaves flatten into exact-capacity
    ``bucket_mb``-MB chunks; each chunk is quantized to int8 with ONE
    per-bucket absmax scale, the (int8 codes, f32 scale) pairs are
    all_gathered — ¼ the bytes of the f32 payload, and a gather moves
    half of what an all-reduce does, so ~8× less bus traffic — then
    dequantized and summed in ascending rank order (deterministic).

    ``residual``: error-feedback state (per-device f32 tree, see
    :func:`init_grad_residual`): the bucket quantizes ``grad + residual``
    and the new residual is what quantization just dropped, so the error
    is re-applied next step instead of compounding (EF-SGD).  Returns
    ``(synced_grads, new_residual-or-None)``.

    Accuracy bound (pinned by tests/test_quant.py): per element the sync
    differs from the exact mean by at most ``mean_d(scale_d) / 2`` — one
    half-quantum of each rank's bucket scale, averaged."""
    leaves, treedef = jax.tree.flatten(grads)
    res_leaves = (jax.tree.leaves(residual) if residual is not None
                  else [None] * len(leaves))
    ws = C.axis_size(axis)
    cap_bytes = max(int(bucket_mb * 2 ** 20), 1)
    by_dtype: dict = {}
    for i, leaf in enumerate(leaves):
        by_dtype.setdefault(jnp.dtype(leaf.dtype), []).append(i)
    out = list(leaves)
    new_res = list(res_leaves)
    for dt, idxs in by_dtype.items():
        flat = jnp.concatenate([leaves[i].reshape(-1).astype(jnp.float32)
                                for i in idxs])
        if residual is not None:
            flat = flat + jnp.concatenate(
                [res_leaves[i].reshape(-1) for i in idxs])
        cap = max(cap_bytes // dt.itemsize, 1)
        red_chunks, err_chunks = [], []
        for s in range(0, flat.size, cap):
            c = flat[s:s + cap]
            amax = jnp.max(jnp.abs(c))
            scale = jnp.where(amax > 0, amax / 127.0, 1.0)
            q = jnp.clip(jnp.round(c / scale), -127, 127).astype(jnp.int8)
            qg = C.all_gather(q, axis, axis=0).reshape(ws, c.size)
            sg = C.all_gather(scale.reshape(1), axis, axis=0)  # (ws,)
            red = jnp.sum(qg.astype(jnp.float32) * sg[:, None], axis=0)
            if mean:
                red = red / ws
            red_chunks.append(red)
            if residual is not None:
                err_chunks.append(c - q.astype(jnp.float32) * scale)
        red = (jnp.concatenate(red_chunks) if len(red_chunks) > 1
               else red_chunks[0])
        err = (jnp.concatenate(err_chunks) if len(err_chunks) > 1
               else err_chunks[0]) if residual is not None else None
        off = 0
        for i in idxs:
            sz = leaves[i].size
            out[i] = red[off:off + sz].reshape(leaves[i].shape).astype(dt)
            if err is not None:
                new_res[i] = err[off:off + sz].reshape(leaves[i].shape)
            off += sz
    synced = jax.tree.unflatten(treedef, out)
    if residual is None:
        return synced, None
    return synced, jax.tree.unflatten(jax.tree.structure(residual), new_res)


def shard_range(n: int, ws: int, rank: int) -> range:
    """Contiguous per-rank dataset shard, remainder to the leading ranks —
    twin of ``DDP/ddp.py:104-112``."""
    base, rem = divmod(n, ws)
    start = rank * base + min(rank, rem)
    return range(start, start + base + (1 if rank < rem else 0))


def make_ddp_train_step(
    loss_fn: Callable,
    update_fn: Callable,
    mesh: Mesh,
    axis: str = "dp",
    *,
    with_barrier: bool = True,
    donate: bool = True,
    bucket_mb: float | None = None,
    quantize_grads: bool = False,
    error_feedback: bool = False,
):
    """Build the jitted DDP step: (params, opt_state, batch) ->
    (params, opt_state, loss).

    ``loss_fn(params, local_batch) -> scalar``; ``update_fn(grads, opt_state,
    params) -> (params, opt_state)`` (see parallel.optim).  The batch enters
    sharded on ``axis`` (global batch dim); params/opt state are replicated.
    ``with_barrier`` appends the 1-elem-psum step barrier the reference uses
    for trace isolation (``zero/zero1.py:184``, README.md:11-12).
    ``bucket_mb`` switches the per-param gradient all_reduce to
    :func:`bucket_gradients`' flat ~N MB buckets (the ``ddp_bucketed``
    choreography).

    ``quantize_grads`` switches the sync to the int8
    :func:`quantized_bucket_all_reduce` (the ``ddp_q8`` choreography) at
    ``bucket_mb`` (default :data:`DEFAULT_Q8_BUCKET_MB`) — ~8× less bus
    traffic, within one half-quantum of the exact mean per element.
    ``error_feedback`` additionally threads the EF residual through the
    opt state: the step then takes/returns
    ``(opt_state, residual)`` with ``residual`` built by
    :func:`init_grad_residual` (P(axis)-sharded leading rank dim).
    """
    q8_bucket = bucket_mb or DEFAULT_Q8_BUCKET_MB

    def step(params, opt_state, batch):
        residual = None
        if quantize_grads and error_feedback:
            opt_state, res_stacked = opt_state
            residual = jax.tree.map(lambda r: r[0], res_stacked)
        with scope("forward_backward"):
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        with scope("sync_grads"):
            if quantize_grads:
                grads, residual = quantized_bucket_all_reduce(
                    grads, axis, q8_bucket, residual=residual)
            elif bucket_mb:
                grads = bucket_gradients(grads, axis, bucket_mb)
            else:
                grads = sync_gradients(grads, axis)
            # the loss is reported averaged over the global batch, like the
            # reference's rank-0 print of its local loss post-allreduce-free
            loss = C.all_reduce(loss, axis, mean=True)
        with scope("opt_step"):
            params, opt_state = update_fn(grads, opt_state, params)
        if quantize_grads and error_feedback:
            opt_state = (opt_state,
                         jax.tree.map(lambda r: r[None], residual))
        if with_barrier:
            with scope("barrier"):
                loss = loss + 0.0 * C.barrier(axis)
        return params, opt_state, loss

    state_spec = ((P(), P(axis)) if quantize_grads and error_feedback  # spec-ok
                  else P())
    sharded_step = C.smap(
        step, mesh,
        in_specs=(P(), state_spec, P(axis)),  # spec-ok
        out_specs=(P(), state_spec, P()),
    )
    return jax.jit(sharded_step, donate_argnums=(0, 1) if donate else ())
