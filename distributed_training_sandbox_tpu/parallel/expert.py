"""Expert parallelism: switch-style MoE with ``all_to_all`` dispatch.

The reference records MoE/EP only as a learning note on how expert
parallelism folds into the mesh (``README.md:13-14`` — SURVEY.md §2.2:
absent as code).  On TPU it is the canonical use of ``lax.all_to_all``
(the collective the reference's course stops short of): experts shard
across the ``ep`` mesh axis, every device routes its tokens, and two
all_to_alls per layer move token buckets to their experts' devices and
back.

Mechanics (Switch Transformer, top-1, fixed capacity):

  * router: logits = x @ w_router, expert = argmax, gate = softmax prob
    of the chosen expert
  * capacity C per expert bucket; tokens overflowing their bucket are
    dropped (output 0 for them — the standard switch trade)
  * dispatch/combine are one-hot einsums over a (tokens, E, C) tensor —
    static shapes, MXU-friendly, the idiom XLA pipelines well
  * device d owns experts [d·E/ep, (d+1)·E/ep): the first all_to_all
    regroups buckets by owning device, the second returns them
  * aux load-balance loss: E · Σ_e fraction_e · mean_prob_e (Switch
    eq. 4), averaged over the ep group

Shapes are per-device inside ``shard_map``; expert weights live ONLY on
their owner (ep-sharded pytree), router weights are replicated.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import collectives as C
from ..utils.profiling import scope
from . import optim


class MoEParams(NamedTuple):
    """Per-device pytree: router replicated, experts ep-sharded dim 0."""
    w_router: jax.Array   # (H, E)
    w_gate: jax.Array     # (E_local, H, F)
    w_up: jax.Array       # (E_local, H, F)
    w_down: jax.Array     # (E_local, F, H)


def init_moe_params(key, *, hidden: int, ffn: int, n_experts: int,
                    dtype=jnp.float32) -> MoEParams:
    """Full (unsharded) init — shard with ``shard_moe_params``."""
    kr, kg, ku, kd = jax.random.split(key, 4)
    s_in = hidden ** -0.5
    s_ff = ffn ** -0.5
    return MoEParams(
        w_router=(jax.random.normal(kr, (hidden, n_experts), dtype) * s_in),
        w_gate=(jax.random.normal(kg, (n_experts, hidden, ffn), dtype)
                * s_in),
        w_up=(jax.random.normal(ku, (n_experts, hidden, ffn), dtype)
              * s_in),
        w_down=(jax.random.normal(kd, (n_experts, ffn, hidden), dtype)
                * s_ff))


def moe_specs(axis: str = "ep") -> MoEParams:
    return MoEParams(w_router=P(), w_gate=P(axis), w_up=P(axis),
                     w_down=P(axis))


def shard_moe_params(params: MoEParams, mesh: Mesh,
                     axis: str = "ep") -> MoEParams:
    return jax.tree.map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
        params, moe_specs(axis), is_leaf=lambda x: isinstance(x, P))


def _resolve_group(n_tokens: int, group_size: int) -> int:
    """Largest divisor of ``n_tokens`` that is <= ``group_size`` — the
    grouped dispatch must tile the local chunk exactly, so an awkward
    token count (sharded seq, odd batch) shrinks the group rather than
    raising; G=1 is the (valid, capacity≈cf/E-per-token) floor."""
    g = min(group_size, n_tokens)
    while n_tokens % g:
        g -= 1
    return g


def _grouped_caps(n_tokens: int, group_size: int, capacity_factor: float,
                  n_experts: int) -> tuple[int, int, int]:
    """(G, NG, capg) of the grouped dispatch — THE one place its group
    and per-group-capacity rule lives."""
    G = _resolve_group(n_tokens, group_size)
    capg = int(-(-G * capacity_factor // n_experts))
    return G, n_tokens // G, capg


def _group_slot_positions(eg: jax.Array, n_experts: int):
    """Per-(group, expert) bucket position of each token: ``onehot``
    (NG, G, E) int32 and ``pos`` (NG, G, E), -1 off the token's expert —
    shared by the dispatch and its drop-rate report."""
    onehot = jax.nn.one_hot(eg, n_experts, dtype=jnp.int32)
    pos = jnp.cumsum(onehot, axis=1) * onehot - 1
    return onehot, pos


def grouped_drop_fraction(expert: jax.Array, n_experts: int,
                          group_size: int, capacity_factor: float):
    """Fraction of (token, assignment) pairs the grouped dispatch would
    drop — computed with the SAME helpers as ``moe_mlp``'s "grouped"
    branch, so reports (scripts/moe_bench.py) cannot drift from the
    timed path's semantics.  ``expert``: (N,) top-1 assignments or
    (N, k) top-k (choice-major priority, capacity cf·k·G/E — exactly the
    dispatch's rule)."""
    if expert.ndim == 1:
        expert = expert[:, None]
    N, k = expert.shape
    G, NG, capg = _grouped_caps(N, group_size, capacity_factor * k,
                                n_experts)
    eg = expert.reshape(NG, G, k).transpose(0, 2, 1).reshape(NG, k * G)
    _, pos = _group_slot_positions(eg, n_experts)
    return jnp.mean((jnp.max(pos, axis=-1) >= capg).astype(jnp.float32))


def _route_topk(x2d, w_router, k: int):
    """(N, H) tokens → (gates (N, k), experts (N, k), probs (N, E)).
    k = 1 keeps the Switch convention (gate = raw top prob); k ≥ 2
    normalizes the gates over the chosen experts (GShard top-2)."""
    logits = (x2d @ w_router).astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    gates, experts = lax.top_k(probs, k)
    if k > 1:
        gates = gates / jnp.sum(gates, axis=-1, keepdims=True)
    return gates, experts, probs


def router_z_loss(x2d, w_router):
    """ST-MoE router z-loss: mean over tokens of logsumexp(logits)² —
    pulls router logits toward zero so the softmax stays in its
    responsive range (a collapsed router rides saturated logits where
    the balance aux gradient vanishes).  Recomputes the (N, E) router
    matmul — negligible next to the expert MLPs — so callers need no
    logits plumbing."""
    logits = (x2d @ w_router).astype(jnp.float32)
    return jnp.mean(jax.nn.logsumexp(logits, axis=-1) ** 2)


def moe_mlp(x, w_router, w_gate, w_up, w_down, *, axis: str | None = "ep",
            capacity_factor: float = 2.0, dispatch: str = "grouped",
            group_size: int = 128, top_k: int = 1,
            matmul_precision: str = "bf16", router_z_ratio: float = 0.0):
    """The switch-MoE MLP on local tokens ``x`` (B, S, H) →
    ``(y, aux_loss)``.  ``w_gate/w_up/w_down`` hold this device's
    ``E_local`` experts on dim 0; ``axis=None`` means no expert
    parallelism (all experts local, no collectives) — the form the
    MoE transformer uses on a 1-D mesh and the dense oracle of the
    EP choreography.

    ``dispatch``: how tokens reach their (E, C, H) buckets.
      * "grouped" (default): tokens are split into groups of
        ``group_size``; each group routes its tokens to per-group expert
        buckets with a small one-hot matmul (G × E·capg), and one regular
        leading-dim transpose rearranges (NG, E, capg, H) → (E, NG·capg,
        H).  This is the GShard/Switch TPU idiom: dispatch/combine are
        MXU einsums + a layout-regular transpose, so the hot path never
        runs an XLA gather/scatter — which on TPU are row-serialized
        (~0.2 µs/row: a (32k, 2048) permutation costs ~6.5 ms vs ~0.4 ms
        for the group one-hot matmuls; measured on v5e, r3).  Capacity is
        enforced PER GROUP (capg = ceil(cf·G/E)): bursty groups drop
        sooner than the global rule, the standard trade of this layout.
        When ``group_size`` does not divide the local token count the
        group shrinks to the largest divisor (``_resolve_group``) so any
        chunk shape trains.
      * "sort": stable-sort tokens by expert, scatter kept ones into
        their slots, gather back — O(N·H) data movement, but every row
        moves through the serialized gather path (~66 ms vs grouped's
        ~39 ms per layer fwd+bwd at N=32k cf=2.0 on v5e).
      * "einsum": the classic one-hot (N, E, C) dispatch/combine einsums
        over the WHOLE chunk (GShard with one group).  O(N·E·C·H)
        compute — the semantics oracle: "grouped" with group_size=N
        computes identical outputs/gradients (pinned by tests).

    ``top_k``: experts per token.  1 = Switch (gate = raw top prob);
    2+ = GShard-style top-k (gates normalized over the chosen experts,
    per-group capacity capg = ceil(cf·k·G/E) counted with FIRST choices
    ahead of second choices — bursty seconds drop first).  top_k > 1
    requires the "grouped" dispatch.
    """
    ep = C.axis_size(axis) if axis else 1
    B, S, H = x.shape
    N = B * S
    E = w_router.shape[1]
    E_local = w_gate.shape[0]
    if E_local * ep != E:
        raise ValueError(f"router knows {E} experts but ep={ep} devices "
                         f"hold {E_local} each")
    cap = int(-(-N * capacity_factor // E))
    x2d = x.reshape(N, H)
    if top_k > 1 and dispatch != "grouped":
        raise ValueError(f"top_k={top_k} requires dispatch='grouped' "
                         f"(got {dispatch!r})")

    with scope("moe_route"):
        gates, experts, probs = _route_topk(x2d, w_router, top_k)
        gate, expert = gates[:, 0], experts[:, 0]  # k=1 paths' view

    if dispatch == "grouped":
        G, NG, capg = _grouped_caps(N, group_size,
                                    capacity_factor * top_k, E)
        cap = NG * capg   # downstream a2a reshapes see one (E, cap, H)
        with scope("moe_dispatch"):
            # assignments flattened FIRST-choices-first within each
            # group: index j·G + t — earlier choices claim capacity
            # before any second choice does.
            eg = experts.reshape(NG, G, top_k).transpose(
                0, 2, 1).reshape(NG, top_k * G)
            onehot, pos = _group_slot_positions(eg, E)
            kept = (pos < capg) & (onehot > 0)
            slotoh = jax.nn.one_hot(jnp.clip(pos, 0, capg - 1), capg,
                                    dtype=jnp.bool_)
            disp = (kept[..., None] & slotoh).reshape(
                NG, top_k, G, E * capg).astype(x.dtype)      # (NG,k,G,S)
            # per-group dispatch matmul, contracting token AND choice
            # dims at once (no tiled token copy); the transpose is
            # layout-regular (leading dims only) — HBM-rate.
            buckets = jnp.einsum("gkts,gth->gsh", disp,
                                 x2d.reshape(NG, G, H))
            buckets = buckets.reshape(NG, E, capg, H).transpose(
                1, 0, 2, 3).reshape(E, cap, H)
    elif dispatch == "einsum":
        with scope("moe_route_onehot"):
            # position of each token within its expert's bucket
            onehot = jax.nn.one_hot(expert, E, dtype=jnp.int32)  # (N, E)
            pos = jnp.cumsum(onehot, axis=0) * onehot - 1         # (N, E)
            kept = (pos < cap) & (onehot > 0)                     # (N, E)
            # (N, E, C) dispatch mask
            disp = kept[..., None] & (jax.nn.one_hot(
                jnp.clip(pos, 0, cap - 1), cap, dtype=jnp.bool_))
            disp = disp.astype(x.dtype)
        with scope("moe_dispatch"):
            buckets = jnp.einsum("nec,nh->ech", disp, x2d)       # (E, C, H)
    elif dispatch == "sort":
        with scope("moe_dispatch"):
            # Stable sort groups tokens by expert in original order, so
            # position-within-group == the cumsum position the drop rule
            # is defined by.
            order = jnp.argsort(expert, stable=True)             # (N,)
            sorted_e = expert[order]
            counts = jnp.bincount(expert, length=E)
            starts = jnp.cumsum(counts) - counts                 # exclusive
            pos = jnp.arange(N) - starts[sorted_e]
            keep = pos < cap
            # kept tokens scatter to their slot; dropped ones target the
            # out-of-bounds index E*cap, which mode="drop" discards (no
            # trash-row write whose winner would be unspecified).
            slot = jnp.where(keep, sorted_e * cap + jnp.minimum(pos, cap - 1),
                             E * cap)
            buckets = jnp.zeros((E * cap, H), x.dtype).at[slot].set(
                x2d[order], mode="drop")
            buckets = buckets.reshape(E, cap, H)
    else:
        raise ValueError(f"unknown dispatch {dispatch!r}")

    with scope("moe_a2a_out"):
        # regroup buckets by owning device: (ep, E_local, C, H) split on
        # the device dim → every device receives its experts' buckets
        # from the whole group, stacked on a new leading dim.
        recv = buckets.reshape(ep, E_local, cap, H)
        if axis:
            recv = C.all_to_all(recv, axis, split_axis=0, concat_axis=0,
                                tiled=False)                   # (ep, El, C, H)

    with scope("moe_expert_mlp"):
        toks = recv.transpose(1, 0, 2, 3).reshape(E_local, ep * cap, H)
        # per-expert matmuls: vmap the same precision resolver the
        # attention projections use (ops/quant.py) over the expert dim —
        # one precision string selects one impl everywhere (bf16 included:
        # vmap of a plain matmul lowers to the same batched dot_general).
        from ..ops.quant import resolve_quantized_dense
        pe_dense = jax.vmap(resolve_quantized_dense(matmul_precision))
        h_gate = pe_dense(toks, w_gate)
        h_up = pe_dense(toks, w_up)
        out = pe_dense(jax.nn.silu(h_gate) * h_up,
                       w_down)                                 # (El, ep*C, H)

    with scope("moe_a2a_back"):
        back = out.reshape(E_local, ep, cap, H).transpose(1, 0, 2, 3)
        if axis:
            back = C.all_to_all(back, axis, split_axis=0, concat_axis=0,
                                tiled=False)                   # (ep, El, C, H)
        ret = back.reshape(E * cap, H)

    with scope("moe_combine"):
        if dispatch == "grouped":
            # undo the leading-dim transpose, then one combine matmul per
            # group — the exact adjoint of the dispatch einsum; the k
            # assignment outputs sum gate-weighted per token.
            back_g = ret.reshape(E, NG, capg, H).transpose(
                1, 0, 2, 3).reshape(NG, E * capg, H)
            ya = jnp.einsum("gkts,gsh->gkth", disp, back_g)
            gates_g = gates.reshape(NG, G, top_k).transpose(0, 2, 1)
            y2d = jnp.sum(ya * gates_g[..., None].astype(ya.dtype),
                          axis=1).reshape(N, H)
        elif dispatch == "einsum":
            y2d = jnp.einsum("nec,ech->nh", disp,
                             ret.reshape(E, cap, H)) * gate[:, None]
        else:
            pulled = jnp.concatenate([ret, jnp.zeros((1, H), ret.dtype)])
            y_sorted = pulled[slot] * keep[:, None].astype(ret.dtype)
            # O(N) inverse of the sort permutation (not a second sort)
            inv = jnp.zeros((N,), order.dtype).at[order].set(
                jnp.arange(N, dtype=order.dtype))
            y2d = y_sorted[inv] * gate[:, None]

    with scope("moe_aux_loss"):
        # Switch load-balance: fraction of (token, assignment) pairs per
        # expert × mean router prob per expert, summed, scaled by E;
        # averaged over the group.  top_k=1 reduces to the Switch eq. 4.
        frac = (jnp.bincount(experts.reshape(-1), length=E)
                / (N * top_k)).astype(jnp.float32)
        mean_p = jnp.mean(probs, axis=0)
        if axis:
            frac = C.all_reduce(frac, axis, mean=True)
            mean_p = C.all_reduce(mean_p, axis, mean=True)
        aux = E * jnp.sum(frac * mean_p)
        if router_z_ratio:
            # the z term rides the SAME aux channel (callers multiply by
            # the balance weight), pre-divided so the configured z weight
            # lands exactly: ratio = z_weight / aux_weight
            z = router_z_loss(x2d, w_router)
            if axis:
                z = C.all_reduce(z, axis, mean=True)
            aux = aux + router_z_ratio * z
    return y2d.reshape(B, S, H).astype(x.dtype), aux


def moe_layer(params: MoEParams, x, axis: str = "ep", *,
              capacity_factor: float = 2.0, dispatch: str = "grouped",
              group_size: int = 128, top_k: int = 1,
              router_z_ratio: float = 0.0):
    """Apply the expert-parallel MoE MLP to local tokens ``x`` (B, S, H)
    (shard_map only).  Returns (y, aux_loss)."""
    return moe_mlp(x, params.w_router, params.w_gate, params.w_up,
                   params.w_down, axis=axis,
                   capacity_factor=capacity_factor, dispatch=dispatch,
                   group_size=group_size, top_k=top_k,
                   router_z_ratio=router_z_ratio)


def moe_reference(params: MoEParams, x, *, capacity_factor: float = 2.0):
    """Single-device semantics oracle for the GLOBAL-capacity drop rule
    ("sort"/"einsum" dispatch, and "grouped" whenever the local chunk
    fits one group, N <= group_size), computed densely with FULL expert
    weights (E on dim 0), no collectives.  NOT an oracle for multi-group
    "grouped" at tight capacity — that path enforces capacity per group
    and is pinned instead by
    ``test_grouped_dispatch_matches_per_group_einsum``."""
    B, S, H = x.shape
    N = B * S
    E = params.w_router.shape[1]
    cap = int(-(-N * capacity_factor // E))
    x2d = x.reshape(N, H)
    gates, experts, _ = _route_topk(x2d, params.w_router, 1)
    gate, expert = gates[:, 0], experts[:, 0]
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.int32)
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1
    kept = ((pos < cap) & (onehot > 0)).any(axis=1)
    h_g = jnp.einsum("nh,nhf->nf", x2d,
                     params.w_gate[expert])
    h_u = jnp.einsum("nh,nhf->nf", x2d, params.w_up[expert])
    out = jnp.einsum("nf,nfh->nh", jax.nn.silu(h_g) * h_u,
                     params.w_down[expert])
    y = out * gate[:, None] * kept[:, None]
    return y.reshape(B, S, H).astype(x.dtype)


def moe_lm_specs(params, axis: str = "ep") -> dict:
    """PartitionSpec tree for the MoE transformer: expert-stacked layer
    leaves (L, E, ...) shard the expert dim over ``axis``; the router and
    every dense leaf are replicated."""
    expert_leaves = {"w_gate", "w_up", "w_down"}

    def leaf_spec(path, leaf):
        name = next((getattr(k, "key", None) for k in reversed(path)
                     if getattr(k, "key", None)), None)
        if name in expert_leaves and leaf.ndim == 4:   # (L, E, h/F, F/h)
            return P(None, axis)
        return P()

    return jax.tree_util.tree_map_with_path(leaf_spec, params)


def shard_moe_lm_params(params, mesh: Mesh, axis: str = "ep"):
    return jax.tree.map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
        params, moe_lm_specs(params, axis),
        is_leaf=lambda x: isinstance(x, P))


def make_moe_lm_train_step(
    params_sharded,
    cfg,
    mesh: Mesh,
    *,
    dp_axis: str = "dp",
    ep_axis: str = "ep",
    sp_axis: str | None = None,
    lr: float = 3e-4,
    b1: float = 0.9,
    b2: float = 0.95,
    eps: float = 1e-8,
    donate: bool = True,
):
    """Jitted dp×ep step for the MoE *transformer*
    (``models.transformer`` with ``cfg.n_experts > 0``):
    ``(param_shards, opt_state, batch) -> (param_shards, opt_state, loss)``.
    Batch (input_ids, labels) sharded over BOTH axes (dp×ep is the data
    group — every device routes only its own token shard); each layer's
    MoE MLP all_to_alls tokens to the expert owners across the ep row
    and back.  Expert grads arrive via the all_to_all transposes (psum
    over dp only); dense/router grads mean-psum over the whole group.

    ``sp_axis`` makes it the dp×sp×ep step: the sequence dim additionally
    shards over ``sp_axis`` with ring attention (each device then routes
    its B_local × S_local tokens — routing is per-token, so the expert
    choreography is unchanged; only the chunk the capacity is computed
    over shrinks)."""
    import dataclasses

    from ..models import transformer as T

    if not cfg.n_experts:
        raise ValueError("cfg.n_experts must be > 0 for the MoE step")
    ws_dp = int(mesh.shape[dp_axis])
    ws_ep = int(mesh.shape[ep_axis])
    if cfg.n_experts % ws_ep:
        raise ValueError(f"n_experts={cfg.n_experts} must be divisible "
                         f"by ep={ws_ep}")
    if sp_axis is None and cfg.sp_axis is not None:
        raise ValueError(
            f"cfg.sp_axis={cfg.sp_axis!r} (ring attention) but "
            f"make_moe_lm_train_step got sp_axis=None — the batch would "
            f"replicate over {cfg.sp_axis!r} and sp grads would never "
            f"sync.  Pass sp_axis={cfg.sp_axis!r} (the step sets the "
            f"ring config itself).")
    cfg = dataclasses.replace(cfg, ep_axis=ep_axis)
    n_total = ws_dp * ws_ep
    rep_axes = [dp_axis]
    if sp_axis is not None:
        cfg = dataclasses.replace(cfg, attention_impl="ring",
                                  sp_axis=sp_axis)
        n_total *= int(mesh.shape[sp_axis])
        rep_axes.append(sp_axis)
    specs = moe_lm_specs(params_sharded, ep_axis)

    def sync_grad(g, spec):
        axes = tuple(rep_axes) + ((ep_axis,) if ep_axis not in spec
                                  else ())
        return jax.lax.psum(g, axes) / n_total

    def step(shards, opt_state, batch):
        with scope("forward_backward"):
            loss, grads = jax.value_and_grad(
                lambda p: T.lm_loss(p, batch, cfg))(shards)
        with scope("loss_mean"):
            # one fused mean over every axis (equal shard sizes)
            loss = jax.lax.pmean(loss, tuple(rep_axes + [ep_axis]))
        with scope("grad_sync"):
            grads = jax.tree.map(sync_grad, grads, specs,
                                 is_leaf=lambda x: isinstance(x, P))
        with scope("opt_step"):
            shards, opt_state = optim.adam_update(
                grads, opt_state, shards, lr=lr, b1=b1, b2=b2, eps=eps,
                lr_mults=lr_mults)
        return shards, opt_state, loss

    # router LR multiplier (cfg.moe_router_lr_mult): per-leaf LR tree —
    # the same router-health knob the FSDP step honors
    lr_mults = None
    if getattr(cfg, "moe_router_lr_mult", 1.0) != 1.0:
        lr_mults = jax.tree_util.tree_map_with_path(
            lambda path, _leaf: (cfg.moe_router_lr_mult
                                 if any(getattr(k, "key", None) == "w_router"
                                        for k in path) else 1.0),
            params_sharded)
    state_specs = optim.AdamState(mu=specs, nu=specs, count=P())
    batch_spec = (P((dp_axis, ep_axis)) if sp_axis is None  # spec-ok
                  else P((dp_axis, ep_axis), sp_axis))
    sharded = C.smap(step, mesh,
                     in_specs=(specs, state_specs, batch_spec),
                     out_specs=(specs, state_specs, P()))
    return jax.jit(sharded, donate_argnums=(0, 1) if donate else ())


def make_ep_train_step(
    params_sharded: MoEParams,
    mesh: Mesh,
    *,
    axis: str = "ep",
    capacity_factor: float = 2.0,
    aux_weight: float = 0.01,
    lr: float = 1e-3,
    donate: bool = True,
):
    """Jitted EP step on the toy MoE regression
    ``(params, opt, (x, y)) -> (params, opt, loss)``: batch sharded on
    ``ep`` (each device routes its own tokens), expert grads stay local,
    router grads mean-psum across the group."""
    ws = int(mesh.shape[axis])
    specs = moe_specs(axis)

    def step(p, opt_state, batch):
        x, y = batch

        def loss_fn(p):
            out, aux = moe_layer(p, x, axis,
                                 capacity_factor=capacity_factor)
            return jnp.mean((out - y) ** 2) + aux_weight * aux

        with scope("forward_backward"):
            loss, grads = jax.value_and_grad(loss_fn)(p)
        with scope("loss_mean"):
            loss = C.all_reduce(loss, axis, mean=True)
        with scope("grad_sync"):
            # ep-sharded expert weights: each device owns its experts'
            # grads outright (tokens from the whole group arrived via
            # all_to_all, whose transpose already returned their
            # cotangents).  Replicated router: mean across the group.
            grads = jax.tree.map(
                lambda g, s: C.all_reduce(g, axis, mean=True)
                if axis not in s else g / ws,
                grads, specs, is_leaf=lambda s: isinstance(s, P))
        with scope("opt_step"):
            p, opt_state = optim.adam_update(grads, opt_state, p, lr=lr)
        return p, opt_state, loss

    state_specs = optim.AdamState(mu=specs, nu=specs, count=P())
    sharded = C.smap(step, mesh,
                     in_specs=(specs, state_specs, P(axis)),  # spec-ok
                     out_specs=(specs, state_specs, P()))
    return jax.jit(sharded, donate_argnums=(0, 1) if donate else ())
