"""8-bit Adam moments: the at-rest optimizer state stored int8.

The r4 memory accounting (EXPERIMENTS.md) put the flagship's Adam
mu/nu at 3.31 GB of the 4.96 GB resident state — the largest block on
the chip.  Storing both moments int8 with per-row fp32 scales cuts that
to ~1.7 GB, which is the same order as the 2.3–2.7 GB OOM margins that
killed the save_dots×int8 knob crossings (BENCH_r04) — the state-side
attack on the 125.8 TFLOPS ceiling the r4 verdict prescribed (#4).

Scheme (bitsandbytes-style blockwise, TPU-shaped):
  * ``mu`` (signed): per-LAST-AXIS-row absmax / 127 linear int8 — rows
    are the natural TPU-contiguous blocks and the scale tree keeps the
    param's sharding spec (scales shard like the leaf, last dim 1).
  * ``nu`` (nonnegative, huge dynamic range): quantized in the SQRT
    domain — q = √v / scale, dequant v = (q·scale)² — which halves the
    stored exponent range; per-row absmax again.
  * update math runs in fp32 after dequant, exactly
    ``optim.adam_update``'s kernel, then requantizes.  No error
    feedback buffer (it would give back the memory the scheme exists to
    save); the trajectory-parity test pins the consequence.

The reference's analogue is its memory-for-throughput trades around
FSDP state (``fsdp/train_fsdp.py:84-88``); 8-bit state is this repo's
extension past it.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from .optim import AdamState


class Q8(NamedTuple):
    """One int8-stored moment leaf: codes + per-row fp32 scales."""
    q: jax.Array       # int8, the param's shape
    scale: jax.Array   # f32, shape[:-1] + (1,)


def _quant_linear(x) -> Q8:
    amax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.maximum(amax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return Q8(q=q, scale=scale)


def _dequant_linear(m: Q8) -> jax.Array:
    return m.q.astype(jnp.float32) * m.scale


def _quant_sqrt(v) -> Q8:
    s = jnp.sqrt(v)
    amax = jnp.max(s, axis=-1, keepdims=True)
    scale = jnp.maximum(amax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(s / scale), 0, 127).astype(jnp.int8)
    return Q8(q=q, scale=scale)


def _dequant_sqrt(m: Q8) -> jax.Array:
    s = m.q.astype(jnp.float32) * m.scale
    return s * s


def adam8_init(params) -> AdamState:
    """Zero moments in quantized form, sharded like the params they
    track (the scale inherits the leaf's sharding minus its last dim).
    1-D leaves (RMSNorm scales) stay full precision: their only dim may
    be the FSDP-sharded one (a size-1 scale can't shard over it), and
    their bytes are negligible."""

    def zq(p):
        if p.ndim < 2:
            return jnp.zeros_like(p)
        return Q8(q=jnp.zeros(p.shape, jnp.int8),
                  scale=jnp.zeros(p.shape[:-1] + (1,), jnp.float32))

    return AdamState(mu=jax.tree.map(zq, params),
                     nu=jax.tree.map(zq, params),
                     count=jnp.zeros((), jnp.int32))


def adam8_update(grads, state: AdamState, params, *, lr=1e-3, b1=0.9,
                 b2=0.999, eps=1e-8, lr_mults=None):
    """``optim.adam_update`` with int8-at-rest moments: dequant → fp32
    moment math → requant, per leaf.  The fp32 copies are transient
    inside the fused step; only the int8 codes + scales persist."""
    count = state.count + 1
    c = count.astype(jnp.float32)
    bc1 = 1 - b1 ** c
    bc2 = 1 - b2 ** c

    def leaf(p, g, mq, vq, s=1.0):
        g32 = g.astype(jnp.float32)
        quantized = isinstance(mq, Q8)
        m_prev = _dequant_linear(mq) if quantized else mq.astype(jnp.float32)
        v_prev = _dequant_sqrt(vq) if quantized else vq.astype(jnp.float32)
        m = b1 * m_prev + (1 - b1) * g32
        v = b2 * v_prev + (1 - b2) * g32 * g32
        step = (lr * s) * (m / bc1) / (jnp.sqrt(v / bc2) + eps)
        new_p = (p.astype(jnp.float32) - step).astype(p.dtype)
        if quantized:
            return new_p, _quant_linear(m), _quant_sqrt(v)
        return new_p, m.astype(mq.dtype), v.astype(vq.dtype)

    # primary tree = params: its leaves line up with Q8 SUBTREES in
    # mu/nu (tree.map flattens rest trees up to the primary's leaves)
    if lr_mults is None:
        out = jax.tree.map(leaf, params, grads, state.mu, state.nu)
    else:
        out = jax.tree.map(leaf, params, grads, state.mu, state.nu,
                           lr_mults)
    td = jax.tree.structure(params)
    tups = td.flatten_up_to(out)
    return (td.unflatten([t[0] for t in tups]),
            AdamState(mu=td.unflatten([t[1] for t in tups]),
                      nu=td.unflatten([t[2] for t in tups]),
                      count=count))


from functools import partial


@partial(jax.jit, donate_argnums=(0, 1, 2))
def adam8_step_donated(grads, state: AdamState, params, lr):
    """One compiled donated program, the ``optim.adam_step_donated``
    twin for int8 state — pipeline stages at billion-param scale need
    the in-place update either way, and the int8 codes make the
    at-rest state ~2× smaller on top."""
    return adam8_update(grads, state, params, lr=lr)
