"""Sentence-pair classification data — twin of the reference's GLUE MRPC
pipeline (``DDP/training_utils/utils.py:90-107``) with its DDP collate
(``DDP/ddp.py:64-71``: ``tokenizer.pad(padding="longest",
pad_to_multiple_of=8)``) and per-rank contiguous dataset sharding
(``DDP/ddp.py:104-112``).

Examples are plain dicts ``{"input_ids": list[int], "labels": int}`` —
the post-tokenization shape of the reference's mapped dataset.  The real
MRPC path (HF datasets + tokenizer) is gated behind hub reachability; the
offline fallback generates deterministic variable-length synthetic pairs
whose *learnable rule* (label = whether the two halves share their most
frequent token) gives training curves something real to descend.
"""

from __future__ import annotations

import numpy as np

from .packing import _hub_reachable


def synthetic_pair_examples(n_examples: int, vocab_size: int,
                            seed: int = 42, min_len: int = 16,
                            max_len: int = 96) -> list[dict]:
    """Deterministic MRPC-stand-in: two token spans [sep-joined]; label 1
    iff span B reuses span A's dominant token.  Variable lengths exercise
    the pad-to-multiple-of-8 collate the way real tokenized pairs do."""
    rng = np.random.default_rng(seed)
    sep = vocab_size - 1
    out = []
    for _ in range(n_examples):
        la, lb = rng.integers(min_len // 2, max_len // 2, size=2)
        a = rng.integers(1, vocab_size - 1, size=la)
        b = rng.integers(1, vocab_size - 1, size=lb)
        label = int(rng.random() < 0.5)
        dominant = np.bincount(a).argmax()
        if label:
            b[rng.integers(0, lb, size=max(lb // 4, 1))] = dominant
        else:
            b = b[b != dominant]
            if len(b) == 0:
                b = np.array([1 + (dominant + 1) % (vocab_size - 2)])
        ids = np.concatenate([a, [sep], b]).astype(np.int32)
        out.append({"input_ids": ids.tolist(), "labels": label})
    return out


def get_mrpc_examples(tokenizer_name: str = "HuggingFaceTB/SmolLM2-360M-Instruct",
                      split: str = "train") -> list[dict]:
    """The real GLUE MRPC path (requires network): tokenize sentence pairs,
    keep input_ids + labels — reference ``get_dataset``
    (``DDP/training_utils/utils.py:90-107``)."""
    from datasets import load_dataset  # gated import
    from transformers import AutoTokenizer

    ds = load_dataset("glue", "mrpc", split=split)
    tok = AutoTokenizer.from_pretrained(tokenizer_name)
    out = []
    for ex in ds:
        ids = tok(ex["sentence1"], ex["sentence2"], truncation=True,
                  max_length=512)["input_ids"]
        out.append({"input_ids": ids, "labels": int(ex["label"])})
    return out


def make_classification_examples(vocab_size: int, *, n_examples: int = 2048,
                                 seed: int = 42,
                                 source: str = "auto") -> list[dict]:
    """source: "mrpc" (requires network), "synthetic", or "auto" (mrpc
    with synthetic fallback — the zero-egress default)."""
    if source not in ("mrpc", "synthetic", "auto"):
        raise ValueError(f"unknown source {source!r}")
    if source in ("mrpc", "auto"):
        try:
            if source == "auto" and not _hub_reachable():
                raise OSError("hub unreachable")
            examples = get_mrpc_examples()
            too_big = max(max(e["input_ids"]) for e in examples)
            if too_big >= vocab_size:
                raise ValueError(
                    f"MRPC token ids go up to {too_big}, model vocab is "
                    f"{vocab_size}; use a matching tokenizer or "
                    f"source='synthetic'")
            return examples
        except Exception as e:
            # "auto" is best-effort by contract: ANY unusable-MRPC condition
            # (offline, download error, or tokenizer ids exceeding a small
            # model's vocab) falls back, loudly.  Explicit source="mrpc"
            # propagates the error instead.
            if source == "mrpc":
                raise
            print(f"[data] GLUE MRPC unusable ({type(e).__name__}: {e}); "
                  f"falling back to synthetic pairs", flush=True)
    return synthetic_pair_examples(n_examples, vocab_size, seed)


def pad_collate(examples: list[dict], *, pad_to_multiple_of: int = 8,
                pad_id: int = 0) -> dict:
    """Batch list of examples → padded arrays: pad to the longest sequence
    rounded UP to a multiple of 8 — the exact semantics of the reference's
    ``tokenizer.pad(padding="longest", pad_to_multiple_of=8)``
    (``DDP/ddp.py:64-71``; keeps tensor-core/MXU-friendly shapes and caps
    XLA recompiles at one per bucketed length)."""
    longest = max(len(e["input_ids"]) for e in examples)
    m = pad_to_multiple_of
    width = -(-longest // m) * m
    B = len(examples)
    input_ids = np.full((B, width), pad_id, np.int32)
    mask = np.zeros((B, width), np.int32)
    labels = np.empty((B,), np.int32)
    for i, e in enumerate(examples):
        ids = e["input_ids"]
        input_ids[i, :len(ids)] = ids
        mask[i, :len(ids)] = 1
        labels[i] = e["labels"]
    return {"input_ids": input_ids, "attention_mask": mask,
            "labels": labels}


def shard_examples(examples: list, rank: int, ws: int) -> list:
    """Contiguous per-rank shard, remainder to the LAST rank — the exact
    reference split (``DDP/ddp.py:104-112``: every rank takes
    ``len // ws`` except the last, which runs to the end)."""
    per = len(examples) // ws
    start = rank * per
    end = start + per if rank != ws - 1 else len(examples)
    return examples[start:end]


def classification_batches(examples: list[dict], batch_size: int, ws: int,
                           *, seed: int = 42, epochs: int = 1,
                           pad_to_multiple_of: int = 8):
    """Global-batch iterator with per-rank contiguous sharding: each rank
    draws from ITS shard (shuffled per epoch, drop_last=True as the
    reference's DataLoader), and the global batch is the rank-major
    concatenation — handing it to shard_map with in_spec P("dp") gives
    every rank exactly its own shard's rows.  Collation pads across the
    whole global batch so ranks agree on the step's padded width (SPMD
    needs one shape; the reference pays per-rank ragged widths instead)."""
    rng = np.random.default_rng(seed)
    shards = [shard_examples(examples, r, ws) for r in range(ws)]
    per_rank = batch_size // ws
    if per_rank == 0:
        raise ValueError(f"batch_size {batch_size} < world size {ws}")
    steps = min(len(s) for s in shards) // per_rank
    for _ in range(epochs):
        orders = [rng.permutation(len(s)) for s in shards]
        for b in range(steps):
            chosen = []
            for r, shard in enumerate(shards):
                idx = orders[r][b * per_rank:(b + 1) * per_rank]
                chosen += [shard[i] for i in idx]
            yield pad_collate(chosen, pad_to_multiple_of=pad_to_multiple_of)
