from .packing import (  # noqa: F401
    pack_tokens, packed_batches, synthetic_token_stream,
    get_tinystories_tokens, make_packed_dataset, VocabMismatchError)
