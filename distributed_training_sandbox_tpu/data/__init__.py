from .packing import (  # noqa: F401
    pack_tokens, packed_batches, synthetic_token_stream,
    get_tinystories_tokens, get_corpus_tokens, tokenize_documents,
    read_corpus_documents, make_packed_dataset, VocabMismatchError)
from .classification import (  # noqa: F401
    classification_batches, make_classification_examples, pad_collate,
    shard_examples, synthetic_pair_examples)
