"""Packed causal-LM pretraining pipeline.

Twin of the reference's TinyStories pipeline (``fsdp/utils.py:29-91``):
tokenize every document → concatenate all tokens into one stream → slice
into fixed (seq_len + 1) windows → ``input_ids = window[:-1]``,
``labels = window[1:]``.  That packing logic is pure Python and ports
conceptually as-is; what changes is the substrate:

  * the host-side pipeline feeds jax arrays (device put happens at the
    train loop, sharded over the ``dp`` axis);
  * the download path (HF ``datasets`` + ``transformers`` tokenizer) is
    *gated*: on an air-gapped TPU pod it degrades to a seeded synthetic
    token stream with a Zipfian unigram distribution — the same role the
    reference's ``randn`` batches play for the toys (``zero1.py:115-117``).

The reference's split knob (5% fsdp vs 10% fp8 — the single line differing
between its two copies of utils.py, SURVEY.md §2.8) survives as the
``split_percent`` argument of one shared function.
"""

from __future__ import annotations

import numpy as np


class VocabMismatchError(ValueError):
    """Token ids exceed the model vocab — a configuration error that must
    never be silently papered over by the synthetic fallback (JAX clamps
    OOB gather indices instead of raising)."""


def pack_tokens(tokens: np.ndarray, seq_len: int) -> tuple[np.ndarray, np.ndarray]:
    """Concatenated token stream → (input_ids, labels), each
    (n_windows, seq_len).  Window stride is seq_len + 1 and the ragged tail
    is dropped, exactly as reference ``fsdp/utils.py:58-89``."""
    tokens = np.asarray(tokens).reshape(-1)
    window = seq_len + 1
    n = len(tokens) // window
    if n == 0:
        raise ValueError(f"stream of {len(tokens)} tokens too short for one "
                         f"window of {window}")
    w = tokens[: n * window].reshape(n, window)
    return w[:, :-1].astype(np.int32), w[:, 1:].astype(np.int32)


def synthetic_token_stream(num_tokens: int, vocab_size: int,
                           seed: int = 42) -> np.ndarray:
    """Seeded Zipfian token stream — deterministic, offline, with a
    realistic (skewed) unigram distribution so loss curves behave like text
    rather than uniform noise."""
    rng = np.random.default_rng(seed)
    ranks = np.arange(1, vocab_size + 1, dtype=np.float64)
    probs = 1.0 / ranks
    probs /= probs.sum()
    return rng.choice(vocab_size, size=num_tokens, p=probs).astype(np.int32)


def tokenize_documents(docs, tok, max_docs: int | None = None) -> np.ndarray:
    """The real-tokenizer core shared by every text source: tokenize each
    document, append EOS, concatenate into one int32 stream — exactly the
    reference's per-doc loop (``fsdp/utils.py:47-57``).  ``docs`` yields
    strings; ``tok`` is any HF tokenizer with ``__call__`` and
    ``eos_token_id``."""
    chunks = []
    for i, doc in enumerate(docs):
        if max_docs is not None and i >= max_docs:
            break
        ids = list(tok(doc)["input_ids"])
        if tok.eos_token_id is not None:
            ids.append(tok.eos_token_id)
        chunks.append(np.asarray(ids, dtype=np.int32))
    if not chunks:
        raise ValueError("no documents to tokenize")
    return np.concatenate(chunks)


def get_tinystories_tokens(tokenizer_name: str = "HuggingFaceTB/SmolLM3-3B",
                           split_percent: int = 5,
                           max_docs: int | None = None) -> np.ndarray:
    """Tokenize TinyStories into one concatenated stream (reference
    ``fsdp/utils.py:29-57``; ``split_percent`` 5 = fsdp flavor, 10 = fp8
    flavor).  Requires network + ``datasets``/``transformers``; callers on
    air-gapped hosts should catch and fall back to
    ``synthetic_token_stream`` — or point ``get_corpus_tokens`` at a local
    text corpus to keep the real-tokenizer path without the network."""
    from datasets import load_dataset  # gated import
    from transformers import AutoTokenizer

    ds = load_dataset("roneneldan/TinyStories",
                      split=f"train[:{split_percent}%]")
    tok = AutoTokenizer.from_pretrained(tokenizer_name)
    return tokenize_documents((doc["text"] for doc in ds), tok, max_docs)


def read_corpus_documents(corpus_path) -> list[str]:
    """A local text file as a document list: blank-line-separated blocks,
    each block one document (the fixture-corpus convention,
    ``tests/fixtures/tiny_corpus.txt``)."""
    from pathlib import Path
    text = Path(corpus_path).read_text()
    docs = [blk.strip() for blk in text.split("\n\n") if blk.strip()]
    if not docs:
        raise ValueError(f"no documents in {corpus_path}")
    return docs


def get_corpus_tokens(corpus_path, *,
                      tokenizer_file=None,
                      tokenizer_name: str | None = None,
                      max_docs: int | None = None) -> np.ndarray:
    """The offline real-tokenizer branch: tokenize a LOCAL corpus through
    a genuine HF tokenizer — same per-doc tokenize→EOS→concat core as the
    TinyStories path, no network.  ``tokenizer_file`` loads a committed
    ``tokenizer.json`` (``transformers.PreTrainedTokenizerFast``);
    ``tokenizer_name`` falls back to ``AutoTokenizer`` (cached/hub)."""
    if tokenizer_file is not None:
        tok = load_corpus_tokenizer(tokenizer_file)
    elif tokenizer_name is not None:
        from transformers import AutoTokenizer
        tok = AutoTokenizer.from_pretrained(tokenizer_name)
    else:
        raise ValueError("need tokenizer_file or tokenizer_name")
    return tokenize_documents(read_corpus_documents(corpus_path), tok,
                              max_docs)


def _hub_reachable(timeout: float = 2.0) -> bool:
    """Fast offline detection so ``source="auto"`` doesn't sit through HF's
    retry/backoff loop on air-gapped hosts."""
    import os
    import socket
    if os.environ.get("HF_HUB_OFFLINE") or os.environ.get("HF_DATASETS_OFFLINE"):
        return False
    prev = socket.getdefaulttimeout()
    try:
        socket.setdefaulttimeout(timeout)
        socket.getaddrinfo("huggingface.co", 443)
        return True
    except OSError:
        return False
    finally:
        socket.setdefaulttimeout(prev)


def make_packed_dataset(seq_len: int, vocab_size: int, *,
                        num_tokens: int | None = None,
                        split_percent: int = 5,
                        seed: int = 42,
                        source: str = "auto",
                        engine: str = "numpy",
                        corpus_path=None,
                        tokenizer_file=None,
                        tokenizer_name: str | None = None):
    """One-call dataset: (input_ids, labels) arrays.

    source: "tinystories" (requires network), "synthetic", "corpus"
    (local text file through a real tokenizer — needs ``corpus_path`` and
    ``tokenizer_file``/``tokenizer_name``), or "auto" (tinystories with
    synthetic fallback — the zero-egress default).

    engine: "numpy" (default — the committed benchmarks' deterministic
    stream) or "native" (the C++ engine, ``data/native.py``: same Zipf
    law and packing rule, ~10× faster sampling — measured,
    ``data_results/native_data_bench.json`` — and its OWN seeded
    stream — pick per run, not per step).
    """
    if source not in ("tinystories", "synthetic", "auto", "corpus"):
        raise ValueError(f"unknown source {source!r}; expected 'tinystories',"
                         f" 'synthetic', 'corpus' or 'auto'")
    if engine not in ("numpy", "native"):
        raise ValueError(f"unknown engine {engine!r}")
    if engine == "native":
        from . import native
        if not native.available():
            raise RuntimeError("native data engine unavailable "
                               f"({native.build_error()}); use "
                               f"engine='numpy'")
        sample, pack = native.synthetic_token_stream, native.pack_tokens
    else:
        sample, pack = synthetic_token_stream, pack_tokens
    if source == "corpus":
        if corpus_path is None:
            raise ValueError("source='corpus' needs corpus_path")
        stream = get_corpus_tokens(corpus_path, tokenizer_file=tokenizer_file,
                                   tokenizer_name=tokenizer_name)
        if stream.max() >= vocab_size:
            raise VocabMismatchError(
                f"corpus token ids go up to {stream.max()}, model vocab is "
                f"{vocab_size}; use a matching tokenizer")
        return pack(stream, seq_len)
    if source in ("tinystories", "auto"):
        try:
            if source == "auto" and not _hub_reachable():
                raise OSError("hub unreachable")
            stream = get_tinystories_tokens(split_percent=split_percent)
            if stream.max() >= vocab_size:
                # A configuration error, not an availability problem, so it
                # escapes the auto fallback below.
                raise VocabMismatchError(
                    f"TinyStories token ids go up to {stream.max()}, model "
                    f"vocab is {vocab_size}; use a matching tokenizer or "
                    f"source='synthetic'")
            return pack(stream, seq_len)
        except VocabMismatchError:
            raise
        except Exception as e:
            if source == "tinystories":
                raise
            print(f"[data] TinyStories unavailable ({type(e).__name__}: {e});"
                  f" falling back to synthetic Zipfian tokens", flush=True)
    if num_tokens is None:
        num_tokens = 64 * (seq_len + 1)
    stream = sample(num_tokens, vocab_size, seed)
    return pack(stream, seq_len)


def packed_batches(input_ids: np.ndarray, labels: np.ndarray,
                   batch_size: int, *, epochs: int = 1, drop_last: bool = True):
    """Minimal epoch iterator (reference uses a bs=1 DataLoader,
    ``train_fsdp.py:72``; batching is a knob here)."""
    n = len(input_ids)
    for _ in range(epochs):
        for i in range(0, n - (batch_size - 1 if drop_last else 0),
                       batch_size):
            yield input_ids[i:i + batch_size], labels[i:i + batch_size]


def load_corpus_tokenizer(tokenizer_file):
    """The committed corpus tokenizer as a HF-fast tokenizer — ONE place
    configures its special tokens, shared by the data path
    (``get_corpus_tokens``) and the decode-side scripts (detokenizing
    generated ids must use the exact training-tokenizer config)."""
    from transformers import PreTrainedTokenizerFast
    return PreTrainedTokenizerFast(tokenizer_file=str(tokenizer_file),
                                   eos_token="<eos>", unk_token="<unk>")


# THE corpus train/holdout boundary parameters.  train_flagship.py and
# eval_lm.py both split with these exact values (no per-script overrides)
# so the evaluator can never score a window the trainer touched.
CORPUS_HOLDOUT_FRAC = 0.05
CORPUS_HOLDOUT_MIN_WINDOWS = 4


def corpus_holdout_split(input_ids, labels, *,
                         frac: float = CORPUS_HOLDOUT_FRAC,
                         min_windows: int = CORPUS_HOLDOUT_MIN_WINDOWS):
    """ONE definition of the corpus train/holdout split: the TAIL
    ``frac`` of packed windows (≥ ``min_windows``) is held out.  Both
    the trainer (which must NOT touch it) and the evaluator (which
    scores exactly it) call this, so the two can never disagree about
    where the boundary sits."""
    n_hold = max(int(len(input_ids) * frac), min_windows)
    if n_hold >= len(input_ids):
        # a tiny corpus (or oversized frac/min_windows) would silently
        # yield an empty train split and zero batches downstream — fail
        # at the boundary where the misconfiguration is visible
        raise ValueError(
            f"corpus_holdout_split: holdout of {n_hold} windows would "
            f"consume the whole corpus ({len(input_ids)} windows); need "
            f"more data or smaller frac/min_windows")
    return ((input_ids[:-n_hold], labels[:-n_hold]),
            (input_ids[-n_hold:], labels[-n_hold:]))
