"""ctypes bindings for the native (C++) data engine.

The reference's host data path rides torch's C++-backed DataLoader;
this module is the TPU build's native equivalent for the pieces that
are hot on the host (``native/dtsdata.cpp``): the alias-method Zipfian
sampler behind the synthetic stream, the window packer, and epoch
shuffles.  The shared library builds on first use with plain ``g++``
(no pybind11) and caches next to the source; every entry point has the
numpy twin in ``packing.py``, so environments without a toolchain lose
speed, not function — check ``available()``.

Determinism: native streams are pure functions of (args, seed) —
identical across runs/hosts — but the Zipf sampler is its OWN stream,
not bit-identical to numpy's ``Generator.choice`` (the packer IS exact:
pure arithmetic, equality-pinned by tests).
"""

from __future__ import annotations

import ctypes
import subprocess
from pathlib import Path

import numpy as np

_SRC = Path(__file__).resolve().parents[2] / "native" / "dtsdata.cpp"
_LIB = _SRC.with_name("libdtsdata.so")
_lib: ctypes.CDLL | None = None
_err: str | None = None


def _load() -> ctypes.CDLL | None:
    global _lib, _err
    if _lib is not None or _err is not None:
        return _lib
    try:
        if (not _LIB.exists()
                or _LIB.stat().st_mtime < _SRC.stat().st_mtime):
            # build to a per-pid temp and atomically rename: concurrent
            # first-use builders (pytest workers, a bench beside a
            # training job) must never let a reader dlopen a
            # partially-written library.
            import os
            tmp = _LIB.with_name(f".{_LIB.name}.{os.getpid()}")
            try:
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", "-o", str(tmp),
                     str(_SRC)],
                    check=True, capture_output=True, text=True,
                    timeout=120)
                os.replace(tmp, _LIB)
            finally:
                tmp.unlink(missing_ok=True)  # leak nothing on failure
        lib = ctypes.CDLL(str(_LIB))
        lib.dts_zipf_fill.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
            ctypes.c_int32, ctypes.c_uint64]
        lib.dts_pack_windows.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
            ctypes.c_int64, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32)]
        lib.dts_pack_windows.restype = ctypes.c_int64
        lib.dts_shuffle_indices.argtypes = [
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.c_uint64]
        _lib = lib
    except Exception as e:  # noqa: BLE001 — degrade to the numpy twins
        _err = f"{type(e).__name__}: {e}"
    return _lib


def available() -> bool:
    return _load() is not None


def build_error() -> str | None:
    """Why the native engine is unavailable (None when it is)."""
    _load()
    return _err


def _i32ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def synthetic_token_stream(num_tokens: int, vocab_size: int,
                           seed: int = 42) -> np.ndarray:
    """Native twin of ``packing.synthetic_token_stream`` (same Zipf law,
    its own deterministic stream)."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native data engine unavailable: {_err}")
    out = np.empty(num_tokens, np.int32)
    lib.dts_zipf_fill(_i32ptr(out), num_tokens, vocab_size, seed)
    return out


def pack_tokens(tokens: np.ndarray, seq_len: int):
    """Native twin of ``packing.pack_tokens`` — identical outputs."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native data engine unavailable: {_err}")
    tokens = np.ascontiguousarray(np.asarray(tokens).reshape(-1),
                                  np.int32)
    window = seq_len + 1
    n = len(tokens) // window
    if n == 0:
        raise ValueError(f"stream of {len(tokens)} tokens too short for "
                         f"one window of {window}")
    inputs = np.empty((n, seq_len), np.int32)
    labels = np.empty((n, seq_len), np.int32)
    got = lib.dts_pack_windows(_i32ptr(tokens), len(tokens), seq_len,
                               _i32ptr(inputs), _i32ptr(labels))
    assert got == n, (got, n)
    return inputs, labels


def shuffle_indices(n: int, seed: int = 0) -> np.ndarray:
    """Seeded Fisher–Yates permutation of [0, n) (epoch shuffles)."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native data engine unavailable: {_err}")
    out = np.empty(n, np.int64)
    lib.dts_shuffle_indices(
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), n, seed)
    return out
