"""Memory planner: pre-flight HBM waterline prediction, auto-fit search
over remat × accumulation × quantization × offload, and contracted host
offload of optimizer state / remat activations.

Three layers (ROADMAP open item 4 — the BENCH_r03–r05 OOM wall):

  * ``predictor`` — per-config waterline without running a step:
    compile-based (``memory_analysis()`` / the compiler's own
    used-vs-capacity OOM verdict) with an analytic tensor-walk fallback;
  * ``planner`` — reject predicted-over-budget configs *pre-compile* and
    rank the survivors by modeled throughput (bench-JSON priors when
    measured rows exist);
  * ``offload`` — host memory-kind placements for optimizer state and
    named remat activations, with an :class:`OffloadPlan` declaring the
    per-step transfer counts so ``analysis/hlo_lint`` can expect them
    instead of flagging them.
"""

from .offload import (  # noqa: F401
    OFFLOAD_MODES,
    OffloadPlan,
    offload_tree,
    plan_offload,
    stream_tree,
    supports_host_offload,
)
from .planner import (  # noqa: F401
    Candidate,
    NoFittingConfig,
    Plan,
    PlannedCandidate,
    enumerate_candidates,
    load_bench_priors,
    parse_bench_config_name,
    plan,
)
from .predictor import (  # noqa: F401
    MEMORY_PRIORS_SCHEMA_VERSION,
    WaterlinePrediction,
    analytic_waterline,
    load_memory_priors,
    predict,
    predict_from_step,
)
