"""Pre-flight HBM waterline prediction — per-config peak device memory
without running a step.

Two sources, in order of authority:

  * **compile-based** (:func:`predict_from_step`): XLA's own allocation
    plan via ``step.lower(...).compile().memory_analysis()`` — argument +
    output + temp buffers minus donation aliasing, the same accounting
    ``scripts/memory_waterline.py`` reads.  On backends that validate HBM
    fit at compile time (TPU) an over-budget plan surfaces as the
    compiler's ``Used X G of Y G hbm`` verdict instead — parsed through
    the shared ``utils.memory.parse_hbm_oom`` into a prediction with
    ``source="compiler_oom"``.
  * **analytic** (:func:`analytic_waterline`): a tensor-walk model over
    the architecture — params/grads/optimizer at rest plus a phase model
    of activations per remat policy and the streamed-loss buffers.  No
    lowering, no compile: this is what lets ``bench.py`` and the planner
    reject a config in microseconds instead of burning the compile that
    would OOM anyway.  Calibrated against the BENCH_r03–r05 compiler
    verdicts (see RESULTS.md); the compile-based source supersedes it
    whenever a compile is affordable.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from ..utils.memory import GB, parse_hbm_oom

# schema of the measured-residual priors file that
# ``scripts/runs.py export-memory-priors`` emits from indexed memory
# ledgers (telemetry.memledger) — the memory twin of the tuner's
# cost_model.json
MEMORY_PRIORS_SCHEMA_VERSION = 1


def load_memory_priors(path: str) -> dict | None:
    """Parse an ``export-memory-priors`` file; None when missing,
    unreadable, or from a different schema generation (recalibration
    must never crash a planner run)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(doc, dict) or \
            doc.get("schema_version") != MEMORY_PRIORS_SCHEMA_VERSION:
        return None
    return doc


@dataclass
class WaterlinePrediction:
    """One config's predicted per-device HBM waterline."""
    gb: float
    source: str            # "memory_analysis" | "compiler_oom" | "analytic"
    fits: bool | None = None       # vs capacity_gb when known
    capacity_gb: float | None = None
    components: dict = field(default_factory=dict)  # GB breakdown

    def judge(self, capacity_gb: float | None) -> "WaterlinePrediction":
        """Fill ``fits`` against a capacity/budget (keeps a compiler OOM
        verdict's own ``fits=False`` even when no budget was given)."""
        if capacity_gb is not None:
            self.capacity_gb = capacity_gb
            self.fits = self.gb <= capacity_gb
        return self

    def to_dict(self) -> dict:
        return {"predicted_gb": round(self.gb, 3), "source": self.source,
                "fits": self.fits, "capacity_gb": self.capacity_gb,
                "components": {k: round(v, 3)
                               for k, v in self.components.items()}}


def predict_from_step(step, *args, capacity_gb: float | None = None
                      ) -> WaterlinePrediction:
    """Compile-time waterline of a jitted step: args + out + temp − alias
    from ``memory_analysis()``, or the compiler's own used-vs-capacity
    verdict when the plan itself exceeds HBM at compile."""
    try:
        compiled = step.lower(*args).compile()
    except Exception as e:  # noqa: BLE001 - only the OOM verdict is ours
        oom = parse_hbm_oom(str(e))
        if oom is None:
            raise
        needed, cap = oom
        return WaterlinePrediction(
            gb=needed, source="compiler_oom", fits=False,
            capacity_gb=capacity_gb or cap,
            components={"compiler_needed": needed})
    ma = compiled.memory_analysis()
    if ma is None:  # backend exposes no plan: caller falls back to analytic
        raise RuntimeError("backend returned no memory_analysis(); use "
                           "analytic_waterline instead")
    comp = {
        "args": ma.argument_size_in_bytes / GB,
        "out": ma.output_size_in_bytes / GB,
        "temp": ma.temp_size_in_bytes / GB,
        "alias": ma.alias_size_in_bytes / GB,
    }
    gb = comp["args"] + comp["out"] + comp["temp"] - comp["alias"]
    return WaterlinePrediction(gb=gb, source="memory_analysis",
                               components=comp).judge(capacity_gb)


# ------------------------------------------------------------- analytic

def _dtype_size(dtype) -> int:
    import jax.numpy as jnp
    return jnp.dtype(dtype).itemsize


def _per_token_dot_bytes(cfg, itemsize: int) -> int:
    """Bytes of ALL projection-matmul outputs for one token — the
    save_dots residency unit: q, k, v, attn-out, gate, up, down."""
    hd = cfg.head_dim or cfg.hidden_size // cfg.num_attention_heads
    nq, nkv = cfg.num_attention_heads, cfg.num_key_value_heads
    F = (getattr(cfg, "moe_ffn", None) or cfg.intermediate_size) \
        * max(getattr(cfg, "moe_top_k", 1), 1)
    elems = nq * hd + 2 * nkv * hd + cfg.hidden_size + 2 * F \
        + cfg.hidden_size
    return elems * itemsize


def analytic_waterline(cfg, *, batch: int, seq: int, ws: int = 1,
                       accum_steps: int = 1, state_precision: str = "full",
                       offload: str = "none", dense_grads: bool = True,
                       capacity_gb: float | None = None,
                       priors: dict | None = None,
                       mesh_plan=None
                       ) -> WaterlinePrediction:
    """Tensor-walk waterline model for one FSDP-style train step of
    ``cfg`` (any ``TransformerConfig``-shaped object) at global ``batch``
    × ``seq`` over ``ws`` devices.

    Phase model (per device): the peak is the at-rest state plus the
    policy-saved activations of ALL layers plus the scan-boundary
    residuals, plus the larger of one layer's working set and the loss
    buffers — layer workspace and loss-phase buffers never coexist, but
    remat-saved tensors live through both.  Optimizer state under
    ``offload`` in ("opt", "opt_act") counts one stacked-leaf pair of
    streaming headroom instead of full residency.

    ``priors`` is an ``export-memory-priors`` dict (see
    :func:`load_memory_priors`): its ``overall_ratio`` — median
    measured-ledger peak over analytic prediction across indexed runs —
    rescales the total the same way bench priors anchor the tuner, so
    the model recalibrates against ground truth without reweighing its
    own terms.

    ``mesh_plan`` (a ``parallel.composable.MeshPlan`` or anything with
    its ``param_shard_ways`` / ``opt_shard_ways`` / ``data_ways`` /
    ``tp`` attributes) replaces the flat-dp assumption: params at rest
    divide by the plan's param-shard ways (fsdp × tp × dp under W3),
    optimizer state by its opt-shard ways (W1+), the global batch by the
    data axes (dp × fsdp), and the per-layer working/saved activations
    by tp (Megatron shards the projection outputs).  ``mesh_plan=None``
    keeps the legacy flat-``ws`` law bit-for-bit."""
    itemsize = _dtype_size(getattr(cfg, "dtype", "bfloat16"))
    if mesh_plan is not None:
        param_ways = max(int(mesh_plan.param_shard_ways), 1)
        opt_ways = max(int(mesh_plan.opt_shard_ways), 1)
        data_ways = max(int(mesh_plan.data_ways), 1)
        tp_ways = max(int(getattr(mesh_plan, "tp", 1)), 1)
    else:
        param_ways = opt_ways = data_ways = ws
        tp_ways = 1
    P = cfg.param_count() if hasattr(cfg, "param_count") else 0
    params = P * itemsize / param_ways
    grads = params if dense_grads else 0.0

    # Adam moments: 2×params at the state dtype ("full" = params' dtype,
    # "int8" = ~1 byte/elem + per-row scales ≈ 9/8 byte).
    state_itemsize = itemsize if state_precision == "full" else 1.125
    opt = 2 * P * state_itemsize / opt_ways
    if offload in ("opt", "opt_act"):
        # parked on host; device cost = streaming headroom of roughly the
        # largest stacked leaf pair (mu+nu of one projection matrix stack)
        L = max(cfg.num_hidden_layers, 1)
        biggest = max(
            cfg.hidden_size * cfg.intermediate_size * L,
            cfg.vocab_size * cfg.hidden_size) * state_itemsize
        opt = 2 * biggest / opt_ways

    b = max(batch // data_ways, 1)              # per-device batch
    micro = max(b // max(accum_steps, 1), 1)    # per-microbatch rows
    H, L = cfg.hidden_size, cfg.num_hidden_layers
    hd = cfg.head_dim or H // cfg.num_attention_heads
    nq = cfg.num_attention_heads

    # scan-boundary residuals: one (micro, S, H) per layer survives the
    # forward under every remat policy
    boundaries = L * micro * seq * H * itemsize

    # policy-saved tensors (live through backward, additive with loss)
    policy = getattr(cfg, "remat_policy", "full")
    remat_on = getattr(cfg, "remat", True)
    dot_bytes = _per_token_dot_bytes(cfg, itemsize)
    saved = 0.0
    if not remat_on:
        saved = L * micro * seq * dot_bytes            # everything lives
    elif policy == "save_attn":
        saved = L * micro * seq * nq * hd * itemsize
    elif policy == "save_dots":
        saved = L * micro * seq * dot_bytes
    elif policy == "save_dots_q8":
        # int8 codes + per-row f32 scales ≈ 1.1 byte per saved element
        saved = L * micro * seq * dot_bytes / itemsize * 1.1
    if offload == "opt_act" and policy in ("save_attn", "save_dots_q8"):
        saved = 0.0                                    # parked on host
    precision = str(getattr(cfg, "matmul_precision", "bf16"))
    # low-precision matmuls (int8 STE or fp8 e4m3/e5m2) keep 1-byte
    # operand code copies for the bwd dots — same working-set shape, so
    # both precisions share the multiplier; they ride the saved-dots
    # budget when remat keeps those (save_dots_q8's saved tensors
    # already ARE the int8 codes: no extra)
    lp_mm = precision.startswith("int8") or precision.startswith("fp8")
    if lp_mm and policy == "save_dots":
        saved *= 1.5

    # one layer's transient working set (freed before the loss phase);
    # low-precision matmuls add the live microbatch's quantize buffers
    working = micro * seq * dot_bytes * (1.5 if lp_mm else 1.0)
    if getattr(cfg, "attention_impl", "xla") == "xla":
        # unfused attention materializes fp32 scores (B, n, S, S)
        working += micro * nq * seq * seq * 4
    # tp shards every projection output (and its heads) column-wise, so
    # both the policy-saved dots and the live working set divide by it
    saved /= tp_ways
    working /= tp_ways

    # loss-phase buffers: streamed vocab chunk (fp32 logits chunk + the
    # checkpointed backward's recompute) or the dense 3-spike trio
    chunk = getattr(cfg, "loss_vocab_chunk", None)
    V = cfg.vocab_size
    loss = micro * seq * (chunk or V) * 4 * (1.0 if chunk else 3.0)

    batch_bytes = b * seq * 4 * 2                      # int32 ids+labels
    total = (params + grads + opt + boundaries + saved
             + max(working, loss) + batch_bytes)
    comp = {
        "params": params / GB, "grads": grads / GB, "opt": opt / GB,
        "boundaries": boundaries / GB, "saved_activations": saved / GB,
        "layer_working": working / GB, "loss": loss / GB,
        "batch": batch_bytes / GB,
    }
    gb = total / GB
    if priors:
        try:
            ratio = float(priors.get("overall_ratio") or 0.0)
        except (TypeError, ValueError):
            ratio = 0.0
        if ratio > 0:
            gb *= ratio
            comp["priors_ratio"] = ratio
    return WaterlinePrediction(gb=gb, source="analytic",
                               components=comp).judge(capacity_gb)


def predict(cfg=None, *, step=None, args=(), capacity_gb=None,
            **analytic_kw) -> WaterlinePrediction:
    """One-call form: compile-based when a ``step`` (+ example args) is
    given and the backend can plan it, analytic from ``cfg`` otherwise —
    a compile that dies on a *non*-OOM error also degrades to analytic
    when a cfg is at hand (the 'compile itself OOMs host-side' case)."""
    if step is not None:
        try:
            return predict_from_step(step, *args, capacity_gb=capacity_gb)
        except Exception:  # noqa: BLE001 - analytic is the safety net
            if cfg is None:
                raise
    if cfg is None:
        raise ValueError("predict() needs a step or a model cfg")
    return analytic_waterline(cfg, capacity_gb=capacity_gb, **analytic_kw)
