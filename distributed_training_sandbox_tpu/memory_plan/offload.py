"""Contracted host offload: optimizer state (and named remat activations)
parked in host memory, streamed over PCIe around the step.

The mechanism is JAX memory kinds: a leaf placed under a
``NamedSharding(..., memory_kind="pinned_host")`` lives in host DRAM; a
``jax.device_put`` to the ``"device"`` kind *inside* a jitted step lowers
to a ``MoveToDevice`` custom call (and back, ``MoveToHost``) that XLA's
latency-hiding scheduler can overlap with compute.  ``analysis/hlo_lint``
used to classify every such custom call as a hot-path violation; with the
:class:`OffloadPlan` below the transfers become *declared* — the lint
count-checks them instead (see ``hlo_lint.check_host_transfers``).

Backends without a ``pinned_host`` memory space (the 8-way CPU CI mesh:
its only space IS host memory) degrade to an identity placement — the
step is bitwise-identical to no-offload, the plan records
``supported=False`` and declares zero transfers, and the contract lint
then *forbids* transfer custom calls, so the fallback is still checked.
"""

from __future__ import annotations

from dataclasses import dataclass, field

OFFLOAD_MODES = ("none", "opt", "opt_act")
HOST_KIND = "pinned_host"
DEVICE_KIND = "device"

# Checkpoint names offloadable per remat policy (the policies that save
# *named* tensors — the only ones save_and_offload_only_these_names can
# redirect to host).
OFFLOADABLE_REMAT_NAMES = {
    "save_attn": ("attn_out",),
    "save_dots_q8": ("dot_q8",),
}


def supports_host_offload(device=None) -> bool:
    """True when the backend exposes a ``pinned_host`` memory space next
    to device HBM (TPU; not the CPU sim, whose only space is host)."""
    import jax
    device = device or jax.devices()[0]
    try:
        kinds = {m.kind for m in device.addressable_memories()}
    except Exception:
        return False
    return HOST_KIND in kinds


@dataclass(frozen=True)
class OffloadPlan:
    """What one step's host-offload choreography is *declared* to do —
    produced by :func:`plan_offload` at step-build time, recorded into
    ``ContractContext.extra["offload"]`` so the contract lint can expect
    exactly these transfers and reject any others."""
    mode: str = "none"              # none | opt | opt_act
    supported: bool = False         # backend has a pinned_host space
    n_state_leaves: int = 0         # optimizer-state leaves parked on host
    state_bytes: int = 0            # bytes per direction per step (opt)
    act_names: tuple = field(default_factory=tuple)  # offloaded ckpt names

    def host_transfer_counts(self) -> dict:
        """Declared ``MoveToHost``/``MoveToDevice`` custom-call count
        ranges for the compiled step.  Site counts are ranges, not exact:
        XLA may fuse per-leaf moves or split them per shard, and the
        activation moves repeat per saved name — but zero transfers when
        offload is active (the annotation silently dropped) and any
        transfer when it is not are both violations."""
        if not (self.supported and self.mode != "none"):
            return {}
        n = self.n_state_leaves
        hi = 2 * n + 8 * len(self.act_names)
        return {"move_to_host": (1, max(hi, 1)),
                "move_to_device": (1, max(hi, 1))}

    def to_dict(self) -> dict:
        return {"mode": self.mode, "supported": self.supported,
                "n_state_leaves": self.n_state_leaves,
                "state_bytes": self.state_bytes,
                "act_names": list(self.act_names)}


def plan_offload(mode: str, opt_state=None, *, act_names=(),
                 supported: bool | None = None) -> OffloadPlan:
    """Declare the offload choreography for one step build.  ``opt_state``
    is the optimizer-state tree whose array leaves get parked on host
    (mode "opt"/"opt_act"); ``act_names`` the remat checkpoint names
    redirected to host (mode "opt_act")."""
    if mode not in OFFLOAD_MODES:
        raise ValueError(f"offload={mode!r}; choose from {OFFLOAD_MODES}")
    if supported is None:
        supported = supports_host_offload()
    if mode == "none":
        return OffloadPlan()
    import jax
    from ..utils.memory import tree_size_bytes
    leaves = [l for l in jax.tree.leaves(opt_state)
              if hasattr(l, "shape") and getattr(l, "ndim", 0) > 0]
    return OffloadPlan(
        mode=mode, supported=supported, n_state_leaves=len(leaves),
        state_bytes=tree_size_bytes(opt_state) if opt_state is not None
        else 0,
        act_names=tuple(act_names) if mode == "opt_act" else ())


def _retarget(leaf, kind: str):
    """The leaf's own sharding with its memory kind swapped — keeps the
    partition spec (and mesh) exactly as the strategy placed it."""
    import jax
    sh = getattr(leaf, "sharding", None)
    if sh is None or not hasattr(sh, "with_memory_kind"):
        return None
    return sh.with_memory_kind(kind)


def offload_tree(tree, kind: str = HOST_KIND):
    """``device_put`` every array leaf of ``tree`` into the ``kind``
    memory space, preserving each leaf's partition spec.  Outside jit
    this is the at-rest placement (park the Adam moments on host between
    steps); scalar/unsharded leaves pass through untouched."""
    import jax

    def put(l):
        target = _retarget(l, kind)
        if target is None or getattr(l, "ndim", 0) == 0:
            return l
        return jax.device_put(l, target)

    return jax.tree.map(put, tree)


def stream_tree(tree, kind: str):
    """The *in-jit* transfer: ``device_put`` each leaf toward ``kind``
    memory, lowering to MoveToDevice/MoveToHost custom calls the
    scheduler can hide.  Identity on scalars (the Adam step counter
    stays wherever jit wants it)."""
    import jax

    def put(l):
        if getattr(l, "ndim", 0) == 0:
            return l
        from jax._src.sharding_impls import TransferToMemoryKind
        return jax.device_put(l, TransferToMemoryKind(kind))

    return jax.tree.map(put, tree)
