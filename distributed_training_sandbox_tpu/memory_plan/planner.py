"""Auto-fit planner: search remat × accumulation × quantization × offload
for the best predicted-fitting config under an HBM budget.

Given a target (model geometry, global batch, sequence length, device
count) and a budget in GB, the planner enumerates the discrete knob space

    remat_policy  {full, save_attn, save_dots, save_dots_q8}
  × accum_steps   {1, 2, 4, ...}        (must divide the per-device batch)
  × matmul        {bf16, int8_bwd}
  × state         {full, int8}
  × offload       {none, opt, opt_act}

predicts each candidate's waterline with the *analytic* predictor (no
lowering — rejection is pre-compile by construction), drops everything
over budget, and ranks the survivors by modeled throughput: measured
step-time priors from bench JSON artifacts when a row with the same knobs
exists, a relative-speed model calibrated on BENCH_r01–r05 otherwise.
An optional ``verify`` hook re-checks the winner with the compile-based
predictor (``predict_from_step``) before anyone commits real time to it.
"""

from __future__ import annotations

import glob
import json
import re
from dataclasses import dataclass, field, replace as _dc_replace

from .predictor import WaterlinePrediction, analytic_waterline

REMAT_POLICIES = ("full", "save_attn", "save_dots", "save_dots_q8")
QUANT_CHOICES = ("bf16", "int8_bwd", "fp8")
STATE_CHOICES = ("full", "int8")
OFFLOAD_CHOICES = ("none", "opt")

# Relative step-speed multipliers, calibrated on the measured BENCH_r03–r05
# matrix (SMOLLM3_3B_L8 @ seq 8192, v5e): save_dots 110.1 vs full 103.6
# bf16 TFLOPS; int8_bwd 122.0 vs 103.6; s8 state ~parity (126.2 vs 125.7);
# q8-saved dots give ~most of save_dots' win back to the round-trip.
_REMAT_SPEED = {"full": 1.00, "save_attn": 1.03, "save_dots": 1.06,
                "save_dots_q8": 1.045}
# fp8 multipliers are CPU-tier placeholders pending a TPU-measured row
# (no fp8 units on v5e — see ops/quant.py), so they sit strictly BELOW
# the measured int8_bwd anchor: a config no bench row has ever timed
# must not outrank one a row has — the same measured-beats-multiplier
# pessimism the tuner cost model applies.  Internal ordering kept:
# delayed scaling saves the per-step amax reduction over dynamic, the
# hand Pallas kernel trails XLA (matching the measured int8 kernel gap).
_QUANT_SPEED = {"bf16": 1.00, "int8_bwd": 1.18, "fp8": 1.10,
                "fp8_delayed": 1.11, "fp8_pallas": 1.05}
_STATE_SPEED = {"full": 1.00, "int8": 1.00}
# host offload pays PCIe streaming; activation offload pays it per layer
_OFFLOAD_SPEED = {"none": 1.00, "opt": 0.97, "opt_act": 0.90}
_ACCUM_OVERHEAD = 0.02     # per extra microbatch: scan + carry update cost


@dataclass(frozen=True)
class Candidate:
    """One point of the planner's discrete knob space."""
    remat_policy: str = "full"
    accum_steps: int = 1
    matmul_precision: str = "bf16"
    state_precision: str = "full"
    offload: str = "none"

    def label(self) -> str:
        parts = [self.remat_policy]
        if self.matmul_precision != "bf16":
            parts.append(self.matmul_precision)
        if self.state_precision != "full":
            parts.append("s8")
        if self.accum_steps > 1:
            parts.append(f"accum{self.accum_steps}")
        if self.offload != "none":
            parts.append(f"offload_{self.offload}")
        return "+".join(parts)

    def apply_to(self, cfg):
        """The model config with this candidate's knobs applied
        (``accum_steps``/``state_precision``/``offload`` are step-factory
        knobs — read them off the candidate when building the step)."""
        over = {"remat_policy": self.remat_policy,
                "matmul_precision": self.matmul_precision}
        if self.offload == "opt_act":
            over["offload_activations"] = True
        return _dc_replace(cfg, **over)


@dataclass
class PlannedCandidate:
    candidate: Candidate
    prediction: WaterlinePrediction
    fits: bool
    score: float                   # modeled relative throughput
    prior: dict | None = None      # measured bench row backing the score
    est_step_ms: float | None = None   # absolute, when TFLOPS-anchored

    def to_dict(self) -> dict:
        return {"config": self.candidate.label(),
                **self.prediction.to_dict(),
                "fits": self.fits, "modeled_speed": round(self.score, 4),
                "est_step_ms": round(self.est_step_ms, 1)
                if self.est_step_ms else None,
                "prior": (self.prior or {}).get("config")}


@dataclass
class Plan:
    best: PlannedCandidate | None
    rows: list = field(default_factory=list)     # every candidate, ranked
    budget_gb: float | None = None

    def to_dict(self) -> dict:
        return {"budget_gb": self.budget_gb,
                "chosen": self.best.to_dict() if self.best else None,
                "candidates": [r.to_dict() for r in self.rows]}

    def summary(self) -> str:
        n_fit = sum(r.fits for r in self.rows)
        head = (f"{n_fit}/{len(self.rows)} candidates fit "
                f"budget {self.budget_gb:.2f} GB"
                if self.budget_gb is not None
                else f"{len(self.rows)} candidates (no budget)")
        if self.best is None:
            return f"{head}; NO FITTING CONFIG"
        return (f"{head}; chose {self.best.candidate.label()} "
                f"(predicted {self.best.prediction.gb:.2f} GB)")


class NoFittingConfig(RuntimeError):
    """Every candidate's predicted waterline exceeds the budget."""

    def __init__(self, plan: Plan):
        self.plan = plan
        tight = min(plan.rows, key=lambda r: r.prediction.gb) \
            if plan.rows else None
        msg = f"no candidate fits {plan.budget_gb:.2f} GB"
        if tight is not None:
            msg += (f"; smallest is {tight.candidate.label()} at "
                    f"{tight.prediction.gb:.2f} GB — shrink the batch "
                    f"or raise --hbm-budget-gb")
        super().__init__(msg)


def enumerate_candidates(*, per_device_batch: int,
                         remat=REMAT_POLICIES,
                         accum=(1, 2, 4),
                         quant=QUANT_CHOICES,
                         state=STATE_CHOICES,
                         offload=OFFLOAD_CHOICES) -> list[Candidate]:
    """The cross product, pruned to accum splits that divide the
    per-device batch (the step factory's own requirement)."""
    out = []
    for r in remat:
        for a in accum:
            if a < 1 or (per_device_batch % a):
                continue
            for q in quant:
                for s in state:
                    for o in offload:
                        if o == "opt_act" and r not in ("save_attn",
                                                        "save_dots_q8"):
                            continue  # needs a named-save remat policy
                        out.append(Candidate(r, a, q, s, o))
    return out


def modeled_speed(c: Candidate, prior: dict | None = None) -> float:
    """Relative throughput of one candidate.  A measured prior row (same
    remat/quant/state knobs, any batch) anchors the score directly via
    its TFLOPS; the calibrated multiplier model covers the rest of the
    space.  Offload and accumulation never appear in bench row names, so
    their multipliers apply on top of an anchored score too — otherwise
    an offloaded twin would tie its no-offload prior and win on the
    waterline tie-break despite the PCIe cost."""
    accum = 1.0 + _ACCUM_OVERHEAD * (c.accum_steps - 1)
    residual = _OFFLOAD_SPEED.get(c.offload, 1.0) / accum
    if prior and prior.get("tflops_per_device"):
        return float(prior["tflops_per_device"]) * residual
    speed = (_REMAT_SPEED.get(c.remat_policy, 1.0)
             * _QUANT_SPEED.get(c.matmul_precision, 1.0)
             * _STATE_SPEED.get(c.state_precision, 1.0))
    return speed * residual


# ---------------------------------------------------------- bench priors

# bench.py row names: explicit[_reshard|_noreshard][_save_*]
# [_int8(_bwd)|_fp8(_delayed|_pallas)][_s8][_b{N}x][_mesh{D}x{F}x{T}] —
# parsed back into candidate knobs so measured rows can anchor the
# planner's throughput model.
_NAME_BSCALE = re.compile(r"_b(\d+)x$")
_NAME_MESH = re.compile(r"_mesh(\d+(?:x\d+){2,3})")


def parse_bench_config_name(name: str) -> dict | None:
    """Knob dict for one bench matrix row name, or None for rows that are
    not explicit-FSDP knob points (auto variant, sync-step A/B, ring)."""
    if not name.startswith("explicit"):
        return None
    if any(t in name for t in ("syncstep", "ring", "noreshard")):
        return None
    rest = name.removeprefix("explicit").removeprefix("_reshard")
    # mesh token first: it trails the name, and the batch-scale regex
    # is end-anchored
    mesh_shape = None
    mm = _NAME_MESH.search(rest)
    if mm:
        mesh_shape = tuple(int(s) for s in mm.group(1).split("x"))
        rest = rest[:mm.start()] + rest[mm.end():]
    m = _NAME_BSCALE.search(rest)
    bscale = int(m.group(1)) if m else 1
    if m:
        rest = rest[:m.start()]
    knobs = {"remat_policy": "full", "matmul_precision": "bf16",
             "state_precision": "full", "batch_scale": bscale}
    if mesh_shape is not None:
        # only mesh rows carry the key, so legacy names parse to the
        # exact dict shape they always did; read with .get()
        knobs["mesh_shape"] = mesh_shape
    if "_s8" in rest:
        knobs["state_precision"] = "int8"
        rest = rest.replace("_s8", "")
    if "_int8" in rest:
        knobs["matmul_precision"] = "int8_bwd"
        rest = rest.replace("_int8_bwd", "").replace("_int8", "")
    elif "_fp8" in rest:
        # longest token first so "fp8" never eats its variants' suffixes
        for tok in ("fp8_delayed", "fp8_pallas", "fp8"):
            if f"_{tok}" in rest:
                knobs["matmul_precision"] = tok
                rest = rest.replace(f"_{tok}", "")
                break
    rest = rest.strip("_")
    if rest:
        if rest not in REMAT_POLICIES:
            return None
        knobs["remat_policy"] = rest
    return knobs


def load_bench_priors(paths=None) -> list[dict]:
    """Measured matrix rows from bench JSON artifacts (the checked-in
    ``BENCH_*.json`` / ``bench_matrix_tpu.json``), each annotated with
    its parsed knobs — the planner's step-time priors."""
    if paths is None:
        paths = sorted(glob.glob("BENCH_*.json")) \
            + [p for p in ("bench_matrix_tpu.json",)
               if glob.glob(p)]
    rows = []
    from ..telemetry.report import load_baseline_rows
    for p in paths:
        try:
            loaded = load_baseline_rows(str(p))
        except Exception:  # noqa: BLE001 - priors are best-effort
            continue
        for r in loaded:
            name = r.get("config")
            if not name or r.get("error"):
                continue
            knobs = parse_bench_config_name(str(name))
            if knobs and r.get("tflops_per_device"):
                rows.append({**r, "knobs": knobs})
    return rows


def _find_prior(c: Candidate, priors, per_device_batch: int,
                base_batch: int | None = None) -> dict | None:
    """Latest measured row with this candidate's exact knobs; prefers a
    matching batch scale when ``base_batch`` is known."""
    want_mesh = getattr(c, "mesh_shape", None)
    hits = [p for p in priors or [] if p["knobs"]["remat_policy"]
            == c.remat_policy
            and p["knobs"]["matmul_precision"] == c.matmul_precision
            and p["knobs"]["state_precision"] == c.state_precision
            and (tuple(p["knobs"]["mesh_shape"])
                 if p["knobs"].get("mesh_shape") else None) == want_mesh]
    if not hits:
        return None
    if base_batch:
        exact = [p for p in hits
                 if p["knobs"]["batch_scale"] * base_batch
                 == per_device_batch]
        if exact:
            hits = exact
    return hits[-1]


# ---------------------------------------------------------------- plan()

def plan(cfg, *, batch: int, seq: int, ws: int = 1,
         hbm_budget_gb: float | None = None, candidates=None,
         priors=None, prior_base_batch: int | None = None,
         verify=None) -> Plan:
    """Rank the knob space for ``cfg`` at global ``batch`` × ``seq`` over
    ``ws`` devices and pick the best predicted-fitting candidate.

    Every candidate is costed with the analytic predictor only — a
    candidate over ``hbm_budget_gb`` is rejected *pre-compile* with its
    predicted waterline attached.  ``verify(candidate) -> step, args``
    optionally re-checks the winner compile-side (demoting it and
    promoting the runner-up on a compiler OOM).  Raises
    :class:`NoFittingConfig` when nothing fits."""
    pdb = max(batch // ws, 1)
    if candidates is None:
        candidates = enumerate_candidates(per_device_batch=pdb)
    rows = []
    for c in candidates:
        pred = analytic_waterline(
            c.apply_to(cfg), batch=batch, seq=seq, ws=ws,
            accum_steps=c.accum_steps, state_precision=c.state_precision,
            offload=c.offload, capacity_gb=hbm_budget_gb)
        fits = pred.fits if pred.fits is not None else True
        prior = _find_prior(c, priors, pdb, prior_base_batch)
        row = PlannedCandidate(c, pred, fits, modeled_speed(c, prior),
                               prior)
        if prior:
            # prior-anchored score IS TFLOPS/device: convert to an
            # absolute step-time estimate via the analytic FLOPs model
            from ..utils.flops import get_model_flops_per_token
            ft = get_model_flops_per_token(c.apply_to(cfg), seq)
            row.est_step_ms = (batch * seq * ft
                               / (row.score * 1e12 * ws) * 1e3)
        rows.append(row)
    rows.sort(key=lambda r: (-r.fits, -r.score, r.prediction.gb))
    fitting = [r for r in rows if r.fits]
    result = Plan(best=None, rows=rows, budget_gb=hbm_budget_gb)
    while fitting:
        head = fitting[0]
        if verify is None:
            result.best = head
            return result
        from .predictor import predict_from_step
        step, args = verify(head.candidate)
        compiled = predict_from_step(step, *args,
                                     capacity_gb=hbm_budget_gb)
        head.prediction = compiled
        if compiled.fits is not False:
            result.best = head
            return result
        head.fits = False           # compiler overruled the analytic fit
        fitting.pop(0)
    raise NoFittingConfig(result)
