"""Sequence classification on the transformer trunk — twin of the
reference's DDP payload model, ``AutoModelForSequenceClassification``
over SmolLM2-360M with 2 labels (``DDP/training_utils/utils.py:17-29``).

HF's causal-LM classification recipe, reproduced functionally: run the
decoder trunk, pool the hidden state of the LAST NON-PAD token, project to
``num_labels`` logits.  With right padding and causal attention no pad mask
is needed in the trunk: pads sit *after* the real tokens, and causal
masking already prevents any real position from attending forward into
them, so real-token hidden states are bitwise independent of pad content;
the pooled readout never touches a pad position's state.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from . import transformer as T


def init_classifier_params(key: jax.Array, cfg: T.TransformerConfig,
                           num_labels: int = 2) -> dict:
    """Trunk params + a zero-init classification head (HF's score layer is
    a bias-free Linear; zero init gives uniform initial class probs)."""
    kt, _ = jax.random.split(key)
    return {
        "trunk": T.init_params(kt, cfg),
        "cls_head": jnp.zeros((cfg.hidden_size, num_labels), cfg.dtype),
    }


def classifier_logits(params: dict, input_ids: jax.Array,
                      attention_mask: jax.Array,
                      cfg: T.TransformerConfig, *, layer_hook=None,
                      return_aux: bool = False):
    """(B, S) ids + 0/1 mask → (B, num_labels) logits: trunk → last-non-pad
    pool → head.  ``return_aux`` adds the trunk's summed auxiliary loss
    (MoE load balance; 0 for dense trunks)."""
    h, aux = T.hidden_states(params["trunk"], input_ids, cfg,
                             layer_hook=layer_hook,
                             return_aux=True)           # (B, S, H)
    last = jnp.maximum(jnp.sum(attention_mask, axis=-1) - 1, 0)  # (B,)
    pooled = jnp.take_along_axis(
        h, last[:, None, None].astype(jnp.int32), axis=1)[:, 0]  # (B, H)
    logits = pooled @ params["cls_head"].astype(h.dtype)
    return (logits, aux) if return_aux else logits


def classification_loss(params: dict, batch, cfg: T.TransformerConfig,
                        *, layer_hook=None) -> jax.Array:
    """Mean softmax cross-entropy.  ``batch`` = dict with ``input_ids``
    (B, S) int32, ``attention_mask`` (B, S) 0/1, ``labels`` (B,) int32 —
    the collate contract of ``data.classification.pad_collate``."""
    logits, aux = classifier_logits(params, batch["input_ids"],
                                    batch["attention_mask"], cfg,
                                    layer_hook=layer_hook,
                                    return_aux=True)
    logits = logits.astype(jnp.float32)
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, batch["labels"][:, None],
                               axis=-1)[:, 0]
    loss = jnp.mean(logz - gold)
    if cfg.n_experts:
        loss = loss + cfg.moe_aux_weight * aux
    return loss


def classification_accuracy(params: dict, batch,
                            cfg: T.TransformerConfig) -> jax.Array:
    logits = classifier_logits(params, batch["input_ids"],
                               batch["attention_mask"], cfg)
    return jnp.mean((jnp.argmax(logits, axis=-1)
                     == batch["labels"]).astype(jnp.float32))
