from .mlp import init_mlp, mlp_apply, zero_toy_mlp, pp_toy_mlp  # noqa: F401
from .transformer import (  # noqa: F401
    TransformerConfig, SMOLLM3_3B, SMOLLM3_350M, TINY_LM,
    init_params, forward, lm_loss, model_flops_per_token)
