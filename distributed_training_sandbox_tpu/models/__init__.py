from .mlp import init_mlp, mlp_apply, zero_toy_mlp, pp_toy_mlp  # noqa: F401
