from .mlp import init_mlp, mlp_apply, zero_toy_mlp, pp_toy_mlp  # noqa: F401
from .transformer import (  # noqa: F401
    TransformerConfig, SMOLLM3_3B, SMOLLM3_3B_L8, SMOLLM3_350M, TINY_LM,
    QWEN3_4B, QWEN3_4B_L6, LLAMA32_1B, LLAMA31_8B,
    init_params, forward, lm_loss, model_flops_per_token)
from .generate import (  # noqa: F401
    generate, init_cache, KVCache, quantize_decode_params)
from .classifier import (  # noqa: F401
    init_classifier_params, classifier_logits, classification_loss,
    classification_accuracy)

# CLI name -> TransformerConfig attribute, shared by every script.
MODEL_REGISTRY = {
    "smollm3-3b": "SMOLLM3_3B",
    "smollm3-3b-l8": "SMOLLM3_3B_L8",
    "smollm3-350m": "SMOLLM3_350M",
    "qwen3-4b": "QWEN3_4B",
    "qwen3-4b-l6": "QWEN3_4B_L6",
    "llama3.2-1b": "LLAMA32_1B",
    "llama3.1-8b": "LLAMA31_8B",
    "tiny": "TINY_LM",
    "tiny8": "TINY_LM_L8",
    "corpus-70m": "CORPUS_LM",
    "corpus-350m": "CORPUS_350M",
}
