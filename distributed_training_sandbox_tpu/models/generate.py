"""Autoregressive decoding with a KV cache — the inference face of the
transformer.

The reference is a training course and never decodes (its models run
with ``use_cache=False``, ``fsdp/train_fsdp.py:61-64``); a framework a
user can switch to needs the other half.  TPU-shaped design:

  * the cache is a fixed-capacity pytree ``(L, B, S_max, n_kv, hd)`` —
    static shapes end to end, so the whole decode loop is ONE compiled
    ``lax.scan`` (no per-token retrace, no dynamic shapes);
  * prefill = the normal batched forward (MXU-friendly) that also
    writes the cache via ``lax.dynamic_update_slice``;
  * decode steps run single-query attention against the cache with a
    length mask (positions ≥ the current length contribute nothing);
  * greedy or temperature sampling, PRNG threaded through the scan.

Works under any single-device jit; GQA, RoPE(+NoPE schedule) and the
tied unembedding reuse the training model's code so the two paths
cannot drift.

**int8 decode** (``quantize_decode_params``): decode at real batch sizes
is HBM-bandwidth-bound — every step reads every weight byte.  Weights
are static for the whole generate call, so they are quantized ONCE to
int8 (+ per-column scales) and stored that way; every projection then
reads half the bytes (``ops/quant.QuantizedWeight`` routed through the
same shared ``_dense`` dispatch).  The tied unembedding gets its own
int8 copy (the (H, vocab) matmul is the single largest weight read of a
decode step); the embedding table stays bf16 for the lookup, and norm
scales stay bf16 (negligible bytes, outsized numerics).
"""

from __future__ import annotations

import math
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from . import transformer as T


class KVCache(NamedTuple):
    k: jax.Array      # (L, B, S_max, n_kv, hd)
    v: jax.Array      # (L, B, S_max, n_kv, hd)
    length: jax.Array  # () int32 — tokens currently cached


def init_cache(cfg: T.TransformerConfig, batch: int,
               max_len: int) -> KVCache:
    L, nkv, hd = (cfg.num_hidden_layers, cfg.num_key_value_heads,
                  cfg.resolved_head_dim)
    shape = (L, batch, max_len, nkv, hd)
    return KVCache(k=jnp.zeros(shape, cfg.dtype),
                   v=jnp.zeros(shape, cfg.dtype),
                   length=jnp.zeros((), jnp.int32))


# Projection leaves quantized for decode; stacked (L, K, N) → per-layer
# scales.  Norm scales (1-D per layer) stay bf16.
_QUANT_LAYER_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def quantize_decode_params(params: dict, cfg: T.TransformerConfig) -> dict:
    """bf16 training params → decode params with every projection weight
    stored int8 (``ops/quant.QuantizedWeight``) and a dedicated int8 copy
    of the unembedding under ``"unembed_q"``.  Quantize once at cache
    build; weight bytes per decode step roughly halve (the decode
    roofline is the weight read).  MoE configs keep their expert banks
    (and router) bf16 — the grouped dispatch inspects weight shapes
    directly; dense projections still quantize."""
    from ..ops.quant import quantize_weight

    layers = dict(params["layers"])
    keys = (_QUANT_LAYER_KEYS if not cfg.n_experts
            else ("wq", "wk", "wv", "wo"))
    for k in keys:
        if k in layers:
            layers[k] = quantize_weight(layers[k], contract_axis=-2)
    out = {**params, "layers": layers}
    # The unembedding matmul is x @ W with W = (H, vocab) — quantize that
    # orientation directly (contraction over H).
    w_vocab = T._output_embedding(params, cfg)          # (vocab, H) rows
    out["unembed_q"] = quantize_weight(w_vocab.T, contract_axis=-2)
    out.pop("lm_head", None)   # superseded by unembed_q for decode
    return out


def _cached_layer_body(x, layer, *, cfg, cos, sin, use_rope, li,
                       cache: KVCache, start):
    """One decoder layer that READS/WRITES the cache: the training
    layer's SHARED projection/MLP helpers (``transformer._qkv_proj`` /
    ``_mlp_block`` — one implementation, no drift) with attention run
    against [0, start + S) of the cache instead of the local chunk.
    x: (B, S, H) with S = prefill length or 1."""
    B, S, H = x.shape
    hd = cfg.resolved_head_dim
    nq, nkv = cfg.num_attention_heads, cfg.num_key_value_heads
    dense = T._dense(cfg)

    r = T.rms_norm(x, layer["ln1"], cfg.rms_norm_eps)
    q, k, v = T._qkv_proj(r, layer, cfg=cfg, cos=cos, sin=sin,
                          use_rope=use_rope)

    ck = lax.dynamic_update_slice(cache.k[li], k, (0, start, 0, 0))
    cv = lax.dynamic_update_slice(cache.v[li], v, (0, start, 0, 0))
    new_cache = (ck, cv)

    # attention over the cache: visible = pos_kv <= pos_q (absolute)
    S_max = ck.shape[1]
    rep = nq // nkv
    kf = jnp.repeat(ck, rep, axis=2) if rep != 1 else ck
    vf = jnp.repeat(cv, rep, axis=2) if rep != 1 else cv
    scores = jnp.einsum("bqnh,bknh->bnqk", q.astype(jnp.float32),
                        kf.astype(jnp.float32)) / math.sqrt(hd)
    pos_q = start + jnp.arange(S)
    pos_kv = jnp.arange(S_max)
    vis = pos_kv[None, :] <= pos_q[:, None]
    scores = jnp.where(vis[None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    attn = jnp.einsum("bnqk,bknh->bqnh", probs,
                      vf.astype(jnp.float32)).astype(x.dtype)
    x = x + dense(attn.reshape(B, S, nq * hd), layer["wo"])

    r = T.rms_norm(x, layer["ln2"], cfg.rms_norm_eps)
    mlp, _aux = T._mlp_block(r, layer, cfg=cfg)
    return x + mlp, new_cache


def _forward_cached(params, ids, cfg, cache: KVCache, start):
    """ids (B, S) → (last-position logits (B, V) fp32, cache') using /
    refreshing the cache; ``start`` = absolute position of ids[:, 0].
    Only the LAST position's logits are computed — decoding never needs
    the rest, and a full (B, S, vocab) fp32 prefill buffer would be the
    exact memory spike the streamed training loss exists to avoid."""
    B, S = ids.shape
    x = params["embed"].astype(cfg.dtype)[ids]
    cos, sin = T._rope_tables(S, cfg.resolved_head_dim, cfg.rope_theta,
                              start)
    flags = T._rope_flags(cfg)

    def body(x, scanned):
        li, layer, use_rope = scanned
        x, (ck, cv) = _cached_layer_body(
            x, layer, cfg=cfg, cos=cos, sin=sin, use_rope=use_rope,
            li=li, cache=cache, start=start)
        return x, (ck, cv)

    idx = jnp.arange(cfg.num_hidden_layers)
    x, (ks, vs) = lax.scan(body, x, (idx, params["layers"], flags))
    x = T.rms_norm(x[:, -1:], params["final_norm"], cfg.rms_norm_eps)
    uq = params.get("unembed_q")
    if uq is not None:       # int8 decode: the (H, vocab) read halves
        from ..ops.quant import prequantized_dense
        logits = prequantized_dense(x, uq)[:, 0]
    else:
        logits = (x @ T._output_embedding(params, cfg).T)[:, 0]
    new = KVCache(k=ks, v=vs, length=start + S)
    return logits.astype(jnp.float32), new


@partial(jax.jit, static_argnames=("cfg", "max_new_tokens",
                                   "temperature"))
def generate(params, prompt_ids, cfg: T.TransformerConfig, *,
             max_new_tokens: int = 32, temperature: float = 0.0,
             rng: jax.Array | None = None):
    """Decode ``max_new_tokens`` after ``prompt_ids`` (B, S_prompt).

    temperature 0 = greedy argmax; > 0 = categorical sampling — ``rng``
    is then REQUIRED (a silent default key would return identical
    "samples" on every call).  Returns (B, max_new_tokens) int32.  One
    prefill forward + one scanned decode loop — two compiled programs
    total, static shapes throughout.
    """
    if temperature > 0.0 and rng is None:
        raise ValueError("temperature > 0 samples stochastically: pass "
                         "rng=jax.random.PRNGKey(...) explicitly")
    if rng is None:
        rng = jax.random.PRNGKey(0)   # unused by greedy picks
    B, S0 = prompt_ids.shape
    S_max = S0 + max_new_tokens
    cache = init_cache(cfg, B, S_max)
    logits, cache = _forward_cached(params, prompt_ids, cfg, cache, 0)

    def pick(logits_1, key):
        if temperature == 0.0:
            return jnp.argmax(logits_1, axis=-1).astype(jnp.int32)
        return jax.random.categorical(
            key, logits_1 / temperature, axis=-1).astype(jnp.int32)

    tok0 = pick(logits, rng)

    def step(carry, key):
        tok, cache = carry
        logits, cache = _forward_cached(params, tok[:, None], cfg,
                                        cache, cache.length)
        nxt = pick(logits, key)
        return (nxt, cache), nxt

    # max_new_tokens - 1 scanned steps: tok0 came from the prefill
    # logits, and each step emits the token it computes — no wasted
    # final forward (the r3 advisor's finding on this loop).
    keys = jax.random.split(jax.random.fold_in(rng, 1),
                            max_new_tokens - 1)
    (_, _), toks = lax.scan(step, (tok0, cache), keys)
    toks = jnp.concatenate([tok0[None], toks], axis=0)
    return toks.swapaxes(0, 1)   # (B, max_new_tokens)
