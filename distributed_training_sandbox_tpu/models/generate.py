"""Autoregressive decoding with a KV cache — the inference face of the
transformer.

The reference is a training course and never decodes (its models run
with ``use_cache=False``, ``fsdp/train_fsdp.py:61-64``); a framework a
user can switch to needs the other half.  TPU-shaped design:

  * the cache is a fixed-capacity pytree of per-layer HEAD-MAJOR
    ``(B, n_kv, S_max, hd)`` buffers — static shapes end to end, so the
    whole decode loop is ONE compiled ``lax.scan`` (no per-token
    retrace, no dynamic shapes);
  * prefill = the normal batched forward (MXU-friendly) that also
    writes the cache via ``lax.dynamic_update_slice``;
  * decode steps run single-query attention against the cache with a
    length mask (positions ≥ the current length contribute nothing);
  * greedy or temperature sampling, PRNG threaded through the scan.

Works under any single-device jit; GQA, RoPE(+NoPE schedule) and the
tied unembedding reuse the training model's code so the two paths
cannot drift.

**int8 decode** (``quantize_decode_params``): decode at real batch sizes
is HBM-bandwidth-bound — every step reads every weight byte.  Weights
are static for the whole generate call, so they are quantized ONCE to
int8 (+ per-column scales) and stored that way; every projection then
reads half the bytes (``ops/quant.QuantizedWeight`` routed through the
same shared ``_dense`` dispatch).  The tied unembedding gets its own
int8 copy (the (H, vocab) matmul is the single largest weight read of a
decode step); the embedding table stays bf16 for the lookup, and norm
scales stay bf16 (negligible bytes, outsized numerics).
"""

from __future__ import annotations

import math
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.collectives import axis_size

from . import transformer as T


class KVCache(NamedTuple):
    """Per-layer cache buffers (tuples of L arrays, each HEAD-MAJOR
    (B, n_kv, S_max, hd)) rather than one stacked (L, ...) array: the
    stacked layout made every decode step pay a dynamic-slice COPY of
    each layer's cache (indexing ``cache.k[li]`` inside the layer scan)
    plus a full re-stack into the scan's ys — ~3× the unavoidable
    cache-read traffic, measured as the r4 long-prompt gap (0.50 of
    roofline at prompt 2048).  With per-layer buffers the layer loop is
    unrolled (static layer index), ``dynamic_update_slice`` writes only
    the new token column in place, and the attention einsum reads the
    buffer directly.  HEAD-major (heads before positions) matches the
    attention dot's batch-dim layout — position-major made XLA
    materialize a transposed copy of the whole cache every step (the
    residual bf16 long-prompt gap after the per-layer rewrite).

    ``k_scale``/``v_scale``: per-(batch, head, position) fp32 absmax
    scales when the cache is stored int8 (``quantized=True``) — half the
    cache-read bytes, the decode twin of the int8 weight path; None for
    the bf16 cache."""
    k: tuple          # L × (B, n_kv, S_max, hd) cfg.dtype or int8
    v: tuple          # L × (B, n_kv, S_max, hd)
    k_scale: tuple | None   # L × (B, n_kv, S_max, 1) f32 (int8 only)
    v_scale: tuple | None
    length: jax.Array  # () int32 — tokens currently cached


def init_cache(cfg: T.TransformerConfig, batch: int,
               max_len: int, tp: int = 1,
               quantized: bool = False) -> KVCache:
    """``tp`` > 1: the TENSOR-PARALLEL cache — each rank caches only its
    ``n_kv/tp`` local heads (the KV memory and the per-step cache read
    both shrink by tp, the point of TP-sharded decode)."""
    L, nkv, hd = (cfg.num_hidden_layers, cfg.num_key_value_heads,
                  cfg.resolved_head_dim)
    shape = (batch, nkv // tp, max_len, hd)
    dt = jnp.int8 if quantized else cfg.dtype
    zeros = lambda: tuple(jnp.zeros(shape, dt) for _ in range(L))
    scales = lambda: (tuple(jnp.ones(shape[:-1] + (1,), jnp.float32)
                            for _ in range(L)) if quantized else None)
    return KVCache(k=zeros(), v=zeros(), k_scale=scales(),
                   v_scale=scales(), length=jnp.zeros((), jnp.int32))


# Projection leaves quantized for decode; stacked (L, K, N) → per-layer
# scales.  Norm scales (1-D per layer) stay bf16.
_QUANT_LAYER_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def quantize_decode_params(params: dict, cfg: T.TransformerConfig) -> dict:
    """bf16 training params → decode params with every projection weight
    stored int8 (``ops/quant.QuantizedWeight``) and a dedicated int8 copy
    of the unembedding under ``"unembed_q"``.  Quantize once at cache
    build; weight bytes per decode step roughly halve (the decode
    roofline is the weight read).  MoE configs keep their expert banks
    (and router) bf16 — the grouped dispatch inspects weight shapes
    directly; dense projections still quantize."""
    from ..ops.quant import quantize_weight

    layers = dict(params["layers"])
    keys = (_QUANT_LAYER_KEYS if not cfg.n_experts
            else ("wq", "wk", "wv", "wo"))
    for k in keys:
        if k in layers:
            layers[k] = quantize_weight(layers[k], contract_axis=-2)
    out = {**params, "layers": layers}
    # The unembedding matmul is x @ W with W = (H, vocab) — quantize that
    # orientation directly (contraction over H).
    w_vocab = T._output_embedding(params, cfg)          # (vocab, H) rows
    out["unembed_q"] = quantize_weight(w_vocab.T, contract_axis=-2)
    out.pop("lm_head", None)   # superseded by unembed_q for decode
    return out


def _quant_kv(t):
    """Row quantization over the LAST axis: ``(..., D)`` →
    ``(int8 (..., D), f32 (..., 1) scales)`` via the shared symmetric
    absmax quantizer (``ops.quant.quantize_int8``).  Used on head-major
    K/V tensors (rows over hd), on q (rows over hd), and on the
    v-scaled probs (rows over the cache-position axis)."""
    from ..ops.quant import quantize_int8
    return quantize_int8(t, axis=-1)


def _cached_layer_body(x, layer, *, cfg, cos, sin, use_rope,
                       ck, cv, ck_s, cv_s, start, tp_axis=None):
    """One decoder layer that READS/WRITES its cache buffers: the
    training layer's SHARED projection/MLP helpers
    (``transformer._qkv_proj`` / ``_mlp_block`` — one implementation, no
    drift) with attention run against [0, start + S) of the cache
    instead of the local chunk.  x: (B, S, H) with S = prefill length
    or 1.  ``ck``/``cv`` are THIS layer's HEAD-MAJOR
    (B, n_kv, S_max, hd) buffers; ``ck_s``/``cv_s`` their
    (B, n_kv, S_max, 1) int8 row scales or None — updates are single
    in-place ``dynamic_update_slice`` writes of the new token column
    (the stacked-(L, ...) layout's per-step slice copy + restack was
    the r4 long-prompt decode gap; position-major additionally made
    XLA transpose the whole cache for the attention dot each step).

    ``tp_axis``: Megatron tensor-parallel decode (shard_map only) —
    ``layer`` holds this rank's head/intermediate shards
    (``parallel.tensor.tp_specs`` layout), the cache holds only the
    local ``n_kv/tp`` heads, and the two row-parallel outputs are psum'd
    back into the (replicated) residual stream — the same f/g pairing
    the training layer uses (``transformer._layer_body``)."""
    B, S, H = x.shape
    hd = cfg.resolved_head_dim
    tp = axis_size(tp_axis) if tp_axis else 1
    nq, nkv = cfg.num_attention_heads // tp, cfg.num_key_value_heads // tp
    dense = T._dense(cfg)

    r = T.rms_norm(x, layer["ln1"], cfg.rms_norm_eps)
    q, k, v = T._qkv_proj(r, layer, cfg=cfg, cos=cos, sin=sin,
                          use_rope=use_rope, tp=tp)
    # head-major like the cache: (B, S, n_kv, hd) -> (B, n_kv, S, hd) —
    # a tiny S-token transpose instead of a whole-cache one per step
    k = k.transpose(0, 2, 1, 3)
    v = v.transpose(0, 2, 1, 3)

    quantized = ck.dtype == jnp.int8
    if quantized:
        kq, ks_new = _quant_kv(k)
        vq, vs_new = _quant_kv(v)
        ck = lax.dynamic_update_slice(ck, kq, (0, 0, start, 0))
        cv = lax.dynamic_update_slice(cv, vq, (0, 0, start, 0))
        ck_s = lax.dynamic_update_slice(ck_s, ks_new, (0, 0, start, 0))
        cv_s = lax.dynamic_update_slice(cv_s, vs_new, (0, 0, start, 0))
    else:
        ck = lax.dynamic_update_slice(ck, k, (0, 0, start, 0))
        cv = lax.dynamic_update_slice(cv, v, (0, 0, start, 0))

    # attention over the cache: visible = pos_kv <= pos_q (absolute).
    # GQA reads the cache DIRECTLY — grouping the q heads per kv head
    # instead of jnp.repeat'ing (and fp32-upcasting) the cache, which
    # materialized nq/nkv × the KV bytes per step and made long-prompt
    # decode cache-copy-bound (measured 0.17 of roofline at prompt 2048
    # before this).  Scores accumulate in fp32 via
    # preferred_element_type; probs drop to the compute dtype for PV,
    # mirroring the training attention's numerics (_attention_xla).
    # int8 cache: scores contract the int8 codes directly (fp32
    # accumulation) and the per-row K scale — constant over hd, the
    # contracted dim — multiplies the score afterwards, so the HBM read
    # really is int8; the V side folds its scale into the fp32 PV
    # accumulation the same way.
    S_max = ck.shape[2]
    rep = nq // nkv
    qg = q.reshape(B, S, nkv, rep, hd)
    if quantized:
        # TRUE int8 attention: quantize q per row too and contract the
        # int8 CODES on the MXU with int32 accumulation — the cache is
        # read raw (half the bytes), no fp32 upcast copy of it (the
        # upcast-then-dot variant measured SLOWER than the bf16 cache
        # at prompt 2048).  Scales fold outside the contraction: the K
        # row scale is constant over the contracted hd axis, so it
        # multiplies the score afterwards.
        qq, q_s = _quant_kv(qg)                       # rows over hd
        scores_i = jnp.einsum("bsgrh,bgkh->bgrsk", qq, ck,
                              preferred_element_type=jnp.int32)
        scores = (scores_i.astype(jnp.float32)
                  * q_s[..., 0].transpose(0, 2, 3, 1)[..., None]
                  * ck_s[..., 0][:, :, None, None, :]) / math.sqrt(hd)
    else:
        scores = jnp.einsum(
            "bsgrh,bgkh->bgrsk", qg, ck,
            preferred_element_type=jnp.float32) / math.sqrt(hd)
    pos_q = start + jnp.arange(S)
    pos_kv = jnp.arange(S_max)
    vis = pos_kv[None, :] <= pos_q[:, None]          # (S, S_max)
    scores = jnp.where(vis[None, None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    if quantized:
        # fold the per-POSITION V scales into probs (they vary along
        # the contracted axis k), then row-quantize the weighted probs
        # so the V dot also runs int8 × int8 over the raw cache
        pv = probs * cv_s[..., 0][:, :, None, None, :]
        pvq, pv_s = _quant_kv(pv)                     # rows over k
        attn_i = jnp.einsum("bgrsk,bgkh->bsgrh", pvq, cv,
                            preferred_element_type=jnp.int32)
        attn = attn_i.astype(jnp.float32) \
            * pv_s[..., 0].transpose(0, 3, 1, 2)[..., None]
    else:
        attn = jnp.einsum("bgrsk,bgkh->bsgrh", probs.astype(x.dtype), cv,
                          preferred_element_type=jnp.float32)
    attn = attn.astype(x.dtype).reshape(B, S, nq * hd)
    attn_out = dense(attn, layer["wo"])
    if tp_axis:
        from ..ops import collectives as C
        attn_out = C.all_reduce(attn_out, tp_axis)
    x = x + attn_out

    r = T.rms_norm(x, layer["ln2"], cfg.rms_norm_eps)
    mlp, _aux = T._mlp_block(r, layer, cfg=cfg)
    if tp_axis:
        mlp = C.all_reduce(mlp, tp_axis)
    return x + mlp, (ck, cv, ck_s, cv_s)


def _forward_cached(params, ids, cfg, cache: KVCache, start,
                    tp_axis=None):
    """ids (B, S) → (last-position logits (B, V) fp32, cache') using /
    refreshing the cache; ``start`` = absolute position of ids[:, 0].
    Only the LAST position's logits are computed — decoding never needs
    the rest, and a full (B, S, vocab) fp32 prefill buffer would be the
    exact memory spike the streamed training loss exists to avoid.

    The layer loop is UNROLLED (static layer index into the per-layer
    cache buffers): each layer's params are sliced statically from the
    stacked (L, ...) leaves and its cache update is one in-place
    ``dynamic_update_slice`` — no per-step dynamic-slice copy, no
    restack.  Decode-depth models (L ≤ ~36) compile fine unrolled; the
    training path keeps its ``lax.scan``."""
    B, S = ids.shape
    x = params["embed"].astype(cfg.dtype)[ids]
    cos, sin = T._rope_tables(S, cfg.resolved_head_dim, cfg.rope_theta,
                              start)
    # host-side: the unrolled loop needs CONCRETE per-layer flags
    # (T._rope_flags stages jnp ops, which are tracers under this jit)
    flags = [(li + 1) % cfg.nope_interval != 0 if cfg.nope_interval
             else True for li in range(cfg.num_hidden_layers)]

    ks, vs = list(cache.k), list(cache.v)
    kss = list(cache.k_scale) if cache.k_scale is not None else None
    vss = list(cache.v_scale) if cache.v_scale is not None else None
    for li in range(cfg.num_hidden_layers):
        layer = jax.tree.map(lambda p: p[li], params["layers"])
        x, (ks[li], vs[li], ksc, vsc) = _cached_layer_body(
            x, layer, cfg=cfg, cos=cos, sin=sin,
            use_rope=bool(flags[li]),
            ck=ks[li], cv=vs[li],
            ck_s=kss[li] if kss is not None else None,
            cv_s=vss[li] if vss is not None else None,
            start=start, tp_axis=tp_axis)
        if kss is not None:
            kss[li], vss[li] = ksc, vsc
    x = T.rms_norm(x[:, -1:], params["final_norm"], cfg.rms_norm_eps)
    uq = params.get("unembed_q")
    if uq is not None:       # int8 decode: the (H, vocab) read halves
        from ..ops.quant import prequantized_dense
        logits = prequantized_dense(x, uq)[:, 0]
    else:
        logits = (x @ T._output_embedding(params, cfg).T)[:, 0]
    new = KVCache(k=tuple(ks), v=tuple(vs),
                  k_scale=tuple(kss) if kss is not None else None,
                  v_scale=tuple(vss) if vss is not None else None,
                  length=start + S)
    return logits.astype(jnp.float32), new


def _generate_core(params, prompt_ids, rng, cfg: T.TransformerConfig,
                   max_new_tokens: int, temperature: float,
                   tp_axis=None, kv_quant: bool = False,
                   cache_capacity: int | None = None):
    B, S0 = prompt_ids.shape
    # ``cache_capacity`` pins the attention's contraction extent: XLA's
    # softmax-denominator reduction order depends on the K dimension, so
    # two decodes agree BITWISE only when they contract over the same
    # capacity (masked tail positions contribute exact zeros, but the
    # sum's association differs).  The serving engine always contracts
    # over its fixed page-pool view; parity checks pass the same value
    # here.
    if cache_capacity is not None and cache_capacity < S0 + max_new_tokens:
        raise ValueError(
            f"cache_capacity={cache_capacity} < prompt+new "
            f"({S0}+{max_new_tokens}); the decode would write past it")
    S_max = cache_capacity or (S0 + max_new_tokens)
    tp = axis_size(tp_axis) if tp_axis else 1
    cache = init_cache(cfg, B, S_max, tp=tp, quantized=kv_quant)
    logits, cache = _forward_cached(params, prompt_ids, cfg, cache, 0,
                                    tp_axis=tp_axis)

    def pick(logits_1, key):
        if temperature == 0.0:
            return jnp.argmax(logits_1, axis=-1).astype(jnp.int32)
        return jax.random.categorical(
            key, logits_1 / temperature, axis=-1).astype(jnp.int32)

    tok0 = pick(logits, rng)

    def step(carry, key):
        tok, cache = carry
        logits, cache = _forward_cached(params, tok[:, None], cfg,
                                        cache, cache.length,
                                        tp_axis=tp_axis)
        nxt = pick(logits, key)
        return (nxt, cache), nxt

    # max_new_tokens - 1 scanned steps: tok0 came from the prefill
    # logits, and each step emits the token it computes — no wasted
    # final forward (the r3 advisor's finding on this loop).
    keys = jax.random.split(jax.random.fold_in(rng, 1),
                            max_new_tokens - 1)
    (_, _), toks = lax.scan(step, (tok0, cache), keys)
    toks = jnp.concatenate([tok0[None], toks], axis=0)
    return toks.swapaxes(0, 1)   # (B, max_new_tokens)


@partial(jax.jit, static_argnames=("cfg", "max_new_tokens",
                                   "temperature", "kv_quant",
                                   "cache_capacity"))
def generate(params, prompt_ids, cfg: T.TransformerConfig, *,
             max_new_tokens: int = 32, temperature: float = 0.0,
             rng: jax.Array | None = None, kv_quant: bool = False,
             cache_capacity: int | None = None):
    """Decode ``max_new_tokens`` after ``prompt_ids`` (B, S_prompt).

    temperature 0 = greedy argmax; > 0 = categorical sampling — ``rng``
    is then REQUIRED (a silent default key would return identical
    "samples" on every call).  ``kv_quant`` stores the KV cache int8
    with per-row scales — half the cache-read bytes per step, the
    long-prompt lever.  ``cache_capacity`` (static) pads the cache to a
    fixed S_max ≥ prompt+new — the attention then contracts over that
    capacity, which is what makes tokens bitwise-comparable against the
    serving engine's fixed-size paged view (see ``serving.engine``).
    Returns (B, max_new_tokens) int32.  One prefill forward + one
    scanned decode loop — two compiled programs total, static shapes
    throughout.
    """
    if temperature > 0.0 and rng is None:
        raise ValueError("temperature > 0 samples stochastically: pass "
                         "rng=jax.random.PRNGKey(...) explicitly")
    if rng is None:
        rng = jax.random.PRNGKey(0)   # unused by greedy picks
    return _generate_core(params, prompt_ids, rng, _decode_cfg(cfg),
                          max_new_tokens, temperature,
                          kv_quant=kv_quant,
                          cache_capacity=cache_capacity)


def _decode_cfg(cfg: T.TransformerConfig) -> T.TransformerConfig:
    """Decode never checkpoints, so remat knobs must not leak in: a
    save_dots_q8-trained config would otherwise pay the int8 save
    round-trip (noise + cost, zero memory benefit) on every decode
    projection."""
    if cfg.remat:
        import dataclasses
        return dataclasses.replace(cfg, remat=False)
    return cfg


def make_tp_generate(cfg: T.TransformerConfig, mesh, *, axis: str = "tp",
                     max_new_tokens: int = 32, temperature: float = 0.0,
                     kv_quant: bool = False,
                     cache_capacity: int | None = None):
    """TP-sharded decode: ``fn(params_tp, prompt_ids, rng) -> tokens``.

    ``params_tp`` hold Megatron layer shards
    (``parallel.tensor.shard_params_tp``: wq/wk/wv/w_gate/w_up
    column-sharded, wo/w_down row-sharded, embed/norms replicated); the
    KV cache holds only each rank's ``n_kv/tp`` heads, so both the
    weight read AND the cache read of every decode step shrink by tp —
    the multi-chip decode scaling path.  Prompt and emitted tokens are
    replicated (every rank decodes the same stream)."""
    from ..ops import collectives as C
    from ..parallel.tensor import check_tp_divisibility, tp_specs

    check_tp_divisibility(cfg, int(mesh.shape[axis]))
    cfg = _decode_cfg(cfg)

    def core(params, prompt_ids, rng):
        return _generate_core(params, prompt_ids, rng, cfg,
                              max_new_tokens, temperature, tp_axis=axis,
                              kv_quant=kv_quant,
                              cache_capacity=cache_capacity)

    compiled = {}   # built once on first call (specs need a params tree)

    def fn(params_tp, prompt_ids, rng=None):
        if temperature > 0.0 and rng is None:
            raise ValueError("temperature > 0 needs an explicit rng")
        if rng is None:
            rng = jax.random.PRNGKey(0)
        if "jit" not in compiled:
            from jax.sharding import PartitionSpec as P
            compiled["jit"] = jax.jit(C.smap(
                core, mesh,
                in_specs=(tp_specs(params_tp, axis), P(), P()),
                out_specs=P()))
        return compiled["jit"](params_tp, prompt_ids, rng)

    return fn
