"""Decoder-only transformer LM, the "real model" of the framework.

Twin of the reference's FSDP/fp8 model path, which instantiates
SmolLM3-3B-class HF causal LMs from config with random init, bf16,
``use_cache=False`` (reference ``fsdp/train_fsdp.py:61-64``,
``fp8/fp8_benchmark.py:34-44``).  Here the model is a pure-functional JAX
pytree so every parallelism strategy (DDP / ZeRO / FSDP / PP / quantized)
can manipulate params directly:

  * SmolLM3-class architecture: RMSNorm, rotary attention with a NoPE
    interval (every 4th layer skips RoPE), grouped-query attention, gated
    SwiGLU MLP, tied embeddings.
  * **Scanned layers**: per-layer params are stacked on a leading axis and
    the forward runs ``lax.scan`` over them — one compiled layer body
    regardless of depth (compile time and HLO size stay O(1) in layers, and
    FSDP-style per-layer gathers become one collective inside the scan body).
  * ``jax.checkpoint`` around the scan body = the reference's
    activation-memory story (README.md:26-33): only per-layer boundaries
    are live across the backward.
  * Attention impl selectable: "xla" (einsum + causal mask — runs anywhere,
    XLA fuses on TPU) or "flash" (fused Pallas TPU kernel, the MXU/HBM-
    friendly path for seq 8192).

Shapes use (batch, seq, hidden) with weights stored (in, out) so the hot
matmuls are plain ``x @ w`` on the MXU.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field, replace
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.collectives import axis_size


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 128_256
    hidden_size: int = 2048
    intermediate_size: int = 11_008
    num_hidden_layers: int = 36
    num_attention_heads: int = 16
    num_key_value_heads: int = 4
    head_dim: int | None = None
    rope_theta: float = 5_000_000.0
    rms_norm_eps: float = 1e-6
    tie_word_embeddings: bool = True
    # Every nope_interval-th layer (0-indexed: layers where (i+1) % interval
    # == 0) skips RoPE — SmolLM3's NoPE scheme.  0 disables (RoPE everywhere).
    nope_interval: int = 4
    dtype: Any = jnp.bfloat16
    remat: bool = True
    # "full" recomputes the whole layer in backward; "save_attn" keeps each
    # layer's attention output resident (+S·nq·hd bf16 per layer) so the
    # fused-attention forward doesn't run twice; "save_dots" keeps every
    # matmul output resident — the backward recomputes only cheap
    # elementwise ops, trading ~all of remat's extra forward FLOPs for
    # O(layers · S · (heads+ffn)) activation memory.  The
    # rematerialisation trade the reference's reshard_after_forward
    # comments gesture at (fsdp/train_fsdp.py:84-88), applied to FLOPs
    # instead of gathers.
    # "save_dots_q8" is save_dots with int8-QUANTIZED saved activations
    # (ops/quant.quantized_residual): every projection output makes an
    # int8 round-trip whose quantized pair is what remat keeps — half
    # save_dots' activation bytes, same recompute savings, at the cost
    # of per-row int8 noise in the forward (the attack on the r3
    # save_dots×int8 OOM wall).
    remat_policy: str = "full"
    # "full" | "save_attn" | "save_dots" | "save_dots_q8"
    # Host offload of the policy-saved activations (memory planner,
    # --offload opt_act): the named saved tensors ride
    # ``save_and_offload_only_these_names`` to pinned host memory instead
    # of staying resident in HBM — only meaningful for the *named*-save
    # policies (save_attn / save_dots_q8).  Backends without a
    # pinned_host space (CPU sim) silently keep the plain save policy
    # (``memory_plan.offload.supports_host_offload``).
    offload_activations: bool = False
    # "ring" = exact causal attention over a sequence-sharded mesh axis
    # (``sp_axis``) — context parallelism for sequences past one chip's
    # HBM; only valid inside shard_map (see parallel/sequence.py).
    attention_impl: str = "xla"  # "xla" | "flash" | "ring"
    sp_axis: str | None = None  # mesh axis the sequence is sharded on
    # Ring q-chunk: bound each fold's fp32 score buffer to
    # (B, n, ring_block_q, S_local); 0 = unchunked.  Must divide S_local
    # (S_local/2 for the zigzag layout).
    ring_block_q: int = 0
    # Ring KV layout: "contiguous" (rank-order chunks) or "zigzag"
    # (balanced stripes — ~half the ring's score FLOPs; batches must be
    # fed through parallel.sequence.zigzag_shuffle).
    ring_layout: str = "contiguous"
    # Cross-entropy vocab chunk: None materializes full (B, S, vocab) fp32
    # logits (the reference's documented ~4 GB spikes, README.md:28-33);
    # an int streams the vocab through an online logsumexp in chunks of
    # that size, capping loss memory at B·S·chunk fp32.
    loss_vocab_chunk: int | None = None
    # Projection-matmul precision: "bf16", or int8 with dynamic absmax
    # scaling (forward quantized, backward bf16) — the reference's fp8
    # benchmark knob (fp8_benchmark.py:47) with v5e's native low-precision
    # format.  "int8_pallas" routes through the hand-tiled Pallas kernel.
    # The fp8 tier is the recipe-faithful Float8Linear twin (e4m3 fwd /
    # e5m2 bwd per-tensor scales, ops/quant.fp8_dense): "fp8" (dynamic
    # scaling), "fp8_delayed" (amax-history delayed scaling, depth
    # ``fp8_amax_history_len``), "fp8_pallas" (Pallas forward kernel).
    # "bf16" | "int8" | "int8_pallas" | "int8_bwd" | "int8_pallas_bwd"
    #        | "fp8" | "fp8_delayed" | "fp8_pallas"
    matmul_precision: str = "bf16"
    # Delayed-scaling amax history depth for "fp8_delayed" (torchao's
    # ``delayed`` recipe rolls this many step amaxes; ignored by the
    # dynamic fp8 variants).
    fp8_amax_history_len: int = 16
    gated_mlp: bool = True  # duck-types as FlopsConfig for utils.flops
    # Mixture-of-experts MLP (parallel/expert.py): 0 = dense.  With
    # n_experts > 0 every layer's MLP becomes a top-1 switch-MoE of
    # ``n_experts`` experts with ``moe_ffn`` (default intermediate_size)
    # hidden width; ``ep_axis`` shards experts across that mesh axis
    # (None = all experts local).  The Switch load-balance aux loss is
    # summed over layers and added to lm_loss with ``moe_aux_weight``.
    n_experts: int = 0
    moe_ffn: int | None = None
    moe_capacity_factor: float = 2.0
    moe_aux_weight: float = 0.01
    # "grouped" (GShard-style per-group one-hot matmuls — fastest on TPU,
    # no gather/scatter) | "sort" (global-capacity sort dispatch) |
    # "einsum" (whole-chunk one-hot oracle == grouped with one group).
    moe_dispatch: str = "grouped"
    moe_group_size: int = 128  # tokens per dispatch group ("grouped" only)
    # experts per token: 1 = Switch, 2+ = GShard top-k (normalized gates,
    # active FLOPs ×k; requires the grouped dispatch).
    moe_top_k: int = 1
    # Router-health knobs (ST-MoE): z-loss weight on mean
    # logsumexp(router logits)² — keeps logits small so the balance aux
    # keeps gradient signal; and a router LR multiplier (<1 slows the
    # router relative to the experts, the standard fix when the router
    # collapses faster than experts can differentiate).
    moe_router_z_weight: float = 0.0
    moe_router_lr_mult: float = 1.0
    ep_axis: str | None = None

    def __post_init__(self):
        # Covers every construction path incl. dataclasses.replace: a
        # sequence-sharded config with a local-chunk attention impl would
        # silently never attend across chunk boundaries.
        if self.sp_axis is not None and self.attention_impl != "ring":
            raise ValueError(
                f"sp_axis={self.sp_axis!r} (sequence sharded) requires "
                f"attention_impl='ring', got {self.attention_impl!r} "
                f"(parallel.sequence.sp_config sets both)")
        if self.attention_impl == "ring" and self.sp_axis is None:
            raise ValueError(
                "attention_impl='ring' needs sp_axis set to the mesh axis "
                "the sequence is sharded on, and must run inside shard_map "
                "(see parallel.sequence.sp_config)")
        if self.ring_layout not in ("contiguous", "zigzag"):
            raise ValueError(f"unknown ring_layout {self.ring_layout!r}")
        if self.moe_top_k > 1 and self.moe_dispatch != "grouped":
            raise ValueError(
                f"moe_top_k={self.moe_top_k} requires moe_dispatch="
                f"'grouped' (got {self.moe_dispatch!r})")
        if self.moe_router_z_weight and not self.moe_aux_weight:
            raise ValueError(
                "moe_router_z_weight rides the aux-loss channel scaled "
                "by moe_aux_weight — set moe_aux_weight > 0 too")
        if self.offload_activations and (
                not self.remat
                or self.remat_policy not in ("save_attn", "save_dots_q8")):
            raise ValueError(
                "offload_activations redirects NAMED saved tensors to "
                "host memory — it needs remat=True and remat_policy in "
                "('save_attn', 'save_dots_q8'); "
                f"got remat={self.remat}, "
                f"remat_policy={self.remat_policy!r}")

    @property
    def resolved_head_dim(self) -> int:
        return self.head_dim or self.hidden_size // self.num_attention_heads

    def param_count(self) -> int:
        h, hd = self.hidden_size, self.resolved_head_dim
        attn = h * hd * (self.num_attention_heads * 2
                         + self.num_key_value_heads * 2)
        if self.n_experts:
            F = self.moe_ffn or self.intermediate_size
            mlp = self.n_experts * 3 * h * F + h * self.n_experts
        else:
            mlp = 3 * h * self.intermediate_size
        norms = 2 * h
        per_layer = attn + mlp + norms
        embed = self.vocab_size * h
        head = 0 if self.tie_word_embeddings else embed
        return self.num_hidden_layers * per_layer + embed + head + h


# SmolLM3-3B-class config (~3.1 B params), the reference's FSDP benchmark
# model (fsdp/train_fsdp.py:61-64).
SMOLLM3_3B = TransformerConfig()

# Single-chip flagship: the 3B architecture (same hidden/heads/vocab/MLP
# geometry, so per-layer compute is identical) truncated to 8 layers to fit
# one 16 GB v5e with AdamW state; fused attention + streamed vocab loss.
SMOLLM3_3B_L8 = TransformerConfig(
    num_hidden_layers=8, attention_impl="flash", loss_vocab_chunk=16_032)

# Switch-MoE flagship: the 3B-L8 geometry with its MLP split into 8
# experts of ffn 2752 (dense MLP FLOPs 4-ways active) — the bench/MoE-A/B
# configuration as a named constant (scripts/moe_bench.py BASE).
SMOLLM3_3B_L8_MOE = TransformerConfig(
    num_hidden_layers=8, attention_impl="flash", loss_vocab_chunk=16_032,
    n_experts=8, moe_ffn=2752, moe_dispatch="grouped")

# Qwen3-4B-class geometry — the reference fp8 benchmark's default model
# family (``fp8/modal_app.py:40``: Qwen/Qwen3-4B): hidden 2560, 9728
# FFN, 32/8 GQA heads at head_dim 128, 151936 vocab, rope 1M.  Geometry
# class only (random init like every config here); Qwen3's QK-norm is
# not modeled — the benchmark-relevant shapes are.
QWEN3_4B = TransformerConfig(
    vocab_size=151_936, hidden_size=2560, intermediate_size=9728,
    num_hidden_layers=36, num_attention_heads=32, num_key_value_heads=8,
    head_dim=128, rope_theta=1_000_000.0, nope_interval=0)

# One-chip flagship sibling (same per-layer geometry, 6 layers — the
# L8 trick applied to the 4B family).
QWEN3_4B_L6 = TransformerConfig(
    vocab_size=151_936, hidden_size=2560, intermediate_size=9728,
    num_hidden_layers=6, num_attention_heads=32, num_key_value_heads=8,
    head_dim=128, rope_theta=1_000_000.0, nope_interval=0,
    attention_impl="flash", loss_vocab_chunk=15_194)

# Llama-3.2-1B / Llama-3.1-8B geometry classes — the remaining fp8
# benchmark target families (``fp8/fp8_benchmark.py:34-37``).  The 1B
# trains WHOLE on one 16 GB v5e (1.24 B params); the 8B is the
# multi-chip configuration (FSDP/TP it over a mesh).
LLAMA32_1B = TransformerConfig(
    vocab_size=128_256, hidden_size=2048, intermediate_size=8192,
    num_hidden_layers=16, num_attention_heads=32, num_key_value_heads=8,
    head_dim=64, rope_theta=500_000.0, nope_interval=0,
    attention_impl="flash", loss_vocab_chunk=16_032)
LLAMA31_8B = TransformerConfig(
    vocab_size=128_256, hidden_size=4096, intermediate_size=14_336,
    num_hidden_layers=32, num_attention_heads=32, num_key_value_heads=8,
    head_dim=128, rope_theta=500_000.0, nope_interval=0,
    tie_word_embeddings=False)

# Smaller siblings for 1-chip benches and CI (same shape family).
SMOLLM3_350M = TransformerConfig(
    vocab_size=49_152, hidden_size=960, intermediate_size=2560,
    num_hidden_layers=32, num_attention_heads=15, num_key_value_heads=5,
    head_dim=64)
TINY_LM = TransformerConfig(
    vocab_size=512, hidden_size=64, intermediate_size=160,
    num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
    rope_theta=10_000.0, dtype=jnp.float32, remat=False)

# Real-text fixture geometry (~70 M params): vocab matches the committed
# corpus tokenizer (data/corpus/tokenizer.json, vocab 8192) so the
# offline real-text path trains it directly — the substrate for the MoE
# quality A/B and the corpus flagship runs (reference trains on real
# TinyStories text, fsdp/utils.py:29-91).
CORPUS_LM = TransformerConfig(
    vocab_size=8192, hidden_size=768, intermediate_size=2048,
    num_hidden_layers=8, num_attention_heads=12, num_key_value_heads=4,
    head_dim=64, rope_theta=10_000.0, nope_interval=0,
    attention_impl="flash")

# 350M-class real-text flagship: the SmolLM3-350M geometry at the corpus
# tokenizer's vocab (49k→8k trims the embedding; ~270 M params remain) —
# the substrate for the ≥500-step real-text flagship run.
CORPUS_350M = TransformerConfig(
    vocab_size=8192, hidden_size=960, intermediate_size=2560,
    num_hidden_layers=32, num_attention_heads=15, num_key_value_heads=5,
    head_dim=64, nope_interval=0, attention_impl="flash")
# 8-layer sibling: depth experiments (4-stage / interleaved pipelines
# need more layers than TINY_LM's 4).
TINY_LM_L8 = replace(TINY_LM, num_hidden_layers=8)


# ------------------------------------------------------------------- init

def init_params(key: jax.Array, cfg: TransformerConfig) -> dict:
    """Random init from config — the reference never loads checkpoints
    (``fsdp/train_fsdp.py:61-64``), so neither does the default path here.
    Truncated-normal 0.02 (HF default), out-projections scaled by
    1/sqrt(2·layers) for depth-stable residuals."""
    h = cfg.hidden_size
    hd = cfg.resolved_head_dim
    nq, nkv = cfg.num_attention_heads, cfg.num_key_value_heads
    L = cfg.num_hidden_layers
    keys = iter(jax.random.split(key, 16))

    def tn(k, shape, std=0.02):
        return (std * jax.random.truncated_normal(k, -2, 2, shape,
                                                  jnp.float32)
                ).astype(cfg.dtype)

    out_std = 0.02 / math.sqrt(2 * L)
    params = {
        "embed": tn(next(keys), (cfg.vocab_size, h)),
        "layers": {
            "ln1": jnp.ones((L, h), cfg.dtype),
            "wq": tn(next(keys), (L, h, nq * hd)),
            "wk": tn(next(keys), (L, h, nkv * hd)),
            "wv": tn(next(keys), (L, h, nkv * hd)),
            "wo": tn(next(keys), (L, nq * hd, h), out_std),
            "ln2": jnp.ones((L, h), cfg.dtype),
        },
        "final_norm": jnp.ones((h,), cfg.dtype),
    }
    if cfg.n_experts:
        E, F = cfg.n_experts, cfg.moe_ffn or cfg.intermediate_size
        params["layers"].update(
            w_router=tn(next(keys), (L, h, E)),
            w_gate=tn(next(keys), (L, E, h, F)),
            w_up=tn(next(keys), (L, E, h, F)),
            w_down=tn(next(keys), (L, E, F, h), out_std))
    else:
        params["layers"].update(
            w_gate=tn(next(keys), (L, h, cfg.intermediate_size)),
            w_up=tn(next(keys), (L, h, cfg.intermediate_size)),
            w_down=tn(next(keys), (L, cfg.intermediate_size, h),
                      out_std))
    if not cfg.tie_word_embeddings:
        params["lm_head"] = tn(next(keys), (h, cfg.vocab_size))
    return params


# ---------------------------------------------------------------- building blocks

def rms_norm(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    dt = x.dtype
    x = x.astype(jnp.float32)
    x = x * lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    return (x * w.astype(jnp.float32)).astype(dt)


def _rope_tables(seq_len: int, head_dim: int, theta: float, offset=0,
                 positions=None):
    """``offset`` (may be traced) shifts positions — under sequence
    parallelism each device's chunk starts at rank · S_local.
    ``positions`` overrides with an explicit (seq_len,) global-position
    array (zigzag layout: the chunk is two non-adjacent stripes)."""
    inv_freq = 1.0 / theta ** (jnp.arange(0, head_dim, 2,
                                          dtype=jnp.float32) / head_dim)
    if positions is None:
        positions = offset + jnp.arange(seq_len, dtype=jnp.float32)
    ang = positions.astype(jnp.float32)[:, None] * inv_freq[None, :]
    return jnp.cos(ang), jnp.sin(ang)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x: (B, S, n_heads, head_dim); split-half rotation (HF convention)."""
    dt = x.dtype
    x = x.astype(jnp.float32)
    x1, x2 = jnp.split(x, 2, axis=-1)
    c = cos[None, :, None, :]
    s = sin[None, :, None, :]
    return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s],
                           axis=-1).astype(dt)


def _attention_xla(q, k, v, scale: float) -> jax.Array:
    """Plain causal attention: (B, S, n, hd) → (B, S, n, hd).  Scores in
    fp32 (the numerically load-bearing part); XLA fuses mask+softmax."""
    B, S, nq, hd = q.shape
    nkv = k.shape[2]
    if nq != nkv:  # GQA: repeat kv heads
        rep = nq // nkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scores = jnp.einsum("bqnh,bknh->bnqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    mask = jnp.tril(jnp.ones((S, S), jnp.bool_))
    scores = jnp.where(mask[None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bnqk,bknh->bqnh", probs, v)


def _attention_flash(q, k, v, scale: float) -> jax.Array:
    """Fused Pallas TPU attention (splash kernel): never materializes the
    S×S score matrix in HBM, handles GQA natively (no kv repeat), causal
    blocks skipped above the diagonal.  Block sizes 512/1024 measured ~2×
    over the kernel defaults at seq 8192 on v5e.  The seq-8192 path."""
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as sk, splash_attention_mask as sm)
    B, S, nq, hd = q.shape
    if S % 128:
        # splash blocks must be lane-aligned (multiples of 128); odd
        # lengths take the einsum path instead of crashing in the kernel.
        return _attention_xla(q, k, v, scale)
    bq, bkv = min(512, S), min(1024, S)
    # block_kv_compute must itself be a multiple of 128
    bkv_c = bkv // 2 if bkv % 256 == 0 else bkv
    mask = sm.MultiHeadMask([sm.CausalMask((S, S)) for _ in range(nq)])
    kernel = sk.make_splash_mha_single_device(
        mask=mask,
        block_sizes=sk.BlockSizes(
            block_q=bq, block_kv=bkv, block_kv_compute=bkv_c,
            block_q_dkv=bq, block_kv_dkv=bkv,
            block_kv_dkv_compute=bkv_c,
            block_q_dq=bq, block_kv_dq=bkv))

    def one(q1, k1, v1):  # (S, n, hd) -> kernel layout (n, S, hd)
        out = kernel(q1.swapaxes(0, 1) * scale, k1.swapaxes(0, 1),
                     v1.swapaxes(0, 1))
        return out.swapaxes(0, 1)

    return jax.vmap(one)(q, k, v)


def _dense(cfg: TransformerConfig):
    """The projection matmul at the configured precision.  Precisions:
    bf16; int8 (XLA fwd); int8_pallas (fused quantize-matmul kernel fwd);
    *_bwd variants additionally run both backward matmuls at int8; the
    fp8 family (fp8 / fp8_delayed / fp8_pallas) runs the Float8Linear
    e4m3-forward/e5m2-backward recipe end to end.

    Under ``remat_policy="save_dots_q8"`` (and only with remat ON —
    without ``jax.checkpoint`` nothing is saved, so the round-trip
    would be pure noise+cost) every output makes the int8 save
    round-trip (``quant.quantized_residual``) so the remat policy keeps
    the int8 pair instead of the bf16 tensor.

    A weight arriving as :class:`ops.collectives.RingShard` (the
    ``overlap="ring_fused"`` FSDP layer hook leaves projection weights
    sharded along their contraction dim) routes through the decomposed
    collective matmul — ``all_gather_matmul``, or its Pallas tile-kernel
    twin when the shard is marked ``impl="pallas"``
    (``overlap="ring_fused_pallas"``) — gather hops interleaved with
    the chunk matmuls instead of a monolithic gather-then-dot."""
    from ..ops import collectives as C
    from ..ops.quant import quantized_residual, resolve_quantized_dense
    base = resolve_quantized_dense(
        cfg.matmul_precision, fp8_history_len=cfg.fp8_amax_history_len)

    def dispatch(a, w):
        if isinstance(w, C.RingShard):
            if w.impl == "pallas":
                return C.all_gather_matmul_pallas(a, w.shard, w.axis_name)
            return C.all_gather_matmul(a, w.shard, w.axis_name)
        return base(a, w)

    if cfg.remat and cfg.remat_policy == "save_dots_q8":
        return lambda a, w: quantized_residual(dispatch(a, w))
    return dispatch


def _qkv_proj(r, layer, *, cfg: TransformerConfig, cos, sin, use_rope,
              tp: int = 1):
    """Normed residual → RoPE'd (q, k, v) — the projection math shared
    by the training layer and the KV-cache decode layer
    (``models/generate.py``), so the two paths cannot drift."""
    B, S, _ = r.shape
    hd = cfg.resolved_head_dim
    nq = cfg.num_attention_heads // tp
    nkv = cfg.num_key_value_heads // tp
    dense = _dense(cfg)
    q = dense(r, layer["wq"]).reshape(B, S, nq, hd)
    k = dense(r, layer["wk"]).reshape(B, S, nkv, hd)
    v = dense(r, layer["wv"]).reshape(B, S, nkv, hd)
    q = jnp.where(use_rope, apply_rope(q, cos, sin), q)
    k = jnp.where(use_rope, apply_rope(k, cos, sin), k)
    return q, k, v


def _mlp_block(r, layer, *, cfg: TransformerConfig):
    """Post-attention MLP (dense SwiGLU or top-k MoE) on the normed
    residual — shared by training and decode.  Returns (mlp, aux)."""
    aux = jnp.zeros((), jnp.float32)
    if cfg.n_experts:
        from ..parallel.expert import moe_mlp
        mlp, aux = moe_mlp(r, layer["w_router"], layer["w_gate"],
                           layer["w_up"], layer["w_down"],
                           axis=cfg.ep_axis,
                           capacity_factor=cfg.moe_capacity_factor,
                           dispatch=cfg.moe_dispatch,
                           group_size=cfg.moe_group_size,
                           top_k=cfg.moe_top_k,
                           matmul_precision=cfg.matmul_precision,
                           router_z_ratio=(cfg.moe_router_z_weight
                                           / cfg.moe_aux_weight
                                           if cfg.moe_router_z_weight
                                           else 0.0))
    else:
        dense = _dense(cfg)
        mlp = dense(jax.nn.silu(dense(r, layer["w_gate"]))
                    * dense(r, layer["w_up"]), layer["w_down"])
    return mlp, aux


def _layer_body(x, layer, *, cfg: TransformerConfig, cos, sin, use_rope,
                tp_axis: str | None = None, tp_overlap: str = "none"):
    """One decoder layer.  ``layer`` holds this layer's (unstacked) params;
    ``use_rope`` is a traced bool scalar (NoPE schedule).

    ``tp_axis``: Megatron tensor parallelism (parallel/tensor.py) — the
    layer weights are LOCAL shards (wq/wk/wv/w_gate/w_up column-sharded,
    wo/w_down row-sharded over that mesh axis) and the two row-parallel
    outputs are psum'd back into the residual stream.

    ``tp_overlap="ring"`` decomposes those two psums into
    psum_scatter + ring all-gather (``ops.collectives.
    decomposed_all_reduce`` over the hidden dim) — bitwise-identical
    values/grads, but the rejoin exposes tp-1 schedulable hops instead
    of one monolithic all-reduce."""
    B, S, h = x.shape
    hd = cfg.resolved_head_dim
    tp = axis_size(tp_axis) if tp_axis else 1
    nq = cfg.num_attention_heads // tp
    dense = _dense(cfg)

    r = rms_norm(x, layer["ln1"], cfg.rms_norm_eps)
    q, k, v = _qkv_proj(r, layer, cfg=cfg, cos=cos, sin=sin,
                        use_rope=use_rope, tp=tp)
    scale = 1.0 / math.sqrt(hd)
    if cfg.attention_impl == "flash":
        attn = _attention_flash(q, k, v, scale).astype(x.dtype)
    elif cfg.attention_impl == "ring":  # sp_axis validated in __post_init__
        from ..ops.ring_attention import ring_attention
        attn = ring_attention(q, k, v, cfg.sp_axis, scale=scale,
                              block_q=cfg.ring_block_q or None,
                              layout=cfg.ring_layout)
    else:
        attn = _attention_xla(q, k, v, scale).astype(x.dtype)
    from jax.ad_checkpoint import checkpoint_name
    attn = checkpoint_name(attn, "attn_out")
    attn_out = dense(attn.reshape(B, S, nq * hd), layer["wo"])
    if tp_axis:  # Megatron f/g: rejoin the row-parallel partial sums
        from ..ops import collectives as C
        from ..utils.profiling import scope
        if tp_overlap == "ring":
            _rejoin = lambda v: C.decomposed_all_reduce(v, tp_axis,
                                                        axis=-1)
        elif tp_overlap == "q8":
            # EQuARX two-shot: partial sums ship as int8 codes + scales
            # (~4x fewer bus bytes than the f32 psum), dequant-sum after
            # the wire; backward stays a full-precision psum.
            from ..ops.quant import quantized_all_reduce
            _rejoin = lambda v: quantized_all_reduce(v, tp_axis)
        else:
            _rejoin = lambda v: C.all_reduce(v, tp_axis)
        with scope("tp_attn_psum"):
            attn_out = _rejoin(attn_out)
    x = x + attn_out

    r = rms_norm(x, layer["ln2"], cfg.rms_norm_eps)
    if tp_axis and cfg.n_experts and cfg.ep_axis:
        raise ValueError("shard experts over ep OR split them over "
                         "tp, not both (ep_axis and tp_axis set)")
    # Under TP each rank holds every expert's F/tp slice (tp_specs):
    # routing/dispatch are replicated across the tp group (tokens and
    # router are), the per-expert matmuls produce partial sums, and one
    # psum after combine rejoins them — the Megatron row/column pairing
    # applied inside each expert (dense MLP: the classic pairing).
    mlp, aux = _mlp_block(r, layer, cfg=cfg)
    if tp_axis:
        with scope("tp_moe_psum" if cfg.n_experts else "tp_mlp_psum"):
            mlp = _rejoin(mlp)
    return x + mlp, aux


def resolve_remat_policy(cfg: TransformerConfig):
    """cfg.remat_policy name → jax.checkpoint policy (one mapping for
    every scaffold that remats the layer scan — hidden_states and
    parallel/pipeline's stage bodies).

    With ``cfg.offload_activations`` (and a backend that has a
    pinned_host space) the named-save policies become
    ``save_and_offload_only_these_names``: the same tensors survive the
    backward, but parked in host DRAM instead of HBM — the
    remat-activation leg of the memory planner's host offload."""
    if cfg.offload_activations:
        from ..memory_plan.offload import (
            OFFLOADABLE_REMAT_NAMES, supports_host_offload)
        names = OFFLOADABLE_REMAT_NAMES[cfg.remat_policy]
        if supports_host_offload():
            return jax.checkpoint_policies.save_and_offload_only_these_names(
                names_which_can_be_saved=[],
                names_which_can_be_offloaded=list(names),
                offload_src="device", offload_dst="pinned_host")
        # CPU sim: no host space distinct from device — keep the plain
        # save policy (bitwise-identical math, zero transfers declared)
    return {
        "save_attn":
            jax.checkpoint_policies.save_only_these_names("attn_out"),
        "save_dots":
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        # the saved tensors are the int8 pairs _dense's round-trip tagged
        "save_dots_q8":
            jax.checkpoint_policies.save_only_these_names("dot_q8"),
        "full": None,
    }[cfg.remat_policy]


def _rope_flags(cfg: TransformerConfig) -> jax.Array:
    """Per-layer use-RoPE flags: SmolLM3 drops RoPE on every
    ``nope_interval``-th layer."""
    idx = jnp.arange(cfg.num_hidden_layers)
    if cfg.nope_interval:
        return (idx + 1) % cfg.nope_interval != 0
    return jnp.ones_like(idx, dtype=jnp.bool_)


# ---------------------------------------------------------------- forward

def forward(params: dict, input_ids: jax.Array, cfg: TransformerConfig,
            *, layer_hook=None, layer_body=None) -> jax.Array:
    """``input_ids`` (B, S) int32 → logits (B, S, vocab) in cfg.dtype.

    ``layer_hook(layer_params) -> layer_params`` runs inside the scan body
    *before* the layer computes — the seam where ZeRO-3/FSDP materialize
    full params from shards (the JAX twin of the reference's module
    forward-pre hooks, ``zero/zero3.py:56-77``).  Because the scan body is
    rematerialized, the hook (and its all_gather) re-runs in the backward
    pass, reproducing the backward pre-hook re-gather.

    ``layer_body`` replaces the decoder-layer computation itself (same
    signature as ``_layer_body``) — the seam where tensor parallelism
    substitutes its Megatron-sharded layer (``parallel/tensor.py``) while
    reusing this scaffold (RoPE tables, NoPE flags, remat, scan, loss).
    """
    x = hidden_states(params, input_ids, cfg, layer_hook=layer_hook,
                      layer_body=layer_body)
    return x @ _output_embedding(params, cfg).T


def hidden_states(params: dict, input_ids: jax.Array,
                  cfg: TransformerConfig, *, layer_hook=None,
                  layer_body=None, return_aux: bool = False):
    """Trunk only: (B, S) ids → final-norm hidden states (B, S, H).
    ``return_aux=True`` additionally returns the per-layer auxiliary
    losses summed (the MoE load-balance term; 0 for dense layers)."""
    B, S = input_ids.shape
    apply_layer = layer_body or _layer_body
    x = params["embed"].astype(cfg.dtype)[input_ids]
    # Under sequence parallelism S is the LOCAL chunk; RoPE positions and
    # the causal structure use this rank's GLOBAL positions — an offset
    # for contiguous chunks, the stripe-pair position map for zigzag.
    if cfg.sp_axis and cfg.ring_layout == "zigzag":
        from ..ops.ring_attention import zigzag_positions
        cos, sin = _rope_tables(S, cfg.resolved_head_dim, cfg.rope_theta,
                                positions=zigzag_positions(cfg.sp_axis, S))
    else:
        offset = lax.axis_index(cfg.sp_axis) * S if cfg.sp_axis else 0
        cos, sin = _rope_tables(S, cfg.resolved_head_dim, cfg.rope_theta,
                                offset)
    flags = _rope_flags(cfg)

    def body(carry, scanned):
        layer, use_rope = scanned
        if layer_hook is not None:
            layer = layer_hook(layer)
        x, aux = apply_layer(carry, layer, cfg=cfg, cos=cos, sin=sin,
                             use_rope=use_rope)
        return x, aux

    if cfg.remat:
        body = jax.checkpoint(body, prevent_cse=False,
                              policy=resolve_remat_policy(cfg))
    x, aux = lax.scan(body, x, (params["layers"], flags))
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    return (x, jnp.sum(aux)) if return_aux else x


def _output_embedding(params: dict, cfg: TransformerConfig) -> jax.Array:
    """Unembedding as (vocab, H) rows (tied: the input embedding itself)."""
    w = params.get("lm_head")
    if w is None:
        return params["embed"].astype(cfg.dtype)
    return w.astype(cfg.dtype).T


def chunked_softmax_xent(x: jax.Array, w_vocab: jax.Array,
                         labels: jax.Array, chunk: int) -> jax.Array:
    """Mean cross-entropy of ``x @ w_vocab.T`` against ``labels`` without
    ever materializing the (B, S, vocab) logits: stream vocab-row chunks
    through an online (running max/sum) logsumexp, gathering the gold logit
    as its chunk passes.  ``jax.checkpoint`` on the chunk body keeps the
    backward at one chunk of logits too.  This removes all three of the
    reference's ~4 GB fp32 spikes (logits, log-probs, grad-wrt-log-probs —
    README.md:28-33) at once."""
    V, H = w_vocab.shape
    n_chunks = -(-V // chunk)
    pad = n_chunks * chunk - V
    if pad:
        w_vocab = jnp.pad(w_vocab, ((0, pad), (0, 0)))
    B, S, _ = x.shape

    def body(carry, c):
        m, s, gold = carry
        w_c = lax.dynamic_slice(w_vocab, (c * chunk, 0), (chunk, H))
        logits = jnp.einsum("bsh,vh->bsv", x, w_c,
                            preferred_element_type=jnp.float32)
        col = c * chunk + jnp.arange(chunk)
        logits = jnp.where(col < V, logits, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[..., None]), axis=-1)
        idx = labels - c * chunk
        hit = (idx >= 0) & (idx < chunk)
        g = jnp.take_along_axis(logits, jnp.clip(idx, 0, chunk - 1)[..., None],
                                axis=-1)[..., 0]
        gold = gold + jnp.where(hit, g, 0.0)
        return (m_new, s, gold), None

    init = (jnp.full((B, S), -jnp.inf, jnp.float32),
            jnp.zeros((B, S), jnp.float32),
            jnp.zeros((B, S), jnp.float32))
    (m, s, gold), _ = lax.scan(jax.checkpoint(body, prevent_cse=False),
                               init, jnp.arange(n_chunks))
    return jnp.mean(jnp.log(s) + m - gold)


def lm_loss(params: dict, batch, cfg: TransformerConfig,
            *, layer_hook=None, layer_body=None) -> jax.Array:
    """Causal-LM cross-entropy.  ``batch`` = (input_ids, labels) both (B, S),
    the packed-window contract of the reference's TinyStories pipeline
    (``fsdp/utils.py:58-89``: inputs = window[:-1], labels = window[1:]).

    With ``cfg.loss_vocab_chunk`` unset this is the reference-faithful dense
    path: fp32 log-softmax over full (B, S, vocab) logits — the same memory
    spike the reference documents (README.md:28-33).  Set it to stream the
    vocab instead (see chunked_softmax_xent).
    """
    input_ids, labels = batch
    x, aux = hidden_states(params, input_ids, cfg, layer_hook=layer_hook,
                           layer_body=layer_body, return_aux=True)
    loss = xent_from_hidden(x, _output_embedding(params, cfg), labels,
                            chunk=cfg.loss_vocab_chunk)
    if cfg.n_experts:
        loss = loss + cfg.moe_aux_weight * aux
    return loss


def xent_from_hidden(x: jax.Array, w_vocab: jax.Array, labels: jax.Array,
                     *, chunk: int | None = None) -> jax.Array:
    """Mean causal-LM cross-entropy from final hidden states:
    streamed-vocab when ``chunk`` is set, dense fp32 otherwise.
    ``w_vocab``: (vocab, H) unembedding rows.  Shared by ``lm_loss`` and
    the pipeline's last stage so the numerics exist once."""
    if chunk:
        return chunked_softmax_xent(x, w_vocab, labels, chunk)
    logits = (x @ w_vocab.T).astype(jnp.float32)
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    return jnp.mean(logz - gold)


def model_flops_per_token(cfg: TransformerConfig, seq_len: int) -> float:
    from ..utils.flops import get_model_flops_per_token
    return get_model_flops_per_token(cfg, seq_len)
