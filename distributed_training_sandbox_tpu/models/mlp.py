"""Toy MLPs matching the reference's strategy-exercise models.

Two configurations recur in the reference (SURVEY.md §2.4):
  * the ZeRO toy: 6 × Linear(10_000, 10_000) with ReLU between
    (reference ``zero/zero1.py:237-249``) — 12 params, ~1.2 GB fp32, big
    enough that sharding optimizer state visibly moves peak memory;
  * the PP toy: Linear(50,500) → 4×Linear(500,500) → Linear(500,50) with
    ReLU between (reference ``pp/gpipe.py:23-35``).

Params are a plain pytree: a list of ``{"w": (in, out), "b": (out,)}`` dicts,
one per linear layer — 2 leaves per layer, so per-param collective counts map
1:1 to the reference's 12-param traces.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

ZERO_TOY_SIZES = (10_000,) * 7
PP_TOY_SIZES = (50, 500, 500, 500, 500, 500, 50)


def init_mlp(key: jax.Array, sizes, dtype=jnp.float32) -> list[dict]:
    """Kaiming-uniform init (torch nn.Linear's default), so A/B peak-memory
    and loss curves are comparable with the reference's toys."""
    params = []
    for i in range(len(sizes) - 1):
        key, wk, bk = jax.random.split(key, 3)
        fan_in = sizes[i]
        bound = 1.0 / math.sqrt(fan_in)
        w = jax.random.uniform(wk, (sizes[i], sizes[i + 1]),
                               minval=-math.sqrt(6.0 / fan_in) / math.sqrt(2),
                               maxval=math.sqrt(6.0 / fan_in) / math.sqrt(2),
                               dtype=jnp.float32)
        b = jax.random.uniform(bk, (sizes[i + 1],), minval=-bound,
                               maxval=bound, dtype=jnp.float32)
        params.append({"w": w.astype(dtype), "b": b.astype(dtype)})
    return params


def mlp_apply_stage(params: list[dict], x: jax.Array,
                    *, last_stage: bool = False) -> jax.Array:
    """Apply a (slice of a) layered MLP: ReLU after every layer except the
    final layer of the last stage.  A non-final pipeline stage keeps the
    ReLU after its last layer too — splitting nn.Sequential keeps the
    activation modules with their chunk (reference ``pp/gpipe.py:38-47``)."""
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if not (last_stage and i == len(params) - 1):
            x = jax.nn.relu(x)
    return x


def mlp_apply(params: list[dict], x: jax.Array) -> jax.Array:
    """ReLU between layers, none after the last (nn.Sequential twin)."""
    return mlp_apply_stage(params, x, last_stage=True)


def zero_toy_mlp(key: jax.Array, dtype=jnp.float32, scale: int = 1):
    """The ZeRO exercise model; ``scale`` divides the width for fast tests."""
    sizes = tuple(s // scale for s in ZERO_TOY_SIZES)
    return init_mlp(key, sizes, dtype)


def pp_toy_mlp(key: jax.Array, dtype=jnp.float32):
    return init_mlp(key, PP_TOY_SIZES, dtype)


def mse_loss(params, batch, apply_fn=mlp_apply):
    x, y = batch
    pred = apply_fn(params, x)
    return jnp.mean((pred - y) ** 2)
