"""RunState: the strategy-agnostic unit of resumability.

A training run's restorable identity is more than its arrays.  Resuming
bit-exactly needs, beyond the (possibly sharded) params and optimizer
state, the *host-side* position of the run: which batch the loop would
consume next (``data_cursor``), the root PRNG key the seed produced
(``prng_key`` — a resume under a different ``--seed`` must fail loudly,
not silently fork the trajectory), the last completed step, and the loss
sequence so far (so a stitched run can report — and tests can pin — the
full concatenated series without replaying segment 1).

Array leaves travel through ``utils/checkpoint.py`` (Orbax: parallel
per-shard writes, reshard-on-restore when the mesh changed); the host
scalars or variable-length pieces (step, cursor, loss log, lineage) ride
in a ``runstate-<step>.json`` sidecar next to the Orbax step directory,
written after the save's host copy completes so the sidecar can never
describe data that was not yet captured.

:class:`Checkpointer` is the driver-facing policy object: ``--checkpoint
-every N`` saves are *asynchronous* and deferred to the step pump's next
sync point (``maybe_save(..., synced=...)``), so checkpointing rides the
existing host-sync schedule instead of adding blocking points; ``close()``
always waits for in-flight writes — the guarantee that a crash mid-write
never leaves a torn newest step (``tests/test_resilience.py``).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any

from ..utils import checkpoint as C

STATE_SCHEMA_VERSION = 1


class CheckpointCorruptError(RuntimeError):
    """A checkpoint step exists on disk but cannot be restored (torn
    write, truncation, bit rot).  The message is the CLI-facing contract:
    readable, names the step and directory, says what to do next."""


@dataclass
class RunState:
    """Everything one strategy run needs to resume bit-exactly.

    ``params``/``opt_state``/``prng_key`` are pytrees of (possibly
    sharded) arrays; the rest is host data.  ``step`` is the LAST
    COMPLETED step index; ``data_cursor`` counts host batches the loop
    has consumed (== step+1 for one-batch-per-step drivers, epochs for
    the pipeline driver) — the prefetcher may have pulled further ahead,
    which is exactly why the loop-side cursor is the thing saved."""

    params: Any
    opt_state: Any = None
    step: int = -1
    data_cursor: int = 0
    prng_key: Any = None
    loss_log: list = field(default_factory=list)
    lineage: dict = field(default_factory=dict)

    def array_tree(self) -> dict:
        """The Orbax-bound leaves (structure mirrored by ``_like_tree``)."""
        tree = {"params": self.params}
        if self.opt_state is not None:
            tree["opt"] = self.opt_state
        if self.prng_key is not None:
            tree["prng"] = self.prng_key
        return tree


def _meta_path(directory: str, step: int) -> str:
    return os.path.join(directory, f"runstate-{step}.json")


def _write_meta(directory: str, state: RunState,
                fingerprint: dict | None) -> None:
    meta = {
        "schema": STATE_SCHEMA_VERSION,
        "step": int(state.step),
        "data_cursor": int(state.data_cursor),
        "loss_log": [float(l) for l in state.loss_log],
        "lineage": state.lineage or {},
        "has_opt": state.opt_state is not None,
        "has_prng": state.prng_key is not None,
        "fingerprint": fingerprint or {},
    }
    path = _meta_path(directory, state.step)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(meta, f, indent=1)
        f.write("\n")
    os.replace(tmp, path)   # atomic: a reader sees old-or-new, never torn


def _globalize(tree):
    """Multi-process saves require every array leaf to be GLOBAL: Orbax
    refuses process-local arrays ("Cannot serialize host local arrays").
    Params and optimizer state come out of jit already global, but the
    PRNG root (``set_seed``'s single-device key) and any host-side numpy
    leaves are local to each process.  Their values are identical on
    every rank by construction (identically seeded), so replicating them
    over a mesh of ALL devices is value-preserving.  Single-process this
    is the identity."""
    import jax
    import numpy as np

    if jax.process_count() == 1:
        return tree
    from jax.sharding import Mesh, PartitionSpec
    from ..utils.mesh import host_to_global
    mesh = Mesh(np.asarray(jax.devices()), ("all",))

    def fix(leaf):
        if isinstance(leaf, jax.Array) and leaf.is_fully_addressable:
            return host_to_global(np.asarray(leaf), mesh, PartitionSpec())
        if isinstance(leaf, np.ndarray):
            return host_to_global(leaf, mesh, PartitionSpec())
        return leaf

    return jax.tree.map(fix, tree)


def _localize(restored, like):
    """Inverse of :func:`_globalize` on the restore path: leaves the
    caller's ``like`` holds process-locally (the PRNG key) come back
    from a globalized checkpoint as non-addressable global arrays —
    fold each back to the local replica so downstream code sees the
    same shape of array it handed in."""
    import jax
    import numpy as np

    def fix(r, l):
        if isinstance(l, jax.Array) and l.is_fully_addressable \
                and isinstance(r, jax.Array) \
                and not r.is_fully_addressable:
            return jax.device_put(np.asarray(r.addressable_data(0)),
                                  l.sharding)
        return r

    return jax.tree.map(fix, restored, like)


def save_run_state(mgr, state: RunState, *, wait: bool = False,
                   fingerprint: dict | None = None) -> None:
    """Save ``state`` under its step.  ``wait=False`` leaves the disk
    write async (the device->host copy inside Orbax is synchronous, so
    the next train step may donate/overwrite the buffers immediately);
    the sidecar is written right after — by then the data is captured."""
    C.save_state(mgr, state.step, _globalize(state.array_tree()),
                 wait=wait)
    _write_meta(os.fspath(mgr.directory), state, fingerprint)


def _read_meta(directory: str, step: int) -> dict | None:
    try:
        with open(_meta_path(directory, step)) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def _match_commitment(restored, like):
    """Orbax restores every leaf COMMITTED to ``like``'s sharding — but
    optimizer trees routinely carry uncommitted host scalars (Adam's
    ``count``), and a scalar pinned to device 0 next to mesh-sharded
    params is a "incompatible devices" jit error on the very next step.
    Leaves that were uncommitted in ``like`` are returned uncommitted."""
    import jax
    import numpy as np

    def fix(r, l):
        if isinstance(l, jax.Array) and not getattr(l, "_committed", True):
            return jax.device_put(np.asarray(r))
        return r

    return jax.tree.map(fix, restored, like)


def restore_run_state(mgr, *, like: RunState,
                      step: int | None = None) -> RunState:
    """Restore the newest (or given) step into ``like``'s structure and
    shardings (resharding if ``like`` lives on a different mesh than the
    one that saved).  A torn or corrupted step raises
    :class:`CheckpointCorruptError` with a readable message, not a raw
    tensorstore traceback."""
    directory = os.fspath(mgr.directory)
    if step is None:
        step = C.latest_step(mgr)
        if step is None:
            raise FileNotFoundError(f"no checkpoint steps in {directory}")
    meta = _read_meta(directory, step) or {}
    tree = {"params": like.params}
    if meta.get("has_opt", like.opt_state is not None) \
            and like.opt_state is not None:
        tree["opt"] = like.opt_state
    if meta.get("has_prng", like.prng_key is not None) \
            and like.prng_key is not None:
        tree["prng"] = like.prng_key
    try:
        restored = _localize(C.restore_state(mgr, like=_globalize(tree),
                                             step=step), tree)
        restored = _match_commitment(restored, tree)
    except CheckpointCorruptError:
        raise
    except Exception as e:  # noqa: BLE001 - rewrapped with context
        raise CheckpointCorruptError(
            f"failed to restore step {step} from {directory}: "
            f"{type(e).__name__}: {str(e).splitlines()[0] if str(e) else e}"
            f" — the checkpoint is torn or corrupted; delete "
            f"{os.path.join(directory, str(step))} to fall back to an "
            f"earlier step, or restart without --resume") from e
    return RunState(
        params=restored["params"],
        opt_state=restored.get("opt"),
        prng_key=restored.get("prng"),
        step=int(meta.get("step", step)),
        data_cursor=int(meta.get("data_cursor", step + 1)),
        loss_log=list(meta.get("loss_log", [])),
        lineage=dict(meta.get("lineage", {})),
    )


class Checkpointer:
    """Policy + lifecycle around one run's checkpoint directory.

    ``maybe_save`` marks a step due every ``every`` steps but only
    writes at the next pump sync point (``synced=True``), asynchronously;
    ``save`` is the unconditional form; ``close()`` waits for in-flight
    writes on EVERY exit path (the supervisor calls it from a finally),
    so an async save can never be torn by process exit — the hazard
    ``save_state(..., wait=False)`` callers had before this class."""

    def __init__(self, directory, *, every: int = 0, keep: int = 3,
                 fingerprint: dict | None = None):
        self.directory = os.path.abspath(os.fspath(directory))
        self.every = max(int(every), 0)
        self.keep = keep
        self.fingerprint = dict(fingerprint or {})
        self._mgr = None
        self._due = False
        self._saved_steps: set[int] = set()
        # host-phase span stream (telemetry.spans.SpanStream): drivers
        # with a TelemetryRun assign it so each save's blocking portion
        # shows up as a checkpoint/save span on the merged timeline
        self.spans = None
        # live MetricsRegistry, same late-assignment pattern
        # (``ckpt.metrics = telem.metrics``); feeds are None-tolerant
        self.metrics = None

    @property
    def mgr(self):
        if self._mgr is None:
            self._mgr = C.checkpoint_manager(self.directory,
                                             max_to_keep=self.keep)
        return self._mgr

    # ---- restore --------------------------------------------------------
    def restore_latest(self, like: RunState) -> RunState | None:
        """Latest *intact* RunState, or None when the directory holds no
        steps (a resume of a run that never reached its first save
        starts fresh).  Verifies the saved fingerprint (seed/precision/
        batch) against this run's — a silently different config must not
        wear a restored trajectory.  A torn or corrupted newest step
        (the shape a SIGKILL mid-async-save leaves behind) is SKIPPED
        with a warning and the previous intact step restored instead —
        an elastic resume after a torn save self-heals; only when every
        step is corrupt does the error propagate."""
        if not os.path.isdir(self.directory):
            return None
        self.mgr.wait_until_finished()
        steps = sorted(self.mgr.all_steps() or [], reverse=True)
        if not steps:
            return None
        last_err: CheckpointCorruptError | None = None
        for step in steps:
            meta = _read_meta(self.directory, step) or {}
            saved_fp = meta.get("fingerprint") or {}
            for k, want in self.fingerprint.items():
                have = saved_fp.get(k)
                if have is not None and want is not None and have != want:
                    raise SystemExit(
                        f"cannot resume from {self.directory}: checkpoint "
                        f"was written with {k}={have!r}, this run has "
                        f"{k}={want!r} — resuming would silently fork the "
                        f"trajectory (rerun with the original {k}, or a "
                        f"fresh --checkpoint-dir)")
            try:
                state = restore_run_state(self.mgr, like=like, step=step)
            except CheckpointCorruptError as e:
                print(f"[resilience] WARNING: checkpoint step {step} in "
                      f"{self.directory} is torn or corrupt — skipping it"
                      f" and falling back to the previous intact step")
                last_err = e
                continue
            if like.prng_key is not None and state.prng_key is not None:
                import numpy as np
                if not np.array_equal(np.asarray(like.prng_key),
                                      np.asarray(state.prng_key)):
                    raise SystemExit(
                        f"cannot resume from {self.directory}: the "
                        f"checkpointed PRNG root key differs from this "
                        f"run's (different --seed?) — the resumed data/"
                        f"init stream would not match the original run")
            self._saved_steps.add(step)
            return state
        raise last_err

    # ---- save policy ----------------------------------------------------
    def maybe_save(self, i: int, state_fn, *, synced: bool) -> bool:
        """Call once per completed step ``i``.  Marks a save due every
        ``every`` steps; performs it (async) at the first due step where
        the pump has synced — all losses <= i are then resolved, so the
        saved ``loss_log`` is complete and the device is quiesced enough
        that the host copy does not race dispatch."""
        if self.every and (i + 1) % self.every == 0:
            self._due = True
        if self._due and synced:
            self.save(state_fn(), wait=False)
            self._due = False
            return True
        return False

    def save(self, state: RunState, *, wait: bool = False) -> None:
        if state.step in self._saved_steps:
            return
        from ..telemetry.spans import maybe_span
        state.lineage.setdefault("fingerprint", {}).update(self.fingerprint)
        with maybe_span(self.spans, "checkpoint/save", cat="checkpoint",
                        step=int(state.step), wait=bool(wait)):
            save_run_state(self.mgr, state, wait=wait,
                           fingerprint=self.fingerprint)
        from ..telemetry.metrics import maybe_inc
        maybe_inc(self.metrics, "checkpoint_saves_total")
        self._saved_steps.add(state.step)
        self._prune_meta()

    def save_final(self, state: RunState) -> None:
        """The exit/preemption save: unconditional, then waits — the
        step the next segment resumes from must be fully committed
        before this process exits."""
        self.save(state, wait=True)

    def _prune_meta(self) -> None:
        """Drop sidecars for steps Orbax's max_to_keep already pruned."""
        try:
            live = set(self.mgr.all_steps())
            for name in os.listdir(self.directory):
                if name.startswith("runstate-") and name.endswith(".json"):
                    step = int(name[len("runstate-"):-len(".json")])
                    if step not in live:
                        os.unlink(os.path.join(self.directory, name))
        except (OSError, ValueError):
            pass

    # ---- lifecycle ------------------------------------------------------
    def close(self) -> None:
        """Wait for in-flight async writes.  Idempotent; the supervisor
        runs this in a ``finally`` so even a crashing attempt cannot
        leave a half-committed newest step behind."""
        if self._mgr is not None:
            self._mgr.wait_until_finished()

    def __enter__(self) -> "Checkpointer":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False
