"""Run-level fault tolerance: preemption-safe full-run resume.

The reference (and this repo through PR 3) treats every interruption as
fatal: only ``scripts/train_flagship.py`` saved anything, and only the
final params — no optimizer state, no PRNG root, no data position, so an
interrupted run restarted from scratch and the bit-exact trajectories
the async pump pinned were unverifiable across a restart.  This package
is the missing run-level half, layered over the existing Orbax wrapper
(``utils/checkpoint.py``), StepPump, and telemetry:

  * :mod:`state` — :class:`RunState`, the strategy-agnostic snapshot of
    everything a resume needs (params, opt state, root PRNG key, host
    data cursor, step index, loss log, restart lineage), saved
    asynchronously at StepPump sync points by :class:`Checkpointer` so
    checkpointing rides the existing host-sync schedule;
  * :mod:`supervisor` — the in-process restart loop: a SIGTERM handler
    that drains the pump, flushes telemetry, takes a final checkpoint
    and exits cleanly; ``--max-restarts`` with backoff resumes from the
    latest step and records restart lineage in ``manifest.json``;
  * :mod:`faults` — deterministic fault injection (crash-at-step-N,
    simulated preemption, worker SIGKILL, hung/straggling ranks,
    truncated/corrupted checkpoint files) behind the ``--inject-fault``
    debug flag and the test suite;
  * :mod:`elastic` — the elastic mesh runtime: per-worker heartbeat
    failure detection, shrink-to-survivors resume (rebuild a smaller
    mesh, reshard-restore, bitwise-pinned continuation), and the
    collective watchdog that converts hung steps into diagnosable
    :class:`StepTimeoutError` instead of silent deadlocks.

The headline guarantee, pinned by ``tests/test_resilience.py`` on the
8-way CPU mesh: preempt a run at step k, resume it, and the concatenated
loss sequence is bitwise-identical to the uninterrupted run — including
the host data cursor and PRNG position.
"""

from .state import (  # noqa: F401
    CheckpointCorruptError,
    Checkpointer,
    RunState,
    restore_run_state,
    save_run_state,
)
from .faults import (  # noqa: F401
    FaultInjector,
    FaultSpec,
    InjectedCrash,
    corrupt_checkpoint,
    parse_fault_spec,
    truncate_checkpoint,
    unreaped_workers,
)
from .supervisor import (  # noqa: F401
    GracefulShutdown,
    Preempted,
    ResilienceContext,
    Supervisor,
)
from .elastic import (  # noqa: F401
    ElasticPlan,
    ElasticSupervisor,
    Heartbeat,
    HeartbeatMonitor,
    StepTimeoutError,
    Watchdog,
    WorkerLost,
    read_heartbeats,
    shrink_plan,
)
