"""Deterministic fault injection for the resilience runtime.

Real failure modes on preemptible TPU fleets — hard crashes, SIGTERM
preemption notices, torn checkpoint writes — are nondeterministic by
nature, which makes "survives preemption" untestable unless the faults
themselves become deterministic.  This module is that harness:

  * ``--inject-fault crash@N`` — raise :class:`InjectedCrash` when the
    loop is about to execute step N (no final checkpoint: the recovery
    path must come from the last *periodic* save);
  * ``--inject-fault preempt@N`` — deliver a real ``SIGTERM`` to this
    process at step N, exercising the supervisor's graceful-shutdown
    path (drain pump, flush telemetry, final checkpoint, clean exit);
  * ``crash@N:label`` / ``preempt@N:label`` — scope the fault to one
    named leg of a multi-leg driver (the zero A/B scripts' ``baseline``
    / ``sharded`` legs);
  * ``--inject-fault kill_worker@N:k`` — the elastic-runtime fault: at
    step N, worker rank ``k`` dies without warning.  Under the
    multi-process launcher the targeted worker drops a heartbeat
    ``.dead`` breadcrumb and SIGKILLs itself; in the single-process
    CPU-mesh sim it raises :class:`~.elastic.WorkerLost` — the
    deterministic twin the :class:`~.elastic.ElasticSupervisor` shrink
    path consumes;
  * ``--inject-fault hang@N`` — wedge the collective watchdog at step
    N, the deterministic form of a rank dying *inside* a collective:
    the next pump sync point blocks forever and the watchdog converts
    it into a :class:`~.elastic.StepTimeoutError` (needs
    ``--watchdog-timeout`` > 0, enforced loudly);
  * ``--inject-fault slow@N:ms`` — a straggler: sleep ``ms`` at step N.
    Must NOT trip the heartbeat monitor (its timeout bounds detection
    of *death*, not slowness);
  * the serving-fleet kinds (checked per decode *burst*, not per
    training step): ``kill_replica@N:k`` — replica ``k`` dies without
    warning at its burst N (raises :class:`~.elastic.WorkerLost`; the
    fleet re-enqueues its in-flight requests onto survivors);
    ``hang_decode@N:k`` — wedge replica ``k``'s watchdog at burst N so
    its next burst converts to a :class:`~.elastic.StepTimeoutError`;
    ``slow_replica@N:ms`` — straggler burst: sleep ``ms`` at burst N;
    ``corrupt_swap`` — no step: tear the hot-swap checkpoint before the
    fleet restores it, pinning that a torn swap leaves the fleet
    serving on the old weights;
  * :func:`truncate_checkpoint` / :func:`corrupt_checkpoint` — tamper
    with a saved step's files on disk, for pinning that a torn restore
    fails with a readable error instead of a tensorstore traceback.

Faults fire exactly once per process (the injector is shared across
in-process restart attempts), so a resumed segment runs to completion.
"""

from __future__ import annotations

import os
import re
import signal
import time
from dataclasses import dataclass
from pathlib import Path


@dataclass(frozen=True)
class FaultKindInfo:
    """One registered fault kind.  Everything derived from the spec
    grammar — the valid-kind tuple, the integer-target rule, the parse
    error message's examples — reads from :data:`FAULT_REGISTRY`, so a
    new kind cannot drift out of sync with its validation."""
    name: str
    int_target: bool     # :target is an integer, not a leg label
    target_what: str     # what the integer means, for error messages
    step_required: bool  # "@STEP" mandatory (False: fires at a
                         # context-defined moment, e.g. swap time)
    example: str


FAULT_REGISTRY: dict[str, FaultKindInfo] = {k.name: k for k in (
    FaultKindInfo("crash", False, "", True, "crash@5"),
    FaultKindInfo("preempt", False, "", True, "preempt@8:sharded"),
    FaultKindInfo("kill_worker", True, "worker rank", True,
                  "kill_worker@5:3"),
    FaultKindInfo("hang", False, "", True, "hang@4"),
    FaultKindInfo("slow", True, "milliseconds", True, "slow@3:50"),
    FaultKindInfo("kill_replica", True, "replica index", True,
                  "kill_replica@2:1"),
    FaultKindInfo("hang_decode", True, "replica index", True,
                  "hang_decode@2:0"),
    FaultKindInfo("slow_replica", True, "milliseconds", True,
                  "slow_replica@1:80"),
    FaultKindInfo("corrupt_swap", False, "", False, "corrupt_swap"),
)}

FAULT_KINDS = tuple(FAULT_REGISTRY)
#: kinds whose ``:target`` suffix is an integer (worker rank / replica
#: index / milliseconds), not a leg label — derived, never hand-listed
_INT_TARGET_KINDS = tuple(
    k for k, info in FAULT_REGISTRY.items() if info.int_target)
#: kinds consumed by the serving fleet (per-burst), not the train loop
SERVING_FAULT_KINDS = (
    "kill_replica", "hang_decode", "slow_replica", "corrupt_swap")
_SPEC_RE = re.compile(
    r"^(?P<kind>[a-z_]+)(?:@(?P<step>\d+))?(?::(?P<target>[\w-]+))?$")


class InjectedCrash(RuntimeError):
    """The simulated hard failure — semantically a power cut: no
    graceful path runs, no final checkpoint is taken."""


@dataclass(frozen=True)
class FaultSpec:
    kind: str            # one of FAULT_KINDS (see FAULT_REGISTRY)
    step: int            # loop step / decode burst at which it fires
    target: str = ""     # scope label or int target ("" = any leg)

    def __str__(self) -> str:
        base = self.kind
        if FAULT_REGISTRY[self.kind].step_required:
            base = f"{base}@{self.step}"
        return f"{base}:{self.target}" if self.target else base


def parse_fault_spec(spec: str | None) -> FaultSpec | None:
    """``"crash@5"`` / ``"preempt@8:sharded"`` -> FaultSpec; None/""
    -> None.  Bad specs fail loudly (a typo'd fault flag that silently
    never fires would make a passing resilience test meaningless)."""
    if not spec:
        return None
    m = _SPEC_RE.match(spec.strip())
    if not m or m.group("kind") not in FAULT_REGISTRY:
        examples = ", ".join(
            info.example for info in FAULT_REGISTRY.values())
        raise SystemExit(
            f"--inject-fault {spec!r} not understood: expected "
            f"KIND@STEP[:target] with KIND in {'/'.join(FAULT_KINDS)} "
            f"(e.g. {examples})")
    kind, target = m.group("kind"), m.group("target") or ""
    info = FAULT_REGISTRY[kind]
    if m.group("step") is None and info.step_required:
        raise SystemExit(
            f"--inject-fault {spec!r}: {kind} needs @STEP "
            f"(e.g. {info.example})")
    if info.int_target and target and not target.isdigit():
        raise SystemExit(
            f"--inject-fault {spec!r}: {kind}'s :target is a "
            f"{info.target_what} (an integer), got {target!r}")
    return FaultSpec(kind=kind, step=int(m.group("step") or 0),
                     target=target)


class FaultInjector:
    """One-shot trigger checked at the top of every loop iteration."""

    def __init__(self, spec: FaultSpec | None):
        self.spec = spec
        self.fired = False

    def check(self, step: int, shutdown=None, scope: str = "",
              watchdog=None) -> None:
        """Fire the configured fault if ``step``/``scope`` match.
        ``crash`` raises; ``preempt`` delivers SIGTERM to this process
        and returns once the handler has observed it (deterministic for
        the caller's next ``shutdown.requested`` check); ``kill_worker``
        SIGKILLs the targeted spawned worker (or raises
        :class:`~.elastic.WorkerLost` in the single-process sim);
        ``hang`` wedges ``watchdog``; ``slow`` sleeps its target ms."""
        if self.fired or self.spec is None or step != self.spec.step:
            return
        if self.spec.kind in SERVING_FAULT_KINDS:
            return  # fleet-scoped: fired via check_serving / swap path
        if self.spec.kind in ("crash", "preempt") \
                and self.spec.target and self.spec.target != scope:
            return
        self.fired = True
        kind = self.spec.kind
        if kind == "crash":
            raise InjectedCrash(
                f"injected crash at step {step}"
                + (f" ({scope})" if scope else ""))
        if kind == "slow":
            # multi-process straggler selection: every worker parses the
            # same --inject-fault argv, so without a filter ALL ranks
            # would sleep and no rank lags its peers.  DTS_FAULT_RANK
            # (set via LaunchConfig.env) restricts the sleep to one
            # rank — the shape fleet_timeline's straggler report must
            # attribute.  Unset = legacy behavior (every parser fires).
            only = os.environ.get("DTS_FAULT_RANK")
            if only is not None and only != "" and \
                    int(only) != int(os.environ.get("DTS_PROCESS_ID",
                                                    "0") or 0):
                return
            time.sleep(int(self.spec.target or "100") / 1000.0)
            return
        if kind == "hang":
            if watchdog is None:
                raise SystemExit(
                    f"--inject-fault hang@{step} needs a collective "
                    f"watchdog — pass --watchdog-timeout SECONDS > 0, "
                    f"otherwise the injected hang would block forever")
            watchdog.wedge()
            return
        if kind == "kill_worker":
            rank = int(self.spec.target or "0")
            proc_rank = os.environ.get("DTS_PROCESS_ID")
            if proc_rank is not None:
                # real spawned worker: only the targeted rank dies —
                # breadcrumb first so the coordinator detects instantly
                if int(proc_rank) == rank:
                    hb = os.environ.get("DTS_HEARTBEAT_DIR")
                    if hb:
                        from .elastic import Heartbeat
                        Heartbeat(hb, rank).mark_dead(
                            f"kill_worker@{step}")
                    os.kill(os.getpid(), signal.SIGKILL)
                return
            # single-process CPU-mesh sim: the deterministic twin of a
            # SIGKILLed worker is losing that rank's devices mid-run
            from .elastic import WorkerLost
            raise WorkerLost([rank], step=step, trigger="kill_worker")
        os.kill(os.getpid(), signal.SIGTERM)
        # CPython runs the handler between bytecodes; wait until the
        # flag is visible so the caller's very next check sees it
        deadline = time.monotonic() + 2.0
        while shutdown is not None and not shutdown.requested \
                and time.monotonic() < deadline:
            time.sleep(0.001)

    def check_serving(self, replica: int, burst: int,
                      watchdog=None) -> None:
        """Serving-fleet twin of :meth:`check`, called at the top of
        each replica's decode burst with that replica's own burst
        counter.  ``kill_replica`` raises
        :class:`~.elastic.WorkerLost` for the targeted replica (the
        fleet's failover path consumes it); ``hang_decode`` wedges the
        replica's watchdog so the burst's sync point converts to a
        :class:`~.elastic.StepTimeoutError`; ``slow_replica`` sleeps
        its target ms on whichever replica reaches burst N first.
        ``corrupt_swap`` never fires here — the fleet consumes it at
        swap time (see :meth:`wants_corrupt_swap`)."""
        if self.fired or self.spec is None:
            return
        kind = self.spec.kind
        if kind not in ("kill_replica", "hang_decode", "slow_replica"):
            return
        if burst != self.spec.step:
            return
        if kind in ("kill_replica", "hang_decode") \
                and int(self.spec.target or "0") != replica:
            return
        self.fired = True
        if kind == "slow_replica":
            time.sleep(int(self.spec.target or "100") / 1000.0)
            return
        if kind == "hang_decode":
            if watchdog is None:
                raise SystemExit(
                    f"--inject-fault hang_decode@{burst} needs a "
                    f"decode watchdog — pass --watchdog-timeout "
                    f"SECONDS > 0, otherwise the injected hang would "
                    f"block forever")
            watchdog.wedge()
            return
        from .elastic import WorkerLost
        raise WorkerLost([replica], step=burst, trigger="kill_replica")

    @staticmethod
    def unreaped(procs) -> list[int]:
        """Alias of :func:`unreaped_workers` on the injector, so fault
        call sites can verify the kill they caused was fully collected."""
        return unreaped_workers(procs)

    def wants_corrupt_swap(self) -> bool:
        """True exactly once when the configured fault is
        ``corrupt_swap`` — the fleet calls this at swap time and, if
        true, tears the incoming checkpoint before restoring it."""
        if self.fired or self.spec is None \
                or self.spec.kind != "corrupt_swap":
            return False
        self.fired = True
        return True


# ---- reap verification (coordinator side) --------------------------------

def unreaped_workers(procs) -> list[int]:
    """Pids of spawned workers that are NOT fully collected: either
    never waited on (``returncode`` unset) or still pinned as a zombie
    in the kernel process table.  The ``kill_worker`` fault SIGKILLs a
    real process; before the coordinator may shrink the group and
    relaunch, the kill must have been *reaped* — a zombie keeps its pid
    entry (and on a real host its device slots) and would poison the
    next attempt.  Empty list == clean teardown."""
    bad = []
    for p in procs:
        pid = getattr(p, "pid", None)
        if getattr(p, "returncode", None) is None:
            bad.append(pid)
            continue
        try:
            stat = Path(f"/proc/{pid}/stat").read_text()
        except OSError:
            continue   # no /proc entry: fully reaped (or non-Linux)
        # state is the field after the parenthesized comm (which may
        # itself contain spaces/parens — split on the LAST close-paren)
        state = stat.rsplit(")", 1)[-1].split()
        if state and state[0] == "Z":
            bad.append(pid)
    return bad


# ---- checkpoint tampering (tests + manual debugging) ---------------------

def _step_files(directory, step: int | None) -> list[Path]:
    root = Path(directory)
    if step is None:
        step_dirs = sorted((d for d in root.iterdir()
                            if d.is_dir() and d.name.isdigit()),
                           key=lambda d: int(d.name))
        if not step_dirs:
            raise FileNotFoundError(f"no checkpoint step dirs in {root}")
        root = step_dirs[-1]
    else:
        root = root / str(step)
    files = [p for p in root.rglob("*") if p.is_file()]
    if not files:
        raise FileNotFoundError(f"no files under checkpoint step {root}")
    return files


def truncate_checkpoint(directory, step: int | None = None,
                        *, keep_bytes: int = 8) -> list[Path]:
    """Truncate every payload file of a saved step — the torn-write
    shape a preemption mid-flush leaves behind (a tiny array's bytes
    can hide in more than one tensorstore file, so tearing just the
    largest file may leave a restorable copy).  Returns the mangled
    paths."""
    files = _step_files(directory, step)
    for p in files:
        with open(p, "r+b") as f:
            f.truncate(min(keep_bytes, p.stat().st_size))
    return files


def corrupt_checkpoint(directory, step: int | None = None) -> list[Path]:
    """Overwrite the head of every file in a saved step with garbage —
    the bit-rot/partial-overwrite shape.  Returns the mangled paths."""
    files = _step_files(directory, step)
    for p in files:
        size = p.stat().st_size
        with open(p, "r+b") as f:
            f.write(b"\xde\xad\xbe\xef" * max(1, min(size, 64) // 4))
    return files
