"""Elastic mesh runtime: failure detection + shrink-to-survivors resume.

PR 4's resilience runtime can reshard a checkpoint into a *different*
mesh, but nothing could *decide* to: there was no notion of a worker
dying mid-run, and a hung collective blocked forever.  This module is
the deciding layer, in three parts:

  * **failure detection** — :class:`Heartbeat` (each worker touches
    ``worker_<rank>.hb`` every completed step; a ``kill_worker`` fault
    drops a ``worker_<rank>.dead`` breadcrumb first so detection is
    instant) and :class:`HeartbeatMonitor` (the launcher-coordinator
    probe: a worker is declared lost after ``timeout_s`` without a
    beat — a *bounded* interval, never an indefinite collective hang);
  * **shrink-to-survivors** — :func:`shrink_plan` maps (world size,
    lost ranks) to the next viable mesh: the largest power-of-two
    worker count the survivors can fill, survivors chosen
    deterministically lowest-rank-first (8→4→2 on the CPU sim).
    :class:`ElasticSupervisor` extends the restart loop: a
    :class:`WorkerLost` or :class:`StepTimeoutError` tears the attempt
    down, shrinks the world, and the next attempt rebuilds its mesh
    from the survivor devices, restores the latest RunState through
    the existing reshard path, fast-forwards the host data cursor
    (global batches are world-size-invariant, so the cursor carries
    over unchanged and every batch is consumed exactly once), and
    re-derives + re-verifies the strategy's CollectiveContract at the
    new world size.  Every transition is recorded as first-class
    lineage (old/new world, trigger, lost ranks, step) in the
    checkpoint sidecar and ``manifest.json``;
  * **collective watchdog** — :class:`Watchdog` wraps the step pump's
    dispatch sync points: a blocking wait that does not return within
    ``timeout_s`` raises a diagnosable :class:`StepTimeoutError`
    carrying the in-flight step index and the last contract verdict,
    which feeds the same shrink path.  The deterministic ``hang@N``
    fault wedges the watchdog the way a dead peer wedges a collective.

The headline guarantee, pinned by ``tests/test_elastic.py`` on the
8-way CPU mesh: ``kill_worker@5`` on a ddp run and a sharded zero3 run
→ the supervisor shrinks to 4 survivors and the post-transition loss
sequence is bitwise-identical to a clean run launched on a 4-way mesh
from the same checkpoint.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path

from .faults import InjectedCrash
from .supervisor import Supervisor


class WorkerLost(RuntimeError):
    """One or more workers of the current mesh are gone (SIGKILLed,
    preempted without notice, or declared dead by the heartbeat
    monitor).  Restartable under :class:`ElasticSupervisor`, fatal
    under the plain :class:`~.supervisor.Supervisor`."""

    def __init__(self, ranks, *, step: int | None = None,
                 trigger: str = "worker_lost"):
        self.ranks = sorted(int(r) for r in ranks)
        self.step = step
        self.trigger = trigger
        at = f" at step {step}" if step is not None else ""
        super().__init__(f"worker(s) {self.ranks} lost{at} ({trigger})")


class StepTimeoutError(RuntimeError):
    """A pump sync point did not retire within the watchdog budget —
    the diagnosable form of a hung collective.  Carries the in-flight
    step index and the last contract verdict so the failure names the
    choreography it hung inside, instead of a silent deadlock."""

    def __init__(self, *, step: int | None = None,
                 timeout_s: float | None = None,
                 contract: str | None = None):
        self.step = step
        self.timeout_s = timeout_s
        self.contract = contract
        msg = (f"step {step if step is not None else '?'} did not retire "
               f"within {timeout_s:.1f}s — hung collective or wedged rank"
               if timeout_s is not None else
               f"step {step} did not retire — hung collective")
        if contract:
            msg += f"; last contract verdict: {contract}"
        super().__init__(msg)


# ------------------------------------------------------------- heartbeats

def _hb_path(directory, rank: int) -> Path:
    return Path(directory) / f"worker_{int(rank)}.hb"


def _dead_path(directory, rank: int) -> Path:
    return Path(directory) / f"worker_{int(rank)}.dead"


class Heartbeat:
    """Per-worker liveness file.  ``beat(step)`` atomically rewrites
    ``worker_<rank>.hb`` with the last completed step and a wall-clock
    stamp; ``mark_dead`` drops a ``.dead`` breadcrumb (written by the
    ``kill_worker`` fault right before SIGKILL) so the monitor learns of
    a deliberate death instantly instead of after the stale timeout."""

    def __init__(self, directory, rank: int = 0):
        self.directory = Path(directory)
        self.rank = int(rank)
        self.directory.mkdir(parents=True, exist_ok=True)

    def beat(self, step: int) -> None:
        path = _hb_path(self.directory, self.rank)
        tmp = path.with_suffix(".hb.tmp")
        tmp.write_text(json.dumps({"rank": self.rank, "step": int(step),
                                   "time": time.time()}))
        os.replace(tmp, path)   # atomic: the monitor never reads a torn beat

    def mark_dead(self, reason: str = "") -> None:
        _dead_path(self.directory, self.rank).write_text(
            json.dumps({"rank": self.rank, "reason": reason,
                        "time": time.time()}))


def read_heartbeats(directory) -> dict[int, dict]:
    """rank -> last beat record (empty when the dir doesn't exist)."""
    out: dict[int, dict] = {}
    root = Path(directory)
    if not root.is_dir():
        return out
    for p in root.glob("worker_*.hb"):
        try:
            out[int(p.stem.split("_", 1)[1])] = json.loads(p.read_text())
        except (ValueError, json.JSONDecodeError, OSError):
            continue
    return out


class HeartbeatMonitor:
    """The coordinator-side liveness probe: declares worker ``k`` lost
    when (a) a ``.dead`` breadcrumb exists (instant), or (b) its last
    beat — or, before the first beat, the monitor's start — is older
    than ``timeout_s``.  The bound is the contract: the supervisor
    learns "worker k is gone" within ``timeout_s`` + one poll interval,
    instead of hanging in a collective forever.  Stragglers that are
    merely slow (``slow@N:ms`` with ms < timeout) never trip it."""

    def __init__(self, directory, nworkers: int, *,
                 timeout_s: float = 10.0,
                 startup_grace_s: float | None = None):
        self.directory = Path(directory)
        self.nworkers = int(nworkers)
        self.timeout_s = float(timeout_s)
        # a worker that has never beaten is still importing jax /
        # compiling — judge it against the (much longer) startup grace,
        # not the steady-state beat timeout, or bring-up reads as death
        self.startup_grace_s = (float(startup_grace_s)
                                if startup_grace_s is not None
                                else max(self.timeout_s, 120.0))
        self.started = time.time()
        self.directory.mkdir(parents=True, exist_ok=True)

    def _is_stale(self, path: Path) -> bool:
        """A liveness file written BEFORE this monitor's attempt started
        belongs to a previous run sharing the directory: a stale ``.hb``
        must not mask a worker that died before its first beat, and a
        stale ``.dead`` must not kill a worker that is alive now."""
        try:
            return os.path.getmtime(path) < self.started
        except OSError:
            return True   # vanished between glob and stat: not evidence

    def dead_workers(self, now: float | None = None) -> list[int]:
        now = time.time() if now is None else now
        beats = read_heartbeats(self.directory)
        dead = []
        for rank in range(self.nworkers):
            dp = _dead_path(self.directory, rank)
            if dp.exists() and not self._is_stale(dp):
                dead.append(rank)
                continue
            beat = beats.get(rank)
            if beat is not None \
                    and beat.get("time", self.started) < self.started:
                # pre-dates this attempt: treat as never-beaten
                beat = None
            if beat is None:
                if now - self.started > self.startup_grace_s:
                    dead.append(rank)
            elif now - beat.get("time", self.started) > self.timeout_s:
                dead.append(rank)
        return dead


# ------------------------------------------------------------ shrink plan

@dataclass(frozen=True)
class ElasticPlan:
    """One mesh transition: who survived and what the next world is."""
    old_world: int
    new_world: int
    survivors: tuple[int, ...]    # ranks kept (lowest-first, determinism)
    lost_ranks: tuple[int, ...]

    def to_dict(self) -> dict:
        return {"old_world": self.old_world, "new_world": self.new_world,
                "survivors": list(self.survivors),
                "lost_ranks": list(self.lost_ranks)}


def shrink_plan(world: int, lost_ranks, *, min_world: int = 1,
                force_shrink: bool = False) -> ElasticPlan:
    """Deterministic shrink policy: drop the lost ranks, keep the
    lowest-ranked survivors, and round the world DOWN to the largest
    power of two they can fill (strategies assume power-of-two meshes;
    8 lose 1 → 7 survivors → world 4 → 2 → 1).  ``force_shrink`` (the
    hung-step path, where the wedged rank is unknown) halves the world
    even when no specific rank is named.  Below ``min_world`` the run
    is unrecoverable and this raises."""
    lost = sorted({int(r) for r in lost_ranks if 0 <= int(r) < world})
    survivors = [r for r in range(world) if r not in lost]
    cap = len(survivors)
    if force_shrink and not lost:
        cap = max(world // 2, 0)
    new_world = 1
    while new_world * 2 <= cap:
        new_world *= 2
    if cap < 1 or new_world < min_world:
        raise WorkerLost(lost or list(range(world // 2, world)),
                         trigger="unrecoverable")
    return ElasticPlan(old_world=world, new_world=new_world,
                       survivors=tuple(survivors[:new_world]),
                       lost_ranks=tuple(lost))


# --------------------------------------------------------------- watchdog

class Watchdog:
    """Timeout/backoff wrapper around the pump's blocking sync points.

    ``block(fn, *args, step=i)`` runs the wait in a daemon thread and
    joins with the budget; a wait that outlives it raises
    :class:`StepTimeoutError` with the in-flight step index and the
    last contract verdict from ``context()`` — the wedged thread is
    abandoned (the process is about to be torn down and relaunched on
    the survivors, which is the only real cure for a hung collective).

    ``wedge()`` is the deterministic-fault hook: the ``hang@N`` fault
    calls it, after which the next guarded wait blocks on an event that
    never fires — exactly the shape a dead peer gives a collective."""

    def __init__(self, timeout_s: float, *, context=None):
        self.timeout_s = float(timeout_s)
        self._context = context
        self._wedged = False

    def wedge(self) -> None:
        self._wedged = True

    def block(self, fn, *args, step: int | None = None):
        if self.timeout_s <= 0 and not self._wedged:
            return fn(*args)
        done: dict = {}
        never = threading.Event()

        def run():
            try:
                if self._wedged:
                    never.wait()   # the injected hung collective
                done["value"] = fn(*args)
            except BaseException as e:  # noqa: BLE001 - re-raised below
                done["error"] = e

        t = threading.Thread(target=run, daemon=True,
                             name="collective-watchdog-wait")
        t.start()
        t.join(self.timeout_s if self.timeout_s > 0 else None)
        if t.is_alive():
            info = {}
            if self._context is not None:
                try:
                    info = dict(self._context() or {})
                except Exception:  # noqa: BLE001 - diagnosis must not mask
                    pass
            raise StepTimeoutError(step=step, timeout_s=self.timeout_s,
                                   contract=info.get("contract"))
        if "error" in done:
            raise done["error"]
        return done.get("value")


# ------------------------------------------------------ elastic supervisor

class ElasticSupervisor(Supervisor):
    """The restart loop that survives worker loss.  On top of the base
    crash/preemption policy: :class:`WorkerLost` and
    :class:`StepTimeoutError` consume restart budget, shrink the world
    via :func:`shrink_plan`, and the next attempt's context carries the
    smaller ``world_size`` — the driver rebuilds its mesh from the
    survivor devices, the restore reshards into it, and the re-derived
    contract is re-verified before any step runs."""

    def __init__(self, *, min_world: int = 1, **kw):
        super().__init__(**kw)
        self.min_world = int(min_world)
        self.transitions: list[dict] = []

    _restartable = (InjectedCrash, WorkerLost, StepTimeoutError)

    @property
    def active(self) -> bool:
        return True   # elastic runs always record lineage

    def _world(self) -> int:
        if self.world_size:
            return int(self.world_size)
        import jax
        return len(jax.devices())

    def _make_ctx(self, attempt, shutdown):
        ctx = super()._make_ctx(attempt, shutdown)
        ctx._lineage["elastic"] = True
        ctx._lineage["mesh_transitions"] = self.transitions
        return ctx

    def _on_failure(self, e, ctx, attempt) -> bool:
        if not isinstance(e, (WorkerLost, StepTimeoutError)):
            return super()._on_failure(e, ctx, attempt)
        if attempt >= self.max_restarts:
            return False
        old = self._world()
        lost = list(getattr(e, "ranks", []) or [])
        trigger = getattr(e, "trigger", None) or (
            "step_timeout" if isinstance(e, StepTimeoutError)
            else "worker_lost")
        try:
            plan = shrink_plan(old, lost, min_world=self.min_world,
                               force_shrink=isinstance(e, StepTimeoutError))
        except WorkerLost:
            print(f"[elastic] {e} — no viable mesh below world {old} "
                  f"(min_world {self.min_world}); giving up")
            return False
        self.transitions.append({
            "old_world": plan.old_world, "new_world": plan.new_world,
            "trigger": trigger, "lost_ranks": list(plan.lost_ranks),
            "step": getattr(e, "step", None),
            "survivors": list(plan.survivors),
        })
        self.segments.append({
            "attempt": attempt, "scope": "", "run_id": None,
            "start_step": ctx.start_step, "end_step": ctx._last_step,
            "status": trigger, "error": str(e)})
        self.world_size = plan.new_world
        print(f"[elastic] {e}; shrinking mesh {plan.old_world} -> "
              f"{plan.new_world} (survivors {list(plan.survivors)}), "
              f"restart {attempt + 1}/{self.max_restarts}")
        return True
