"""In-process restart supervisor + the per-attempt driver context.

The process-level contract a preemptible fleet imposes:

  * SIGTERM is a *notice*, not a kill — the run gets a grace window to
    drain the step pump (resolving in-flight losses), flush telemetry,
    take a final checkpoint, and exit cleanly (:class:`GracefulShutdown`
    + ``ResilienceContext.finalize``);
  * a crash or preemption with restart budget left resumes from the
    latest checkpoint with exponential backoff (:class:`Supervisor`),
    and every segment is recorded as *lineage* — in the checkpoint
    sidecar AND in each segment's telemetry ``manifest.json``, which
    ``scripts/report.py`` renders as stitched segments;
  * on resume the strategy's :class:`CollectiveContract` is re-verified
    (``verify_contract``) so a restore that silently changed sharding
    choreography fails loudly instead of training wrong.

Every strategy driver runs its leg body through ``Supervisor.run``; when
nothing resilience-related is configured the supervisor is inert — one
pass, no checkpoint manager, no signal juggling beyond install/restore —
so the wiring costs the common path nothing.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time

from .faults import FaultInjector, InjectedCrash, parse_fault_spec
from .state import Checkpointer, RunState

LINEAGE_SCHEMA_VERSION = 1


class Preempted(RuntimeError):
    """Raised after the graceful-shutdown path completed (final
    checkpoint committed, telemetry finalized) to unwind to the
    supervisor, which either restarts or exits cleanly."""

    def __init__(self, step: int, scope: str = ""):
        super().__init__(f"preempted after step {step}"
                         + (f" ({scope})" if scope else ""))
        self.step = step
        self.scope = scope


class GracefulShutdown:
    """SIGTERM -> a flag the step loop polls.  Installs only in the
    main thread (signal.signal's requirement); elsewhere — or for the
    fault injector's direct path — ``trigger()`` sets the same flag."""

    def __init__(self):
        self.requested = False
        self._prev = None
        self._installed = False

    def trigger(self, signum=None, frame=None) -> None:
        self.requested = True

    def install(self) -> "GracefulShutdown":
        if threading.current_thread() is threading.main_thread():
            self._prev = signal.signal(signal.SIGTERM, self.trigger)
            self._installed = True
        return self

    def uninstall(self) -> None:
        if self._installed:
            signal.signal(signal.SIGTERM, self._prev)
            self._installed = False

    def __enter__(self) -> "GracefulShutdown":
        return self.install()

    def __exit__(self, *exc) -> bool:
        self.uninstall()
        return False


class ResilienceContext:
    """What one attempt of one leg sees.  Drivers call, in loop order:

        rs = ctx.restore(like=RunState(params=..., opt_state=...,
                                       prng_key=key))      # maybe None
        ctx.verify_contract(verdict)                       # after counts
        for i, batch in zip(range(ctx.start_step, n), pref):
            if ctx.should_stop(i):                         # faults+SIGTERM
                break
            ... step ...
            synced = pump.emit(loss, ...)
            ctx.after_step(i, synced, state_fn)            # async ckpt
        ctx.finalize(telem)    # final save; raises Preempted on SIGTERM

    Multi-leg drivers (``_zero_driver``) take per-leg children via
    ``ctx.scope("baseline")`` — own checkpoint subdirectory and resume
    position, shared shutdown flag / fault injector / lineage.
    """

    def __init__(self, *, attempt: int = 0, resume: bool = False,
                 ckptr: Checkpointer | None = None,
                 injector: FaultInjector | None = None,
                 shutdown: GracefulShutdown | None = None,
                 lineage: dict | None = None, label: str = "",
                 supervisor: "Supervisor | None" = None,
                 world_size: int | None = None,
                 watchdog_timeout: float = 0.0,
                 heartbeat_dir: str | None = None):
        self.attempt = attempt
        self.resume = resume
        self.ckptr = ckptr
        self.injector = injector or FaultInjector(None)
        self.shutdown = shutdown or GracefulShutdown()
        self.label = label
        self._lineage = lineage if lineage is not None else {}
        self._sup = supervisor
        self.world_size = int(world_size) if world_size else None
        self.watchdog_timeout = float(watchdog_timeout or 0.0)
        self.heartbeat_dir = heartbeat_dir
        self.start_step = 0
        self.restored: RunState | None = None
        self.last_verdict = None
        self._watchdog = None
        self._heartbeat = None
        self._restored_losses: list[float] = []
        self._state_fn = None
        self._last_step: int | None = None
        self._preempted_at: int | None = None
        self._children: list[ResilienceContext] = []

    # ---- configuration-derived properties --------------------------------
    @property
    def active(self) -> bool:
        return (self.ckptr is not None or self.injector.spec is not None
                or bool(self._lineage))

    @property
    def data_cursor(self) -> int:
        """Host batches segment 1..n-1 already consumed — skip this many
        from the (deterministically rebuilt) batch stream on resume."""
        return self.restored.data_cursor if self.restored else 0

    def scope(self, label: str) -> "ResilienceContext":
        child = ResilienceContext(
            attempt=self.attempt, resume=self.resume,
            ckptr=Checkpointer(os.path.join(self.ckptr.directory, label),
                               every=self.ckptr.every,
                               keep=self.ckptr.keep,
                               fingerprint=self.ckptr.fingerprint)
            if self.ckptr else None,
            injector=self.injector, shutdown=self.shutdown,
            lineage=self._lineage, label=label, supervisor=self._sup,
            world_size=self.world_size,
            watchdog_timeout=self.watchdog_timeout,
            heartbeat_dir=self.heartbeat_dir)
        self._children.append(child)
        return child

    # ---- elastic mesh -----------------------------------------------------
    def mesh_devices(self):
        """The device subset this attempt's mesh is built from: the
        first ``world_size`` devices (the deterministic survivor slice
        after an elastic shrink), or None when the run owns every
        visible device — ``make_mesh(devices=None)`` is the default."""
        if not self.world_size:
            return None
        import jax
        devs = jax.devices()
        if self.world_size > len(devs):
            raise SystemExit(
                f"--world-size {self.world_size} exceeds the "
                f"{len(devs)} visible devices")
        return devs[:self.world_size]

    def make_watchdog(self):
        """The collective watchdog the driver hands the step pump:
        None when ``--watchdog-timeout`` is unset (zero-cost default);
        otherwise a :class:`~.elastic.Watchdog` whose timeout error
        carries this context's last contract verdict.  Also the wedge
        target of the deterministic ``hang@N`` fault."""
        if self.watchdog_timeout > 0 and self._watchdog is None:
            from .elastic import Watchdog
            self._watchdog = Watchdog(
                self.watchdog_timeout,
                context=lambda: {
                    "contract": self.last_verdict.summary()
                    if self.last_verdict is not None else None})
        return self._watchdog

    def _beat(self, step: int) -> None:
        if not self.heartbeat_dir:
            return
        if self._heartbeat is None:
            from .elastic import Heartbeat
            self._heartbeat = Heartbeat(
                self.heartbeat_dir,
                rank=int(os.environ.get("DTS_PROCESS_ID", "0")))
        self._heartbeat.beat(step)

    # ---- resume ----------------------------------------------------------
    def restore(self, like: RunState) -> RunState | None:
        """Restore the latest RunState when this attempt should resume
        (``--resume`` or a restart), else None.  Sets ``start_step`` /
        ``data_cursor`` and adopts the saved loss log so downstream
        reporting sees the stitched sequence."""
        if not (self.resume and self.ckptr is not None):
            return None
        rs = self.ckptr.restore_latest(like)
        if rs is None:
            return None
        self.restored = rs
        self.start_step = rs.step + 1
        self._restored_losses = list(rs.loss_log)
        self._scope_lineage()["resumed_from_step"] = rs.step
        # a cross-process resume carries the prior segments in the
        # checkpoint sidecar; merge them when the supervisor has none
        prior = (rs.lineage or {}).get("segments")
        if prior and self._sup is not None and not self._sup.segments:
            self._sup.segments.extend(prior)
            self._lineage["segments"] = self._sup.segments
        print(f"[resilience] resumed{' ' + self.label if self.label else ''}"
              f" from step {rs.step} in {self.ckptr.directory} "
              f"(cursor {rs.data_cursor}, {len(rs.loss_log)} losses)")
        return rs

    def verify_contract(self, verdict) -> None:
        """Re-check the strategy's collective contract after a restore —
        a resume whose choreography changed (different mesh/sharding
        than the checkpoint expects) must fail loudly, and the verdict
        is recorded in the lineage the manifest captures."""
        self.last_verdict = verdict   # the watchdog attaches this
        if verdict is None or self.restored is None:
            return
        self._scope_lineage()["resume_contract"] = {
            "ok": bool(verdict.ok), "summary": verdict.summary()}
        if not verdict.ok:
            raise SystemExit(
                f"resume aborted: collective contract re-check failed "
                f"after restore{' (' + self.label + ')' if self.label else ''}"
                f" — {verdict.summary()}; the restored state is sharded "
                f"differently than this run's step choreography expects")

    # ---- per-step --------------------------------------------------------
    def should_stop(self, i: int) -> bool:
        """Top-of-iteration check: fires any due injected fault (crash
        raises from here), then reports whether a preemption notice has
        arrived — the loop breaks and ``finalize`` handles the rest."""
        self.injector.check(i, shutdown=self.shutdown, scope=self.label,
                            watchdog=self._watchdog)
        if self.shutdown.requested:
            self._preempted_at = i - 1
            return True
        return False

    def after_step(self, i: int, synced: bool, state_fn) -> None:
        """Record step ``i`` complete; ride the pump's sync schedule for
        due asynchronous checkpoints.  ``state_fn`` is a zero-arg
        closure over the loop's live state — evaluated only when a save
        actually happens."""
        self._state_fn = state_fn
        self._last_step = i
        self._beat(i)
        if self.ckptr is not None:
            self.ckptr.maybe_save(i, lambda: self._stamped(state_fn()),
                                  synced=synced)

    def full_losses(self, new_losses) -> list[float]:
        """Restored segment losses + this segment's — the stitched
        sequence the headline bitwise test compares."""
        return self._restored_losses + [float(l) for l in new_losses]

    # ---- exit ------------------------------------------------------------
    def finalize(self, telem=None) -> None:
        """After the pump has drained: take the final checkpoint (waited
        — the resume step must be fully committed before exit), and on
        preemption finalize telemetry as status="preempted" then raise
        :class:`Preempted` for the supervisor."""
        if self.ckptr is not None and self._state_fn is not None:
            self.ckptr.save_final(self._stamped(self._state_fn()))
        preempted = self.shutdown.requested
        self._record_segment(telem, "preempted" if preempted
                             else "completed")
        if preempted:
            if telem is not None:
                telem.finalize(status="preempted")
            raise Preempted(self._preempted_at
                            if self._preempted_at is not None
                            else (self._last_step if self._last_step
                                  is not None else -1),
                            scope=self.label)

    def manifest_lineage(self) -> dict | None:
        """The lineage block for this attempt's RunManifest; None when
        resilience is inert so plain runs keep a clean manifest."""
        return self._lineage if self.active else None

    def close(self) -> None:
        """Wait out in-flight checkpoint writes — runs in the
        supervisor's finally, crash included (the torn-save guarantee)."""
        for child in self._children:
            child.close()
        if self.ckptr is not None:
            self.ckptr.close()

    # ---- internals -------------------------------------------------------
    def _scope_lineage(self) -> dict:
        if not self.label:
            return self._lineage
        return self._lineage.setdefault("scopes", {}).setdefault(
            self.label, {})

    def _stamped(self, state: RunState) -> RunState:
        state.lineage = dict(state.lineage or {})
        state.lineage.update({
            "schema": LINEAGE_SCHEMA_VERSION,
            "attempt": self.attempt,
            "segments": list(self._sup.segments) if self._sup else [],
        })
        transitions = list(getattr(self._sup, "transitions", None) or [])
        raw = os.environ.get("DTS_MESH_TRANSITIONS")
        if raw:
            # launcher-level elastic shrinks (real worker loss) arrive
            # via env — the survivors' in-process supervisor never saw
            # the transition, only the relaunch
            try:
                transitions = json.loads(raw) + transitions
            except (ValueError, TypeError):
                print(f"[resilience] ignoring malformed "
                      f"DTS_MESH_TRANSITIONS: {raw!r}", file=sys.stderr)
        if transitions:
            state.lineage["mesh_transitions"] = transitions
        return state

    def _record_segment(self, telem, status: str) -> None:
        if self._sup is None or not self.active:
            return
        self._sup.segments.append({
            "attempt": self.attempt,
            "scope": self.label,
            "run_id": getattr(telem, "run_id", None),
            "start_step": self.start_step,
            "end_step": self._last_step,
            "status": status,
        })


class Supervisor:
    """The restart loop.  ``run(leg)`` calls ``leg(ctx)`` with a fresh
    context per attempt; :class:`Preempted` and :class:`InjectedCrash`
    consume restart budget (exponential backoff) and resume from the
    latest checkpoint; anything else propagates.  Exhausted budget after
    a preemption returns a clean ``{"status": "preempted", ...}`` result
    — the preemption contract is a clean exit, not a traceback."""

    #: failure types whose handling may restart the loop (the elastic
    #: subclass widens this with WorkerLost / StepTimeoutError)
    _restartable: tuple = (InjectedCrash,)

    def __init__(self, *, checkpoint_dir=None, checkpoint_every: int = 0,
                 resume: bool = False, max_restarts: int = 0,
                 fault: str | None = None, strategy: str = "",
                 fingerprint: dict | None = None, keep: int = 3,
                 backoff_s: float = 0.25, world_size: int | None = None,
                 watchdog_timeout: float = 0.0,
                 heartbeat_dir: str | None = None):
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every = checkpoint_every
        self.resume = resume
        self.max_restarts = max(int(max_restarts), 0)
        self.spec = parse_fault_spec(fault)
        self.strategy = strategy
        self.fingerprint = dict(fingerprint or {})
        self.keep = keep
        self.backoff_s = backoff_s
        self.world_size = int(world_size) if world_size else None
        self.watchdog_timeout = float(watchdog_timeout or 0.0)
        self.heartbeat_dir = heartbeat_dir
        self.segments: list[dict] = []
        self._injector = FaultInjector(self.spec)   # shared: one-shot

    @classmethod
    def from_config(cls, cfg, strategy: str,
                    extra_fingerprint: dict | None = None) -> "Supervisor":
        fp = {"strategy": strategy,
              "seed": getattr(cfg, "seed", None),
              "batch_size": getattr(cfg, "batch_size", None),
              "precision": getattr(cfg, "precision", None)}
        fp.update(extra_fingerprint or {})
        klass = cls
        if getattr(cfg, "elastic", False):
            from .elastic import ElasticSupervisor
            klass = ElasticSupervisor
        return klass(
            checkpoint_dir=getattr(cfg, "checkpoint_dir", None),
            checkpoint_every=getattr(cfg, "checkpoint_every", 0),
            resume=getattr(cfg, "resume", False),
            max_restarts=getattr(cfg, "max_restarts", 0),
            fault=getattr(cfg, "inject_fault", None),
            strategy=strategy, fingerprint=fp,
            world_size=getattr(cfg, "world_size", 0) or None,
            watchdog_timeout=getattr(cfg, "watchdog_timeout", 0.0) or 0.0,
            heartbeat_dir=getattr(cfg, "heartbeat_dir", None)
            or os.environ.get("DTS_HEARTBEAT_DIR"))

    @property
    def active(self) -> bool:
        return bool(self.checkpoint_dir or self.spec
                    or self.max_restarts or self.resume)

    def _make_ctx(self, attempt: int,
                  shutdown: GracefulShutdown) -> ResilienceContext:
        ckptr = Checkpointer(self.checkpoint_dir,
                             every=self.checkpoint_every,
                             keep=self.keep,
                             fingerprint=self.fingerprint) \
            if self.checkpoint_dir else None
        lineage = {"schema": LINEAGE_SCHEMA_VERSION,
                   "attempt": attempt,
                   "max_restarts": self.max_restarts,
                   "segments": self.segments} if self.active else {}
        return ResilienceContext(
            attempt=attempt, resume=self.resume or attempt > 0,
            ckptr=ckptr, injector=self._injector, shutdown=shutdown,
            lineage=lineage, supervisor=self,
            world_size=self.world_size,
            watchdog_timeout=self.watchdog_timeout,
            heartbeat_dir=self.heartbeat_dir)

    def _on_failure(self, e, ctx, attempt: int) -> bool:
        """Handle one restartable failure; True = restart, False =
        re-raise (budget exhausted / unrecoverable)."""
        if attempt >= self.max_restarts:
            return False
        self.segments.append({
            "attempt": attempt, "scope": "", "run_id": None,
            "start_step": ctx.start_step,
            "end_step": ctx._last_step,
            "status": "crashed", "error": str(e)})
        print(f"[resilience] crashed ({e}); restart "
              f"{attempt + 1}/{self.max_restarts}")
        return True

    def run(self, leg):
        """Run ``leg(ctx)`` under the restart policy and return its
        result (or the clean preempted-status dict)."""
        attempt = 0
        with GracefulShutdown() as shutdown:
            while True:
                ctx = self._make_ctx(attempt, shutdown)
                try:
                    return leg(ctx)
                except Preempted as e:
                    if attempt >= self.max_restarts:
                        print(f"[resilience] preempted at step {e.step} "
                              f"with no restart budget left — exiting "
                              f"cleanly (resume with --resume)")
                        return {"status": "preempted", "step": e.step,
                                "scope": e.scope,
                                "lineage": {"segments": self.segments}}
                    print(f"[resilience] preempted at step {e.step}; "
                          f"restart {attempt + 1}/{self.max_restarts}")
                except self._restartable as e:
                    if not self._on_failure(e, ctx, attempt):
                        raise
                finally:
                    ctx.close()   # torn-save guarantee, every exit path
                # fresh attempt: clear a consumed preemption notice so
                # the resumed segment is not instantly re-preempted
                shutdown.requested = False
                time.sleep(min(8.0, self.backoff_s * (2 ** attempt)))
                attempt += 1
