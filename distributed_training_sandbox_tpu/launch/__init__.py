"""L4 launch/deploy layer (SURVEY.md §1, §2.1): config-driven strategy
launcher with run-id'd trace directories and a run→sync→view loop — the
TPU-native twin of ``modal_utils.py`` + ``DDP/scripts/profile.sh`` +
``DDP/training_utils/trun.py``."""

from . import launcher  # noqa: F401
from .launcher import (  # noqa: F401
    GroupResult, LaunchConfig, RunResult, STRATEGY_SCRIPTS,
    build_launch_command, parse_device_spec, run_elastic_group,
    run_training, sync_traces, view_command)
