"""``python -m distributed_training_sandbox_tpu.launch`` → the CLI
(same entry as the installed ``dts-launch`` script)."""

import sys

from .cli import main

if __name__ == "__main__":
    sys.exit(main())
