"""L4 launch layer: config-driven strategy launcher with run-id'd trace dirs.

TPU-native twin of the reference's Modal launcher library
(``modal_utils.py:21-246``) and of its local ``trun`` wrapper
(``DDP/training_utils/trun.py:16-25``).  The responsibilities transfer; the
substrate changes:

  * the reference builds a cloud container and execs ``torchrun
    --nproc_per_node=N`` inside it; here the "cluster" is a jax device mesh —
    real TPU chips, or ``--cpu-devices N`` simulated devices (the gloo-mode
    twin) — so the SPMD default is ONE Python process per host.  The
    torchrun contract itself is ``nprocs > 1``: the launcher stands up a
    local coordinator (``DTS_COORDINATOR``/``DTS_NUM_PROCESSES``/
    ``DTS_PROCESS_ID`` env, consumed by
    ``utils.mesh.auto_initialize_from_env``) and spawns N workers whose
    simulated devices join ONE global mesh — the
    ``torchrun --standalone --nproc_per_node=N`` twin
    (``modal_utils.py:115-119``).
  * the GPU spec string ``"A10G:2"`` (``modal_utils.get_gpu_count``,
    ``modal_utils.py:60-72``) becomes a device spec ``"tpu"`` / ``"tpu:4"`` /
    ``"cpu:8"``: platform[:count].
  * the trace Volume + ``modal volume get`` retrieval loop
    (``DDP/scripts/profile.sh:97-109``) becomes a local run-id'd trace
    directory (``TRACE_DIR/<run_id>``) plus `sync_traces` (copy to a
    destination, e.g. a mounted bucket or rsync staging dir) and a printed
    TensorBoard recipe (``modal_utils._print_completion_message`` twin).

Config schema (dict or JSON/YAML file — inline dicts are what the reference
uses in every per-dir ``modal_app.py``, e.g. ``zero/modal_app.py:9-17``):

    {"app":      {"name": "zero", "script_dir": "scripts"},
     "devices":  {"spec": "cpu:8", "timeout": 1800},
     "trace":    {"root": "./profiler_traces", "local_dir": "./traces"},
     "launcher": {"env": {...}, "args": [...]}}

Every key has a default; ``LaunchConfig()`` with no args launches on
whatever devices exist.
"""

from __future__ import annotations

import json
import os
import shlex
import shutil
import subprocess
import sys
from dataclasses import dataclass, field
from pathlib import Path

from ..utils.config import build_run_id

#: strategy name -> script filename under script_dir (the `--script zero2.py`
#: surface of `RUN_MODAL.md:7-28`; bare names and `.py` names both accepted).
STRATEGY_SCRIPTS = {
    "ddp": "ddp.py",
    "zero1": "zero1.py",
    "zero2": "zero2.py",
    "zero3": "zero3.py",
    "fsdp": "train_fsdp.py",
    "train_fsdp": "train_fsdp.py",
    "gpipe": "gpipe.py",
    "1f1b": "1f1b.py",
    "interleaved_1f1b": "interleaved_1f1b.py",
    "interleaved": "interleaved_1f1b.py",
    "precision": "precision_benchmark.py",
    "precision_benchmark": "precision_benchmark.py",
    "busbench": "busbench.py",
    "train_sp": "train_sp.py",
    "sp": "train_sp.py",
    "train_tp": "train_tp.py",
    "tp": "train_tp.py",
    "moe": "moe.py",
    "train_moe": "train_moe.py",
    "composable": "train_composable.py",
    "train_composable": "train_composable.py",
    "ddp_utilization": "ddp_utilization.py",
}
# (ops_demo / long_context / memory_waterline / analyze_results /
# moe_bench / moe_profile / zigzag_flops / make_ops_notebook are NOT
# registered: they don't speak the strategy CLI contract the launcher
# injects (--num-steps/--cpu-devices) — run them directly.)

_REPO_ROOT = Path(__file__).resolve().parents[2]


def parse_device_spec(spec: str) -> tuple[str, int | None]:
    """``"cpu:8"`` -> ("cpu", 8); ``"tpu"`` -> ("tpu", None) = all chips.
    Twin of ``modal_utils.get_gpu_count`` (``modal_utils.py:60-72``)."""
    if ":" not in spec:
        return spec, None
    platform, count_str = spec.split(":", 1)
    try:
        count = int(count_str)
    except ValueError as exc:
        raise ValueError(f"Invalid device spec {spec!r}. Expected "
                         f"'PLATFORM:COUNT'.") from exc
    if count < 1:
        raise ValueError("device count must be >= 1")
    return platform, count


@dataclass
class LaunchConfig:
    """Launcher-level knobs, twin of ``ModalConfig`` (``modal_utils.py:21-59``)
    minus the container-image concerns a TPU-VM doesn't have."""
    name: str = "dts"
    script_dir: str | os.PathLike = _REPO_ROOT / "scripts"
    script: str = "fsdp"
    device_spec: str = "tpu"
    #: worker processes per host — the ``torchrun --nproc_per_node=N``
    #: twin (``modal_utils.py:115-119``).  1 = the SPMD default (one
    #: process per host); N > 1 spawns a coordinator env (DTS_* vars) and
    #: N workers whose simulated devices form ONE global mesh.
    nprocs: int = 1
    timeout: float | None = 1800.0          # zero/modal_app.py:12
    trace_root: str | os.PathLike = "./profiler_traces"
    trace_output_dir: str | os.PathLike = "./traces"   # sync destination
    env: dict = field(default_factory=dict)
    extra_args: list = field(default_factory=list)
    #: elastic worker groups (nprocs > 1): on a worker death — process
    #: exit, SIGKILL, or heartbeat staleness past ``heartbeat_timeout``
    #: seconds — tear down the group, shrink to the largest power-of-two
    #: worker count the survivors can fill, and relaunch with --resume
    #: appended (up to ``group_restarts`` times).  The training script's
    #: own checkpoint flags (--checkpoint-dir/--checkpoint-every) ride
    #: in ``extra_args``.
    elastic: bool = False
    group_restarts: int = 1
    heartbeat_timeout: float = 10.0
    #: real-distributed mode (``--distributed``): workers run the
    #: cross-process bring-up barrier after ``jax.distributed``
    #: initialize, with ``bringup_timeout`` seconds budget for both the
    #: rendezvous and the barrier — a missing peer becomes a readable
    #: BringupTimeout / StepTimeoutError in the worker log instead of a
    #: group that hangs until the launch timeout.
    distributed: bool = False
    bringup_timeout: float = 120.0

    @classmethod
    def from_config(cls, config: dict | str | os.PathLike) -> "LaunchConfig":
        """Dict, or path to a JSON/YAML file with the schema in the module
        docstring."""
        if not isinstance(config, dict):
            text = Path(config).read_text()
            if str(config).endswith((".yaml", ".yml")):
                import yaml  # gated: baked into the image with jax
                config = yaml.safe_load(text)
            else:
                config = json.loads(text)
        app = config.get("app", {})
        devices = config.get("devices", {})
        trace = config.get("trace", {})
        launcher = config.get("launcher", {})
        kw = {}
        if "name" in app:
            kw["name"] = app["name"]
        if "script_dir" in app:
            kw["script_dir"] = app["script_dir"]
        if "training_script" in app:
            kw["script"] = app["training_script"]
        if "spec" in devices:
            kw["device_spec"] = devices["spec"]
        if "nprocs" in devices:
            kw["nprocs"] = int(devices["nprocs"])
        if "timeout" in devices:
            kw["timeout"] = devices["timeout"]
        if "root" in trace:
            kw["trace_root"] = trace["root"]
        if "local_dir" in trace:
            kw["trace_output_dir"] = trace["local_dir"]
        if "elastic" in devices:
            kw["elastic"] = bool(devices["elastic"])
        if "group_restarts" in devices:
            kw["group_restarts"] = int(devices["group_restarts"])
        if "heartbeat_timeout" in devices:
            kw["heartbeat_timeout"] = float(devices["heartbeat_timeout"])
        if "distributed" in devices:
            kw["distributed"] = bool(devices["distributed"])
        if "bringup_timeout" in devices:
            kw["bringup_timeout"] = float(devices["bringup_timeout"])
        kw["env"] = dict(launcher.get("env", {}))
        kw["extra_args"] = list(launcher.get("args", []))
        return cls(**kw)

    def resolve_script(self, script: str | None = None) -> Path:
        """Strategy name or filename -> script path, validated the way each
        ``modal_app.py`` local entrypoint validates ``--script``
        (``zero/modal_app.py:21-31``).

        The default script_dir is the source checkout's ``scripts/``; a
        wheel install doesn't ship it, so fall back to ``./scripts`` (run
        from a checkout) before erroring with a pointer to the config."""
        name = script or self.script
        fname = STRATEGY_SCRIPTS.get(name.removesuffix(".py"),
                                     name if name.endswith(".py")
                                     else name + ".py")
        for base in (Path(self.script_dir), Path.cwd() / "scripts"):
            path = base / fname
            if path.exists():
                return path
        known = ", ".join(sorted(set(STRATEGY_SCRIPTS)))
        raise FileNotFoundError(
            f"training script {fname} not found under {self.script_dir} or "
            f"./scripts — run from a source checkout or point "
            f"app.script_dir at the strategy scripts. Known strategies: "
            f"{known}")


@dataclass
class RunResult:
    run_id: str
    trace_dir: Path
    command: list[str]
    returncode: int


def build_launch_command(config: LaunchConfig, script: str | None = None,
                         extra_args: list | None = None) -> list[str]:
    """Twin of ``modal_utils.build_launch_command`` (``modal_utils.py:107-148``).
    The torchrun/accelerate/python trichotomy collapses: SPMD JAX wants ONE
    process per host, so the launcher is always ``sys.executable``; the
    device spec rides on ``--cpu-devices`` (simulated mesh) or the default
    TPU runtime (real chips)."""
    platform, count = parse_device_spec(config.device_spec)
    cmd = [sys.executable, str(config.resolve_script(script))]
    if platform == "cpu":
        cmd += ["--cpu-devices", str(count or 8)]
    elif platform in ("tpu", "auto"):
        if count is not None:
            # Chip subsetting needs runtime support the scripts don't have
            # (they build their mesh over every visible device); refuse
            # loudly rather than run on all chips while claiming `count`.
            raise ValueError(
                f"device spec {config.device_spec!r}: TPU chip subsetting "
                f"is not supported — use 'tpu' (all chips) or 'cpu:<n>'")
    else:
        raise ValueError(f"unsupported platform {platform!r} "
                         f"(expected tpu, cpu:<n>, or auto)")
    cmd += [str(a) for a in config.extra_args]
    if extra_args:
        cmd += [str(a) for a in extra_args]
    return cmd


def run_training(config: LaunchConfig, *, script: str | None = None,
                 run_name: str | None = None, num_steps: int | None = None,
                 num_epochs: int | None = None, extra_args: list | None = None,
                 dry_run: bool = False) -> RunResult:
    """Launch one strategy script with a run-id'd trace dir — the
    ``run_training`` + ``train()`` arg-mapping twin
    (``modal_utils.py:151-188`` and ``:211-241``).

    Env contract: ``TRACE_DIR=<trace_root>/<run_id>`` is exported to the
    child (the scripts' ``default_trace_dir`` reads it), so traces land in
    a per-run directory the way each Modal run lands in its own volume
    prefix (``DDP/modal_app.py:116-121``)."""
    combined = []
    if num_steps is not None:
        combined += ["--num-steps", str(num_steps)]
    if num_epochs is not None:
        combined += ["--num-epochs", str(num_epochs)]
    if extra_args:
        combined += list(extra_args)

    run_id = build_run_id(run_name)
    trace_dir = Path(config.trace_root) / run_id
    cmd = build_launch_command(config, script, combined)

    env = os.environ.copy()
    env["TRACE_DIR"] = str(trace_dir)
    # group id shared by every worker of this launch: each rank's
    # TelemetryRun stamps it into its manifest (extra.launch_group), and
    # scripts/fleet_timeline.py groups the per-rank run dirs by it
    env["DTS_LAUNCH_GROUP"] = f"{config.name}-{run_id}"
    env.update({k: str(v) for k, v in config.env.items()})

    nprocs = int(config.nprocs or 1)
    if config.distributed and nprocs < 2:
        raise ValueError("--distributed needs --nprocs >= 2 (one process "
                         "is not a process group)")
    print(f"[launch] {config.name}: {' '.join(cmd)}"
          + (f" (x{nprocs} processes)" if nprocs > 1 else ""))
    print(f"[launch] TRACE_DIR={trace_dir}")
    if dry_run:
        return RunResult(run_id, trace_dir, cmd, 0)
    trace_dir.mkdir(parents=True, exist_ok=True)
    if nprocs > 1 and config.elastic:
        returncode = run_elastic_group(config, cmd, env, trace_dir, nprocs)
    elif nprocs > 1:
        returncode = _run_multiprocess(config, cmd, env, trace_dir, nprocs)
    else:
        returncode = subprocess.run(cmd, env=env,
                                    timeout=config.timeout).returncode
    if returncode == 0:
        print_completion_message(config, run_id, script or config.script)
    else:
        print(f"[launch] FAILED (exit {returncode}): {' '.join(cmd)}",
              file=sys.stderr)
    return RunResult(run_id, trace_dir, cmd, returncode)


def _free_port() -> int:
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _exit_code(raw: int) -> int:
    """Propagatable exit code: signal-killed children report negative
    codes — map -SIG to the shell convention 128+SIG so the launcher's
    own exit status says *which* signal, not a flattened 1."""
    return 128 - raw if raw < 0 else raw


def _die_with_parent():
    """preexec_fn: workers get SIGTERM when the coordinator process
    dies (Linux PR_SET_PDEATHSIG) — a crashed/killed launcher must not
    leave stragglers spinning in collectives.  Best-effort: on
    platforms without prctl the group-kill paths below still cover
    every exit the coordinator survives long enough to handle."""
    try:
        import ctypes
        import signal as _signal
        libc = ctypes.CDLL("libc.so.6", use_errno=True)
        libc.prctl(1, _signal.SIGTERM)   # 1 == PR_SET_PDEATHSIG
    except Exception:  # noqa: BLE001 - portability fallback
        pass


@dataclass
class GroupResult:
    """Outcome of one worker-group attempt: the propagatable exit code
    (first nonzero worker's, 128+SIG for signal deaths), which ranks
    failed, how long detection took from first poll of the dead worker
    (the bounded-interval contract of the failure detector), and any
    worker pids that survived teardown un-reaped (zombies — must be
    empty before the coordinator may shrink and relaunch)."""
    returncode: int
    failed_ranks: list
    detect_s: float | None = None
    unreaped: list = field(default_factory=list)


def _group_hit_addrinuse(trace_dir: Path, nprocs: int) -> bool:
    """Did any worker of the last attempt die on a coordinator-port
    collision?  The launcher bind-probes for a free port, but the probe
    socket closes before ``jax.distributed`` rebinds it — another
    process can race into the gap, and the worker-side in-place retry
    (``setup_distributed``) only cures TIME_WAIT, not a genuinely taken
    port.  Scanned from the worker logs: the failure happens inside the
    child."""
    for pid in range(nprocs):
        log = trace_dir / f"worker_{pid}.log"
        try:
            text = log.read_text()
        except OSError:
            continue
        if "EADDRINUSE" in text or "address already in use" in text.lower():
            return True
    return False


def _run_worker_group(config: LaunchConfig, cmd: list[str], env: dict,
                      trace_dir: Path, nprocs: int,
                      heartbeat_dir: Path | None = None) -> GroupResult:
    """Port-rotating wrapper over :func:`_run_worker_group_once`: pick a
    fresh ephemeral coordinator port (bind-probe, never hardcoded), run
    the group, and if the attempt died with EADDRINUSE in a worker log,
    rotate to a NEW port and retry — bounded, so a genuinely broken
    network surfaces instead of looping."""
    max_attempts = 3
    res = None
    for attempt in range(max_attempts):
        port = _free_port()
        coord = f"127.0.0.1:{port}"
        res = _run_worker_group_once(config, cmd, env, trace_dir, nprocs,
                                     heartbeat_dir=heartbeat_dir,
                                     coord=coord)
        if res.returncode and attempt < max_attempts - 1 \
                and _group_hit_addrinuse(trace_dir, nprocs):
            print(f"[launch] coordinator port {port} collided "
                  f"(EADDRINUSE in worker log); rotating to a fresh "
                  f"port [{attempt + 1}/{max_attempts - 1}]",
                  file=sys.stderr)
            continue
        break
    return res


def _run_worker_group_once(config: LaunchConfig, cmd: list[str], env: dict,
                           trace_dir: Path, nprocs: int,
                           heartbeat_dir: Path | None = None,
                           coord: str | None = None) -> GroupResult:
    """The torchrun contract: coordinator address + N worker processes,
    each joining one global mesh via the DTS_* env consumed in
    ``utils.mesh.auto_initialize_from_env``.  Requires a ``cpu:K`` device
    spec (K simulated devices per process → an N·K-device mesh); real
    multi-host TPU launches use one process per host with JAX's own
    topology discovery instead.

    Failure detection in the coordinator path: every worker is polled
    for process death AND — when ``heartbeat_dir`` is set — probed
    through :class:`~..resilience.elastic.HeartbeatMonitor`, so a rank
    that is alive-but-wedged (or SIGKILLed with a ``.dead`` breadcrumb)
    is detected within ``config.heartbeat_timeout`` seconds instead of
    the group hanging in collectives until the full launch timeout.
    On any worker failure the survivors are killed promptly; every exit
    path (timeout, exception, coordinator death via PDEATHSIG) reaps
    the group — stragglers cannot outlive the launch.

    Worker stdout/stderr stream to ``<trace_dir>/worker_<i>.log``;
    worker 0's log is echoed on completion (the rank-0-prints-the-report
    convention of every strategy script)."""
    platform, _ = parse_device_spec(config.device_spec)
    if platform != "cpu":
        raise ValueError(
            f"nprocs={nprocs} needs a 'cpu:<k>' device spec (got "
            f"{config.device_spec!r}) — multi-process TPU uses one "
            f"process per host with auto topology discovery")
    if coord is None:
        coord = f"127.0.0.1:{_free_port()}"
    base_env = {k: v for k, v in env.items()
                if k not in ("JAX_PLATFORMS", "JAX_NUM_PROCESSES")}
    # keep user XLA_FLAGS; strip only the host-device-count flag that
    # would conflict with the per-worker cpu:K spec.  shlex keeps flag
    # values containing spaces (quoted --xla_dump_to paths) intact —
    # str.split would shatter them into separate bogus tokens.
    if "XLA_FLAGS" in base_env:
        kept = [f for f in shlex.split(base_env["XLA_FLAGS"])
                if not f.startswith("--xla_force_host_platform_device_count")]
        if kept:
            base_env["XLA_FLAGS"] = shlex.join(kept)
        else:
            del base_env["XLA_FLAGS"]
    monitor = None
    if heartbeat_dir is not None:
        from ..resilience.elastic import HeartbeatMonitor
        heartbeat_dir = Path(heartbeat_dir)
        monitor = HeartbeatMonitor(heartbeat_dir, nprocs,
                                   timeout_s=config.heartbeat_timeout)
    procs, logs = [], []
    for pid in range(nprocs):
        wenv = {**base_env, "DTS_COORDINATOR": coord,
                "DTS_NUM_PROCESSES": str(nprocs),
                "DTS_PROCESS_ID": str(pid)}
        if heartbeat_dir is not None:
            wenv["DTS_HEARTBEAT_DIR"] = str(heartbeat_dir)
        if config.distributed:
            # real-distributed mode: bounded bring-up + cross-process
            # barrier in the worker (utils.mesh.auto_initialize_from_env)
            wenv["DTS_DISTRIBUTED"] = "1"
            wenv["DTS_BRINGUP_TIMEOUT"] = str(config.bringup_timeout)
        log = (trace_dir / f"worker_{pid}.log").open("w")
        logs.append(log)
        procs.append(subprocess.Popen(
            cmd, env=wenv, stdout=log, stderr=subprocess.STDOUT,
            preexec_fn=_die_with_parent if os.name == "posix" else None))
    import time as _time
    deadline = (_time.monotonic() + config.timeout
                if config.timeout else None)
    rc, failed, detect_s = 0, [], None
    t_start = _time.monotonic()
    try:
        # poll ALL workers: if one dies during bring-up the survivors
        # block in collectives until timeout — kill the group as soon
        # as any worker exits nonzero (or goes heartbeat-dead) instead
        # of waiting it out
        live = dict(enumerate(procs))
        while live:
            if deadline and _time.monotonic() > deadline:
                raise subprocess.TimeoutExpired(cmd, config.timeout)
            for pid in list(live):
                code = live[pid].poll()
                if code is None:
                    continue
                del live[pid]
                # signal-killed workers return NEGATIVE codes — any
                # nonzero (either sign) must fail the run, and the
                # FIRST failure's code is the one the launch reports
                if code != 0:
                    if not failed:
                        rc = _exit_code(code)
                        detect_s = _time.monotonic() - t_start
                    failed.append(pid)
                    live.clear()
                    break
            if live and monitor is not None:
                dead = [r for r in monitor.dead_workers() if r in live]
                if dead:
                    rc = 128 + 9   # treated as SIGKILLed
                    detect_s = _time.monotonic() - t_start
                    failed.extend(dead)
                    live.clear()
            if live:
                _time.sleep(0.1)
    except subprocess.TimeoutExpired:
        raise
    finally:
        # orphan cleanup on EVERY exit path (failure, timeout,
        # KeyboardInterrupt, coordinator unwinding): kill + reap, then
        # VERIFY the reap — a pid still visible as a zombie after
        # wait() means teardown lied, and the relaunch would inherit
        # its coordinator port and device slots
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            p.wait()
        for log in logs:
            log.close()
        from ..resilience.faults import unreaped_workers
        unreaped = unreaped_workers(procs)
        if unreaped:
            print(f"[launch] ERROR: worker pid(s) {unreaped} not reaped "
                  f"after group teardown (zombie)", file=sys.stderr)
    w0 = trace_dir / "worker_0.log"
    if w0.exists():
        sys.stdout.write(w0.read_text())
    for pid, p in enumerate(procs):
        if p.returncode:
            print(f"[launch] worker {pid} exit {p.returncode} — see "
                  f"{trace_dir / f'worker_{pid}.log'}", file=sys.stderr)
    if unreaped and rc == 0:
        rc = 1
    return GroupResult(returncode=rc, failed_ranks=sorted(set(failed)),
                       detect_s=detect_s, unreaped=unreaped)


def _run_multiprocess(config: LaunchConfig, cmd: list[str], env: dict,
                      trace_dir: Path, nprocs: int) -> int:
    """Back-compat shim over :func:`_run_worker_group`."""
    return _run_worker_group(config, cmd, env, trace_dir, nprocs).returncode


def run_elastic_group(config: LaunchConfig, cmd: list[str], env: dict,
                      trace_dir: Path, nprocs: int) -> int:
    """The coordinator-side elastic loop: launch the worker group with
    heartbeat monitoring; on a worker death shrink to the largest
    power-of-two count the survivors can fill and relaunch with
    ``--resume`` appended (the workers' own resilience runtime reshards
    the latest RunState into the smaller mesh).  Gives up when the
    restart budget is spent or the world cannot shrink further."""
    from ..resilience.elastic import shrink_plan, WorkerLost
    world, attempt = nprocs, 0
    cmd = list(cmd)
    transitions: list[dict] = []
    env = dict(env)
    while True:
        hb_dir = Path(trace_dir) / f"heartbeats-{attempt}"
        if transitions:
            # survivors stamp the launcher-level shrink into their
            # checkpoint lineage (supervisor._stamped reads this)
            env["DTS_MESH_TRANSITIONS"] = json.dumps(transitions)
        res = _run_worker_group(config, cmd, env, Path(trace_dir), world,
                                heartbeat_dir=hb_dir)
        if res.returncode == 0:
            return 0
        if attempt >= config.group_restarts:
            print(f"[launch] elastic: restart budget "
                  f"({config.group_restarts}) exhausted", file=sys.stderr)
            return res.returncode
        if res.unreaped:
            # shrinking over a zombie would relaunch while the dead
            # worker still pins its pid table entry (and, on a real
            # host, its device slots) — refuse rather than stack a new
            # group on top of an un-torn-down one
            print(f"[launch] elastic: refusing to shrink — worker "
                  f"pid(s) {res.unreaped} not reaped", file=sys.stderr)
            return res.returncode
        lost = res.failed_ranks or [world - 1]
        try:
            if len(set(lost)) >= world:
                # the WHOLE group went heartbeat-dead — a group-wide
                # wedge (hung collective), not a named worker loss:
                # halve the world, the StepTimeoutError policy
                plan = shrink_plan(world, [], force_shrink=True)
            else:
                plan = shrink_plan(world, lost)
        except WorkerLost:
            print(f"[launch] elastic: no viable group below {world} "
                  f"workers", file=sys.stderr)
            return res.returncode
        detect = (f" (detected in {res.detect_s:.1f}s)"
                  if res.detect_s is not None else "")
        print(f"[launch] elastic: worker(s) {lost} lost{detect}; "
              f"relaunching {plan.old_world} -> {plan.new_world} "
              f"workers with --resume "
              f"[{attempt + 1}/{config.group_restarts}]")
        transitions.append({
            "attempt": attempt, "old_world": plan.old_world,
            "new_world": plan.new_world, "lost": sorted(set(lost)),
            "detect_s": res.detect_s,
        })
        world = plan.new_world
        if "--resume" not in cmd:
            cmd.append("--resume")
        attempt += 1


def sync_traces(config: LaunchConfig, run_id: str | None = None,
                dest: str | os.PathLike | None = None) -> Path:
    """Copy trace dirs to the retrieval destination — local twin of
    ``modal volume get <vol> / <dest> --force`` (``profile.sh:97-102``).
    ``run_id=None`` syncs every run under the trace root."""
    dest = Path(dest or config.trace_output_dir)
    root = Path(config.trace_root)
    if run_id:
        if not (root / run_id).is_dir():
            raise FileNotFoundError(f"no run {run_id!r} under {root}")
        src_dirs = [root / run_id]
    else:
        src_dirs = sorted(p for p in root.iterdir() if p.is_dir()) \
            if root.exists() else []
        if not src_dirs:
            print(f"[launch] nothing to sync under {root}")
    dest.mkdir(parents=True, exist_ok=True)
    for src in src_dirs:
        shutil.copytree(src, dest / src.name, dirs_exist_ok=True)
        print(f"[launch] synced {src} -> {dest / src.name}")
    return dest


def print_completion_message(config: LaunchConfig, run_id: str,
                             script: str) -> None:
    """``modal_utils._print_completion_message`` twin (``:249-260``)."""
    root = Path(config.trace_root)
    print(f"\n[launch] Training complete!\n"
          f"  Run ID: {run_id}\n"
          f"  Script: {script}\n"
          f"  Traces: {root / run_id}\n"
          f"View with:\n"
          f"  tensorboard --logdir {root / run_id}\n"
          f"(or open the .trace.json.gz under plugins/profile/ at "
          f"ui.perfetto.dev)")


def view_command(config: LaunchConfig, run_id: str | None = None,
                 port: int = 6006) -> list[str]:
    """The `view` leg of profile.sh (``:104-109``): returns the TensorBoard
    invocation (callers may exec it; the CLI prints it by default since the
    build environment is headless)."""
    logdir = Path(config.trace_root)
    if run_id:
        logdir = logdir / run_id
    return ["tensorboard", "--logdir", str(logdir), "--port", str(port)]
