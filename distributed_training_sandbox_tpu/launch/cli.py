"""`dts-launch` — the one-command strategy launcher CLI.

Surface twin of ``modal run <dir>/modal_app.py --script X --run-name Y
--num-steps N`` (``RUN_MODAL.md:7-28``) plus the ``profile.sh
run|sync|view|all`` loop (``DDP/scripts/profile.sh:167-199``), collapsed
into subcommands of one entrypoint:

    dts-launch run  --script zero2 --run-name sweep1 --num-steps 20 \
                    --devices cpu:8 [-- extra script args...]
    dts-launch sync [--run-id ID] [--dest DIR]
    dts-launch view [--run-id ID] [--port 6006]
    dts-launch all  --script ddp ...      # run -> sync -> print view recipe
    dts-launch list                       # known strategies + past runs
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from .launcher import (LaunchConfig, STRATEGY_SCRIPTS, run_training,
                       sync_traces, view_command)


def _add_common(p: argparse.ArgumentParser) -> None:
    p.add_argument("--config", type=str, default=None,
                   help="JSON/YAML launch config (modal_app config-dict twin)")
    p.add_argument("--trace-root", type=str, default=None)
    p.add_argument("--dest", type=str, default=None,
                   help="sync destination (profile.sh --dest twin)")


def _build_config(args) -> LaunchConfig:
    cfg = (LaunchConfig.from_config(args.config) if args.config
           else LaunchConfig())
    if getattr(args, "trace_root", None):
        cfg.trace_root = args.trace_root
    if getattr(args, "dest", None):
        cfg.trace_output_dir = args.dest
    if getattr(args, "devices", None):
        cfg.device_spec = args.devices
    if getattr(args, "nprocs", None):
        cfg.nprocs = args.nprocs
    if getattr(args, "elastic", None):
        cfg.elastic = True
    if getattr(args, "group_restarts", None) is not None:
        cfg.group_restarts = args.group_restarts
    if getattr(args, "heartbeat_timeout", None) is not None:
        cfg.heartbeat_timeout = args.heartbeat_timeout
    if getattr(args, "distributed", None):
        cfg.distributed = True
    if getattr(args, "bringup_timeout", None) is not None:
        cfg.bringup_timeout = args.bringup_timeout
    return cfg


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="dts-launch", description=__doc__)
    sub = p.add_subparsers(dest="command", required=True)

    run = sub.add_parser("run", help="launch one strategy script")
    allp = sub.add_parser("all", help="run -> sync -> print view recipe")
    for sp in (run, allp):
        sp.add_argument("--script", required=True,
                        help=f"strategy ({', '.join(sorted(set(STRATEGY_SCRIPTS)))}) "
                             f"or a script filename")
        sp.add_argument("--run-name", type=str, default=None)
        sp.add_argument("--num-steps", type=int, default=None)
        sp.add_argument("--num-epochs", type=int, default=None)
        sp.add_argument("--devices", type=str, default=None,
                        help='device spec: "tpu" (default) or "cpu:8"')
        sp.add_argument("--nprocs", type=int, default=None,
                        help="worker processes (torchrun --nproc_per_node"
                             " twin); needs a cpu:<k> device spec")
        sp.add_argument("--distributed", action="store_true", default=None,
                        help="with --nprocs N: real jax.distributed mode — "
                             "bounded bring-up with a cross-process "
                             "barrier, one global mesh spanning all "
                             "worker processes")
        sp.add_argument("--bringup-timeout", type=float, default=None,
                        help="--distributed: seconds allowed for "
                             "rendezvous + bring-up barrier before a "
                             "missing peer raises (default 120)")
        sp.add_argument("--elastic", action="store_true", default=None,
                        help="with --nprocs: on worker death, shrink to "
                             "the survivors and relaunch with --resume "
                             "(heartbeat-monitored worker group)")
        sp.add_argument("--group-restarts", type=int, default=None,
                        help="elastic: worker-group relaunch budget "
                             "(default 1)")
        sp.add_argument("--heartbeat-timeout", type=float, default=None,
                        help="elastic: seconds without a worker heartbeat "
                             "before it is declared dead (default 10)")
        sp.add_argument("--dry-run", action="store_true",
                        help="print the command + trace dir, don't execute")
        sp.add_argument("extra", nargs=argparse.REMAINDER,
                        help="args after -- go to the training script")
        _add_common(sp)

    sync = sub.add_parser("sync", help="copy traces to the retrieval dir")
    sync.add_argument("--run-id", type=str, default=None)
    _add_common(sync)

    view = sub.add_parser("view", help="print the TensorBoard invocation")
    view.add_argument("--run-id", type=str, default=None)
    view.add_argument("--port", type=int, default=6006)
    view.add_argument("--exec", action="store_true", dest="exec_tb",
                      help="exec tensorboard instead of printing the recipe")
    _add_common(view)

    lst = sub.add_parser("list", help="known strategies and past runs")
    _add_common(lst)

    # closed-loop autotuner: `dts-launch tune ...` forwards everything
    # after the subcommand to scripts/tune.py (enumerate / prune / rank /
    # measure -> plan.json; --check = the CI staleness gate)
    tune = sub.add_parser(
        "tune", add_help=False,
        help="autotune knobs -> plan.json (scripts/tune.py)")
    tune.add_argument("tune_args", nargs=argparse.REMAINDER,
                      help="args for scripts/tune.py (see its --help)")

    # virtual-clock fleet simulator: `dts-launch sim ...` forwards to
    # scripts/sim_bench.py (traffic sim / --smoke / --validate /
    # --variant policy ranking / --rank-knobs prerank)
    sim = sub.add_parser(
        "sim", add_help=False,
        help="virtual-clock fleet simulator (scripts/sim_bench.py)")
    sim.add_argument("sim_args", nargs=argparse.REMAINDER,
                     help="args for scripts/sim_bench.py (see its "
                          "--help)")
    return p


def _forward(script: str, argv: list) -> int:
    """Run a scripts/ entry point in-process, argv forwarded verbatim
    (incl. --help)."""
    import importlib.util
    path = Path(__file__).resolve().parents[2] / "scripts" / script
    spec = importlib.util.spec_from_file_location(
        f"_dts_{path.stem}", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.main([a for a in argv if a != "--"])


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    if argv[:1] == ["tune"]:
        return _forward("tune.py", argv[1:])
    if argv[:1] == ["sim"]:
        return _forward("sim_bench.py", argv[1:])
    args = build_parser().parse_args(argv)
    cfg = _build_config(args)

    if args.command in ("run", "all"):
        extra = [a for a in (args.extra or []) if a != "--"]
        result = run_training(
            cfg, script=args.script, run_name=args.run_name,
            num_steps=args.num_steps, num_epochs=args.num_epochs,
            extra_args=extra, dry_run=args.dry_run)
        if args.command == "all" and not args.dry_run:
            sync_traces(cfg, result.run_id)
            print("[launch] view with: "
                  + " ".join(view_command(cfg, result.run_id)))
        return result.returncode

    if args.command == "sync":
        sync_traces(cfg, args.run_id)
        return 0

    if args.command == "view":
        cmd = view_command(cfg, args.run_id, args.port)
        if args.exec_tb:
            import subprocess
            return subprocess.call(cmd)
        print(" ".join(cmd))
        return 0

    if args.command == "list":
        print("strategies:")
        for name in sorted(set(STRATEGY_SCRIPTS)):
            print(f"  {name} -> {STRATEGY_SCRIPTS[name]}")
        root = Path(cfg.trace_root)
        runs = sorted(p.name for p in root.iterdir() if p.is_dir()) \
            if root.exists() else []
        print(f"runs under {root}:" if runs else f"no runs under {root}")
        for r in runs:
            print(f"  {r}")
        return 0
    return 2


if __name__ == "__main__":
    sys.exit(main())
