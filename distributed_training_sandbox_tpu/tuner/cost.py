"""Stage-3 cost model: price every surviving candidate before anyone
compiles it.

Two ingredient streams, both already produced by the stack:

  * **bench priors** (``memory_plan.planner.load_bench_priors``): a
    measured matrix row with the same (remat, quant, state) knobs anchors
    a candidate's TFLOPS directly; the calibrated multiplier model
    (BENCH_r03–r05) covers the unmeasured rest of the space, scaled by
    the best measured baseline row so anchored and unanchored scores are
    the same unit.
  * **run-registry cost model** (``scripts/runs.py export-cost-model``):
    ledger-measured bus bandwidth per (collective kind, payload bucket,
    mesh axis), loaded through the registry's own schema-validated
    :class:`CostModel` so a drifted export fails loudly here instead of
    mis-ranking silently.  It prices the FSDP choreography's per-step
    comm (two param all-gathers + one grad reduce-scatter on the dp
    axis); with no cost model, or on a 1-device mesh, comm is 0 and the
    ordering is compute-only.
"""

from __future__ import annotations

import hashlib
import importlib.util
import json
from pathlib import Path

from ..memory_plan.planner import (_ACCUM_OVERHEAD, _OFFLOAD_SPEED,
                                   _QUANT_SPEED, _REMAT_SPEED,
                                   _STATE_SPEED, Candidate, _find_prior,
                                   load_bench_priors, modeled_speed)

_REPO = Path(__file__).resolve().parents[2]


def _registry_mod():
    """Import ``scripts/runs.py`` (the run registry is a script, not a
    package module) under a stable name."""
    spec = importlib.util.spec_from_file_location(
        "_dts_runs", _REPO / "scripts" / "runs.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _planner_candidate(c) -> Candidate:
    """The memory-planner projection of a tuner candidate (the knobs the
    planner's prior-matching and multiplier model know about)."""
    return Candidate(remat_policy=c.remat_policy,
                     accum_steps=c.accum_steps,
                     matmul_precision=c.matmul_precision,
                     state_precision=c.state_precision,
                     offload=c.offload)


def _digest(blob: bytes) -> str:
    return hashlib.sha256(blob).hexdigest()[:16]


class TunerCostModel:
    """Assembled pricing for stage 3; see module docstring."""

    def __init__(self, *, cost_model=None, priors: list | None = None,
                 prior_paths: list | None = None,
                 cost_model_path: str | None = None):
        self.cost_model = cost_model
        self.priors = priors or []
        self.prior_paths = [str(p) for p in (prior_paths or [])]
        self.cost_model_path = cost_model_path
        # baseline anchor: the best measured full/bf16/full row converts
        # the multiplier model's relative scores into TFLOPS
        base = [p for p in self.priors
                if p["knobs"]["remat_policy"] == "full"
                and p["knobs"]["matmul_precision"] == "bf16"
                and p["knobs"]["state_precision"] == "full"]
        self.baseline_tflops = max(
            (float(p["tflops_per_device"]) for p in base), default=None)

    @classmethod
    def from_artifacts(cls, *, cost_model_path: str | None = None,
                       prior_paths: list | None = None
                       ) -> "TunerCostModel":
        """Load from the checked-in artifacts: ``BENCH_*.json`` bench
        priors and (when present) the registry's ``cost_model.json``.
        A cost model that exists but fails schema validation raises —
        drift must not silently degrade to compute-only ranking."""
        cm = None
        if cost_model_path and Path(cost_model_path).is_file():
            cm = _registry_mod().load_cost_model(str(cost_model_path))
        priors = load_bench_priors(
            [str(p) for p in prior_paths] if prior_paths else None)
        return cls(cost_model=cm, priors=priors,
                   prior_paths=prior_paths, cost_model_path=cost_model_path)

    # ---------------------------------------------------------- hashes
    def priors_hash(self) -> str:
        """Digest over the prior artifacts' bytes (sorted by path) —
        part of a plan's provenance."""
        h = hashlib.sha256()
        for p in sorted(self.prior_paths):
            try:
                h.update(Path(p).read_bytes())
            except OSError:
                h.update(f"missing:{p}".encode())
        return h.hexdigest()[:16]

    def hash(self) -> str:
        """Digest over everything that shapes the ordering: the cost
        model doc + the priors."""
        cm_blob = json.dumps(
            self.cost_model.doc if self.cost_model else None,
            sort_keys=True, default=str).encode()
        return _digest(cm_blob + self.priors_hash().encode())

    # --------------------------------------------------------- pricing
    def comm_us(self, cfg, ws: int, axis: str = "dp") -> float | None:
        """Ledger-priced per-step FSDP comm: forward param all-gather,
        backward re-gather (reshard_after_forward), grad reduce-scatter.
        None when the cost model has no matching (kind, bucket, axis)
        entry (reported, never silently zero)."""
        if self.cost_model is None or ws <= 1:
            return 0.0
        import jax.numpy as jnp
        nbytes = int(cfg.param_count()
                     * jnp.dtype(getattr(cfg, "dtype", "bfloat16")).itemsize)
        total, missing = 0.0, False
        for kind in ("all_gather", "all_gather", "reduce_scatter"):
            us = self.cost_model.estimate_us(kind, nbytes, axis)
            if us is None:
                missing = True
            else:
                total += us
        return None if missing else total

    def _closest_prior(self, pc: Candidate, pdb: int,
                       base_batch: int | None):
        """The measured rows that anchor ``pc``: the exact (remat,
        quant, state) match when one exists (the planner's own
        semantics), else EVERY row at the minimal knob distance — the
        caller extrapolates from each and keeps the most pessimistic,
        so a measured contradiction (save_dots×int8 measured SLOWER
        than the multipliers claim, BENCH_r03) overrides a sibling
        anchor's optimistic extrapolation.  A pure multiplier model
        makes exactly that mistake: it ranks unmeasured crossings above
        the measured champion.  Returns ``(priors, knob_distance)``."""
        exact = _find_prior(pc, self.priors, pdb, base_batch)
        if exact is not None:
            return [exact], 0
        dists = []
        for p in self.priors:
            k = p["knobs"]
            dist = ((k["remat_policy"] != pc.remat_policy)
                    + (k["matmul_precision"] != pc.matmul_precision)
                    + (k["state_precision"] != pc.state_precision))
            dists.append((dist, p))
        if not dists:
            return [], None
        dmin = min(d for d, _ in dists)
        return [p for d, p in dists if d == dmin], dmin

    @staticmethod
    def _mult(remat: str, quant: str, state: str) -> float:
        return (_REMAT_SPEED.get(remat, 1.0)
                * _QUANT_SPEED.get(quant, 1.0)
                * _STATE_SPEED.get(state, 1.0))

    def predict(self, cand, cfg, *, batch: int, seq: int, ws: int,
                base_batch: int | None = None,
                axis: str = "dp") -> dict:
        """Predicted step time + throughput for one candidate at global
        ``batch`` × ``seq`` over ``ws`` devices.  ``base_batch`` is the
        per-device batch at scale 1 (prior rows are matched on it).

        Anchoring: the closest measured prior's TFLOPS, scaled by the
        calibrated multiplier RATIO between the candidate's knobs and
        the prior's (exact match → ratio 1), times the residual for the
        knobs bench rows never carry (offload, accumulation).  With no
        priors at all the score stays relative (multiplier product)."""
        from ..utils.flops import get_model_flops_per_token
        pc = _planner_candidate(cand)
        pdb = max(batch // ws, 1)
        anchors, dist = self._closest_prior(pc, pdb, base_batch)
        prior = None
        score = modeled_speed(pc, anchors[0] if dist == 0 else None)
        residual = (_OFFLOAD_SPEED.get(pc.offload, 1.0)
                    / (1.0 + _ACCUM_OVERHEAD * (pc.accum_steps - 1)))
        tflops = None
        if anchors:
            cand_mult = self._mult(pc.remat_policy, pc.matmul_precision,
                                   pc.state_precision)
            per_anchor = []
            for p in anchors:
                k = p["knobs"]
                ratio = cand_mult / self._mult(k["remat_policy"],
                                               k["matmul_precision"],
                                               k["state_precision"])
                per_anchor.append(
                    (float(p["tflops_per_device"]) * ratio * residual, p))
            tflops, prior = min(per_anchor, key=lambda t: t[0])
        elif self.baseline_tflops:
            tflops = self.baseline_tflops * score
        anchor_exact_batch = bool(
            prior is not None and base_batch is not None
            and prior["knobs"]["batch_scale"] * base_batch == pdb)
        row = {"config": cand.bench_name(),
               "anchor": (prior or {}).get("config"),
               "anchor_knob_distance": dist,
               "anchor_exact_batch": anchor_exact_batch,
               "relative_score": round(score, 4),
               "predicted_tflops": round(tflops, 2) if tflops else None,
               "predicted_step_ms": None, "compute_ms": None,
               "comm_ms": None}
        if tflops:
            cfg_c = pc.apply_to(cfg)
            ft = get_model_flops_per_token(cfg_c, seq)
            compute_ms = batch * seq * ft / (tflops * 1e12 * ws) * 1e3
            comm = self.comm_us(cfg_c, ws, axis)
            comm_ms = (comm or 0.0) / 1e3
            step_ms = compute_ms + comm_ms
            # tokens/s from the UNROUNDED step time: at tiny-model step
            # times the display rounding below is coarser than the
            # spread between candidates and would scramble the ordering
            row.update(
                compute_ms=round(compute_ms, 3),
                comm_ms=round(comm_ms, 3) if comm is not None else None,
                predicted_step_ms=round(step_ms, 3),
                predicted_tokens_per_sec=round(
                    batch * seq / (step_ms / 1e3), 1))
        return row

    def rank(self, cands, cfg, *, seq: int, base_batch: int, ws: int,
             axis: str = "dp") -> list[tuple]:
        """Stage-3 ordering: every candidate priced and sorted best
        first.  Throughput objective = predicted tokens/s (global batch
        tokens over predicted step time); candidates the model cannot
        price absolutely (no baseline anchor) sort by relative score
        below the priced ones."""
        rows = []
        for c in cands:
            batch = base_batch * c.batch_scale * ws
            pred = self.predict(c, cfg, batch=batch, seq=seq, ws=ws,
                                base_batch=base_batch, axis=axis)
            pred.setdefault("predicted_tokens_per_sec", None)
            rows.append((c, pred))
        rows.sort(key=lambda t: (
            -(t[1]["predicted_tokens_per_sec"] or 0.0),
            t[1]["anchor_knob_distance"] if
            t[1]["anchor_knob_distance"] is not None else 9,
            0 if t[1]["anchor_exact_batch"] else 1,
            -t[1]["relative_score"], t[0].bench_name()))
        return rows
