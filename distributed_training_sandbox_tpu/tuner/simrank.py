"""Simulator pre-rank for the serving knob space.

The serving tuner's bottleneck is stage 4: every candidate it measures
costs a compile + a live trace.  The fleet simulator prices a candidate
in milliseconds instead — the real admission/router/batcher policy
stack runs against the calibrated :class:`~..sim.SimCostModel`, so the
QUEUEING consequences of the knobs (batch slots, page granularity,
burst length, speculative lookahead) are captured even though the
device is modeled.  ``sim_rank_serving`` replays one seeded trace
through every candidate and ranks by the tuner's serving objective
(p99 TTFT, with sheds priced in), and ``write_prerank`` files the
ranking as ``sim_prerank.json`` next to the knob-space hash so a later
``tune --serving`` run can measure only the head of the list.

Candidates that differ only in ``draft_layers`` are sim-twins (the
cost model prices a macro-step, not the draft depth), so the ranking
dedups them the same way the space dedups ``spec_k=0``.
"""

from __future__ import annotations

import json
from pathlib import Path

from ..sim.cost import SimCostModel
from ..sim.fleet import simulate_trace

__all__ = ["PRERANK_SCHEMA", "load_prerank", "sim_rank_serving",
           "write_prerank"]

PRERANK_SCHEMA = 1

# the knobs the simulator can actually distinguish — draft_layers only
# changes which draft net a REAL engine builds
_SIM_KNOBS = ("max_batch", "page_size", "prefill_chunk", "sync_every",
              "spec_k")


def _objective(rep: dict) -> float:
    """Smaller is better: p99 TTFT (ms) with a shed penalty — a config
    that sheds its way to a flat tail must not outrank one that serves
    the same load."""
    p99 = rep["ttft_ms"]["p99"]
    if p99 is None:
        p99 = float("inf")
    offered = max(rep["offered"], 1)
    return float(p99) * (1.0 + rep["shed"] / offered)


def sim_rank_serving(space, trace, *, cost: SimCostModel | None = None,
                     replicas: int = 2, max_seq_len: int = 64,
                     max_queue: int = 8, deadline_s: float | None = None,
                     prefix_cache: bool = False,
                     flash_prefill: bool = False,
                     top_k: int | None = None) -> list[dict]:
    """Simulate every candidate in ``space`` (a
    :class:`~.knobs.ServingKnobSpace`) against ``trace`` and return
    rows sorted best-first by :func:`_objective`.  Each row carries the
    knobs, the sim metrics that priced them, and the run digest (the
    reproducibility pin)."""
    cost = cost if cost is not None else SimCostModel()
    seen: dict[tuple, dict] = {}
    for knobs in space.enumerate():
        key = tuple(knobs[k] for k in _SIM_KNOBS)
        if key in seen:
            seen[key]["sim_twins"].append(dict(knobs))
            continue
        if knobs["page_size"] > max_seq_len:
            continue
        try:
            fleet = simulate_trace(
                trace, cost=cost, replicas=replicas,
                deadline_s=deadline_s,
                fleet_kwargs={"max_queue": max_queue},
                engine_kwargs={
                    "max_batch": knobs["max_batch"],
                    "page_size": knobs["page_size"],
                    "max_seq_len": max_seq_len,
                    "prefill_chunk": knobs["prefill_chunk"],
                    "sync_every": knobs["sync_every"],
                    "spec_k": knobs["spec_k"],
                    "prefix_cache": prefix_cache,
                    "flash_prefill": flash_prefill,
                })
        except ValueError:
            # infeasible for this trace (e.g. a prompt outlives the
            # view capacity) — skip, exactly like the tuner's pre-
            # compile waterline prune
            continue
        rep = fleet.slo_report()
        seen[key] = {
            "knobs": dict(knobs),
            "sim_twins": [],
            "objective": round(_objective(rep), 3),
            "ttft_ms": rep["ttft_ms"],
            "per_token_ms": rep["per_token_ms"],
            "completed": rep["completed"],
            "shed": rep["shed"],
            "virtual_duration_s": rep["virtual_duration_s"],
            "digest": rep["digest"],
        }
    ranked = sorted(seen.values(), key=lambda r: r["objective"])
    for i, row in enumerate(ranked):
        row["rank"] = i
    return ranked[:top_k] if top_k is not None else ranked


def write_prerank(path, ranked: list[dict], space,
                  cost: SimCostModel | None = None) -> dict:
    """File the ranking as ``sim_prerank.json``: candidates best-first
    plus the knob-space hash and cost-model provenance, so a consumer
    can verify it ranks the space it is about to measure."""
    doc = {
        "schema": PRERANK_SCHEMA,
        "space_hash": space.space_hash(),
        "axes": space.axes(),
        "cost_model": (cost or SimCostModel()).to_dict(),
        "candidates": ranked,
    }
    p = Path(path)
    p.write_text(json.dumps(doc, indent=1) + "\n")
    return doc


def load_prerank(path, space=None) -> dict:
    """Round-trip ``sim_prerank.json``; when ``space`` is given, refuse
    a ranking whose hash doesn't match the space about to be measured."""
    doc = json.loads(Path(path).read_text())
    if int(doc.get("schema") or 0) != PRERANK_SCHEMA:
        raise ValueError(f"{path}: not a sim_prerank.json (schema "
                         f"{doc.get('schema')!r})")
    if space is not None and doc.get("space_hash") != space.space_hash():
        raise ValueError(
            f"{path}: ranks space {doc.get('space_hash')} but the "
            f"space to measure hashes to {space.space_hash()} — "
            f"re-run sim_bench --rank-knobs")
    return doc
