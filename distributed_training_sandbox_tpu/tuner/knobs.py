"""Declarative knob space: the axes ``bench.py:KNOB_MATRIX``
hand-enumerates, as data.

A :class:`TunerCandidate` is one point — a superset of the memory
planner's :class:`~..memory_plan.planner.Candidate` (which covers the
per-step knobs) extended with the driver-level knobs the planner never
sees: batch scale, the overlap engine mode, sync cadence, and DDP bucket
size.  A :class:`KnobSpace` is a cross product of named axes with the
same feasibility rules the step factories enforce, so enumeration never
emits a candidate the drivers would reject.
"""

from __future__ import annotations

import hashlib
import json
import random
from dataclasses import asdict, dataclass

from ..memory_plan.planner import REMAT_POLICIES


def mesh_feasible(shape, *, n_devices=None, n_heads=None,
                  n_kv_heads=None, seq_len=None) -> bool:
    """Enumeration-time feasibility of one ``mesh_shape`` tuple
    (dp, fsdp, tp[, sp]): the axis product must equal the device count,
    tp must divide both head counts, sp must divide the sequence length.
    Mirrors ``parallel.composable.plan_feasible`` without importing the
    jax-side machinery (the two are pinned equal by
    tests/test_composable.py).  Unknown context (None) never prunes."""
    dp, fsdp, tp, sp = (tuple(shape) + (1, 1, 1, 1))[:4]
    if min(dp, fsdp, tp, sp) < 1:
        return False
    if n_devices is not None and dp * fsdp * tp * sp != n_devices:
        return False
    if tp > 1:
        for heads in (n_heads, n_kv_heads):
            if heads is not None and heads % tp:
                return False
    if sp > 1 and seq_len is not None and seq_len % sp:
        return False
    return True


@dataclass(frozen=True)
class TunerCandidate:
    """One point of the tuner's knob space."""
    strategy: str = "fsdp"
    batch_scale: int = 1
    accum_steps: int = 1
    remat_policy: str = "full"
    matmul_precision: str = "bf16"
    state_precision: str = "full"
    offload: str = "none"
    overlap: str = "none"   # "none"|"ring"|"ring_fused"|"ring_fused_pallas"
    sync_every: int = 0            # 0 = pump default (no per-step sync)
    bucket_mb: float | None = None  # DDP-family bucket size
    mesh_shape: tuple | None = None  # (dp, fsdp, tp[, sp]); None = flat dp

    # ------------------------------------------------------------ names
    def bench_name(self) -> str:
        """The ``bench.py`` row name for this candidate, in the grammar
        ``parse_bench_config_name`` reads back (explicit[_remat]
        [_int8_bwd|_fp8(_delayed|_pallas)][_s8][_b{N}x]).  Knobs the
        bench grammar has no token for
        (accum, offload, overlap, sync) get trailing tags — such names
        parse to None, which is correct: no measured bench row covers
        them."""
        parts = ["explicit"]
        if self.remat_policy != "full":
            parts.append(self.remat_policy)
        if self.matmul_precision == "int8_bwd":
            parts.append("int8_bwd")
        elif self.matmul_precision.startswith("fp8"):
            parts.append(self.matmul_precision)
        if self.state_precision == "int8":
            parts.append("s8")
        if self.batch_scale > 1:
            parts.append(f"b{self.batch_scale}x")
        if self.accum_steps > 1:
            parts.append(f"accum{self.accum_steps}")
        if self.offload != "none":
            parts.append(f"offload_{self.offload}")
        if self.overlap != "none":
            parts.append(self.overlap)
        if self.sync_every:
            parts.append(f"sync{self.sync_every}")
        if self.mesh_shape:
            # "_mesh2x2x2" — parse_bench_config_name reads this back
            parts.append("mesh" + "x".join(str(s)
                                           for s in self.mesh_shape))
        return "_".join(parts)

    def label(self) -> str:
        return self.bench_name()

    # -------------------------------------------------- driver adapters
    def cfg_overrides(self) -> dict:
        """``TransformerConfig`` overrides (``dataclasses.replace``)."""
        over = {"remat_policy": self.remat_policy,
                "matmul_precision": self.matmul_precision}
        if self.offload == "opt_act":
            over["offload_activations"] = True
        return over

    def step_kwargs(self) -> dict:
        """``fsdp.make_fsdp_train_step`` kwargs for this candidate."""
        kw: dict = {"reshard_after_forward": True}
        if self.accum_steps > 1:
            kw["accum_steps"] = self.accum_steps
        if self.state_precision != "full":
            kw["state_precision"] = self.state_precision
        if self.offload != "none":
            kw["offload"] = self.offload
        if self.overlap != "none":
            kw["overlap"] = self.overlap
        return kw

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "TunerCandidate":
        kw = {k: d[k] for k in cls.__dataclass_fields__ if k in d}
        if kw.get("mesh_shape") is not None:
            # plan.json round trip: JSON has no tuples
            kw["mesh_shape"] = tuple(int(s) for s in kw["mesh_shape"])
        return cls(**kw)


# default axes: the envelope of every hand-written KNOB_MATRIX row plus
# the planner-only knobs (accum, offload) the matrix never swept
_DEFAULT_AXES = dict(
    strategy=("fsdp",),
    batch_scale=(1, 2, 4, 8),
    accum_steps=(1, 2),
    remat_policy=REMAT_POLICIES,
    matmul_precision=("bf16", "int8_bwd", "fp8", "fp8_delayed",
                      "fp8_pallas"),
    state_precision=("full", "int8"),
    offload=("none", "opt"),
    overlap=("none",),
    sync_every=(0,),
    bucket_mb=(None,),
    # None = the flat-dp fsdp mesh; tuples are (dp, fsdp, tp) composable
    # plans — the combinatorial axis the composable driver executes.
    # Infeasible shapes (axis product != device count, tp not dividing
    # the head counts) are dropped at enumeration when the context is
    # known; the analytic waterline prunes the over-budget rest.
    mesh_shape=(None, (2, 2, 2), (1, 2, 4), (1, 4, 2)),
)


@dataclass(frozen=True)
class KnobSpace:
    """Cross product of knob axes with the step factories' feasibility
    rules applied at enumeration time.  Frozen + tuple-valued so the
    space itself is hashable content: :meth:`space_hash` is the
    provenance stamp a ``plan.json`` carries."""
    strategy: tuple = _DEFAULT_AXES["strategy"]
    batch_scale: tuple = _DEFAULT_AXES["batch_scale"]
    accum_steps: tuple = _DEFAULT_AXES["accum_steps"]
    remat_policy: tuple = _DEFAULT_AXES["remat_policy"]
    matmul_precision: tuple = _DEFAULT_AXES["matmul_precision"]
    state_precision: tuple = _DEFAULT_AXES["state_precision"]
    offload: tuple = _DEFAULT_AXES["offload"]
    overlap: tuple = _DEFAULT_AXES["overlap"]
    sync_every: tuple = _DEFAULT_AXES["sync_every"]
    bucket_mb: tuple = _DEFAULT_AXES["bucket_mb"]
    mesh_shape: tuple = _DEFAULT_AXES["mesh_shape"]

    def axes(self) -> dict:
        return {k: list(getattr(self, k))
                for k in _DEFAULT_AXES}

    def space_hash(self) -> str:
        """sha256 over the canonical JSON of the axes — two spaces with
        the same axes hash identically regardless of construction."""
        blob = json.dumps(self.axes(), sort_keys=True, default=str)
        return hashlib.sha256(blob.encode()).hexdigest()[:16]

    def enumerate(self, per_device_batch: int, *,
                  n_devices: int | None = None,
                  n_heads: int | None = None,
                  n_kv_heads: int | None = None,
                  seq_len: int | None = None) -> list[TunerCandidate]:
        """Every feasible candidate, in a deterministic (sorted-axes
        cross-product) order.  Feasibility = the step factories' own
        rules: accumulation must divide the per-device batch at that
        candidate's scale; activation offload needs a named-save remat
        policy (same rule as ``memory_plan.enumerate_candidates``); a
        mesh shape must pass :func:`mesh_feasible` under whatever device
        /head/sequence context the caller knows (None never prunes)."""
        out = []
        mesh_shapes = [ms for ms in self.mesh_shape
                       if ms is None or mesh_feasible(
                           ms, n_devices=n_devices, n_heads=n_heads,
                           n_kv_heads=n_kv_heads, seq_len=seq_len)]
        for bs in self.batch_scale:
            pdb = max(per_device_batch, 1) * bs
            for strat in self.strategy:
                for a in self.accum_steps:
                    if a < 1 or (pdb % a):
                        continue
                    for r in self.remat_policy:
                        for q in self.matmul_precision:
                            for s in self.state_precision:
                                for o in self.offload:
                                    if o == "opt_act" and r not in (
                                            "save_attn", "save_dots_q8"):
                                        continue
                                    for ov in self.overlap:
                                        for se in self.sync_every:
                                            for bm in self.bucket_mb:
                                                for ms in mesh_shapes:
                                                    if ms is not None \
                                                            and (s != "full"
                                                                 or o != "none"):
                                                        # the composable
                                                        # step composes
                                                        # accum/overlap
                                                        # only — int8
                                                        # state and
                                                        # offload are
                                                        # flat-dp fsdp
                                                        # knobs
                                                        continue
                                                    out.append(
                                                        TunerCandidate(
                                                            strat, bs, a,
                                                            r, q, s, o,
                                                            ov, se, bm,
                                                            ms))
        return out

    def sample(self, n: int, seed: int,
               per_device_batch: int = 1) -> list[TunerCandidate]:
        """Deterministic sample of the feasible space — the same seed
        yields the same candidates on every host/run."""
        cands = self.enumerate(per_device_batch)
        if n >= len(cands):
            return cands
        return random.Random(seed).sample(cands, n)

    @classmethod
    def from_axes(cls, axes: dict) -> "KnobSpace":
        def _axis(k, v):
            if k == "mesh_shape":
                # JSON round trip: inner lists -> tuples so candidates
                # and hashes compare equal regardless of provenance
                return tuple(None if s is None else tuple(s) for s in v)
            return tuple(v)
        kw = {k: _axis(k, v) for k, v in axes.items()
              if k in _DEFAULT_AXES}
        return cls(**kw)


#: the ServingKnobSpace axis names, in canonical order — axes(),
#: space_hash(), enumerate() and from_axes() all key off this one tuple
_SERVING_AXES = ("max_batch", "page_size", "prefill_chunk",
                 "sync_every", "spec_k", "draft_layers")


@dataclass(frozen=True)
class ServingKnobSpace:
    """The serving-pool half of the knob space (objective = p99
    latency): the ``ServingEngine`` pool knobs ``serve_bench.py``
    exposes as flags, plus the speculative-decoding axes (``spec_k`` =
    draft proposal length, 0 = off; ``draft_layers`` = depth of the
    truncated-target draft model — the draft-model choice axis)."""
    max_batch: tuple = (2, 4, 8)
    page_size: tuple = (4, 8, 16)
    prefill_chunk: tuple = (8, 16, 32)
    sync_every: tuple = (2, 4, 8)
    spec_k: tuple = (0, 2, 4)
    draft_layers: tuple = (1, 2)

    def axes(self) -> dict:
        return {k: list(getattr(self, k)) for k in _SERVING_AXES}

    def space_hash(self) -> str:
        blob = json.dumps(self.axes(), sort_keys=True)
        return hashlib.sha256(blob.encode()).hexdigest()[:16]

    def enumerate(self) -> list[dict]:
        out = []
        for mb in self.max_batch:
            for ps in self.page_size:
                for pc in self.prefill_chunk:
                    for se in self.sync_every:
                        for sk in self.spec_k:
                            # draft_layers only varies a live draft:
                            # spec_k=0 pins it to the first value so
                            # vanilla decode isn't enumerated twice
                            dls = (self.draft_layers if sk
                                   else self.draft_layers[:1])
                            for dl in dls:
                                out.append({
                                    "max_batch": mb, "page_size": ps,
                                    "prefill_chunk": pc,
                                    "sync_every": se, "spec_k": sk,
                                    "draft_layers": dl})
        return out

    @classmethod
    def from_axes(cls, axes: dict) -> "ServingKnobSpace":
        kw = {k: tuple(v) for k, v in axes.items()
              if k in _SERVING_AXES}
        return cls(**kw)
