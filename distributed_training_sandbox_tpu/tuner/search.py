"""Stage orchestration: enumerate → prune → rank → measure → plan.

``tune()`` is the subsystem's one programmatic entry point; it never
compiles anything outside stage 4, and stage 4 compiles at most
``top_k`` candidates — the whole point (BENCH_r01–r05 burnt ~6 compiles
on OOMs alone before the planner existed, and dozens measuring rows a
cost model would have ranked out).
"""

from __future__ import annotations

import sys
import time
from pathlib import Path

from ..memory_plan.predictor import analytic_waterline
from .cost import TunerCostModel, _planner_candidate
from .knobs import KnobSpace, ServingKnobSpace
from .plan import PLAN_SCHEMA

_REPO = Path(__file__).resolve().parents[2]


def _default_measure(model_name: str, seq: int, base_batch: int,
                     ws: int, num_steps: int):
    """bench.py's own ``measure()`` as the stage-4 harness — the same
    timed loop the hand-written matrix rows go through, so an
    ``autotuned`` number is comparable to every hand row by
    construction."""
    if str(_REPO) not in sys.path:
        sys.path.insert(0, str(_REPO))
    import bench

    def fn(c):
        return bench.measure(
            model_name, seq, base_batch * c.batch_scale * ws,
            num_steps=num_steps, cfg_overrides=c.cfg_overrides(),
            step_kwargs=c.step_kwargs(),
            mesh_shape=getattr(c, "mesh_shape", None))
    return fn


def _candidate_mesh_plan(c):
    """The MeshPlan a candidate's ``mesh_shape`` names, or None for the
    flat-dp fsdp family (lazy import: the composable module pulls the
    jax-side step machinery)."""
    shape = getattr(c, "mesh_shape", None)
    if not shape:
        return None
    from ..parallel.composable import MeshPlan
    dp, fsdp, tp, sp = (tuple(shape) + (1, 1, 1, 1))[:4]
    return MeshPlan(dp=dp, fsdp=fsdp, tp=tp, sp=sp)


def prune_candidates(cands, cfg, *, base_batch: int, seq: int, ws: int,
                     capacity_gb: float | None):
    """Stage 2: analytic waterline per candidate, pre-compile.  Returns
    ``(survivors, pruned_rows)``; every rejected candidate is reported
    with its predicted GB (never silently dropped).  With no capacity
    (CPU sim exposes none and no budget was given) nothing prunes, but
    the predictions still ride along."""
    survivors, pruned = [], []
    preds = {}
    for c in cands:
        pc = _planner_candidate(c)
        batch = base_batch * c.batch_scale * ws
        pred = analytic_waterline(
            pc.apply_to(cfg), batch=batch, seq=seq, ws=ws,
            accum_steps=c.accum_steps, state_precision=c.state_precision,
            offload=c.offload, capacity_gb=capacity_gb,
            mesh_plan=_candidate_mesh_plan(c))
        preds[c] = round(pred.gb, 3)
        if pred.fits is False:
            pruned.append({"config": c.bench_name(),
                           "predicted_gb": round(pred.gb, 3),
                           "capacity_gb": round(capacity_gb, 2)})
        else:
            survivors.append(c)
    return survivors, pruned, preds


def tune(model_name: str, seq: int, base_batch: int, *,
         objective: str = "throughput", space=None,
         budget_gb: float | None = None, top_k: int = 5,
         num_steps: int = 4, cost_model_path: str | None = None,
         prior_paths: list | None = None, measure_fn=None,
         cost: TunerCostModel | None = None, log=None) -> dict:
    """Run all four stages and return the plan document (the caller
    decides whether to ``save_plan`` it).  ``top_k=0`` stops after
    ranking (no compiles) — the transfer-prediction mode where the
    chosen candidate is the predicted argmax.  ``base_batch`` is the
    per-device batch at scale 1; global batch for a candidate is
    ``base_batch × batch_scale × ws``."""
    import jax
    log = log or (lambda *a: None)
    if objective == "p99_latency":
        return _tune_serving(space, top_k=top_k, log=log)
    if objective != "throughput":
        raise ValueError(f"unknown objective {objective!r}")

    from ..models import transformer as T
    from ..utils.memory import hbm_capacity_gb
    cfg = getattr(T, model_name)
    ws = len(jax.devices())
    space = space or KnobSpace()
    if cost is None:
        cost = TunerCostModel.from_artifacts(
            cost_model_path=cost_model_path, prior_paths=prior_paths)

    # 1. enumerate — mesh-shape feasibility (axis product == devices,
    # tp | heads, sp | seq) prunes right here, before any pricing
    cands = space.enumerate(
        base_batch, n_devices=ws, n_heads=cfg.num_attention_heads,
        n_kv_heads=getattr(cfg, "num_key_value_heads", None),
        seq_len=seq)
    log(f"[tune] stage 1: {len(cands)} candidates from the knob space")

    # 2. prune
    capacity = budget_gb if budget_gb is not None else hbm_capacity_gb()
    survivors, pruned, preds = prune_candidates(
        cands, cfg, base_batch=base_batch, seq=seq, ws=ws,
        capacity_gb=capacity)
    log(f"[tune] stage 2: {len(pruned)} pruned analytically "
        f"(capacity {capacity} GB), {len(survivors)} survive")

    # 3. rank
    ranked = cost.rank(survivors, cfg, seq=seq, base_batch=base_batch,
                       ws=ws)
    ranking_rows = [{**pred, "predicted_gb": preds[c],
                     "knobs": c.to_dict()} for c, pred in ranked]
    log(f"[tune] stage 3: ranked {len(ranked)} "
        f"(top: {ranking_rows[0]['config'] if ranking_rows else '-'})")

    # 4. measure top-k
    measured, compiles = [], 0
    if top_k > 0 and ranked:
        fn = measure_fn or _default_measure(model_name, seq, base_batch,
                                            ws, num_steps)
        for c, pred in ranked[:top_k]:
            t0 = time.perf_counter()
            try:
                row = fn(c)
            except Exception as e:  # noqa: BLE001 - a row must not kill the plan
                row = {"error": f"{type(e).__name__}: {str(e)[:200]}"}
            compiles += 1
            measured.append({"config": c.bench_name(),
                             "knobs": c.to_dict(), "predicted": pred,
                             "measure_s": round(
                                 time.perf_counter() - t0, 2), **row})
            log(f"[tune] stage 4: {c.bench_name()} -> "
                f"{row.get('tflops_per_device', row.get('error'))}")

    good = [m for m in measured if "error" not in m]
    if good:
        best = max(good, key=lambda m: m.get("tokens_per_sec") or 0.0)
        chosen = {"config": best["config"], "knobs": best["knobs"],
                  "predicted": best["predicted"],
                  "measured": {k: best[k] for k in
                               ("tokens_per_sec", "step_ms",
                                "tflops_per_device")
                               if k in best}}
    elif ranking_rows:
        top = ranking_rows[0]
        chosen = {"config": top["config"], "knobs": top["knobs"],
                  "predicted": {k: top[k] for k in top
                                if k not in ("knobs",)},
                  "measured": None}
    else:
        chosen = None

    return {
        "schema_version": PLAN_SCHEMA,
        "objective": objective,
        "model": model_name, "seq": seq, "base_batch": base_batch,
        "devices": ws, "platform": jax.devices()[0].platform,
        "knob_space": space.axes(),
        "knob_space_hash": space.space_hash(),
        "cost_model_hash": cost.hash(),
        "priors_hash": cost.priors_hash(),
        "provenance": {"cost_model_path": cost.cost_model_path,
                       "prior_paths": cost.prior_paths},
        "budget_gb": capacity,
        "enumerated": len(cands),
        "pruned": pruned,
        "ranking": ranking_rows,
        "measured": measured,
        "compiles_spent": compiles,
        "chosen": chosen,
    }


# ------------------------------------------------------------- serving

def _serving_proxy(k: dict) -> float:
    """Heuristic pre-measurement ordering for pool knobs (measurement
    decides among the top-k; this only picks WHICH k to measure): more
    decode slots amortize the per-step scheduler overhead, bigger
    prefill chunks cut TTFT chunking stalls, tighter sync cadence costs
    host round-trips.  Speculation is priced as a mild bonus that grows
    with k but is taxed by draft depth (k draft forwards ride every
    verify) — measurement owns the real acceptance-rate question."""
    spec = 0.0
    if k.get("spec_k"):
        spec = (0.4 * k["spec_k"]
                - 0.2 * k["spec_k"] * k.get("draft_layers", 1))
    return (k["max_batch"] * 1.0 + k["prefill_chunk"] / 32.0
            - 4.0 / max(k["sync_every"], 1) - k["page_size"] / 64.0
            + spec)


def _measure_serving_knobs(knobs: dict, n_requests: int = 16) -> dict:
    """Closed seeded burst through the real ServingEngine — the p99
    objective's stage-4 harness (mirrors ``bench.measure_serving``)."""
    import numpy as np
    import jax
    from ..models import transformer as T
    from ..serving import ServingEngine
    cfg = T.TINY_LM
    params = T.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(0)
    eng = ServingEngine(params, cfg, max_seq_len=64, **knobs)
    for _ in range(n_requests):
        plen = int(rng.integers(4, 25))
        prompt = rng.integers(1, cfg.vocab_size,
                              size=plen).astype("int32")
        eng.submit(prompt, max_new_tokens=int(rng.integers(4, 13)))
    t0 = time.perf_counter()
    eng.run()
    wall_ms = (time.perf_counter() - t0) * 1e3
    slo = eng.slo_report()
    return {"wall_ms": round(wall_ms, 1),
            "p99_ttft_ms": slo.get("ttft_ms", {}).get("p99"),
            "p99_per_token_ms": slo.get("per_token_ms", {}).get("p99"),
            "tokens_per_s": slo.get("tokens_per_s")}


def _tune_serving(space, *, top_k: int, log) -> dict:
    import jax
    space = space or ServingKnobSpace()
    cands = space.enumerate()
    log(f"[tune] serving: {len(cands)} pool-knob candidates")
    ranked = sorted(cands, key=_serving_proxy, reverse=True)
    measured = []
    for k in ranked[:max(top_k, 1)]:
        try:
            row = _measure_serving_knobs(k)
        except Exception as e:  # noqa: BLE001 - a row must not kill the plan
            row = {"error": f"{type(e).__name__}: {str(e)[:200]}"}
        measured.append({"knobs": k, **row})
        log(f"[tune] serving {k} -> "
            f"p99/token {row.get('p99_per_token_ms', row.get('error'))}")
    good = [m for m in measured
            if "error" not in m and m.get("p99_per_token_ms")]
    chosen = None
    if good:
        best = min(good, key=lambda m: m["p99_per_token_ms"])
        chosen = {"config": "serving_pool", "knobs": best["knobs"],
                  "predicted": {"proxy": _serving_proxy(best["knobs"])},
                  "measured": {k: best[k] for k in
                               ("p99_ttft_ms", "p99_per_token_ms",
                                "tokens_per_s")}}
    return {
        "schema_version": PLAN_SCHEMA,
        "objective": "p99_latency",
        "model": "TINY_LM", "devices": len(jax.devices()),
        "platform": jax.devices()[0].platform,
        "knob_space": space.axes(),
        "knob_space_hash": space.space_hash(),
        "cost_model_hash": "serving_proxy_v1",
        "enumerated": len(cands),
        "pruned": [],
        "ranking": [{"knobs": k,
                     "proxy": round(_serving_proxy(k), 3)}
                    for k in ranked],
        "measured": measured,
        "compiles_spent": len(measured),
        "chosen": chosen,
    }
