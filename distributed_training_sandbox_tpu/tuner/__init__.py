"""Closed-loop autotuner (ROADMAP item 5): turn the telemetry the stack
already produces — waterline predictions (``memory_plan``), measured
per-collective busbw (``telemetry/ledger`` via the run-registry export),
bench priors (``BENCH_*.json``) — into the knobs a human used to pick by
hand.

Four stages behind one entry point (``scripts/tune.py`` /
``dts-launch tune``):

  1. **enumerate** — a declarative :class:`KnobSpace` over strategy ×
     batch × accum × remat × quantization × opt-state precision × host
     offload × overlap/sync knobs (the same axes ``bench.py:run_matrix``
     hand-enumerates), deterministic under a fixed seed.
  2. **prune** — reject over-HBM candidates *pre-compile* via the
     analytic waterline model; every rejection is reported with its
     predicted GB.
  3. **rank** — price the survivors with :class:`TunerCostModel`:
     bench-prior-anchored TFLOPS where a measured row with the same
     knobs exists, the calibrated multiplier model otherwise, plus
     ledger-measured comm cost per (kind, payload bucket, axis) from
     the run-registry ``cost_model.json`` export.
  4. **measure** — compile + short-measure only the top-k, and emit a
     versioned, reproducible ``plan.json`` (chosen knobs + predicted and
     measured numbers + provenance hashes of the cost model and knob
     space) that the drivers replay exactly via ``--plan``.
"""

from .knobs import KnobSpace, ServingKnobSpace, TunerCandidate
from .cost import TunerCostModel
from .plan import (PLAN_SCHEMA, apply_plan_to_train_config, check_plan,
                   load_plan, plan_cfg_overrides, plan_manifest_stamp,
                   plan_serving_knobs, plan_step_kwargs,
                   plan_train_overrides, save_plan)
from .search import tune
from .simrank import (PRERANK_SCHEMA, load_prerank, sim_rank_serving,
                      write_prerank)

__all__ = [
    "KnobSpace", "ServingKnobSpace", "TunerCandidate", "TunerCostModel",
    "PLAN_SCHEMA", "apply_plan_to_train_config", "check_plan",
    "load_plan", "save_plan", "plan_cfg_overrides", "plan_serving_knobs",
    "plan_step_kwargs", "plan_train_overrides", "plan_manifest_stamp",
    "tune",
    "PRERANK_SCHEMA", "load_prerank", "sim_rank_serving",
    "write_prerank",
]
