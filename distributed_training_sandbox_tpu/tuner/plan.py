"""``plan.json``: the tuner's versioned, replayable output.

A plan records WHAT was chosen (the candidate's knobs), WHY (predicted
and measured numbers for everything enumerated, pruned, ranked, and
measured), and FROM WHAT (provenance hashes of the knob space, the cost
model, and the bench priors) — so a driver can replay the choice
exactly and CI can detect a plan gone stale against the code that would
re-derive it (``scripts/tune.py --check``).
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path

PLAN_SCHEMA = 1


def save_plan(doc: dict, path: str) -> None:
    doc = dict(doc)
    doc.setdefault("schema_version", PLAN_SCHEMA)
    with open(path, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=False, default=str)
        f.write("\n")


def load_plan(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    ver = doc.get("schema_version")
    if ver != PLAN_SCHEMA:
        raise ValueError(
            f"{path}: plan schema_version {ver!r} != {PLAN_SCHEMA} — "
            f"re-run scripts/tune.py")
    if not isinstance(doc.get("chosen"), dict) \
            or "knobs" not in doc["chosen"]:
        raise ValueError(f"{path}: plan has no chosen.knobs")
    return doc


def check_plan(doc: dict, *, space=None, cost=None) -> dict:
    """Staleness verdict for a committed plan against the CURRENT code
    and artifacts.  ``space``/``cost`` default to the plan's own
    objective-appropriate knob space rebuilt from today's defaults and
    a :class:`~.cost.TunerCostModel` loaded from the plan's recorded
    artifact paths.  Returns ``{"stale": bool, "reasons": [...]}``."""
    from .cost import TunerCostModel
    from .knobs import KnobSpace, ServingKnobSpace
    reasons = []
    if space is None:
        if doc.get("objective") == "p99_latency":
            space = ServingKnobSpace()
        else:
            space = KnobSpace()
    cur_space = space.space_hash()
    if doc.get("knob_space_hash") != cur_space:
        reasons.append(
            f"knob space drifted: plan {doc.get('knob_space_hash')} "
            f"vs current {cur_space}")
    if cost is None:
        prov = doc.get("provenance") or {}
        cost = TunerCostModel.from_artifacts(
            cost_model_path=prov.get("cost_model_path"),
            prior_paths=prov.get("prior_paths"))
    cur_cost = cost.hash()
    if doc.get("cost_model_hash") != cur_cost:
        reasons.append(
            f"cost model / priors drifted: plan "
            f"{doc.get('cost_model_hash')} vs current {cur_cost}")
    return {"stale": bool(reasons), "reasons": reasons,
            "knob_space_hash": cur_space, "cost_model_hash": cur_cost}


# -------------------------------------------------------- driver adapters

def plan_cfg_overrides(doc: dict) -> dict:
    """``TransformerConfig`` overrides for the chosen candidate (the
    FSDP-family driver path)."""
    from .knobs import TunerCandidate
    return TunerCandidate.from_dict(doc["chosen"]["knobs"]).cfg_overrides()


def plan_step_kwargs(doc: dict) -> dict:
    """``fsdp.make_fsdp_train_step`` kwargs for the chosen candidate."""
    from .knobs import TunerCandidate
    return TunerCandidate.from_dict(doc["chosen"]["knobs"]).step_kwargs()


def plan_train_overrides(doc: dict, base_batch_size: int | None = None
                         ) -> dict:
    """``TrainConfig``-level overrides for the chosen candidate: the
    knobs the strategy drivers (``_zero_driver``/``_2d_driver``) thread
    through ``TrainConfig`` rather than the step factory.  Only knobs
    the plan actually moves off their defaults appear, so a driver's
    own flags keep working for everything the plan doesn't set."""
    k = doc["chosen"]["knobs"]
    over: dict = {}
    bs = int(k.get("batch_scale", 1))
    if bs > 1 and base_batch_size:
        over["batch_size"] = base_batch_size * bs
    if int(k.get("accum_steps", 1)) > 1:
        over["accum_steps"] = int(k["accum_steps"])
    if k.get("sync_every"):
        over["sync_every"] = int(k["sync_every"])
    if k.get("overlap", "none") != "none":
        over["overlap"] = k["overlap"]
    if k.get("offload", "none") != "none":
        over["offload"] = k["offload"]
    if k.get("bucket_mb") is not None:
        over["bucket_mb"] = float(k["bucket_mb"])
    return over


def apply_plan_to_train_config(doc: dict, cfg):
    """One-call form: the driver's ``TrainConfig`` with the plan's
    overrides applied (batch scaled off the cfg's own batch_size)."""
    over = plan_train_overrides(doc, base_batch_size=cfg.batch_size)
    return dataclasses.replace(cfg, **over) if over else cfg


def plan_serving_knobs(doc: dict) -> dict:
    """ServingEngine pool knobs for a p99-objective plan."""
    return dict(doc["chosen"]["knobs"])


def plan_manifest_stamp(doc: dict, path: str | None = None) -> dict:
    """The tuner-verdict block a replaying driver stamps into its
    telemetry manifest (``TelemetryRun(extra={"tuner": ...})``) — ties
    every replayed run back to the plan that chose its knobs."""
    chosen = doc.get("chosen") or {}
    return {
        "plan": str(Path(path).name) if path else None,
        "schema_version": doc.get("schema_version"),
        "objective": doc.get("objective"),
        "chosen": chosen.get("config") or chosen.get("knobs"),
        "knob_space_hash": doc.get("knob_space_hash"),
        "cost_model_hash": doc.get("cost_model_hash"),
        "predicted": chosen.get("predicted"),
        "measured": chosen.get("measured"),
    }
