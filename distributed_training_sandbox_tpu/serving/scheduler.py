"""Continuous-batching scheduler: the per-request state machine.

Requests move WAITING → PREFILL → DECODE → DONE.  The device-side decode
step has STATIC shape — ``max_batch`` slots, an active mask — so
admission and eviction are pure host bookkeeping between decode bursts:
a fresh slot's token/length/page-table rows are rewritten and the next
burst's ``device_put`` ships the same-shaped arrays (zero retraces, the
recompile watch in ``serve_bench`` proves it over a whole trace).

Admission policy: FCFS with head-of-line blocking, and ALL pages a
request can ever need — ``ceil((prompt + max_new) / page_size)`` — are
granted at admit time.  Lazier per-token growth would pack more
requests in, but a request mid-decode could then hit an empty free list
and must be preempted (re-prefilled later); granting up front makes
admitted requests run to completion unconditionally, which is the right
trade at this repo's tier and keeps the engine's device loop free of
page-fault paths.  Eviction (page + slot release) happens at the sync
point where a request's emission count reaches ``max_new``.

Timestamps are elapsed seconds on the engine's clock: ``t_submit`` is
the request's (virtual) arrival, ``t_first`` when its first token
resolved on the host (prefill is synchronous at admission, so TTFT is
measured at token resolution), ``t_done`` at the retiring sync point —
so per-token latency is measured at sync granularity, the price of the
pump's bounded-async dispatch.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field

import numpy as np

from .kv_pool import PageAllocator

WAITING = "WAITING"
PREFILL = "PREFILL"
DECODE = "DECODE"
DONE = "DONE"


@dataclass
class Request:
    """One generation request plus its runtime state.  ``tokens`` holds
    the emitted ids (greedy continuation of ``prompt``); the first entry
    comes from the prefill's last-position logits."""
    rid: int
    prompt: np.ndarray          # (S0,) int32
    max_new_tokens: int
    #: virtual arrival time; None = "now" at submit.  An explicit None
    #: sentinel, NOT falsy-0.0 — the first request of every virtual
    #: trace legitimately arrives at 0.0 and must keep that timestamp.
    arrival_s: float | None = None
    #: distributed trace id, minted once at Router.submit (or by the
    #: single engine's submit).  Part of request IDENTITY, not runtime
    #: state: ``reset_for_replay`` preserves it, so every span/step
    #: event from a dead replica's attempt and its survivor replay
    #: joins into one swimlane.
    trace_id: str | None = None

    state: str = WAITING
    slot: int | None = None
    pages: list[int] | None = None
    #: radix-cache nodes this request holds refs on; a prefix of
    #: ``pages`` (same order) — those pages are TRIE-owned, only
    #: ``pages[len(cache_nodes):]`` go back to the allocator at retire
    cache_nodes: list = field(default_factory=list)
    prefill_pos: int = 0
    tokens: list[int] = field(default_factory=list)
    t_submit: float | None = None
    t_admit: float | None = None
    t_first: float | None = None
    t_done: float | None = None

    @property
    def n_prompt(self) -> int:
        return int(self.prompt.shape[0])

    @property
    def ttft_s(self) -> float | None:
        if self.t_first is None or self.t_submit is None:
            return None
        return self.t_first - self.t_submit

    @property
    def per_token_s(self) -> float | None:
        """Mean decode latency per token AFTER the first (TTFT owns the
        first); sync-granular — see the module docstring."""
        if self.t_done is None or self.t_first is None:
            return None
        return (self.t_done - self.t_first) / max(len(self.tokens) - 1, 1)


class ContinuousBatcher:
    """Slot + page bookkeeping for the engine.  Owns the waiting queue,
    the ``max_batch`` slot table and the page allocator; knows nothing
    about devices."""

    def __init__(self, max_batch: int, allocator: PageAllocator,
                 page_size: int):
        self.max_batch = int(max_batch)
        self.allocator = allocator
        self.page_size = int(page_size)
        self.waiting: deque[Request] = deque()
        self.slots: list[Request | None] = [None] * self.max_batch
        self.admitted_total = 0
        self.completed_total = 0
        # live MetricsRegistry, late-assigned by the engine; None-safe
        self.metrics = None
        # RadixPrefixCache, late-assigned by the engine when prefix
        # caching is on; None = every page comes from the allocator
        self.prefix_cache = None

    # ---- queries ------------------------------------------------------
    def has_work(self) -> bool:
        return bool(self.waiting) or any(
            r is not None for r in self.slots)

    def slot_request(self, b: int) -> Request | None:
        return self.slots[b]

    def next_prefill(self) -> Request | None:
        """The oldest request still in PREFILL (chunked prefill drains
        FCFS — one long prompt can't starve, it just shares rounds)."""
        cands = [r for r in self.slots
                 if r is not None and r.state == PREFILL]
        return min(cands, key=lambda r: r.t_admit) if cands else None

    def pages_needed(self, req: Request) -> int:
        total = req.n_prompt + req.max_new_tokens
        return -(-total // self.page_size)

    # ---- transitions --------------------------------------------------
    def submit(self, req: Request, now: float) -> None:
        req.state = WAITING
        # explicit None check: arrival_s == 0.0 is a real timestamp
        # (the head of every virtual trace), not "unset"
        req.t_submit = req.arrival_s if req.arrival_s is not None else now
        self.waiting.append(req)

    def admit(self, now: float) -> list[Request]:
        """FCFS: admit while a slot AND the full page grant are free.
        Head-of-line blocking is deliberate — skipping ahead would
        starve long requests under load.

        With a prefix cache attached, the grant counts only the
        NON-CACHED suffix: cached full-prompt pages are aliased (refs
        taken, never written — see ``kv_pool.RadixPrefixCache``) and
        prefill starts at the matched page boundary.  Under pool
        pressure the cache is asked to evict idle pages before the
        head request is declared blocked."""
        admitted = []
        cache = self.prefix_cache
        while self.waiting:
            free = [b for b, r in enumerate(self.slots) if r is None]
            if not free:
                break
            req = self.waiting[0]
            nodes = cache.match(req.prompt) if cache is not None else []
            need = self.pages_needed(req) - len(nodes)
            pages = self.allocator.alloc(need)
            if pages is None and cache is not None:
                # pin the matched prefix first: its refs-0 nodes are
                # legal LRU victims, and evicting a page this request
                # is about to alias would hand it a freed page
                cache.acquire(nodes)
                ev = cache.evict(need - self.allocator.free_pages)
                cache.release(nodes)
                if ev:
                    from ..telemetry.metrics import maybe_inc
                    maybe_inc(self.metrics,
                              "prefix_cache_evictions_total", ev)
                pages = self.allocator.alloc(need)
            if pages is None:
                break
            self.waiting.popleft()
            req.pages = [n.page for n in nodes] + pages
            req.cache_nodes = list(nodes)
            req.prefill_pos = len(nodes) * self.page_size
            if cache is not None:
                cache.acquire(nodes)
                n_full = (req.n_prompt - 1) // self.page_size
                cache.note_lookup(len(nodes), n_full)
                from ..telemetry.metrics import maybe_inc
                maybe_inc(self.metrics, "prefix_cache_hit_pages_total",
                          len(nodes))
                maybe_inc(self.metrics,
                          "prefix_cache_lookup_pages_total", n_full)
            req.slot = free[0]
            req.state = PREFILL
            req.t_admit = now
            self.slots[req.slot] = req
            self.admitted_total += 1
            from ..telemetry.metrics import maybe_inc
            maybe_inc(self.metrics, "batcher_admitted_total")
            admitted.append(req)
        return admitted

    def retire(self, req: Request, now: float) -> None:
        """DONE: release the slot and every page (eviction between
        decode bursts — the device never sees it, only the next burst's
        rewritten host arrays do).  Double-retire (or retiring a
        request this batcher never admitted) is a real failover-churn
        hazard — rejected loudly, never a silent double-free."""
        if req.slot is None or self.slots[req.slot] is not req:
            raise ValueError(
                f"retire(rid={req.rid}): request is not resident in "
                f"this batcher (slot={req.slot}, state={req.state}) — "
                f"double retire or foreign request")
        self.slots[req.slot] = None
        self._release_pages(req)
        req.slot = None
        req.state = DONE
        req.t_done = now
        self.completed_total += 1
        from ..telemetry.metrics import maybe_inc
        maybe_inc(self.metrics, "batcher_completed_total")

    def _release_pages(self, req: Request) -> None:
        """Cached pages go back to the trie (deref, stay resident for
        the next prefix twin); only request-OWNED pages return to the
        allocator."""
        if req.cache_nodes:
            self.prefix_cache.release(req.cache_nodes)
        owned = req.pages[len(req.cache_nodes):]
        if owned:
            self.allocator.free(owned)
        req.pages = None
        req.cache_nodes = []

    def release_all(self) -> list[Request]:
        """Failover teardown: free every resident request's slot and
        pages and drain the waiting queue, returning all unfinished
        requests (resident first, in slot order, then waiting FCFS) so
        the fleet can replay them on a survivor.  Counters are NOT
        rewound — the survivor's ``admitted_total`` will count the
        re-admission, and the fleet aggregates by rid."""
        orphans: list[Request] = []
        for b, req in enumerate(self.slots):
            if req is None:
                continue
            self.slots[b] = None
            self._release_pages(req)
            reset_for_replay(req)
            orphans.append(req)
        while self.waiting:
            req = self.waiting.popleft()
            reset_for_replay(req)
            orphans.append(req)
        return orphans


def reset_for_replay(req: Request) -> None:
    """Rewind a request to its just-submitted state so a survivor
    replica can replay it from scratch.  Greedy decode is deterministic
    in (params, prompt), so a full replay reproduces the exact token
    stream an undisturbed run would have emitted — partial progress is
    deliberately discarded rather than migrated (KV pages died with the
    replica).  Identity (rid, prompt, max_new_tokens, arrival_s,
    t_submit, trace_id) is preserved; runtime state is cleared."""
    req.state = WAITING
    req.slot = None
    req.pages = None
    req.cache_nodes = []
    req.prefill_pos = 0
    req.tokens = []
    req.t_admit = None
    req.t_first = None
    req.t_done = None
