"""Shared decode/serving byte accounting + the pool capacity planner.

One home for the HBM-read formulas the decode roofline
(``scripts/decode_bench.py``) and the serving runtime both price steps
with — previously the bench owned them privately, so the serving pool's
capacity planner would have had to re-derive the same arithmetic and
drift.  The planner half answers the sizing question the paged pool
asks at startup: *how many KV pages fit the HBM budget after the
weights are resident?* — the serving twin of the training-side
``memory_plan.analytic_waterline`` ledger (serving has no optimizer
state or activation peak worth modeling; the waterline is weights +
pool + headroom).
"""

from __future__ import annotations

GB = 1024 ** 3


def kv_bytes_per_step(cfg, batch: int, s_max: int, kv_quant: bool) -> int:
    """HBM bytes the attention READS from the KV cache per decode step:
    batch × S_max × layers × n_kv × hd × 2 (K and V) × itemsize.  The
    cache is a static (B, S_max, ...) buffer, so every step reads the
    whole capacity (masked), not just the live prefix — the honest
    denominator.  int8 cache adds the f32 row scales (hd→4 bytes)."""
    elems = batch * s_max * cfg.num_hidden_layers \
        * cfg.num_key_value_heads * cfg.resolved_head_dim * 2
    if kv_quant:
        return elems + (elems // cfg.resolved_head_dim) * 4
    return elems * 2          # bf16


def weight_read_bytes(cfg, params, wb: int) -> int:
    """Weight bytes a decode STEP actually reads: the embedding table is
    only GATHERED (B rows) per step, so when a separate unembedding
    exists (int8 decode's ``unembed_q``, or an untied ``lm_head``) the
    embed bytes drop out of the per-step read.  Tied bf16 decode reads
    the table as the unembedding matmul, so it stays."""
    if "unembed_q" in params or "lm_head" in params:
        return wb - cfg.vocab_size * cfg.hidden_size * 2   # bf16 embed
    return wb


def page_bytes(cfg, page_size: int, *, kv_quant: bool = False,
               tp: int = 1) -> int:
    """Bytes ONE page occupies across every layer's K and V pool:
    page_size × layers × (n_kv/tp local heads) × hd × 2 × itemsize,
    plus the f32 per-row scales for the int8 pool.  This is the unit
    the capacity planner divides the budget by."""
    import jax.numpy as jnp
    nkv = cfg.num_key_value_heads // tp
    elems = page_size * cfg.num_hidden_layers * nkv \
        * cfg.resolved_head_dim * 2
    if kv_quant:
        return elems + (elems // cfg.resolved_head_dim) * 4
    return elems * jnp.dtype(cfg.dtype).itemsize


def serve_waterline_gb(cfg, n_pages: int, page_size: int, *,
                       weight_bytes: int = 0, kv_quant: bool = False,
                       tp: int = 1, draft_weight_bytes: int = 0,
                       draft_cfg=None) -> float:
    """Static serving HBM waterline: resident weights + the paged KV
    pool.  Decode-step activations are a few (B, 1, H) rows — noise next
    to these two, so they are the whole ledger (the serving counterpart
    of ``memory_plan.analytic_waterline``'s train-side terms).

    Speculative decoding adds two resident terms: the draft model's
    weights, and the draft's OWN paged pool — the draft pool mirrors the
    target's page table 1:1 (same ``n_pages``, same ``page_size``, the
    draft cfg's shallower layer stack), so its bytes scale with the same
    page count.  Prefix sharing adds nothing here: aliased pages are the
    same physical pages, refcounts are host-side metadata — the waterline
    is a function of pool CAPACITY, not of how requests share it."""
    pool = n_pages * page_bytes(cfg, page_size, kv_quant=kv_quant, tp=tp)
    if draft_cfg is not None:
        pool += n_pages * page_bytes(draft_cfg, page_size,
                                     kv_quant=kv_quant, tp=tp)
    return (weight_bytes + draft_weight_bytes + pool) / GB


def pool_capacity_pages(cfg, page_size: int, *, budget_gb: float,
                        weight_bytes: int = 0, kv_quant: bool = False,
                        tp: int = 1,
                        headroom_fraction: float = 0.10,
                        draft_weight_bytes: int = 0,
                        draft_cfg=None) -> int:
    """Pages that fit ``budget_gb`` once the weights are resident, with
    ``headroom_fraction`` of the budget held back for the decode step's
    working set and allocator slack — the pool-sizing inverse of
    :func:`serve_waterline_gb`.  Returns 0 when the weights alone
    exceed the usable budget (the caller should refuse to serve).

    With a draft model resident (speculative decoding) the draft's
    weights come off the top and each page's marginal cost is the
    target page PLUS its draft-pool twin, keeping the inverse exact:
    ``serve_waterline_gb(cfg, N, p, ..., draft_cfg=d)`` at the returned
    N stays within budget."""
    usable = budget_gb * GB * (1.0 - headroom_fraction) \
        - weight_bytes - draft_weight_bytes
    if usable <= 0:
        return 0
    per_page = page_bytes(cfg, page_size, kv_quant=kv_quant, tp=tp)
    if draft_cfg is not None:
        per_page += page_bytes(draft_cfg, page_size, kv_quant=kv_quant,
                               tp=tp)
    return int(usable // per_page)
