"""Shared traffic-trace generators: one seeded source of truth for
`scripts/serve_bench.py` (real engine/fleet) and the virtual-clock
simulator (`distributed_training_sandbox_tpu.sim`).

The Poisson/tenant-skewed generator used to live inline in
serve_bench; it moved here VERBATIM — same rng call order, same
distributions — so a given seed produces byte-identical traces on
both substrates (pinned by ``tests/test_sim.py``; the digest of the
drawn stream is the contract, not the source text).  On top of it,
:func:`build_fleet_trace` scales the traffic model to the simulator's
regime: 10^5–10^6 requests with diurnal rate modulation, Zipf tenant
skew, flash crowds — shapes the real-engine driver can't afford but
the discrete-event engine chews through in minutes.

Everything draws from ONE ``numpy.random.Generator`` passed by the
caller, and nothing here reads a clock: arrivals are virtual seconds
from t=0.  That is what makes shed sets, cache-hit rates and p99s
reproducible from the seed alone.
"""

from __future__ import annotations

import hashlib
import math
import struct
from dataclasses import dataclass

import numpy as np

__all__ = ["TraceRequest", "build_trace", "build_tenant_trace",
           "build_fleet_trace", "trace_digest"]


@dataclass(frozen=True)
class TraceRequest:
    """One offered request on the virtual clock.  ``tenant`` is -1 for
    anonymous (non-tenant) traffic; otherwise the index of the system
    prompt the request opens with."""
    arrival_s: float
    prompt: np.ndarray
    max_new: int
    tenant: int = -1


def build_tenant_trace(rng, n_requests: int, rate: float, vocab: int,
                       max_seq_len: int, *, tenants: int = 0,
                       overlap_frac: float = 0.0, sys_len: int = 16
                       ) -> list[TraceRequest]:
    """The serve_bench generator with tenant attribution: Poisson
    arrivals, bimodal prompt lengths (70 % chat-short 4–16, 30 %
    document-long 24–48, clipped to capacity), 4–24 new tokens.

    Tenant-skewed mode (``tenants > 0``): each of ``tenants`` tenants
    owns a fixed ``sys_len``-token system prompt drawn up front; an
    ``overlap_frac`` fraction of requests opens with a (uniformly
    chosen) tenant's system prompt followed by a unique user suffix —
    the traffic shape the radix prefix cache exists for.  Everything
    is drawn from the one seeded ``rng``, so cache-hit rates and TTFT
    deltas reproduce run-to-run from the seed alone.

    The rng call order is the serve_bench original's, unchanged —
    tenant ids fall out of draws that already happen, so recording
    them costs nothing and the byte-identity pin holds.
    """
    sys_prompts = [rng.integers(1, vocab, size=sys_len).astype("int32")
                   for _ in range(tenants)]
    t = 0.0
    trace = []
    for _ in range(n_requests):
        t += float(rng.exponential(1.0 / rate))
        new = int(rng.integers(4, 25))
        tenant = -1
        if sys_prompts and rng.random() < overlap_frac:
            tenant = int(rng.integers(len(sys_prompts)))
            head = sys_prompts[tenant]
            tail = rng.integers(1, vocab,
                                size=int(rng.integers(4, 17)))
            prompt = np.concatenate(
                [head, tail.astype("int32")])[:max_seq_len - new]
        else:
            long = rng.random() < 0.3
            plen = int(rng.integers(24, 49) if long
                       else rng.integers(4, 17))
            plen = min(plen, max_seq_len - new)
            prompt = rng.integers(1, vocab, size=plen).astype("int32")
        trace.append(TraceRequest(t, prompt, new, tenant))
    return trace


def build_trace(rng, n_requests: int, rate: float, vocab: int,
                max_seq_len: int, *, tenants: int = 0,
                overlap_frac: float = 0.0, sys_len: int = 16):
    """(arrival_s, prompt, max_new) triples — serve_bench's historical
    interface, backed by the same draw stream as
    :func:`build_tenant_trace` (the tenant id is simply not carried)."""
    return [(r.arrival_s, r.prompt, r.max_new)
            for r in build_tenant_trace(
                rng, n_requests, rate, vocab, max_seq_len,
                tenants=tenants, overlap_frac=overlap_frac,
                sys_len=sys_len)]


def _zipf_cdf(n: int, skew: float) -> np.ndarray:
    w = 1.0 / np.arange(1, n + 1, dtype=np.float64) ** float(skew)
    return np.cumsum(w / w.sum())


def build_fleet_trace(rng, n_requests: int, *, base_rate: float,
                      vocab: int, max_seq_len: int, tenants: int = 8,
                      overlap_frac: float = 0.6, sys_len: int = 16,
                      tenant_skew: float = 1.1,
                      diurnal_amplitude: float = 0.6,
                      diurnal_period_s: float | None = None,
                      flash_crowds: tuple = (),
                      ) -> list[TraceRequest]:
    """Fleet-scale trace for the simulator: a non-homogeneous Poisson
    process whose instantaneous rate follows a diurnal sinusoid around
    ``base_rate`` (peak/trough ratio set by ``diurnal_amplitude``),
    with optional flash crowds — ``(start_s, duration_s, multiplier)``
    windows that multiply the rate — and Zipf-skewed tenant choice
    (exponent ``tenant_skew``: tenant 0 is the whale, the tail starves,
    which is exactly what the per-tenant fairness report must surface).

    ``diurnal_period_s`` defaults to the mean span of the whole trace
    (one "day" over the run), so the sim sees a full peak AND trough
    regardless of request count.  Arrivals are drawn by inverting the
    local rate — dt ~ Exp(1/rate(t)) — which is exact enough for
    traffic shaping and keeps generation O(n) with one rng draw per
    field, so a 10^6-request trace builds in well under a minute.
    """
    if tenants < 1:
        raise ValueError("build_fleet_trace needs tenants >= 1")
    if diurnal_period_s is None:
        diurnal_period_s = max(n_requests / float(base_rate), 1e-9)
    cdf = _zipf_cdf(tenants, tenant_skew)
    sys_prompts = [rng.integers(1, vocab, size=sys_len).astype("int32")
                   for _ in range(tenants)]
    crowds = [(float(s), float(s) + float(d), float(m))
              for s, d, m in flash_crowds]
    t = 0.0
    trace = []
    two_pi = 2.0 * math.pi
    for _ in range(n_requests):
        rate = base_rate * (
            1.0 + diurnal_amplitude
            * math.sin(two_pi * t / diurnal_period_s))
        for s, e, m in crowds:
            if s <= t < e:
                rate *= m
        rate = max(rate, 1e-3 * base_rate)
        t += float(rng.exponential(1.0 / rate))
        new = int(rng.integers(4, 25))
        tenant = int(np.searchsorted(cdf, rng.random()))
        if rng.random() < overlap_frac:
            head = sys_prompts[tenant]
            tail = rng.integers(1, vocab,
                                size=int(rng.integers(4, 17)))
            prompt = np.concatenate(
                [head, tail.astype("int32")])[:max_seq_len - new]
        else:
            long = rng.random() < 0.3
            plen = int(rng.integers(24, 49) if long
                       else rng.integers(4, 17))
            plen = min(plen, max_seq_len - new)
            prompt = rng.integers(1, vocab, size=plen).astype("int32")
        trace.append(TraceRequest(t, prompt, new, tenant))
    return trace


def trace_digest(trace) -> str:
    """sha256 over the full drawn stream — arrivals (as IEEE-754
    bits), token ids, max_new and tenant — the byte-identity pin for
    "same seed ⇒ same trace" across serve_bench and the simulator.
    Accepts both :class:`TraceRequest` lists and serve_bench's
    (arrival, prompt, max_new) triples; a triple digests identically
    to its tenant-less record."""
    h = hashlib.sha256()
    for rec in trace:
        if isinstance(rec, TraceRequest):
            t, prompt, new, tenant = (rec.arrival_s, rec.prompt,
                                      rec.max_new, rec.tenant)
        else:
            t, prompt, new = rec
            tenant = -1
        h.update(struct.pack("<dqq", float(t), int(new), int(tenant)))
        h.update(np.ascontiguousarray(prompt, np.int32).tobytes())
    return h.hexdigest()
