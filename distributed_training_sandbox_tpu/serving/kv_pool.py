"""Sharded paged KV cache pool — the serving-side cache substrate.

One-shot decode (``models/generate.py``) gives every request a private
``(B, n_kv, S_max, hd)`` cache sized to its own prompt+new.  A server
cannot: requests arrive and finish continuously, so the cache must be a
FIXED pool whose blocks are reassigned between requests without
reallocating (or retracing) anything.  vLLM's paged layout, TPU-shaped:

  * per-layer POOLS of page blocks, ``(n_pages, page_size, n_kv, hd)``
    in ``cfg.dtype`` — or int8 codes + ``(n_pages, page_size, n_kv, 1)``
    f32 row scales via the same ``_quant_kv`` row quantizer the one-shot
    int8 cache uses;
  * a host-side PAGE TABLE per request slot: absolute position ``p`` of
    a request lives at ``(page_table[slot, p // page_size],
    p % page_size)``;
  * page 0 is RESERVED as the null page: writes for padded/inactive
    positions are diverted there (a scatter must always have a target —
    static shapes), and unassigned page-table entries point at it, so
    reads of dead slots land on masked garbage, never out of bounds;
  * under tensor parallelism the head axis (dim 2) is sharded over the
    mesh's ``tp`` axis — the same each-rank-caches-its-local-heads
    layout ``init_cache(tp=...)`` uses, so pool memory and per-step
    cache reads shrink by tp.

The device arrays live in a :class:`PoolBuffers` namedtuple that the
jitted decode/prefill steps DONATE and return — the pool object just
tracks the current buffers plus the free list.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class PoolBuffers(NamedTuple):
    """The device half of the pool: per-layer page-block arrays (tuples
    of L arrays, mirroring ``KVCache``'s per-layer-buffer decision — a
    stacked (L, ...) layout would pay a dynamic-slice copy per layer per
    step).  ``k_scale``/``v_scale`` are the f32 row scales of the int8
    pool, None for the ``cfg.dtype`` pool."""
    k: tuple            # L × (n_pages, page_size, n_kv, hd)
    v: tuple
    k_scale: tuple | None   # L × (n_pages, page_size, n_kv, 1) f32
    v_scale: tuple | None


class PageAllocator:
    """Host-side free list over pages ``1..n_pages-1`` (page 0 is the
    reserved null page).  LIFO reuse keeps recently-touched pages warm;
    allocation is all-or-nothing so a request can never deadlock holding
    a partial page set."""

    def __init__(self, n_pages: int):
        if n_pages < 2:
            raise ValueError(f"need >= 2 pages (1 null + 1 usable), "
                             f"got {n_pages}")
        self.n_pages = n_pages
        self._free = list(range(n_pages - 1, 0, -1))

    def alloc(self, n: int) -> list[int] | None:
        """``n`` pages or None — never a partial grant."""
        if n <= 0:
            raise ValueError(f"alloc({n})")
        if len(self._free) < n:
            return None
        got = self._free[-n:]
        del self._free[-n:]
        return got

    def free(self, pages: list[int]) -> None:
        for p in pages:
            if not 0 < p < self.n_pages:
                raise ValueError(f"freeing invalid page {p}")
        self._free.extend(pages)

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return (self.n_pages - 1) - len(self._free)

    @property
    def utilization(self) -> float:
        usable = self.n_pages - 1
        return self.pages_in_use / usable if usable else 0.0


class _TrieNode:
    """One cached full page of prompt KV: the page-size token chunk that
    keys it under its parent, the pool page holding those positions'
    K/V rows, and the in-flight refcount."""
    __slots__ = ("key", "page", "parent", "children", "refs", "last_used")

    def __init__(self, key: tuple, page: int, parent):
        self.key = key
        self.page = page
        self.parent = parent          # _TrieNode | None (root child)
        self.children: dict = {}
        self.refs = 0
        self.last_used = 0

    @property
    def depth(self) -> int:
        d, n = 1, self.parent
        while n is not None:
            d, n = d + 1, n.parent
        return d


class RadixPrefixCache:
    """Token-trie over committed KV pages — the prefix-reuse substrate.

    Nodes are PAGE-granular: each trie edge is an exact ``page_size``
    token chunk, so a node's path from the root spells out a full-page
    prompt prefix and its ``page`` holds exactly those positions' K/V
    rows.  Content identity is positional: K/V at absolute position
    ``p`` depends only on the token at ``p`` and ``p`` itself (per-row
    bitwise independence, the engine's parity invariant), so two
    requests sharing a page-aligned token prefix can alias the same
    pages and stay bitwise-identical to their private-cache runs.

    Ownership: pages referenced by the trie are OWNED by the trie —
    they are never on the allocator's free list and are returned to it
    only by :meth:`evict`.  Requests hold refcounts on the nodes they
    alias (``acquire``/``release``); eviction takes refcount-0 LEAF
    nodes in LRU order, so an in-flight request can never lose a page
    under it and interior nodes never orphan their children.

    Copy-on-write falls out of page granularity: the first divergent
    page has a different token chunk, so it simply isn't in the trie —
    admission allocates a fresh page for it and prefill recomputes from
    the matched boundary.  Aliased pages are never scatter targets
    (prefill starts at the matched page boundary; decode writes at
    positions past the prompt), which the CoW test pins byte-for-byte.

    The last prompt page is never cached even when full: prefill must
    run at least the final prompt position to produce the first token's
    logits, so matchable pages are capped at ``(n_prompt - 1) //
    page_size``.
    """

    def __init__(self, allocator: PageAllocator, page_size: int):
        self.allocator = allocator
        self.page_size = int(page_size)
        self._root: dict = {}         # key chunk -> _TrieNode
        self._nodes: list[_TrieNode] = []
        self._clock = 0
        # counters the engine mirrors into telemetry / slo_report
        self.hit_pages = 0
        self.lookup_pages = 0
        self.evictions = 0
        self.inserted_pages = 0
        # refcount-0 node count, maintained O(1) at every transition.
        # Exact reclaimability: a holder always refs its node's whole
        # prefix path, so a refs-0 node's subtree is refs-0 throughout
        # and :meth:`evict` can drain all of it leaf-first.
        self._idle_pages = 0

    # ---- queries ------------------------------------------------------
    @property
    def cached_pages(self) -> int:
        """Pages owned by the trie (not in the allocator free list)."""
        return len(self._nodes)

    @property
    def reclaimable_pages(self) -> int:
        """Pages :meth:`evict` could free right now (refcount-0 nodes).
        ``can_accept`` credits these against a request's page grant —
        without the credit a saturated trie wedges dispatch forever
        while every replica sits idle (the fleet-sim-discovered
        livelock)."""
        return self._idle_pages

    @property
    def hit_rate(self) -> float:
        return self.hit_pages / self.lookup_pages if self.lookup_pages \
            else 0.0

    def _chunks(self, tokens) -> list[tuple]:
        p = self.page_size
        n_full = (len(tokens) - 1) // p
        return [tuple(int(t) for t in tokens[i * p:(i + 1) * p])
                for i in range(n_full)]

    def match(self, tokens) -> list[_TrieNode]:
        """Longest cached full-page prefix of ``tokens`` — the nodes
        whose pages an admitted request will alias.  Pure lookup: no
        refcounts taken, no counters (admission may retry the same
        head-of-line request many rounds; it calls :meth:`note_lookup`
        once on success)."""
        nodes: list[_TrieNode] = []
        kids = self._root
        for key in self._chunks(tokens):
            node = kids.get(key)
            if node is None:
                break
            nodes.append(node)
            kids = node.children
        return nodes

    def note_lookup(self, hit_pages: int, lookup_pages: int) -> None:
        self.hit_pages += hit_pages
        self.lookup_pages += lookup_pages

    # ---- refcounts ----------------------------------------------------
    def acquire(self, nodes: list[_TrieNode]) -> None:
        self._clock += 1
        for n in nodes:
            if n.refs == 0:
                self._idle_pages -= 1
            n.refs += 1
            n.last_used = self._clock

    def release(self, nodes: list[_TrieNode]) -> None:
        for n in nodes:
            if n.refs <= 0:
                raise ValueError("prefix-cache refcount underflow — "
                                 "double release")
            n.refs -= 1
            if n.refs == 0:
                self._idle_pages += 1

    # ---- growth -------------------------------------------------------
    def insert(self, tokens, pages: list[int],
               matched: list[_TrieNode]):
        """Donate a just-prefilled request's full-prompt pages into the
        trie.  ``pages`` is the request's page list (cached prefix
        first, then granted pages); ``matched`` the nodes it acquired at
        admission.  Returns ``(nodes, swaps)``: the full prefix-aligned
        node list (refs held by the caller) and a ``{page_index: page}``
        map for chunks a CONCURRENT twin already cached — the caller's
        duplicate page is freed and its page-table entry must be
        rewritten to the cached twin (contents are bitwise-identical,
        so the swap is invisible to decode)."""
        chunks = self._chunks(tokens)
        nodes = list(matched)
        swaps: dict[int, int] = {}
        self._clock += 1
        for i in range(len(matched), len(chunks)):
            kids = nodes[-1].children if nodes else self._root
            node = kids.get(chunks[i])
            if node is None:
                node = _TrieNode(chunks[i], pages[i],
                                 nodes[-1] if nodes else None)
                kids[chunks[i]] = node
                self._nodes.append(node)
                self.inserted_pages += 1
                self._idle_pages += 1   # born refs-0; claimed below
            elif node.page != pages[i]:
                # two requests with the same prefix prefilled
                # concurrently; adopt the cached twin, free ours
                swaps[i] = node.page
                self.allocator.free([pages[i]])
            if node.refs == 0:
                self._idle_pages -= 1
            node.refs += 1
            node.last_used = self._clock
            nodes.append(node)
        return nodes, swaps

    # ---- pressure -----------------------------------------------------
    def evict(self, n: int) -> int:
        """Free up to ``n`` pages by evicting refcount-0 LEAF nodes in
        LRU order (ties broken deepest-first so chains drain tail-in).
        Returns the number actually freed — the caller retries its
        allocation and sheds load if the trie couldn't give enough."""
        freed = 0
        while freed < n:
            victims = [nd for nd in self._nodes
                       if nd.refs == 0 and not nd.children]
            if not victims:
                break
            v = min(victims, key=lambda nd: (nd.last_used, -nd.depth))
            kids = v.parent.children if v.parent is not None \
                else self._root
            del kids[v.key]
            self._nodes.remove(v)
            self.allocator.free([v.page])
            self.evictions += 1
            self._idle_pages -= 1
            freed += 1
        return freed

    def stats(self) -> dict:
        return {"cached_pages": self.cached_pages,
                "hit_pages": self.hit_pages,
                "lookup_pages": self.lookup_pages,
                "hit_rate": round(self.hit_rate, 4),
                "evictions": self.evictions,
                "inserted_pages": self.inserted_pages}


class PagedKVPool:
    """Device pools + allocator + (optional) mesh sharding.

    ``mesh``/``tp_axis``: shard the head axis over ``tp_axis`` via a
    NamedSharding — the buffers stay one logical array addressed by the
    engine's ``shard_map`` step.  ``device``: commit the pool to one
    device (the disaggregated prefill/decode slices).  Neither: default
    placement."""

    def __init__(self, cfg, n_pages: int, page_size: int, *,
                 kv_quant: bool = False, mesh=None, tp_axis: str = "tp",
                 device=None):
        if mesh is not None and device is not None:
            raise ValueError("pass mesh or device, not both")
        self.cfg = cfg
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        self.kv_quant = bool(kv_quant)
        self.mesh = mesh
        self.tp_axis = tp_axis
        self.device = device
        L = cfg.num_hidden_layers
        nkv, hd = cfg.num_key_value_heads, cfg.resolved_head_dim
        shape = (self.n_pages, self.page_size, nkv, hd)
        dt = jnp.int8 if kv_quant else cfg.dtype
        put = self._put
        k = tuple(put(jnp.zeros(shape, dt)) for _ in range(L))
        v = tuple(put(jnp.zeros(shape, dt)) for _ in range(L))
        # scales init to ones like init_cache's — unwritten rows then
        # dequantize to exact zeros, matching the one-shot cache
        ks = vs = None
        if kv_quant:
            ks = tuple(put(jnp.ones(shape[:-1] + (1,), jnp.float32))
                       for _ in range(L))
            vs = tuple(put(jnp.ones(shape[:-1] + (1,), jnp.float32))
                       for _ in range(L))
        self.bufs = PoolBuffers(k=k, v=v, k_scale=ks, v_scale=vs)
        self.allocator = PageAllocator(self.n_pages)

    def _put(self, x):
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            return jax.device_put(
                x, NamedSharding(self.mesh,
                                 P(None, None, self.tp_axis, None)))
        if self.device is not None:
            return jax.device_put(x, self.device)
        return x

    @property
    def spec(self) -> PoolBuffers:
        """PartitionSpec pytree matching ``bufs`` — the in/out spec the
        engine hands ``shard_map`` (heads sharded over tp, everything
        else replicated)."""
        from jax.sharding import PartitionSpec as P
        L = self.cfg.num_hidden_layers
        ps = P(None, None, self.tp_axis if self.mesh is not None else None,
               None)
        sc = (ps,) * L if self.kv_quant else None
        return PoolBuffers(k=(ps,) * L, v=(ps,) * L, k_scale=sc,
                           v_scale=sc)

    @property
    def utilization(self) -> float:
        return self.allocator.utilization
