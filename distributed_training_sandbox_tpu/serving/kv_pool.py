"""Sharded paged KV cache pool — the serving-side cache substrate.

One-shot decode (``models/generate.py``) gives every request a private
``(B, n_kv, S_max, hd)`` cache sized to its own prompt+new.  A server
cannot: requests arrive and finish continuously, so the cache must be a
FIXED pool whose blocks are reassigned between requests without
reallocating (or retracing) anything.  vLLM's paged layout, TPU-shaped:

  * per-layer POOLS of page blocks, ``(n_pages, page_size, n_kv, hd)``
    in ``cfg.dtype`` — or int8 codes + ``(n_pages, page_size, n_kv, 1)``
    f32 row scales via the same ``_quant_kv`` row quantizer the one-shot
    int8 cache uses;
  * a host-side PAGE TABLE per request slot: absolute position ``p`` of
    a request lives at ``(page_table[slot, p // page_size],
    p % page_size)``;
  * page 0 is RESERVED as the null page: writes for padded/inactive
    positions are diverted there (a scatter must always have a target —
    static shapes), and unassigned page-table entries point at it, so
    reads of dead slots land on masked garbage, never out of bounds;
  * under tensor parallelism the head axis (dim 2) is sharded over the
    mesh's ``tp`` axis — the same each-rank-caches-its-local-heads
    layout ``init_cache(tp=...)`` uses, so pool memory and per-step
    cache reads shrink by tp.

The device arrays live in a :class:`PoolBuffers` namedtuple that the
jitted decode/prefill steps DONATE and return — the pool object just
tracks the current buffers plus the free list.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class PoolBuffers(NamedTuple):
    """The device half of the pool: per-layer page-block arrays (tuples
    of L arrays, mirroring ``KVCache``'s per-layer-buffer decision — a
    stacked (L, ...) layout would pay a dynamic-slice copy per layer per
    step).  ``k_scale``/``v_scale`` are the f32 row scales of the int8
    pool, None for the ``cfg.dtype`` pool."""
    k: tuple            # L × (n_pages, page_size, n_kv, hd)
    v: tuple
    k_scale: tuple | None   # L × (n_pages, page_size, n_kv, 1) f32
    v_scale: tuple | None


class PageAllocator:
    """Host-side free list over pages ``1..n_pages-1`` (page 0 is the
    reserved null page).  LIFO reuse keeps recently-touched pages warm;
    allocation is all-or-nothing so a request can never deadlock holding
    a partial page set."""

    def __init__(self, n_pages: int):
        if n_pages < 2:
            raise ValueError(f"need >= 2 pages (1 null + 1 usable), "
                             f"got {n_pages}")
        self.n_pages = n_pages
        self._free = list(range(n_pages - 1, 0, -1))

    def alloc(self, n: int) -> list[int] | None:
        """``n`` pages or None — never a partial grant."""
        if n <= 0:
            raise ValueError(f"alloc({n})")
        if len(self._free) < n:
            return None
        got = self._free[-n:]
        del self._free[-n:]
        return got

    def free(self, pages: list[int]) -> None:
        for p in pages:
            if not 0 < p < self.n_pages:
                raise ValueError(f"freeing invalid page {p}")
        self._free.extend(pages)

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return (self.n_pages - 1) - len(self._free)

    @property
    def utilization(self) -> float:
        usable = self.n_pages - 1
        return self.pages_in_use / usable if usable else 0.0


class PagedKVPool:
    """Device pools + allocator + (optional) mesh sharding.

    ``mesh``/``tp_axis``: shard the head axis over ``tp_axis`` via a
    NamedSharding — the buffers stay one logical array addressed by the
    engine's ``shard_map`` step.  ``device``: commit the pool to one
    device (the disaggregated prefill/decode slices).  Neither: default
    placement."""

    def __init__(self, cfg, n_pages: int, page_size: int, *,
                 kv_quant: bool = False, mesh=None, tp_axis: str = "tp",
                 device=None):
        if mesh is not None and device is not None:
            raise ValueError("pass mesh or device, not both")
        self.cfg = cfg
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        self.kv_quant = bool(kv_quant)
        self.mesh = mesh
        self.tp_axis = tp_axis
        self.device = device
        L = cfg.num_hidden_layers
        nkv, hd = cfg.num_key_value_heads, cfg.resolved_head_dim
        shape = (self.n_pages, self.page_size, nkv, hd)
        dt = jnp.int8 if kv_quant else cfg.dtype
        put = self._put
        k = tuple(put(jnp.zeros(shape, dt)) for _ in range(L))
        v = tuple(put(jnp.zeros(shape, dt)) for _ in range(L))
        # scales init to ones like init_cache's — unwritten rows then
        # dequantize to exact zeros, matching the one-shot cache
        ks = vs = None
        if kv_quant:
            ks = tuple(put(jnp.ones(shape[:-1] + (1,), jnp.float32))
                       for _ in range(L))
            vs = tuple(put(jnp.ones(shape[:-1] + (1,), jnp.float32))
                       for _ in range(L))
        self.bufs = PoolBuffers(k=k, v=v, k_scale=ks, v_scale=vs)
        self.allocator = PageAllocator(self.n_pages)

    def _put(self, x):
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            return jax.device_put(
                x, NamedSharding(self.mesh,
                                 P(None, None, self.tp_axis, None)))
        if self.device is not None:
            return jax.device_put(x, self.device)
        return x

    @property
    def spec(self) -> PoolBuffers:
        """PartitionSpec pytree matching ``bufs`` — the in/out spec the
        engine hands ``shard_map`` (heads sharded over tp, everything
        else replicated)."""
        from jax.sharding import PartitionSpec as P
        L = self.cfg.num_hidden_layers
        ps = P(None, None, self.tp_axis if self.mesh is not None else None,
               None)
        sc = (ps,) * L if self.kv_quant else None
        return PoolBuffers(k=(ps,) * L, v=(ps,) * L, k_scale=sc,
                           v_scale=sc)

    @property
    def utilization(self) -> float:
        return self.allocator.utilization
