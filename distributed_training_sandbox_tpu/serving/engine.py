"""Continuous-batching decode engine over the paged KV pool.

The serving half of ``models/generate.py``: same layer math, different
cache substrate and driver.  Three invariants carry the design:

**Bitwise parity with one-shot decode.**  Every per-row op (rms_norm,
projections, per-query-row attention, logits) is bitwise-independent of
which OTHER rows share its batch — so chunked prefill, mixed-length
ragged batches, and admit/evict churn cannot change a request's tokens
… with ONE exception, measured on this backend: the softmax
denominator's reduction order depends on the attention's contraction
extent.  The engine therefore always contracts over the FIXED pool view
(``P_max × page_size`` positions; masked tails contribute exact zeros),
and one-shot ``generate`` grew a static ``cache_capacity`` arg to pin
the same extent.  With matched capacity, serving output is
bitwise-identical to ``generate`` — the invariant the parity suite
asserts per request.

**Zero retraces after warmup.**  The decode step has static shape:
``max_batch`` slots, an active mask, full-size page-table rows.
Admit/evict between bursts rewrites host arrays and ``device_put``s the
same shapes/dtypes/shardings — the jit cache stays at one entry per
program over a whole traffic trace (``slo_report`` carries the watch).

**Host blocks only at sync points.**  Decode bursts chain
``sync_every`` donated-buffer steps through ``runtime.StepPump``'s
bounded in-flight dispatch; the host resolves tokens, retires finished
requests and admits new ones once per burst.  Prefill is synchronous at
admission (TTFT is measured at first-token resolution) and CHUNKED so a
long prompt shares rounds with decode instead of stalling it.

Modes: single-program (default, one jit per device set), tensor-parallel
(``mesh`` + ``tp_axis``: params via ``parallel.tensor.tp_specs``, pool
heads sharded, 2 psums/layer — the ``serve_decode`` contract), and
prefill/decode DISAGGREGATED (mesh split into a prefill slice and a
decode slice as separate single-device programs, KV handed off by page
block — the separate-programs-per-role seam; intra-slice sharding is
future work).
"""

from __future__ import annotations

import math
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..models import transformer as T
from ..models.generate import _decode_cfg, _quant_kv
from ..ops import collectives as C
from .kv_pool import PagedKVPool, PoolBuffers
from .scheduler import ContinuousBatcher, DECODE, PREFILL, Request

__all__ = ["ServingEngine", "serve", "make_serve_decode_step",
           "make_serve_prefill_step"]


# ---------------------------------------------------------------- layer math

def _ragged_rope_tables(positions, head_dim: int, theta: float):
    """Per-BATCH rope tables: ``positions`` (B, S) int32 → cos/sin
    (B, S, hd/2) f32.  Same inv_freq/angle formula as
    ``transformer._rope_tables`` so a position's table row is bitwise
    the one the one-shot path computes for it."""
    inv_freq = 1.0 / theta ** (jnp.arange(0, head_dim, 2,
                                          dtype=jnp.float32) / head_dim)
    ang = positions.astype(jnp.float32)[..., None] * inv_freq
    return jnp.cos(ang), jnp.sin(ang)


def _apply_rope_ragged(x, cos, sin):
    """``transformer.apply_rope`` with per-batch tables: x (B, S, n, hd),
    cos/sin (B, S, hd/2) — identical split-half rotation, broadcast over
    heads instead of batch."""
    dt = x.dtype
    x = x.astype(jnp.float32)
    x1, x2 = jnp.split(x, 2, axis=-1)
    c = cos[:, :, None, :]
    s = sin[:, :, None, :]
    return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s],
                           axis=-1).astype(dt)


def _paged_layer_body(x, layer, *, cfg, cos, sin, use_rope, pk, pv,
                      pk_s, pv_s, pages, apos, valid, tp_axis=None,
                      paged_kernel=False):
    """One decoder layer against the PAGED pool — the numerics of
    ``generate._cached_layer_body`` with scatter/gather storage:

      * new K/V rows scatter token-granularly into their page table
        slots; rows with ``valid`` False (prompt padding, inactive
        decode slots) divert to the reserved null page 0;
      * attention gathers the slot's pages back into a contiguous
        (B, n_kv, V, hd) view — position ``v`` of the view IS absolute
        position ``v`` (pages are ordered), so the causal mask
        ``pos_kv <= apos`` is unchanged and masked stale/garbage
        positions contribute exact zeros (finite garbage → −1e30 score
        → 0.0 prob), which is what keeps the paged path bitwise equal
        to the contiguous cache at matched contraction extent.

    x (B, S, H); pages (B, P) int32; apos (B, S) int32 absolute
    positions of x's rows; valid (B, S) bool."""
    B, S, H = x.shape
    hd = cfg.resolved_head_dim
    tp = C.axis_size(tp_axis) if tp_axis else 1
    nq = cfg.num_attention_heads // tp
    nkv = cfg.num_key_value_heads // tp
    dense = T._dense(cfg)
    page = pk.shape[1]
    P = pages.shape[1]
    V = P * page

    r = T.rms_norm(x, layer["ln1"], cfg.rms_norm_eps)
    q = dense(r, layer["wq"]).reshape(B, S, nq, hd)
    k = dense(r, layer["wk"]).reshape(B, S, nkv, hd)
    v = dense(r, layer["wv"]).reshape(B, S, nkv, hd)
    q = jnp.where(use_rope, _apply_rope_ragged(q, cos, sin), q)
    k = jnp.where(use_rope, _apply_rope_ragged(k, cos, sin), k)

    # scatter the new rows: target page from the slot's table, offset
    # within it; invalid rows all collapse onto page 0 (duplicate
    # scatter targets there are fine — it's the trash page)
    pi = jnp.clip(apos // page, 0, P - 1)
    pg = jnp.where(valid, jnp.take_along_axis(pages, pi, axis=1), 0)
    off = apos % page
    quantized = pk.dtype == jnp.int8
    if quantized:
        kq, ks_new = _quant_kv(k)
        vq, vs_new = _quant_kv(v)
        pk = pk.at[pg, off].set(kq)
        pv = pv.at[pg, off].set(vq)
        pk_s = pk_s.at[pg, off].set(ks_new)
        pv_s = pv_s.at[pg, off].set(vs_new)
    else:
        pk = pk.at[pg, off].set(k)
        pv = pv.at[pg, off].set(v)

    if paged_kernel and S == 1:
        # Pallas decode kernel: pages are read IN PLACE via the table —
        # the (B, V, nkv, hd) gather view below never materializes.
        # Bitwise-equal to the gather path (ops/paged_attention.py).
        from ..ops.paged_attention import paged_attention_decode
        rep = nq // nkv
        qg = q.reshape(B, S, nkv, rep, hd)
        if quantized:
            qq, q_s = _quant_kv(qg)
            attn = paged_attention_decode(
                qq, pk, pv, pages, apos, q_scale=q_s,
                pk_s=pk_s, pv_s=pv_s)
        else:
            attn = paged_attention_decode(qg, pk, pv, pages, apos,
                                          probs_dtype=x.dtype)
        attn = attn.astype(x.dtype).reshape(B, S, nq * hd)
        attn_out = dense(attn, layer["wo"])
        if tp_axis:
            attn_out = C.all_reduce(attn_out, tp_axis)
        x = x + attn_out
        r = T.rms_norm(x, layer["ln2"], cfg.rms_norm_eps)
        mlp, _aux = T._mlp_block(r, layer, cfg=cfg)
        if tp_axis:
            mlp = C.all_reduce(mlp, tp_axis)
        return x + mlp, (pk, pv, pk_s, pv_s)

    # gather the slot's pages into the contiguous head-major view the
    # attention contracts over — fixed extent V for every request, the
    # parity-bearing choice (see module docstring)
    vk = pk[pages].reshape(B, V, nkv, hd).transpose(0, 2, 1, 3)
    vv = pv[pages].reshape(B, V, nkv, hd).transpose(0, 2, 1, 3)

    rep = nq // nkv
    qg = q.reshape(B, S, nkv, rep, hd)
    if quantized:
        vk_s = pk_s[pages].reshape(B, V, nkv, 1).transpose(0, 2, 1, 3)
        vv_s = pv_s[pages].reshape(B, V, nkv, 1).transpose(0, 2, 1, 3)
        qq, q_s = _quant_kv(qg)
        scores_i = jnp.einsum("bsgrh,bgkh->bgrsk", qq, vk,
                              preferred_element_type=jnp.int32)
        scores = (scores_i.astype(jnp.float32)
                  * q_s[..., 0].transpose(0, 2, 3, 1)[..., None]
                  * vk_s[..., 0][:, :, None, None, :]) / math.sqrt(hd)
    else:
        scores = jnp.einsum(
            "bsgrh,bgkh->bgrsk", qg, vk,
            preferred_element_type=jnp.float32) / math.sqrt(hd)
    pos_kv = jnp.arange(V)
    vis = pos_kv[None, None, :] <= apos[:, :, None]      # (B, S, V)
    scores = jnp.where(vis[:, None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    if quantized:
        pvw = probs * vv_s[..., 0][:, :, None, None, :]
        pvq, pv_sc = _quant_kv(pvw)
        attn_i = jnp.einsum("bgrsk,bgkh->bsgrh", pvq, vv,
                            preferred_element_type=jnp.int32)
        attn = attn_i.astype(jnp.float32) \
            * pv_sc[..., 0].transpose(0, 3, 1, 2)[..., None]
    else:
        attn = jnp.einsum("bgrsk,bgkh->bsgrh", probs.astype(x.dtype), vv,
                          preferred_element_type=jnp.float32)
    attn = attn.astype(x.dtype).reshape(B, S, nq * hd)
    attn_out = dense(attn, layer["wo"])
    if tp_axis:
        attn_out = C.all_reduce(attn_out, tp_axis)
    x = x + attn_out

    r = T.rms_norm(x, layer["ln2"], cfg.rms_norm_eps)
    mlp, _aux = T._mlp_block(r, layer, cfg=cfg)
    if tp_axis:
        mlp = C.all_reduce(mlp, tp_axis)
    return x + mlp, (pk, pv, pk_s, pv_s)


def _paged_forward(params, ids, cfg, bufs: PoolBuffers, pages, apos,
                   valid, tp_axis=None, paged_kernel=False):
    """ids (B, S) → (hidden x (B, S, H), bufs') through the UNROLLED
    layer stack (static layer index into the per-layer pools, like
    ``generate._forward_cached``)."""
    x = params["embed"].astype(cfg.dtype)[ids]
    cos, sin = _ragged_rope_tables(apos, cfg.resolved_head_dim,
                                   cfg.rope_theta)
    flags = [(li + 1) % cfg.nope_interval != 0 if cfg.nope_interval
             else True for li in range(cfg.num_hidden_layers)]
    ks, vs = list(bufs.k), list(bufs.v)
    kss = list(bufs.k_scale) if bufs.k_scale is not None else None
    vss = list(bufs.v_scale) if bufs.v_scale is not None else None
    for li in range(cfg.num_hidden_layers):
        layer = jax.tree.map(lambda p: p[li], params["layers"])
        x, (ks[li], vs[li], ksc, vsc) = _paged_layer_body(
            x, layer, cfg=cfg, cos=cos, sin=sin,
            use_rope=bool(flags[li]),
            pk=ks[li], pv=vs[li],
            pk_s=kss[li] if kss is not None else None,
            pv_s=vss[li] if vss is not None else None,
            pages=pages, apos=apos, valid=valid, tp_axis=tp_axis,
            paged_kernel=paged_kernel)
        if kss is not None:
            kss[li], vss[li] = ksc, vsc
    out = PoolBuffers(k=tuple(ks), v=tuple(vs),
                      k_scale=tuple(kss) if kss is not None else None,
                      v_scale=tuple(vss) if vss is not None else None)
    return x, out


def _last_logits(params, x_last, cfg):
    """(B, 1, H) hidden → (B, vocab) fp32 logits, same tail as
    ``generate._forward_cached``."""
    x = T.rms_norm(x_last, params["final_norm"], cfg.rms_norm_eps)
    uq = params.get("unembed_q")
    if uq is not None:
        from ..ops.quant import prequantized_dense
        logits = prequantized_dense(x, uq)[:, 0]
    else:
        logits = (x @ T._output_embedding(params, cfg).T)[:, 0]
    return logits.astype(jnp.float32)


def _decode_core(bufs, params, pages, toks, lengths, stop_at, active, *,
                 cfg, tp_axis=None, paged_kernel=False):
    """One fixed-shape decode step over every slot.  toks/lengths/
    stop_at (B,) int32, active (B,) bool.  Emits the next greedy token
    per ACTIVE slot (inactive slots freeze); a slot auto-retires ON
    DEVICE when its length reaches ``stop_at`` — the device can never
    write past a request's page grant even mid-burst, the host only
    observes retirement at the next sync."""
    apos = lengths[:, None]
    x, bufs = _paged_forward(params, toks[:, None], cfg, bufs, pages,
                             apos, active[:, None], tp_axis=tp_axis,
                             paged_kernel=paged_kernel)
    logits = _last_logits(params, x[:, -1:], cfg)
    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    nxt = jnp.where(active, nxt, toks)
    new_len = lengths + active.astype(jnp.int32)
    new_active = jnp.logical_and(active, new_len < stop_at)
    occ = jnp.sum(active.astype(jnp.int32))
    return nxt, new_len, new_active, bufs, occ


def _prefill_core(bufs, params, pages_row, ids, pos, plen, *, cfg,
                  tp_axis=None):
    """One prefill CHUNK for one request: ids (1, C) host-padded with
    zeros, pos/plen () int32 (chunk start, full prompt length).  Writes
    the chunk's K/V into the request's pages; rows past the prompt
    divert to the null page.  Returns the greedy first token — only
    meaningful on the FINAL chunk (position plen-1 falls inside it)."""
    Ck = ids.shape[1]
    apos = pos + jnp.arange(Ck, dtype=jnp.int32)[None, :]
    valid = apos < plen
    x, bufs = _paged_forward(params, ids, cfg, bufs, pages_row, apos,
                             valid, tp_axis=tp_axis)
    last = jnp.clip(plen - 1 - pos, 0, Ck - 1)
    xl = jax.lax.dynamic_slice_in_dim(x, last, 1, axis=1)
    logits = _last_logits(params, xl, cfg)
    tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return tok, bufs


# ------------------------------------------------------------- step builders

def make_serve_decode_step(cfg, params=None, *, mesh=None,
                           tp_axis: str = "tp", pool_spec=None,
                           paged_kernel: bool = False):
    """The jitted fixed-shape decode step, donated pool buffers.
    ``mesh`` selects the tensor-parallel shard_map wrapping (params must
    then be the tree ``parallel.tensor.tp_specs`` describes and
    ``pool_spec`` the pool's PartitionSpec pytree).  ``paged_kernel``
    routes attention through the Pallas decode kernel
    (``ops/paged_attention.py`` — pages read in place via the table, no
    contiguous gather view; bitwise-equal outputs)."""
    cfg = _decode_cfg(cfg)
    if mesh is None:
        return jax.jit(partial(_decode_core, cfg=cfg, tp_axis=None,
                               paged_kernel=paged_kernel),
                       donate_argnums=(0,))
    from jax.sharding import PartitionSpec as P
    from ..parallel.tensor import tp_specs
    core = partial(_decode_core, cfg=cfg, tp_axis=tp_axis,
                   paged_kernel=paged_kernel)
    in_specs = (pool_spec, tp_specs(params, tp_axis), P(), P(), P(),
                P(), P())
    out_specs = (P(), P(), P(), pool_spec, P())
    return jax.jit(C.smap(core, mesh, in_specs=in_specs,
                          out_specs=out_specs), donate_argnums=(0,))


def make_serve_prefill_step(cfg, params=None, *, mesh=None,
                            tp_axis: str = "tp", pool_spec=None):
    """The jitted single-request prefill-chunk step (see
    :func:`_prefill_core`)."""
    cfg = _decode_cfg(cfg)
    if mesh is None:
        return jax.jit(partial(_prefill_core, cfg=cfg, tp_axis=None),
                       donate_argnums=(0,))
    from jax.sharding import PartitionSpec as P
    from ..parallel.tensor import tp_specs
    core = partial(_prefill_core, cfg=cfg, tp_axis=tp_axis)
    in_specs = (pool_spec, tp_specs(params, tp_axis), P(), P(), P(), P())
    out_specs = (P(), pool_spec)
    return jax.jit(C.smap(core, mesh, in_specs=in_specs,
                          out_specs=out_specs), donate_argnums=(0,))


# ------------------------------------------------------------------- engine

class ServingEngine:
    """Continuous-batching server over the paged pool.

    ``submit()`` requests (with optional virtual ``arrival_s`` offsets),
    then ``run()`` drives the round loop to completion and returns the
    finished :class:`scheduler.Request` records; ``slo_report()``
    aggregates them into the TTFT / per-token-latency percentiles and
    throughput the SLO table renders.  ``telem``: a
    ``telemetry.TelemetryRun`` to stream per-round events into
    (prefill events carry per-request TTFT, decode-burst events carry
    occupancy/pool gauges and per-request latency at completion)."""

    def __init__(self, params, cfg, *, mesh=None, tp_axis: str = "tp",
                 max_batch: int = 4, page_size: int = 8,
                 max_seq_len: int = 64, n_pages: int | None = None,
                 prefill_chunk: int = 16,
                 prefill_chunks_per_round: int = 2,
                 sync_every: int = 4, max_in_flight: int = 8,
                 kv_quant: bool = False,
                 paged_kernel: bool = False,
                 hbm_budget_gb: float | None = None,
                 disaggregate: bool = False, device=None,
                 watchdog=None, telem=None):
        self.cfg = _decode_cfg(cfg)
        self.max_batch = int(max_batch)
        self.page_size = int(page_size)
        self.pages_per_request = -(-int(max_seq_len) // self.page_size)
        # the fixed contraction extent — pass as generate()'s
        # cache_capacity for bitwise comparison
        self.view_capacity = self.pages_per_request * self.page_size
        self.prefill_chunk = int(prefill_chunk)
        self.prefill_chunks_per_round = int(prefill_chunks_per_round)
        self.sync_every = max(int(sync_every), 1)
        self.max_in_flight = int(max_in_flight)
        self.kv_quant = bool(kv_quant)
        # decode attention through the Pallas paged kernel (pages read
        # in place via the table — ops/paged_attention.py); prefill
        # (S > 1) keeps the gather path
        self.paged_kernel = bool(paged_kernel)
        self.mesh = mesh
        self.tp_axis = tp_axis if mesh is not None else None
        self.telem = telem
        # fleet replica index (set by Fleet at construction); stamped
        # into this engine's serve spans so a merged timeline can tell
        # the dead replica's attempt from the survivor's replay
        self.replica = None
        self.disaggregate = bool(disaggregate)
        # collective watchdog (resilience.elastic.Watchdog): every
        # blocking point in the decode path — the pump's sync sites and
        # the burst's token resolution — routes through it, so a wedged
        # burst becomes a StepTimeoutError the fleet's failover path
        # can consume instead of a hung server
        self.watchdog = watchdog

        tp = 1
        if mesh is not None:
            if device is not None:
                raise ValueError("pass mesh or device, not both")
            if disaggregate:
                raise ValueError("disaggregate splits devices into "
                                 "single-program slices; pass mesh=None")
            from ..parallel.tensor import (check_tp_divisibility,
                                           shard_params_tp)
            tp = int(mesh.shape[tp_axis])
            check_tp_divisibility(self.cfg, tp)
            if "unembed_q" in params:
                raise ValueError("tensor-parallel serving takes bf16 "
                                 "params (int8 weight sharding is not "
                                 "wired)")
            params = shard_params_tp(params, mesh, tp_axis)

        if n_pages is None:
            n_pages = self.max_batch * self.pages_per_request + 1
            if hbm_budget_gb is not None:
                from ..utils.memory import tree_size_bytes
                from .accounting import pool_capacity_pages
                fit = pool_capacity_pages(
                    self.cfg, self.page_size, budget_gb=hbm_budget_gb,
                    weight_bytes=tree_size_bytes(params),
                    kv_quant=self.kv_quant, tp=tp) + 1
                n_pages = min(n_pages, fit)
        if n_pages < self.pages_per_request + 1:
            raise ValueError(
                f"pool of {n_pages} pages cannot hold one request "
                f"({self.pages_per_request} pages + null); raise the "
                f"HBM budget or shrink max_seq_len")
        self.n_pages = int(n_pages)

        devs = jax.devices()
        self._prefill_dev = self._decode_dev = None
        if device is not None:
            # whole-engine device commitment: the fleet's per-replica
            # slice, reusing the disaggregation device_put machinery
            # with prefill and decode on the SAME device
            if self.disaggregate:
                raise ValueError("device commits the whole engine to "
                                 "one device; disaggregate splits it — "
                                 "pick one")
            self._prefill_dev = self._decode_dev = device
            self._params = self._params_pre = jax.device_put(params,
                                                             device)
        elif self.disaggregate:
            if len(devs) < 2:
                raise ValueError("disaggregate needs >= 2 devices")
            self._prefill_dev = devs[0]
            self._decode_dev = devs[len(devs) // 2]
            self._params = jax.device_put(params, self._decode_dev)
            self._params_pre = jax.device_put(params, self._prefill_dev)
        else:
            self._params = params
            self._params_pre = params

        self.pool = PagedKVPool(self.cfg, self.n_pages, self.page_size,
                                kv_quant=self.kv_quant, mesh=mesh,
                                tp_axis=tp_axis, device=self._decode_dev)
        # the serving-side waterline prediction the memory ledger joins:
        # accounting's weights+pool model vs the decode program's own
        # memory_analysis() (attached at the first decode burst)
        from ..utils.memory import GB, tree_size_bytes
        from .accounting import serve_waterline_gb
        _wb = tree_size_bytes(self._params)
        _pool_b = tree_size_bytes(self.pool.bufs)
        self._mem_prediction = {
            "predicted_gb": round(serve_waterline_gb(
                self.cfg, self.n_pages, self.page_size, weight_bytes=_wb,
                kv_quant=self.kv_quant, tp=tp), 3),
            "source": "serve_accounting",
            "components": {"weights": round(_wb / GB, 3),
                           "kv_pool": round(_pool_b / GB, 3)},
        }
        self.pool_pre = None
        if self.disaggregate:
            self.pool_pre = PagedKVPool(
                self.cfg, self.n_pages, self.page_size,
                kv_quant=self.kv_quant, device=self._prefill_dev)
            self._pre_pages: dict[int, list[int]] = {}

        self._decode = make_serve_decode_step(
            self.cfg, self._params, mesh=mesh, tp_axis=tp_axis,
            pool_spec=self.pool.spec if mesh is not None else None,
            paged_kernel=self.paged_kernel)
        self._prefill = make_serve_prefill_step(
            self.cfg, self._params_pre, mesh=mesh, tp_axis=tp_axis,
            pool_spec=self.pool.spec if mesh is not None else None)
        if self.disaggregate:
            # KV handoff: gather the request's page blocks out of the
            # prefill pool, ship, scatter into its decode pages.  Full
            # padded rows keep the programs single-shape; null-row
            # blocks land on masked positions (exact-zero contribution).
            def extract(bufs, row):
                sc = None
                if bufs.k_scale is not None:
                    sc = (tuple(s[row] for s in bufs.k_scale),
                          tuple(s[row] for s in bufs.v_scale))
                return (tuple(k[row] for k in bufs.k),
                        tuple(v[row] for v in bufs.v), sc)

            def inject(bufs, blocks, row):
                bk, bv, sc = blocks
                ks = vs = None
                if bufs.k_scale is not None:
                    ks = tuple(s.at[row].set(b)
                               for s, b in zip(bufs.k_scale, sc[0]))
                    vs = tuple(s.at[row].set(b)
                               for s, b in zip(bufs.v_scale, sc[1]))
                return PoolBuffers(
                    k=tuple(p.at[row].set(b)
                            for p, b in zip(bufs.k, bk)),
                    v=tuple(p.at[row].set(b)
                            for p, b in zip(bufs.v, bv)),
                    k_scale=ks, v_scale=vs)

            self._extract = jax.jit(extract)
            self._inject = jax.jit(inject, donate_argnums=(0,))

        B, P = self.max_batch, self.pages_per_request
        self._h_tokens = np.zeros(B, np.int32)
        self._h_lengths = np.zeros(B, np.int32)
        self._h_stop = np.zeros(B, np.int32)
        self._h_active = np.zeros(B, np.bool_)
        self._h_pages = np.zeros((B, P), np.int32)

        self.batcher = ContinuousBatcher(self.max_batch,
                                         self.pool.allocator,
                                         self.page_size)
        self.batcher.metrics = getattr(telem, "metrics", None)
        self._pending: list[Request] = []
        self.completed: list[Request] = []
        self._rid = 0
        self._pump = None
        self._t0: float | None = None
        self._warm_sizes = None
        self.stats = {"rounds": 0, "decode_steps": 0, "prefill_chunks": 0,
                      "admit_s": 0.0, "bookkeep_s": 0.0,
                      "occupancy_sum": 0, "peak_pool_util": 0.0,
                      "wall_s": 0.0, "host_sync_count": 0}

    # ---- request intake ----------------------------------------------
    def submit(self, prompt, max_new_tokens: int,
               arrival_s: float | None = None) -> Request:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1 or max_new_tokens < 1:
            raise ValueError("need >= 1 prompt token and >= 1 new token")
        if prompt.size + max_new_tokens > self.view_capacity:
            raise ValueError(
                f"prompt {prompt.size} + new {max_new_tokens} exceeds "
                f"the engine's view capacity {self.view_capacity} "
                f"(raise max_seq_len)")
        req = Request(rid=self._rid, prompt=prompt,
                      max_new_tokens=int(max_new_tokens),
                      arrival_s=(None if arrival_s is None
                                 else float(arrival_s)))
        # single-engine runs have no Router in front; mint the trace id
        # here with the same shape the fleet router uses
        req.trace_id = f"tr-{req.rid:06d}"
        self._rid += 1
        self._pending.append(req)
        return req

    def enqueue(self, req: Request, now: float) -> None:
        """Hand an externally-built request straight to the batcher —
        the fleet router's dispatch path, where rids are fleet-global
        and admission control already ran at submit."""
        self.batcher.submit(req, now)

    # ---- fleet queries -----------------------------------------------
    def can_accept(self, req: Request) -> bool:
        """True when ``req`` would be admitted at the next round: a
        free slot AND its full page grant, with nothing already queued
        (the fleet router keeps one global queue rather than stacking
        head-of-line blocking inside every replica)."""
        if self.batcher.waiting:
            return False
        if not any(r is None for r in self.batcher.slots):
            return False
        return (self.pool.allocator.free_pages
                >= self.batcher.pages_needed(req))

    def in_flight(self) -> int:
        """Unfinished requests resident in this engine (queued or
        holding a slot)."""
        return len(self.batcher.waiting) + sum(
            r is not None for r in self.batcher.slots)

    # ---- device-put helpers ------------------------------------------
    def _put(self, x, device=None):
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            return jax.device_put(x, NamedSharding(self.mesh, P()))
        if device is not None:
            return jax.device_put(x, device)
        if self._decode_dev is not None:
            return jax.device_put(x, self._decode_dev)
        return jnp.asarray(x)

    # ---- prefill ------------------------------------------------------
    def _padded_row(self, pages: list[int]) -> np.ndarray:
        row = np.zeros((1, self.pages_per_request), np.int32)
        row[0, :len(pages)] = pages
        return row

    def _prefill_one_chunk(self, req: Request, t0: float) -> None:
        Ck = self.prefill_chunk
        pos = req.prefill_pos
        chunk = req.prompt[pos:pos + Ck]
        ids = np.zeros((1, Ck), np.int32)
        ids[0, :chunk.shape[0]] = chunk
        dev = self._prefill_dev
        if self.disaggregate:
            row = self._padded_row(self._pre_pages[req.rid])
            bufs = self.pool_pre.bufs
        else:
            row = self._padded_row(req.pages)
            bufs = self.pool.bufs
        t_chunk = time.perf_counter()
        tok_d, bufs = self._prefill(
            bufs, self._params_pre, self._put(row, dev),
            self._put(ids, dev), self._put(np.int32(pos), dev),
            self._put(np.int32(req.n_prompt), dev))
        if self.disaggregate:
            self.pool_pre.bufs = bufs
        else:
            self.pool.bufs = bufs
        req.prefill_pos = min(pos + Ck, req.n_prompt)
        self.stats["prefill_chunks"] += 1
        if req.prefill_pos < req.n_prompt:
            return
        # final chunk: hand off KV (disaggregated), resolve the first
        # token — prefill is synchronous at admission, so this blocks
        # the host by design and stamps TTFT at token resolution
        if self.disaggregate:
            dec_row = self._padded_row(req.pages)
            blocks = self._extract(self.pool_pre.bufs,
                                   self._put(row[0], self._prefill_dev))
            blocks = jax.device_put(blocks, self._decode_dev)
            self.pool.bufs = self._inject(
                self.pool.bufs, blocks,
                self._put(dec_row[0], self._decode_dev))
            self.pool_pre.allocator.free(self._pre_pages.pop(req.rid))
        first = int(np.asarray(tok_d)[0])   # sync-ok: TTFT resolution
        self.stats["host_sync_count"] += 1
        now = time.perf_counter() - t0
        req.tokens.append(first)
        req.t_first = now
        prefill_s = time.perf_counter() - t_chunk
        spans = getattr(self.telem, "spans", None)
        if spans is not None:
            # t_submit/t_admit/t_first ride along (engine-clock seconds)
            # so fleet_timeline can decompose TTFT into queue wait +
            # prefill without re-deriving request state
            spans.record("serve/prefill_chunk", start_perf=t_chunk,
                         end_perf=time.perf_counter(), cat="serve",
                         rid=req.rid, n_prompt=int(req.n_prompt),
                         request_id=req.rid, trace_id=req.trace_id,
                         replica=self.replica,
                         t_submit_s=req.t_submit, t_admit_s=req.t_admit,
                         t_first_s=req.t_first)
        if self.telem is not None:
            self.telem.step(
                loss=None, tokens=req.n_prompt,
                tracker_metrics={"last_step_time_s": prefill_s},
                phase="prefill", rid=req.rid,
                request_id=req.rid, trace_id=req.trace_id,
                ttft_ms=round(1e3 * (req.ttft_s or 0.0), 3),
                pool_util=round(self.pool.utilization, 4))
        b = req.slot
        stop = req.n_prompt + req.max_new_tokens - 1
        if req.n_prompt >= stop:      # max_new == 1: done at prefill
            req.state = DECODE
            self.batcher.retire(req, now)
            self.completed.append(req)
            self._h_active[b] = False
            self._h_pages[b] = 0
            return
        req.state = DECODE
        self._h_tokens[b] = first
        self._h_lengths[b] = req.n_prompt
        self._h_stop[b] = stop
        self._h_active[b] = True

    # ---- decode -------------------------------------------------------
    def _decode_burst(self, pump, t0: float) -> None:
        sync = self.sync_every
        L0 = self._h_lengths.copy()
        A0 = self._h_active.copy()
        toks_d = self._put(self._h_tokens)
        len_d = self._put(self._h_lengths)
        stop_d = self._put(self._h_stop)
        act_d = self._put(self._h_active)
        pages_d = self._put(self._h_pages)
        bufs = self.pool.bufs
        if self.telem is not None:
            # ledger join (no-op unless the run owns an enabled
            # profiler, and only compiles once): the decode program's
            # text at this burst's exact arg shardings
            self.telem.attach_step_hlo(self._decode, bufs, self._params,
                                       pages_d, toks_d, len_d, stop_d,
                                       act_d,
                                       trees={"kv_pool": bufs,
                                              "params": self._params},
                                       prediction=self._mem_prediction)
        t_burst = time.perf_counter()
        step_tokens = []
        for _ in range(sync):
            toks_d, len_d, act_d, bufs, occ = self._decode(
                bufs, self._params, pages_d, toks_d, len_d, stop_d,
                act_d)
            pump.emit(occ)
            step_tokens.append(toks_d)
        self.pool.bufs = bufs
        self.stats["decode_steps"] += sync
        # sync point: the pump just resolved the last step's occupancy,
        # so the burst's token buffers are (near-)ready — resolve and
        # replay the device's deterministic active chain on the host.
        # Watchdog-guarded: a burst wedged here must surface as
        # StepTimeoutError for the fleet's failover, never a silent hang
        if self.watchdog is not None:
            mats = self.watchdog.block(
                lambda ts: [np.asarray(t) for t in ts],   # sync-ok
                step_tokens, step=self.stats["decode_steps"])
        else:
            mats = [np.asarray(t) for t in step_tokens]   # sync-ok
        self.stats["host_sync_count"] += 1
        burst_s = time.perf_counter() - t_burst
        spans = getattr(self.telem, "spans", None)
        if spans is not None:
            spans.record("serve/decode_burst", start_perf=t_burst,
                         end_perf=time.perf_counter(), cat="serve",
                         steps=int(sync), replica=self.replica)
        t_book = time.perf_counter()
        active, lengths = A0.copy(), L0.copy()
        occ_burst, emitted = [], 0
        for j in range(sync):
            occ_burst.append(int(active.sum()))
            for b in np.nonzero(active)[0]:
                self.batcher.slot_request(int(b)).tokens.append(
                    int(mats[j][b]))
                emitted += 1
            lengths = lengths + active
            active = active & (lengths < self._h_stop)
        self._h_tokens = mats[-1].copy()
        self._h_lengths = lengths
        self._h_active = active
        now = time.perf_counter() - t0
        finished = []
        for b in range(self.max_batch):
            req = self.batcher.slot_request(b)
            if req is not None and req.state == DECODE and not active[b]:
                self.batcher.retire(req, now)
                self._h_pages[b] = 0     # slot back to the null page
                self.completed.append(req)
                finished.append(req)
        self.stats["bookkeep_s"] += time.perf_counter() - t_book
        if self.telem is not None:
            self.telem.step(
                loss=None, tokens=emitted,
                tracker_metrics={"last_step_time_s": burst_s / sync},
                phase="decode",
                active=round(float(np.mean(occ_burst)), 3),
                admitted=self.batcher.admitted_total,
                completed=self.batcher.completed_total,
                kv_pages_in_use=self.pool.allocator.pages_in_use,
                pool_util=round(self.pool.utilization, 4),
                completed_requests=[
                    {"rid": r.rid,
                     "trace_id": r.trace_id,
                     "ttft_ms": round(1e3 * (r.ttft_s or 0.0), 3),
                     "per_token_ms": round(1e3 * (r.per_token_s or 0.0),
                                           3),
                     "tokens": len(r.tokens)} for r in finished])

    # ---- round loop ---------------------------------------------------
    def start(self, t0: float | None = None) -> None:
        """Arm the engine clock and the persistent pump without driving
        the loop.  ``run()`` calls it implicitly; the fleet calls it
        explicitly with a SHARED ``t0`` so every replica's timestamps
        live on one clock, then drives rounds via :meth:`step_round`."""
        if self._t0 is None:
            self._t0 = time.perf_counter() if t0 is None else t0
        if self._pump is None:
            from ..runtime.pump import StepPump
            self._pump = StepPump(mode="async",
                                  sync_every=self.sync_every,
                                  max_in_flight=self.max_in_flight,
                                  watchdog=self.watchdog)

    def close_pump(self) -> None:
        """Drain and drop the persistent pump (normal shutdown)."""
        if self._pump is not None:
            pump, self._pump = self._pump, None
            pump.close()
            self.stats["host_sync_count"] += pump.host_sync_count

    def abandon_pump(self) -> None:
        """Drop the pump WITHOUT draining — the failover path for a
        dead/wedged replica whose in-flight work will never resolve
        (draining would just re-raise the timeout or block)."""
        self._pump = None

    def step_round(self, now: float) -> list[Request]:
        """One scheduler round at elapsed time ``now``: admit from the
        waiting queue, run up to ``prefill_chunks_per_round`` prefill
        chunks, one decode burst if any slot is active.  Returns the
        requests that finished THIS round.  Faults surface here —
        :class:`~..resilience.elastic.StepTimeoutError` propagates from
        the burst's watchdog-guarded sync points."""
        self.start()
        t0 = self._t0
        done_base = len(self.completed)
        t_admit = time.perf_counter()
        admitted = self.batcher.admit(now)
        for req in admitted:
            # install the slot's page-table row in the host
            # mirror the decode burst ships (unused entries
            # point at the null page)
            self._h_pages[req.slot] = 0
            self._h_pages[req.slot, :len(req.pages)] = req.pages
            if self.disaggregate:
                n = -(-req.n_prompt // self.page_size)
                pre = self.pool_pre.allocator.alloc(n)
                if pre is None:
                    raise RuntimeError(
                        "prefill pool exhausted — it is sized "
                        "like the decode pool, so this is a "
                        "leak, not load")
                self._pre_pages[req.rid] = pre
        self.stats["admit_s"] += time.perf_counter() - t_admit
        for _ in range(self.prefill_chunks_per_round):
            req = self.batcher.next_prefill()
            if req is None:
                break
            self._prefill_one_chunk(req, t0)
        if self._h_active.any():
            self._decode_burst(self._pump, t0)
        self.stats["rounds"] += 1
        self.stats["occupancy_sum"] += int(self._h_active.sum())
        self.stats["peak_pool_util"] = max(
            self.stats["peak_pool_util"], self.pool.utilization)
        if self._warm_sizes is None \
                and self.stats["decode_steps"] > 0:
            self._warm_sizes = self._jit_sizes()
        return self.completed[done_base:]

    def run(self) -> list[Request]:
        def vt(r):
            return r.arrival_s if r.arrival_s is not None else 0.0

        pending = sorted(self._pending, key=vt)
        self._pending = []
        self.start()
        t0 = self._t0
        newly_done_base = len(self.completed)
        try:
            while pending or self.batcher.has_work():
                now = time.perf_counter() - t0
                while pending and vt(pending[0]) <= now:
                    self.batcher.submit(pending.pop(0), now)
                if not self.batcher.has_work():
                    # idle until the next virtual arrival
                    time.sleep(min(max(vt(pending[0]) - now, 0.0),
                                   0.05))
                    continue
                self.step_round(now)
        finally:
            self.close_pump()
        self.stats["wall_s"] += time.perf_counter() - t0
        return self.completed[newly_done_base:]

    # ---- failover / hot-swap -----------------------------------------
    def release_all(self) -> list[Request]:
        """Failover teardown: every unfinished request leaves reset for
        replay (see ``scheduler.reset_for_replay``), slots and pages are
        freed, the host mirrors zeroed.  The device pool is NOT touched
        — a dead replica's buffers die with it."""
        orphans = self.batcher.release_all()
        if self.disaggregate:
            for rid in list(self._pre_pages):
                self.pool_pre.allocator.free(self._pre_pages.pop(rid))
        self._h_active[:] = False
        self._h_pages[:] = 0
        return orphans

    def swap_params(self, params) -> None:
        """Install new weights on a DRAINED engine — the fleet's
        hot-swap lands here once the replica has zero requests in
        flight.  Placement mirrors ``__init__`` (tp shard / device
        commit), and the new tree must match the old one's
        shapes/dtypes, so the jitted steps see identical avals and the
        zero-retrace contract survives the swap."""
        if self.batcher.has_work():
            raise RuntimeError(
                f"swap_params with {self.in_flight()} request(s) in "
                f"flight — drain the replica first (the fleet's swap "
                f"path does this at a burst boundary)")
        if self.mesh is not None:
            from ..parallel.tensor import shard_params_tp
            params = shard_params_tp(params, self.mesh, self.tp_axis)
            self._params = self._params_pre = params
        elif self._decode_dev is not None:
            self._params = jax.device_put(params, self._decode_dev)
            self._params_pre = (
                self._params if self._prefill_dev is self._decode_dev
                else jax.device_put(params, self._prefill_dev))
        else:
            self._params = self._params_pre = params

    def _jit_sizes(self) -> dict:
        from ..analysis.recompile import jit_cache_size
        fns = {"decode": self._decode, "prefill": self._prefill}
        if self.disaggregate:
            fns["extract"] = self._extract
            fns["inject"] = self._inject
        return {k: jit_cache_size(f) for k, f in fns.items()}

    # ---- reporting ----------------------------------------------------
    def retraces_after_warmup(self) -> int | None:
        """Jit-cache growth since the first round finished — 0 is the
        contract (admit/evict over the whole trace never retraces);
        None before any decode ran or when the cache is unreadable."""
        if self._warm_sizes is None:
            return None
        cur = self._jit_sizes()
        known = [(w, cur[k]) for k, w in self._warm_sizes.items()
                 if w is not None and cur.get(k) is not None]
        if not known:
            return None
        return sum(c - w for w, c in known)

    def slo_report(self) -> dict:
        """TTFT / per-token percentiles + throughput + pool/scheduler
        health for the finished requests — the dict ``serve_bench``
        files under summary.json's ``serving`` key."""
        done = [r for r in self.completed if r.t_done is not None]
        ttft = np.array([r.ttft_s for r in done
                         if r.ttft_s is not None]) * 1e3
        ptl = np.array([r.per_token_s for r in done
                        if r.per_token_s is not None]) * 1e3
        pct = lambda a, q: (round(float(np.percentile(a, q)), 3)
                            if a.size else None)
        toks = int(sum(len(r.tokens) for r in done))
        wall = self.stats["wall_s"] or 1e-9
        ndev = len(jax.devices()) if self.mesh is None \
            else int(self.mesh.devices.size)
        steps = max(self.stats["decode_steps"], 1)
        return {
            "requests": self.batcher.admitted_total,
            "completed": len(done),
            "ttft_ms": {"p50": pct(ttft, 50), "p99": pct(ttft, 99)},
            "per_token_ms": {"p50": pct(ptl, 50), "p99": pct(ptl, 99)},
            "tokens_total": toks,
            "tokens_per_s": round(toks / wall, 2),
            "tokens_per_s_per_device": round(toks / wall / ndev, 2),
            "devices": ndev,
            "pool": {"n_pages": self.n_pages,
                     "page_size": self.page_size,
                     "peak_util": round(self.stats["peak_pool_util"], 4)},
            "scheduler": {
                "rounds": self.stats["rounds"],
                "decode_steps": self.stats["decode_steps"],
                "prefill_chunks": self.stats["prefill_chunks"],
                "admit_ms_total": round(1e3 * self.stats["admit_s"], 3),
                "bookkeep_ms_total": round(
                    1e3 * self.stats["bookkeep_s"], 3),
                "mean_occupancy": round(
                    self.stats["occupancy_sum"]
                    / max(self.stats["rounds"], 1), 3),
                "host_syncs": self.stats["host_sync_count"],
            },
            "disaggregated": self.disaggregate,
            "kv_quant": self.kv_quant,
            "recompiles_after_warmup": self.retraces_after_warmup(),
        }


def serve(params, cfg, prompts, *, max_new_tokens: int = 16,
          **engine_kwargs) -> list[np.ndarray]:
    """One-call convenience: build an engine, run every prompt to
    completion, return each continuation as an int32 array (in prompt
    order)."""
    eng = ServingEngine(params, cfg, **engine_kwargs)
    reqs = [eng.submit(p, max_new_tokens=max_new_tokens)
            for p in prompts]
    eng.run()
    return [np.asarray(r.tokens, np.int32) for r in reqs]
