"""Continuous-batching decode engine over the paged KV pool.

The serving half of ``models/generate.py``: same layer math, different
cache substrate and driver.  Three invariants carry the design:

**Bitwise parity with one-shot decode.**  Every per-row op (rms_norm,
projections, per-query-row attention, logits) is bitwise-independent of
which OTHER rows share its batch — so chunked prefill, mixed-length
ragged batches, and admit/evict churn cannot change a request's tokens
… with ONE exception, measured on this backend: the softmax
denominator's reduction order depends on the attention's contraction
extent.  The engine therefore always contracts over the FIXED pool view
(``P_max × page_size`` positions; masked tails contribute exact zeros),
and one-shot ``generate`` grew a static ``cache_capacity`` arg to pin
the same extent.  With matched capacity, serving output is
bitwise-identical to ``generate`` — the invariant the parity suite
asserts per request.

**Zero retraces after warmup.**  The decode step has static shape:
``max_batch`` slots, an active mask, full-size page-table rows.
Admit/evict between bursts rewrites host arrays and ``device_put``s the
same shapes/dtypes/shardings — the jit cache stays at one entry per
program over a whole traffic trace (``slo_report`` carries the watch).

**Host blocks only at sync points.**  Decode bursts chain
``sync_every`` donated-buffer steps through ``runtime.StepPump``'s
bounded in-flight dispatch; the host resolves tokens, retires finished
requests and admits new ones once per burst.  Prefill is synchronous at
admission (TTFT is measured at first-token resolution) and CHUNKED so a
long prompt shares rounds with decode instead of stalling it.

Modes: single-program (default, one jit per device set), tensor-parallel
(``mesh`` + ``tp_axis``: params via ``parallel.tensor.tp_specs``, pool
heads sharded, 2 psums/layer — the ``serve_decode`` contract), and
prefill/decode DISAGGREGATED (mesh split into a prefill slice and a
decode slice as separate single-device programs, KV handed off by page
block — the separate-programs-per-role seam; intra-slice sharding is
future work).
"""

from __future__ import annotations

import dataclasses
import math
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..models import transformer as T
from ..models.generate import _decode_cfg, _quant_kv
from ..ops import collectives as C
from .kv_pool import PagedKVPool, PoolBuffers, RadixPrefixCache
from .scheduler import ContinuousBatcher, DECODE, PREFILL, Request

__all__ = ["ServingEngine", "serve", "make_serve_decode_step",
           "make_serve_prefill_step", "make_serve_spec_verify_step",
           "make_serve_prefill_batch_step", "make_draft_params"]


# ---------------------------------------------------------------- layer math

def _ragged_rope_tables(positions, head_dim: int, theta: float):
    """Per-BATCH rope tables: ``positions`` (B, S) int32 → cos/sin
    (B, S, hd/2) f32.  Same inv_freq/angle formula as
    ``transformer._rope_tables`` so a position's table row is bitwise
    the one the one-shot path computes for it."""
    inv_freq = 1.0 / theta ** (jnp.arange(0, head_dim, 2,
                                          dtype=jnp.float32) / head_dim)
    ang = positions.astype(jnp.float32)[..., None] * inv_freq
    return jnp.cos(ang), jnp.sin(ang)


def _apply_rope_ragged(x, cos, sin):
    """``transformer.apply_rope`` with per-batch tables: x (B, S, n, hd),
    cos/sin (B, S, hd/2) — identical split-half rotation, broadcast over
    heads instead of batch."""
    dt = x.dtype
    x = x.astype(jnp.float32)
    x1, x2 = jnp.split(x, 2, axis=-1)
    c = cos[:, :, None, :]
    s = sin[:, :, None, :]
    return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s],
                           axis=-1).astype(dt)


def _paged_layer_body(x, layer, *, cfg, cos, sin, use_rope, pk, pv,
                      pk_s, pv_s, pages, apos, valid, tp_axis=None,
                      paged_kernel=False, flash_prefill=False):
    """One decoder layer against the PAGED pool — the numerics of
    ``generate._cached_layer_body`` with scatter/gather storage:

      * new K/V rows scatter token-granularly into their page table
        slots; rows with ``valid`` False (prompt padding, inactive
        decode slots) divert to the reserved null page 0;
      * attention gathers the slot's pages back into a contiguous
        (B, n_kv, V, hd) view — position ``v`` of the view IS absolute
        position ``v`` (pages are ordered), so the causal mask
        ``pos_kv <= apos`` is unchanged and masked stale/garbage
        positions contribute exact zeros (finite garbage → −1e30 score
        → 0.0 prob), which is what keeps the paged path bitwise equal
        to the contiguous cache at matched contraction extent.

    x (B, S, H); pages (B, P) int32; apos (B, S) int32 absolute
    positions of x's rows; valid (B, S) bool."""
    B, S, H = x.shape
    hd = cfg.resolved_head_dim
    tp = C.axis_size(tp_axis) if tp_axis else 1
    nq = cfg.num_attention_heads // tp
    nkv = cfg.num_key_value_heads // tp
    dense = T._dense(cfg)
    page = pk.shape[1]
    P = pages.shape[1]
    V = P * page

    r = T.rms_norm(x, layer["ln1"], cfg.rms_norm_eps)
    q = dense(r, layer["wq"]).reshape(B, S, nq, hd)
    k = dense(r, layer["wk"]).reshape(B, S, nkv, hd)
    v = dense(r, layer["wv"]).reshape(B, S, nkv, hd)
    q = jnp.where(use_rope, _apply_rope_ragged(q, cos, sin), q)
    k = jnp.where(use_rope, _apply_rope_ragged(k, cos, sin), k)

    # scatter the new rows: target page from the slot's table, offset
    # within it; invalid rows all collapse onto page 0 (duplicate
    # scatter targets there are fine — it's the trash page)
    pi = jnp.clip(apos // page, 0, P - 1)
    pg = jnp.where(valid, jnp.take_along_axis(pages, pi, axis=1), 0)
    off = apos % page
    quantized = pk.dtype == jnp.int8
    if quantized:
        kq, ks_new = _quant_kv(k)
        vq, vs_new = _quant_kv(v)
        pk = pk.at[pg, off].set(kq)
        pv = pv.at[pg, off].set(vq)
        pk_s = pk_s.at[pg, off].set(ks_new)
        pv_s = pv_s.at[pg, off].set(vs_new)
    else:
        pk = pk.at[pg, off].set(k)
        pv = pv.at[pg, off].set(v)

    if paged_kernel and S == 1:
        # Pallas decode kernel: pages are read IN PLACE via the table —
        # the (B, V, nkv, hd) gather view below never materializes.
        # Bitwise-equal to the gather path (ops/paged_attention.py).
        from ..ops.paged_attention import paged_attention_decode
        rep = nq // nkv
        qg = q.reshape(B, S, nkv, rep, hd)
        if quantized:
            qq, q_s = _quant_kv(qg)
            attn = paged_attention_decode(
                qq, pk, pv, pages, apos, q_scale=q_s,
                pk_s=pk_s, pv_s=pv_s)
        else:
            attn = paged_attention_decode(qg, pk, pv, pages, apos,
                                          probs_dtype=x.dtype)
        attn = attn.astype(x.dtype).reshape(B, S, nq * hd)
        attn_out = dense(attn, layer["wo"])
        if tp_axis:
            attn_out = C.all_reduce(attn_out, tp_axis)
        x = x + attn_out
        r = T.rms_norm(x, layer["ln2"], cfg.rms_norm_eps)
        mlp, _aux = T._mlp_block(r, layer, cfg=cfg)
        if tp_axis:
            mlp = C.all_reduce(mlp, tp_axis)
        return x + mlp, (pk, pv, pk_s, pv_s)

    if flash_prefill and S > 1 and not quantized:
        # Pallas flash prefill: the whole chunk's attention in one
        # tiled online-softmax kernel reading pages via the table — no
        # (B, V, nkv, hd) gather view.  Single-tile (the default) is
        # bitwise-equal to the gather+einsum path below
        # (ops/flash_prefill.py pins the epilogue ordering).
        from ..ops.flash_prefill import paged_flash_prefill
        rep = nq // nkv
        qg = q.reshape(B, S, nkv, rep, hd)
        attn = paged_flash_prefill(qg, pk, pv, pages, apos,
                                   probs_dtype=x.dtype)
        attn = attn.astype(x.dtype).reshape(B, S, nq * hd)
        attn_out = dense(attn, layer["wo"])
        if tp_axis:
            attn_out = C.all_reduce(attn_out, tp_axis)
        x = x + attn_out
        r = T.rms_norm(x, layer["ln2"], cfg.rms_norm_eps)
        mlp, _aux = T._mlp_block(r, layer, cfg=cfg)
        if tp_axis:
            mlp = C.all_reduce(mlp, tp_axis)
        return x + mlp, (pk, pv, pk_s, pv_s)

    # gather the slot's pages into the contiguous head-major view the
    # attention contracts over — fixed extent V for every request, the
    # parity-bearing choice (see module docstring)
    vk = pk[pages].reshape(B, V, nkv, hd).transpose(0, 2, 1, 3)
    vv = pv[pages].reshape(B, V, nkv, hd).transpose(0, 2, 1, 3)

    rep = nq // nkv
    qg = q.reshape(B, S, nkv, rep, hd)
    if quantized:
        vk_s = pk_s[pages].reshape(B, V, nkv, 1).transpose(0, 2, 1, 3)
        vv_s = pv_s[pages].reshape(B, V, nkv, 1).transpose(0, 2, 1, 3)
        qq, q_s = _quant_kv(qg)
        scores_i = jnp.einsum("bsgrh,bgkh->bgrsk", qq, vk,
                              preferred_element_type=jnp.int32)
        scores = (scores_i.astype(jnp.float32)
                  * q_s[..., 0].transpose(0, 2, 3, 1)[..., None]
                  * vk_s[..., 0][:, :, None, None, :]) / math.sqrt(hd)
    else:
        scores = jnp.einsum(
            "bsgrh,bgkh->bgrsk", qg, vk,
            preferred_element_type=jnp.float32) / math.sqrt(hd)
    pos_kv = jnp.arange(V)
    vis = pos_kv[None, None, :] <= apos[:, :, None]      # (B, S, V)
    scores = jnp.where(vis[:, None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    if quantized:
        pvw = probs * vv_s[..., 0][:, :, None, None, :]
        pvq, pv_sc = _quant_kv(pvw)
        attn_i = jnp.einsum("bgrsk,bgkh->bsgrh", pvq, vv,
                            preferred_element_type=jnp.int32)
        attn = attn_i.astype(jnp.float32) \
            * pv_sc[..., 0].transpose(0, 3, 1, 2)[..., None]
    else:
        attn = jnp.einsum("bgrsk,bgkh->bsgrh", probs.astype(x.dtype), vv,
                          preferred_element_type=jnp.float32)
    attn = attn.astype(x.dtype).reshape(B, S, nq * hd)
    attn_out = dense(attn, layer["wo"])
    if tp_axis:
        attn_out = C.all_reduce(attn_out, tp_axis)
    x = x + attn_out

    r = T.rms_norm(x, layer["ln2"], cfg.rms_norm_eps)
    mlp, _aux = T._mlp_block(r, layer, cfg=cfg)
    if tp_axis:
        mlp = C.all_reduce(mlp, tp_axis)
    return x + mlp, (pk, pv, pk_s, pv_s)


def _paged_forward(params, ids, cfg, bufs: PoolBuffers, pages, apos,
                   valid, tp_axis=None, paged_kernel=False,
                   flash_prefill=False):
    """ids (B, S) → (hidden x (B, S, H), bufs') through the UNROLLED
    layer stack (static layer index into the per-layer pools, like
    ``generate._forward_cached``)."""
    x = params["embed"].astype(cfg.dtype)[ids]
    cos, sin = _ragged_rope_tables(apos, cfg.resolved_head_dim,
                                   cfg.rope_theta)
    flags = [(li + 1) % cfg.nope_interval != 0 if cfg.nope_interval
             else True for li in range(cfg.num_hidden_layers)]
    ks, vs = list(bufs.k), list(bufs.v)
    kss = list(bufs.k_scale) if bufs.k_scale is not None else None
    vss = list(bufs.v_scale) if bufs.v_scale is not None else None
    for li in range(cfg.num_hidden_layers):
        layer = jax.tree.map(lambda p: p[li], params["layers"])
        x, (ks[li], vs[li], ksc, vsc) = _paged_layer_body(
            x, layer, cfg=cfg, cos=cos, sin=sin,
            use_rope=bool(flags[li]),
            pk=ks[li], pv=vs[li],
            pk_s=kss[li] if kss is not None else None,
            pv_s=vss[li] if vss is not None else None,
            pages=pages, apos=apos, valid=valid, tp_axis=tp_axis,
            paged_kernel=paged_kernel, flash_prefill=flash_prefill)
        if kss is not None:
            kss[li], vss[li] = ksc, vsc
    out = PoolBuffers(k=tuple(ks), v=tuple(vs),
                      k_scale=tuple(kss) if kss is not None else None,
                      v_scale=tuple(vss) if vss is not None else None)
    return x, out


def _all_logits(params, x, cfg):
    """(B, S, H) hidden → (B, S, vocab) fp32 logits: the
    ``generate._forward_cached`` tail at EVERY row.  rms_norm and the
    unembedding are per-row ops, so row ``i`` is bitwise the
    single-position tail evaluated at that position — what lets the
    speculative verify step read k+1 greedy tokens from one forward."""
    x = T.rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    uq = params.get("unembed_q")
    if uq is not None:
        from ..ops.quant import prequantized_dense
        logits = prequantized_dense(x, uq)
    else:
        logits = x @ T._output_embedding(params, cfg).T
    return logits.astype(jnp.float32)


def _last_logits(params, x_last, cfg):
    """(B, 1, H) hidden → (B, vocab) fp32 logits, same tail as
    ``generate._forward_cached``."""
    return _all_logits(params, x_last, cfg)[:, 0]


def _decode_core(bufs, params, pages, toks, lengths, stop_at, active, *,
                 cfg, tp_axis=None, paged_kernel=False):
    """One fixed-shape decode step over every slot.  toks/lengths/
    stop_at (B,) int32, active (B,) bool.  Emits the next greedy token
    per ACTIVE slot (inactive slots freeze); a slot auto-retires ON
    DEVICE when its length reaches ``stop_at`` — the device can never
    write past a request's page grant even mid-burst, the host only
    observes retirement at the next sync."""
    apos = lengths[:, None]
    x, bufs = _paged_forward(params, toks[:, None], cfg, bufs, pages,
                             apos, active[:, None], tp_axis=tp_axis,
                             paged_kernel=paged_kernel)
    logits = _last_logits(params, x[:, -1:], cfg)
    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    nxt = jnp.where(active, nxt, toks)
    new_len = lengths + active.astype(jnp.int32)
    new_active = jnp.logical_and(active, new_len < stop_at)
    occ = jnp.sum(active.astype(jnp.int32))
    return nxt, new_len, new_active, bufs, occ


def _prefill_core(bufs, params, pages_row, ids, pos, plen, *, cfg,
                  tp_axis=None):
    """One prefill CHUNK for one request: ids (1, C) host-padded with
    zeros, pos/plen () int32 (chunk start, full prompt length).  Writes
    the chunk's K/V into the request's pages; rows past the prompt
    divert to the null page.  Returns the greedy first token — only
    meaningful on the FINAL chunk (position plen-1 falls inside it)."""
    Ck = ids.shape[1]
    apos = pos + jnp.arange(Ck, dtype=jnp.int32)[None, :]
    valid = apos < plen
    x, bufs = _paged_forward(params, ids, cfg, bufs, pages_row, apos,
                             valid, tp_axis=tp_axis)
    last = jnp.clip(plen - 1 - pos, 0, Ck - 1)
    xl = jax.lax.dynamic_slice_in_dim(x, last, 1, axis=1)
    logits = _last_logits(params, xl, cfg)
    tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return tok, bufs


def _prefill_batch_core(bufs, params, pages, ids, pos, plen, *, cfg,
                        tp_axis=None, flash_prefill=False):
    """One prefill chunk for a BATCH of requests: ids (Bp, C), pages
    (Bp, P), pos/plen (Bp,) int32 — the multi-request prefill step.
    Pad rows carry ``plen == 0``: every position is invalid, scatters
    divert to the null page, and the (garbage) token output is never
    read.  Returns each row's greedy token at its final prompt position
    — meaningful only for rows whose final chunk this is.  Rows are
    per-request bitwise-independent (the parity invariant), so batching
    requests changes nothing a single-row prefill would emit."""
    Bp, Ck = ids.shape
    apos = pos[:, None] + jnp.arange(Ck, dtype=jnp.int32)[None, :]
    valid = apos < plen[:, None]
    x, bufs = _paged_forward(params, ids, cfg, bufs, pages, apos, valid,
                             tp_axis=tp_axis,
                             flash_prefill=flash_prefill)
    last = jnp.clip(plen - 1 - pos, 0, Ck - 1)
    xl = jnp.take_along_axis(x, last[:, None, None], axis=1)
    logits = _last_logits(params, xl, cfg)
    tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return tok, bufs


def _spec_verify_core(bufs, params, pages, toks_blk, lengths, stop_at,
                      active, *, cfg, tp_axis=None):
    """The speculative VERIFY step: one fixed-shape target forward over
    a (B, k+1) token block per slot — the last accepted token plus the
    draft's k proposals.  Row ``i`` writes its K/V at ``lengths + i``
    (scatter precedes the gather inside every layer, so each row
    attends over exactly the committed prefix plus proposal rows
    ``<= i`` — the same visible set a sequential greedy decode would
    see, hence bitwise-identical per-row logits at temperature 0).
    Rows at positions ``>= stop_at`` divert to the null page: the
    device can never write past a request's page grant, mirroring the
    vanilla step's on-device auto-retire.  Returns per-row greedy
    argmax (B, k+1); acceptance is a separate collective-free jit
    (:func:`_spec_accept_core`) so macro-steps chain without a host
    sync."""
    B, S = toks_blk.shape
    apos = lengths[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]
    valid = active[:, None] & (apos < stop_at[:, None])
    x, bufs = _paged_forward(params, toks_blk, cfg, bufs, pages, apos,
                             valid, tp_axis=tp_axis)
    greedy = jnp.argmax(_all_logits(params, x, cfg),
                        axis=-1).astype(jnp.int32)
    occ = jnp.sum(active.astype(jnp.int32))
    return greedy, bufs, occ


def _spec_accept_core(toks_blk, greedy, toks, lengths, stop_at, active):
    """Device-side acceptance: longest verified prefix per slot.  Draft
    proposal ``toks_blk[:, i+1]`` is accepted iff it equals the
    target's greedy continuation ``greedy[:, i]`` and every earlier
    proposal matched — so the emitted stream ``greedy[:, :e]`` is
    exactly what sequential greedy decode would have produced (the
    rejected tail's pool rows are dead weight the next macro-step
    overwrites).  ``e`` is capped at ``stop_at - lengths`` so a slot
    never emits past its budget; inactive slots freeze with e = 0."""
    k = toks_blk.shape[1] - 1
    match = (toks_blk[:, 1:] == greedy[:, :-1]).astype(jnp.int32)
    e = 1 + jnp.sum(jnp.cumprod(match, axis=1), axis=1)
    e = jnp.minimum(e, stop_at - lengths)
    e = jnp.where(active, e, 0).astype(jnp.int32)
    new_len = lengths + e
    new_active = jnp.logical_and(active, new_len < stop_at)
    idx = jnp.clip(e - 1, 0, k)
    nxt = jnp.take_along_axis(greedy, idx[:, None], axis=1)[:, 0]
    nxt = jnp.where(active, nxt, toks).astype(jnp.int32)
    return nxt, new_len, new_active, e


# ------------------------------------------------------------- step builders

def make_serve_decode_step(cfg, params=None, *, mesh=None,
                           tp_axis: str = "tp", pool_spec=None,
                           paged_kernel: bool = False):
    """The jitted fixed-shape decode step, donated pool buffers.
    ``mesh`` selects the tensor-parallel shard_map wrapping (params must
    then be the tree ``parallel.tensor.tp_specs`` describes and
    ``pool_spec`` the pool's PartitionSpec pytree).  ``paged_kernel``
    routes attention through the Pallas decode kernel
    (``ops/paged_attention.py`` — pages read in place via the table, no
    contiguous gather view; bitwise-equal outputs)."""
    cfg = _decode_cfg(cfg)
    if mesh is None:
        return jax.jit(partial(_decode_core, cfg=cfg, tp_axis=None,
                               paged_kernel=paged_kernel),
                       donate_argnums=(0,))
    from jax.sharding import PartitionSpec as P
    from ..parallel.tensor import tp_specs
    core = partial(_decode_core, cfg=cfg, tp_axis=tp_axis,
                   paged_kernel=paged_kernel)
    in_specs = (pool_spec, tp_specs(params, tp_axis), P(), P(), P(),
                P(), P())
    out_specs = (P(), P(), P(), pool_spec, P())
    return jax.jit(C.smap(core, mesh, in_specs=in_specs,
                          out_specs=out_specs), donate_argnums=(0,))


def make_serve_prefill_step(cfg, params=None, *, mesh=None,
                            tp_axis: str = "tp", pool_spec=None):
    """The jitted single-request prefill-chunk step (see
    :func:`_prefill_core`)."""
    cfg = _decode_cfg(cfg)
    if mesh is None:
        return jax.jit(partial(_prefill_core, cfg=cfg, tp_axis=None),
                       donate_argnums=(0,))
    from jax.sharding import PartitionSpec as P
    from ..parallel.tensor import tp_specs
    core = partial(_prefill_core, cfg=cfg, tp_axis=tp_axis)
    in_specs = (pool_spec, tp_specs(params, tp_axis), P(), P(), P(), P())
    out_specs = (P(), pool_spec)
    return jax.jit(C.smap(core, mesh, in_specs=in_specs,
                          out_specs=out_specs), donate_argnums=(0,))


def make_serve_prefill_batch_step(cfg, params=None, *, mesh=None,
                                  tp_axis: str = "tp", pool_spec=None,
                                  flash_prefill: bool = True):
    """The jitted BATCHED multi-request prefill-chunk step (see
    :func:`_prefill_batch_core`).  ``flash_prefill`` routes the chunk's
    attention through the Pallas flash kernel
    (``ops/flash_prefill.py``) instead of the gather+einsum path —
    bitwise-equal in the default single-tile mode."""
    cfg = _decode_cfg(cfg)
    if mesh is None:
        return jax.jit(partial(_prefill_batch_core, cfg=cfg,
                               tp_axis=None,
                               flash_prefill=flash_prefill),
                       donate_argnums=(0,))
    from jax.sharding import PartitionSpec as P
    from ..parallel.tensor import tp_specs
    core = partial(_prefill_batch_core, cfg=cfg, tp_axis=tp_axis,
                   flash_prefill=flash_prefill)
    in_specs = (pool_spec, tp_specs(params, tp_axis), P(), P(), P(), P())
    out_specs = (P(), pool_spec)
    return jax.jit(C.smap(core, mesh, in_specs=in_specs,
                          out_specs=out_specs), donate_argnums=(0,))


def make_serve_spec_verify_step(cfg, params=None, *, mesh=None,
                                tp_axis: str = "tp", pool_spec=None):
    """The jitted speculative-verify step (see
    :func:`_spec_verify_core`): one (B, k+1) target forward replaces
    k+1 sequential decode steps.  Same collective shape as the decode
    step — 2 psums per layer over ``tp`` — which is the
    ``serve_decode_spec`` contract."""
    cfg = _decode_cfg(cfg)
    if mesh is None:
        return jax.jit(partial(_spec_verify_core, cfg=cfg,
                               tp_axis=None), donate_argnums=(0,))
    from jax.sharding import PartitionSpec as P
    from ..parallel.tensor import tp_specs
    core = partial(_spec_verify_core, cfg=cfg, tp_axis=tp_axis)
    in_specs = (pool_spec, tp_specs(params, tp_axis), P(), P(), P(),
                P(), P())
    out_specs = (P(), pool_spec, P())
    return jax.jit(C.smap(core, mesh, in_specs=in_specs,
                          out_specs=out_specs), donate_argnums=(0,))


def make_draft_params(params, cfg, n_layers: int):
    """A correlated toy draft model: the target's first ``n_layers``
    decoder layers with the embedding / final-norm / unembedding kept.
    Cheap to run, right often enough on easy tokens to be a useful
    proposer — and parity never depends on it: at temperature 0 ANY
    draft yields the vanilla greedy stream, a bad one just lowers the
    acceptance rate.  Returns ``(draft_params, draft_cfg)``."""
    if not 1 <= int(n_layers) <= cfg.num_hidden_layers:
        raise ValueError(f"draft of {n_layers} layers from a "
                         f"{cfg.num_hidden_layers}-layer target")
    draft = dict(params)
    draft["layers"] = jax.tree.map(lambda p: p[:int(n_layers)],
                                   params["layers"])
    return draft, dataclasses.replace(cfg,
                                      num_hidden_layers=int(n_layers))


# ------------------------------------------------------------------- engine

class ServingEngine:
    """Continuous-batching server over the paged pool.

    ``submit()`` requests (with optional virtual ``arrival_s`` offsets),
    then ``run()`` drives the round loop to completion and returns the
    finished :class:`scheduler.Request` records; ``slo_report()``
    aggregates them into the TTFT / per-token-latency percentiles and
    throughput the SLO table renders.  ``telem``: a
    ``telemetry.TelemetryRun`` to stream per-round events into
    (prefill events carry per-request TTFT, decode-burst events carry
    occupancy/pool gauges and per-request latency at completion)."""

    def __init__(self, params, cfg, *, mesh=None, tp_axis: str = "tp",
                 max_batch: int = 4, page_size: int = 8,
                 max_seq_len: int = 64, n_pages: int | None = None,
                 prefill_chunk: int = 16,
                 prefill_chunks_per_round: int = 2,
                 sync_every: int = 4, max_in_flight: int = 8,
                 kv_quant: bool = False,
                 paged_kernel: bool = False,
                 prefix_cache: bool = False,
                 spec_k: int = 0, draft_params=None, draft_cfg=None,
                 draft_layers: int | None = None,
                 flash_prefill: bool = False,
                 hbm_budget_gb: float | None = None,
                 disaggregate: bool = False, device=None,
                 watchdog=None, telem=None):
        self.cfg = _decode_cfg(cfg)
        self.max_batch = int(max_batch)
        self.page_size = int(page_size)
        self.pages_per_request = -(-int(max_seq_len) // self.page_size)
        # the fixed contraction extent — pass as generate()'s
        # cache_capacity for bitwise comparison
        self.view_capacity = self.pages_per_request * self.page_size
        self.prefill_chunk = int(prefill_chunk)
        self.prefill_chunks_per_round = int(prefill_chunks_per_round)
        self.sync_every = max(int(sync_every), 1)
        self.max_in_flight = int(max_in_flight)
        self.kv_quant = bool(kv_quant)
        # decode attention through the Pallas paged kernel (pages read
        # in place via the table — ops/paged_attention.py); prefill
        # (S > 1) keeps the gather path
        self.paged_kernel = bool(paged_kernel)
        # prefill through the BATCHED multi-request step with the
        # Pallas flash-attention kernel (ops/flash_prefill.py)
        self.flash_prefill = bool(flash_prefill)
        self.spec_k = int(spec_k)
        if self.flash_prefill and kv_quant:
            raise ValueError("the flash prefill kernel is float-only — "
                             "drop kv_quant or flash_prefill")
        if prefix_cache and disaggregate:
            raise ValueError(
                "prefix_cache aliases decode-pool pages across "
                "requests; the disaggregated handoff injects full page "
                "rows and would overwrite shared pages — not wired")
        if self.spec_k and disaggregate:
            raise ValueError("speculative decoding needs a resident "
                             "draft pool; the disaggregated handoff is "
                             "not wired for it")
        if self.spec_k:
            if draft_params is None:
                if draft_layers is None:
                    raise ValueError(
                        "spec_k > 0 needs draft_params + draft_cfg, or "
                        "draft_layers to truncate the target")
                draft_params, draft_cfg = make_draft_params(
                    params, self.cfg, draft_layers)
            elif draft_cfg is None:
                raise ValueError("draft_params needs draft_cfg")
            self.draft_cfg = _decode_cfg(draft_cfg)
        else:
            self.draft_cfg = None
        self.mesh = mesh
        self.tp_axis = tp_axis if mesh is not None else None
        self.telem = telem
        # fleet replica index (set by Fleet at construction); stamped
        # into this engine's serve spans so a merged timeline can tell
        # the dead replica's attempt from the survivor's replay
        self.replica = None
        self.disaggregate = bool(disaggregate)
        # collective watchdog (resilience.elastic.Watchdog): every
        # blocking point in the decode path — the pump's sync sites and
        # the burst's token resolution — routes through it, so a wedged
        # burst becomes a StepTimeoutError the fleet's failover path
        # can consume instead of a hung server
        self.watchdog = watchdog

        tp = 1
        if mesh is not None:
            if device is not None:
                raise ValueError("pass mesh or device, not both")
            if disaggregate:
                raise ValueError("disaggregate splits devices into "
                                 "single-program slices; pass mesh=None")
            from ..parallel.tensor import (check_tp_divisibility,
                                           shard_params_tp)
            tp = int(mesh.shape[tp_axis])
            check_tp_divisibility(self.cfg, tp)
            if "unembed_q" in params:
                raise ValueError("tensor-parallel serving takes bf16 "
                                 "params (int8 weight sharding is not "
                                 "wired)")
            params = shard_params_tp(params, mesh, tp_axis)
            if self.spec_k:
                check_tp_divisibility(self.draft_cfg, tp)
                draft_params = shard_params_tp(draft_params, mesh,
                                               tp_axis)

        if n_pages is None:
            n_pages = self.max_batch * self.pages_per_request + 1
            if hbm_budget_gb is not None:
                from ..utils.memory import tree_size_bytes
                from .accounting import pool_capacity_pages
                fit = pool_capacity_pages(
                    self.cfg, self.page_size, budget_gb=hbm_budget_gb,
                    weight_bytes=tree_size_bytes(params),
                    kv_quant=self.kv_quant, tp=tp,
                    draft_weight_bytes=(tree_size_bytes(draft_params)
                                        if self.spec_k else 0),
                    draft_cfg=self.draft_cfg) + 1
                n_pages = min(n_pages, fit)
        if n_pages < self.pages_per_request + 1:
            raise ValueError(
                f"pool of {n_pages} pages cannot hold one request "
                f"({self.pages_per_request} pages + null); raise the "
                f"HBM budget or shrink max_seq_len")
        self.n_pages = int(n_pages)

        devs = jax.devices()
        self._prefill_dev = self._decode_dev = None
        if device is not None:
            # whole-engine device commitment: the fleet's per-replica
            # slice, reusing the disaggregation device_put machinery
            # with prefill and decode on the SAME device
            if self.disaggregate:
                raise ValueError("device commits the whole engine to "
                                 "one device; disaggregate splits it — "
                                 "pick one")
            self._prefill_dev = self._decode_dev = device
            self._params = self._params_pre = jax.device_put(params,
                                                             device)
            if self.spec_k:
                draft_params = jax.device_put(draft_params, device)
        elif self.disaggregate:
            if len(devs) < 2:
                raise ValueError("disaggregate needs >= 2 devices")
            self._prefill_dev = devs[0]
            self._decode_dev = devs[len(devs) // 2]
            self._params = jax.device_put(params, self._decode_dev)
            self._params_pre = jax.device_put(params, self._prefill_dev)
        else:
            self._params = params
            self._params_pre = params
        self._draft_params = draft_params if self.spec_k else None

        self.pool = PagedKVPool(self.cfg, self.n_pages, self.page_size,
                                kv_quant=self.kv_quant, mesh=mesh,
                                tp_axis=tp_axis, device=self._decode_dev)
        # the draft model's own pool, addressed by the SAME page tables
        # as the target pool (no second allocator): position p of a
        # request's draft KV lives at the same (page, offset) as its
        # target KV, so admission/eviction/prefix-alias bookkeeping is
        # shared and the draft rows for a trie-cached page stay valid
        # exactly as long as the page is cached
        self.draft_pool = None
        if self.spec_k:
            self.draft_pool = PagedKVPool(
                self.draft_cfg, self.n_pages, self.page_size,
                kv_quant=self.kv_quant, mesh=mesh, tp_axis=tp_axis,
                device=self._decode_dev)
        # the serving-side waterline prediction the memory ledger joins:
        # accounting's weights+pool model vs the decode program's own
        # memory_analysis() (attached at the first decode burst)
        from ..utils.memory import GB, tree_size_bytes
        from .accounting import serve_waterline_gb
        _wb = tree_size_bytes(self._params)
        _pool_b = tree_size_bytes(self.pool.bufs)
        _dwb = tree_size_bytes(self._draft_params) if self.spec_k else 0
        comps = {"weights": round(_wb / GB, 3),
                 "kv_pool": round(_pool_b / GB, 3)}
        if self.spec_k:
            comps["draft_weights"] = round(_dwb / GB, 3)
            comps["draft_kv_pool"] = round(
                tree_size_bytes(self.draft_pool.bufs) / GB, 3)
        self._mem_prediction = {
            "predicted_gb": round(serve_waterline_gb(
                self.cfg, self.n_pages, self.page_size, weight_bytes=_wb,
                kv_quant=self.kv_quant, tp=tp,
                draft_weight_bytes=_dwb, draft_cfg=self.draft_cfg), 3),
            "source": "serve_accounting",
            "components": comps,
        }
        self.pool_pre = None
        if self.disaggregate:
            self.pool_pre = PagedKVPool(
                self.cfg, self.n_pages, self.page_size,
                kv_quant=self.kv_quant, device=self._prefill_dev)
            self._pre_pages: dict[int, list[int]] = {}

        pool_spec = self.pool.spec if mesh is not None else None
        self._decode = make_serve_decode_step(
            self.cfg, self._params, mesh=mesh, tp_axis=tp_axis,
            pool_spec=pool_spec, paged_kernel=self.paged_kernel)
        self._prefill = self._prefill_batch = None
        if self.flash_prefill:
            self._prefill_batch = make_serve_prefill_batch_step(
                self.cfg, self._params_pre, mesh=mesh, tp_axis=tp_axis,
                pool_spec=pool_spec, flash_prefill=True)
        else:
            self._prefill = make_serve_prefill_step(
                self.cfg, self._params_pre, mesh=mesh, tp_axis=tp_axis,
                pool_spec=pool_spec)
        self._draft_decode = self._verify = self._accept = None
        self._draft_prefill = self._draft_prefill_batch = None
        if self.spec_k:
            dspec = self.draft_pool.spec if mesh is not None else None
            self._draft_decode = make_serve_decode_step(
                self.draft_cfg, self._draft_params, mesh=mesh,
                tp_axis=tp_axis, pool_spec=dspec,
                paged_kernel=self.paged_kernel)
            self._verify = make_serve_spec_verify_step(
                self.cfg, self._params, mesh=mesh, tp_axis=tp_axis,
                pool_spec=pool_spec)
            self._accept = jax.jit(_spec_accept_core)
            if self.flash_prefill:
                self._draft_prefill_batch = make_serve_prefill_batch_step(
                    self.draft_cfg, self._draft_params, mesh=mesh,
                    tp_axis=tp_axis, pool_spec=dspec, flash_prefill=True)
            else:
                self._draft_prefill = make_serve_prefill_step(
                    self.draft_cfg, self._draft_params, mesh=mesh,
                    tp_axis=tp_axis, pool_spec=dspec)
        if self.disaggregate:
            # KV handoff: gather the request's page blocks out of the
            # prefill pool, ship, scatter into its decode pages.  Full
            # padded rows keep the programs single-shape; null-row
            # blocks land on masked positions (exact-zero contribution).
            def extract(bufs, row):
                sc = None
                if bufs.k_scale is not None:
                    sc = (tuple(s[row] for s in bufs.k_scale),
                          tuple(s[row] for s in bufs.v_scale))
                return (tuple(k[row] for k in bufs.k),
                        tuple(v[row] for v in bufs.v), sc)

            def inject(bufs, blocks, row):
                bk, bv, sc = blocks
                ks = vs = None
                if bufs.k_scale is not None:
                    ks = tuple(s.at[row].set(b)
                               for s, b in zip(bufs.k_scale, sc[0]))
                    vs = tuple(s.at[row].set(b)
                               for s, b in zip(bufs.v_scale, sc[1]))
                return PoolBuffers(
                    k=tuple(p.at[row].set(b)
                            for p, b in zip(bufs.k, bk)),
                    v=tuple(p.at[row].set(b)
                            for p, b in zip(bufs.v, bv)),
                    k_scale=ks, v_scale=vs)

            self._extract = jax.jit(extract)
            self._inject = jax.jit(inject, donate_argnums=(0,))

        B, P = self.max_batch, self.pages_per_request
        self._h_tokens = np.zeros(B, np.int32)
        self._h_lengths = np.zeros(B, np.int32)
        self._h_stop = np.zeros(B, np.int32)
        self._h_active = np.zeros(B, np.bool_)
        self._h_pages = np.zeros((B, P), np.int32)

        self.batcher = ContinuousBatcher(self.max_batch,
                                         self.pool.allocator,
                                         self.page_size)
        self.batcher.metrics = getattr(telem, "metrics", None)
        self.prefix_cache = None
        if prefix_cache:
            self.prefix_cache = RadixPrefixCache(self.pool.allocator,
                                                 self.page_size)
            self.batcher.prefix_cache = self.prefix_cache
        self._pending: list[Request] = []
        self.completed: list[Request] = []
        self._rid = 0
        self._pump = None
        self._t0: float | None = None
        self._warm_sizes = None
        self.stats = {"rounds": 0, "decode_steps": 0, "prefill_chunks": 0,
                      "admit_s": 0.0, "bookkeep_s": 0.0,
                      # measured per-phase device time — the per-burst
                      # priors the virtual-clock simulator's cost model
                      # calibrates from (sim.SimCostModel.from_fleet)
                      "prefill_s": 0.0, "decode_s": 0.0,
                      "occupancy_sum": 0, "peak_pool_util": 0.0,
                      "wall_s": 0.0, "host_sync_count": 0,
                      "draft_steps": 0, "spec_proposed": 0,
                      "spec_accepted": 0}

    # ---- request intake ----------------------------------------------
    def submit(self, prompt, max_new_tokens: int,
               arrival_s: float | None = None) -> Request:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1 or max_new_tokens < 1:
            raise ValueError("need >= 1 prompt token and >= 1 new token")
        if prompt.size + max_new_tokens > self.view_capacity:
            raise ValueError(
                f"prompt {prompt.size} + new {max_new_tokens} exceeds "
                f"the engine's view capacity {self.view_capacity} "
                f"(raise max_seq_len)")
        req = Request(rid=self._rid, prompt=prompt,
                      max_new_tokens=int(max_new_tokens),
                      arrival_s=(None if arrival_s is None
                                 else float(arrival_s)))
        # single-engine runs have no Router in front; mint the trace id
        # here with the same shape the fleet router uses
        req.trace_id = f"tr-{req.rid:06d}"
        self._rid += 1
        self._pending.append(req)
        return req

    def enqueue(self, req: Request, now: float) -> None:
        """Hand an externally-built request straight to the batcher —
        the fleet router's dispatch path, where rids are fleet-global
        and admission control already ran at submit."""
        self.batcher.submit(req, now)

    # ---- fleet queries -----------------------------------------------
    def can_accept(self, req: Request) -> bool:
        """True when ``req`` would be admitted at the next round: a
        free slot AND its full page grant, with nothing already queued
        (the fleet router keeps one global queue rather than stacking
        head-of-line blocking inside every replica)."""
        if self.batcher.waiting:
            return False
        if not any(r is None for r in self.batcher.slots):
            return False
        # credit the trie's evictable pages: admit() evicts under
        # pressure, so a grant coverable by free + reclaimable WILL
        # seat — without the credit a saturated prefix cache wedges
        # dispatch forever while the replica sits idle
        free = self.pool.allocator.free_pages
        if self.prefix_cache is not None:
            free += self.prefix_cache.reclaimable_pages
        return free >= self.batcher.pages_needed(req)

    def in_flight(self) -> int:
        """Unfinished requests resident in this engine (queued or
        holding a slot)."""
        return len(self.batcher.waiting) + sum(
            r is not None for r in self.batcher.slots)

    # ---- device-put helpers ------------------------------------------
    def _put(self, x, device=None):
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            return jax.device_put(x, NamedSharding(self.mesh, P()))
        if device is not None:
            return jax.device_put(x, device)
        if self._decode_dev is not None:
            return jax.device_put(x, self._decode_dev)
        return jnp.asarray(x)

    # ---- prefill ------------------------------------------------------
    def _padded_row(self, pages: list[int]) -> np.ndarray:
        row = np.zeros((1, self.pages_per_request), np.int32)
        row[0, :len(pages)] = pages
        return row

    def _prefill_one_chunk(self, req: Request, t0: float) -> None:
        Ck = self.prefill_chunk
        pos = req.prefill_pos
        chunk = req.prompt[pos:pos + Ck]
        ids = np.zeros((1, Ck), np.int32)
        ids[0, :chunk.shape[0]] = chunk
        dev = self._prefill_dev
        if self.disaggregate:
            row = self._padded_row(self._pre_pages[req.rid])
            bufs = self.pool_pre.bufs
        else:
            row = self._padded_row(req.pages)
            bufs = self.pool.bufs
        t_chunk = time.perf_counter()  # clock-ok
        tok_d, bufs = self._prefill(
            bufs, self._params_pre, self._put(row, dev),
            self._put(ids, dev), self._put(np.int32(pos), dev),
            self._put(np.int32(req.n_prompt), dev))
        if self.disaggregate:
            self.pool_pre.bufs = bufs
        else:
            self.pool.bufs = bufs
        if self.spec_k:
            # the draft needs the prompt's KV in ITS pool to propose —
            # ride the same chunk schedule (same pages, draft params)
            _dtok, dbufs = self._draft_prefill(
                self.draft_pool.bufs, self._draft_params,
                self._put(row, dev), self._put(ids, dev),
                self._put(np.int32(pos), dev),
                self._put(np.int32(req.n_prompt), dev))
            self.draft_pool.bufs = dbufs
        req.prefill_pos = min(pos + Ck, req.n_prompt)
        self.stats["prefill_chunks"] += 1
        if req.prefill_pos < req.n_prompt:
            self.stats["prefill_s"] += time.perf_counter() - t_chunk  # clock-ok
            return
        # final chunk: hand off KV (disaggregated), resolve the first
        # token — prefill is synchronous at admission, so this blocks
        # the host by design and stamps TTFT at token resolution
        if self.disaggregate:
            dec_row = self._padded_row(req.pages)
            blocks = self._extract(self.pool_pre.bufs,
                                   self._put(row[0], self._prefill_dev))
            blocks = jax.device_put(blocks, self._decode_dev)
            self.pool.bufs = self._inject(
                self.pool.bufs, blocks,
                self._put(dec_row[0], self._decode_dev))
            self.pool_pre.allocator.free(self._pre_pages.pop(req.rid))
        first = int(np.asarray(tok_d)[0])   # sync-ok: TTFT resolution
        self.stats["host_sync_count"] += 1
        self._finish_prefill(req, first, t_chunk, t0)
        self.stats["prefill_s"] += time.perf_counter() - t_chunk  # clock-ok

    def _finish_prefill(self, req: Request, first: int, t_chunk: float,
                        t0: float) -> None:
        """Shared final-chunk bookkeeping: donate full-prompt pages to
        the prefix cache, stamp TTFT, emit telemetry, and flip the slot
        into DECODE (or retire it when ``max_new == 1``)."""
        now = time.perf_counter() - t0  # clock-ok
        if self.prefix_cache is not None:
            # insert at prefill COMPLETION: the request's full prompt
            # pages hold committed KV now, so later arrivals sharing
            # the prefix alias them.  A concurrent twin that finished
            # first wins the trie slot — our duplicate page is freed
            # and the page-table entry swaps to the cached twin
            # (bitwise-identical content, invisible to decode).
            nodes, swaps = self.prefix_cache.insert(
                req.prompt, req.pages, req.cache_nodes)
            req.cache_nodes = nodes
            for i, pg in swaps.items():
                req.pages[i] = pg
                self._h_pages[req.slot, i] = pg
        req.tokens.append(first)
        req.t_first = now
        prefill_s = time.perf_counter() - t_chunk  # clock-ok
        spans = getattr(self.telem, "spans", None)
        if spans is not None:
            # t_submit/t_admit/t_first ride along (engine-clock seconds)
            # so fleet_timeline can decompose TTFT into queue wait +
            # prefill without re-deriving request state
            spans.record("serve/prefill_chunk", start_perf=t_chunk,
                         end_perf=time.perf_counter(), cat="serve",  # clock-ok
                         rid=req.rid, n_prompt=int(req.n_prompt),
                         request_id=req.rid, trace_id=req.trace_id,
                         replica=self.replica,
                         t_submit_s=req.t_submit, t_admit_s=req.t_admit,
                         t_first_s=req.t_first)
        if self.telem is not None:
            self.telem.step(
                loss=None, tokens=req.n_prompt,
                tracker_metrics={"last_step_time_s": prefill_s},
                phase="prefill", rid=req.rid,
                request_id=req.rid, trace_id=req.trace_id,
                ttft_ms=round(1e3 * (req.ttft_s or 0.0), 3),
                pool_util=round(self.pool.utilization, 4))
        b = req.slot
        stop = req.n_prompt + req.max_new_tokens - 1
        if req.n_prompt >= stop:      # max_new == 1: done at prefill
            req.state = DECODE
            self.batcher.retire(req, now)
            self.completed.append(req)
            self._h_active[b] = False
            self._h_pages[b] = 0
            return
        req.state = DECODE
        self._h_tokens[b] = first
        self._h_lengths[b] = req.n_prompt
        self._h_stop[b] = stop
        self._h_active[b] = True

    def _prefill_batch_chunk(self, reqs: list[Request],
                             t0: float) -> None:
        """One BATCHED prefill chunk: every in-flight PREFILL request
        advances one chunk through a single fixed-shape
        (max_batch, C) step — the multi-request prefill the flash
        kernel tier serves.  Pad rows carry ``plen = 0`` (every
        position invalid); requests whose final chunk this is resolve
        their first token in ONE host sync."""
        B, Ck = self.max_batch, self.prefill_chunk
        ids = np.zeros((B, Ck), np.int32)
        pages = np.zeros((B, self.pages_per_request), np.int32)
        pos = np.zeros(B, np.int32)
        plen = np.zeros(B, np.int32)
        for i, req in enumerate(reqs):
            chunk = req.prompt[req.prefill_pos:req.prefill_pos + Ck]
            ids[i, :chunk.shape[0]] = chunk
            src = (self._pre_pages[req.rid] if self.disaggregate
                   else req.pages)
            pages[i, :len(src)] = src
            pos[i] = req.prefill_pos
            plen[i] = req.n_prompt
        dev = self._prefill_dev
        bufs = self.pool_pre.bufs if self.disaggregate \
            else self.pool.bufs
        t_chunk = time.perf_counter()  # clock-ok
        tok_d, bufs = self._prefill_batch(
            bufs, self._params_pre, self._put(pages, dev),
            self._put(ids, dev), self._put(pos, dev),
            self._put(plen, dev))
        if self.disaggregate:
            self.pool_pre.bufs = bufs
        else:
            self.pool.bufs = bufs
        if self.spec_k:
            _dt, dbufs = self._draft_prefill_batch(
                self.draft_pool.bufs, self._draft_params,
                self._put(pages, dev), self._put(ids, dev),
                self._put(pos, dev), self._put(plen, dev))
            self.draft_pool.bufs = dbufs
        self.stats["prefill_chunks"] += 1
        finishing = []
        for i, req in enumerate(reqs):
            req.prefill_pos = min(req.prefill_pos + Ck, req.n_prompt)
            if req.prefill_pos >= req.n_prompt:
                finishing.append((i, req))
        if not finishing:
            self.stats["prefill_s"] += time.perf_counter() - t_chunk  # clock-ok
            return
        if self.disaggregate:
            for i, req in finishing:
                row = self._padded_row(self._pre_pages[req.rid])
                dec_row = self._padded_row(req.pages)
                blocks = self._extract(
                    self.pool_pre.bufs,
                    self._put(row[0], self._prefill_dev))
                blocks = jax.device_put(blocks, self._decode_dev)
                self.pool.bufs = self._inject(
                    self.pool.bufs, blocks,
                    self._put(dec_row[0], self._decode_dev))
                self.pool_pre.allocator.free(
                    self._pre_pages.pop(req.rid))
        toks = np.asarray(tok_d)    # sync-ok: TTFT resolution, one
        self.stats["host_sync_count"] += 1   # sync for all finishers
        for i, req in finishing:
            self._finish_prefill(req, int(toks[i]), t_chunk, t0)
        self.stats["prefill_s"] += time.perf_counter() - t_chunk  # clock-ok

    # ---- decode -------------------------------------------------------
    def _decode_burst(self, pump, t0: float) -> None:
        sync = self.sync_every
        L0 = self._h_lengths.copy()
        A0 = self._h_active.copy()
        toks_d = self._put(self._h_tokens)
        len_d = self._put(self._h_lengths)
        stop_d = self._put(self._h_stop)
        act_d = self._put(self._h_active)
        pages_d = self._put(self._h_pages)
        bufs = self.pool.bufs
        if self.telem is not None:
            # ledger join (no-op unless the run owns an enabled
            # profiler, and only compiles once): the decode program's
            # text at this burst's exact arg shardings
            self.telem.attach_step_hlo(self._decode, bufs, self._params,
                                       pages_d, toks_d, len_d, stop_d,
                                       act_d,
                                       trees={"kv_pool": bufs,
                                              "params": self._params},
                                       prediction=self._mem_prediction)
        t_burst = time.perf_counter()  # clock-ok
        step_tokens = []
        for _ in range(sync):
            toks_d, len_d, act_d, bufs, occ = self._decode(
                bufs, self._params, pages_d, toks_d, len_d, stop_d,
                act_d)
            pump.emit(occ)
            step_tokens.append(toks_d)
        self.pool.bufs = bufs
        self.stats["decode_steps"] += sync
        # sync point: the pump just resolved the last step's occupancy,
        # so the burst's token buffers are (near-)ready — resolve and
        # replay the device's deterministic active chain on the host.
        # Watchdog-guarded: a burst wedged here must surface as
        # StepTimeoutError for the fleet's failover, never a silent hang
        if self.watchdog is not None:
            mats = self.watchdog.block(
                lambda ts: [np.asarray(t) for t in ts],   # sync-ok
                step_tokens, step=self.stats["decode_steps"])
        else:
            mats = [np.asarray(t) for t in step_tokens]   # sync-ok
        self.stats["host_sync_count"] += 1
        burst_s = time.perf_counter() - t_burst  # clock-ok
        self.stats["decode_s"] += burst_s
        spans = getattr(self.telem, "spans", None)
        if spans is not None:
            spans.record("serve/decode_burst", start_perf=t_burst,
                         end_perf=time.perf_counter(), cat="serve",  # clock-ok
                         steps=int(sync), replica=self.replica)
        t_book = time.perf_counter()  # clock-ok
        active, lengths = A0.copy(), L0.copy()
        occ_burst, emitted = [], 0
        for j in range(sync):
            occ_burst.append(int(active.sum()))
            for b in np.nonzero(active)[0]:
                self.batcher.slot_request(int(b)).tokens.append(
                    int(mats[j][b]))
                emitted += 1
            lengths = lengths + active
            active = active & (lengths < self._h_stop)
        self._h_tokens = mats[-1].copy()
        self._h_lengths = lengths
        self._h_active = active
        now = time.perf_counter() - t0  # clock-ok
        finished = []
        for b in range(self.max_batch):
            req = self.batcher.slot_request(b)
            if req is not None and req.state == DECODE and not active[b]:
                self.batcher.retire(req, now)
                self._h_pages[b] = 0     # slot back to the null page
                self.completed.append(req)
                finished.append(req)
        self.stats["bookkeep_s"] += time.perf_counter() - t_book  # clock-ok
        if self.telem is not None:
            self.telem.step(
                loss=None, tokens=emitted,
                tracker_metrics={"last_step_time_s": burst_s / sync},
                phase="decode",
                active=round(float(np.mean(occ_burst)), 3),
                admitted=self.batcher.admitted_total,
                completed=self.batcher.completed_total,
                kv_pages_in_use=self.pool.allocator.pages_in_use,
                pool_util=round(self.pool.utilization, 4),
                completed_requests=[
                    {"rid": r.rid,
                     "trace_id": r.trace_id,
                     "ttft_ms": round(1e3 * (r.ttft_s or 0.0), 3),
                     "per_token_ms": round(1e3 * (r.per_token_s or 0.0),
                                           3),
                     "tokens": len(r.tokens)} for r in finished])

    def _spec_burst(self, pump, t0: float) -> None:
        """Speculative decode burst: ``sync_every`` macro-steps, each =
        k draft decode steps + one (B, k+1) target verify + a
        device-side acceptance update — the whole chain dispatches
        without touching the host; ONE sync at the end resolves every
        macro-step's greedy rows and acceptance counts, and the host
        replays the acceptance chain to append tokens and retire
        finished requests.  Rollback of rejected draft tails is free:
        their pool rows sit at positions past the committed length,
        masked from every live query (``pos_kv <= apos``), and the next
        macro-step's scatter overwrites them before any read — in both
        the target and the draft pool."""
        sync, k = self.sync_every, self.spec_k
        L0 = self._h_lengths.copy()
        A0 = self._h_active.copy()
        toks_d = self._put(self._h_tokens)
        len_d = self._put(self._h_lengths)
        stop_d = self._put(self._h_stop)
        act_d = self._put(self._h_active)
        pages_d = self._put(self._h_pages)
        bufs = self.pool.bufs
        dbufs = self.draft_pool.bufs
        if self.telem is not None:
            blk0 = self._put(np.zeros((self.max_batch, k + 1),
                                      np.int32))
            self.telem.attach_step_hlo(self._verify, bufs, self._params,
                                       pages_d, blk0, len_d, stop_d,
                                       act_d,
                                       trees={"kv_pool": bufs,
                                              "params": self._params},
                                       prediction=self._mem_prediction)
        t_burst = time.perf_counter()  # clock-ok
        g_steps, e_steps = [], []
        for _ in range(sync):
            # k draft self-decode steps propose a token chain per slot;
            # the draft runs against ITS pool at the same page table,
            # with the same stop_at so it can never write past a grant
            d_toks, d_len, d_act = toks_d, len_d, act_d
            props = [toks_d]
            for _i in range(k):
                d_toks, d_len, d_act, dbufs, _docc = self._draft_decode(
                    dbufs, self._draft_params, pages_d, d_toks, d_len,
                    stop_d, d_act)
                props.append(d_toks)
            blk = jnp.stack(props, axis=1)          # (B, k+1)
            g_d, bufs, occ = self._verify(bufs, self._params, pages_d,
                                          blk, len_d, stop_d, act_d)
            pump.emit(occ)
            toks_d, len_d, act_d, e_d = self._accept(
                blk, g_d, toks_d, len_d, stop_d, act_d)
            g_steps.append(g_d)
            e_steps.append(e_d)
        self.pool.bufs = bufs
        self.draft_pool.bufs = dbufs
        self.stats["decode_steps"] += sync
        self.stats["draft_steps"] += sync * k
        arrs = g_steps + e_steps + [toks_d]
        if self.watchdog is not None:
            mats = self.watchdog.block(
                lambda ts: [np.asarray(t) for t in ts],   # sync-ok
                arrs, step=self.stats["decode_steps"])
        else:
            mats = [np.asarray(t) for t in arrs]          # sync-ok
        self.stats["host_sync_count"] += 1
        gs, es = mats[:sync], mats[sync:2 * sync]
        burst_s = time.perf_counter() - t_burst  # clock-ok
        self.stats["decode_s"] += burst_s
        spans = getattr(self.telem, "spans", None)
        if spans is not None:
            spans.record("serve/spec_burst", start_perf=t_burst,
                         end_perf=time.perf_counter(), cat="serve",  # clock-ok
                         steps=int(sync), k=int(k),
                         replica=self.replica)
        t_book = time.perf_counter()  # clock-ok
        active, lengths = A0.copy(), L0.copy()
        occ_burst, emitted = [], 0
        proposed = accepted = 0
        for j in range(sync):
            occ_burst.append(int(active.sum()))
            for b in np.nonzero(active)[0]:
                e_b = int(es[j][b])
                self.batcher.slot_request(int(b)).tokens.extend(
                    int(t) for t in gs[j][b, :e_b])
                emitted += e_b
                proposed += k
                accepted += e_b - 1
            lengths = lengths + es[j]
            active = active & (lengths < self._h_stop)
        self.stats["spec_proposed"] += proposed
        self.stats["spec_accepted"] += accepted
        from ..telemetry.metrics import maybe_inc
        maybe_inc(self.batcher.metrics, "spec_proposed_total", proposed)
        maybe_inc(self.batcher.metrics, "spec_accepted_total", accepted)
        self._h_tokens = mats[-1].copy()
        self._h_lengths = lengths
        self._h_active = active
        now = time.perf_counter() - t0  # clock-ok
        finished = []
        for b in range(self.max_batch):
            req = self.batcher.slot_request(b)
            if req is not None and req.state == DECODE and not active[b]:
                self.batcher.retire(req, now)
                self._h_pages[b] = 0     # slot back to the null page
                self.completed.append(req)
                finished.append(req)
        self.stats["bookkeep_s"] += time.perf_counter() - t_book  # clock-ok
        if self.telem is not None:
            self.telem.step(
                loss=None, tokens=emitted,
                tracker_metrics={"last_step_time_s": burst_s / sync},
                phase="decode",
                active=round(float(np.mean(occ_burst)), 3),
                admitted=self.batcher.admitted_total,
                completed=self.batcher.completed_total,
                kv_pages_in_use=self.pool.allocator.pages_in_use,
                pool_util=round(self.pool.utilization, 4),
                spec_accept_rate=round(accepted / proposed, 4)
                if proposed else None,
                completed_requests=[
                    {"rid": r.rid,
                     "trace_id": r.trace_id,
                     "ttft_ms": round(1e3 * (r.ttft_s or 0.0), 3),
                     "per_token_ms": round(1e3 * (r.per_token_s or 0.0),
                                           3),
                     "tokens": len(r.tokens)} for r in finished])

    # ---- round loop ---------------------------------------------------
    def start(self, t0: float | None = None) -> None:
        """Arm the engine clock and the persistent pump without driving
        the loop.  ``run()`` calls it implicitly; the fleet calls it
        explicitly with a SHARED ``t0`` so every replica's timestamps
        live on one clock, then drives rounds via :meth:`step_round`."""
        if self._t0 is None:
            self._t0 = time.perf_counter() if t0 is None else t0  # clock-ok
        if self._pump is None:
            from ..runtime.pump import StepPump
            self._pump = StepPump(mode="async",
                                  sync_every=self.sync_every,
                                  max_in_flight=self.max_in_flight,
                                  watchdog=self.watchdog)

    def close_pump(self) -> None:
        """Drain and drop the persistent pump (normal shutdown)."""
        if self._pump is not None:
            pump, self._pump = self._pump, None
            pump.close()
            self.stats["host_sync_count"] += pump.host_sync_count

    def abandon_pump(self) -> None:
        """Drop the pump WITHOUT draining — the failover path for a
        dead/wedged replica whose in-flight work will never resolve
        (draining would just re-raise the timeout or block)."""
        self._pump = None

    def step_round(self, now: float) -> list[Request]:
        """One scheduler round at elapsed time ``now``: admit from the
        waiting queue, run up to ``prefill_chunks_per_round`` prefill
        chunks, one decode burst if any slot is active.  Returns the
        requests that finished THIS round.  Faults surface here —
        :class:`~..resilience.elastic.StepTimeoutError` propagates from
        the burst's watchdog-guarded sync points."""
        self.start()
        t0 = self._t0
        done_base = len(self.completed)
        t_admit = time.perf_counter()  # clock-ok
        admitted = self.batcher.admit(now)
        for req in admitted:
            # install the slot's page-table row in the host
            # mirror the decode burst ships (unused entries
            # point at the null page)
            self._h_pages[req.slot] = 0
            self._h_pages[req.slot, :len(req.pages)] = req.pages
            if self.disaggregate:
                n = -(-req.n_prompt // self.page_size)
                pre = self.pool_pre.allocator.alloc(n)
                if pre is None:
                    raise RuntimeError(
                        "prefill pool exhausted — it is sized "
                        "like the decode pool, so this is a "
                        "leak, not load")
                self._pre_pages[req.rid] = pre
        self.stats["admit_s"] += time.perf_counter() - t_admit  # clock-ok
        if self.flash_prefill:
            # batched multi-request prefill: all PREFILL residents
            # advance together, one fixed-shape step per chunk round
            for _ in range(self.prefill_chunks_per_round):
                reqs = sorted(
                    (r for r in self.batcher.slots
                     if r is not None and r.state == PREFILL),
                    key=lambda r: r.t_admit)
                if not reqs:
                    break
                self._prefill_batch_chunk(reqs, t0)
        else:
            for _ in range(self.prefill_chunks_per_round):
                req = self.batcher.next_prefill()
                if req is None:
                    break
                self._prefill_one_chunk(req, t0)
        if self._h_active.any():
            if self.spec_k:
                self._spec_burst(self._pump, t0)
            else:
                self._decode_burst(self._pump, t0)
        self.stats["rounds"] += 1
        self.stats["occupancy_sum"] += int(self._h_active.sum())
        self.stats["peak_pool_util"] = max(
            self.stats["peak_pool_util"], self.pool.utilization)
        if self._warm_sizes is None \
                and self.stats["decode_steps"] > 0:
            self._warm_sizes = self._jit_sizes()
        return self.completed[done_base:]

    def run(self) -> list[Request]:
        def vt(r):
            return r.arrival_s if r.arrival_s is not None else 0.0

        pending = sorted(self._pending, key=vt)
        self._pending = []
        self.start()
        t0 = self._t0
        newly_done_base = len(self.completed)
        try:
            while pending or self.batcher.has_work():
                now = time.perf_counter() - t0  # clock-ok
                while pending and vt(pending[0]) <= now:
                    self.batcher.submit(pending.pop(0), now)
                if not self.batcher.has_work():
                    # idle until the next virtual arrival
                    time.sleep(min(max(vt(pending[0]) - now, 0.0),
                                   0.05))
                    continue
                self.step_round(now)
        finally:
            self.close_pump()
        self.stats["wall_s"] += time.perf_counter() - t0  # clock-ok
        return self.completed[newly_done_base:]

    # ---- failover / hot-swap -----------------------------------------
    def release_all(self) -> list[Request]:
        """Failover teardown: every unfinished request leaves reset for
        replay (see ``scheduler.reset_for_replay``), slots and pages are
        freed, the host mirrors zeroed.  The device pool is NOT touched
        — a dead replica's buffers die with it."""
        orphans = self.batcher.release_all()
        if self.disaggregate:
            for rid in list(self._pre_pages):
                self.pool_pre.allocator.free(self._pre_pages.pop(rid))
        self._h_active[:] = False
        self._h_pages[:] = 0
        return orphans

    def swap_params(self, params) -> None:
        """Install new weights on a DRAINED engine — the fleet's
        hot-swap lands here once the replica has zero requests in
        flight.  Placement mirrors ``__init__`` (tp shard / device
        commit), and the new tree must match the old one's
        shapes/dtypes, so the jitted steps see identical avals and the
        zero-retrace contract survives the swap."""
        if self.batcher.has_work():
            raise RuntimeError(
                f"swap_params with {self.in_flight()} request(s) in "
                f"flight — drain the replica first (the fleet's swap "
                f"path does this at a burst boundary)")
        if self.mesh is not None:
            from ..parallel.tensor import shard_params_tp
            params = shard_params_tp(params, self.mesh, self.tp_axis)
            self._params = self._params_pre = params
        elif self._decode_dev is not None:
            self._params = jax.device_put(params, self._decode_dev)
            self._params_pre = (
                self._params if self._prefill_dev is self._decode_dev
                else jax.device_put(params, self._prefill_dev))
        else:
            self._params = self._params_pre = params

    def _jit_sizes(self) -> dict:
        from ..analysis.recompile import jit_cache_size
        fns = {"decode": self._decode}
        for name, f in (("prefill", self._prefill),
                        ("prefill_batch", self._prefill_batch),
                        ("draft_decode", self._draft_decode),
                        ("verify", self._verify),
                        ("accept", self._accept),
                        ("draft_prefill", self._draft_prefill),
                        ("draft_prefill_batch",
                         self._draft_prefill_batch)):
            if f is not None:
                fns[name] = f
        if self.disaggregate:
            fns["extract"] = self._extract
            fns["inject"] = self._inject
        return {k: jit_cache_size(f) for k, f in fns.items()}

    # ---- reporting ----------------------------------------------------
    def retraces_after_warmup(self) -> int | None:
        """Jit-cache growth since the first round finished — 0 is the
        contract (admit/evict over the whole trace never retraces);
        None before any decode ran or when the cache is unreadable."""
        if self._warm_sizes is None:
            return None
        cur = self._jit_sizes()
        known = [(w, cur[k]) for k, w in self._warm_sizes.items()
                 if w is not None and cur.get(k) is not None]
        if not known:
            return None
        return sum(c - w for w, c in known)

    def slo_report(self) -> dict:
        """TTFT / per-token percentiles + throughput + pool/scheduler
        health for the finished requests — the dict ``serve_bench``
        files under summary.json's ``serving`` key."""
        done = [r for r in self.completed if r.t_done is not None]
        ttft = np.array([r.ttft_s for r in done
                         if r.ttft_s is not None]) * 1e3
        ptl = np.array([r.per_token_s for r in done
                        if r.per_token_s is not None]) * 1e3
        pct = lambda a, q: (round(float(np.percentile(a, q)), 3)
                            if a.size else None)
        toks = int(sum(len(r.tokens) for r in done))
        wall = self.stats["wall_s"] or 1e-9
        ndev = len(jax.devices()) if self.mesh is None \
            else int(self.mesh.devices.size)
        steps = max(self.stats["decode_steps"], 1)
        # tokens emitted by decode steps (first token of each completed
        # request comes from prefill) — the steps-per-token the
        # speculative leg is judged on
        dec_toks = max(toks - len(done), 1)
        rep = {
            "requests": self.batcher.admitted_total,
            "completed": len(done),
            "ttft_ms": {"p50": pct(ttft, 50), "p99": pct(ttft, 99),
                        "mean": (round(float(ttft.mean()), 3)
                                 if ttft.size else None)},
            "per_token_ms": {"p50": pct(ptl, 50), "p99": pct(ptl, 99)},
            "tokens_total": toks,
            "tokens_per_s": round(toks / wall, 2),
            "tokens_per_s_per_device": round(toks / wall / ndev, 2),
            "devices": ndev,
            "pool": {"n_pages": self.n_pages,
                     "page_size": self.page_size,
                     "peak_util": round(self.stats["peak_pool_util"], 4)},
            "scheduler": {
                "rounds": self.stats["rounds"],
                "decode_steps": self.stats["decode_steps"],
                "prefill_chunks": self.stats["prefill_chunks"],
                "admit_ms_total": round(1e3 * self.stats["admit_s"], 3),
                "bookkeep_ms_total": round(
                    1e3 * self.stats["bookkeep_s"], 3),
                # measured per-phase totals: divide by prefill_chunks /
                # decode_steps for the per-burst priors the simulator's
                # cost model calibrates from
                "prefill_ms_total": round(
                    1e3 * self.stats["prefill_s"], 3),
                "decode_ms_total": round(
                    1e3 * self.stats["decode_s"], 3),
                "mean_occupancy": round(
                    self.stats["occupancy_sum"]
                    / max(self.stats["rounds"], 1), 3),
                "host_syncs": self.stats["host_sync_count"],
                "decode_steps_per_token": round(
                    self.stats["decode_steps"] / dec_toks, 4),
            },
            "disaggregated": self.disaggregate,
            "kv_quant": self.kv_quant,
            "flash_prefill": self.flash_prefill,
            "recompiles_after_warmup": self.retraces_after_warmup(),
        }
        if self.prefix_cache is not None:
            rep["prefix_cache"] = self.prefix_cache.stats()
        if self.spec_k:
            prop = self.stats["spec_proposed"]
            rep["speculative"] = {
                "k": self.spec_k,
                "draft_layers": self.draft_cfg.num_hidden_layers,
                "draft_steps": self.stats["draft_steps"],
                "proposed": prop,
                "accepted": self.stats["spec_accepted"],
                "acceptance_rate": round(
                    self.stats["spec_accepted"] / prop, 4) if prop
                else None,
            }
        return rep


def serve(params, cfg, prompts, *, max_new_tokens: int = 16,
          **engine_kwargs) -> list[np.ndarray]:
    """One-call convenience: build an engine, run every prompt to
    completion, return each continuation as an int32 array (in prompt
    order)."""
    eng = ServingEngine(params, cfg, **engine_kwargs)
    reqs = [eng.submit(p, max_new_tokens=max_new_tokens)
            for p in prompts]
    eng.run()
    return [np.asarray(r.tokens, np.int32) for r in reqs]
