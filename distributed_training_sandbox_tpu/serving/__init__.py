"""Serving runtime: continuous-batching inference over a sharded paged
KV cache, with latency-SLO telemetry.

The inference counterpart of the training ``runtime``: ``engine`` drives
fixed-shape jitted decode steps over ``kv_pool``'s page blocks under
``scheduler``'s WAITING→PREFILL→DECODE→DONE state machine, and
``accounting`` holds the byte formulas shared with the decode roofline
bench plus the pool capacity planner.  ``fleet`` + ``router`` stack N
engine replicas behind SLO-driven admission control with failover
(deterministic request replay on survivors) and zero-drop weight
hot-swap.  Entry points: :class:`ServingEngine` / :func:`serve` /
:class:`Fleet` here, ``scripts/serve_bench.py`` for the
Poisson-traffic SLO report (``--replicas N`` for the fleet).
"""

from .accounting import (kv_bytes_per_step, page_bytes,
                         pool_capacity_pages, serve_waterline_gb,
                         weight_read_bytes)
from .engine import (ServingEngine, make_draft_params,
                     make_serve_decode_step,
                     make_serve_prefill_batch_step,
                     make_serve_prefill_step,
                     make_serve_spec_verify_step, serve)
from .fleet import Fleet, Replica
from .kv_pool import (PageAllocator, PagedKVPool, PoolBuffers,
                      RadixPrefixCache)
from .router import AdmissionController, Rejection, Router
from .scheduler import ContinuousBatcher, Request, reset_for_replay
from .traces import (TraceRequest, build_fleet_trace, build_tenant_trace,
                     build_trace, trace_digest)

__all__ = [
    "ServingEngine", "serve", "make_serve_decode_step",
    "make_serve_prefill_step", "make_serve_prefill_batch_step",
    "make_serve_spec_verify_step", "make_draft_params",
    "Fleet", "Replica",
    "AdmissionController", "Rejection", "Router",
    "PagedKVPool", "PageAllocator", "PoolBuffers", "RadixPrefixCache",
    "ContinuousBatcher", "Request", "reset_for_replay",
    "kv_bytes_per_step", "weight_read_bytes", "page_bytes",
    "serve_waterline_gb", "pool_capacity_pages",
    "TraceRequest", "build_trace", "build_tenant_trace",
    "build_fleet_trace", "trace_digest",
]
