"""Serving runtime: continuous-batching inference over a sharded paged
KV cache, with latency-SLO telemetry.

The inference counterpart of the training ``runtime``: ``engine`` drives
fixed-shape jitted decode steps over ``kv_pool``'s page blocks under
``scheduler``'s WAITING→PREFILL→DECODE→DONE state machine, and
``accounting`` holds the byte formulas shared with the decode roofline
bench plus the pool capacity planner.  Entry points:
:class:`ServingEngine` / :func:`serve` here, ``scripts/serve_bench.py``
for the Poisson-traffic SLO report.
"""

from .accounting import (kv_bytes_per_step, page_bytes,
                         pool_capacity_pages, serve_waterline_gb,
                         weight_read_bytes)
from .engine import (ServingEngine, make_serve_decode_step,
                     make_serve_prefill_step, serve)
from .kv_pool import PageAllocator, PagedKVPool, PoolBuffers
from .scheduler import ContinuousBatcher, Request

__all__ = [
    "ServingEngine", "serve", "make_serve_decode_step",
    "make_serve_prefill_step",
    "PagedKVPool", "PageAllocator", "PoolBuffers",
    "ContinuousBatcher", "Request",
    "kv_bytes_per_step", "weight_read_bytes", "page_bytes",
    "serve_waterline_gb", "pool_capacity_pages",
]
