"""Serving fleet: N engine replicas behind the router, with failover,
deadline shedding, and zero-drop weight hot-swap.

The layer that turns PR 8's single continuous-batching engine into a
service that survives the scenarios ROADMAP item 3 names:

**Failover with deterministic replay.**  Each replica is a
:class:`~.engine.ServingEngine` committed to its own device slice
(``device=`` — the disaggregation ``device_put`` machinery, whole
engine on one device), running its decode bursts under a
:class:`~..resilience.elastic.Watchdog` and beating a
:class:`~..resilience.elastic.Heartbeat`.  A replica death —
``kill_replica`` raising :class:`~..resilience.elastic.WorkerLost`, or
a wedged burst the watchdog converts to
:class:`~..resilience.elastic.StepTimeoutError` — marks it dead, frees
its batcher/pool bookkeeping, and re-enqueues its unfinished requests
at the router's queue head, RESET for replay.  Greedy decode is a pure
function of (params, prompt), and every engine contracts attention
over the same fixed pool view, so a replayed request's final token
stream is bitwise-identical to an undisturbed run — the PR 8 parity
law extended across failover.  Partial progress is discarded, not
migrated: the dead replica's KV pages died with it, and re-decoding a
handful of tokens is cheaper than being wrong.

**SLO-driven admission.**  Every ``submit`` runs through
:class:`~.router.AdmissionController` on the trace's virtual clock —
bounded queue, deadline shedding from modeled TTFT, structured
:class:`~.router.Rejection` records.  Shed ≠ dropped: a request is
*dropped* only if it was admitted and never completed, and the fleet's
invariant is that number is ZERO through kills, hangs, and swaps.

**Zero-drop hot-swap.**  :meth:`Fleet.swap_weights` (or
``schedule_swap`` mid-traffic) restores new params ONCE through the
``resilience.state`` reshard path — fingerprint-checked
``Checkpointer.restore_latest``, torn-newest-step fallback — then
drains one replica at a time at burst boundaries: mark it ``draining``
(router stops dispatching to it), let its resident requests finish,
``swap_params`` at zero in-flight, return it live, move to the next.
Traffic keeps flowing through the other replicas the whole time.  A
torn checkpoint (the ``corrupt_swap`` fault tears it deterministically)
aborts the swap with a readable warning and the fleet keeps serving on
the OLD weights — a bad artifact must never take the service down.
"""

from __future__ import annotations

import sys
import time

import jax
import numpy as np

from ..resilience.elastic import (Heartbeat, StepTimeoutError, Watchdog,
                                  WorkerLost)
from ..resilience.faults import FaultInjector, FaultSpec, parse_fault_spec
from .engine import ServingEngine
from .router import AdmissionController, Rejection, Router
from .scheduler import Request

__all__ = ["Fleet", "Replica"]


class _ReplicaTelem:
    """Thin TelemetryRun facade: every step event a replica's engine
    emits carries its ``replica`` index, so one steps.jsonl interleaves
    all replicas' prefill/decode events distinguishably."""

    def __init__(self, telem, idx: int):
        self._telem = telem
        self.replica = int(idx)

    @property
    def spans(self):
        return getattr(self._telem, "spans", None)

    @property
    def metrics(self):
        return getattr(self._telem, "metrics", None)

    def step(self, **kw):
        kw.setdefault("replica", self.replica)
        return self._telem.step(**kw)

    def attach_step_hlo(self, jitted, *args, **kw):
        return self._telem.attach_step_hlo(jitted, *args, **kw)


class Replica:
    """One engine + its liveness machinery.  ``state``: ``live`` (takes
    traffic), ``draining`` (finishes residents, router skips it — the
    hot-swap window), ``dead`` (failed over, never touched again)."""

    def __init__(self, idx: int, engine: ServingEngine,
                 watchdog: Watchdog | None,
                 heartbeat: Heartbeat | None):
        self.idx = int(idx)
        self.engine = engine
        self.watchdog = watchdog
        self.heartbeat = heartbeat
        self.state = "live"
        self.bursts = 0          # rounds-with-work this replica ran
        self.death: str | None = None


class Fleet:
    """N replicas + router + fault plumbing (module docstring).

    ``replicas`` device slices are carved from ``jax.devices()`` — one
    committed device per replica (slice width ``n_dev // replicas``;
    intra-replica sharding composes later via ROADMAP item 2).
    ``fault``: a spec string or :class:`FaultSpec` for the serving
    kinds (``kill_replica@N:k`` / ``hang_decode@N:k`` /
    ``slow_replica@N:ms`` / ``corrupt_swap``).  ``deadline_s`` is the
    default per-request deadline ``submit`` applies when the caller
    gives none.  Engine kwargs (``max_batch``, ``page_size``,
    ``max_seq_len``, ``sync_every``, ...) pass through to every
    replica."""

    def __init__(self, params, cfg, *, replicas: int = 2,
                 watchdog_timeout_s: float = 5.0,
                 fault: FaultSpec | str | None = None,
                 heartbeat_dir=None, telem=None,
                 max_queue: int = 8, burst_s_prior: float = 0.05,
                 calibrate_admission: bool = True,
                 deadline_s: float | None = None,
                 **engine_kwargs):
        devs = jax.devices()
        n = int(replicas)
        if n < 1:
            raise ValueError(f"need >= 1 replica, got {n}")
        if len(devs) < n:
            raise ValueError(f"{n} replicas need >= {n} devices, have "
                             f"{len(devs)}")
        if isinstance(fault, str):
            fault = parse_fault_spec(fault)
        self.injector = FaultInjector(fault)
        self.telem = telem
        self.deadline_s = deadline_s
        self._params_host = params   # uncommitted tree: restore `like`
        self.cfg = cfg

        stride = len(devs) // n
        self.replicas: list[Replica] = []
        for i in range(n):
            wd = (Watchdog(watchdog_timeout_s)
                  if watchdog_timeout_s and watchdog_timeout_s > 0
                  else None)
            hb = (Heartbeat(heartbeat_dir, i)
                  if heartbeat_dir is not None else None)
            eng = ServingEngine(
                params, cfg, device=devs[i * stride], watchdog=wd,
                telem=_ReplicaTelem(telem, i) if telem is not None
                else None,
                **engine_kwargs)
            eng.replica = i
            self.replicas.append(Replica(i, eng, wd, hb))

        eng0 = self.replicas[0].engine
        self.view_capacity = eng0.view_capacity
        self.admission = AdmissionController(
            n * eng0.max_batch, max_queue=max_queue,
            burst_s=burst_s_prior, steps_per_burst=eng0.sync_every,
            calibrate=calibrate_admission)
        self.router = Router(self.admission)
        self.router.metrics = getattr(telem, "metrics", None)

        self._pending: list[Request] = []
        self._rid = 0
        self.completed: list[Request] = []
        self.submitted: list[Request] = []
        self.events: list[dict] = []
        self._swap: dict | None = None
        self._t0: float | None = None

    # ---- intake -------------------------------------------------------
    def submit(self, prompt, max_new_tokens: int,
               arrival_s: float | None = None,
               deadline_s: float | None = None
               ) -> Request | Rejection:
        """Admission-controlled submit: returns the Request when
        admitted, the structured :class:`Rejection` when shed.  Call in
        virtual-arrival order — the admission model is sequential by
        construction, which is what makes the shed set reproducible."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1 or max_new_tokens < 1:
            raise ValueError("need >= 1 prompt token and >= 1 new token")
        if prompt.size + max_new_tokens > self.view_capacity:
            raise ValueError(
                f"prompt {prompt.size} + new {max_new_tokens} exceeds "
                f"the fleet's view capacity {self.view_capacity} "
                f"(raise max_seq_len)")
        req = Request(rid=self._rid, prompt=prompt,
                      max_new_tokens=int(max_new_tokens),
                      arrival_s=(None if arrival_s is None
                                 else float(arrival_s)))
        self._rid += 1
        if deadline_s is None:
            deadline_s = self.deadline_s
        rej = self.router.submit(req, deadline_s)
        if rej is not None:
            return rej
        self._pending.append(req)
        self.submitted.append(req)
        return req

    # ---- hot-swap -----------------------------------------------------
    def schedule_swap(self, ckpt_dir, *, after_completed: int = 0,
                      fingerprint: dict | None = None) -> None:
        """Arm a weight hot-swap: once ``after_completed`` requests have
        finished, restore the newest intact step of ``ckpt_dir`` through
        the resilience reshard path and roll it across the replicas one
        drain at a time.  ``swap_weights`` is the immediate form."""
        self._swap = {"dir": ckpt_dir, "after": int(after_completed),
                      "fingerprint": fingerprint, "state": "armed",
                      "new_params": None, "queue": []}

    def swap_weights(self, ckpt_dir, *,
                     fingerprint: dict | None = None) -> None:
        self.schedule_swap(ckpt_dir, after_completed=0,
                           fingerprint=fingerprint)

    def _event(self, now: float, event: str, **kw) -> None:
        ev = {"t_s": round(now, 4), "event": event, **kw}
        self.events.append(ev)

    def _restore_swap_params(self, now: float):
        """One restore for the whole fleet, through Checkpointer's
        fingerprint check + torn-step fallback.  Returns the new param
        tree, or None when the checkpoint is unusable (fleet keeps the
        old weights — the corrupt_swap acceptance path)."""
        from ..resilience.state import (CheckpointCorruptError,
                                        Checkpointer, RunState)
        sw = self._swap
        if self.injector.wants_corrupt_swap():
            from ..resilience.faults import corrupt_checkpoint
            corrupt_checkpoint(sw["dir"])
            self._event(now, "swap_fault_injected", kind="corrupt_swap")
        ckpt = Checkpointer(sw["dir"],
                            fingerprint=sw["fingerprint"] or {})
        try:
            state = ckpt.restore_latest(
                RunState(params=self._params_host))
        except CheckpointCorruptError as e:
            print(f"[fleet] WARNING: weight swap from {sw['dir']} "
                  f"aborted — every step is torn or corrupt ({e}); "
                  f"fleet keeps serving on the previous weights",
                  file=sys.stderr, flush=True)
            self._event(now, "swap_failed", reason="corrupt_checkpoint")
            return None
        finally:
            ckpt.close()
        if state is None:
            print(f"[fleet] WARNING: weight swap from {sw['dir']} "
                  f"aborted — no checkpoint steps found; fleet keeps "
                  f"serving on the previous weights",
                  file=sys.stderr, flush=True)
            self._event(now, "swap_failed", reason="no_steps")
            return None
        return state.params

    def _maybe_swap(self, now: float, force: bool = False) -> None:
        sw = self._swap
        if sw is None:
            return
        if sw["state"] == "armed":
            # ``force``: the trace drained before the trigger count was
            # reached — swap now rather than arm forever
            if len(self.completed) < sw["after"] and not force:
                return
            new = self._restore_swap_params(now)
            if new is None:
                self._swap = None
                return
            sw["new_params"] = new
            sw["queue"] = [r for r in self.replicas
                           if r.state != "dead"]
            sw["state"] = "draining"
            self._event(now, "swap_started",
                        replicas=[r.idx for r in sw["queue"]])
        if sw["state"] == "draining":
            while sw["queue"]:
                rep = sw["queue"][0]
                if rep.state == "dead":
                    sw["queue"].pop(0)
                    continue
                rep.state = "draining"
                if rep.engine.in_flight() > 0:
                    return        # let its residents finish first
                rep.engine.swap_params(sw["new_params"])
                rep.state = "live"
                sw["queue"].pop(0)
                self._event(now, "swap_replica", replica=rep.idx)
            self._event(now, "swap_complete")
            self._swap = None

    # ---- failover -----------------------------------------------------
    def _on_replica_death(self, rep: Replica, exc: BaseException,
                          now: float) -> None:
        rep.state = "dead"
        rep.death = type(exc).__name__
        rep.engine.abandon_pump()
        if rep.heartbeat is not None:
            rep.heartbeat.mark_dead(f"{type(exc).__name__}@burst"
                                    f"{rep.bursts}")
        orphans = rep.engine.release_all()
        self.router.requeue_front(orphans)
        survivors = [r.idx for r in self.replicas if r.state == "live"]
        print(f"[fleet] WARNING: replica {rep.idx} died "
              f"({type(exc).__name__} at burst {rep.bursts}) — "
              f"re-enqueued {len(orphans)} in-flight request(s) onto "
              f"survivors {survivors}", file=sys.stderr, flush=True)
        self._event(now, "replica_dead", replica=rep.idx,
                    trigger=type(exc).__name__, burst=rep.bursts,
                    requeued=len(orphans))
        from ..telemetry.metrics import maybe_inc
        maybe_inc(getattr(self.telem, "metrics", None),
                  "fleet_replica_deaths_total", replica=rep.idx)
        if not survivors:
            raise RuntimeError(
                f"all {len(self.replicas)} replicas dead — last "
                f"failure: {type(exc).__name__} on replica {rep.idx}")

    # ---- the drive loop ----------------------------------------------
    def _has_work(self) -> bool:
        return bool(self.router.queue) or any(
            r.state != "dead" and r.engine.in_flight() > 0
            for r in self.replicas)

    def run(self) -> list[Request]:
        """Drive every admitted request to completion (arrivals on the
        shared virtual clock), applying faults, failover and any armed
        swap along the way.  Returns the requests completed by this
        call; the zero-drop invariant — every admitted request
        completes — is the caller-visible contract."""
        def vt(r: Request) -> float:
            return r.arrival_s if r.arrival_s is not None else 0.0

        pending = sorted(self._pending, key=vt)
        self._pending = []
        if self._t0 is None:
            self._t0 = time.perf_counter()  # clock-ok
        t0 = self._t0
        for rep in self.replicas:
            if rep.state != "dead":
                rep.engine.start(t0)
        done_base = len(self.completed)
        try:
            while pending or self._has_work() or (
                    self._swap is not None):
                now = time.perf_counter() - t0  # clock-ok
                while pending and vt(pending[0]) <= now:
                    req = pending.pop(0)
                    self.router.enqueue(req)
                self._maybe_swap(
                    now, force=not pending and not self._has_work())
                self.router.dispatch(self.replicas, now)
                progressed = False
                for rep in self.replicas:
                    if rep.state == "dead" \
                            or rep.engine.in_flight() == 0:
                        continue
                    try:
                        self.injector.check_serving(
                            rep.idx, rep.bursts, rep.watchdog)
                        t_b = time.perf_counter()  # clock-ok
                        done = rep.engine.step_round(now)
                        self.admission.observe_burst(
                            time.perf_counter() - t_b)  # clock-ok
                        if rep.engine.prefix_cache is not None:
                            # cache-hit rate feeds the modeled-TTFT
                            # prior: hits skip prefill chunks, so the
                            # admission model discounts the service
                            # round for later offers
                            self.admission.note_cache_hit_rate(
                                rep.engine.prefix_cache.hit_rate)
                        rep.bursts += 1
                        if rep.heartbeat is not None:
                            rep.heartbeat.beat(rep.bursts)
                            from ..telemetry.metrics import maybe_inc
                            maybe_inc(
                                getattr(self.telem, "metrics", None),
                                "heartbeat_beats_total",
                                replica=rep.idx)
                        self.completed.extend(done)
                        progressed = True
                    except (WorkerLost, StepTimeoutError) as e:
                        self._on_replica_death(rep, e, now)
                if not progressed and not self.router.queue \
                        and pending:
                    # idle until the next virtual arrival
                    time.sleep(min(max(vt(pending[0]) - now, 0.0),
                                   0.05))
                if self._swap is None and not pending \
                        and not self._has_work():
                    break
        finally:
            for rep in self.replicas:
                if rep.state != "dead":
                    rep.engine.close_pump()
        wall = time.perf_counter() - t0  # clock-ok
        for rep in self.replicas:
            rep.engine.stats["wall_s"] = wall
        return self.completed[done_base:]

    # ---- reporting ----------------------------------------------------
    def dropped(self) -> list[int]:
        """rids that were ADMITTED but never completed — the zero-drop
        invariant says this is empty after ``run()``.  Shed requests
        are rejections, not drops."""
        done = {r.rid for r in self.completed}
        return [r.rid for r in self.submitted if r.rid not in done]

    def retraces_after_warmup(self) -> int | None:
        vals = [r.engine.retraces_after_warmup()
                for r in self.replicas if r.state != "dead"]
        known = [v for v in vals if v is not None]
        return sum(known) if known else None

    def slo_report(self) -> dict:
        """Fleet-level SLO aggregate + per-replica blocks + the event
        timeline — what ``serve_bench --replicas N`` files under
        summary.json's ``fleet`` key."""
        done = [r for r in self.completed if r.t_done is not None]
        ttft = np.array([r.ttft_s for r in done
                         if r.ttft_s is not None]) * 1e3
        ptl = np.array([r.per_token_s for r in done
                        if r.per_token_s is not None]) * 1e3
        pct = lambda a, q: (round(float(np.percentile(a, q)), 3)
                            if a.size else None)
        per_replica = []
        for rep in self.replicas:
            slo = rep.engine.slo_report()
            per_replica.append({
                "replica": rep.idx, "state": rep.state,
                "death": rep.death, "bursts": rep.bursts,
                "requests": slo["requests"],
                "completed": slo["completed"],
                "ttft_ms": slo["ttft_ms"],
                "per_token_ms": slo["per_token_ms"],
                "tokens_per_s": slo["tokens_per_s"],
                "pool": slo["pool"],
                # per-phase measured totals ride along so an archived
                # fleet run can calibrate the simulator's cost model
                "scheduler": slo["scheduler"],
                "recompiles_after_warmup":
                    slo["recompiles_after_warmup"],
            })
        return {
            "replicas": len(self.replicas),
            "live": sum(r.state == "live" for r in self.replicas),
            "submitted": len(self.submitted),
            "shed": len(self.router.rejections),
            "completed": len(done),
            "dropped": len(self.dropped()),
            "ttft_ms": {"p50": pct(ttft, 50), "p99": pct(ttft, 99)},
            "per_token_ms": {"p50": pct(ptl, 50), "p99": pct(ptl, 99)},
            "admission": {
                "offered": self.admission.offered_total,
                "shed": self.admission.shed_total,
                "max_queue": self.admission.max_queue,
                "burst_s_prior": round(self.admission.burst_s, 5),
                "total_slots": self.admission.total_slots,
            },
            "rejections": [r.as_dict()
                           for r in self.router.rejections],
            "replica_slo": per_replica,
            "events": list(self.events),
            "recompiles_after_warmup": self.retraces_after_warmup(),
        }
