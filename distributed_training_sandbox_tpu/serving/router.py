"""SLO-driven admission control + load-aware dispatch for the fleet.

Two pieces, both pure host bookkeeping:

:class:`AdmissionController` — deadline-aware load shedding decided AT
SUBMIT TIME on the trace's *virtual* clock.  The model is deliberately
the simple one the ISSUE names: modeled TTFT = (modeled queue wait + 1
service round) where the wait is ``queue_depth_beyond_capacity ×
per-burst latency``.  A request is rejected with a structured
:class:`Rejection` when the bounded queue is full (``queue_full``) or
when the modeled TTFT exceeds its deadline (``deadline``) — instead of
admitting it into a tail blowup it can only lose.  Because every
decision is a pure function of (virtual arrival order, arrival times,
max_new, the burst-latency prior), the shed set is REPRODUCIBLE from
the traffic seed — the determinism the overload test pins.  Measured
per-burst latency feeds back via :meth:`observe_burst` (EWMA), which
only affects offers made *after* the observation; open-loop drivers
that submit the whole trace up front therefore shed identically on
every run.

:class:`Router` — one fleet-global FCFS dispatch queue in front of the
replicas (head-of-line blocking stays HERE, not stacked inside every
engine), least-loaded dispatch among live replicas that can actually
seat the request (free slot + full page grant), and
:meth:`requeue_front` for failover: a dead replica's replayed requests
go back to the queue HEAD in their original order, so survivors pick
them up before newer traffic.
"""

from __future__ import annotations

import heapq
from collections import deque
from dataclasses import dataclass

from .scheduler import Request

__all__ = ["AdmissionController", "Rejection", "Router"]


@dataclass(frozen=True)
class Rejection:
    """Structured load-shed record — what the client gets instead of a
    silent tail blowup, and what the fleet report renders."""
    rid: int
    reason: str                    # "queue_full" | "deadline"
    t_s: float                     # virtual arrival of the decision
    modeled_ttft_ms: float
    deadline_ms: float | None
    queue_depth: int

    def as_dict(self) -> dict:
        return {"rid": self.rid, "reason": self.reason,
                "t_s": round(self.t_s, 4),
                "modeled_ttft_ms": round(self.modeled_ttft_ms, 3),
                "deadline_ms": (None if self.deadline_ms is None
                                else round(self.deadline_ms, 3)),
                "queue_depth": self.queue_depth}


class AdmissionController:
    """Virtual-time occupancy model + shed policy (module docstring).

    ``total_slots``: fleet-wide concurrent capacity (replicas ×
    max_batch) — arrivals beyond it are modeled as waiting.
    ``max_queue``: bound on the modeled waiting line; deeper arrivals
    are shed ``queue_full``.  ``burst_s``: the per-burst latency prior;
    ``steps_per_burst``: tokens a request earns per burst (the engine's
    ``sync_every``), used to model service time.  ``calibrate=False``
    freezes the prior (fully deterministic even for closed-loop
    drivers)."""

    def __init__(self, total_slots: int, *, max_queue: int = 8,
                 burst_s: float = 0.05, steps_per_burst: int = 4,
                 calibrate: bool = True):
        self.total_slots = max(int(total_slots), 1)
        self.max_queue = max(int(max_queue), 0)
        self.burst_s = float(burst_s)
        self.steps_per_burst = max(int(steps_per_burst), 1)
        self.calibrate = bool(calibrate)
        #: prefix-cache hit-rate prior (fraction of prompt pages served
        #: from cache), EWMA-fed by the engines via
        #: :meth:`note_cache_hit_rate`; 0 = no cache = the old model
        self.cache_hit_rate = 0.0
        #: heap of modeled completion times of admitted requests
        self._backlog: list[float] = []
        self.offered_total = 0
        self.shed_total = 0

    # ---- the submit-time decision ------------------------------------
    def offer(self, arrival_s: float, max_new_tokens: int,
              deadline_s: float | None = None
              ) -> tuple[str | None, float, int]:
        """Decide one arrival: returns ``(reason, modeled_ttft_s,
        queue_depth)`` with reason None on admit.  Admitting pushes the
        request's modeled completion into the backlog, so later offers
        see it occupying capacity until then."""
        self.offered_total += 1
        while self._backlog and self._backlog[0] <= arrival_s:
            heapq.heappop(self._backlog)
        depth = len(self._backlog)
        waiting = max(0, depth - self.total_slots)
        # the service round (the +1) is mostly prefill for a fresh
        # arrival; a prefix-cache hit skips the cached pages' chunks, so
        # the hit-rate prior discounts that term (floored — the last
        # prompt page is always prefilled for the first-token logits)
        service_round = max(1.0 - self.cache_hit_rate, 0.25)
        modeled_ttft = (waiting + service_round) * self.burst_s
        if waiting >= self.max_queue:
            self.shed_total += 1
            return "queue_full", modeled_ttft, depth
        if deadline_s is not None and modeled_ttft > deadline_s:
            self.shed_total += 1
            return "deadline", modeled_ttft, depth
        service = self.burst_s * (
            -(-int(max_new_tokens) // self.steps_per_burst))
        heapq.heappush(self._backlog,
                       arrival_s + modeled_ttft + service)
        return None, modeled_ttft, depth

    def observe_burst(self, burst_s: float) -> None:
        """EWMA-calibrate the prior from a measured burst.  Only offers
        made AFTER this call see the update — submit-up-front drivers
        keep a bit-stable shed set."""
        if self.calibrate and burst_s > 0:
            self.burst_s = 0.8 * self.burst_s + 0.2 * float(burst_s)

    def note_cache_hit_rate(self, rate: float) -> None:
        """Prefix-cache hit-rate feedback from an engine (its
        ``RadixPrefixCache.hit_rate``) — discounts the modeled-TTFT
        service round for offers made AFTER this call.  Same EWMA
        discipline and determinism caveat as :meth:`observe_burst`;
        gated on ``calibrate`` for the same bit-stable-shed reason."""
        if self.calibrate and 0.0 <= rate <= 1.0:
            self.cache_hit_rate = (0.8 * self.cache_hit_rate
                                   + 0.2 * float(rate))


class Router:
    """Fleet-global dispatch queue + structured rejections."""

    def __init__(self, admission: AdmissionController):
        self.admission = admission
        self.queue: deque[Request] = deque()
        self.rejections: list[Rejection] = []
        self.dispatched_total = 0
        # live MetricsRegistry, late-assigned by the fleet
        # (``router.metrics = telem.metrics``); feeds are None-tolerant
        self.metrics = None

    def submit(self, req: Request,
               deadline_s: float | None = None) -> Rejection | None:
        """Admission decision for one request at its (virtual) arrival.
        Returns the Rejection when shed (the request never enters the
        system), None when admitted — the caller then feeds it to
        :meth:`enqueue` once its arrival time is due.

        Mints the request's distributed ``trace_id`` here — submit is
        the single front door, so every attempt at serving this request
        (admission decision, prefill chunks, decode bursts, a failover
        replay on a different replica) shares the one id."""
        from ..telemetry.metrics import maybe_inc
        if req.trace_id is None:
            req.trace_id = f"tr-{req.rid:06d}"
        maybe_inc(self.metrics, "router_offered_total")
        arrival = req.arrival_s if req.arrival_s is not None else 0.0
        reason, ttft_s, depth = self.admission.offer(
            arrival, req.max_new_tokens, deadline_s)
        if reason is None:
            return None
        maybe_inc(self.metrics, "router_shed_total", reason=reason)
        rej = Rejection(
            rid=req.rid, reason=reason, t_s=arrival,
            modeled_ttft_ms=1e3 * ttft_s,
            deadline_ms=None if deadline_s is None else 1e3 * deadline_s,
            queue_depth=depth)
        self.rejections.append(rej)
        return rej

    def enqueue(self, req: Request) -> None:
        self.queue.append(req)

    def requeue_front(self, reqs: list[Request]) -> None:
        """Failover: replayed requests re-enter at the queue HEAD in
        their original order — survivors serve them before new work."""
        self.queue.extendleft(reversed(reqs))

    def dispatch(self, replicas, now: float) -> list[tuple[object, Request]]:
        """Drain the queue head onto the least-loaded LIVE replica that
        can seat it (free slot + full page grant).  FCFS: a head that
        no replica can seat blocks the queue — deliberate, matching the
        engines' own no-starvation policy."""
        sent = []
        while self.queue:
            req = self.queue[0]
            cands = [r for r in replicas
                     if r.state == "live" and r.engine.can_accept(req)]
            if not cands:
                break
            rep = min(cands,
                      key=lambda r: (r.engine.in_flight(), r.idx))
            self.queue.popleft()
            rep.engine.enqueue(req, now)
            self.dispatched_total += 1
            from ..telemetry.metrics import maybe_inc
            maybe_inc(self.metrics, "router_dispatched_total",
                      replica=rep.idx)
            sent.append((rep, req))
        return sent
