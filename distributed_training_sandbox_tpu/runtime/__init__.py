"""The async step pump — the shared hot-loop machinery every strategy
driver runs through.

The reference drivers (and this repo's, before this package) ran a
strictly synchronous loop: host batch prep, an unsharded ``jnp.asarray``
transfer, dispatch, then ``jax.block_until_ready(loss)`` + ``float(loss)``
on every step — the TPU idles during data movement and the host idles
during compute.  This package is the overlap layer:

  * :class:`DevicePrefetcher` (``prefetch.py``) — the host batch pipeline
    in a background thread, double-buffering batches onto the mesh via
    sharding-aware ``jax.device_put``;
  * :class:`StepPump` (``pump.py``) — bounded in-flight dispatch with a
    declared sync policy: losses retire as device arrays and the host
    only blocks at profile-schedule boundaries, every ``--sync-every``
    steps, and at loop exit.

``scripts/lint_sharding.py`` enforces the migration: a per-step
``jax.block_until_ready``/``float(loss)`` in a driver hot loop is now a
lint error unless routed through the pump (or marked ``# sync-ok``).
"""

from .prefetch import DevicePrefetcher, sharded_put  # noqa: F401
from .pump import StepPump  # noqa: F401
