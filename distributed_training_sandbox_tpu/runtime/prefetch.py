"""Sharding-aware background batch prefetch.

The host half of the overlap story: while the device executes step ``i``,
a producer thread is already collating batch ``i+1`` and staging it onto
the mesh with ``jax.device_put`` under the batch's *training* sharding
(e.g. ``P("dp")``), so the transfer happens concurrently with compute and
the array arrives committed — no replicated/uncommitted ``jnp.asarray``
put in the hot loop, no device-side reshard on first use.

Semantics:
  * order-preserving: the prefetcher yields exactly the wrapped
    iterator's sequence (same seed ⇒ bitwise-identical batches vs. eager
    iteration — tested);
  * bounded: at most ``depth`` staged batches exist at once (the queue
    blocks the producer), default 2 = classic double buffering;
  * crash-clean: ``close()`` (also run by ``__exit__`` on loop
    exceptions) stops the producer, drains the queue and joins the
    thread — no leaked thread, no orphaned device buffers;
  * error-transparent: an exception in the host pipeline re-raises at
    the consumer's next ``next()``.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterable, Iterator


def sharded_put(batch, mesh, spec):
    """Stage every array leaf of ``batch`` onto ``mesh`` under
    ``NamedSharding(mesh, spec)``.  ``spec`` is one ``PartitionSpec``
    applied to all leaves (the batch-dim sharding every strategy here
    uses), or a pytree of specs matching ``batch``'s structure.

    When the mesh spans processes (real ``--distributed`` launches) the
    put routes through :func:`~..utils.mesh.process_local_put`, which
    slices this process's shard out of the host batch and builds the
    global array via ``jax.make_array_from_process_local_data`` — each
    worker only ever transfers its own rows.  Single-process this is the
    classic committed ``jax.device_put``."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from ..utils.mesh import process_local_put

    if mesh is None:
        return batch

    def put(a, s):
        sh = NamedSharding(mesh, s or PartitionSpec())
        if not sh.is_fully_addressable:
            return process_local_put(a, mesh, s or PartitionSpec())
        return jax.device_put(a, sh)

    if isinstance(spec, PartitionSpec) or spec is None:
        return jax.tree.map(lambda a: put(a, spec), batch)
    return jax.tree.map(put, batch, spec)


class _End:
    """Sentinel: iterator exhausted."""


class _Err:
    def __init__(self, exc: BaseException):
        self.exc = exc


class DevicePrefetcher(Iterator[Any]):
    """Iterate ``it`` through a ``depth``-bounded background pipeline.

    ``mesh``/``spec`` select the sharded ``device_put`` (see
    :func:`sharded_put`); with ``mesh=None`` this is a plain host-side
    prefetch thread (the pipeline drivers' mode — their stage transfer is
    host-mediated).  ``transform`` optionally replaces the put entirely
    (receives the host batch, returns what the consumer should get).
    """

    def __init__(self, it: Iterable[Any], *, mesh=None, spec=None,
                 depth: int = 2,
                 transform: Callable[[Any], Any] | None = None,
                 spans=None):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self.depth = depth
        # host-phase span stream (telemetry.spans.SpanStream) — drivers
        # that build the prefetcher before the TelemetryRun assign it
        # afterwards (``pref.spans = telem.spans``); records the
        # consumer's queue waits and the producer thread's staging time
        self.spans = spans
        # live MetricsRegistry, same late-assignment pattern
        # (``pref.metrics = telem.metrics``); both feeds None-tolerant
        self.metrics = None
        self._it = iter(it)
        self._put = transform if transform is not None \
            else (lambda b: sharded_put(b, mesh, spec))
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._closed = False
        self._thread = threading.Thread(
            target=self._produce, name="device-prefetcher", daemon=True)
        self._thread.start()

    # ---- producer (background thread) -----------------------------------
    def _produce(self) -> None:
        from ..telemetry.metrics import maybe_inc
        from ..telemetry.spans import maybe_span
        try:
            for item in self._it:
                with maybe_span(self.spans, "prefetch/stage",
                                cat="prefetch"):
                    staged = self._put(item)
                maybe_inc(self.metrics, "prefetch_staged_total")
                while not self._stop.is_set():
                    try:
                        self._q.put(staged, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if self._stop.is_set():
                    return
            self._enqueue_final(_End())
        except BaseException as e:  # noqa: BLE001 - relayed to consumer
            self._enqueue_final(_Err(e))

    def _enqueue_final(self, token) -> None:
        while not self._stop.is_set():
            try:
                self._q.put(token, timeout=0.1)
                return
            except queue.Full:
                continue

    # ---- consumer --------------------------------------------------------
    def __iter__(self) -> "DevicePrefetcher":
        return self

    def __next__(self) -> Any:
        if self._closed:
            raise StopIteration
        import time
        from ..telemetry.metrics import maybe_observe
        from ..telemetry.spans import maybe_span
        t0 = time.perf_counter()
        with maybe_span(self.spans, "prefetch/wait", cat="prefetch"):
            item = self._q.get()
        maybe_observe(self.metrics, "prefetch_wait_seconds",
                      time.perf_counter() - t0)
        if isinstance(item, _End):
            self.close()
            raise StopIteration
        if isinstance(item, _Err):
            self.close()
            raise item.exc
        return item

    # ---- lifecycle -------------------------------------------------------
    @property
    def alive(self) -> bool:
        return self._thread.is_alive()

    def close(self) -> None:
        """Stop the producer and join it.  Idempotent; safe to call from
        an exception handler mid-loop (the ``with`` form does)."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        # drain so a producer blocked on a full queue sees the stop flag
        while self._thread.is_alive():
            try:
                self._q.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=0.05)
        self._thread.join()

    def __enter__(self) -> "DevicePrefetcher":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False
