"""Bounded async dispatch with a declared host-sync policy.

The synchronous loop this replaces paid two host round-trips per step
(``jax.block_until_ready(loss)`` + ``float(loss)``), serializing host
and device.  The pump instead lets up to ``max_in_flight`` dispatched
steps retire their losses as *device arrays* and only blocks the host
at three policy points:

  * profile-schedule boundaries (so ``jax.profiler`` traces bound
    exactly the intended steps — checked via
    ``Profiler.pending_transition``);
  * every ``sync_every`` steps (the ``--sync-every`` flag);
  * loop exit (``close()`` / the ``with`` exit, crash included).

Plus a fourth, non-policy wait: when ``max_in_flight`` losses are
pending, the oldest is retired before dispatching further (backpressure,
so an unbounded host can't race arbitrarily far ahead of the device).
Every blocking event is instrumented: ``host_sync_count`` and its
per-reason breakdown land in the run's ``summary.json``.

Losses are resolved to floats at sync points and fed, in step order, to
the ``TelemetryRun`` (which buffered the deferred events — the JSONL
schema is unchanged), to ``PerformanceTracker.record_loss`` (so
``avg_loss`` survives async mode), and to the per-step ``log`` callbacks
the drivers pass for their console prints.

``mode="sync"`` reproduces the old strictly synchronous loop through
the same code path — the A/B lever the smoke test and ``bench.py`` use.
"""

from __future__ import annotations

from collections import deque


def _to_float(x) -> float:
    from ..utils.mesh import local_scalar
    return local_scalar(x)


class StepPump:
    """Drive one training loop's loss retirement and sync policy.

    Usage (the shape every strategy driver now follows)::

        with TelemetryRun(...) as telem:
            with StepPump(telem=telem, tracker=tracker,
                          mode=cfg.dispatch, sync_every=cfg.sync_every,
                          max_in_flight=cfg.max_in_flight) as pump:
                for i, batch in zip(range(cfg.num_steps), prefetcher):
                    params, opt, loss = step(params, opt, batch)
                    pump.emit(loss, tokens=..., log=maybe_print)
            metrics = pump.metrics   # final tracker metrics, losses resolved
    """

    def __init__(self, *, telem=None, tracker=None, mode: str = "async",
                 sync_every: int = 10, max_in_flight: int = 16,
                 profiler=None, watchdog=None):
        if mode not in ("async", "sync"):
            raise ValueError(f"dispatch mode must be async|sync, got {mode!r}")
        if max_in_flight < 1:
            raise ValueError(f"max_in_flight must be >= 1, got {max_in_flight}")
        self.telem = telem
        self.tracker = tracker
        self.mode = mode
        self.sync_every = max(int(sync_every), 0)
        self.max_in_flight = int(max_in_flight)
        # collective watchdog (resilience.elastic.Watchdog): every
        # blocking sync point below routes through it so a hung
        # collective becomes a diagnosable StepTimeoutError with the
        # in-flight step index attached, never a silent deadlock
        self.watchdog = watchdog
        self.profiler = profiler if profiler is not None \
            else getattr(telem, "profiler", None)
        self._pending: deque = deque()   # (step_idx, device loss, log cb)
        self._emitted = 0
        self._closed = False
        self.resolved: list[tuple[int, float]] = []  # (step_idx, loss)
        self.sync_breakdown: dict[str, int] = {}
        self.metrics: dict | None = None

    # ---- accounting ------------------------------------------------------
    @property
    def host_sync_count(self) -> int:
        return sum(self.sync_breakdown.values())

    @property
    def losses(self) -> list[float]:
        """Resolved losses in step order (complete after ``close()``)."""
        return [l for _, l in self.resolved]

    def _count(self, reason: str) -> None:
        self.sync_breakdown[reason] = self.sync_breakdown.get(reason, 0) + 1
        from ..telemetry.metrics import maybe_inc
        maybe_inc(getattr(self.telem, "metrics", None),
                  "pump_host_sync_total", reason=reason)

    def _block(self, arr, step: int | None = None,
               reason: str = "sync") -> None:
        """One blocking wait at a sync point, watchdog-guarded and
        recorded as a ``pump/<reason>`` host span when the telemetry run
        carries a span stream (the timeline evidence of where the host
        actually stalls)."""
        import jax
        from ..telemetry.spans import maybe_span
        # the reason set is closed (per_step/profile_boundary/sync_every/
        # throttle/drain/exit), so the span-name cardinality is bounded
        with maybe_span(getattr(self.telem, "spans", None),  # span-ok
                        f"pump/{reason}", cat="pump", step=step):
            if self.watchdog is not None:
                self.watchdog.block(jax.block_until_ready, arr, step=step)
            else:
                jax.block_until_ready(arr)

    # ---- resolution ------------------------------------------------------
    def _resolve_one(self, idx: int, arr, log) -> float | None:
        try:
            lf = _to_float(arr)
        except Exception:   # crash path: a poisoned array must not mask
            return None     # the original loop exception
        self.resolved.append((idx, lf))
        if self.tracker is not None:
            self.tracker.record_loss(lf)
        if log is not None:
            log(lf)
        return lf

    def _drain(self) -> None:
        """Resolve every pending loss (oldest first) and flush the
        telemetry events that were deferred on them."""
        if not self._pending:
            return
        self._block(self._pending[-1][1], step=self._pending[-1][0],
                    reason="drain")
        while self._pending:
            self._resolve_one(*self._pending.popleft())
        if self.telem is not None:
            self.telem.flush()

    # ---- the per-step call ----------------------------------------------
    def emit(self, loss, *, tokens: int | None = None, log=None,
             **extra) -> bool:
        """Record one dispatched step whose loss is ``loss`` (a device
        array).  ``log``, if given, is called with the resolved float at
        sync time — drivers put their console prints there.

        Returns True when this step was a full sync point (everything
        up to and including this loss resolved) — the signal the
        resilience checkpointer rides so async saves land on the
        existing host-sync schedule instead of adding barriers."""
        if self._closed:
            raise RuntimeError("emit() after close()")
        i = self._emitted
        self._emitted += 1
        metrics = None
        if self.tracker is not None:
            metrics = self.tracker.step(tokens or 0)
        boundary = (self.profiler is not None
                    and getattr(self.profiler, "enabled", False)
                    and self.profiler.pending_transition())
        if self.mode == "sync" or boundary or (
                self.sync_every and (i + 1) % self.sync_every == 0):
            reason = ("per_step" if self.mode == "sync"
                      else "profile_boundary" if boundary
                      else "sync_every")
            self._block(loss, step=i, reason=reason)
            self._drain()
            lf = self._resolve_one(i, loss, log)
            self._count(reason)
            if self.telem is not None:
                self.telem.step(loss=lf, tokens=tokens,
                                tracker_metrics=metrics, **extra)
            return True
        else:
            self._pending.append((i, loss, log))
            if self.telem is not None:
                # deferred: TelemetryRun buffers the event and resolves
                # the device-array loss at flush time
                self.telem.step(loss=loss, tokens=tokens,
                                tracker_metrics=metrics, **extra)
            if len(self._pending) > self.max_in_flight:
                idx0, arr0, log0 = self._pending.popleft()
                self._block(arr0, step=idx0, reason="throttle")
                self._resolve_one(idx0, arr0, log0)
                if self.telem is not None:
                    self.telem.flush(up_to=1)
                self._count("throttle")
            return False

    # ---- lifecycle -------------------------------------------------------
    def close(self) -> None:
        """Drain in-flight losses (one final barrier when any are
        pending), snapshot final tracker metrics, and report the sync
        accounting into the owning TelemetryRun's summary."""
        if self._closed:
            return
        self._closed = True
        if self._pending:
            try:
                self._drain()
            finally:
                self._count("exit")
        if self.tracker is not None:
            self.metrics = self.tracker.metrics(sample_memory=True)
        if self.telem is not None:
            self.telem.host_sync_count = self.host_sync_count
            self.telem.host_sync_breakdown = dict(self.sync_breakdown)

    def __enter__(self) -> "StepPump":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False
