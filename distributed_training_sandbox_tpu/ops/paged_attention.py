"""Paged-attention decode kernel (Pallas).

``serving/engine._paged_layer_body`` attends against the paged KV pool
by first gathering every slot's pages into a contiguous
``(B, V, n_kv, hd)`` HBM view (``pk[pages]``) and then contracting over
it.  That gather is pure data movement: for a decode step (S == 1) it
re-materializes the entire visible KV window per layer per token just
to feed one matvec-sized contraction.

This kernel reads the pages IN PLACE instead: the page table row rides
into the kernel, and each page is dynamically loaded from the pool ref
straight into kernel-local (VMEM-resident on TPU) storage — the
``(B, V, n_kv, hd)`` intermediate never exists at the XLA level, so HBM
traffic drops from (gather-write + gather-read) to a single pool read.
The attention math on the in-kernel view is the exact op sequence of
``_paged_layer_body`` — same einsum specs, mask constant, softmax axis,
probs cast, and (for int8 pools) the same quantize/scale-fold ordering
with the per-page scales folded in-kernel — so the kernel output is
BITWISE equal to the reference path on matched inputs (asserted in
tests/test_kernels.py on the CPU interpret tier).

CPU-tier note: ``interpret=True`` executes the dynamic page loads with
jax.lax machinery; on real TPU the page table row would sit in SMEM
(scalar prefetch) and the loads become VMEM DMAs — recorded as the
hardware-tier evolution, same kernel body.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["paged_attention_decode"]


def _gather_pool(pool_ref, pages_ref, n_slot_pages: int, page: int):
    """Load this slot's pages from the pool ref into one kernel-local
    ``(V, …)`` array via dynamically-indexed page reads (no XLA-level
    gather)."""
    tail = pool_ref.shape[2:]
    acc0 = jnp.zeros((n_slot_pages * page,) + tail, pool_ref.dtype)

    def load(p, acc):
        pg = pages_ref[0, p]
        blk = pl.load(pool_ref,
                      (pl.ds(pg, 1),) + (slice(None),) * (1 + len(tail)))
        return jax.lax.dynamic_update_slice(
            acc, blk[0], (p * page,) + (0,) * len(tail))

    return jax.lax.fori_loop(0, n_slot_pages, load, acc0)


def _decode_kernel(pages_ref, q_ref, apos_ref, pk_ref, pv_ref, o_ref, *,
                   n_slot_pages: int, probs_dtype):
    """Float pool: mirror of the non-quantized `_paged_layer_body`
    attention core for one batch slot (S == 1)."""
    page = pk_ref.shape[1]
    hd = q_ref.shape[-1]
    q = q_ref[0, 0]                                       # (g, r, hd)
    a = apos_ref[0, 0]
    kv = _gather_pool(pk_ref, pages_ref, n_slot_pages, page)   # (V, g, hd)
    vv = _gather_pool(pv_ref, pages_ref, n_slot_pages, page)
    scores = jnp.einsum("grh,kgh->grk", q, kv,
                        preferred_element_type=jnp.float32) / math.sqrt(hd)
    vis = jnp.arange(kv.shape[0]) <= a
    scores = jnp.where(vis[None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    o_ref[0, 0] = jnp.einsum("grk,kgh->grh", probs.astype(probs_dtype),
                             vv, preferred_element_type=jnp.float32)


def _decode_kernel_q8(pages_ref, q_ref, qs_ref, apos_ref, pk_ref, pv_ref,
                      pks_ref, pvs_ref, o_ref, *, n_slot_pages: int):
    """int8 pool: the quantized `_paged_layer_body` attention core with
    the per-page K/V scales folded in-kernel (scale-fold order matches
    the reference exactly for bitwise parity)."""
    from .quant import quantize_int8
    page = pk_ref.shape[1]
    hd = q_ref.shape[-1]
    qq = q_ref[0, 0]                                      # int8 (g, r, hd)
    qs = qs_ref[0, 0]                                     # f32  (g, r, 1)
    a = apos_ref[0, 0]
    kv = _gather_pool(pk_ref, pages_ref, n_slot_pages, page)   # int8 (V, g, hd)
    vv = _gather_pool(pv_ref, pages_ref, n_slot_pages, page)
    ks = _gather_pool(pks_ref, pages_ref, n_slot_pages, page)  # f32 (V, g, 1)
    vs = _gather_pool(pvs_ref, pages_ref, n_slot_pages, page)
    scores_i = jnp.einsum("grh,kgh->grk", qq, kv,
                          preferred_element_type=jnp.int32)
    scores = (scores_i.astype(jnp.float32) * qs
              * ks[..., 0].T[:, None, :]) / math.sqrt(hd)
    vis = jnp.arange(kv.shape[0]) <= a
    scores = jnp.where(vis[None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    pvw = probs * vs[..., 0].T[:, None, :]
    pvq, pv_sc = quantize_int8(pvw, axis=-1)
    attn_i = jnp.einsum("grk,kgh->grh", pvq, vv,
                        preferred_element_type=jnp.int32)
    o_ref[0, 0] = attn_i.astype(jnp.float32) * pv_sc


def paged_attention_decode(qg, pk, pv, pages, apos, *, q_scale=None,
                           pk_s=None, pv_s=None, probs_dtype=None,
                           interpret: bool | None = None):
    """Decode-step paged attention, pages read in place via the table.

    qg (B, 1, n_kv, rep, hd) — grouped query (already rope'd); int8
    codes with ``q_scale`` (B, 1, n_kv, rep, 1) f32 when the pool is
    int8.  pk/pv (n_pages, page, n_kv, hd); pk_s/pv_s their f32 scales
    for int8 pools.  pages (B, P) int32 page table; apos (B, 1) int32
    absolute position of the new row.  Returns f32 (B, 1, n_kv, rep,
    hd), the exact value of the reference gather-then-einsum path
    (caller applies the same ``astype`` epilogue).
    """
    import functools
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B, S, nkv, rep, hd = qg.shape
    if S != 1:
        raise ValueError(f"decode kernel is S==1 only, got S={S}")
    P = pages.shape[1]
    page = pk.shape[1]
    quantized = pk.dtype == jnp.int8

    whole = lambda arr: pl.BlockSpec(
        arr.shape, lambda b: (0,) * arr.ndim)
    row = pl.BlockSpec((1, P), lambda b: (b, 0))
    qspec = pl.BlockSpec((1, 1, nkv, rep, hd), lambda b: (b, 0, 0, 0, 0))
    aspec = pl.BlockSpec((1, 1), lambda b: (b, 0))
    out_spec = pl.BlockSpec((1, 1, nkv, rep, hd), lambda b: (b, 0, 0, 0, 0))
    out_shape = jax.ShapeDtypeStruct((B, 1, nkv, rep, hd), jnp.float32)

    if quantized:
        if q_scale is None or pk_s is None or pv_s is None:
            raise ValueError("int8 pool needs q_scale, pk_s and pv_s")
        kernel = functools.partial(_decode_kernel_q8, n_slot_pages=P)
        sspec = pl.BlockSpec((1, 1, nkv, rep, 1), lambda b: (b, 0, 0, 0, 0))
        return pl.pallas_call(
            kernel,
            grid=(B,),
            in_specs=[row, qspec, sspec, aspec, whole(pk), whole(pv),
                      whole(pk_s), whole(pv_s)],
            out_specs=out_spec,
            out_shape=out_shape,
            interpret=interpret,
        )(pages, qg, q_scale, apos, pk, pv, pk_s, pv_s)

    kernel = functools.partial(
        _decode_kernel, n_slot_pages=P,
        probs_dtype=probs_dtype or qg.dtype)
    return pl.pallas_call(
        kernel,
        grid=(B,),
        in_specs=[row, qspec, aspec, whole(pk), whole(pv)],
        out_specs=out_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(pages, qg, apos, pk, pv)
