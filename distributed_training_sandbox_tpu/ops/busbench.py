"""Collective bus-bandwidth microbenchmark — the BASELINE.json headline metric.

The reference teaches each collective interactively over 2 NCCL ranks
(``02-operations.ipynb``) and its real output artifact is NCCL profiler
traces.  This module produces the ICI side of the side-by-side: per collective,
per payload size, wall-clock and algorithm/bus bandwidth using the nccl-tests
accounting so numbers are directly comparable with NCCL's:

    all_reduce      busbw = algbw · 2(n-1)/n
    all_gather      busbw = algbw · (n-1)/n     (algbw over the *full* tensor)
    reduce_scatter  busbw = algbw · (n-1)/n
    ppermute        busbw = algbw               (every link carries the payload)
    all_to_all      busbw = algbw · (n-1)/n
"""

from __future__ import annotations

import time
from dataclasses import dataclass, asdict

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from . import collectives as C


@dataclass
class BusResult:
    collective: str
    payload_bytes: int
    n_devices: int
    time_ms: float
    algbw_gbps: float
    busbw_gbps: float

    def to_dict(self):
        return asdict(self)


def bus_factor(name: str, n: int) -> float:
    """nccl-tests busbw/algbw wire factor for an ``n``-rank collective.
    Shared with ``telemetry.ledger`` so measured trace bandwidths use the
    exact same accounting as this microbenchmark."""
    if n <= 1:
        return 1.0
    if name == "all_reduce":
        return 2.0 * (n - 1) / n
    if name in ("all_gather", "reduce_scatter", "all_to_all"):
        return (n - 1) / n
    return 1.0  # ppermute / collective_permute


_bus_factor = bus_factor  # original (private) spelling


def _build(name: str, mesh: Mesh, axis: str, nelems: int):
    """Jitted one-collective function + global input shape.

    nccl-tests message-size accounting: for all_reduce / reduce_scatter /
    ppermute / all_to_all every device holds a full ``nelems`` buffer (global
    input (n, nelems) sharded on dim 0); for all_gather each device holds
    ``nelems/n`` and the *output* is the ``nelems`` buffer.  algbw is then
    ``nelems·itemsize / t`` for every collective, directly comparable with
    nccl-tests' column of the same name.
    """
    n = mesh.devices.size
    if name == "all_reduce":
        f = lambda x: C.all_reduce(x[0], axis)
        in_spec, out_spec, shape = P(axis), P(), (n, nelems)
    elif name == "all_gather":
        f = lambda x: C.all_gather(x, axis)
        in_spec, out_spec, shape = P(axis), P(), (nelems,)
    elif name == "reduce_scatter":
        f = lambda x: C.reduce_scatter(x[0], axis)
        in_spec, out_spec, shape = P(axis), P(axis), (n, nelems)
    elif name == "ppermute":
        f = lambda x: C.ppermute_ring(x, axis)
        in_spec, out_spec, shape = P(axis), P(axis), (n, nelems)
    elif name == "all_to_all":
        f = lambda x: C.all_to_all(x[0], axis)[None]
        in_spec, out_spec, shape = P(axis), P(axis), (n, nelems)
    else:
        raise ValueError(name)
    return jax.jit(C.smap(f, mesh, in_spec, out_spec)), shape


def bench_collective(name: str, payload_bytes: int, mesh: Mesh | None = None,
                     axis: str | None = None, *, dtype=jnp.bfloat16,
                     iters: int = 10, warmup: int = 3) -> BusResult:
    """Time one collective at ``payload_bytes`` total payload (the full
    logical tensor, matching how nccl-tests sizes all_reduce)."""
    from ..utils.mesh import get_mesh
    mesh = mesh or get_mesh()
    axis = axis or mesh.axis_names[0]
    n = mesh.devices.size
    itemsize = jnp.dtype(dtype).itemsize
    nelems = max(payload_bytes // itemsize, n)
    nelems -= nelems % n  # divisible shards
    fn, shape = _build(name, mesh, axis, nelems)
    total = 1
    for s in shape:
        total *= s
    x = jax.device_put(
        jnp.arange(total, dtype=jnp.float32).astype(dtype).reshape(shape),
        jax.sharding.NamedSharding(mesh, P(axis)))
    for _ in range(warmup):
        jax.block_until_ready(fn(x))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(x)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters
    algbw = nelems * itemsize / dt / 1e9
    return BusResult(
        collective=name,
        payload_bytes=nelems * itemsize,
        n_devices=n,
        time_ms=dt * 1e3,
        algbw_gbps=algbw,
        busbw_gbps=algbw * _bus_factor(name, n),
    )


def run_sweep(payloads=(1 << 20, 16 << 20, 128 << 20), mesh: Mesh | None = None,
              collectives=("all_reduce", "all_gather", "reduce_scatter",
                           "ppermute", "all_to_all"), **kw) -> list[BusResult]:
    return [bench_collective(c, p, mesh, **kw)
            for c in collectives for p in payloads]
