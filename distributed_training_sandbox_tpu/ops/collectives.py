"""L1 communication backend: explicit XLA collectives over a named mesh.

The reference's L1 is ``torch.distributed`` over NCCL; the complete set of
collectives it exercises (SURVEY.md §2.3) maps 1:1 onto ``jax.lax`` ops used
*inside* ``shard_map``:

    dist.all_reduce            -> lax.psum / pmax / pmin (all_reduce here)
    dist.broadcast             -> masked psum (broadcast here; NCCL's own
                                  barrier trick in reverse — reference
                                  README.md:11 notes barriers ARE all_reduces)
    dist.all_gather(_into_tensor) -> lax.all_gather
    dist.reduce_scatter_tensor -> lax.psum_scatter
    dist.send/recv/isend/irecv -> lax.ppermute (ring / point-to-point)
    dist.all_to_all            -> lax.all_to_all
    dist.barrier               -> 1-element psum (barrier here)
    dist.scatter               -> psum_scatter of a masked stack, or slicing
                                  of a broadcast — provided as ``scatter``

These wrappers exist so strategy code reads like the reference's choreography
and so traces/HLO show one collective per logical call (shard_map keeps XLA
from re-choreographing them — SURVEY.md §7.1).
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.6 top-level export (check_vma kwarg)
    from jax import shard_map as _shard_map
    _RELAX_KW = {"check_vma": False}
except ImportError:  # pragma: no cover - older jax: experimental, check_rep
    from jax.experimental.shard_map import shard_map as _shard_map
    _RELAX_KW = {"check_rep": False}


def smap(f, mesh: Mesh, in_specs, out_specs, **kw):
    """shard_map with this repo's defaults (explicit collectives allowed)."""
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **_RELAX_KW, **kw)


def axis_rank(axis_name: str) -> jax.Array:
    """Device's coordinate along ``axis_name`` — the in-SPMD 'rank'."""
    return lax.axis_index(axis_name)


def axis_size(axis_name: str) -> int:
    """Static size of the named mesh axis, usable inside shard_map/pmap.

    ``lax.axis_size`` only exists on newer jax; older versions (this
    substrate ships 0.4.x) get the classic ``psum(1, axis)`` trick, which
    constant-folds to the same trace-time Python int — every call site
    that uses the result as a shape/loop bound keeps working."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def all_reduce(x, axis_name: str, op: str = "sum", *, mean: bool = False):
    """Twin of ``dist.all_reduce`` with SUM/MAX/MIN/PRODUCT (reference
    ``DDP/ddp.py:46``, ``02-operations.ipynb`` cells 33-36).  ``mean=True``
    fuses the reference's all_reduce-then-divide-by-ws DDP idiom."""
    if op == "sum":
        out = lax.psum(x, axis_name)
    elif op == "max":
        out = lax.pmax(x, axis_name)
    elif op == "min":
        out = lax.pmin(x, axis_name)
    elif op in ("prod", "product"):
        # No pprod primitive: product = sign-corrected exp(sum(log|x|)).
        # Costs 3 psums (magnitude, sign parity, zero detection) but handles
        # negatives/zeros like dist.all_reduce(PRODUCT); prod is a teaching
        # op (02-operations.ipynb cell 36), never on a hot path.
        neg = lax.psum((x < 0).astype(jnp.float32), axis_name)
        has_zero = lax.pmax((x == 0).astype(jnp.float32), axis_name)
        mag = jnp.exp(lax.psum(jnp.log(jnp.abs(jnp.where(x == 0, 1, x))),
                               axis_name))
        sign = jnp.where(neg % 2 == 1, -1.0, 1.0)
        out = jnp.where(has_zero > 0, 0.0, sign * mag).astype(x.dtype)
    else:
        raise ValueError(f"unknown reduce op {op!r}")
    if mean:
        if op != "sum":
            raise ValueError("mean only makes sense with sum")
        out = out / axis_size(axis_name)
    return out


def all_gather(x, axis_name: str, *, axis: int = 0, tiled: bool = True):
    """Twin of ``dist.all_gather_into_tensor`` (reference ``zero/zero3.py:39``):
    concatenate every device's shard along ``axis``."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name: str, *, axis: int = 0, tiled: bool = True):
    """Twin of ``dist.reduce_scatter_tensor`` (reference ``zero/zero2.py:107``):
    sum across devices, each device keeps its ``axis``-chunk."""
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=tiled)


def broadcast(x, axis_name: str, root=0):
    """Twin of ``dist.broadcast`` (reference ``DDP/ddp.py:36``,
    ``zero/zero1.py:102``): every device receives root's value.

    Implemented as a masked psum — one all-reduce on the wire, which is how
    NCCL traces also account small broadcasts/barriers (reference
    README.md:11-12).  ``root`` may be traced (zero1 recomputes the owner
    rank arithmetically per param, ``zero1.py:91-102``)."""
    mask = (lax.axis_index(axis_name) == root)
    zeros = jax.tree.map(jnp.zeros_like, x)
    masked = jax.tree.map(lambda a, z: jnp.where(mask, a, z), x, zeros)
    return jax.tree.map(lambda a: lax.psum(a, axis_name), masked)


def scatter(x, axis_name: str, *, axis: int = 0):
    """Twin of ``dist.scatter`` (nb cell 30): root's tensor split into
    equal chunks, one per device.  SPMD formulation: every device slices its
    own chunk of the (already broadcast) input."""
    idx = lax.axis_index(axis_name)
    n = axis_size(axis_name)
    if x.shape[axis] % n:
        raise ValueError(f"scatter: dim {axis} of size {x.shape[axis]} not "
                         f"divisible by axis {axis_name!r} size {n}")
    chunk = x.shape[axis] // n
    return lax.dynamic_slice_in_dim(x, idx * chunk, chunk, axis=axis)


def ppermute_ring(x, axis_name: str, *, shift: int = 1):
    """Ring send/recv: device i sends to (i+shift) mod n — the twin of the
    reference's send/recv pairs (``02-operations.ipynb`` cells 11-21) and of
    pipeline stage hops."""
    n = axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def all_to_all(x, axis_name: str, *, split_axis: int = 0, concat_axis: int = 0,
               tiled: bool = True):
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=tiled)


# ------------------------------------------------------- ring decomposition
#
# The overlap engine's primitives (SimpleFSDP, arXiv:2411.00284): the same
# bytes the monolithic all_gather / psum_scatter / psum ops move, but
# decomposed into ppermute ring hops the XLA scheduler can interleave with
# compute — a monolithic collective is an opaque wall; n-1 hops with a
# matmul chunk between each are a pipeline.  Two exactness classes:
#
#   * ``ring_all_gather`` and ``decomposed_all_reduce`` are BITWISE equal
#     to their monolithic twins: the ring moves data without arithmetic
#     (chunks land in rank order), the reduction arithmetic stays in the
#     monolithic psum_scatter (same per-element reduction order as psum —
#     pinned by tests/test_overlap.py), and their custom_vjp backward IS
#     the monolithic op's transpose.  These power ``--overlap ring``,
#     whose loss sequences are bitwise-identical to ``--overlap none``.
#   * ``all_gather_matmul`` / ``matmul_reduce_scatter`` additionally fuse
#     the matmul into the ring (multiply the chunk already on device
#     while the next shard travels / scatter partial products as they
#     finish).  Chunked contraction reassociates the K-sum, so these are
#     numerically equivalent but NOT bitwise — they power
#     ``--overlap ring_fused``.


class RingShard:
    """A weight that stays SHARDED along its contraction dim: the marker
    ``parallel.fsdp``'s ring_fused layer hook hands to the model so the
    projection matmul runs as ``all_gather_matmul`` instead of
    gather-then-matmul.  Registered as a pytree so it rides through scan
    / remat / AD like the plain array it replaces.

    ``impl`` selects the per-chunk matmul engine: ``"xla"`` (the plain
    traced ``@``) or ``"pallas"`` (:func:`all_gather_matmul_pallas`'s
    tile kernel) — aux data, so the two variants trace as distinct
    programs."""

    def __init__(self, shard, axis_name: str, impl: str = "xla"):
        self.shard = shard
        self.axis_name = axis_name
        self.impl = impl

    def __repr__(self):  # pragma: no cover - debugging aid
        return (f"RingShard({self.shard.shape}, axis={self.axis_name!r}, "
                f"impl={self.impl!r})")


jax.tree_util.register_pytree_node(
    RingShard,
    lambda rs: ((rs.shard,), (rs.axis_name, rs.impl)),
    lambda aux, children: RingShard(children[0], *aux))


def _ring_perm(n: int, shift: int = 1):
    return [(i, (i + shift) % n) for i in range(n)]


def _check_chunk(name: str, what: str, size: int, n: int, axis_name: str):
    """Explicit divisibility guard: the ring splits ``what`` into one
    chunk per device, so an indivisible dim would otherwise surface as an
    opaque reshape/dynamic-slice failure deep in the trace."""
    if size % n:
        raise ValueError(
            f"{name}: {what} of size {size} is not divisible by mesh "
            f"axis {axis_name!r} size {n} — the ring needs one "
            f"equal chunk per device (pad the dim or use the "
            f"monolithic collective)")


def _ring_gather_impl(x, axis_name: str, axis: int):
    """n-1 ppermute hops assembling shards in rank order — value-wise
    identical to ``lax.all_gather(tiled=True)`` (pure data movement)."""
    n = axis_size(axis_name)
    if n == 1:  # degenerate ring: nothing to gather
        return x
    idx = lax.axis_index(axis_name)
    axis = axis % x.ndim
    chunk = x.shape[axis]
    out = jnp.zeros(x.shape[:axis] + (n * chunk,) + x.shape[axis + 1:],
                    x.dtype)
    cur = x
    for t in range(n):
        src = (idx - t) % n          # whose shard arrived after t hops
        out = lax.dynamic_update_slice_in_dim(out, cur, src * chunk, axis)
        if t < n - 1:
            cur = lax.ppermute(cur, axis_name, _ring_perm(n))
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def ring_all_gather(x, axis_name: str, axis: int = 0):
    """Ring-decomposed twin of :func:`all_gather`: bitwise-identical
    output (rank-order chunk placement, zero arithmetic), backward pinned
    to the monolithic gather's transpose (one psum_scatter) so gradients
    are bitwise-identical too.  The n-1 exposed hops are what the
    latency-hiding scheduler overlaps with the compute consuming the
    early chunks."""
    return _ring_gather_impl(x, axis_name, axis)


def _rag_fwd(x, axis_name, axis):
    return _ring_gather_impl(x, axis_name, axis), None


def _rag_bwd(axis_name, axis, _res, g):
    if axis_size(axis_name) == 1:
        return (g,)
    return (lax.psum_scatter(g, axis_name, scatter_dimension=axis % g.ndim,
                             tiled=True),)


ring_all_gather.defvjp(_rag_fwd, _rag_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def decomposed_all_reduce(x, axis_name: str, axis: int = -1):
    """all_reduce split into psum_scatter + ring all-gather — the RS+AG
    identity EQuARX treats as first-class.  The reduction arithmetic
    stays in the monolithic psum_scatter (same per-element order as
    lax.psum — pinned by test), the re-assembly is the exact ring, so
    the value is BITWISE equal to ``lax.psum`` while exposing n-1
    schedulable hops.  Backward is pinned to psum's own transpose
    (a psum of the cotangent).  ``axis``: the dim to scatter over; must
    be divisible by the ring size."""
    n = axis_size(axis_name)
    if n == 1:
        return x
    axis = axis % x.ndim
    _check_chunk("decomposed_all_reduce", f"scatter dim {axis}",
                 x.shape[axis], n, axis_name)
    scattered = lax.psum_scatter(x, axis_name, scatter_dimension=axis,
                                 tiled=True)
    return _ring_gather_impl(scattered, axis_name, axis)


def _dar_fwd(x, axis_name, axis):
    return decomposed_all_reduce(x, axis_name, axis), None


def _dar_bwd(axis_name, axis, _res, g):
    # lax.psum transposes to lax.psum (replicated cotangent summed) —
    # keep the ring variant's backward identical to the baseline's
    return (lax.psum(g, axis_name),)


decomposed_all_reduce.defvjp(_dar_fwd, _dar_bwd)


def all_gather_matmul(a, w_shard, axis_name: str):
    """Decomposed collective matmul, gather side: ``a @ W`` where ``W``
    is the rank-order concatenation of ``w_shard`` (each device's rows
    of the contraction dim).  At ring step t the chunk already on device
    multiplies while the next shard travels — the all-gather never
    materializes as one op, so nothing blocks the MXU.

    Plain traceable code: its AD transpose IS the ring
    matmul-reduce-scatter (cotangent contributions ride the reversed
    ring and sum along the way), which is why the ring_fused FSDP
    backward needs no separate reduce-scatter.  Chunked contraction
    reassociates the K-sum: numerically equivalent, not bitwise.
    """
    n = axis_size(axis_name)
    if n == 1:   # degenerate ring: the shard IS the whole weight
        return a @ w_shard
    k_chunk = w_shard.shape[0]
    K = a.shape[-1]
    if K != n * k_chunk:
        raise ValueError(
            f"all_gather_matmul: activation contraction dim {K} != "
            f"mesh axis {axis_name!r} size {n} x weight shard rows "
            f"{k_chunk} — the shard must be a 1/{n} row-slice of the "
            f"full weight (got shard shape {tuple(w_shard.shape)})")
    idx = lax.axis_index(axis_name)
    acc = jnp.zeros(a.shape[:-1] + (w_shard.shape[1],),
                    jnp.promote_types(a.dtype, w_shard.dtype))
    cur = w_shard
    for t in range(n):
        src = (idx - t) % n
        a_chunk = lax.dynamic_slice_in_dim(a, src * k_chunk, k_chunk,
                                           axis=a.ndim - 1)
        acc = acc + a_chunk @ cur
        if t < n - 1:
            cur = lax.ppermute(cur, axis_name, _ring_perm(n))
    return acc.astype(a.dtype)


def _agmm_chunk_kernel(a_ref, w_ref, o_ref):
    o_ref[...] = jnp.dot(a_ref[...], w_ref[...],
                         preferred_element_type=o_ref.dtype)


def _agmm_tile_call(a2, w, out_dtype, block_m, block_n, interpret):
    """One ring chunk's matmul as a Pallas call: grid over (M/bm, N/bn)
    row/col tiles, each block carrying full K (the chunk's contraction
    dim) so every output element's K-sum happens in ONE dot — which is
    what keeps the default full-block configuration bitwise against the
    traced ``a_chunk @ cur``."""
    from jax.experimental import pallas as pl

    M, K = a2.shape
    N = w.shape[1]
    bm = block_m or M
    bn = block_n or N
    return pl.pallas_call(
        _agmm_chunk_kernel,
        grid=(M // bm, N // bn),
        in_specs=[pl.BlockSpec((bm, K), lambda i, j: (i, 0)),
                  pl.BlockSpec((K, bn), lambda i, j: (0, j))],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((M, N), out_dtype),
        interpret=interpret,
    )(a2, w)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _pallas_chunk_matmul(a, w, block_m, block_n, interpret):
    """``a @ w`` with the forward tile-matmul in Pallas and the backward
    pinned to the XLA dot transposes the traced ``@`` would generate —
    pallas_call has no AD rule, and pinning keeps the ring_fused_pallas
    step's gradients on the same arithmetic as ring_fused's."""
    out, _ = _pcm_fwd(a, w, block_m, block_n, interpret)
    return out


def _pcm_fwd(a, w, block_m, block_n, interpret):
    lead = a.shape[:-1]
    a2 = a.reshape(-1, a.shape[-1])
    out_dtype = jnp.promote_types(a.dtype, w.dtype)
    out = _agmm_tile_call(a2, w, out_dtype, block_m, block_n, interpret)
    return out.reshape(*lead, w.shape[1]), (a, w)


def _pcm_bwd(block_m, block_n, interpret, res, g):
    a, w = res
    g2 = g.reshape(-1, g.shape[-1])
    a2 = a.reshape(-1, a.shape[-1])
    da = lax.dot_general(g2, w, (((1,), (1,)), ((), ())))
    dw = lax.dot_general(a2, g2, (((0,), (0,)), ((), ())))
    return da.reshape(a.shape).astype(a.dtype), dw.astype(w.dtype)


_pallas_chunk_matmul.defvjp(_pcm_fwd, _pcm_bwd)


def all_gather_matmul_pallas(a, w_shard, axis_name: str, *,
                             block_m: int | None = None,
                             block_n: int | None = None,
                             interpret: bool | None = None):
    """Kernel-tier :func:`all_gather_matmul`: the same ring choreography
    (shard hops stay ``lax.ppermute`` — the collective the contract
    counts and the ledger prices), with each per-chunk tile matmul
    running as a Pallas kernel instead of a traced ``@``.

    On the CPU tier (``interpret=True``, the default off-TPU) the ring
    hops cannot become in-kernel remote DMAs — interpret mode has no
    inter-device copy — so the decomposition point is the per-chunk
    matmul, and the default whole-chunk block makes the kernel's dot
    bit-identical to the XLA path's (pinned by test).  On TPU the same
    call sites tile via ``block_m``/``block_n``; folding the hop itself
    into the kernel (``pltpu.make_async_remote_copy`` double-buffered
    against the tile loop) is the recorded next step once a TPU BENCH
    round can measure it.

    AD: the ring scaffold stays plain traceable code (its transpose is
    the reversed-ring matmul-reduce-scatter, as for the XLA variant);
    only the chunk matmul carries a custom_vjp with XLA-dot backward."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n = axis_size(axis_name)
    if n == 1:   # degenerate ring: one whole-weight kernel call
        return _pallas_chunk_matmul(a, w_shard, block_m, block_n,
                                    interpret).astype(a.dtype)
    k_chunk = w_shard.shape[0]
    K = a.shape[-1]
    if K != n * k_chunk:
        raise ValueError(
            f"all_gather_matmul_pallas: activation contraction dim {K} "
            f"!= mesh axis {axis_name!r} size {n} x weight shard rows "
            f"{k_chunk} — the shard must be a 1/{n} row-slice of the "
            f"full weight (got shard shape {tuple(w_shard.shape)})")
    idx = lax.axis_index(axis_name)
    acc = jnp.zeros(a.shape[:-1] + (w_shard.shape[1],),
                    jnp.promote_types(a.dtype, w_shard.dtype))
    cur = w_shard
    for t in range(n):
        src = (idx - t) % n
        a_chunk = lax.dynamic_slice_in_dim(a, src * k_chunk, k_chunk,
                                           axis=a.ndim - 1)
        acc = acc + _pallas_chunk_matmul(a_chunk, cur, block_m, block_n,
                                         interpret)
        if t < n - 1:
            cur = lax.ppermute(cur, axis_name, _ring_perm(n))
    return acc.astype(a.dtype)


def matmul_reduce_scatter(a, b, axis_name: str, *, axis: int = 0):
    """Decomposed collective matmul, scatter side:
    ``psum_scatter(a @ b, axis)`` with each row-chunk's partial product
    computed right before its traveling accumulator needs it — partial
    products scatter as they finish instead of waiting for the full
    matmul then the full reduce-scatter.  Ring accumulation reassociates
    the device sum: numerically equivalent to the monolithic form, not
    bitwise."""
    n = axis_size(axis_name)
    if n == 1:
        return a @ b
    axis = axis % a.ndim
    if axis != 0:
        raise ValueError("matmul_reduce_scatter: only axis=0 (row chunks "
                         "of the result) is supported")
    _check_chunk("matmul_reduce_scatter", "result row dim", a.shape[0],
                 n, axis_name)
    idx = lax.axis_index(axis_name)
    chunk = a.shape[0] // n

    def partial_product(c):
        rows = lax.dynamic_slice_in_dim(a, c * chunk, chunk, axis=0)
        return rows @ b

    # accumulator for chunk (idx - s - 1) at step s lands fully summed on
    # its owner after n-1 hops (derivation: f(d, s) = d - s - 1 mod n)
    acc = partial_product((idx - 1) % n)
    for s in range(1, n):
        acc = lax.ppermute(acc, axis_name, _ring_perm(n))
        acc = acc + partial_product((idx - s - 1) % n)
    return acc


def barrier(axis_name: str):
    """Step-isolation barrier: a 1-element psum, exactly what
    ``dist.barrier`` is under NCCL (reference README.md:11-12,
    ``zero1.py:19-20``).  Returns the summed token; callers
    ``block_until_ready`` it for host-side isolation."""
    return lax.psum(jnp.ones((), dtype=jnp.float32), axis_name)


def tree_all_reduce(tree: Any, axis_name: str, *, mean: bool = True):
    """Per-leaf all_reduce of a pytree — the reference's per-param gradient
    all_reduce loop (``DDP/ddp.py:43-47``) as one tree_map.  One collective
    per leaf in the HLO, preserving trace-count parity."""
    return jax.tree.map(lambda g: all_reduce(g, axis_name, mean=mean), tree)


def tree_all_gather(tree: Any, axis_name: str, *, axis: int = 0,
                    tiled: bool = True):
    """Per-leaf all_gather of an arbitrarily nested pytree — the twin of
    the reference's recursive structured ``gather()``
    (``DDP/training_utils/utils.py:137-198``), which walks nested
    containers all-gathering every tensor.  Pytrees make the recursion a
    tree_map; non-array leaves pass through untouched, as the
    reference's non-tensor branches do; 0-d leaves gather into a
    (world_size,) vector (the reference stacks scalars the same way)."""
    def leaf(x):
        if not hasattr(x, "ndim"):
            return x
        if x.ndim == 0:
            return all_gather(x[None], axis_name, axis=0, tiled=True)
        return all_gather(x, axis_name, axis=axis, tiled=tiled)
    return jax.tree.map(leaf, tree)
