"""L1 communication backend: explicit XLA collectives over a named mesh.

The reference's L1 is ``torch.distributed`` over NCCL; the complete set of
collectives it exercises (SURVEY.md §2.3) maps 1:1 onto ``jax.lax`` ops used
*inside* ``shard_map``:

    dist.all_reduce            -> lax.psum / pmax / pmin (all_reduce here)
    dist.broadcast             -> masked psum (broadcast here; NCCL's own
                                  barrier trick in reverse — reference
                                  README.md:11 notes barriers ARE all_reduces)
    dist.all_gather(_into_tensor) -> lax.all_gather
    dist.reduce_scatter_tensor -> lax.psum_scatter
    dist.send/recv/isend/irecv -> lax.ppermute (ring / point-to-point)
    dist.all_to_all            -> lax.all_to_all
    dist.barrier               -> 1-element psum (barrier here)
    dist.scatter               -> psum_scatter of a masked stack, or slicing
                                  of a broadcast — provided as ``scatter``

These wrappers exist so strategy code reads like the reference's choreography
and so traces/HLO show one collective per logical call (shard_map keeps XLA
from re-choreographing them — SURVEY.md §7.1).
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.6 top-level export (check_vma kwarg)
    from jax import shard_map as _shard_map
    _RELAX_KW = {"check_vma": False}
except ImportError:  # pragma: no cover - older jax: experimental, check_rep
    from jax.experimental.shard_map import shard_map as _shard_map
    _RELAX_KW = {"check_rep": False}


def smap(f, mesh: Mesh, in_specs, out_specs, **kw):
    """shard_map with this repo's defaults (explicit collectives allowed)."""
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **_RELAX_KW, **kw)


def axis_rank(axis_name: str) -> jax.Array:
    """Device's coordinate along ``axis_name`` — the in-SPMD 'rank'."""
    return lax.axis_index(axis_name)


def axis_size(axis_name: str) -> int:
    """Static size of the named mesh axis, usable inside shard_map/pmap.

    ``lax.axis_size`` only exists on newer jax; older versions (this
    substrate ships 0.4.x) get the classic ``psum(1, axis)`` trick, which
    constant-folds to the same trace-time Python int — every call site
    that uses the result as a shape/loop bound keeps working."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def all_reduce(x, axis_name: str, op: str = "sum", *, mean: bool = False):
    """Twin of ``dist.all_reduce`` with SUM/MAX/MIN/PRODUCT (reference
    ``DDP/ddp.py:46``, ``02-operations.ipynb`` cells 33-36).  ``mean=True``
    fuses the reference's all_reduce-then-divide-by-ws DDP idiom."""
    if op == "sum":
        out = lax.psum(x, axis_name)
    elif op == "max":
        out = lax.pmax(x, axis_name)
    elif op == "min":
        out = lax.pmin(x, axis_name)
    elif op in ("prod", "product"):
        # No pprod primitive: product = sign-corrected exp(sum(log|x|)).
        # Costs 3 psums (magnitude, sign parity, zero detection) but handles
        # negatives/zeros like dist.all_reduce(PRODUCT); prod is a teaching
        # op (02-operations.ipynb cell 36), never on a hot path.
        neg = lax.psum((x < 0).astype(jnp.float32), axis_name)
        has_zero = lax.pmax((x == 0).astype(jnp.float32), axis_name)
        mag = jnp.exp(lax.psum(jnp.log(jnp.abs(jnp.where(x == 0, 1, x))),
                               axis_name))
        sign = jnp.where(neg % 2 == 1, -1.0, 1.0)
        out = jnp.where(has_zero > 0, 0.0, sign * mag).astype(x.dtype)
    else:
        raise ValueError(f"unknown reduce op {op!r}")
    if mean:
        if op != "sum":
            raise ValueError("mean only makes sense with sum")
        out = out / axis_size(axis_name)
    return out


def all_gather(x, axis_name: str, *, axis: int = 0, tiled: bool = True):
    """Twin of ``dist.all_gather_into_tensor`` (reference ``zero/zero3.py:39``):
    concatenate every device's shard along ``axis``."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name: str, *, axis: int = 0, tiled: bool = True):
    """Twin of ``dist.reduce_scatter_tensor`` (reference ``zero/zero2.py:107``):
    sum across devices, each device keeps its ``axis``-chunk."""
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=tiled)


def broadcast(x, axis_name: str, root=0):
    """Twin of ``dist.broadcast`` (reference ``DDP/ddp.py:36``,
    ``zero/zero1.py:102``): every device receives root's value.

    Implemented as a masked psum — one all-reduce on the wire, which is how
    NCCL traces also account small broadcasts/barriers (reference
    README.md:11-12).  ``root`` may be traced (zero1 recomputes the owner
    rank arithmetically per param, ``zero1.py:91-102``)."""
    mask = (lax.axis_index(axis_name) == root)
    zeros = jax.tree.map(jnp.zeros_like, x)
    masked = jax.tree.map(lambda a, z: jnp.where(mask, a, z), x, zeros)
    return jax.tree.map(lambda a: lax.psum(a, axis_name), masked)


def scatter(x, axis_name: str, *, axis: int = 0):
    """Twin of ``dist.scatter`` (nb cell 30): root's tensor split into
    equal chunks, one per device.  SPMD formulation: every device slices its
    own chunk of the (already broadcast) input."""
    idx = lax.axis_index(axis_name)
    n = axis_size(axis_name)
    if x.shape[axis] % n:
        raise ValueError(f"scatter: dim {axis} of size {x.shape[axis]} not "
                         f"divisible by axis {axis_name!r} size {n}")
    chunk = x.shape[axis] // n
    return lax.dynamic_slice_in_dim(x, idx * chunk, chunk, axis=axis)


def ppermute_ring(x, axis_name: str, *, shift: int = 1):
    """Ring send/recv: device i sends to (i+shift) mod n — the twin of the
    reference's send/recv pairs (``02-operations.ipynb`` cells 11-21) and of
    pipeline stage hops."""
    n = axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def all_to_all(x, axis_name: str, *, split_axis: int = 0, concat_axis: int = 0,
               tiled: bool = True):
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=tiled)


def barrier(axis_name: str):
    """Step-isolation barrier: a 1-element psum, exactly what
    ``dist.barrier`` is under NCCL (reference README.md:11-12,
    ``zero1.py:19-20``).  Returns the summed token; callers
    ``block_until_ready`` it for host-side isolation."""
    return lax.psum(jnp.ones((), dtype=jnp.float32), axis_name)


def tree_all_reduce(tree: Any, axis_name: str, *, mean: bool = True):
    """Per-leaf all_reduce of a pytree — the reference's per-param gradient
    all_reduce loop (``DDP/ddp.py:43-47``) as one tree_map.  One collective
    per leaf in the HLO, preserving trace-count parity."""
    return jax.tree.map(lambda g: all_reduce(g, axis_name, mean=mean), tree)


def tree_all_gather(tree: Any, axis_name: str, *, axis: int = 0,
                    tiled: bool = True):
    """Per-leaf all_gather of an arbitrarily nested pytree — the twin of
    the reference's recursive structured ``gather()``
    (``DDP/training_utils/utils.py:137-198``), which walks nested
    containers all-gathering every tensor.  Pytrees make the recursion a
    tree_map; non-array leaves pass through untouched, as the
    reference's non-tensor branches do; 0-d leaves gather into a
    (world_size,) vector (the reference stacks scalars the same way)."""
    def leaf(x):
        if not hasattr(x, "ndim"):
            return x
        if x.ndim == 0:
            return all_gather(x[None], axis_name, axis=0, tiled=True)
        return all_gather(x, axis_name, axis=axis, tiled=tiled)
    return jax.tree.map(leaf, tree)
