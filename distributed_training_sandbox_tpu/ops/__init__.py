from .collectives import (  # noqa: F401
    all_reduce,
    all_gather,
    reduce_scatter,
    broadcast,
    scatter,
    ppermute_ring,
    all_to_all,
    barrier,
    axis_rank,
    axis_size,
    smap,
    tree_all_reduce,
    tree_all_gather,
    ring_all_gather,
    all_gather_matmul,
    matmul_reduce_scatter,
    decomposed_all_reduce,
    RingShard,
)
from .hlo import count_collectives, lowered_text  # noqa: F401
from . import quant  # noqa: F401
