from .collectives import (  # noqa: F401
    all_reduce,
    all_gather,
    reduce_scatter,
    broadcast,
    ppermute_ring,
    all_to_all,
    barrier,
    axis_rank,
    axis_size,
    smap,
)
from .hlo import count_collectives, lowered_text  # noqa: F401
