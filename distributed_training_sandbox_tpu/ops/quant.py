"""Low-precision (int8) matmul path — the TPU twin of the reference's FP8
benchmark stack (``fp8/fp8_benchmark.py:61-92``: torchao Float8Linear with
dynamic scaling under FSDP2).

v5e has no fp8 units (SURVEY.md §7.3), so the honest low-precision twin is
int8: the MXU multiplies int8×int8 into int32 at twice the bf16 rate.  The
pieces, mirroring torchao's roles:

  * dynamic **per-row absmax scaling** (`quantize_int8`) — the twin of
    Float8Linear's dynamic scaling;
  * `int8_matmul`: XLA path (``lax.dot_general`` with int32 accumulation);
  * `int8_matmul_pallas`: the same contraction as a hand-tiled **Pallas
    kernel** with the dequant fused into the epilogue — the repo's
    native/kernel-level component (runs in interpreter mode off-TPU).
    VERDICT: measured end-to-end twice (r2 and r3, flagship 3B-L8
    seq 8192: 68.9 vs 74.7 TFLOPS/dev in r3) the hand-tiled kernel is
    ~8-9% BEHIND XLA's own int8 dot + fused quantize epilogue, across a
    block-size sweep.  XLA won; the kernel stays as the from-scratch
    teaching artifact and `"int8"` (the XLA path) is the production
    precision;
  * `quantized_dense`: straight-through-estimator linear layer for
    training (forward int8, backward bf16) — what Float8Linear does;
  * `quantized_all_gather`: gather int8 shards + scales and dequantize
    *after* the wire, the twin of torchao's
    ``enable_fsdp_float8_all_gather`` (``fp8_benchmark.py:79-81``) — 4x
    fewer bytes over ICI than a bf16 gather, with a full-precision
    psum_scatter backward.
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from . import collectives as C


def quantize_int8(x: jax.Array, axis: int = -1):
    """Symmetric per-row absmax int8 quantization along ``axis`` (the
    contraction dim): returns (q int8, scale f32 with ``axis`` kept at 1).
    """
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axis, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale), -127, 127)
    return q.astype(jnp.int8), scale


class QuantizedWeight(NamedTuple):
    """A weight stored AS int8 in HBM (plus its dequant scales) — for
    weight-STATIC uses (decode: weights never change across the whole
    generate call), where the win is not MXU rate but HBM bandwidth:
    every decode step reads every weight byte, so int8 storage halves the
    weight-read-bound step time.  Quantize once (``quantize_weight``),
    then any ``resolve_quantized_dense`` matmul accepts it in place of
    the bf16 array.  ``q``: int8 with the contraction dim where the bf16
    weight had it; ``s``: f32 scales, contraction dim kept at size 1."""
    q: jax.Array
    s: jax.Array


def quantize_weight(w: jax.Array, *, contract_axis: int = -2) -> QuantizedWeight:
    """(…, K, N) bf16 → QuantizedWeight: per-output-column absmax over the
    contraction dim (default: second-minor, the (K, N) layout of every
    projection here; stacked (L, K, N) leaves quantize per layer)."""
    q, s = quantize_int8(w, axis=contract_axis)
    return QuantizedWeight(q=q, s=s)


def _qres_value(y: jax.Array, name: str) -> jax.Array:
    from jax.ad_checkpoint import checkpoint_name
    q, s = quantize_int8(y, axis=-1)
    q = checkpoint_name(q, name)
    s = checkpoint_name(s, name)
    return dequantize(q, s, y.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def quantized_residual(y: jax.Array, name: str = "dot_q8") -> jax.Array:
    """int8 round-trip through a named remat checkpoint — quantized saved
    activations, the ActNN-style attack on the save_dots memory wall
    (r3's binding constraint: save_dots×int8 planned 18.2 GB vs 15.75 GB
    HBM).  Under ``save_only_these_names(name)`` the SAVED tensors are
    the int8 pair (½ the bytes of the bf16 activation + a per-row f32
    scale); every consumer reads the dequantized value, so the producing
    matmul is never recomputed in the backward — save_dots' FLOPs
    savings at roughly half its activation memory.  Cost: forward
    activations carry per-row absmax int8 noise (~0.4% relative), the
    same noise int8 training matmuls already inject at their inputs.

    Backward is straight-through (identity): ``round``'s true derivative
    is zero a.e., which would null every gradient flowing through the
    round-trip — the STE is what makes the saved-quantized trick
    trainable, exactly as in ``quantized_dense``."""
    return _qres_value(y, name)


def _qres_fwd(y, name):
    return _qres_value(y, name), None   # no residual: backward is identity


def _qres_bwd(name, _res, g):
    return (g,)


quantized_residual.defvjp(_qres_fwd, _qres_bwd)


def prequantized_dense(a: jax.Array, w: QuantizedWeight) -> jax.Array:
    """(…, K) · QuantizedWeight(K, N) → (…, N): dynamic per-row activation
    quantize + int8 MXU dot.  The weight arrives int8 from HBM — half the
    bytes of bf16, the decode-bandwidth play."""
    lead = a.shape[:-1]
    a2 = a.reshape(-1, a.shape[-1])
    xq, xs = quantize_int8(a2, axis=-1)
    out = int8_matmul(xq, xs, w.q, w.s.reshape(1, -1), out_dtype=a.dtype)
    return out.reshape(*lead, w.q.shape[-1])


def dequantize(q: jax.Array, scale: jax.Array, dtype=jnp.bfloat16):
    return (q.astype(jnp.float32) * scale).astype(dtype)


# ------------------------------------------------------------------- XLA

def int8_matmul(xq, xs, wq, ws, out_dtype=jnp.bfloat16):
    """(M,K)int8 · (K,N)int8 → (M,N), int32 accumulation on the MXU, scales
    applied in the epilogue.  xs: (M,1) f32, ws: (1,N) f32."""
    acc = lax.dot_general(xq, wq, (((1,), (0,)), ((), ())),
                          preferred_element_type=jnp.int32)
    return (acc.astype(jnp.float32) * xs * ws).astype(out_dtype)


# ---------------------------------------------------------------- pallas

def _pick_block(dim: int, target: int, mult: int) -> int:
    """Largest divisor of ``dim`` that is <= target and a multiple of
    ``mult`` (TPU lowering wants sublane/lane-aligned blocks: second-minor
    % 8, minor % 128 — or the whole dim)."""
    if dim <= target:
        return dim
    b = target - target % mult
    while b >= mult:
        if dim % b == 0:
            return b
        b -= mult
    return dim


def _qmm_kernel(xq_ref, xs_ref, wq_ref, ws_ref, o_ref):
    acc = jnp.dot(xq_ref[...], wq_ref[...],
                  preferred_element_type=jnp.int32)
    o_ref[...] = (acc.astype(jnp.float32) * xs_ref[...] * ws_ref[...]
                  ).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("out_dtype", "block_m",
                                             "block_n", "interpret"))
def int8_matmul_pallas(xq, xs, wq, ws, *, out_dtype=jnp.bfloat16,
                       block_m: int | None = None,
                       block_n: int | None = None,
                       interpret: bool = False):
    """Tiled Pallas twin of `int8_matmul`: grid over (M/bm, N/bn), full-K
    int8 blocks in VMEM, int32 MXU accumulation, fused dequant epilogue."""
    from jax.experimental import pallas as pl

    M, K = xq.shape
    K2, N = wq.shape
    assert K == K2, (K, K2)
    bm, bn = _auto_blocks(M, K, N, 1, block_m or 256, block_n or 512)
    return pl.pallas_call(
        _qmm_kernel,
        grid=(M // bm, N // bn),
        in_specs=[
            pl.BlockSpec((bm, K), lambda i, j: (i, 0)),
            pl.BlockSpec((bm, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((K, bn), lambda i, j: (0, j)),
            pl.BlockSpec((1, bn), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((M, N), out_dtype),
        interpret=interpret,
    )(xq, xs, wq, ws)


def _auto_blocks(M: int, K: int, N: int, x_itemsize: int,
                 target_m: int, target_n: int,
                 budget: int = 10 << 20) -> tuple[int, int]:
    """Largest (block_m, block_n) ≤ targets whose working set fits VMEM:
    double-buffered x block (bm, K), w block (K, bn) int8 and scales, plus
    the f32 accumulator/output tile.  ~16 MB/core total; budget leaves
    headroom for Mosaic scratch."""
    candidates_m = [target_m, 512, 256, 128, 64, 32, 16, 8]
    candidates_n = [target_n, 512, 256, 128]
    for tm in candidates_m:
        for tn in candidates_n:
            if tm > target_m or tn > target_n:
                continue
            bm, bn = _pick_block(M, tm, 8), _pick_block(N, tn, 128)
            need = 2 * (bm * K * x_itemsize + K * bn + bn * 4) \
                + bm * bn * 4 + bm * K  # int8 xq scratch
            if need <= budget:
                return bm, bn
    return _pick_block(M, 8, 8), _pick_block(N, 128, 128)


def _fused_qmm_kernel(x_ref, wq_ref, ws_ref, o_ref):
    """Quantize the activation block IN VMEM (per-row absmax over the full
    K that the block carries), then int8 MXU dot with the pre-quantized
    weight block and a fused dequant epilogue — the activation never makes
    an int8 round-trip through HBM."""
    xf = x_ref[...].astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    xs = jnp.where(amax > 0, amax / 127.0, 1.0)
    xq = jnp.clip(jnp.round(xf / xs), -127, 127).astype(jnp.int8)
    acc = jnp.dot(xq, wq_ref[...], preferred_element_type=jnp.int32)
    o_ref[...] = (acc.astype(jnp.float32) * xs * ws_ref[...]
                  ).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("out_dtype", "block_m",
                                             "block_n", "interpret"))
def int8_matmul_pallas_fused(x, wq, ws, *, out_dtype=jnp.bfloat16,
                             block_m: int | None = None,
                             block_n: int | None = None,
                             interpret: bool = False):
    """(M,K)bf16 · (K,N)int8 → (M,N): activation quantize fused into the
    matmul kernel (weights arrive pre-quantized — one pass per step,
    amortized over the whole M grid).  Block sizes default to the largest
    VMEM-fitting tiles (blocks carry full K for exact per-row scales)."""
    from jax.experimental import pallas as pl

    M, K = x.shape
    K2, N = wq.shape
    assert K == K2, (K, K2)
    bm, bn = _auto_blocks(M, K, N, x.dtype.itemsize,
                          block_m or 256, block_n or 512)
    return pl.pallas_call(
        _fused_qmm_kernel,
        grid=(M // bm, N // bn),
        in_specs=[
            pl.BlockSpec((bm, K), lambda i, j: (i, 0)),
            pl.BlockSpec((K, bn), lambda i, j: (0, j)),
            pl.BlockSpec((1, bn), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((M, N), out_dtype),
        interpret=interpret,
    )(x, wq, ws)


def _int8_dot(aq, a_scale, bq, b_scale, dims, out_dtype):
    """General int8 dot_general with int32 accumulation; scales must be
    broadcast-compatible with the (batch..., m, n) result."""
    acc = lax.dot_general(aq, bq, dims, preferred_element_type=jnp.int32)
    return (acc.astype(jnp.float32) * a_scale * b_scale).astype(out_dtype)


# ------------------------------------------------------------- training

def resolve_quantized_dense(precision: str, *, fp8_history_len: int = 0):
    """``matmul_precision`` name → ``(a, w) -> out`` matmul, the ONE
    mapping shared by the attention projections (``transformer._dense``)
    and the per-expert MoE matmuls (``parallel.expert.moe_mlp``), so the
    same precision string always selects the same impl everywhere.
    ``"bf16"`` returns a plain matmul.

    ``"fp8"`` / ``"fp8_pallas"`` select the e4m3-forward/e5m2-backward
    recipe (:func:`fp8_dense`, XLA or Pallas forward kernel);
    ``"fp8_delayed"`` additionally routes scaling through the
    ``fp8_history_len``-deep amax history (the config's
    ``fp8_amax_history_len`` axis).

    Every returned matmul also accepts a ``QuantizedWeight`` in the weight
    slot (decode's weight-static int8 storage) and routes it through
    ``prequantized_dense`` — so the decode path can hand pre-quantized
    layer pytrees to the SAME shared projection helpers the training
    model uses."""
    if precision == "bf16":
        base_fn = lambda a, w: a @ w  # noqa: E731
    elif precision.startswith("fp8"):
        impl = {"fp8": "xla", "fp8_delayed": "xla",
                "fp8_pallas": "pallas"}[precision]
        hist = (fp8_history_len or 16) if precision == "fp8_delayed" else 0
        interpret = jax.default_backend() != "tpu"
        base_fn = lambda a, w: fp8_dense(  # noqa: E731
            a, w, impl, interpret, hist)
    else:
        base = precision.removesuffix("_bwd")
        impl = {"int8": "xla", "int8_pallas": "pallas_fused"}[base]
        quantize_bwd = precision.endswith("_bwd")
        interpret = jax.default_backend() != "tpu"
        base_fn = lambda a, w: quantized_dense(  # noqa: E731
            a, w, impl, interpret, quantize_bwd)
    return lambda a, w: (prequantized_dense(a, w)
                         if isinstance(w, QuantizedWeight) else base_fn(a, w))


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def quantized_dense(x, w, impl: str = "xla", interpret: bool = False,
                    quantize_bwd: bool = False):
    """Linear layer with int8 forward — the Float8Linear training recipe
    (quantize dynamically, matmul in low precision).  ``x``: (..., K),
    ``w``: (K, N).

    impl: "xla" (lax.dot_general), "pallas" (pre-quantized-operand kernel),
    or "pallas_fused" (activation quantize fused into the kernel).

    quantize_bwd=False: straight-through bf16 backward (fwd-only precision,
    1/3 of the step's matmul FLOPs run at int8 rate).  True: the two
    backward matmuls (dX = g·Wᵀ, dW = Xᵀ·g) also run int8 with fresh
    per-contraction absmax scales — the full torchao dynamic recipe
    (Float8Linear quantizes grad_output to e5m2 for backward; int8 is the
    v5e-native analogue), putting ALL step matmul FLOPs at int8 rate.
    """
    out, _ = _qdense_fwd(x, w, impl, interpret, quantize_bwd)
    return out


def _qdense_fwd(x, w, impl, interpret, quantize_bwd):
    lead = x.shape[:-1]
    K = x.shape[-1]
    x2 = x.reshape(-1, K)
    if impl == "pallas_fused":
        wq, ws = quantize_int8(w, axis=0)
        out = int8_matmul_pallas_fused(x2, wq, ws, out_dtype=x.dtype,
                                       interpret=interpret)
    else:
        xq, xs = quantize_int8(x2, axis=-1)
        wq, ws = quantize_int8(w, axis=0)
        if impl == "pallas":
            out = int8_matmul_pallas(xq, xs, wq, ws, out_dtype=x.dtype,
                                     interpret=interpret)
        else:
            out = int8_matmul(xq, xs, wq, ws, out_dtype=x.dtype)
    return out.reshape(*lead, w.shape[1]), (x, w)


def _qdense_bwd(impl, interpret, quantize_bwd, res, g):
    x, w = res
    if not quantize_bwd:
        gx = jnp.einsum("...n,kn->...k", g, w)
        gw = jnp.einsum("...k,...n->kn", x, g)
        return gx, gw
    lead = x.shape[:-1]
    K, N = w.shape
    g2 = g.reshape(-1, N)
    x2 = x.reshape(-1, K)
    # dX = g · Wᵀ, contraction over N: g rows / w along its N axis.
    gq, gs = quantize_int8(g2, axis=-1)                 # (M,N), (M,1)
    wq_n, ws_n = quantize_int8(w, axis=1)               # (K,N), (K,1)
    gx = _int8_dot(gq, gs, wq_n, ws_n.T, (((1,), (1,)), ((), ())),
                   x.dtype)                             # (M,K)
    # dW = Xᵀ · g, contraction over M: both quantized along M.
    xq_m, xs_m = quantize_int8(x2, axis=0)              # (M,K), (1,K)
    gq_m, gs_m = quantize_int8(g2, axis=0)              # (M,N), (1,N)
    gw = _int8_dot(xq_m, xs_m.T, gq_m, gs_m, (((0,), (0,)), ((), ())),
                   w.dtype)                             # (K,N)
    return gx.reshape(*lead, K), gw


quantized_dense.defvjp(_qdense_fwd, _qdense_bwd)


# ----------------------------------------------------- quantized gather

@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def quantized_all_gather(x, axis_name: str, axis: int = 0,
                         q8_bwd: bool = False):
    """All-gather a shard in int8 + per-row scales, dequantize after the
    wire: the twin of torchao's fp8 all-gather under FSDP2
    (``fp8_benchmark.py:79-81``; EQuARX explores the same trade for XLA).
    Backward is a full-precision psum_scatter (mean-free sum), matching
    the plain all_gather transpose — unless ``q8_bwd``, which quantizes
    the gradient reduce-scatter too (:func:`quantized_reduce_scatter`),
    putting BOTH directions of FSDP param traffic on int8 wire bytes
    (the full EQuARX trade; grads then carry the documented
    half-quantum-per-contribution error)."""
    out, _ = _qag_fwd(x, axis_name, axis, q8_bwd)
    return out


def _qag_fwd(x, axis_name, axis, q8_bwd=False):
    if x.ndim == 1:
        # 1-D leaf (e.g. a norm scale): one scalar scale per shard,
        # re-applied segment-wise after the gather.
        ws = C.axis_size(axis_name)
        n = x.shape[0]
        q, s = quantize_int8(x.reshape(1, n), axis=-1)  # s: (1, 1)
        qg = C.all_gather(q.reshape(n), axis_name, axis=0)       # (ws*n,)
        sg = C.all_gather(s.reshape(1), axis_name, axis=0)       # (ws,)
        out = (qg.reshape(ws, n).astype(jnp.float32)
               * sg[:, None]).reshape(-1).astype(x.dtype)
        return out, None
    # quantize along some dim that is NOT the gather dim, so the gathered
    # scales stay broadcast-compatible with the gathered int8 data (each
    # shard's scales travel with it over the wire).
    qaxis = -1 if axis != x.ndim - 1 and axis != -1 else 0
    q, s = quantize_int8(x, axis=qaxis)
    qg = C.all_gather(q, axis_name, axis=axis)
    sg = C.all_gather(s, axis_name, axis=axis)
    return dequantize(qg, sg, x.dtype), None


def _qag_bwd(axis_name, axis, q8_bwd, res, g):
    # the gathered output has x's dtype, so g.dtype == x.dtype
    if q8_bwd:
        return (quantized_reduce_scatter(
            g.astype(jnp.float32), axis_name,
            axis=0 if g.ndim == 1 else axis).astype(g.dtype),)
    return (C.reduce_scatter(g.astype(jnp.float32), axis_name,
                             axis=axis).astype(g.dtype),)


quantized_all_gather.defvjp(_qag_fwd, _qag_bwd)


# --------------------------------------------- quantized all-reduce (EQuARX)

@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def quantized_all_reduce(x, axis_name: str):
    """EQuARX-style two-shot quantized all-reduce (arXiv:2506.17615):
    each rank ships its partial sum as int8 codes + per-row f32 scales,
    every rank dequantizes and sums the contributions in rank order.
    ~4x fewer bus bytes than an f32 psum (int8 codes dominate; scales are
    1/row), generalizing ``ddp.quantized_bucket_all_reduce``'s trick from
    DDP grad buckets to TP rejoin and FSDP grad traffic.

    Error bound: each rank's contribution carries symmetric-round error
    ≤ half its quantum (scale/2 per element), so the summed result is
    within ``n_ranks * max_scale / 2`` of ``lax.psum`` element-wise —
    the documented per-contribution bound the tests assert.

    Backward is pinned to psum's own transpose (a full-precision psum of
    the cotangent), so only forward traffic is quantized — the same
    asymmetry as ``quantized_all_gather``."""
    out, _ = _qar_fwd(x, axis_name)
    return out


def _qar_quant(x):
    """Per-row int8 codes + scales for an arbitrary-rank tensor: rows are
    the last axis (a 0/1-D leaf quantizes as one row with one scale)."""
    x_ = x.reshape(1, -1) if x.ndim < 2 else x
    q, s = quantize_int8(x_, axis=-1)
    return q, s


def _qar_fwd(x, axis_name):
    q, s = _qar_quant(x)
    # two-shot: gather every rank's codes and scales (a new leading rank
    # axis), dequantize-and-sum locally in rank order — deterministic
    # reduction order, identical on every rank.
    qg = C.all_gather(q, axis_name, axis=0, tiled=False)
    sg = C.all_gather(s, axis_name, axis=0, tiled=False)
    out = jnp.sum(qg.astype(jnp.float32) * sg, axis=0)
    return out.reshape(x.shape).astype(x.dtype), None


def _qar_bwd(axis_name, _res, g):
    # lax.psum transposes to lax.psum: keep the quantized variant's
    # backward identical to the baseline all-reduce's.
    return (lax.psum(g, axis_name),)


quantized_all_reduce.defvjp(_qar_fwd, _qar_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def quantized_reduce_scatter(x, axis_name: str, axis: int = 0):
    """Two-shot quantized reduce-scatter — the FSDP grad-traffic leg of
    the EQuARX trade: each rank quantizes its full partial tensor (int8
    codes + per-row scales), an all_to_all routes chunk ``r`` of every
    rank to rank ``r``, and the receiver dequantizes and sums its chunk
    in rank order.  Same per-contribution half-quantum error bound as
    :func:`quantized_all_reduce`; backward pinned to the monolithic
    reduce-scatter's transpose (a full-precision all_gather)."""
    out, _ = _qrs_fwd(x, axis_name, axis)
    return out


def _qrs_fwd(x, axis_name, axis):
    n = C.axis_size(axis_name)
    if x.ndim == 1:
        # 1-D leaf: one scalar scale per rank, codes scattered by chunk.
        if x.shape[0] % n:
            raise ValueError(f"quantized_reduce_scatter: dim of size "
                             f"{x.shape[0]} not divisible by axis "
                             f"{axis_name!r} size {n}")
        q, s = quantize_int8(x.reshape(1, -1), axis=-1)     # s: (1, 1)
        qt = C.all_to_all(q.reshape(n, -1), axis_name, split_axis=0,
                          concat_axis=0, tiled=False)        # (n, chunk)
        sg = C.all_gather(s.reshape(1), axis_name, axis=0,
                          tiled=False)                       # (n, 1)
        out = jnp.sum(qt.astype(jnp.float32) * sg, axis=0)
        return out.reshape(-1).astype(x.dtype), None
    axis = axis % x.ndim
    if x.shape[axis] % n:
        raise ValueError(f"quantized_reduce_scatter: dim {axis} of size "
                         f"{x.shape[axis]} not divisible by axis "
                         f"{axis_name!r} size {n}")
    # quantize along a dim that is NOT the scatter dim so each chunk's
    # scales travel with its codes through the same all_to_all
    qaxis = -1 if axis != x.ndim - 1 else 0
    q, s = quantize_int8(x, axis=qaxis)

    def route(t):
        # rank-chunks of the scatter dim onto a new leading axis, then
        # one all_to_all: rank r ends up holding every rank's chunk r,
        # leading axis indexing the SOURCE rank (rank-order sum below)
        c = t.shape[axis] // n
        tr = t.reshape(t.shape[:axis] + (n, c) + t.shape[axis + 1:])
        tr = jnp.moveaxis(tr, axis, 0)
        return C.all_to_all(tr, axis_name, split_axis=0, concat_axis=0,
                            tiled=False)

    out = jnp.sum(route(q).astype(jnp.float32) * route(s), axis=0)
    return out.astype(x.dtype), None


def _qrs_bwd(axis_name, axis, _res, g):
    if g.ndim == 1:
        return (C.all_gather(g, axis_name, axis=0),)
    return (C.all_gather(g, axis_name, axis=axis % g.ndim),)


quantized_reduce_scatter.defvjp(_qrs_fwd, _qrs_bwd)


# ------------------------------------------------------------------- fp8
#
# The other half of the reference's torchao sweep: real fp8 recipes
# (``fp8/fp8_benchmark.py``: Float8Linear, e4m3 forward operands, e5m2
# grad_output in backward, per-tensor dynamic or delayed amax scaling).
# v5e still has no fp8 MXU mode, so like the int8 tier this ships as a
# recipe-faithful CPU-tier implementation: operands make a REAL fp8
# round-trip (jnp.float8_e4m3fn / float8_e5m2 storage — the quantization
# noise is exactly fp8's), accumulation runs f32.  On fp8-capable
# hardware the explicit upcast before the dot becomes a native fp8
# ``dot_general`` — a one-line swap the RESULTS.md caveat records.

FP8_FWD_DTYPE = jnp.float8_e4m3fn   # forward operands  (finfo max 448)
FP8_BWD_DTYPE = jnp.float8_e5m2     # grad_output       (finfo max 57344)


def fp8_max(dtype) -> float:
    """Largest finite value of an fp8 dtype (448 for e4m3fn, 57344 for
    e5m2) — the denominator of per-tensor absmax scaling."""
    return float(jnp.finfo(dtype).max)


def amax_history_update(history: jax.Array, x: jax.Array) -> jax.Array:
    """Delayed-scaling bookkeeping: shift the tensor's current absmax
    into the rolling (H,) f32 history (oldest entry drops off)."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)))
    return jnp.concatenate([history[1:], amax[None]])


def scale_from_history(history: jax.Array, dtype) -> jax.Array:
    """Delayed scaling's scale choice: absmax over the whole rolling
    history (torchao's ``delayed`` recipe) rather than just the current
    tensor — robust to single-step amax spikes."""
    amax = jnp.max(history)
    return jnp.where(amax > 0, amax / fp8_max(dtype), 1.0)


def quantize_fp8(x: jax.Array, dtype=FP8_FWD_DTYPE, *,
                 amax_history_len: int = 0):
    """Per-TENSOR absmax scaling to fp8 (Float8Linear's granularity —
    coarser than the int8 tier's per-row scales): returns
    ``(q fp8, scale f32 scalar)`` with ``dequant = q * scale``.

    ``amax_history_len > 0`` routes the scale through the delayed-scaling
    helpers.  This stateless CPU-tier instantiation seeds the history
    with the current tensor's absmax (numerically identical to dynamic
    scaling); a stateful trainer threads a real rolling history through
    its train state and gets genuine delayed scaling from the same two
    helpers."""
    if amax_history_len:
        hist = amax_history_update(
            jnp.zeros((amax_history_len,), jnp.float32), x)
        scale = scale_from_history(hist, dtype)
    else:
        amax = jnp.max(jnp.abs(x.astype(jnp.float32)))
        scale = jnp.where(amax > 0, amax / fp8_max(dtype), 1.0)
    fmax = fp8_max(dtype)
    q = jnp.clip(x.astype(jnp.float32) / scale, -fmax, fmax).astype(dtype)
    return q, scale


def fp8_matmul(aq, a_scale, bq, b_scale, dims, out_dtype):
    """Scaled dot over fp8-quantized operands, f32 accumulation, scalar
    dequant epilogue.  The operands already carry fp8 round-trip noise;
    the upcast before the dot is the CPU-tier stand-in for a native fp8
    ``dot_general`` (see the section comment)."""
    acc = lax.dot_general(aq.astype(jnp.float32), bq.astype(jnp.float32),
                          dims, preferred_element_type=jnp.float32)
    return (acc * a_scale * b_scale).astype(out_dtype)


def _fp8_mm_kernel(aq_ref, as_ref, bq_ref, bs_ref, o_ref):
    acc = jnp.dot(aq_ref[...].astype(jnp.float32),
                  bq_ref[...].astype(jnp.float32),
                  preferred_element_type=jnp.float32)
    o_ref[...] = (acc * as_ref[0, 0] * bs_ref[0, 0]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("out_dtype", "block_m",
                                             "block_n", "interpret"))
def fp8_matmul_pallas(aq, a_scale, bq, b_scale, *, out_dtype=jnp.bfloat16,
                      block_m: int | None = None,
                      block_n: int | None = None,
                      interpret: bool = False):
    """Tiled Pallas twin of :func:`fp8_matmul` (2-D operands, per-tensor
    scalar scales passed as (1, 1) blocks) — the fp8 leg of the kernel
    tier, grid/BlockSpec structure of ``int8_matmul_pallas``."""
    from jax.experimental import pallas as pl

    M, K = aq.shape
    K2, N = bq.shape
    assert K == K2, (K, K2)
    bm, bn = _auto_blocks(M, K, N, 1, block_m or 256, block_n or 512)
    return pl.pallas_call(
        _fp8_mm_kernel,
        grid=(M // bm, N // bn),
        in_specs=[
            pl.BlockSpec((bm, K), lambda i, j: (i, 0)),
            pl.BlockSpec((1, 1), lambda i, j: (0, 0)),
            pl.BlockSpec((K, bn), lambda i, j: (0, j)),
            pl.BlockSpec((1, 1), lambda i, j: (0, 0)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((M, N), out_dtype),
        interpret=interpret,
    )(aq, a_scale.reshape(1, 1), bq, b_scale.reshape(1, 1))


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def fp8_dense(x, w, impl: str = "xla", interpret: bool = False,
              amax_history_len: int = 0):
    """Linear layer with the Float8Linear recipe end-to-end: e4m3
    per-tensor-scaled operands forward, and a backward whose THREE
    operands split by role exactly as torchao's — grad_output quantizes
    to e5m2 (wide range for gradient outliers), the saved activation and
    weight re-quantize to e4m3 — so ALL step matmul FLOPs run at fp8
    operand width.  ``impl``: "xla" or "pallas" (forward kernel;
    backward stays XLA).  ``amax_history_len``: > 0 selects delayed
    scaling (see :func:`quantize_fp8`)."""
    out, _ = _fp8_dense_fwd(x, w, impl, interpret, amax_history_len)
    return out


def _fp8_dense_fwd(x, w, impl, interpret, hist):
    lead = x.shape[:-1]
    K = x.shape[-1]
    x2 = x.reshape(-1, K)
    xq, xs = quantize_fp8(x2, FP8_FWD_DTYPE, amax_history_len=hist)
    wq, ws = quantize_fp8(w, FP8_FWD_DTYPE, amax_history_len=hist)
    if impl == "pallas":
        out = fp8_matmul_pallas(xq, xs, wq, ws, out_dtype=x.dtype,
                                interpret=interpret)
    else:
        out = fp8_matmul(xq, xs, wq, ws, (((1,), (0,)), ((), ())),
                         x.dtype)
    return out.reshape(*lead, w.shape[1]), (x, w)


def _fp8_dense_bwd(impl, interpret, hist, res, g):
    x, w = res
    lead = x.shape[:-1]
    K, N = w.shape
    g2 = g.reshape(-1, N)
    x2 = x.reshape(-1, K)
    gq, gs = quantize_fp8(g2, FP8_BWD_DTYPE, amax_history_len=hist)
    # dX = g · Wᵀ (contraction over N): e5m2 grad × e4m3 weight
    wq, ws = quantize_fp8(w, FP8_FWD_DTYPE, amax_history_len=hist)
    gx = fp8_matmul(gq, gs, wq, ws, (((1,), (1,)), ((), ())), x.dtype)
    # dW = Xᵀ · g (contraction over M): e4m3 activation × e5m2 grad
    xq, xs = quantize_fp8(x2, FP8_FWD_DTYPE, amax_history_len=hist)
    gw = fp8_matmul(xq, xs, gq, gs, (((0,), (0,)), ((), ())), w.dtype)
    return gx.reshape(*lead, K), gw


fp8_dense.defvjp(_fp8_dense_fwd, _fp8_dense_bwd)
