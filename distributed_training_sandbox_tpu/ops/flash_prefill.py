"""Flash prefill kernel over the paged KV pool (Pallas).

The serving engine's prefill attends a whole chunk of S query rows
against the slot's visible KV window.  The reference path gathers the
page table into a contiguous ``(B, V, n_kv, hd)`` HBM view and runs two
einsums with a full ``(g, r, S, V)`` score tensor in between — fine at
toy scale, but the score tensor and the gather view are exactly the
materializations a fused flash kernel exists to avoid.

This kernel reads pages IN PLACE via the table (same dynamic page loads
as ``paged_attention.py``) and computes the chunk's attention with a
tiled ONLINE softmax: KV is consumed in blocks of ``kv_block_pages``
pages, carrying running per-row maxima ``m``, denominators ``l`` and a
rescaled accumulator — the classic divide-at-the-end flash recurrence,
so the full score tensor never exists at once.

Parity tiers:

  * ``kv_block_pages=None`` (default) — ONE tile covering the whole
    view.  The epilogue then follows the reference op order exactly
    (mask → ``jax.nn.softmax`` → probs cast → contraction), which makes
    the output BITWISE equal to the engine's gather+einsum path — the
    tier the serving parity gates run.
  * ``kv_block_pages=k`` — genuine multi-block online softmax.  The
    divide-at-end rescaling reassociates the denominator, so this tier
    is allclose-not-bitwise vs the reference (asserted in tests); it is
    the shape the hardware tier runs where VMEM can't hold the view.

Float pools only: the int8 pool's per-row scale folding does not
commute with the online rescale, and prefill is the bandwidth-bound
leg where bf16 pools are the default anyway.

CPU-tier note: ``interpret=True`` executes the page loads with jax.lax
machinery; on real TPU the table row sits in SMEM and loads become
VMEM DMAs — same kernel body.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .paged_attention import _gather_pool

__all__ = ["paged_flash_prefill"]


def _prefill_kernel(pages_ref, q_ref, apos_ref, pk_ref, pv_ref, o_ref, *,
                    n_slot_pages: int, kv_block_pages: int | None,
                    probs_dtype):
    """One batch slot's chunk attention: q (S, g, r, hd) against the
    slot's pages, causal on absolute positions (``pos_kv <= apos[s]``,
    masked positions scored −1e30 → exact-zero probability)."""
    page = pk_ref.shape[1]
    hd = q_ref.shape[-1]
    q = q_ref[0]                                     # (S, g, r, hd)
    a = apos_ref[0]                                  # (S,)

    if kv_block_pages is None:
        # single tile: the reference op order verbatim (softmax →
        # probs cast → contraction) — bitwise tier
        kv = _gather_pool(pk_ref, pages_ref, n_slot_pages, page)
        vv = _gather_pool(pv_ref, pages_ref, n_slot_pages, page)
        scores = jnp.einsum(
            "sgrh,kgh->grsk", q, kv,
            preferred_element_type=jnp.float32) / math.sqrt(hd)
        vis = jnp.arange(kv.shape[0])[None, :] <= a[:, None]  # (S, V)
        scores = jnp.where(vis[None, None], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        o_ref[0] = jnp.einsum("grsk,kgh->sgrh",
                              probs.astype(probs_dtype), vv,
                              preferred_element_type=jnp.float32)
        return

    # tiled online softmax: running (m, l, acc), divide at the end
    T = kv_block_pages * page
    S, g, r, _ = q.shape

    def gather_blk(pool_ref, i):
        tail = pool_ref.shape[2:]
        acc0 = jnp.zeros((T,) + tail, pool_ref.dtype)

        def load(p, accv):
            pg = pages_ref[0, i * kv_block_pages + p]
            blk = pl.load(pool_ref, (pl.ds(pg, 1),)
                          + (slice(None),) * (1 + len(tail)))
            return jax.lax.dynamic_update_slice(
                accv, blk[0], (p * page,) + (0,) * len(tail))

        return jax.lax.fori_loop(0, kv_block_pages, load, acc0)

    def block(i, carry):
        m, l, acc = carry
        kb = gather_blk(pk_ref, i)
        vb = gather_blk(pv_ref, i)
        s = jnp.einsum(
            "sgrh,kgh->grsk", q, kb,
            preferred_element_type=jnp.float32) / math.sqrt(hd)
        pos = i * T + jnp.arange(T)
        vis = pos[None, :] <= a[:, None]             # (S, T)
        s = jnp.where(vis[None, None], s, -1e30)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))  # (g, r, S)
        # block 0 always holds position 0, visible to every row, so
        # m_new is a real score from the first iteration on and the
        # −1e30 of fully-masked later blocks underflows to exactly 0
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l = l * corr + jnp.sum(p, axis=-1)
        pv_blk = jnp.einsum("grsk,kgh->sgrh", p.astype(probs_dtype),
                            vb, preferred_element_type=jnp.float32)
        acc = acc * corr.transpose(2, 0, 1)[..., None] + pv_blk
        return m_new, l, acc

    m0 = jnp.full((g, r, S), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((g, r, S), jnp.float32)
    a0 = jnp.zeros((S, g, r, hd), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_slot_pages // kv_block_pages,
                                  block, (m0, l0, a0))
    o_ref[0] = acc / l.transpose(2, 0, 1)[..., None]


def paged_flash_prefill(qg, pk, pv, pages, apos, *, probs_dtype=None,
                        kv_block_pages: int | None = None,
                        interpret: bool | None = None):
    """Chunked-prefill paged flash attention, pages read in place.

    qg (B, S, n_kv, rep, hd) grouped query (already rope'd); pk/pv
    (n_pages, page, n_kv, hd) float pools; pages (B, P) int32 page
    table; apos (B, S) int32 absolute positions of the chunk's rows.
    Returns f32 (B, S, n_kv, rep, hd) — with the default single tile,
    the exact value of the reference gather-then-einsum path (caller
    applies the same ``astype`` epilogue).  ``kv_block_pages`` must
    divide P; passing P is the same as None.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if pk.dtype == jnp.int8:
        raise ValueError("flash prefill is float-pool only (int8 "
                         "scale folding does not commute with the "
                         "online rescale)")
    B, S, nkv, rep, hd = qg.shape
    P = pages.shape[1]
    if kv_block_pages is not None:
        kv_block_pages = int(kv_block_pages)
        if not 0 < kv_block_pages <= P:
            raise ValueError(f"kv_block_pages={kv_block_pages} with "
                             f"{P} pages per slot")
        if P % kv_block_pages:
            raise ValueError(f"kv_block_pages={kv_block_pages} must "
                             f"divide the {P}-page table")
        if kv_block_pages == P:
            kv_block_pages = None          # degenerate → bitwise tier

    kernel = functools.partial(
        _prefill_kernel, n_slot_pages=P,
        kv_block_pages=kv_block_pages,
        probs_dtype=probs_dtype or qg.dtype)
    whole = lambda arr: pl.BlockSpec(arr.shape, lambda b: (0,) * arr.ndim)
    row = pl.BlockSpec((1, P), lambda b: (b, 0))
    qspec = pl.BlockSpec((1, S, nkv, rep, hd), lambda b: (b, 0, 0, 0, 0))
    aspec = pl.BlockSpec((1, S), lambda b: (b, 0))
    out_spec = pl.BlockSpec((1, S, nkv, rep, hd),
                            lambda b: (b, 0, 0, 0, 0))
    out_shape = jax.ShapeDtypeStruct((B, S, nkv, rep, hd), jnp.float32)
    return pl.pallas_call(
        kernel,
        grid=(B,),
        in_specs=[row, qspec, aspec, whole(pk), whole(pv)],
        out_specs=out_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(pages, qg, apos, pk, pv)
